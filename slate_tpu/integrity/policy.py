"""Silent-data-corruption defense policy: delivery certification,
per-replica integrity scoring with quarantine, and hedged
re-execution knobs.

The serving tier self-heals from crashes, NaN garbage and overload,
but a device that returns a *finite-but-wrong* X passes every one of
those fences.  This module is the control half of the defense
(``integrity/abft.py`` is the math half; ``serve/service.py`` threads
both through dispatch):

* :class:`IntegrityPolicy` — the ``Option.ServeIntegrity`` /
  ``SLATE_TPU_INTEGRITY`` policy: whether (and how often) delivered
  batches are certified, whether gesv/posv buckets are built with
  ABFT checksums, and the hedging/quarantine tuning.  Grammar::

      off                    # no plane (the default; zero overhead)
      full                   # certify every delivered gesv/posv
      sample=0.25            # certify a seeded 25% sample
      full,abft              # + trace checksummed bucket cores
      full,abft,hedge=1.5,cooldown=2.0,threshold=0.6

  keys: ``abft`` (flag), ``hedge=<age/p99 factor>`` (0 disables
  straggler hedging), ``cooldown=<s>`` (quarantine -> probe delay),
  ``threshold=<0..1>`` (failure-EWMA quarantine trip point),
  ``alpha=<0..1>`` (EWMA smoothing), ``retries=<n>`` (certificate
  re-executions before the last-resort direct solve).

* :class:`IntegrityScore` — one replica lane's certificate-failure
  EWMA and quarantine state machine.  **Distinct from the circuit
  breaker by design**: the breaker sees *exceptions and NaNs* (a path
  that fails loudly), the score sees *certified-wrong answers* (a
  device that fails silently).  Lifecycle mirrors the breaker's so
  operators reason about one shape: ``ok`` --EWMA over threshold-->
  ``quarantined`` (admission steers new traffic to healthy lanes)
  --cooldown elapsed--> the lane is selectable again and the next
  certified delivery is the probe: pass -> ``ok`` (recovered), fail ->
  re-quarantined with a fresh cooldown.  One bad chip degrades
  capacity, never answers.
"""

from __future__ import annotations

import os
import random
from typing import Optional

from ..aux import sync

INTEGRITY_ENV = "SLATE_TPU_INTEGRITY"

#: certification modes (the policy grammar's head token)
MODE_SAMPLE = "sample"
MODE_FULL = "full"

#: quarantine states (health()["integrity"] vocabulary)
SCORE_OK = "ok"
SCORE_QUARANTINED = "quarantined"


class IntegrityPolicy:
    """Parsed ``SLATE_TPU_INTEGRITY`` policy (module docstring has the
    grammar).  ``should_check()`` is the per-delivery sampling gate —
    seeded, so a sampled deployment's check pattern replays."""

    def __init__(
        self,
        mode: str = MODE_FULL,
        sample_p: float = 1.0,
        abft: bool = False,
        hedge_factor: float = 1.0,
        hedge_min_age_s: float = 0.01,
        quarantine_cooldown_s: float = 5.0,
        quarantine_threshold: float = 0.6,
        quarantine_alpha: float = 0.5,
        cert_retry_max: int = 2,
        seed: int = 0,
    ):
        if mode not in (MODE_SAMPLE, MODE_FULL):
            raise ValueError(
                f"unknown integrity mode {mode!r} (off|sample=<p>|full)"
            )
        if mode == MODE_SAMPLE and not 0.0 < sample_p <= 1.0:
            raise ValueError(
                f"integrity sample probability out of (0, 1]: {sample_p}"
            )
        if not 0.0 < quarantine_alpha <= 1.0:
            raise ValueError(f"integrity alpha out of (0, 1]: {quarantine_alpha}")
        if not 0.0 < quarantine_threshold <= 1.0:
            raise ValueError(
                f"integrity threshold out of (0, 1]: {quarantine_threshold}"
            )
        self.mode = mode
        self.sample_p = float(sample_p)
        self.abft = bool(abft)
        self.hedge_factor = float(hedge_factor)
        self.hedge_min_age_s = float(hedge_min_age_s)
        self.quarantine_cooldown_s = float(quarantine_cooldown_s)
        self.quarantine_threshold = float(quarantine_threshold)
        self.quarantine_alpha = float(quarantine_alpha)
        self.cert_retry_max = max(int(cert_retry_max), 0)
        self._rng = random.Random(seed)
        self._rng_lock = sync.Lock(name="integrity.IntegrityPolicy._rng_lock")

    def should_check(self) -> bool:
        """Does this delivery get a certificate?  ``full`` -> always;
        ``sample`` -> a seeded Bernoulli draw (lock-guarded: every
        worker thread samples from one replayable stream)."""
        if self.mode == MODE_FULL:
            return True
        with self._rng_lock:
            return self._rng.random() < self.sample_p

    def describe(self) -> str:
        head = (
            MODE_FULL if self.mode == MODE_FULL
            else f"sample={self.sample_p:g}"
        )
        return head + (",abft" if self.abft else "")

    def new_score(self) -> "IntegrityScore":
        """One replica lane's quarantine tracker under this policy."""
        return IntegrityScore(
            alpha=self.quarantine_alpha,
            threshold=self.quarantine_threshold,
            cooldown_s=self.quarantine_cooldown_s,
        )


def parse_spec(spec: str) -> Optional[IntegrityPolicy]:
    """Parse the policy grammar; ``""``/``off``/``0`` -> None (plane
    disabled — the service then pays one ``is None`` branch)."""
    spec = (spec or "").strip()
    if not spec or spec.lower() in ("0", "off", "false", "no"):
        return None
    kw: dict = {}
    for i, item in enumerate(spec.split(",")):
        item = item.strip()
        if not item:
            continue
        k, sep, v = item.partition("=")
        k, v = k.strip().lower(), v.strip()
        if i == 0:
            # head token: the certification mode
            if k == MODE_FULL and not sep:
                kw["mode"] = MODE_FULL
                continue
            if k == MODE_SAMPLE and sep:
                kw["mode"] = MODE_SAMPLE
                kw["sample_p"] = float(v)
                continue
            raise ValueError(
                f"{INTEGRITY_ENV}={spec!r}: expected off|sample=<p>|full, "
                f"got {item!r}"
            )
        if k == "abft" and not sep:
            kw["abft"] = True
        elif k == "hedge" and sep:
            kw["hedge_factor"] = float(v)
        elif k == "cooldown" and sep:
            kw["quarantine_cooldown_s"] = float(v)
        elif k == "threshold" and sep:
            kw["quarantine_threshold"] = float(v)
        elif k == "alpha" and sep:
            kw["quarantine_alpha"] = float(v)
        elif k == "retries" and sep:
            kw["cert_retry_max"] = int(v)
        elif k == "seed" and sep:
            kw["seed"] = int(v)
        else:
            raise ValueError(
                f"{INTEGRITY_ENV}={spec!r}: unknown key {item!r} "
                "(abft|hedge=|cooldown=|threshold=|alpha=|retries=|seed=)"
            )
    return IntegrityPolicy(**kw)


def from_options(integrity=None, opts=None) -> Optional[IntegrityPolicy]:
    """Resolve the service's policy: an explicit
    :class:`IntegrityPolicy` or spec string wins, ``False`` is the
    explicit off-switch (overriding the env — the baseline/AB pattern
    every serve plane follows), ``None`` resolves
    ``SLATE_TPU_INTEGRITY`` then ``Option.ServeIntegrity``."""
    if integrity is False:
        return None
    if isinstance(integrity, IntegrityPolicy):
        return integrity
    if integrity is not None:
        return parse_spec(str(integrity))
    spec = os.environ.get(INTEGRITY_ENV)
    if spec is None:
        from ..enums import Option
        from ..options import get_option

        spec = str(get_option(opts, Option.ServeIntegrity) or "")
    return parse_spec(spec)


def residual_certificate(routine: str, A, X, B) -> bool:
    """Certify one delivered solve AGAINST ITS CONTRACT: the
    factor-cache residual fence ``max|A X - B| <= sqrt(eps)(|A||X| +
    |B|)`` with posv's lower triangle symmetrized first (the api
    contract — "solves with the LOWER triangle of A" — mirrored from
    ``serve/service._cert_operand``: certifying against junk above the
    diagonal would fail every verdict on a correct X).  The fleet
    router certifies cross-process deliveries through this ONE
    spelling; routines without a residual contract (gels) pass
    vacuously.  The check runs in the precision the solve was SERVED
    at (X's dtype): the caller may hold float64 operands while the
    service computes in float32, and judging a float32 solve against
    float64's eps would fail every correct delivery."""
    import numpy as np

    if routine not in ("gesv", "posv"):
        return True
    from ..serve.factor_cache import residual_ok

    X = np.asarray(X)
    A = np.asarray(A, dtype=X.dtype)
    B = np.asarray(B, dtype=X.dtype)
    if B.ndim == 1:
        B = B[:, None]
    if X.ndim == 1:
        X = X[:, None]
    if routine == "posv":
        A = np.tril(A) + np.conj(np.tril(A, -1)).T
    return residual_ok(A, B, X)


class IntegrityScore:
    """One lane's certificate-failure EWMA + quarantine state machine
    (class docstring up top: the breaker's recoverable shape, fed by
    silent-wrong-answer evidence instead of exceptions).  Self-locked:
    workers observe from delivery loops, admission and health() read
    concurrently."""

    def __init__(
        self,
        alpha: float = 0.5,
        threshold: float = 0.6,
        cooldown_s: float = 5.0,
    ):
        self.alpha = float(alpha)
        self.threshold = float(threshold)
        self.cooldown_s = float(cooldown_s)
        # sync.Lock: plain threading.Lock unless the race plane is on
        self._lock = sync.Lock(name="integrity.IntegrityScore._lock")
        # the EWMA + quarantine state machine: workers observe from
        # delivery loops while admission and health() read concurrently
        # — the annotations are ground truth for the lock-discipline
        # and race-guarded-by lint rules
        self.ewma = 0.0  # guarded by: _lock
        self.state = SCORE_OK  # guarded by: _lock
        self.quarantined_at = 0.0  # guarded by: _lock
        self.quarantines = 0  # lifetime transitions  # guarded by: _lock

    def observe(self, ok: bool, now: float) -> Optional[str]:
        """Fold one certificate verdict in; returns the transition it
        caused (``"quarantined"`` / ``"recovered"``) or None.  While
        quarantined and cooling down, verdicts only extend or hold the
        quarantine (requests already queued on the lane keep being
        served — quarantine is an admission-side steer, not a stop);
        the first PASSING verdict after the cooldown is the probe that
        recovers the lane, exactly like a half-open breaker's probe."""
        with self._lock:
            if self.state == SCORE_QUARANTINED:
                if not ok:
                    # failed probe (or in-cooldown traffic still wrong):
                    # fresh cooldown, stay quarantined
                    self.quarantined_at = now
                    self.ewma = 1.0
                    return None
                if now - self.quarantined_at >= self.cooldown_s:
                    self.state = SCORE_OK
                    self.ewma = 0.0
                    return "recovered"
                return None
            self.ewma = (
                (1.0 - self.alpha) * self.ewma
                + self.alpha * (0.0 if ok else 1.0)
            )
            if not ok and self.ewma > self.threshold:
                self.state = SCORE_QUARANTINED
                self.quarantined_at = now
                self.quarantines += 1
                return "quarantined"
            return None

    def suspect(self) -> bool:
        """True while the lane is quarantined (cooldown elapsed or
        not): a sampled certification policy must check EVERY delivery
        from a suspect lane — the post-cooldown probe has to be the
        very next delivery, not the next sampled one ~1/p deliveries
        later."""
        with self._lock:
            return self.state == SCORE_QUARANTINED

    def excluded(self, now: float) -> bool:
        """Admission-side exclusion window: quarantined AND cooling
        down (one definition with the probe eligibility, the Breaker
        ``cooling_down`` pattern — past the cooldown the lane must be
        selectable again or no probe could ever reach it)."""
        with self._lock:
            return (
                self.state == SCORE_QUARANTINED
                and now - self.quarantined_at < self.cooldown_s
            )

    def snapshot(self, now: float) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "ewma": round(self.ewma, 4),
                "quarantines": self.quarantines,
                "quarantined_for_s": (
                    round(now - self.quarantined_at, 3)
                    if self.state == SCORE_QUARANTINED else None
                ),
            }
