"""Level-3 BLAS drivers (reference: src/gemm.cc, gemmA.cc, gemmC.cc,
hemm.cc, symm.cc, herk.cc, her2k.cc, syrk.cc, syr2k.cc, trmm.cc, trsm.cc).

Functional API: every routine returns the updated output matrix.

Two execution paths, selected per call:

* **global path** (single device / small grids): operands are materialized
  as (padded) 2D arrays and the op is one XLA kernel — on one chip this is
  the optimal schedule (max MXU tiles, fused epilogue), replacing the
  reference's 4-way target dispatch + OpenMP task DAG wholesale.
* **spmd path** (multi-device mesh): explicit shard_map SUMMA /
  stationary-A with ICI collectives (parallel/spmd_blas.py), mirroring
  gemmC/gemmA's broadcast/reduce structure.

Method auto-selection mirrors gemm.cc:12-24: stationary-C unless A is
much taller than C is wide (then stationary-A avoids moving A).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..enums import Diag, MethodGemm, Op, Option, Side, Uplo
from ..exceptions import DimensionError, slate_assert
from ..matrix.base import BaseMatrix
from ..matrix.matrix import (
    HermitianMatrix,
    Matrix,
    SymmetricMatrix,
    TrapezoidMatrix,
    TriangularMatrix,
)
from ..options import Options, get_option
from ..ops import blas2d
from ..parallel import spmd_blas, spmd_trsm
from ..parallel.layout import eye_splice, tiles_from_global

from ..internal.precision import accurate_matmul

from ..aux.metrics import instrumented


from ..matrix.base import is_distributed as _is_distributed
from ..internal import fallbacks


def _repack_like(C_new_2d: jnp.ndarray, C: BaseMatrix) -> BaseMatrix:
    """Pack a computed LOGICAL (m, n) global array back into C's
    layout/grid.  For op-views the logical dims are the transpose of the
    storage layout, so the result gets the transposed layout with the op
    resolved away."""
    if C.op != Op.NoTrans:
        lay = C.layout.transposed()
        return Matrix(
            tiles_from_global(C_new_2d.astype(C.dtype), lay), lay, grid=C.grid
        ).shard()
    T = tiles_from_global(C_new_2d.astype(C.dtype), C.layout)
    out = C._with(data=T)
    return out.shard()


@accurate_matmul
@instrumented("gemm")
def gemm(
    alpha,
    A: Matrix,
    B: Matrix,
    beta,
    C: Matrix,
    opts: Optional[Options] = None,
) -> Matrix:
    """C = alpha op(A) op(B) + beta C (reference: src/gemm.cc:82).

    Auto method select (gemm.cc:12-24): stationary-A when A's k dim is
    small and C is narrow; else stationary-C (SUMMA).
    """
    if A.n != B.m or A.m != C.m or B.n != C.n:
        raise DimensionError(
            f"gemm dims: A {A.m}x{A.n}, B {B.m}x{B.n}, C {C.m}x{C.n}"
        )
    method = get_option(opts, Option.MethodGemm, MethodGemm.Auto)
    if isinstance(method, str):
        method = MethodGemm.from_string(method)

    if _is_distributed(C) and get_option(opts, Option.UseShardMap):
        Ar, Br = A.resolved(), B.resolved()
        if method == MethodGemm.Auto:
            # gemm.cc:12-24: use gemmA when A stays put profitably
            method = (
                MethodGemm.A
                if (C.layout.nt <= C.grid.q and Ar.layout.mt > 2 * C.layout.nt)
                else MethodGemm.C
            )
        # tile-size conformability for the tile-level spmd kernels
        ok_tiles = (
            Ar.layout.nb == Br.layout.mb
            and Ar.layout.mb == C.layout.mb
            and Br.layout.nb == C.layout.nb
            and (Ar.layout.p, Ar.layout.q) == (C.layout.p, C.layout.q)
            and (Br.layout.p, Br.layout.q) == (C.layout.p, C.layout.q)
        )
        if ok_tiles:
            fn = (
                spmd_blas.gemm_reduce_a
                if method == MethodGemm.A
                else spmd_blas.summa_gemm
            )
            data = fn(
                C.grid, alpha, Ar.data, Ar.layout, Br.data, Br.layout,
                beta, C.data, C.layout,
            )
            return C._with(data=data)
        # fall through to global path (GSPMD inserts collectives)
        fallbacks.record("gemm", opts, "tile-size/grid mismatch")

    A2 = A.to_global()
    B2 = B.to_global()
    C2 = C.to_global()
    out = blas2d.gemm2d(alpha, A2, B2, beta, C2)
    return _repack_like(out, C)


@accurate_matmul
@instrumented("symm")
def symm(side: Side, alpha, A: SymmetricMatrix, B: Matrix, beta, C: Matrix,
         opts=None) -> Matrix:
    """C = alpha A B + beta C, A symmetric (reference: src/symm.cc)."""
    _check_hemm_dims(side, A, B, C)
    out = _hemm_spmd(side, alpha, A, B, beta, C, opts)
    if out is not None:
        return out
    if _is_distributed(C):
        fallbacks.record("symm", opts, "shape/grid not spmd-conformable")
    Af = A.full_global()
    B2, C2 = B.to_global(), C.to_global()
    out = (
        blas2d.gemm2d(alpha, Af, B2, beta, C2)
        if side == Side.Left
        else blas2d.gemm2d(alpha, B2, Af, beta, C2)
    )
    return _repack_like(out, C)


@accurate_matmul
@instrumented("hemm")
def hemm(side: Side, alpha, A: HermitianMatrix, B: Matrix, beta, C: Matrix,
         opts=None) -> Matrix:
    """C = alpha A B + beta C, A Hermitian (reference: src/hemm.cc,
    method A/C variants collapse to one fused XLA product here;
    distributed: SUMMA over the mirrored tile array)."""
    _check_hemm_dims(side, A, B, C)
    out = _hemm_spmd(side, alpha, A, B, beta, C, opts)
    if out is not None:
        return out
    if _is_distributed(C):
        fallbacks.record("hemm", opts, "shape/grid not spmd-conformable")
    Af = A.full_global()
    B2, C2 = B.to_global(), C.to_global()
    out = (
        blas2d.gemm2d(alpha, Af, B2, beta, C2)
        if side == Side.Left
        else blas2d.gemm2d(alpha, B2, Af, beta, C2)
    )
    return _repack_like(out, C)


def _check_hemm_dims(side, A, B, C):
    if side == Side.Left:
        ok = A.n == B.m and A.m == C.m and B.n == C.n
    else:
        ok = B.n == A.m and B.m == C.m and A.n == C.n
    if not ok:
        raise DimensionError(
            f"hemm/symm dims: A {A.m}x{A.n}, B {B.m}x{B.n}, C {C.m}x{C.n}"
        )


def _hemm_spmd(side, alpha, A, B, beta, C, opts):
    """Distributed hemm/symm via the Hermitian SUMMA (reference: hemmA's
    broadcast/reduce DAG, src/hemmA.cc): the op-full panel of A is
    assembled per step from the STORED triangle's column + row panels —
    no full_global() mirror round trip."""
    if not (_is_distributed(C) and get_option(opts, Option.UseShardMap)):
        return None
    if C.op != Op.NoTrans or A.op != Op.NoTrans:
        return None
    Br = B.resolved()
    layA, layB, layC = A.layout, Br.layout, C.layout
    # conformability on the RESOLVED operand layouts (cf. _trsm_spmd_ok)
    if side == Side.Left:
        ok = layB.mb == layA.nb and layB.nb == layC.nb and layA.mb == layC.mb
    else:
        ok = layB.nb == layA.mb and layB.mb == layC.mb and layA.nb == layC.nb
    if not (
        ok
        and layA.mb == layA.nb
        and (layA.p, layA.q) == (layC.p, layC.q) == (layB.p, layB.q)
    ):
        return None
    data = spmd_blas.spmd_hemm(
        C.grid,
        side == Side.Left,
        alpha,
        A.data,
        layA,
        A.uplo == Uplo.Lower,
        Br.data,
        Br.layout,
        beta,
        C.data,
        layC,
        # complex SYMMETRIC operands mirror without conjugation (the
        # class-dispatched full_global did this before)
        hermitian=isinstance(A, HermitianMatrix),
    )
    return C._with(data=data)


def _herk_like_spmd(alpha, A, beta, C, conj: bool, rank2=False, B=None):
    """Distributed rank-k update over the mesh via the direct panel-gather
    kernel (parallel/spmd_blas.py::spmd_herk — the reference's
    internal::herk batched symmetric update, internal_herk.cc).

    No transposed operand is resolved (a materialized A^H lives on the
    transposed process grid, which breaks p != q meshes) and C's stored
    triangle needs no global mirror.  Returns None if shapes/ops don't
    conform (the caller records the fallback)."""
    if C.op != Op.NoTrans:
        return None
    # supported op(A) combos: NoTrans; ConjTrans with herk (A^H A);
    # Trans with syrk (A^T A).  Mixed conj/op views fall back.
    if A.op == Op.NoTrans:
        trans = False
    elif (A.op == Op.ConjTrans and conj) or (A.op == Op.Trans and not conj):
        trans = True
    else:
        return None
    lay = A.layout  # storage layout (op applies logically only)
    layC = C.layout
    kb = lay.mb if trans else lay.nb
    nt_match = (lay.nt if trans else lay.mt) == layC.mt
    if not (
        nt_match
        and (lay.nb if trans else lay.mb) == layC.mb
        and layC.mb == layC.nb
        and (lay.p, lay.q) == (layC.p, layC.q)
    ):
        return None
    TB = layB = None
    if rank2:
        if B.op != A.op:
            return None
        layB = B.layout
        if not (
            layB.mb == lay.mb
            and layB.nb == lay.nb
            and (layB.p, layB.q) == (layC.p, layC.q)
        ):
            return None
        TB = B.data
    a2 = jnp.conj(alpha) if (conj and C.is_complex) else alpha
    out = spmd_blas.spmd_herk(
        C.grid, alpha, A.data, lay, beta, C.data, layC,
        conj=conj, trans=trans, alpha2=a2, TB=TB, layB=layB,
        lower=(C.uplo == Uplo.Lower),
    )
    return C._with(data=out)


def _herk_like(alpha, A, beta, C, conj: bool, rank2=False, B=None, opts=None):
    slate_assert(C.m == C.n, "herk/syrk C must be square")
    if _is_distributed(C) and get_option(opts, Option.UseShardMap):
        spmd = _herk_like_spmd(alpha, A, beta, C, conj, rank2, B)
        if spmd is not None:
            return spmd
        fallbacks.record(
            "her2k" if rank2 else "herk", opts, "shape/grid not conformable"
        )
    k_dim = A.n
    A2 = A.to_global()
    C2 = C.full_global()
    if rank2:
        B2 = B.to_global()
        out = (
            blas2d.her2k2d(alpha, A2, B2, beta, C2)
            if conj
            else blas2d.syr2k2d(alpha, A2, B2, beta, C2)
        )
    else:
        out = (
            blas2d.herk2d(alpha, A2, beta, C2)
            if conj
            else blas2d.syrk2d(alpha, A2, beta, C2)
        )
    return _repack_like(out, C)


@accurate_matmul
@instrumented("syrk")
def syrk(alpha, A: Matrix, beta, C: SymmetricMatrix, opts=None):
    """C = alpha op(A) op(A)^T + beta C (reference: src/syrk.cc)."""
    if A.m != C.m:
        raise DimensionError(f"syrk dims: A {A.m}x{A.n}, C {C.m}x{C.n}")
    return _herk_like(alpha, A, beta, C, conj=False, opts=opts)


@accurate_matmul
@instrumented("herk")
def herk(alpha, A: Matrix, beta, C: HermitianMatrix, opts=None):
    """C = alpha op(A) op(A)^H + beta C (reference: src/herk.cc)."""
    if A.m != C.m:
        raise DimensionError(f"herk dims: A {A.m}x{A.n}, C {C.m}x{C.n}")
    return _herk_like(alpha, A, beta, C, conj=True, opts=opts)


@accurate_matmul
@instrumented("syr2k")
def syr2k(alpha, A: Matrix, B: Matrix, beta, C: SymmetricMatrix, opts=None):
    """C = alpha (A B^T + B A^T) + beta C (reference: src/syr2k.cc)."""
    if A.m != C.m or B.m != C.m or A.n != B.n:
        raise DimensionError("syr2k dims")
    return _herk_like(alpha, A, beta, C, conj=False, rank2=True, B=B, opts=opts)


@accurate_matmul
@instrumented("her2k")
def her2k(alpha, A: Matrix, B: Matrix, beta, C: HermitianMatrix, opts=None):
    """C = alpha A B^H + conj(alpha) B A^H + beta C (reference: src/her2k.cc)."""
    if A.m != C.m or B.m != C.m or A.n != B.n:
        raise DimensionError("her2k dims")
    return _herk_like(alpha, A, beta, C, conj=True, rank2=True, B=B, opts=opts)


def _resolve_tri(A: TriangularMatrix):
    """Triangular operand as (2D array, uplo, op-applied) honoring A.op."""
    op = A.op
    A_nores = A._with(op=Op.NoTrans)  # storage view
    return A_nores.to_global(), A.uplo if op == Op.NoTrans else (
        Uplo.Upper if A.uplo == Uplo.Lower else Uplo.Lower
    ), op


def _trmm_spmd_ok(side: Side, A: TriangularMatrix, B: Matrix) -> bool:
    layA, layB = A.layout, B.layout
    bdim_b, bt = (layB.mb, layB.mt) if side == Side.Left else (layB.nb, layB.nt)
    return (
        layA.m == layA.n
        and layA.mb == layA.nb == bdim_b
        and layA.nt == bt
        and (layA.p, layA.q) == (layB.p, layB.q)
        and B.op == Op.NoTrans
    )


@accurate_matmul
@instrumented("trmm")
def trmm(side: Side, alpha, A: TriangularMatrix, B: Matrix, opts=None) -> Matrix:
    """B = alpha op(A) B or alpha B op(A) (reference: src/trmm.cc ->
    work::trmm pipeline, src/work/work_trmm.cc).

    Distributed: the triangular SUMMA in parallel/spmd_blas.py::spmd_trmm
    — panel gathers of the masked triangle, psum broadcasts of B's block
    row/column, no gather of A or B."""
    if (
        _is_distributed(B)
        and get_option(opts, Option.UseShardMap)
        and _trmm_spmd_ok(side, A, B)
    ):
        data = spmd_blas.spmd_trmm(
            B.grid,
            side == Side.Left,
            alpha,
            A.data,
            A.layout,
            lower=A.uplo == Uplo.Lower,
            unit_diag=A.diag == Diag.Unit,
            opa_trans=A.op != Op.NoTrans,
            opa_conj=A.op == Op.ConjTrans,
            TB=B.data,
            layB=B.layout,
        )
        return B._with(data=data)
    if _is_distributed(B):
        fallbacks.record("trmm", opts, "shape/grid/view not spmd-conformable")
    A2 = A._with(op=Op.NoTrans).to_global()
    out = blas2d.trmm2d(side, A.uplo, A.op, A.diag, alpha, A2, B.to_global())
    return _repack_like(out, B)


def _trsm_spmd_ok(side: Side, A: TriangularMatrix, B: Matrix) -> bool:
    layT, layB = A.layout, B.layout
    bdim_b, bt = (layB.mb, layB.mt) if side == Side.Left else (layB.nb, layB.nt)
    return (
        layT.m == layT.n
        and layT.mb == layT.nb == bdim_b
        and (layT.p, layT.q) == (layB.p, layB.q)
        and layT.nt == bt
        and B.op == Op.NoTrans
    )


@instrumented("trsm")
def trsm(side: Side, alpha, A: TriangularMatrix, B: Matrix, opts=None) -> Matrix:
    """Solve op(A) X = alpha B (or right) (reference: src/trsm.cc ->
    trsmA/trsmB work pipelines, src/work/work_trsm.cc).

    Global path: one XLA triangular_solve (internally blocked/pipelined by
    XLA — the work_trsm row pipeline is the compiler's job on TPU).
    SPMD paths (distributed): the shard_map row pipeline (left side) or
    its column-pipeline dual (right side) in parallel/spmd_trsm.py — no
    gather of A or B to a global array.
    """
    if (
        _is_distributed(B)
        and get_option(opts, Option.UseShardMap)
        and _trsm_spmd_ok(side, A, B)
    ):
        TT = eye_splice(A.layout, A.data)
        fn = (
            spmd_trsm.spmd_trsm_left
            if side == Side.Left
            else spmd_trsm.spmd_trsm_right
        )
        data = fn(
            B.grid,
            TT,
            A.layout,
            B.data,
            B.layout,
            lower=A.uplo == Uplo.Lower,
            trans=A.op != Op.NoTrans,
            conj=A.op == Op.ConjTrans,
            unit_diag=A.diag == Diag.Unit,
            alpha=alpha,
        )
        return B._with(data=data)
    if _is_distributed(B):
        fallbacks.record(
            "trsm",
            opts,
            "right side / transposed B / non-conformable tiles",
        )
    A2 = A._with(op=Op.NoTrans).to_global()
    out = blas2d.trsm2d(side, A.uplo, A.op, A.diag, alpha, A2, B.to_global())
    return _repack_like(out, B)
