"""Mixed-precision solve drivers (reference: src/gesv_mixed.cc,
gesv_mixed_gmres.cc, posv_mixed.cc, posv_mixed_gmres.cc), routed
through the :mod:`slate_tpu.refine` subsystem.

The shape shared by all four drivers:

1. **Factor once in the cheap precision** (``refine.policy`` picks the
   pair: f32/c64 for f64/c128 working everywhere, bf16 for f32 on
   accelerators) — the factor step reuses the schedule-dispatched
   kernels behind :func:`~slate_tpu.drivers.lu.getrf` /
   :func:`~slate_tpu.drivers.chol.potrf` (``ops/lu_kernels.lu_global``,
   ``ops/chol_kernels.cholesky``), so ``Option.Schedule`` routes the
   low-precision factor exactly like the full-precision one (vendor on
   CPU, recursive above the crossover on accelerators).
2. **Refine in working precision**: classical IR
   (:func:`refine.ir.refine_while`) or restarted GMRES-IR
   (:func:`refine.gmres.gmres_refine`), per ``Option.RefineMethod``;
   componentwise-backward-error stopping, residual under
   ``accurate_matmul`` semantics.
3. **Fallback**: on non-convergence (or an injected factor fault) and
   ``Option.UseFallbackSolver`` (default True), demote to one
   full-precision direct solve and report ``iters < 0``
   (gesv_mixed_gmres.cc:100-106).  With the fallback disabled, a
   non-converged solve returns ``info > 0`` — never silent garbage.

Returns follow the reference: ``(X, info, iters)`` with ``iters < 0``
marking the fallback.  The drivers are **eager** (they read back
``iters``/``converged`` to run the host-side fallback branch); the
serving layer's traced executables use :func:`serve_mixed_core`, which
keeps everything device-resident and NaN-poisons non-converged columns
so the service's corrupt-result validation re-solves them on the
full-precision direct path and the bucket breaker demotes persistent
offenders.

Fault sites (``aux/faults``, zero overhead off): the *factor step*
checks ``result_corrupt`` (NaN-poisons the low-precision factor) and
``info_nonzero`` (reports a fake nonzero factor info) — both drive the
refinement into the fallback path, which is exactly the recovery the
chaos suite asserts.

Metrics: ``refine.calls`` / ``refine.iterations`` /
``refine.converged`` / ``refine.fallbacks`` counters plus the
``refine.residual`` gauge (final componentwise backward error), global
and per-routine (``refine.gesv_mixed.*`` etc.).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..aux import faults, metrics, spans
from ..aux.metrics import instrumented
from ..enums import Option, RefineMethod
from ..matrix.matrix import HermitianMatrix, Matrix
from ..options import Options, get_option, resolve_schedule_opts
from ..ops import chol_kernels, lu_kernels
from ..parallel.layout import tiles_from_global
from ..refine import gmres as _gmres
from ..refine import ir as _ir
from ..refine import policy as _policy


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def _record(routine: str, iters: int, converged: bool, berr: float) -> None:
    if spans.is_on():
        # per-request tracing: the iteration count rides on the span
        # the caller is inside (a user's spans.span block, or the serve
        # `direct` span — context-managed exactly so this annotation
        # reaches it); with no enclosing span, a `refine` instant still
        # puts "IR took 9 sweeps here" on the flight recorder
        if spans.current() is not None:
            spans.annotate(refine_iters=int(iters),
                           refine_converged=bool(converged))
        else:
            spans.event("refine", routine=routine, refine_iters=int(iters),
                        refine_converged=bool(converged))
    if not metrics.is_on():
        return
    for name in ("refine", f"refine.{routine}"):
        metrics.inc(f"{name}.calls")
        metrics.inc(f"{name}.iterations", iters)
        if converged:
            metrics.inc(f"{name}.converged")
        metrics.gauge(f"{name}.residual", berr)


def _record_fallback(routine: str) -> None:
    metrics.inc("refine.fallbacks")
    metrics.inc(f"refine.{routine}.fallbacks")
    if spans.is_on():
        if spans.current() is not None:
            spans.annotate(refine_fallback=True)
        else:
            spans.event("refine_fallback", routine=routine)


# ---------------------------------------------------------------------------
# low-precision factor step (schedule-routed, fault-checked)
# ---------------------------------------------------------------------------


def _inject_factor_faults(factor: jnp.ndarray) -> Tuple[jnp.ndarray, int]:
    """Factor-step fault sites (eager drivers only; one bool when off):
    ``result_corrupt`` NaN-poisons the factor, ``info_nonzero`` reports
    a fake nonzero factor info.  Either way the refinement loop sees a
    useless factor and the fallback solver is exercised."""
    if not faults.is_on():
        return factor, 0
    factor = jnp.asarray(faults.corrupt("result_corrupt", np.asarray(factor)))
    finfo = int(faults.poison_info("info_nonzero", np.zeros(1, np.int32))[0])
    return factor, finfo


def _pad_unit_diag(G: jnp.ndarray, npad: int) -> jnp.ndarray:
    """Embed G in the top-left of an npad x npad array with a unit
    trailing diagonal (blockdiag(A, I): factors restrict exactly, pad
    rows are never pivoted into real columns — the serve pad invariant)."""
    n = G.shape[0]
    if npad == n:
        return G
    Gp = jnp.pad(G, ((0, npad - n), (0, npad - n)))
    idx = jnp.arange(npad)
    return Gp.at[idx, idx].add(
        jnp.where(idx >= n, 1.0, 0.0).astype(G.dtype)
    )


def _lu_solver_lo(
    A2: jnp.ndarray,
    pol: _policy.Policy,
    nb: int,
    opts: Optional[Options],
    inject: bool,
    apply_up: bool = False,
):
    """Low-precision LU factor of A2 + the solve closure.  Returns
    (solve, factor_info).

    ``apply_up=False`` (classical IR) casts the residual down and
    solves in the factor precision — gesv_mixed.cc semantics, the
    cheapest correction step.  ``apply_up=True`` (GMRES-IR) upcasts
    the factors once and applies them in the working precision: the
    Krylov matvec must see the preconditioned operator exactly in
    precision u (Carson & Higham SISC 2018) — an eps_factor-perturbed
    operator stalls GMRES at berr ~ eps_factor, no better than IR."""
    sched, nb_switch, lookahead = resolve_schedule_opts(opts)
    n = A2.shape[0]
    nb = max(min(int(nb), n), 1)
    npad = -(-n // nb) * nb
    Gp = _pad_unit_diag(pol.factor_cast(A2), npad)
    lu_lo, perm = lu_kernels.lu_global(Gp, nb, sched, nb_switch, lookahead)
    finfo = 0
    if inject:
        lu_lo, finfo = _inject_factor_faults(lu_lo)
    lu_lo = lu_lo[:n, :n]
    perm = perm[:n]
    fac = lu_lo.astype(A2.dtype) if apply_up else lu_lo

    def solve(R):
        Rp = (R if apply_up else pol.factor_cast(R))[perm]
        Y = lax.linalg.triangular_solve(
            fac, Rp, left_side=True, lower=True, unit_diagonal=True
        )
        Z = lax.linalg.triangular_solve(fac, Y, left_side=True, lower=False)
        return Z.astype(R.dtype)

    return solve, finfo


def _chol_solver_lo(
    A_full: jnp.ndarray,
    pol: _policy.Policy,
    nb: int,
    opts: Optional[Options],
    conj: bool,
    inject: bool,
    apply_up: bool = False,
):
    """Low-precision Cholesky of the (full, Hermitian) A + the solve
    closure.  Returns (solve, factor_info).  ``apply_up`` as in
    :func:`_lu_solver_lo`: GMRES-IR applies the upcast factors in the
    working precision."""
    sched, nb_switch, lookahead = resolve_schedule_opts(opts)
    n = A_full.shape[0]
    nb_kernel = 512 if n >= 2048 else max(min(int(nb), 512), 1)
    L_lo = chol_kernels.cholesky(
        pol.factor_cast(A_full), nb_kernel, sched, nb_switch, lookahead
    )
    finfo = 0
    if inject:
        L_lo, finfo = _inject_factor_faults(L_lo)
    fac = L_lo.astype(A_full.dtype) if apply_up else L_lo

    def solve(R):
        Y = lax.linalg.triangular_solve(
            fac, R if apply_up else pol.factor_cast(R),
            left_side=True, lower=True,
        )
        Z = lax.linalg.triangular_solve(
            fac, Y, left_side=True, lower=True, transpose_a=True,
            conjugate_a=conj,
        )
        return Z.astype(R.dtype)

    return solve, finfo


# ---------------------------------------------------------------------------
# full-precision fallback solves
# ---------------------------------------------------------------------------


def _full_lu_solve(A2: jnp.ndarray, B2: jnp.ndarray, nb: int) -> jnp.ndarray:
    n = A2.shape[0]
    if lu_kernels.lu_supported(A2.dtype):
        lu_w, _, perm = lax.linalg.lu(A2)
        perm = perm.astype(jnp.int32)
    else:
        npad = -(-n // max(nb, 1)) * max(nb, 1)
        lu_w, perm = lu_kernels.blocked_getrf(
            _pad_unit_diag(A2, npad), max(nb, 1)
        )
        lu_w, perm = lu_w[:n, :n], perm[:n]
    Y = lax.linalg.triangular_solve(
        lu_w, B2[perm], left_side=True, lower=True, unit_diagonal=True
    )
    return lax.linalg.triangular_solve(lu_w, Y, left_side=True, lower=False)


def _full_chol_solve(A_full: jnp.ndarray, B2: jnp.ndarray, conj: bool) -> jnp.ndarray:
    Lw = chol_kernels.cholesky(A_full)
    Y = lax.linalg.triangular_solve(Lw, B2, left_side=True, lower=True)
    return lax.linalg.triangular_solve(
        Lw, Y, left_side=True, lower=True, transpose_a=True, conjugate_a=conj
    )


# ---------------------------------------------------------------------------
# refinement dispatch (shared by all four drivers)
# ---------------------------------------------------------------------------


def _gmres_selected(pol: _policy.Policy) -> bool:
    """True when the resolved method is GMRES-IR, which needs the
    preconditioner applied in working precision (``apply_up``): the
    Krylov matvec must see U^-1 L^-1 A exactly in precision u, or GMRES
    stalls at berr ~ eps_factor — no better than classical IR."""
    return pol.method == RefineMethod.GMRES.value


def _refine(A2, B2, solve_lo, pol: _policy.Policy):
    """Run the policy's method; returns (X, iters, steps, converged,
    berr).  ``iters`` keeps the reference's reporting unit (IR steps,
    or GMRES *inner* iterations = cycles * restart); ``steps`` is the
    method-independent refinement-step count (one GMRES cycle == one
    step) that feeds the iterations counter — refine_report's
    mean_iters column must not mix units across methods."""
    if pol.method == RefineMethod.GMRES.value:
        # one GMRES(restart) cycle is one refinement step, so the
        # outer-cycle budget is MaxIterations (a converged run exits the
        # while_loop early; unconverged cost is bounded by the fallback)
        res = _gmres.gmres_refine(
            A2, B2, solve_lo, pol.tolerance, pol.restart,
            max(1, pol.max_iterations),
        )
        return res.X, res.cycles * pol.restart, res.cycles, res.converged, res.berr
    res = _ir.refine_while(A2, B2, solve_lo, pol.tolerance, pol.max_iterations)
    return res.X, res.iters, res.iters, res.converged, res.berr


def _finish(
    routine: str,
    B: Matrix,
    X,
    iters_dev,
    steps_dev,
    conv_dev,
    berr_dev,
    finfo: int,
    pol: _policy.Policy,
    fallback_solve,
) -> Tuple[Matrix, jnp.ndarray, int]:
    """Host-side epilogue: metrics, fallback, info.  One readback."""
    iters = int(iters_dev)
    converged = bool(conv_dev) and finfo == 0
    _record(routine, int(steps_dev), converged, float(jnp.real(berr_dev)))
    info = jnp.int32(0)
    if not converged:
        if pol.use_fallback:
            _record_fallback(routine)
            X = fallback_solve()
            iters = -max(pol.max_iterations, 1)
        else:
            # no fallback requested: a non-converged solve must surface
            # as a nonzero info, never as silently-wrong finite output
            info = jnp.int32(max(finfo, pol.max_iterations, 1))
    info = jnp.where(
        jnp.all(jnp.isfinite(X)), info, jnp.int32(1)
    ).astype(jnp.int32)
    Xm = B._with(data=tiles_from_global(X.astype(B.dtype), B.layout)).shard()
    return Xm, info, iters


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


@instrumented("gesv_mixed")
def gesv_mixed(
    A: Matrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, jnp.ndarray, int]:
    """Mixed-precision LU solve with iterative refinement (reference:
    src/gesv_mixed.cc: low-precision factor + working-precision IR).

    Returns (X, info, iters); iters < 0 => full-precision fallback ran."""
    A2 = A.to_global()
    B2 = B.to_global()
    pol = _policy.select(A2.dtype, A.n, opts)
    solve_lo, finfo = _lu_solver_lo(
        A2, pol, A.layout.nb, opts, inject=True, apply_up=_gmres_selected(pol)
    )
    X, iters, steps, conv, berr = _refine(A2, B2, solve_lo, pol)
    return _finish(
        "gesv_mixed", B, X, iters, steps, conv, berr, finfo, pol,
        lambda: _full_lu_solve(A2, B2, A.layout.nb),
    )


@instrumented("gesv_mixed_gmres")
def gesv_mixed_gmres(
    A: Matrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, jnp.ndarray, int]:
    """Mixed-precision solve with restarted GMRES-IR, LU preconditioner
    in low precision (reference: src/gesv_mixed_gmres.cc: restart 30,
    fallback on divergence).  Survives ~1/eps_factor more
    ill-conditioning than gesv_mixed (Carson & Higham SISC 2018)."""
    A2 = A.to_global()
    B2 = B.to_global()
    pol = _policy.select(A2.dtype, A.n, opts, method_default=RefineMethod.GMRES)
    solve_lo, finfo = _lu_solver_lo(
        A2, pol, A.layout.nb, opts, inject=True, apply_up=_gmres_selected(pol)
    )
    X, iters, steps, conv, berr = _refine(A2, B2, solve_lo, pol)
    return _finish(
        "gesv_mixed_gmres", B, X, iters, steps, conv, berr, finfo, pol,
        lambda: _full_lu_solve(A2, B2, A.layout.nb),
    )


@instrumented("posv_mixed")
def posv_mixed(
    A: HermitianMatrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, jnp.ndarray, int]:
    """Mixed-precision SPD solve: low-precision Cholesky + working-
    precision IR (reference: src/posv_mixed.cc)."""
    A_full = A.full_global()
    B2 = B.to_global()
    pol = _policy.select(A_full.dtype, A.n, opts)
    solve_lo, finfo = _chol_solver_lo(
        A_full, pol, A.layout.nb, opts, A.is_complex, inject=True,
        apply_up=_gmres_selected(pol),
    )
    X, iters, steps, conv, berr = _refine(A_full, B2, solve_lo, pol)
    return _finish(
        "posv_mixed", B, X, iters, steps, conv, berr, finfo, pol,
        lambda: _full_chol_solve(A_full, B2, A.is_complex),
    )


@instrumented("posv_mixed_gmres")
def posv_mixed_gmres(
    A: HermitianMatrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, jnp.ndarray, int]:
    """Mixed-precision SPD solve with GMRES-IR, low-precision Cholesky
    preconditioner (reference: src/posv_mixed_gmres.cc — shares the
    GMRES-IR core with the LU variant)."""
    A_full = A.full_global()
    B2 = B.to_global()
    pol = _policy.select(
        A_full.dtype, A.n, opts, method_default=RefineMethod.GMRES
    )
    solve_lo, finfo = _chol_solver_lo(
        A_full, pol, A.layout.nb, opts, A.is_complex, inject=True,
        apply_up=_gmres_selected(pol),
    )
    X, iters, steps, conv, berr = _refine(A_full, B2, solve_lo, pol)
    return _finish(
        "posv_mixed_gmres", B, X, iters, steps, conv, berr, finfo, pol,
        lambda: _full_chol_solve(A_full, B2, A.is_complex),
    )


# ---------------------------------------------------------------------------
# serving-layer traced core
# ---------------------------------------------------------------------------


def serve_mixed_core(
    routine: str,
    Ag: jnp.ndarray,
    Bg: jnp.ndarray,
    nb: int,
    schedule: str = "auto",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fully-traceable mixed-precision core for one serve bucket
    (``BucketKey(precision="mixed")``): classical IR only (the jit-able
    ``while_loop`` method), no host branches, no fallback *inside* the
    trace.  Non-converged solves NaN-poison X instead: the service's
    corrupt-result validation then re-solves those items on the
    full-precision direct driver and records a breaker failure, so a
    bucket whose traffic persistently defeats the mixed path has its
    breaker opened and is demoted to the direct path — recovery stays
    in the serving layer where the policy (retry budgets, cooldowns)
    lives, not in the executable.

    ``posv`` references only the lower triangle of ``Ag`` (the serve
    contract) — the Hermitian full matrix is rebuilt in-trace for the
    residual."""
    opts = {Option.Schedule: schedule}
    if routine == "posv":
        T = jnp.tril(Ag)
        # strictly-upper = conj of strictly-lower; the stored diagonal
        # is kept exactly (the direct posv core's Hermitian contract)
        A2 = T + jnp.conj(jnp.tril(Ag, -1)).swapaxes(-1, -2)
        conj = jnp.issubdtype(Ag.dtype, jnp.complexfloating)
        pol = _policy.select(Ag.dtype, Ag.shape[0], opts)
        solve_lo, _ = _chol_solver_lo(A2, pol, nb, opts, bool(conj), inject=False)
    elif routine == "gesv":
        A2 = Ag
        pol = _policy.select(Ag.dtype, Ag.shape[0], opts)
        solve_lo, _ = _lu_solver_lo(A2, pol, nb, opts, inject=False)
    else:
        raise ValueError(
            f"mixed-precision serving supports gesv/posv, not {routine!r}"
        )
    res = _ir.refine_while(A2, Bg, solve_lo, pol.tolerance, pol.max_iterations)
    nan = jnp.asarray(jnp.nan, res.X.dtype)
    X = jnp.where(res.converged, res.X, nan)
    return X, jnp.zeros((), jnp.int32)
