"""SVD family (reference: src/svd.cc, ge2tb.cc, tb2bd.cc, bdsqr.cc,
unmbr_ge2tb.cc, unmbr_tb2bd.cc; SURVEY §3.5: svd is isomorphic to heev:
ge2tb -> gather -> tb2bd -> bdsqr + back-transforms).

ge2tb (dense -> triangular-band via alternating left QR / right LQ panel
reductions) carries the FLOPs and is implemented with our Householder
kernels; the gathered band stage uses the XLA vendor SVD (the reference
gathers to one node and runs LAPACK-style bulge chasing + bdsqr,
svd.cc:270-304).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..enums import Norm, Op, Option, Side, Uplo
from ..exceptions import slate_assert
from ..matrix.base import BaseMatrix, conj_transpose
from ..matrix.matrix import Matrix, TriangularBandMatrix
from ..options import Options, get_option
from ..ops.householder import geqrf as _geqrf_kernel, larft, materialize_v
from ..ops.jacobi import svd_accurate
from ..parallel.layout import TileLayout, tiles_from_global
from ..types import TriangularFactors


def ge2tb(
    A: Matrix, opts: Optional[Options] = None
) -> Tuple[TriangularBandMatrix, Matrix, TriangularFactors, Matrix, TriangularFactors]:
    """Reduce general A to upper triangular band form (reference:
    src/ge2tb.cc): alternating panel QR from the left (columns) and panel
    LQ from the right (rows), bandwidth nb.

    Returns (band, U_V, U_T, V_V, V_T) with the left/right reflector sets
    for unmbr_ge2tb."""
    lay = A.layout
    nb = lay.nb
    m, n = A.m, A.n
    G = A.to_global()
    kt = min(lay.mt, lay.nt)
    complex_t = A.is_complex

    def C(x):
        return jnp.conj(x) if complex_t else x

    UV = jnp.zeros_like(G)  # left reflectors live in A's row space (m)
    VV = jnp.zeros((n, n), G.dtype)  # right reflectors live in the column space
    UTs: List[jnp.ndarray] = []
    VTs: List[jnp.ndarray] = []

    for k in range(kt):
        lo = k * nb
        w = min(nb, n - lo)
        if lo >= m or w <= 0:
            break
        # left QR on panel A[lo:, lo:lo+w]
        panel = G[lo:, lo : lo + w]
        vr, taus = _geqrf_kernel(panel)
        V = materialize_v(vr, offset=0)
        Tk = larft(V, taus)
        G = G.at[lo:, lo : lo + w].set(jnp.triu(vr))
        # trailing: C <- (I - V T^H V^H) C for columns right of the panel
        if lo + w < n:
            Ct = G[lo:, lo + w :]
            W = C(V).T @ Ct
            G = G.at[lo:, lo + w :].set(Ct - V @ (C(Tk).T @ W))
        UV = UV.at[lo:, lo : lo + w].set(V)
        UTs.append(jnp.zeros((nb, nb), G.dtype).at[:w, :w].set(Tk))

        # right LQ on row block A[lo:lo+w, lo+w:] (keeps the upper band)
        if lo + w < n:
            hw = min(nb, m - lo)
            row = G[lo : lo + hw, lo + w :]
            vrL, tausL = _geqrf_kernel(C(row).T)
            VL = materialize_v(vrL, offset=0)  # (n-lo-w, hw)
            TkL = larft(VL, tausL)
            G = G.at[lo : lo + hw, lo + w :].set(C(jnp.triu(vrL)).T)
            # apply from the right to rows below: C <- C (I - VL TkL^H VL^H)^H
            if lo + hw < m:
                Cb = G[lo + hw :, lo + w :]
                Wb = Cb @ VL
                G = G.at[lo + hw :, lo + w :].set(Cb - (Wb @ TkL) @ C(VL).T)
            VV = VV.at[lo + w :, lo : lo + VL.shape[1]].set(VL)
            VTs.append(jnp.zeros((nb, nb), G.dtype).at[:hw, :hw].set(TkL))

    UT = jnp.stack(UTs) if UTs else jnp.zeros((0, nb, nb), G.dtype)
    VT = jnp.stack(VTs) if VTs else jnp.zeros((0, nb, nb), G.dtype)
    band = TriangularBandMatrix(
        tiles_from_global(G, lay), lay, grid=A.grid, kd=nb, uplo=Uplo.Upper
    )
    v_lay = TileLayout(n, n, nb, nb, lay.p, lay.q)
    return (
        band,
        Matrix(tiles_from_global(UV, lay), lay, grid=A.grid),
        TriangularFactors(UT),
        Matrix(tiles_from_global(VV, v_lay), v_lay, grid=A.grid),
        TriangularFactors(VT),
    )


def tb2bd(band: TriangularBandMatrix):
    """Band -> bidiagonal (reference: src/tb2bd.cc bulge chasing).  The
    gathered vendor SVD consumes the band directly (see bdsqr), so this
    returns the band's (d, e) after a dense bidiagonalization on the
    gathered band — kept as an API-parity staging point."""
    G = band.to_global()
    # One-device Householder bidiagonalization of the (narrow-band) matrix
    m, n = G.shape
    k = min(m, n)
    U, s, Vh = svd_accurate(G)
    # represent as exact bidiagonal (diagonal) — svd of band is the vendor
    # stage here
    d = s
    e = jnp.zeros((max(k - 1, 0),), s.dtype)
    return d, e, U, Vh


def bdsqr(d: jnp.ndarray, e: jnp.ndarray, vectors: bool = False):
    """Singular values of a bidiagonal matrix (reference: src/bdsqr.cc QR
    iteration), via the vendor SVD of the assembled bidiagonal."""
    n = d.shape[0]
    B = jnp.zeros((n, n), d.dtype).at[jnp.arange(n), jnp.arange(n)].set(d)
    if n > 1:
        B = B.at[jnp.arange(n - 1), jnp.arange(1, n)].set(e)
    if vectors:
        U, s, Vh = svd_accurate(B)
        return s, U, Vh
    return svd_accurate(B, compute_uv=False), None, None


def svd(
    A: Matrix,
    opts: Optional[Options] = None,
    vectors: bool = False,
) -> Tuple[jnp.ndarray, Optional[Matrix], Optional[Matrix]]:
    """Singular value decomposition (reference: src/svd.cc two-stage:
    ge2tb -> gather -> tb2bd -> bdsqr; tall/wide pre-reduction by QR/LQ
    when m >> n or n >> m, svd.cc:99-141).

    Returns (Sigma, U, VH); U/VH are None unless vectors=True."""
    from . import qr as qr_mod

    m, n = A.m, A.n
    lay = A.layout

    # tall pre-reduction (svd.cc: qr_stage when m >> n)
    if m >= 2 * n:
        fac, Tq = qr_mod.geqrf(A, opts)
        Rg = jnp.triu(fac.to_global()[:n, :n])
        R = Matrix.from_global(Rg, lay.nb, lay.nb, grid=A.grid)
        s, Ur, Vh = svd(R, opts, vectors=vectors)
        if not vectors:
            return s, None, None
        # U = Q [Ur; 0]
        Upad = Matrix.from_global(
            jnp.concatenate(
                [Ur.to_global(), jnp.zeros((m - n, n), A.dtype)], axis=0
            ),
            lay.mb,
            lay.nb,
            grid=A.grid,
        )
        U = qr_mod.unmqr(Side.Left, Op.NoTrans, fac, Tq, Upad, opts)
        return s, U, Vh
    if n >= 2 * m:
        # wide: A^H is tall; A^H = Ut S Vht  =>  A = Vht^H S Ut^H
        Ahr = conj_transpose(A).resolved()
        Ah = Matrix(Ahr.data, Ahr.layout, grid=A.grid)
        s, Ut, Vht = svd(Ah, opts, vectors=vectors)
        if not vectors:
            return s, None, None
        U = Matrix.from_global(
            jnp.conj(Vht.to_global()).T, lay.mb, lay.mb, grid=A.grid
        )
        Vh = Matrix.from_global(
            jnp.conj(Ut.to_global()).T, lay.mb, lay.nb, grid=A.grid
        )
        return s, U, Vh

    band, UVm, UT, VVm, VT = ge2tb(A, opts)
    Gband = band.to_global()
    if not vectors:
        s = svd_accurate(Gband, compute_uv=False)
        return s[: min(m, n)], None, None
    Ub, s, Vhb = svd_accurate(Gband)
    # back-transform (unmbr_ge2tb): U = Q_U Ub, V^H = Vhb Q_V^H
    U = unmbr_ge2tb_left(UVm, UT, Ub, A)
    Vh = unmbr_ge2tb_right(VVm, VT, Vhb, A)
    return s[: min(m, n)], U, Vh


def unmbr_ge2tb_left(UVm: Matrix, UT: TriangularFactors, C2, A: Matrix) -> Matrix:
    """Apply the left (QR-side) ge2tb reflectors: C <- Q_U C
    (reference: src/unmbr_ge2tb.cc)."""
    lay = A.layout
    nb = lay.nb
    UVg = UVm.to_global()
    complex_t = UVm.is_complex

    def C(x):
        return jnp.conj(x) if complex_t else x

    npanels = UT.T.shape[0]
    out = jnp.asarray(C2)
    for k in range(npanels - 1, -1, -1):
        lo = k * nb
        w = min(nb, UVg.shape[1] - lo)
        Vk = UVg[lo:, lo : lo + w]
        Tk = UT.T[k][:w, :w]
        W = C(Vk).T @ out[lo:]
        out = out.at[lo:].set(out[lo:] - Vk @ (Tk @ W))
    return Matrix.from_global(out.astype(A.dtype), lay.mb, lay.nb, grid=A.grid)


def unmbr_ge2tb_right(VVm: Matrix, VT: TriangularFactors, C2, A: Matrix) -> Matrix:
    """Apply the right (LQ-side) reflectors: C <- C Q_V^H."""
    lay = A.layout
    nb = lay.nb
    VVg = VVm.to_global()
    complex_t = VVm.is_complex

    def C(x):
        return jnp.conj(x) if complex_t else x

    npanels = VT.T.shape[0]
    out = jnp.asarray(C2)
    for k in range(npanels - 1, -1, -1):
        lo = k * nb
        co = lo + nb  # columns the k-th LQ panel acts on
        if co >= VVg.shape[0]:
            continue
        w = min(nb, VVg.shape[1] - lo)
        Vk = VVg[co:, lo : lo + w]  # zero-padded columns are no-ops
        Tk = VT.T[k][:w, :w]
        # out <- out Qr_k^H = out (I - Vk Tk^H Vk^H), acting on columns co:
        Wb = out[:, co:] @ Vk
        out = out.at[:, co:].set(out[:, co:] - (Wb @ C(Tk).T) @ C(Vk).T)
    return Matrix.from_global(out.astype(A.dtype), lay.mb, lay.nb, grid=A.grid)
