"""SVD family (reference: src/svd.cc, ge2tb.cc, tb2bd.cc, bdsqr.cc,
unmbr_ge2tb.cc, unmbr_tb2bd.cc; SURVEY §3.5: svd is isomorphic to heev:
ge2tb -> gather -> tb2bd -> bdsqr + back-transforms).

ge2tb (dense -> triangular-band via alternating left QR / right LQ panel
reductions) carries the FLOPs and is implemented with our Householder
kernels; the gathered band stage uses the XLA vendor SVD (the reference
gathers to one node and runs LAPACK-style bulge chasing + bdsqr,
svd.cc:270-304).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..enums import Norm, Op, Option, Side, Uplo
from ..exceptions import slate_assert
from ..matrix.base import BaseMatrix, conj_transpose
from ..matrix.matrix import Matrix, TriangularBandMatrix
from ..options import Options, get_option
from ..ops.householder import larft, materialize_v
from ..ops.jacobi import svd_accurate
from ..parallel.layout import TileLayout, tiles_from_global
from ..types import TriangularFactors

from ..aux.metrics import instrumented
from ..internal.precision import accurate_matmul


from ..matrix.base import is_distributed as _is_distributed


@accurate_matmul
@instrumented("ge2tb")
def ge2tb(
    A: Matrix, opts: Optional[Options] = None
) -> Tuple[TriangularBandMatrix, Matrix, TriangularFactors, Matrix, TriangularFactors]:
    """Reduce general A to upper triangular band form (reference:
    src/ge2tb.cc): alternating panel QR from the left (columns) and panel
    LQ from the right (rows), bandwidth nb.

    Distributed inputs run the shard_map panel pipeline
    (parallel/spmd_ge2tb.py): panel gathers + distributed compact-WY
    trailing updates, no full-matrix gather anywhere in stage 1.

    Returns (band, U_V, U_T, V_V, V_T) with the left/right reflector sets
    for unmbr_ge2tb."""
    from jax import lax

    from ..ops.householder import _geqrf_panel

    lay = A.layout
    nb = lay.nb
    m, n = A.m, A.n

    if (
        _is_distributed(A)
        and get_option(opts, Option.UseShardMap)
        and A.op == Op.NoTrans
        and lay.mb == lay.nb
    ):
        from ..parallel.spmd_ge2tb import spmd_ge2tb

        v_lay = TileLayout(n, n, nb, nb, lay.p, lay.q)
        band_t, UV_t, UT, VV_t, VT = spmd_ge2tb(
            A.grid, A.resolved().data, lay, v_lay
        )
        band = TriangularBandMatrix(
            band_t, lay, grid=A.grid, kd=nb, uplo=Uplo.Upper
        )
        return (
            band,
            Matrix(UV_t, lay, grid=A.grid),
            TriangularFactors(UT),
            Matrix(VV_t, v_lay, grid=A.grid),
            TriangularFactors(VT),
        )

    if _is_distributed(A):
        from ..internal import fallbacks

        fallbacks.record("ge2tb", opts, "viewed / non-square tiles gather")
    G = A.to_global()
    kt = min(lay.mt, lay.nt)
    complex_t = A.is_complex

    def C(x):
        return jnp.conj(x) if complex_t else x

    # static-shape pipeline (see he2hb): per step the active trailing
    # block is rolled to the origin so one traced body serves all kt
    # panels; padded columns yield tau=0 no-op reflectors.
    Mp, Np = lay.mt * nb, lay.nt * nb
    Gp = jnp.pad(G, ((0, Mp - m), (0, Np - n)))
    UV0 = jnp.zeros_like(Gp)
    VV0 = jnp.zeros((Np, Np), Gp.dtype)
    UT0 = jnp.zeros((kt, nb, nb), Gp.dtype)
    VT0 = jnp.zeros((kt, nb, nb), Gp.dtype)
    rowsM = jnp.arange(Mp)
    rowsN = jnp.arange(Np)

    def step(k, carry):
        Gp, UV, VV, UT, VT = carry
        lo = k * nb
        hM = m - lo  # active rows
        # ---- left QR on the rolled frame ----------------------------
        G1 = jnp.roll(Gp, (-lo, -lo), (0, 1))
        actM = (rowsM < hM)[:, None]
        actN = (rowsN < (n - lo))[None, :]
        pan = jnp.where(actM, G1[:, :nb], 0)
        pan = jnp.where((jnp.arange(nb) < (n - lo))[None, :], pan, 0)
        vr, taus = _geqrf_panel(pan)
        V = materialize_v(vr, offset=0)
        Tk = larft(V, taus)
        G1 = G1.at[:, :nb].set(jnp.where(actM, jnp.triu(vr), G1[:, :nb]))
        # trailing columns: C <- (I - V T^H V^H) C
        Ct = jnp.where(actM & actN, G1, 0).at[:, :nb].set(0)
        W = C(V).T @ Ct
        G1 = G1 - jnp.where(actN, V @ (C(Tk).T @ W), 0).at[:, :nb].set(0)
        UVroll = jnp.roll(jnp.where(actM, V, 0), lo, axis=0)
        UV = lax.dynamic_update_slice(UV, UVroll, (0, lo))
        UT = UT.at[k].set(Tk)

        # ---- right LQ on the row block, frame shifted one block right
        G2 = jnp.roll(G1, (0, -nb), (0, 1))  # now rows 0.., cols are lo+nb..
        actN2 = (rowsN < (n - lo - nb))[None, :]
        rowblk = jnp.where(actN2, G2[:nb, :], 0)
        rowblk = jnp.where((jnp.arange(nb) < hM)[:, None], rowblk, 0)
        P2 = C(rowblk).T  # (Np, nb)
        vrL, tausL = _geqrf_panel(P2)
        VL = materialize_v(vrL, offset=0)
        TkL = larft(VL, tausL)
        new_row = C(jnp.triu(vrL)).T
        G2 = G2.at[:nb, :].set(jnp.where(actN2, new_row, G2[:nb, :]))
        # rows below: C <- C (I - VL TkL^H VL^H)^H
        rbelow = (rowsM >= nb)[:, None] & (rowsM < hM)[:, None]
        Cb = jnp.where(rbelow & actN2, G2, 0)
        Wb = Cb @ VL
        G2 = G2 - jnp.where(rbelow, (Wb @ TkL) @ C(VL).T, 0)
        VVroll = jnp.roll(jnp.where(actN2.T, VL, 0), lo + nb, axis=0)
        VV = lax.dynamic_update_slice(VV, VVroll, (0, lo))
        VT = VT.at[k].set(TkL)

        Gp = jnp.roll(G2, (lo, lo + nb), (0, 1))
        return Gp, UV, VV, UT, VT

    Gp, UVp, VVp, UT, VT = lax.fori_loop(
        0, kt, step, (Gp, UV0, VV0, UT0, VT0)
    )
    G = Gp[:m, :n]
    UV = UVp[:m, :n]
    VV = VVp[:n, :n]
    band = TriangularBandMatrix(
        tiles_from_global(G, lay), lay, grid=A.grid, kd=nb, uplo=Uplo.Upper
    )
    v_lay = TileLayout(n, n, nb, nb, lay.p, lay.q)
    return (
        band,
        Matrix(tiles_from_global(UV, lay), lay, grid=A.grid),
        TriangularFactors(UT),
        Matrix(tiles_from_global(VV, v_lay), v_lay, grid=A.grid),
        TriangularFactors(VT),
    )



def _jw_band_storage(Dg: jnp.ndarray, b: int, n: int):
    """Diagonal-major band storage of the perfect-shuffle Jordan-Wielandt
    embedding C = P [[0, B], [B^H, 0]] P^T of an upper-band B given by
    its packed superdiagonals Dg[t, i] = B[i, i+t], t in [0, b]: C is
    Hermitian banded with bandwidth 2b+1, entries C[2j+1, 2i] =
    conj(B[i, i + (d-1)/2]) on the odd subdiagonals of the even columns
    (Golub-Kahan; eigenvalues come in +-sigma pairs and eigenvectors
    shuffle to (u; v)/sqrt(2))."""
    bw = 2 * b + 1
    n2 = 2 * n
    n_pad = n2 + 4 * bw + 8
    W = jnp.zeros((2 * bw + 1, n_pad), Dg.dtype)
    for t in range(b + 1):
        dd = 2 * t + 1
        diag_t = jnp.conj(Dg[t, : n - t])  # (n - t,)
        cols = 2 * jnp.arange(n - t)
        W = W.at[dd, cols].set(diag_t)
    return W, bw, n2


def _band_svd_jw(Dg: jnp.ndarray, n: int, b: int, vectors: bool):
    """SVD of an upper-triangular band matrix (packed superdiagonals Dg,
    shape (b+1, n)) through the shuffled Jordan-Wielandt embedding + the
    hb2st bulge chase: the TPU-native stage 2 (replaces reference tb2bd
    + bdsqr, src/tb2bd.cc, src/bdsqr.cc).  Returns (s desc, U, Vh) with
    U/Vh None unless requested."""
    import jax

    from .. import native as _native
    from ..ops import bulge as bulge_mod
    from .eig import steqr

    dtype = Dg.dtype
    W, bw, n2 = _jw_band_storage(Dg, b, n)
    # native host chaser for eager real f64 (see drivers/eig.py heev)
    host_ok = (
        not isinstance(W, jax.core.Tracer)
        and not jnp.issubdtype(dtype, jnp.complexfloating)
        and W.dtype == jnp.float64
        and _native.hb2st_available()
    )
    if host_ok:
        d_h, e_h, VS, TAUS = _native.hb2st_host_device(np.asarray(W), n2, bw)
        d, e = jnp.asarray(d_h), jnp.asarray(e_h)
        u = jnp.ones((n2,), dtype)
    else:
        d, e, u, VS, TAUS = bulge_mod.hb2st(W, n2, bw)
    if not vectors:
        w = bulge_mod.tridiag_eigvals_bisect(d, e)
        return w[::-1][:n], None, None
    w, ZT = steqr(d, e, vectors=True)
    Zjw = bulge_mod.unmtr_hb2st(
        VS, TAUS, (u[:, None] * ZT).astype(dtype), n2, bw
    )
    top = jnp.argsort(-w)[:n]
    s = w[top]
    Zsel = Zjw[:, top] * np.sqrt(2.0)
    U = Zsel[0::2, :]
    V = Zsel[1::2, :]
    return s, U, jnp.conj(V).T if jnp.issubdtype(dtype, jnp.complexfloating) else V.T


@accurate_matmul
@instrumented("tb2bd")
def tb2bd(band: TriangularBandMatrix):
    """Band -> bidiagonal (reference: src/tb2bd.cc bulge chasing).

    slate_tpu's band stage goes band -> shuffled Jordan-Wielandt ->
    tridiagonal directly (_band_svd_jw), so this API-parity wrapper
    returns the computed singular values as the bidiagonal's diagonal
    (e = 0), plus the band-stage vectors."""
    G = band.to_global()
    m, n = G.shape
    k = min(m, n)
    b = getattr(band, "kd", n)
    if m >= n and n > 4 * (2 * b + 1) and b >= 1:
        t_ = jnp.arange(b + 1)[:, None]
        i_ = jnp.arange(n)[None, :]
        Dg = jnp.stack(
            [jnp.pad(jnp.diagonal(G[:n, :n], t), (0, t)) for t in range(b + 1)]
        )
        Dg = jnp.where(i_ + t_ < n, Dg, 0)
        s, U, Vh = _band_svd_jw(Dg, n, b, vectors=True)
    else:
        U, s, Vh = svd_accurate(G)
    d = s
    e = jnp.zeros((max(k - 1, 0),), jnp.real(G[:1, :1]).dtype)
    return d, e, U, Vh


@accurate_matmul
@instrumented("bdsqr")
def bdsqr(d: jnp.ndarray, e: jnp.ndarray, vectors: bool = False):
    """Singular values of a real bidiagonal matrix (reference:
    src/bdsqr.cc QR iteration): the Golub-Kahan tridiagonal
    tridiag(0; [d1, e1, d2, e2, ...]) has eigenvalues +-sigma, solved by
    the parallel Sturm bisection (values) or the polished dense
    tridiagonal eigensolver (vectors).  The [d1, e1, ...] off-diagonal
    corresponds to the (v1, u1, v2, u2, ...) shuffle, so eigenvectors
    split as v = z[0::2], u = z[1::2]."""
    from ..ops import bulge as bulge_mod

    n = d.shape[0]
    if n == 0:
        return d, None, None
    off = jnp.zeros((2 * n - 1,), jnp.real(d[:1]).dtype)
    off = off.at[0::2].set(jnp.real(d))
    if n > 1:
        off = off.at[1::2].set(jnp.real(e))
    dz = jnp.zeros((2 * n,), off.dtype)
    if not vectors:
        w = bulge_mod.tridiag_eigvals_bisect(dz, off)
        return w[::-1][:n], None, None
    from .eig import steqr

    w, Z = steqr(dz, off, vectors=True)
    top = jnp.argsort(-w)[:n]
    s = w[top]
    Zsel = Z[:, top] * np.sqrt(2.0)
    return s, Zsel[1::2, :], Zsel[0::2, :].T


@accurate_matmul
@instrumented("svd")
def svd(
    A: Matrix,
    opts: Optional[Options] = None,
    vectors: bool = False,
) -> Tuple[jnp.ndarray, Optional[Matrix], Optional[Matrix]]:
    """Singular value decomposition (reference: src/svd.cc two-stage:
    ge2tb -> gather -> tb2bd -> bdsqr; tall/wide pre-reduction by QR/LQ
    when m >> n or n >> m, svd.cc:99-141).

    Returns (Sigma, U, VH); U/VH are None unless vectors=True."""
    from . import qr as qr_mod

    m, n = A.m, A.n
    lay = A.layout

    # tall pre-reduction (svd.cc: qr_stage when m >> n)
    if m >= 2 * n:
        fac, Tq = qr_mod.geqrf(A, opts)
        Rg = jnp.triu(fac.to_global()[:n, :n])
        R = Matrix.from_global(Rg, lay.nb, lay.nb, grid=A.grid)
        s, Ur, Vh = svd(R, opts, vectors=vectors)
        if not vectors:
            return s, None, None
        # U = Q [Ur; 0]
        Upad = Matrix.from_global(
            jnp.concatenate(
                [Ur.to_global(), jnp.zeros((m - n, n), A.dtype)], axis=0
            ),
            lay.mb,
            lay.nb,
            grid=A.grid,
        )
        U = qr_mod.unmqr(Side.Left, Op.NoTrans, fac, Tq, Upad, opts)
        return s, U, Vh
    if n >= 2 * m:
        # wide: A^H is tall; A^H = Ut S Vht  =>  A = Vht^H S Ut^H
        Ahr = conj_transpose(A).resolved()
        Ah = Matrix(Ahr.data, Ahr.layout, grid=A.grid)
        s, Ut, Vht = svd(Ah, opts, vectors=vectors)
        if not vectors:
            return s, None, None
        U = Matrix.from_global(
            jnp.conj(Vht.to_global()).T, lay.mb, lay.mb, grid=A.grid
        )
        Vh = Matrix.from_global(
            jnp.conj(Ut.to_global()).T, lay.mb, lay.nb, grid=A.grid
        )
        return s, U, Vh

    band, UVm, UT, VVm, VT = ge2tb(A, opts)
    b = lay.nb
    k = min(m, n)
    # stage 2: the JW bulge-chase when the band is genuinely narrow
    use_jw = (n <= m) and (n > 4 * (2 * b + 1)) and b >= 1
    if use_jw:
        # band-limited stage gather (ge2tbGather semantics): only the
        # O(n kd) packed superdiagonals move between the stages
        # (reference: TriangularBandMatrix.hh:327, svd.cc:270-304)
        from ..parallel.band_gather import (
            spmd_upper_band_diagonals,
            upper_band_diagonals_tiles,
        )

        if (
            _is_distributed(band)
            and get_option(opts, Option.UseShardMap)
            and band.layout.mb == band.layout.nb
        ):
            Dg = spmd_upper_band_diagonals(
                band.grid, band.data, band.layout, n
            )
        else:
            Dg = upper_band_diagonals_tiles(band.data, band.layout, n)
        if not vectors:
            return _band_svd_jw(Dg, n, b, vectors=False)[0], None, None
        s, Ub, Vhb = _band_svd_jw(Dg, n, b, vectors=True)
        if m > n:
            Ub = jnp.concatenate(
                [Ub, jnp.zeros((m - n, n), A.dtype)], axis=0
            )
    else:
        Gband = band.to_global()
        if not vectors:
            s = svd_accurate(Gband, compute_uv=False)
            return s[:k], None, None
        Ub, s, Vhb = svd_accurate(Gband)
    # back-transform (unmbr_ge2tb): U = Q_U Ub, V^H = Vhb Q_V^H
    U = unmbr_ge2tb_left(UVm, UT, Ub, A, opts)
    Vh = unmbr_ge2tb_right(VVm, VT, Vhb, A, opts)
    return s[: min(m, n)], U, Vh


@accurate_matmul
@instrumented("unmbr_ge2tb_left")
def unmbr_ge2tb_left(
    UVm: Matrix,
    UT: TriangularFactors,
    C2,
    A: Matrix,
    opts: Optional[Options] = None,
) -> Matrix:
    """Apply the left (QR-side) ge2tb reflectors: C <- Q_U C
    (reference: src/unmbr_ge2tb.cc)."""
    lay = A.layout
    nb = lay.nb

    if (
        _is_distributed(UVm)
        and get_option(opts, Option.UseShardMap)
        and UVm.op == Op.NoTrans
        and lay.mb == lay.nb
        and UT.T.shape[0] > 0
    ):
        from ..parallel.spmd_ge2tb import spmd_unmbr_ge2tb_left

        C2a = jnp.asarray(C2).astype(A.dtype)
        c_lay = TileLayout(
            C2a.shape[0], C2a.shape[1], lay.mb, lay.nb, lay.p, lay.q
        )
        Cm = Matrix(tiles_from_global(C2a, c_lay), c_lay, grid=A.grid).shard()
        Ct = spmd_unmbr_ge2tb_left(
            UVm.grid, UVm.data, UT.T, Cm.data, UVm.layout, c_lay
        )
        return Cm._with(data=Ct)

    from jax import lax

    if _is_distributed(UVm):
        from ..internal import fallbacks

        fallbacks.record("unmbr_ge2tb_left", opts, "op view / gate miss")
    UVg = UVm.to_global()
    complex_t = UVm.is_complex

    def C(x):
        return jnp.conj(x) if complex_t else x

    npanels = UT.T.shape[0]
    out = jnp.asarray(C2)
    if npanels == 0:
        return Matrix.from_global(out.astype(A.dtype), lay.mb, lay.nb, grid=A.grid)
    # static-shape fori_loop over panels (compile time flat in panel
    # count): V_k is zero above row k nb and zero in absent columns, and
    # absent reflectors have zero T rows/cols, so full-width applies are
    # exact no-ops there.
    Vp = jnp.pad(UVg, ((0, 0), (0, max(npanels * nb - UVg.shape[1], 0))))
    Ts = UT.T

    def step(i, out):
        k = npanels - 1 - i
        Vk = lax.dynamic_slice_in_dim(Vp, k * nb, nb, axis=1)
        Tk = lax.dynamic_index_in_dim(Ts, k, 0, keepdims=False)
        W = C(Vk).T @ out
        return out - Vk @ (Tk @ W)

    out = lax.fori_loop(0, npanels, step, out)
    return Matrix.from_global(out.astype(A.dtype), lay.mb, lay.nb, grid=A.grid)


@accurate_matmul
@instrumented("unmbr_ge2tb_right")
def unmbr_ge2tb_right(
    VVm: Matrix,
    VT: TriangularFactors,
    C2,
    A: Matrix,
    opts: Optional[Options] = None,
) -> Matrix:
    """Apply the right (LQ-side) reflectors: C <- C Q_V^H."""
    lay = A.layout
    nb = lay.nb

    if (
        _is_distributed(VVm)
        and get_option(opts, Option.UseShardMap)
        and VVm.op == Op.NoTrans
        and lay.mb == lay.nb
        and VT.T.shape[0] > 0
    ):
        from ..parallel.spmd_ge2tb import spmd_unmbr_ge2tb_right

        C2a = jnp.asarray(C2).astype(A.dtype)
        c_lay = TileLayout(
            C2a.shape[0], C2a.shape[1], lay.nb, lay.nb, lay.p, lay.q
        )
        Cm = Matrix(tiles_from_global(C2a, c_lay), c_lay, grid=A.grid).shard()
        Ct = spmd_unmbr_ge2tb_right(
            VVm.grid, VVm.data, VT.T, Cm.data, VVm.layout, c_lay
        )
        return Cm._with(data=Ct)

    from jax import lax

    if _is_distributed(VVm):
        from ..internal import fallbacks

        fallbacks.record("unmbr_ge2tb_right", opts, "op view / gate miss")
    VVg = VVm.to_global()
    complex_t = VVm.is_complex

    def C(x):
        return jnp.conj(x) if complex_t else x

    npanels = VT.T.shape[0]
    out = jnp.asarray(C2)
    if npanels == 0:
        return Matrix.from_global(out.astype(A.dtype), lay.mb, lay.nb, grid=A.grid)
    # static-shape fori_loop (see unmbr_ge2tb_left): V_k is zero above
    # row (k+1) nb and in absent columns, absent reflectors have zero T
    # rows/cols, so the full-width apply is exact.
    Vp = jnp.pad(VVg, ((0, 0), (0, max(npanels * nb - VVg.shape[1], 0))))
    Ts = VT.T

    def step(i, out):
        k = npanels - 1 - i
        Vk = lax.dynamic_slice_in_dim(Vp, k * nb, nb, axis=1)
        Tk = lax.dynamic_index_in_dim(Ts, k, 0, keepdims=False)
        # out <- out Qr_k^H = out (I - Vk Tk^H Vk^H)
        Wb = out @ Vk
        return out - (Wb @ C(Tk).T) @ C(Vk).T

    out = lax.fori_loop(0, npanels, step, out)
    return Matrix.from_global(out.astype(A.dtype), lay.mb, lay.nb, grid=A.grid)
