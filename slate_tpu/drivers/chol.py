"""Cholesky family drivers (reference: src/potrf.cc, potrs.cc, posv.cc,
trtri.cc, trtrm.cc, potri.cc, posv_mixed.cc, pocondest.cc).

potrf is the factorization archetype (SURVEY §3.2): panel factor ->
broadcast -> trsm -> trailing herk with lookahead.  On TPU the global path
runs the native blocked schedule in ops/chol_kernels.py (the vendor
cholesky lowering is ~3% of the chip's gemm rate on this toolchain; CPU
keeps the vendor LAPACK kernel); the spmd path runs the explicit mesh
algorithm in parallel/spmd_chol.py.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..enums import Diag, Op, Option, Side, Uplo
from ..exceptions import DimensionError, NumericalError, slate_assert
from ..matrix.base import BaseMatrix, conj_transpose
from ..matrix.matrix import HermitianMatrix, Matrix, SymmetricMatrix, TriangularMatrix
from ..options import Options, get_option, resolve_schedule_opts
from ..ops import blas2d, chol_kernels
from ..parallel import spmd_chol
from ..parallel.layout import eye_splice, tiles_from_global
from . import blas3

from ..aux import metrics
from ..aux.metrics import instrumented


from ..matrix.base import is_distributed as _is_distributed
from ..internal import fallbacks

# metrics-gated jitted kernel: with metrics ON the eager global path
# dispatches through this wrapper so the compile/run split and the
# cost_analysis flops are attributed to "potrf.kernel"; with metrics off
# the original unjitted call runs, bit-identical to before.  The
# operand (a freshly mirrored full_global copy, never user storage) is
# donated on accelerators when this jit dispatches (metrics-on eager
# calls; inside an outer jit — serve cores, bench steps — the outer
# boundary owns donation, see serve/cache.py).
_cholesky_kernel = metrics.gated_jit(
    chol_kernels.cholesky, "potrf.kernel",
    static_argnums=(1, 2, 3, 4), donate_argnums=(0,),
)


def _hermitian_full_tiles(A: HermitianMatrix) -> jnp.ndarray:
    """Mirror the stored triangle into a full tile array (keeps sharding)."""
    return tiles_from_global(A.full_global().astype(A.dtype), A.layout)


@instrumented("potrf")
def potrf(
    A: HermitianMatrix, opts: Optional[Options] = None
) -> Tuple[TriangularMatrix, jnp.ndarray]:
    """Cholesky: A = L L^H (uplo Lower) or U^H U (Upper)
    (reference: src/potrf.cc:84-209).

    Returns (factor, info); info > 0 signals a non-SPD matrix, detected
    from non-finite entries like internal::reduce_info aggregates the
    per-rank codes (potrf.cc:208).
    """
    slate_assert(A.m == A.n, "potrf requires square A")
    slate_assert(A.layout.mb == A.layout.nb, "potrf requires square tiles")

    use_spmd = _is_distributed(A) and get_option(opts, Option.UseShardMap)
    if use_spmd:
        if A.uplo == Uplo.Lower and A.op == Op.NoTrans:
            # spmd_potrf_lower reads only the stored lower triangle —
            # no mirror round trip needed
            T = A.data
        else:
            fallbacks.record(
                "potrf.mirror", opts, "upper/viewed Hermitian mirrors globally"
            )
            T = _hermitian_full_tiles(A)
        T = eye_splice(A.layout, T)
        Ld = spmd_chol.spmd_potrf_lower(A.grid, T, A.layout)
        L = TriangularMatrix(Ld, A.layout, grid=A.grid, uplo=Uplo.Lower)
    else:
        if _is_distributed(A):
            fallbacks.record("potrf", opts, "UseShardMap disabled")
        full = A.full_global()
        n = A.n
        lay = A.layout
        # schedule-dispatched kernel (ops/chol_kernels.py; handles
        # padding/splicing for any n internally): the vendor lowering
        # runs at ~3% of the chip's gemm rate, the flat blocked loop
        # burns ~2-3x the model FLOPs, the recursive schedule factors
        # exact halving-lattice shapes.  nb is clamped to 512: larger
        # blocks would push chol_unblocked into its bandwidth-bound
        # regime.
        sched, nb_switch, lookahead = resolve_schedule_opts(opts)
        nb_kernel = 512 if n >= 2048 else min(lay.nb, 512)
        if metrics.is_on():
            route = chol_kernels.resolve_schedule(n, sched)
            metrics.record_factor_flops(
                "potrf",
                chol_kernels.chol_schedule_flops(
                    n, nb_kernel, route, nb_switch, lookahead
                ),
            )
        L2 = _cholesky_kernel(full, nb_kernel, sched, nb_switch, lookahead)
        L = TriangularMatrix.from_global(L2, lay.mb, lay.nb, grid=A.grid, uplo=Uplo.Lower)

    info = jnp.where(jnp.all(jnp.isfinite(L.data)), 0, 1).astype(jnp.int32)

    if A.uplo == Uplo.Upper:
        U = conj_transpose(L).resolved()
        U = TriangularMatrix(U.data, U.layout, grid=A.grid, uplo=Uplo.Upper)
        return U, info
    return L, info


@instrumented("potrs")
def potrs(
    L: TriangularMatrix, B: Matrix, opts: Optional[Options] = None
) -> Matrix:
    """Solve A X = B given the Cholesky factor (reference: src/potrs.cc:
    two trsm sweeps)."""
    if L.uplo == Uplo.Lower:
        Y = blas3.trsm(Side.Left, 1.0, L, B, opts)
        X = blas3.trsm(Side.Left, 1.0, conj_transpose(L), Y, opts)
    else:
        Y = blas3.trsm(Side.Left, 1.0, conj_transpose(L), B, opts)
        X = blas3.trsm(Side.Left, 1.0, L, Y, opts)
    return X


def _solve_trsm_route(n: int, schedule: str) -> str:
    """Schedule routing for the solve-phase trsm pair: explicit
    ``pallas`` is honored everywhere (interpret mode off-TPU); ``auto``
    prefers the Pallas pair on accelerators above the same crossover as
    the factor schedules, the vendor solve otherwise."""
    if schedule == "pallas":
        return "pallas"
    if (
        schedule == "auto"
        and jax.default_backend() != "cpu"
        and n >= chol_kernels.RECURSIVE_MIN_N
    ):
        return "pallas"
    return "vendor"


def potrs_from_global(
    Lg: jnp.ndarray, Bg: jnp.ndarray, schedule: str = "auto"
) -> jnp.ndarray:
    """potrs-style solve-only entry point over global arrays: solve
    L L^H X = B by two trsm sweeps against a clean lower-triangular
    factor.  The O(n^2) steady-state kernel of the serve factor
    cache's trsm-only (``phase="solve"``) bucket family; fully
    traceable (jit/vmap).  ``schedule="pallas"`` (or ``auto`` on an
    accelerator above the crossover) runs both sweeps through the
    fused Pallas trsm pair (ops/pallas/panel_kernels.py)."""
    cplx = jnp.iscomplexobj(Lg)
    if _solve_trsm_route(Lg.shape[0], schedule) == "pallas":
        from ..ops.pallas import panel_kernels as pk

        Y = pk.trsm_lower(Lg, Bg)
        U = jnp.conj(Lg).T if cplx else Lg.T
        return pk.trsm_upper(U, Y)
    Y = lax.linalg.triangular_solve(Lg, Bg, left_side=True, lower=True)
    return lax.linalg.triangular_solve(
        Lg, Y, left_side=True, lower=True, transpose_a=True,
        conjugate_a=cplx,
    )


@instrumented("posv")
def posv(
    A: HermitianMatrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, TriangularMatrix, jnp.ndarray]:
    """Solve SPD A X = B (reference: src/posv.cc = potrf + potrs).

    Returns (X, factor, info)."""
    L, info = potrf(A, opts)
    X = potrs(L, B, opts)
    return X, L, info


@instrumented("trtri")
def trtri(T: TriangularMatrix, opts: Optional[Options] = None) -> TriangularMatrix:
    """Triangular inverse (reference: src/trtri.cc) via solve vs identity."""
    slate_assert(T.m == T.n, "trtri requires square")
    A2 = T._with(op=Op.NoTrans).to_global()
    eye = jnp.eye(T.m, dtype=T.dtype)
    inv = blas2d.trsm2d(Side.Left, T.uplo, T.op, T.diag, 1.0, A2, eye)
    # op(A)^-1 lives in the triangle of op(A), not of the storage: a
    # transposed view inverts into the opposite triangle (mirrors
    # resolved()'s uplo swap).
    out_uplo = T.uplo
    if T.op != Op.NoTrans:
        out_uplo = Uplo.Upper if T.uplo == Uplo.Lower else Uplo.Lower
    out = TriangularMatrix.from_global(
        inv, T.layout.mb, T.layout.nb, grid=T.grid, uplo=out_uplo, diag=T.diag
    )
    return out


def trtrm(L: TriangularMatrix, opts: Optional[Options] = None) -> HermitianMatrix:
    """L^H L (or U U^H) keeping the triangle — the second half of potri
    (reference: src/trtrm.cc)."""
    Lg = L._with(op=Op.NoTrans).to_global()
    if L.uplo == Uplo.Lower:
        tri = jnp.tril(Lg)
        out = jnp.conj(tri).T @ tri if L.is_complex else tri.T @ tri
    else:
        tri = jnp.triu(Lg)
        out = tri @ jnp.conj(tri).T if L.is_complex else tri @ tri.T
    return HermitianMatrix.from_global(
        out, L.layout.mb, L.layout.nb, grid=L.grid, uplo=L.uplo
    )


@instrumented("potri")
def potri(L: TriangularMatrix, opts: Optional[Options] = None) -> HermitianMatrix:
    """SPD inverse from the Cholesky factor: A^-1 = L^-H L^-1
    (reference: src/potri.cc = trtri + trtrm)."""
    Linv = trtri(L, opts)
    return trtrm(Linv, opts)


# Mixed-precision SPD solvers: implementations live in
# drivers/mixed.py, routed through the refine/ subsystem (policy +
# IR/GMRES-IR cores); re-exported here for reference-parity import
# paths (chol.posv_mixed).
from .mixed import posv_mixed, posv_mixed_gmres  # noqa: E402,F401


def pocondest(
    L: TriangularMatrix, anorm, opts: Optional[Options] = None
):
    """Reciprocal condition estimate from the Cholesky factor (reference:
    src/pocondest.cc via Hager/Higham 1-norm estimation,
    internal_norm1est.cc:1-511): O(n^2) factor solves per probe instead
    of the O(n^3) explicit inverse; A^-1 is self-adjoint so one solve
    closure serves both directions."""
    from ..internal.norm1est import norm1est

    G = L._with(op=Op.NoTrans).to_global()
    n = G.shape[0]
    lower = L.uplo == Uplo.Lower
    cplx = L.is_complex

    def solve(R):
        Y = lax.linalg.triangular_solve(
            G, R, left_side=True, lower=lower, transpose_a=not lower,
            conjugate_a=cplx and not lower,
        )
        return lax.linalg.triangular_solve(
            G, Y, left_side=True, lower=lower, transpose_a=lower,
            conjugate_a=cplx and lower,
        )

    est = norm1est(solve, solve, n, L.dtype)
    rcond = 1.0 / (jnp.asarray(anorm) * est)
    return jnp.where(jnp.isfinite(rcond), rcond, 0.0)


