"""Auxiliary elementwise/norm drivers (reference: src/add.cc, copy.cc,
scale.cc, scale_row_col.cc, set.cc, set_lambdas (src/set.cc), norm.cc,
colNorms -> NormScope, print.cc, redistribute.cc).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from ..enums import Diag, Norm, NormScope, Op, Uplo
from ..exceptions import DimensionError
from ..internal import norms as _norms
from ..internal import tile_ops
from ..matrix.base import BaseMatrix
from ..matrix.matrix import BaseTrapezoidMatrix, HermitianMatrix, Matrix, SymmetricMatrix
from ..parallel.layout import TileLayout, tiles_from_global


def _check_same_shape(A: BaseMatrix, B: BaseMatrix):
    if (A.m, A.n) != (B.m, B.n):
        raise DimensionError(f"shape mismatch {A.m}x{A.n} vs {B.m}x{B.n}")


def add(alpha, A: BaseMatrix, beta, B: BaseMatrix, opts=None) -> BaseMatrix:
    """B = alpha A + beta B (reference: src/add.cc -> internal geadd/tzadd)."""
    _check_same_shape(A, B)
    Ar, Br = A.resolved(), B.resolved()
    if Ar.layout == Br.layout:
        if isinstance(B, BaseTrapezoidMatrix) and B.uplo != Uplo.General:
            mask = Br.tri_mask()
            out = tile_ops.tzadd(mask, alpha, Ar.data, beta, Br.data)
        else:
            out = tile_ops.geadd(alpha, Ar.data, beta, Br.data)
        return Br._with(data=out)
    # layout mismatch: go through global arrays
    out2d = alpha * Ar.to_global() + beta * Br.to_global()
    return Br._with(data=tiles_from_global(out2d.astype(B.dtype), Br.layout))


def copy(A: BaseMatrix, B: BaseMatrix, opts=None) -> BaseMatrix:
    """B = A with optional precision conversion (reference: src/copy.cc);
    also the precision-converting copy used by mixed-precision solvers."""
    _check_same_shape(A, B)
    Ar, Br = A.resolved(), B.resolved()
    if Ar.layout == Br.layout:
        return Br._with(data=Ar.data.astype(B.dtype))
    return Br._with(
        data=tiles_from_global(Ar.to_global().astype(B.dtype), Br.layout)
    )


def scale(numer, denom, A: BaseMatrix, opts=None) -> BaseMatrix:
    """A *= numer/denom (reference: src/scale.cc)."""
    Ar = A.resolved()
    if isinstance(A, BaseTrapezoidMatrix) and A.uplo != Uplo.General:
        out = tile_ops.tzscale(Ar.tri_mask(), numer, denom, Ar.data)
    else:
        out = tile_ops.gescale(numer, denom, Ar.data)
    return Ar._with(data=out)


def scale_row_col(
    R: Optional[jnp.ndarray],
    C: Optional[jnp.ndarray],
    A: BaseMatrix,
    opts=None,
) -> BaseMatrix:
    """A = diag(R) A diag(C) (reference: src/scale_row_col.cc, Equed)."""
    Ar = A.resolved()
    out = tile_ops.gescale_row_col(Ar.layout, R, C, Ar.data)
    return Ar._with(data=out)


def set(offdiag_value, diag_value, A: BaseMatrix, opts=None) -> BaseMatrix:
    """A = offdiag everywhere, diag on the diagonal (reference: src/set.cc)."""
    Ar = A.resolved()
    if isinstance(A, BaseTrapezoidMatrix) and A.uplo != Uplo.General:
        out = tile_ops.tzset(Ar.layout, Ar.uplo, offdiag_value, diag_value, Ar.data)
    else:
        out = tile_ops.geset(Ar.layout, offdiag_value, diag_value, Ar.data)
    return Ar._with(data=out)


def set_lambdas(value_fn: Callable, A: BaseMatrix, opts=None) -> BaseMatrix:
    """A[i, j] = value_fn(i, j) elementwise over global indices
    (reference: src/set.cc set(lambda) variant used by matgen).

    value_fn receives broadcast (i, j) index arrays and must be
    jnp-traceable; evaluated only on valid elements, padding stays 0.
    """
    Ar = A.resolved()
    lay = Ar.layout
    gr = jnp.asarray(lay.global_rows_np)[:, None, :, None]
    gc = jnp.asarray(lay.global_cols_np)[None, :, None, :]
    vals = value_fn(gr, gc).astype(A.dtype)
    vals = jnp.broadcast_to(vals, lay.storage_shape)
    out = jnp.where(lay.element_mask(), vals, jnp.zeros_like(vals))
    return Ar._with(data=out)


def norm(
    norm_type: Norm,
    A: BaseMatrix,
    scope: NormScope = NormScope.Matrix,
    opts=None,
):
    """Matrix / column / row norms (reference: src/norm.cc dispatching to
    internal::genorm/synorm/henorm/trnorm with MPI allreduce; here one
    masked XLA reduction, psum'd automatically when sharded)."""
    Ar = A.resolved()
    # Pallas tile kernels only for single-chip arrays; sharded arrays
    # stay on the GSPMD jnp path so the reductions lower to psum/pmax.
    pallas_ok = A.grid is None or A.grid.size == 1
    if isinstance(A, HermitianMatrix):
        return _norms.henorm(norm_type, Ar.data, Ar.layout, Ar.uplo)
    if isinstance(A, SymmetricMatrix):
        return _norms.synorm(norm_type, Ar.data, Ar.layout, Ar.uplo)
    if isinstance(A, BaseTrapezoidMatrix) and A.uplo != Uplo.General:
        return _norms.trnorm(norm_type, Ar.data, Ar.layout, Ar.uplo, Ar.diag)
    return _norms.genorm(norm_type, Ar.data, Ar.layout, scope, pallas_ok=pallas_ok)


def colNorms(norm_type: Norm, A: BaseMatrix, opts=None):
    """Per-column norms (reference: src/colNorms.cc, Norm.One scope)."""
    return norm(norm_type if norm_type else Norm.One, A, scope=NormScope.Columns)


def redistribute(A: BaseMatrix, B: BaseMatrix, opts=None) -> BaseMatrix:
    """Copy A into B's (different) distribution (reference:
    src/redistribute.cc — per-tile sends between the two layouts).

    Distributed same-grid inputs run the SPMD two-phase masked-psum
    re-send (parallel/spmd_redistribute.py — O(n^2/q + n^2/p) per
    process, the explicit-traffic analogue of the reference's per-tile
    sends).  Otherwise: one storage-to-storage gather — every element
    of B's tile array addresses its source element in A's tile array
    directly (no padded global intermediate); under sharded inputs
    GSPMD lowers the gather to collectives it is free to implement by
    replicating A, so that route is recorded (internal/fallbacks)."""
    _check_same_shape(A, B)
    from ..enums import Option as _Opt
    from ..matrix.base import is_distributed as _is_dist
    from ..options import get_option as _get

    if (
        (_is_dist(A) or _is_dist(B))
        and _get(opts, _Opt.UseShardMap)
        and A.op == Op.NoTrans
        and B.op == Op.NoTrans
        and (A.layout.p, A.layout.q) == (B.layout.p, B.layout.q)
        and A.grid is not None
        and A.layout.p * A.layout.q > 1
    ):
        from ..parallel.spmd_redistribute import spmd_redistribute

        out = spmd_redistribute(
            A.grid, A.data, A.layout, B.layout, out_dtype=B.dtype
        )
        return B._with(data=out).shard()

    if _is_dist(A) or _is_dist(B):
        from ..internal import fallbacks

        fallbacks.record(
            "redistribute", opts, "GSPMD element gather may replicate A"
        )
    Ar, Br = A.resolved(), B.resolved()
    layA, layB = Ar.layout, Br.layout

    def row_maps(gl, mb_a, srow, mtA):
        ti = np.minimum(gl // mb_a, mtA - 1)
        return srow(ti).astype(np.int32), (gl % mb_a).astype(np.int32)

    grB = np.minimum(layB.global_rows_np, layA.m - 1)
    gcB = np.minimum(layB.global_cols_np, layA.n - 1)
    RS, RA = row_maps(grB, layA.mb, lambda t: layA.srow(t), layA.mt)
    CS, CB = row_maps(gcB, layA.nb, lambda t: layA.scol(t), layA.nt)
    out = Ar.data[
        jnp.asarray(RS)[:, None, :, None],
        jnp.asarray(CS)[None, :, None, :],
        jnp.asarray(RA)[:, None, :, None],
        jnp.asarray(CB)[None, :, None, :],
    ]
    out = jnp.where(layB.element_mask(), out, 0).astype(B.dtype)
    return Br._with(data=out).shard()


def print_matrix(label: str, A: BaseMatrix, opts=None, verbose: int = 4,
                 width: int = 10, precision: int = 4) -> str:
    """Distributed matrix printing (reference: src/print.cc — gathers to
    rank 0 and formats; PrintVerbose levels enums.hh:477-487)."""
    if verbose <= 0:
        return ""
    header = (
        f"% {label}: {type(A).__name__} {A.m}x{A.n}, "
        f"tiles {A.mb}x{A.nb}, grid {A.layout.p}x{A.layout.q}\n"
    )
    if verbose == 1:
        return header
    G = np.asarray(A.to_global())
    if verbose == 2:
        edge = 4
        G = np.block(
            [
                [G[:edge, :edge], G[:edge, -edge:]],
                [G[-edge:, :edge], G[-edge:, -edge:]],
            ]
        )
    body_lines = []
    fmt = f"%{width}.{precision}f"
    for row in G:
        if np.iscomplexobj(row):
            body_lines.append(
                " ".join(
                    (fmt % v.real) + ("+" + (fmt % v.imag).strip() + "i")
                    for v in row
                )
            )
        else:
            body_lines.append(" ".join(fmt % v for v in row))
    text = header + label + " = [\n" + "\n".join(body_lines) + "\n]\n"
    return text
