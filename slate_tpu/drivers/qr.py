"""QR/LQ/least-squares drivers (reference: src/geqrf.cc, unmqr.cc,
gelqf.cc, unmlq.cc, cholqr.cc, gels.cc, gels_qr.cc, gels_cholqr.cc).

Factor representation: the returned matrix stores R on/above the diagonal
and the Householder vectors V (implicit unit diagonal) below; the
TriangularFactors hold one compact-WY T per tile panel — the reference's
Tlocal (slate.hh TriangularFactors).  The reference's Treduce (CAQR tree
factors, internal_ttqrt.cc) has no analogue because the spmd path gathers
panels instead of tree-reducing them (see parallel/spmd_qr.py).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..enums import MethodGels, Norm, Op, Option, Side, Uplo
from ..exceptions import DimensionError, slate_assert
from ..matrix.base import BaseMatrix, conj_transpose
from ..matrix.matrix import HermitianMatrix, Matrix, TriangularMatrix
from ..options import Options, get_option
from ..ops.householder import (
    apply_block_reflector,
    geqrf as _geqrf_kernel,
    larft,
    materialize_v,
)
from ..parallel import spmd_qr
from ..parallel.layout import TileLayout, eye_splice, tiles_from_global
from ..types import TriangularFactors
from . import blas3, chol

from ..internal.precision import accurate_matmul

from ..aux import metrics
from ..aux.metrics import instrumented


from ..matrix.base import is_distributed as _is_distributed

# metrics-gated jitted kernels: attribute the eager QR's compile/run
# split + cost_analysis to "geqrf.kernel" (unjitted original call with
# metrics off).  The padded-global operand (always a fresh temporary)
# is donated on accelerators when these jits dispatch — geqrf
# overwrites A with V/R in place like the reference; under an outer
# jit the outer boundary donates instead (serve/cache.py).
_geqrf_global_kernel = metrics.gated_jit(
    _geqrf_kernel, "geqrf.kernel", donate_argnums=(0,)
)

from ..ops import qr_fast as _qr_fast

_geqrf_recursive_kernel = metrics.gated_jit(
    _qr_fast.geqrf_recursive, "geqrf.kernel_recursive",
    static_argnums=(1,), donate_argnums=(0,),
)

_geqrf_flat_kernel = metrics.gated_jit(
    _qr_fast.geqrf_flat, "geqrf.kernel_flat", donate_argnums=(0,)
)

_geqrf_pallas_kernel = metrics.gated_jit(
    _qr_fast.geqrf_pallas, "geqrf.kernel_pallas",
    static_argnums=(1,), donate_argnums=(0,),
)


def _padded_global_splice(A: BaseMatrix) -> jnp.ndarray:
    lay = A.layout
    G = A.resolved().to_global()
    mp, npd = lay.P * lay.mb, lay.Q * lay.nb
    Gp = jnp.pad(G, ((0, mp - lay.m), (0, npd - lay.n)))
    dmin = min(mp, npd)
    idx = jnp.arange(dmin)
    splice = jnp.where(idx >= min(lay.m, lay.n), 1.0, 0.0).astype(G.dtype)
    return Gp.at[idx, idx].add(splice)


@accurate_matmul
@instrumented("geqrf")
def geqrf(
    A: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, TriangularFactors]:
    """Householder QR: A = Q R (reference: src/geqrf.cc CAQR; SURVEY §3.4).

    Returns (factored, T): factored stores V below the diagonal and R on/
    above; T holds the per-panel compact-WY factors."""
    slate_assert(A.layout.mb == A.layout.nb, "geqrf requires square tiles")
    lay = A.layout
    nb = lay.nb
    kt = min(lay.mt, lay.nt)

    if _is_distributed(A) and get_option(opts, Option.UseShardMap):
        T = eye_splice(lay, A.resolved().data)
        Td, Tstack = spmd_qr.spmd_geqrf(A.grid, T, lay)
        return A._with(data=Td), TriangularFactors(Tstack)

    from ..options import resolve_schedule_opts

    Gp = _padded_global_splice(A)
    mp, npd = Gp.shape
    sched, nb_switch, _lookahead = resolve_schedule_opts(opts)
    # one resolver decides both the kernel and the accounting route, so
    # the factor.geqrf.* counters always describe the traced program
    route = _qr_fast.resolve_qr_schedule(mp, npd, sched)
    if metrics.is_on():
        metrics.record_factor_flops(
            "geqrf",
            _qr_fast.geqrf_schedule_flops(
                mp, npd, nb, route, nb_switch,
                m_true=lay.m, n_true=lay.n,
            ),
        )
    if route == "pallas":
        vr, taus = _geqrf_pallas_kernel(Gp, nb_switch)
    elif route == "recursive":
        vr, taus = _geqrf_recursive_kernel(Gp, nb_switch)
    elif route == "flat" and sched == "flat":
        # explicit flat runs the native schedule on every backend (the
        # auto flat route lets householder.geqrf pick, same kernel)
        vr, taus = _geqrf_flat_kernel(Gp)
    else:
        vr, taus = _geqrf_global_kernel(Gp)
    m_pad = Gp.shape[0]
    Ts = []
    for k in range(kt):
        Vk = materialize_v(
            lax.dynamic_slice_in_dim(vr, k * nb, nb, axis=1), offset=k * nb
        )
        Ts.append(larft(Vk, lax.dynamic_slice_in_dim(taus, k * nb, nb, 0)))
    Tstack = jnp.stack(Ts) if Ts else jnp.zeros((0, nb, nb), A.dtype)
    fac = A._with(data=tiles_from_global(vr[: lay.m, : lay.n], lay)).shard()
    return fac, TriangularFactors(Tstack)


def _vt_panels(fac: Matrix):
    """Iterate (V_k, offset) panels from the factored matrix's global
    form; V_k is full height with zeros above the panel diagonal."""
    lay = fac.layout
    nb = lay.nb
    G = fac.to_global()
    m = lay.m
    kt = min(lay.mt, lay.nt)
    for k in range(kt):
        ncols = min(nb, lay.n - k * nb)
        panel = G[:, k * nb : k * nb + ncols]
        Vk = materialize_v(panel, offset=k * nb)
        # zero any rows above the panel start
        yield k, Vk


@accurate_matmul
@instrumented("unmqr")
def unmqr(
    side: Side,
    op: Op,
    fac: Matrix,
    T: TriangularFactors,
    C: Matrix,
    opts: Optional[Options] = None,
) -> Matrix:
    """Multiply by Q from geqrf (reference: src/unmqr.cc).

    side Left:  C <- Q C (NoTrans) or Q^H C (ConjTrans);
    side Right: C <- C Q or C Q^H."""
    lay = fac.layout
    nb = lay.nb
    kt = min(lay.mt, lay.nt)
    C2 = C.to_global()
    Tn = T.T
    panels = list(_vt_panels(fac))
    forward = (side == Side.Left) == (op != Op.NoTrans)
    order = range(kt) if forward else range(kt - 1, -1, -1)
    conj_T = op != Op.NoTrans
    for k in order:
        _, Vk = panels[k]
        Tk = Tn[k][: Vk.shape[1], : Vk.shape[1]]
        if side == Side.Left:
            C2 = apply_block_reflector(Vk, Tk, C2, trans=conj_T)
        else:
            # C (I - V T V^H) = ((I - V T^T... ) C^H)^H; do it directly:
            W = C2 @ Vk  # (m, nb)
            Tm = (jnp.conj(Tk).T if fac.is_complex else Tk.T) if conj_T else Tk
            Vh = jnp.conj(Vk).T if fac.is_complex else Vk.T
            C2 = C2 - (W @ Tm) @ Vh
    return C._with(data=tiles_from_global(C2.astype(C.dtype), C.layout)).shard()


@accurate_matmul
def ungqr(
    fac: Matrix, T: TriangularFactors, opts: Optional[Options] = None
) -> Matrix:
    """Materialize the m x n orthogonal factor Q (LAPACK orgqr analogue;
    the reference tester materializes Q via unmqr on identity,
    test_geqrf.cc)."""
    lay = fac.layout
    eye = Matrix.from_global(
        jnp.eye(lay.m, min(lay.m, lay.n), dtype=fac.dtype),
        lay.mb,
        lay.nb,
        grid=fac.grid,
    )
    return unmqr(Side.Left, Op.NoTrans, fac, T, eye, opts)


@accurate_matmul
@instrumented("gelqf")
def gelqf(
    A: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, TriangularFactors]:
    """LQ factorization A = L Q (reference: src/gelqf.cc), computed as the
    dual of QR on A^H: A^H = Qr R  =>  A = R^H Qr^H = L Q.

    Returns (factored, T): factored stores L on/below the diagonal and
    V^H rows above (the dual's reflectors); T is the dual's T stack."""
    Ah = conj_transpose(A).resolved()
    Ah = Matrix(Ah.data, Ah.layout, grid=A.grid)
    facH, T = geqrf(Ah, opts)
    fac = conj_transpose(facH).resolved()
    return A._with(data=fac.data, layout=fac.layout), T


@accurate_matmul
def unmlq(
    side: Side,
    op: Op,
    fac: Matrix,
    T: TriangularFactors,
    C: Matrix,
    opts: Optional[Options] = None,
) -> Matrix:
    """Multiply by Q from gelqf (reference: src/unmlq.cc).  With the dual
    representation Q = Qr^H, so ops flip relative to unmqr."""
    facH = conj_transpose(fac).resolved()
    facH = Matrix(facH.data, facH.layout, grid=fac.grid)
    flip = {Op.NoTrans: Op.ConjTrans, Op.ConjTrans: Op.NoTrans, Op.Trans: Op.NoTrans}
    return unmqr(side, flip[op], facH, T, C, opts)


@accurate_matmul
@instrumented("cholqr")
def cholqr(
    A: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, TriangularMatrix, jnp.ndarray]:
    """Cholesky QR (reference: src/cholqr.cc: H = A^H A via herk, potrf,
    Q = A R^-1 via trsm; MethodCholQR variants collapse to herk here).

    Returns (Q, R, info)."""
    lay = A.layout
    h_lay = TileLayout(lay.n, lay.n, lay.nb, lay.nb, lay.p, lay.q)
    H = HermitianMatrix(
        jnp.zeros(h_lay.storage_shape, A.dtype), h_lay, grid=A.grid, uplo=Uplo.Upper
    )
    H = blas3.herk(1.0, conj_transpose(A), 0.0, H)
    R, info = chol.potrf(H, opts)
    Rtri = TriangularMatrix(
        R.data, R.layout, grid=A.grid, uplo=Uplo.Upper
    )
    Q = blas3.trsm(Side.Right, 1.0, Rtri, A, opts)
    return Q, Rtri, info


def gels_solve_from_global(
    Fg: jnp.ndarray, Bg: jnp.ndarray, m: int, nb: int
) -> jnp.ndarray:
    """gels-style solve-only entry point over global arrays: least
    squares against a PRE-COMPUTED packed QR factor.  ``Fg`` is the
    serve factor cache's pack (``serve/buckets.solve_factor_shape``):
    rows [0, m) hold the padded V/R global (Householder vectors below
    the diagonal, R on/above), and each nb-wide column panel's
    compact-WY T factor is flattened below (panel at column offset k
    in rows [m + k, m + k + w), cols [0, w)).  Applies Q^H to B one
    block reflector per panel — no larft rebuild, the cached T rides
    in the pack — then one triangular solve against R: O(m n nrhs)
    against the full phase's O(m n^2) refactor.  Fully traceable
    (jit/vmap over B), so the warmed ``phase="solve"`` gels bucket
    serves a whole coalesced batch from ONE unbatched factor operand."""
    n = Fg.shape[1]
    VR = Fg[:m]
    C = Bg
    for k in range(0, n, nb):
        w = min(nb, n - k)
        Vk = materialize_v(VR[:, k : k + w], offset=k)
        Tk = Fg[m + k : m + k + w, :w]
        C = apply_block_reflector(Vk, Tk, C, trans=True)
    R = jnp.triu(VR[:n, :n])
    return lax.linalg.triangular_solve(
        R, C[:n], left_side=True, lower=False
    )


@accurate_matmul
@instrumented("gels")
def gels(
    A: Matrix, B: Matrix, opts: Optional[Options] = None
) -> Matrix:
    """Least squares / minimum-norm solve (reference: src/gels.cc with
    MethodGels QR | CholQR; gels_qr.cc, gels_cholqr.cc).

    Overdetermined (m >= n): X = argmin ||A X - B||; underdetermined:
    minimum-norm solution via the LQ dual.  Returns X (n x nrhs)."""
    method = get_option(opts, Option.MethodGels, MethodGels.Auto)
    if isinstance(method, str):
        method = MethodGels.from_string(method)
    m, n = A.m, A.n
    if m >= n:
        if method == MethodGels.CholQR:
            Q, R, info = cholqr(A, opts)
            QhB = blas3.gemm(
                1.0,
                conj_transpose(Q),
                B,
                0.0,
                Matrix.zeros(n, B.n, A.layout.nb, dtype=A.dtype, grid=A.grid),
            )
            return blas3.trsm(Side.Left, 1.0, R, QhB, opts)
        fac, T = geqrf(A, opts)
        QhB = unmqr(Side.Left, Op.ConjTrans, fac, T, B, opts)
        QhB_top = Matrix.from_global(
            QhB.to_global()[:n], A.layout.nb, A.layout.nb, grid=A.grid
        )
        Rg = jnp.triu(fac.to_global()[:n, :n])
        R = TriangularMatrix.from_global(
            Rg, A.layout.nb, A.layout.nb, grid=A.grid, uplo=Uplo.Upper
        )
        return blas3.trsm(Side.Left, 1.0, R, QhB_top, opts)
    # underdetermined: A = L Q, X = Q^H L^-1 B (minimum-norm)
    fac, T = gelqf(A, opts)
    Lg = jnp.tril(fac.to_global()[:, :m])
    L = TriangularMatrix.from_global(
        Lg, A.layout.mb, A.layout.mb, grid=A.grid, uplo=Uplo.Lower
    )
    Y = blas3.trsm(Side.Left, 1.0, L, B, opts)
    Yfull = Matrix.from_global(
        jnp.concatenate(
            [Y.to_global(), jnp.zeros((n - m, B.n), A.dtype)], axis=0
        ),
        A.layout.nb,
        A.layout.nb,
        grid=A.grid,
    )
    return unmlq(Side.Left, Op.ConjTrans, fac, T, Yfull, opts)
