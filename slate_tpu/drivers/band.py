"""Band-matrix drivers (reference: src/gbmm.cc, hbmm.cc, tbsm.cc,
tbsmPivots.cc, gbtrf.cc, gbtrs.cc, gbsv.cc, pbtrf.cc, pbtrs.cc, pbsv.cc).

Band matrices are stored on the dense tile grid with out-of-band tiles
zero (matrix/matrix.py BandMatrix) — on TPU uniform dense tiles beat the
reference's band-aware tile maps (static shapes; XLA prunes work on zero
tiles far less than a band layout would, but the band routines' working
sets are small and the dense schedule is one fused kernel).  Pivoting
fill-in (kl extra superdiagonals in gbtrf, LAPACK band semantics) is
automatically absorbed by the dense storage.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

from ..enums import Diag, Op, Option, Side, Uplo
from ..exceptions import slate_assert
from ..matrix.matrix import (
    BandMatrix,
    HermitianBandMatrix,
    HermitianMatrix,
    Matrix,
    TriangularBandMatrix,
    TriangularMatrix,
)
from ..options import Options
from ..parallel.layout import tiles_from_global
from ..types import Pivots
from . import blas3, chol, lu


def gbmm(alpha, A: BandMatrix, B: Matrix, beta, C: Matrix, opts=None) -> Matrix:
    """C = alpha op(A) B + beta C with band A (reference: src/gbmm.cc)."""
    Ag = A._with(op=Op.NoTrans)
    masked = Ag.data * Ag.band_mask().astype(A.dtype)
    Am = Matrix(masked, Ag.layout, grid=A.grid, op=A.op)
    return blas3.gemm(alpha, Am, B, beta, C, opts)


def hbmm(side: Side, alpha, A: HermitianBandMatrix, B: Matrix, beta, C: Matrix,
         opts=None) -> Matrix:
    """C = alpha A B + beta C with Hermitian band A (reference: src/hbmm.cc)."""
    Af = _hermitian_band_full(A)
    B2, C2 = B.to_global(), C.to_global()
    from ..ops import blas2d

    out = (
        blas2d.gemm2d(alpha, Af, B2, beta, C2)
        if side == Side.Left
        else blas2d.gemm2d(alpha, B2, Af, beta, C2)
    )
    return C._with(data=tiles_from_global(out.astype(C.dtype), C.layout))


def _hermitian_band_full(A: HermitianBandMatrix) -> jnp.ndarray:
    import numpy as np

    G = A.to_global()
    n = A.n
    i = np.arange(n)[:, None]
    j = np.arange(n)[None, :]
    if A.uplo == Uplo.Lower:
        keep = (i >= j) & (i - j <= A.kd)
    else:
        keep = (i <= j) & (j - i <= A.kd)
    Gk = jnp.where(jnp.asarray(keep), G, 0)
    diag = jnp.diag(jnp.real(jnp.diag(Gk)).astype(G.dtype)) if A.is_complex else jnp.diag(jnp.diag(Gk))
    return Gk + jnp.conj(Gk).T - diag if A.is_complex else Gk + Gk.T - diag


def tbsm(
    side: Side,
    alpha,
    A: TriangularBandMatrix,
    B: Matrix,
    pivots: Optional[Pivots] = None,
    opts=None,
) -> Matrix:
    """Triangular band solve, optionally applying pivots first
    (reference: src/tbsm.cc + tbsmPivots.cc)."""
    B2 = B.to_global()
    if pivots is not None and pivots.perm.shape[0] > 0:
        Bp = jnp.pad(B2, ((0, pivots.perm.shape[0] - B2.shape[0]), (0, 0)))
        B2 = pivots.apply(Bp)[: B.m]
    T = TriangularMatrix(
        A.data, A.layout, grid=A.grid, uplo=A.uplo, diag=A.diag
    )
    Bm = B._with(data=tiles_from_global(B2.astype(B.dtype), B.layout))
    Top = T if A.op == Op.NoTrans else T._with(op=A.op)
    return blas3.trsm(side, alpha, Top, Bm, opts)


def gbtrf(
    A: BandMatrix, opts: Optional[Options] = None
) -> Tuple[BandMatrix, Pivots, jnp.ndarray]:
    """Band LU with partial pivoting (reference: src/gbtrf.cc).  Dense-
    stored band: pivot fill-in (up to kl extra superdiagonals) lands in
    the zero tiles above the band."""
    Am = Matrix(A.data, A.layout, grid=A.grid)
    LU, piv, info = lu.getrf(Am, opts)
    out = BandMatrix(
        LU.data, LU.layout, grid=A.grid, kl=A.kl, ku=min(A.ku + A.kl, A.n - 1)
    )
    return out, piv, info


def gbtrs(LU: BandMatrix, pivots: Pivots, B: Matrix, opts=None) -> Matrix:
    """(reference: src/gbtrs.cc)"""
    return lu.getrs(Matrix(LU.data, LU.layout, grid=LU.grid), pivots, B, opts)


def gbsv(
    A: BandMatrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, BandMatrix, Pivots, jnp.ndarray]:
    """Band solve (reference: src/gbsv.cc = gbtrf + gbtrs)."""
    LU, piv, info = gbtrf(A, opts)
    X = gbtrs(LU, piv, B, opts)
    return X, LU, piv, info


def pbtrf(
    A: HermitianBandMatrix, opts: Optional[Options] = None
) -> Tuple[TriangularBandMatrix, jnp.ndarray]:
    """Band Cholesky (reference: src/pbtrf.cc); no fill-in beyond kd."""
    Af = _hermitian_band_full(A)
    Ah = HermitianMatrix.from_global(
        Af, A.layout.mb, A.layout.nb, grid=A.grid, uplo=A.uplo
    )
    L, info = chol.potrf(Ah, opts)
    Lb = TriangularBandMatrix(
        L.data, L.layout, grid=A.grid, kd=A.kd, uplo=L.uplo
    )
    return Lb, info


def pbtrs(L: TriangularBandMatrix, B: Matrix, opts=None) -> Matrix:
    """(reference: src/pbtrs.cc)"""
    Lt = TriangularMatrix(L.data, L.layout, grid=L.grid, uplo=L.uplo)
    return chol.potrs(Lt, B, opts)


def pbsv(
    A: HermitianBandMatrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, TriangularBandMatrix, jnp.ndarray]:
    """Band SPD solve (reference: src/pbsv.cc = pbtrf + pbtrs)."""
    L, info = pbtrf(A, opts)
    X = pbtrs(L, B, opts)
    return X, L, info
