"""Band-matrix drivers (reference: src/gbmm.cc, hbmm.cc, tbsm.cc,
tbsmPivots.cc, gbtrf.cc, gbtrs.cc, gbsv.cc, pbtrf.cc, pbtrs.cc, pbsv.cc).

Band matrices are stored on the dense tile grid with out-of-band tiles
zero (matrix/matrix.py BandMatrix) — on TPU uniform dense tiles beat the
reference's band-aware tile maps (static shapes; XLA prunes work on zero
tiles far less than a band layout would, but the band routines' working
sets are small and the dense schedule is one fused kernel).  Pivoting
fill-in (kl extra superdiagonals in gbtrf, LAPACK band semantics) is
automatically absorbed by the dense storage.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

from ..enums import Diag, Op, Option, Side, Uplo
from ..exceptions import slate_assert
from ..matrix.matrix import (
    BandMatrix,
    HermitianBandMatrix,
    HermitianMatrix,
    Matrix,
    TriangularBandMatrix,
    TriangularMatrix,
)
from ..options import Options
from ..parallel.layout import tiles_from_global
from ..types import Pivots
from . import blas3, chol, lu

from ..aux.metrics import instrumented


@instrumented("gbmm")
def gbmm(alpha, A: BandMatrix, B: Matrix, beta, C: Matrix, opts=None) -> Matrix:
    """C = alpha op(A) B + beta C with band A (reference: src/gbmm.cc)."""
    Ag = A._with(op=Op.NoTrans)
    masked = Ag.data * Ag.band_mask().astype(A.dtype)
    Am = Matrix(masked, Ag.layout, grid=A.grid, op=A.op)
    return blas3.gemm(alpha, Am, B, beta, C, opts)


@instrumented("hbmm")
def hbmm(side: Side, alpha, A: HermitianBandMatrix, B: Matrix, beta, C: Matrix,
         opts=None) -> Matrix:
    """C = alpha A B + beta C with Hermitian band A (reference:
    src/hbmm.cc).

    Routes through the hemm driver on the band-masked stored triangle:
    distributed inputs take the spmd_hemm stored-triangle SUMMA (no
    gather of A, B or C), dense inputs the fused global product — the
    band's zero tiles cost nothing either way."""
    # band_mask() already encodes the stored triangle: kl/ku are derived
    # from uplo/kd by the band-matrix hierarchy, padding masked off
    masked = A.data * A.band_mask().astype(A.dtype)
    Ah = HermitianMatrix(masked, A.layout, grid=A.grid, uplo=A.uplo)
    return blas3.hemm(side, alpha, Ah, B, beta, C, opts)


def _hermitian_band_full(A: HermitianBandMatrix) -> jnp.ndarray:
    import numpy as np

    G = A.to_global()
    n = A.n
    i = np.arange(n)[:, None]
    j = np.arange(n)[None, :]
    if A.uplo == Uplo.Lower:
        keep = (i >= j) & (i - j <= A.kd)
    else:
        keep = (i <= j) & (j - i <= A.kd)
    Gk = jnp.where(jnp.asarray(keep), G, 0)
    diag = jnp.diag(jnp.real(jnp.diag(Gk)).astype(G.dtype)) if A.is_complex else jnp.diag(jnp.diag(Gk))
    return Gk + jnp.conj(Gk).T - diag if A.is_complex else Gk + Gk.T - diag


def _band_narrow(kd: int, n: int) -> bool:
    """Use the O(n kd^2) windowed kernels when the band is genuinely
    narrow; wide bands lose nothing to the dense schedule."""
    return kd < n // 4


@instrumented("tbsm")
def tbsm(
    side: Side,
    alpha,
    A: TriangularBandMatrix,
    B: Matrix,
    pivots: Optional[Pivots] = None,
    opts=None,
) -> Matrix:
    """Triangular band solve, optionally applying pivots first
    (reference: src/tbsm.cc + tbsmPivots.cc).

    Narrow bands run the windowed O(n kd nrhs) substitution
    (ops/band_kernels.py::band_trsm_lower); effective-upper and
    right-side cases reduce to it by index reversal / transposition
    (J U J is lower-band).  Distributed inputs keep the dense SPMD
    pipeline (no new gathers on the mesh path)."""
    from ..matrix.base import is_distributed

    slate_assert(
        pivots is None or pivots.band_lperms is None,
        "tbsm cannot apply windowed-gbtrf pivots: the interleaved band "
        "factorization must be solved by gbtrs (net perm + plain "
        "triangular solves do not reproduce it)",
    )
    kd = A.kd
    n = A.n
    eff_lower = (A.uplo == Uplo.Lower) != (A.op != Op.NoTrans)
    if (
        not is_distributed(B)
        and _band_narrow(kd, n)
        and A.m == A.n
    ):
        from ..ops import band_kernels

        B2 = B.to_global()
        if pivots is not None and pivots.perm.shape[0] > 0:
            Bp = jnp.pad(B2, ((0, pivots.perm.shape[0] - B2.shape[0]), (0, 0)))
            B2 = pivots.apply(Bp)[: B.m]
        T2 = A._with(op=Op.NoTrans).to_global()
        if A.op == Op.ConjTrans and A.is_complex:
            E = jnp.conj(T2).T
        elif A.op != Op.NoTrans:
            E = T2.T
        else:
            E = T2
        unit = A.diag == Diag.Unit
        if side == Side.Right:
            # X op(T) = B  <=>  op(T)^T X^T = B^T
            E = E.T
            B2 = B2.T
            eff_lower = not eff_lower
        if eff_lower:
            X = band_kernels.band_trsm_lower(E, B2, kd, unit_diag=unit)
        else:
            # J U J is lower band: solve the reversed system
            X = band_kernels.band_trsm_lower(
                E[::-1, ::-1], B2[::-1], kd, unit_diag=unit
            )[::-1]
        if side == Side.Right:
            X = X.T
        out = (alpha * X).astype(B.dtype)
        return B._with(data=tiles_from_global(out, B.layout))

    B2 = B.to_global()
    if pivots is not None and pivots.perm.shape[0] > 0:
        Bp = jnp.pad(B2, ((0, pivots.perm.shape[0] - B2.shape[0]), (0, 0)))
        B2 = pivots.apply(Bp)[: B.m]
    T = TriangularMatrix(
        A.data, A.layout, grid=A.grid, uplo=A.uplo, diag=A.diag
    )
    Bm = B._with(data=tiles_from_global(B2.astype(B.dtype), B.layout))
    Top = T if A.op == Op.NoTrans else T._with(op=A.op)
    return blas3.trsm(side, alpha, Top, Bm, opts)


@instrumented("gbtrf")
def gbtrf(
    A: BandMatrix, opts: Optional[Options] = None
) -> Tuple[BandMatrix, Pivots, jnp.ndarray]:
    """Band LU with partial pivoting (reference: src/gbtrf.cc).  Dense-
    stored band: pivot fill-in (up to kl extra superdiagonals) lands in
    the zero tiles above the band.

    Narrow bands run the windowed O(n (kl+w)(kl+ku+w)) kernel
    (ops/band_kernels.py::band_getrf — the gbtrf.cc in-band panel loop);
    distributed or wide-band inputs keep the dense getrf schedule."""
    from ..matrix.base import is_distributed

    if (
        not is_distributed(A)
        and A.m == A.n
        and _band_narrow(A.kl + A.ku, A.n)
        and A.op == Op.NoTrans
    ):
        from ..ops import band_kernels

        G = A.to_global()
        lu2d, lperms, perm, w = band_kernels.band_getrf(G, A.kl, A.ku)
        LUb = BandMatrix(
            tiles_from_global(lu2d.astype(A.dtype), A.layout),
            A.layout,
            grid=A.grid,
            kl=A.kl,
            ku=min(A.ku + A.kl, A.n - 1),
        )
        d = jnp.abs(jnp.diagonal(lu2d))
        info = jnp.where(
            jnp.all(jnp.isfinite(lu2d)) & jnp.all(d > 0), 0, 1
        ).astype(jnp.int32)
        return LUb, Pivots(perm, band_lperms=lperms, band_w=w), info

    Am = Matrix(A.data, A.layout, grid=A.grid)
    LU, piv, info = lu.getrf(Am, opts)
    out = BandMatrix(
        LU.data, LU.layout, grid=A.grid, kl=A.kl, ku=min(A.ku + A.kl, A.n - 1)
    )
    return out, piv, info


@instrumented("gbtrs")
def gbtrs(LU: BandMatrix, pivots: Pivots, B: Matrix, opts=None) -> Matrix:
    """(reference: src/gbtrs.cc).

    A windowed-gbtrf factorization (pivots carry band_lperms) MUST be
    solved by the interleaved-pivot band solve (band_getrs) — the net
    perm alone does not reproduce it, so this route is taken regardless
    of B's distribution (a distributed B gathers, recorded as a
    fallback); fully-swapped dense factorizations go through getrs."""
    from ..matrix.base import is_distributed

    if pivots is not None and pivots.band_lperms is not None:
        from ..internal import fallbacks
        from ..ops import band_kernels

        if is_distributed(B):
            fallbacks.record(
                "gbtrs", opts, "windowed band solve gathers distributed B"
            )

        kl = LU.kl
        ku_orig = LU.ku - kl  # gbtrf stored ku = original ku + kl
        G = LU._with(op=Op.NoTrans).to_global()
        B2 = B.to_global()
        X = band_kernels.band_getrs(
            G, pivots.band_lperms, pivots.band_w, kl, ku_orig, B2
        )
        return B._with(data=tiles_from_global(X.astype(B.dtype), B.layout))

    return lu.getrs(Matrix(LU.data, LU.layout, grid=LU.grid), pivots, B, opts)


@instrumented("gbsv")
def gbsv(
    A: BandMatrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, BandMatrix, Pivots, jnp.ndarray]:
    """Band solve (reference: src/gbsv.cc = gbtrf + gbtrs)."""
    LU, piv, info = gbtrf(A, opts)
    X = gbtrs(LU, piv, B, opts)
    return X, LU, piv, info


@instrumented("pbtrf")
def pbtrf(
    A: HermitianBandMatrix, opts: Optional[Options] = None
) -> Tuple[TriangularBandMatrix, jnp.ndarray]:
    """Band Cholesky (reference: src/pbtrf.cc); no fill-in beyond kd.

    Narrow bands run the windowed O(n kd^2) kernel
    (ops/band_kernels.py::band_potrf_lower — the pbtrf.cc loop
    restricted to the band); distributed or wide-band inputs keep the
    dense potrf schedule."""
    from ..matrix.base import is_distributed

    if not is_distributed(A) and _band_narrow(A.kd, A.n):
        from ..ops import band_kernels

        Af = _hermitian_band_full(A)
        L2 = band_kernels.band_potrf_lower(Af, A.kd)
        info = jnp.where(jnp.all(jnp.isfinite(L2)), 0, 1).astype(jnp.int32)
        if A.uplo == Uplo.Upper:
            U2 = jnp.conj(L2).T if A.is_complex else L2.T
            Lb = TriangularBandMatrix(
                tiles_from_global(U2.astype(A.dtype), A.layout),
                A.layout, grid=A.grid, kd=A.kd, uplo=Uplo.Upper,
            )
        else:
            Lb = TriangularBandMatrix(
                tiles_from_global(L2.astype(A.dtype), A.layout),
                A.layout, grid=A.grid, kd=A.kd, uplo=Uplo.Lower,
            )
        return Lb, info

    Af = _hermitian_band_full(A)
    Ah = HermitianMatrix.from_global(
        Af, A.layout.mb, A.layout.nb, grid=A.grid, uplo=A.uplo
    )
    L, info = chol.potrf(Ah, opts)
    Lb = TriangularBandMatrix(
        L.data, L.layout, grid=A.grid, kd=A.kd, uplo=L.uplo
    )
    return Lb, info


@instrumented("pbtrs")
def pbtrs(L: TriangularBandMatrix, B: Matrix, opts=None) -> Matrix:
    """(reference: src/pbtrs.cc): two windowed band solves on narrow
    bands, dense trsm sweeps otherwise."""
    from ..matrix.base import is_distributed

    if not is_distributed(B) and _band_narrow(L.kd, L.n):
        from ..ops import band_kernels

        G = L._with(op=Op.NoTrans).to_global()
        B2 = B.to_global()
        complex_t = L.is_complex
        if L.uplo == Uplo.Upper:
            # A = U^H U: L_eff = U^H (lower band)
            G = jnp.conj(G).T if complex_t else G.T
        Y = band_kernels.band_trsm_lower(G, B2, L.kd)
        # L^H solve by index reversal: J L^H J is lower band
        M = jnp.conj(G[::-1, ::-1]).T if complex_t else G[::-1, ::-1].T
        X = band_kernels.band_trsm_lower(M, Y[::-1], L.kd)[::-1]
        return B._with(data=tiles_from_global(X.astype(B.dtype), B.layout))

    Lt = TriangularMatrix(L.data, L.layout, grid=L.grid, uplo=L.uplo)
    return chol.potrs(Lt, B, opts)


@instrumented("pbsv")
def pbsv(
    A: HermitianBandMatrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, TriangularBandMatrix, jnp.ndarray]:
    """Band SPD solve (reference: src/pbsv.cc = pbtrf + pbtrs)."""
    L, info = pbtrf(A, opts)
    X = pbtrs(L, B, opts)
    return X, L, info
