"""Hermitian eigensolver family (reference: src/heev.cc, he2hb.cc,
hb2st.cc, sterf.cc, steqr.cc, stedc*.cc, unmtr_he2hb.cc, unmtr_hb2st.cc,
hegst.cc, hegv.cc; SURVEY §3.5).

Staging mirrors the reference:

  heev:  he2hb (dense -> band, distributed-capable, all the FLOPs)
         -> gather -> tridiagonal/eigen stage on one device.

The reference also runs stage 2+ on ONE node over a gathered band
(heev.cc:135 he2hbGather, hb2st threads+atomics) calling LAPACK
sterf/steqr/stedc; here the gathered stage calls the XLA eigensolver
(jnp.linalg.eigh — our L0 vendor-kernel layer, exactly as the reference
leans on LAPACK).  A native Pallas bulge-chaser is the planned
replacement (SURVEY §7 step 6).

he2hb is implemented as blocked two-sided Householder updates
(he2hb.cc:174-185's panel QR + trailing her2k-style update), using our
QR panel kernels; the back-transform unmtr_he2hb applies the stored
reflectors.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..enums import MethodEig, Norm, Op, Option, Side, Uplo
from ..exceptions import slate_assert
from ..matrix.base import BaseMatrix, conj_transpose
from ..matrix.matrix import HermitianMatrix, HermitianBandMatrix, Matrix, TriangularMatrix
from ..options import Options, get_option
from ..ops.householder import larft, materialize_v
from ..parallel.layout import TileLayout, tiles_from_global
from ..types import TriangularFactors
from . import blas3

from ..aux import metrics
from ..aux.metrics import instrumented
from ..internal.precision import accurate_matmul, hdot


from ..matrix.base import is_distributed as _is_distributed


def _size_bucket_runs(heights, total, floor=1024):
    """Group consecutive panel indices into size buckets: each height is
    assigned S = total / 2^m, the smallest halving of `total` that still
    covers it, floored at min(floor, total) so tiny tails don't multiply
    compiled bodies.  (Buckets are halvings of `total`, NOT pow2ceil(h):
    for total=6144 a height of 2500 buckets to 3072, not 4096.)
    Yields (i0, i1, S) runs; every height in [i0, i1) is <= S.

    The canonical implementation lives in serve/buckets.py — the
    serving layer's request buckets are the same halving lattice, so
    the rule is defined once (serve's __init__ is lazy; this import
    pulls only the pure buckets module, no cycle)."""
    from ..serve.buckets import size_bucket_runs

    return size_bucket_runs(heights, total, floor)


@accurate_matmul
@instrumented("he2hb")
def he2hb(
    A: HermitianMatrix, opts: Optional[Options] = None
) -> Tuple[HermitianBandMatrix, Matrix, TriangularFactors]:
    """Reduce Hermitian A to band form with bandwidth nb
    (reference: src/he2hb.cc: per-panel QR over panel ranks + two-sided
    trailing update).

    Distributed lower-Hermitian inputs run the shard_map panel pipeline
    (parallel/spmd_he2hb.py — panel gather + masked-einsum two-sided
    trailing update, no full_global(); the reference also restricts
    he2hb to Uplo::Lower, he2hb.cc:36).

    Returns (band, V, T): band Hermitian with kd = nb; V stores the block
    reflectors (panel k in tile column k, rows k+1..), T their compact-WY
    factors — the inputs of unmtr_he2hb."""
    slate_assert(A.m == A.n, "he2hb requires square")
    from jax import lax

    from ..ops.householder import _geqrf_panel

    lay = A.layout
    nb = lay.nb
    n = A.n

    if (
        _is_distributed(A)
        and get_option(opts, Option.UseShardMap)
        and A.uplo == Uplo.Lower
        and A.op == Op.NoTrans
        and lay.mb == lay.nb
    ):
        from ..parallel.spmd_he2hb import spmd_he2hb

        band_t, V_t, Tstack = spmd_he2hb(A.grid, A.data, lay)
        if lay.nt - 1 <= 0:
            Tstack = jnp.zeros((0, nb, nb), A.dtype)
        band = HermitianBandMatrix(
            band_t, lay, grid=A.grid, kd=nb, uplo=Uplo.Lower
        )
        return band, Matrix(V_t, lay, grid=A.grid), TriangularFactors(Tstack)

    if _is_distributed(A):
        from ..internal import fallbacks

        fallbacks.record(
            "he2hb", opts, "upper uplo / viewed / non-square tiles gather"
        )
    G = A.full_global()
    kt = lay.nt
    complex_t = A.is_complex

    def C(x):
        return jnp.conj(x) if complex_t else x

    # static-shape pipeline: every step works on the padded array with
    # the active trailing block rolled to the origin — one traced step
    # body per SIZE BUCKET under lax.fori_loop instead of kt unrolled
    # iterations (the reference's per-panel task loop,
    # he2hb.cc:174-185).  Steps whose trailing size h has shrunk crop
    # the rolled array to the _size_bucket_runs size S (the smallest
    # npad/2^m covering h): the full-array version ran
    # every trailing gemm at n x n regardless of h (3x the true flops
    # — measured 27 s of he2hb's 32 s at n=8192 on-chip; rolls and
    # panels are noise).  The update itself uses the LAPACK hetrd W
    # trick (W = P - V Q2/2, Q2 Hermitian) so the rank-2nb two-sided
    # update is ONE concat gemm instead of three rank-nb products.
    npad = kt * nb
    Gp = jnp.pad(G, ((0, npad - n), (0, npad - n)))
    Vs0 = jnp.zeros_like(Gp)
    Ts0 = jnp.zeros((max(kt - 1, 1), nb, nb), Gp.dtype)
    rows = jnp.arange(npad)

    def make_step(S):
        rows_S = jnp.arange(S)

        def step(k, carry):
            Gp, Vs, Ts = carry
            lo = (k + 1) * nb
            h = n - lo  # active trailing size (<= S; may be <= 0)
            # panel: rows lo.., column block k, rolled to the top
            colblk = lax.dynamic_slice(Gp, (0, k * nb), (npad, nb))
            pan = jnp.roll(colblk, -lo, axis=0)[:S]
            pan = jnp.where((rows_S < h)[:, None], pan, jnp.zeros_like(pan))
            vr, taus = _geqrf_panel(pan)
            V = materialize_v(vr, offset=0)  # (S, nb) unit-lower
            Tk = larft(V, taus)
            R = jnp.triu(vr)
            # write [R; 0] back into the panel and its Hermitian mirror
            newcol = jnp.zeros((npad, nb), Gp.dtype).at[:S].set(
                jnp.where((rows_S < h)[:, None], R, 0)
            )
            newcol = jnp.roll(newcol, lo, axis=0)
            keep_above = (rows < lo)[:, None]
            newcol = jnp.where(keep_above, colblk, newcol)
            Gp = lax.dynamic_update_slice(Gp, newcol, (0, k * nb))
            mirror = C(newcol).T  # (nb, npad)
            rowblk = lax.dynamic_slice(Gp, (k * nb, 0), (nb, npad))
            sel = (rows >= lo)[None, :]
            Gp = lax.dynamic_update_slice(
                Gp, jnp.where(sel, mirror, rowblk), (k * nb, 0)
            )
            # two-sided trailing update on the rolled, cropped A22
            G22 = jnp.roll(Gp, (-lo, -lo), (0, 1))
            act = (rows_S < h)[:, None] & (rows_S < h)[None, :]
            A22 = jnp.where(act, G22[:S, :S], 0)
            P = A22 @ (V @ Tk)
            Q2 = C(Tk).T @ (C(V).T @ P)
            W = P - V @ (0.5 * Q2)
            U1 = jnp.concatenate([V, W], axis=1)  # (S, 2nb)
            U2 = jnp.concatenate([W, V], axis=1)
            A22n = A22 - U1 @ C(U2).T
            G22 = G22.at[:S, :S].set(jnp.where(act, A22n, G22[:S, :S]))
            Gp = jnp.roll(G22, (lo, lo), (0, 1))
            # stash reflectors (global row coordinates)
            Vroll = jnp.roll(
                jnp.zeros((npad, nb), Gp.dtype).at[:S].set(
                    jnp.where((rows_S < h)[:, None], V, 0)
                ),
                lo,
                axis=0,
            )
            Vs = lax.dynamic_update_slice(Vs, Vroll, (0, k * nb))
            Ts = Ts.at[k].set(Tk)
            return Gp, Vs, Ts

        return step

    carry = (Gp, Vs0, Ts0)
    heights = [n - (k + 1) * nb for k in range(max(kt - 1, 0))]
    for k0, k1, S in _size_bucket_runs(heights, npad):
        carry = lax.fori_loop(k0, k1, make_step(S), carry)
    Gp, Vs_p, Tstack = carry
    G = Gp[:n, :n]
    Vs = Vs_p[:n, :n]
    if kt - 1 <= 0:
        Tstack = jnp.zeros((0, nb, nb), G.dtype)
    band = HermitianBandMatrix(
        tiles_from_global(G, lay), lay, grid=A.grid, kd=nb, uplo=A.uplo
    )
    Vm = Matrix(tiles_from_global(Vs, lay), lay, grid=A.grid)
    return band, Vm, TriangularFactors(Tstack)


@accurate_matmul
@instrumented("unmtr_he2hb")
def unmtr_he2hb(
    side: Side,
    op: Op,
    V: Matrix,
    T: TriangularFactors,
    C_mat: Matrix,
    opts: Optional[Options] = None,
) -> Matrix:
    """Apply the he2hb back-transform Q (reference: src/unmtr_he2hb.cc).

    Q = H_0 H_1 ... with H_k = I - V_k T_k V_k^H (V_k in tile column k,
    shifted one block down)."""
    lay = V.layout
    nb = lay.nb
    n = V.n
    kt = lay.nt

    if (
        _is_distributed(V)
        and get_option(opts, Option.UseShardMap)
        and side == Side.Left
        and V.op == Op.NoTrans
        and C_mat.op == Op.NoTrans
        and lay.mb == lay.nb
        and C_mat.layout.mb == lay.mb
    ):
        from ..parallel.spmd_he2hb import spmd_unmtr_he2hb_left

        if T.T.shape[0] == 0:
            return C_mat
        Ct = spmd_unmtr_he2hb_left(
            V.grid,
            V.data,
            T.T,
            C_mat.data,
            lay,
            C_mat.layout,
            trans=(op != Op.NoTrans),
        )
        return C_mat._with(data=Ct)

    from jax import lax

    if _is_distributed(V) or _is_distributed(C_mat):
        from ..internal import fallbacks

        fallbacks.record(
            "unmtr_he2hb", opts, "right side / op view / tile mismatch"
        )
    Vg = V.to_global()
    C2 = C_mat.to_global()
    complex_t = V.is_complex

    def CC(x):
        return jnp.conj(x) if complex_t else x

    npanels = T.T.shape[0]
    if npanels == 0:
        return C_mat
    forward = (side == Side.Left) == (op != Op.NoTrans)
    # one traced body under lax.fori_loop (compile time flat in the panel
    # count — the same static-shape batching as he2hb itself): V_k is the
    # full-height column block, zero above row (k+1) nb, so the masked
    # slice updates collapse into full-size matmuls.
    Vp = jnp.pad(Vg, ((0, 0), (0, max(kt * nb - Vg.shape[1], 0))))
    Ts = T.T

    nrows = C2.shape[0]

    def make_step(S):
        def step(i, C2):
            k = i if forward else npanels - 1 - i
            Tk = lax.dynamic_index_in_dim(Ts, k, 0, keepdims=False)
            Tm = CC(Tk).T if op != Op.NoTrans else Tk
            # the V^H C gram contracts over all n rows: at n >= 4096
            # the f64 emulation drops its compensation terms on such
            # products (BENCH_NOTES round-5 cliff) — hdot k-chunks
            # them; this gram was the WHOLE heev orthogonality budget
            # at n=4096 (107 n eps from this stage vs 3.4 entering it)
            if side == Side.Left and S < nrows:
                # V_k lives in rows [lo, n): slice BOTH operands at the
                # same clamped origin and the panel support stays
                # aligned — the full-height version ran every product
                # at n x m regardless of the active height
                lo = (k + 1) * nb
                org = jnp.minimum(lo, nrows - S)
                Vk = lax.dynamic_slice(Vp, (org, k * nb), (S, nb))
                Cs = lax.dynamic_slice(C2, (org, 0), (S, C2.shape[1]))
                W = hdot(CC(Vk).T, Cs)
                Cs = Cs - Vk @ (Tm @ W)
                return lax.dynamic_update_slice(C2, Cs, (org, 0))
            Vk = lax.dynamic_slice_in_dim(Vp, k * nb, nb, axis=1)
            if side == Side.Left:
                W = hdot(CC(Vk).T, C2)
                return C2 - Vk @ (Tm @ W)
            W = hdot(C2, Vk)
            return C2 - (W @ Tm) @ CC(Vk).T

        return step

    if side == Side.Left:
        # size buckets over the active height h_k = n - (k+1) nb (the
        # same halving-of-total grouping as he2hb); loop index i maps to
        # panel idx[i] (reverse order for Q C)
        idx = list(range(npanels) if forward else range(npanels - 1, -1, -1))
        heights = [n - (idx[i] + 1) * nb for i in range(npanels)]
        for i0, i1, S in _size_bucket_runs(heights, nrows):
            C2 = lax.fori_loop(i0, i1, make_step(S), C2)
    else:
        C2 = lax.fori_loop(0, npanels, make_step(nrows), C2)
    return C_mat._with(data=tiles_from_global(C2.astype(C_mat.dtype), C_mat.layout))



def _gathered_band_eig(
    band_2d: jnp.ndarray, vectors: bool
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Stage 2+: eigensolve the gathered band matrix on one device via the
    XLA vendor eigensolver (reference analogue: gathered hb2st + LAPACK
    steqr/stedc on one node, heev.cc:135-180).

    On TPU f64 the vendor eigh stops ~1e-7 short of working precision;
    ops/jacobi.py's parallel-order Jacobi polish restores LAPACK-level
    accuracy (SURVEY §7 hard-part (5))."""
    from ..ops.jacobi import eigh_accurate

    return eigh_accurate(band_2d, vectors=vectors)


_STAGED_CACHE: dict = {}


@instrumented("heev_staged")
def heev_staged(
    A: HermitianMatrix,
    opts: Optional[Options] = None,
    vectors: bool = True,
):
    """Two-stage heev with PER-STAGE jits for large n (reference
    staging: src/heev.cc:123-210).

    One whole-problem jit exceeds this toolchain's remote-compile
    service beyond n ~ 1024 (BENCH_NOTES r4), so the product path for
    large eigenproblems compiles the four stages separately — he2hb +
    band gather | hb2st (native host chaser when available, on-device
    wavefront otherwise) | tridiagonal eigensolve + hb2st
    back-transform | he2hb back-transform — and reuses the compiled
    stages across calls of the same shape.

    Returns (w, Z-or-None, stage_seconds)."""
    import jax

    from .. import native as _native
    from ..ops import bulge
    from ..parallel.band_gather import band_storage_tiles, spmd_band_storage

    n = A.n
    b = A.layout.nb
    if b < 2 or n <= 2 or n <= 4 * b:
        w, Z = heev(A, opts, vectors=vectors)
        return w, Z, {}
    n_pad = n + 4 * b + 8
    lay = A.layout
    use_spmd_gather = (
        _is_distributed(A)
        and get_option(opts, Option.UseShardMap)
        and lay.mb == lay.nb
    )
    host_ok = (
        not A.is_complex
        and A.dtype == jnp.float64
        and _native.hb2st_available()
    )
    adtype = A.dtype
    grid = A.grid
    opts_key = tuple(
        sorted((str(k), str(v)) for k, v in (opts or {}).items())
    )
    key = (
        n, b, str(adtype), lay.p, lay.q, vectors, use_spmd_gather,
        id(grid), opts_key,
    )
    stages = _STAGED_CACHE.get(key)
    if stages is None:
        # closures capture only scalars/layout/grid + opts — never the
        # input matrix (a captured A would pin its device buffers for
        # the cache's lifetime).  Each stage jit carries the f32/c64
        # precision policy (accurate_matmul applies during tracing) and
        # is metrics-instrumented: compile-vs-run split + cost_analysis
        # flops per stage under "heev.s*" names.

        def _s1_fn(A):
            band, V, T = he2hb(A, opts)
            if use_spmd_gather:
                W = spmd_band_storage(band.grid, band.data, band.layout, n_pad)
            else:
                W = band_storage_tiles(band.data, band.layout, n_pad)
            return W, V.data, T.T

        def _s3_fn(d, e, u, VS, TAUS):
            wv, ZT = steqr(d, e, vectors=True)
            Z2 = bulge.unmtr_hb2st(
                VS=VS, TAUS=TAUS, Z=(u[:, None] * ZT).astype(adtype),
                n=n, b=b,
            )
            return wv, Z2

        def _s4_fn(Vd, Ts, Zd):
            Z = unmtr_he2hb(
                Side.Left,
                Op.NoTrans,
                Matrix(Vd, lay, grid=grid),
                TriangularFactors(Ts),
                Matrix(Zd, lay, grid=grid),
                opts,
            )
            return Z.data

        _s1 = metrics.instrument_jit(
            jax.jit(accurate_matmul(_s1_fn)), "heev.s1_he2hb_gather"
        )
        _s2_chip = metrics.instrument_jit(
            jax.jit(accurate_matmul(bulge.hb2st), static_argnames=("n", "b")),
            "heev.s2_hb2st",
        )
        _s3 = metrics.instrument_jit(
            jax.jit(accurate_matmul(_s3_fn)), "heev.s3_stedc_unmtr_hb2st"
        )
        _s3v = metrics.instrument_jit(
            jax.jit(bulge.tridiag_eigvals_bisect), "heev.s3v_eigvals"
        )
        _s4 = metrics.instrument_jit(
            jax.jit(accurate_matmul(_s4_fn)), "heev.s4_unmtr_he2hb"
        )
        _pack = metrics.instrument_jit(
            jax.jit(lambda Z2: tiles_from_global(Z2, lay)), "heev.pack"
        )

        stages = (_s1, _s2_chip, _s3, _s3v, _s4, _pack)
        _STAGED_CACHE[key] = stages
    _s1, _s2_chip, _s3, _s3v, _s4, _pack = stages

    times = {}
    with metrics.phase("heev.he2hb+gather", always=True) as ph:
        W, Vd, Ts = jax.block_until_ready(_s1(A))
    times["he2hb+gather"] = round(ph.seconds, 2)
    with metrics.phase("heev.hb2st", always=True) as ph:
        if host_ok:
            W_h = np.asarray(W)
            metrics.inc("transfer.d2h_bytes", W_h.nbytes)
            d_h, e_h, VS, TAUS = _native.hb2st_host_device(W_h, n, b)
            d, e = jnp.asarray(d_h), jnp.asarray(e_h)
            u = jnp.ones((n,), A.dtype)
        else:
            d, e, u, VS, TAUS = _s2_chip(W, n, b)
        jax.block_until_ready((d, e, VS, TAUS))
    times["hb2st"] = round(ph.seconds, 2)
    if not vectors:
        with metrics.phase("heev.eigvals", always=True) as ph:
            w = jax.block_until_ready(_s3v(d, e))
        times["eigvals"] = round(ph.seconds, 2)
        return w, None, times
    with metrics.phase("heev.stedc+unmtr_hb2st", always=True) as ph:
        wv, Z2 = jax.block_until_ready(_s3(d, e, u, VS, TAUS))
    times["stedc+unmtr_hb2st"] = round(ph.seconds, 2)
    with metrics.phase("heev.unmtr_he2hb", always=True) as ph:
        Zd = jax.block_until_ready(_s4(Vd, Ts, _pack(Z2)))
    times["unmtr_he2hb"] = round(ph.seconds, 2)
    Z = Matrix(Zd, lay, grid=A.grid)
    return wv, Z, times


@accurate_matmul
@instrumented("heev")
def heev(
    A: HermitianMatrix,
    opts: Optional[Options] = None,
    vectors: bool = True,
) -> Tuple[jnp.ndarray, Optional[Matrix]]:
    """Hermitian eigendecomposition (reference: src/heev.cc two-stage:
    he2hb -> hb2st bulge chase -> tridiagonal eigensolve -> back-transform
    unmtr_hb2st + unmtr_he2hb, heev.cc:123-210).

    Returns (Lambda ascending, Z or None).  Stage 2 runs the wavefront
    bulge chase (ops/bulge.py) when the band is genuinely narrow
    (n > 4 nb); small problems dense-eigensolve the band directly.
    MethodEig.Bisection forces the two-stage chase + Sturm bisection."""
    import jax

    from ..ops import bulge
    from ..parallel.band_gather import band_storage_tiles, spmd_band_storage

    n = A.n
    b = A.layout.nb

    method = get_option(opts, Option.MethodEig, MethodEig.Auto)
    if isinstance(method, str):
        method = MethodEig.from_string(method)
    two_stage = b >= 2 and n > 2 and (
        method == MethodEig.Bisection or (method == MethodEig.Auto and n > 4 * b)
    )
    # large eager accelerator problems: per-stage jits (one whole-heev
    # jit exceeds the remote-compile service past n ~ 1024 on this
    # toolchain — BENCH_NOTES r4); decided BEFORE the he2hb reduction so
    # stage 1 runs exactly once.  Inside a jit trace this re-dispatch is
    # skipped and the whole path traces inline as before.
    if (
        two_stage
        and n >= 1024
        and n > 4 * b  # heev_staged's own guard; avoids a re-dispatch loop
        and not isinstance(A.data, jax.core.Tracer)
        and jax.default_backend() != "cpu"
    ):
        w, Z, _times = heev_staged(A, opts, vectors=vectors)
        return w, Z

    band, V, T = he2hb(A, opts)
    if two_stage:
        # band-limited stage gather (he2hbGather semantics): the packed
        # (2b+1, n_pad) chase storage is built straight from the <= 2
        # relevant tile diagonals — O(n kd) data, never the dense n x n
        # (reference: HermitianBandMatrix.hh:310, heev.cc:133-151)
        n_pad = n + 4 * b + 8
        if (
            _is_distributed(band)
            and get_option(opts, Option.UseShardMap)
            and band.layout.mb == band.layout.nb
        ):
            W = spmd_band_storage(band.grid, band.data, band.layout, n_pad)
        else:
            W = band_storage_tiles(band.data, band.layout, n_pad)
        # stage 2: the native host chaser when running eagerly on real
        # data (the reference's hb2st is likewise a CPU-threaded stage
        # over the gathered band, src/hb2st.cc:44-187); the jittable
        # on-device wavefront otherwise
        from .. import native as _native

        host_ok = (
            not isinstance(W, jax.core.Tracer)
            and not A.is_complex
            and W.dtype == jnp.float64
            and _native.hb2st_available()
        )
        if host_ok:
            W_h = np.asarray(W)
            metrics.inc("transfer.d2h_bytes", W_h.nbytes)
            d_h, e_h, VS, TAUS = _native.hb2st_host_device(W_h, n, b)
            d = jnp.asarray(d_h)
            e = jnp.asarray(e_h)
            u = jnp.ones((n,), A.dtype)
        else:
            d, e, u, VS, TAUS = bulge.hb2st(W, n, b)
        if not vectors:
            return bulge.tridiag_eigvals_bisect(d, e), None
        # tridiagonal stage with vectors (steqr role): dense vendor +
        # Jacobi polish on the (n x n) tridiagonal assembly
        w, ZT = steqr(d, e, vectors=True)
        Z2 = bulge.unmtr_hb2st(
            TAUS=TAUS, VS=VS, Z=(u[:, None] * ZT).astype(A.dtype), n=n, b=b
        )
    else:
        # rebuild the full Hermitian band from the stored triangle (the
        # spmd he2hb band carries the lower triangle only)
        w, Z2 = _gathered_band_eig(band.full_global(), vectors)
        if not vectors:
            return w, None
    Zm = Matrix(
        tiles_from_global(Z2.astype(A.dtype), A.layout), A.layout, grid=A.grid
    ).shard()
    # back-transform: Z = Q_he2hb Z_band (unmtr_he2hb, heev.cc:193-203)
    Z = unmtr_he2hb(Side.Left, Op.NoTrans, V, T, Zm, opts)
    return w, Z


@instrumented("sterf")
def sterf(d: jnp.ndarray, e: jnp.ndarray) -> jnp.ndarray:
    """Eigenvalues of a symmetric tridiagonal matrix, no vectors
    (reference: src/sterf.cc QL/QR iteration) — bisection with
    vectorized Sturm counts (ops/bulge.py), all eigenvalues in
    parallel: the TPU-native replacement for the sequential QL/QR."""
    from ..ops.bulge import tridiag_eigvals_bisect

    return tridiag_eigvals_bisect(jnp.real(d), jnp.real(e))


@instrumented("steqr")
def steqr(
    d: jnp.ndarray, e: jnp.ndarray, vectors: bool = True,
    method: str = "dc",
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Tridiagonal eigensolver (reference: src/steqr.cc implicit QR).

    Values-only runs the parallel Sturm bisection; with vectors, the
    native divide & conquer (ops/stedc.py) — no vendor eigensolver
    anywhere on the path (the vendor f64 eigh is a compile bomb past
    n~512 on this toolchain).  ``method="stein"`` takes the independent
    fallback pairing instead: Sturm-bisection eigenvalues + batched
    inverse-iteration vectors (ops/stein.py — the dstebz+dstein
    analogue, de-risking the D&C path)."""
    if not vectors:
        return sterf(d, e), None
    if method == "stein":
        from ..ops.bulge import tridiag_eigvals_bisect
        from ..ops.stein import stein as _stein

        dr, er = jnp.real(d), jnp.real(e)
        w = tridiag_eigvals_bisect(dr, er)
        return w, _stein(dr, er, w)
    return stedc(d, e, vectors=True)


@instrumented("stedc")
def stedc(
    d: jnp.ndarray, e: jnp.ndarray, vectors: bool = True
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Tridiagonal divide & conquer (reference: src/stedc.cc +
    stedc_deflate/merge/secular/solve/sort/z_vector).

    Native TPU redesign (ops/stedc.py): bottom-up Cuppen merge tree with
    every level's merges vmapped into one batch, vectorized laed4
    secular roots, masked static-shape deflation, Gu-Eisenstat Lowner
    z-vector, and MXU gemms for the back-rotations.  Values-only uses
    the parallel Sturm bisection (no tree needed)."""
    if not vectors:
        return sterf(d, e), None
    from ..ops.stedc import stedc as _stedc_dc

    w, Q = _stedc_dc(jnp.real(d), jnp.real(e))
    return w, Q


@accurate_matmul
@instrumented("hegst")
def hegst(
    itype: int,
    A: HermitianMatrix,
    L: TriangularMatrix,
    opts: Optional[Options] = None,
) -> HermitianMatrix:
    """Reduce the generalized problem to standard form (reference:
    src/hegst.cc + internal_hegst.cc): itype 1: C = L^-1 A L^-H;
    itype 2/3: C = L^H A L.

    Distributed itype-1 inputs run the SPMD composition
    (parallel/spmd_hegst.py): stored-triangle mirror assembly + the two
    column-pipeline trsm sweeps — no global gather."""
    from ..enums import Diag

    if (
        itype == 1
        and _is_distributed(A)
        and get_option(opts, Option.UseShardMap)
        and A.uplo == Uplo.Lower
        and A.op == Op.NoTrans
        and L.uplo == Uplo.Lower
        and L.op == Op.NoTrans
        and A.layout.mb == A.layout.nb
        and L.layout.mb == L.layout.nb
        and A.layout.nb == L.layout.nb
        and A.layout.nt == L.layout.nt
    ):
        from ..parallel.spmd_hegst import spmd_hegst_itype1

        Ct = spmd_hegst_itype1(
            A.grid,
            A.data,
            A.layout,
            L.data,
            L.layout,
            lower_a=True,
            unit_diag=(L.diag == Diag.Unit),
        )
        return HermitianMatrix(
            Ct, A.layout, grid=A.grid, uplo=Uplo.Lower
        )

    from ..ops import blas2d

    if _is_distributed(A) or _is_distributed(L):
        from ..internal import fallbacks

        fallbacks.record(
            "hegst", opts, "itype 2/3 / upper uplo / op view gather"
        )
    Ag = A.full_global()
    Lg = L._with(op=Op.NoTrans).to_global()
    if itype == 1:
        Y = blas2d.trsm2d(Side.Left, L.uplo, Op.NoTrans, L.diag, 1.0, Lg, Ag)
        Ch = blas2d.trsm2d(
            Side.Right, L.uplo, Op.ConjTrans, L.diag, 1.0, Lg, Y
        )
    else:
        LH = jnp.conj(Lg).T if A.is_complex else Lg.T
        Ch = LH @ Ag @ Lg
    return HermitianMatrix.from_global(
        Ch, A.layout.mb, A.layout.nb, grid=A.grid, uplo=A.uplo
    )


@accurate_matmul
@instrumented("hegv")
def hegv(
    itype: int,
    A: HermitianMatrix,
    B: HermitianMatrix,
    opts: Optional[Options] = None,
    vectors: bool = True,
) -> Tuple[jnp.ndarray, Optional[Matrix], jnp.ndarray]:
    """Generalized Hermitian-definite eigenproblem (reference: src/hegv.cc:
    potrf(B) + hegst + heev + triangular back-transform).

    itype 1: A x = lambda B x.  Returns (Lambda, X or None, info)."""
    from . import chol

    L, info = chol.potrf(B, opts)
    C = hegst(itype, A, L, opts)
    w, Z = heev(C, opts, vectors=vectors)
    if not vectors:
        return w, None, info
    # x = L^-H y (itype 1)
    X = blas3.trsm(Side.Left, 1.0, conj_transpose(L), Z, opts)
    return w, X, info


def sygv(itype, A, B, opts=None, vectors=True):
    """Real-symmetric alias of hegv (reference: hegv covers sygv)."""
    return hegv(itype, A, B, opts, vectors)
