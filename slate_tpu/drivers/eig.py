"""Hermitian eigensolver family (reference: src/heev.cc, he2hb.cc,
hb2st.cc, sterf.cc, steqr.cc, stedc*.cc, unmtr_he2hb.cc, unmtr_hb2st.cc,
hegst.cc, hegv.cc; SURVEY §3.5).

Staging mirrors the reference:

  heev:  he2hb (dense -> band, distributed-capable, all the FLOPs)
         -> gather -> tridiagonal/eigen stage on one device.

The reference also runs stage 2+ on ONE node over a gathered band
(heev.cc:135 he2hbGather, hb2st threads+atomics) calling LAPACK
sterf/steqr/stedc; here the gathered stage calls the XLA eigensolver
(jnp.linalg.eigh — our L0 vendor-kernel layer, exactly as the reference
leans on LAPACK).  A native Pallas bulge-chaser is the planned
replacement (SURVEY §7 step 6).

he2hb is implemented as blocked two-sided Householder updates
(he2hb.cc:174-185's panel QR + trailing her2k-style update), using our
QR panel kernels; the back-transform unmtr_he2hb applies the stored
reflectors.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..enums import MethodEig, Norm, Op, Option, Side, Uplo
from ..exceptions import slate_assert
from ..matrix.base import BaseMatrix, conj_transpose
from ..matrix.matrix import HermitianMatrix, HermitianBandMatrix, Matrix, TriangularMatrix
from ..options import Options, get_option
from ..ops.householder import geqrf as _geqrf_kernel, larft, materialize_v
from ..parallel.layout import TileLayout, tiles_from_global
from ..types import TriangularFactors
from . import blas3


def he2hb(
    A: HermitianMatrix, opts: Optional[Options] = None
) -> Tuple[HermitianBandMatrix, Matrix, TriangularFactors]:
    """Reduce Hermitian A to band form with bandwidth nb
    (reference: src/he2hb.cc: per-panel QR over panel ranks + two-sided
    trailing update).

    Returns (band, V, T): band Hermitian with kd = nb; V stores the block
    reflectors (panel k in tile column k, rows k+1..), T their compact-WY
    factors — the inputs of unmtr_he2hb."""
    slate_assert(A.m == A.n, "he2hb requires square")
    lay = A.layout
    nb = lay.nb
    n = A.n
    G = A.full_global()
    kt = lay.nt
    Vs = jnp.zeros_like(G)
    Ts = []
    complex_t = A.is_complex

    def C(x):
        return jnp.conj(x) if complex_t else x

    for k in range(kt - 1):
        lo = (k + 1) * nb
        w = min(nb, n - k * nb)
        if lo >= n:
            break
        panel = G[lo:, k * nb : k * nb + w]
        vr, taus = _geqrf_kernel(panel)
        V = materialize_v(vr, offset=0)  # (n-lo, w) unit-lower
        Tk = larft(V, taus)
        # panel becomes [R; 0]
        R = jnp.triu(vr)
        G = G.at[lo:, k * nb : k * nb + w].set(R)
        G = G.at[k * nb : k * nb + w, lo:].set(C(R).T)
        # two-sided update of trailing A22 (Hermitian):
        # A' = H^H A H,  H = I - V Tk V^H
        A22 = G[lo:, lo:]
        P = A22 @ (V @ Tk)  # (n-lo, w)
        Q2 = C(Tk).T @ (C(V).T @ P)  # (w, w)
        A22 = A22 - V @ C(P).T - P @ C(V).T + V @ Q2 @ C(V).T
        G = G.at[lo:, lo:].set(A22)
        Vs = Vs.at[lo:, k * nb : k * nb + w].set(V)
        Tk_full = jnp.zeros((nb, nb), G.dtype).at[:w, :w].set(Tk)
        Ts.append(Tk_full)

    Tstack = (
        jnp.stack(Ts) if Ts else jnp.zeros((0, nb, nb), G.dtype)
    )
    band = HermitianBandMatrix(
        tiles_from_global(G, lay), lay, grid=A.grid, kd=nb, uplo=A.uplo
    )
    Vm = Matrix(tiles_from_global(Vs, lay), lay, grid=A.grid)
    return band, Vm, TriangularFactors(Tstack)


def unmtr_he2hb(
    side: Side,
    op: Op,
    V: Matrix,
    T: TriangularFactors,
    C_mat: Matrix,
    opts: Optional[Options] = None,
) -> Matrix:
    """Apply the he2hb back-transform Q (reference: src/unmtr_he2hb.cc).

    Q = H_0 H_1 ... with H_k = I - V_k T_k V_k^H (V_k in tile column k,
    shifted one block down)."""
    lay = V.layout
    nb = lay.nb
    n = V.n
    kt = lay.nt
    Vg = V.to_global()
    C2 = C_mat.to_global()
    complex_t = V.is_complex

    def CC(x):
        return jnp.conj(x) if complex_t else x

    npanels = T.T.shape[0]
    forward = (side == Side.Left) == (op != Op.NoTrans)
    order = range(npanels) if forward else range(npanels - 1, -1, -1)
    for k in order:
        lo = (k + 1) * nb
        w = min(nb, n - k * nb)
        Vk = Vg[lo:, k * nb : k * nb + w]
        Tk = T.T[k][:w, :w]
        Tm = CC(Tk).T if op != Op.NoTrans else Tk
        if side == Side.Left:
            W = CC(Vk).T @ C2[lo:]
            C2 = C2.at[lo:].set(C2[lo:] - Vk @ (Tm @ W))
        else:
            W = C2[:, lo:] @ Vk
            C2 = C2.at[:, lo:].set(C2[:, lo:] - (W @ Tm) @ CC(Vk).T)
    return C_mat._with(data=tiles_from_global(C2.astype(C_mat.dtype), C_mat.layout))


def _gathered_band_eig(
    band_2d: jnp.ndarray, vectors: bool
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Stage 2+: eigensolve the gathered band matrix on one device via the
    XLA vendor eigensolver (reference analogue: gathered hb2st + LAPACK
    steqr/stedc on one node, heev.cc:135-180).

    On TPU f64 the vendor eigh stops ~1e-7 short of working precision;
    ops/jacobi.py's parallel-order Jacobi polish restores LAPACK-level
    accuracy (SURVEY §7 hard-part (5))."""
    from ..ops.jacobi import eigh_accurate

    return eigh_accurate(band_2d, vectors=vectors)


def heev(
    A: HermitianMatrix,
    opts: Optional[Options] = None,
    vectors: bool = True,
) -> Tuple[jnp.ndarray, Optional[Matrix]]:
    """Hermitian eigendecomposition (reference: src/heev.cc two-stage).

    Returns (Lambda ascending, Z or None).  MethodEig selects the
    tridiagonal-stage algorithm in the reference (QR iteration vs divide &
    conquer); the vendor eigensolver is D&C-equivalent."""
    band, V, T = he2hb(A, opts)
    Gband = band.to_global()
    w, Z2 = _gathered_band_eig(Gband, vectors)
    if not vectors:
        return w, None
    Zm = Matrix(
        tiles_from_global(Z2.astype(A.dtype), A.layout), A.layout, grid=A.grid
    )
    # back-transform: Z = Q_he2hb Z_band (unmtr_he2hb, heev.cc:193-203)
    Z = unmtr_he2hb(Side.Left, Op.NoTrans, V, T, Zm, opts)
    return w, Z


def sterf(d: jnp.ndarray, e: jnp.ndarray) -> jnp.ndarray:
    """Eigenvalues of a symmetric tridiagonal matrix, no vectors
    (reference: src/sterf.cc QL/QR iteration).  Vendor eigensolver on the
    assembled tridiagonal, Jacobi-polished on TPU f64."""
    Tm = jnp.diag(d) + jnp.diag(e, 1) + jnp.diag(e, -1)
    w, _ = _gathered_band_eig(Tm, vectors=False)
    return w


def steqr(
    d: jnp.ndarray, e: jnp.ndarray, vectors: bool = True
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Tridiagonal eigensolver with vectors (reference: src/steqr.cc
    implicit QR)."""
    Tm = jnp.diag(d) + jnp.diag(e, 1) + jnp.diag(e, -1)
    return _gathered_band_eig(Tm, vectors)


def stedc(
    d: jnp.ndarray, e: jnp.ndarray, vectors: bool = True
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Tridiagonal divide & conquer (reference: src/stedc.cc +
    stedc_deflate/merge/secular/solve/sort/z_vector).  The XLA eigensolver
    is itself a D&C; the reference's explicit deflation pipeline is a
    planned native replacement."""
    return steqr(d, e, vectors)


def hegst(
    itype: int,
    A: HermitianMatrix,
    L: TriangularMatrix,
    opts: Optional[Options] = None,
) -> HermitianMatrix:
    """Reduce the generalized problem to standard form (reference:
    src/hegst.cc): itype 1: C = L^-1 A L^-H; itype 2/3: C = L^H A L."""
    from ..ops import blas2d

    Ag = A.full_global()
    Lg = L._with(op=Op.NoTrans).to_global()
    if itype == 1:
        Y = blas2d.trsm2d(Side.Left, L.uplo, Op.NoTrans, L.diag, 1.0, Lg, Ag)
        Ch = blas2d.trsm2d(
            Side.Right, L.uplo, Op.ConjTrans, L.diag, 1.0, Lg, Y
        )
    else:
        LH = jnp.conj(Lg).T if A.is_complex else Lg.T
        Ch = LH @ Ag @ Lg
    return HermitianMatrix.from_global(
        Ch, A.layout.mb, A.layout.nb, grid=A.grid, uplo=A.uplo
    )


def hegv(
    itype: int,
    A: HermitianMatrix,
    B: HermitianMatrix,
    opts: Optional[Options] = None,
    vectors: bool = True,
) -> Tuple[jnp.ndarray, Optional[Matrix], jnp.ndarray]:
    """Generalized Hermitian-definite eigenproblem (reference: src/hegv.cc:
    potrf(B) + hegst + heev + triangular back-transform).

    itype 1: A x = lambda B x.  Returns (Lambda, X or None, info)."""
    from . import chol

    L, info = chol.potrf(B, opts)
    C = hegst(itype, A, L, opts)
    w, Z = heev(C, opts, vectors=vectors)
    if not vectors:
        return w, None, info
    # x = L^-H y (itype 1)
    X = blas3.trsm(Side.Left, 1.0, conj_transpose(L), Z, opts)
    return w, X, info


def sygv(itype, A, B, opts=None, vectors=True):
    """Real-symmetric alias of hegv (reference: hegv covers sygv)."""
    return hegv(itype, A, B, opts, vectors)
