"""Hermitian-indefinite solvers (reference: src/hetrf.cc Aasen two-stage
LTL^H to band, hetrs.cc, hesv.cc).

The reference's Aasen algorithm (panel factor + band reduction with
partial pivoting inside the panel sub-communicator) is built around
fine-grained row exchanges that map poorly to static TPU schedules.  Here
hetrf computes a blocked LDL^H without pivoting; when that breaks down
(zero/non-finite D entry — e.g. a singular leading minor of a genuinely
indefinite matrix), it refactors after a two-sided random butterfly
congruence A' = U^H A U (gesv_rbt rationale: randomization replaces
pivoting on schedule-hostile hardware).  The butterfly, when used, rides
on the returned factor and hetrs applies it transparently; iterative
refinement in hesv restores accuracy either way.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..enums import Op, Side, Uplo
from ..exceptions import slate_assert
from ..matrix.base import conj_transpose
from ..matrix.matrix import HermitianMatrix, Matrix, TriangularMatrix
from ..options import Options
from ..parallel.layout import tiles_from_global
from . import lu as lu_mod
from .lu import _apply_butterfly, _butterfly_diags

from ..aux.metrics import instrumented


# Breakdown thresholds for the pivot-free pass.  Partial pivoting keeps
# |L| <= 1; without pivoting a near-singular leading minor shows up as
# element growth in L or a collapsed D entry.  Either trips the
# butterfly refactor — exact zeros alone would let a 1e-12 minor slip
# through to IR with catastrophic growth (reference: src/hetrf.cc,
# Aasen's stability rationale).
_GROWTH_LIMIT = 1e6
_DRATIO_LIMIT = 1e-12


def _ldl_nopiv(Af: jnp.ndarray, mb: int, grid, opts):
    """No-pivot LDL^H of a full Hermitian 2D array via getrf_nopiv."""
    Am = Matrix.from_global(Af, mb, grid=grid)
    LU, info = lu_mod.getrf_nopiv(Am, opts)
    G = LU.to_global()
    # A = L U with U = D L^H for Hermitian A  =>  D = diag(U)
    d = jnp.real(jnp.diagonal(G))
    n = Af.shape[0]
    Ltri = jnp.tril(G, -1)
    L = TriangularMatrix.from_global(
        Ltri + jnp.eye(n, dtype=G.dtype),
        mb,
        mb,
        grid=grid,
        uplo=Uplo.Lower,
    )
    growth = jnp.abs(Ltri).max()
    dmax = jnp.abs(d).max()
    dmin = jnp.abs(d).min()
    bad = (
        jnp.any((d == 0) | ~jnp.isfinite(d))
        | ~jnp.isfinite(growth)
        | (growth > _GROWTH_LIMIT)
        | (dmin < _DRATIO_LIMIT * dmax)
    )
    info = jnp.maximum(info, jnp.where(bad, 1, 0)).astype(jnp.int32)
    return L, d, info


@instrumented("hetrf")
def hetrf(
    A: HermitianMatrix, opts: Optional[Options] = None,
    method: str = "auto",
) -> Tuple[TriangularMatrix, jnp.ndarray, jnp.ndarray]:
    """Factor A = L D L^H, L unit lower, D real diagonal
    (reference contract: src/hetrf.cc; see module docstring for the
    pivot-free TPU algorithm).

    Returns (L, d, info).  ``method``:

    * "auto"  — pivot-free LDL^H; on breakdown, refactor with Aasen's
      partially-pivoted LTL^H (ops/aasen.py — the reference's hetrf
      algorithm, host-driven there too); L carries the Aasen factors
      (L._aasen) and hetrs consumes them transparently.
    * "aasen" — Aasen directly (the reference's method).
    * "rbt"   — pivot-free with the random-butterfly breakdown fallback
      of earlier rounds (L._rbt).

    Inside jit there is no host info value to branch on, so the
    breakdown refactors cannot engage: traced calls return the
    no-pivot factor with the lazy info array (nonzero = breakdown),
    matching the other drivers' info contract."""
    slate_assert(A.m == A.n, "hetrf requires square")
    Af = A.full_global()
    lay = A.layout

    def _aasen_factor():
        from ..ops.aasen import aasen_ltl

        Lnp, al, be, perm, _info = aasen_ltl(np.asarray(Af))
        L = TriangularMatrix.from_global(
            jnp.asarray(Lnp), lay.mb, lay.mb, grid=A.grid, uplo=Uplo.Lower
        )
        L._aasen = (al, be, perm)
        return L, jnp.asarray(al), jnp.zeros((), jnp.int32)

    if method == "aasen":
        return _aasen_factor()
    L, d, info = _ldl_nopiv(Af, lay.mb, A.grid, opts)
    import jax

    if isinstance(info, jax.core.Tracer):
        # Traced (inside jit): the lazy-info contract of the other
        # drivers (potrf/getrf) applies — return the no-pivot factor
        # and the info ARRAY as-is; a nonzero info flags the breakdown
        # to the caller, and NumericalError is raised only where a host
        # value is demanded (serve's direct_call, compat int(info)).
        # The Aasen/butterfly breakdown refactors are host-driven
        # algorithms (as in the reference) and engage on eager calls.
        return L, d, info
    if int(info) == 0:
        return L, d, info
    if method == "auto":
        # breakdown: the reference's pivoted-stability algorithm
        return _aasen_factor()
    # breakdown: randomize with a Hermitian-preserving butterfly congruence
    # A' = U^H A U, pad to a power of 2 with an identity block so the
    # static-shape butterfly stays invertible (gesv_rbt structure).
    n = A.n
    n2 = 1 << int(np.ceil(np.log2(max(n, 1))))
    # Full-depth butterfly, unconditionally: depth-2 (gesv_rbt's default)
    # only mixes at coarse strides and leaves fine-grained singular-minor
    # structure (e.g. kron(I, [[0,1],[1,0]])) intact; log2(n) levels mix
    # every pair.  Deliberately NOT Option.Depth: that key tunes gesv_rbt
    # and a shared opts dict must not weaken this fallback.
    depth = max(int(np.log2(n2)), 1)
    Ap = jnp.pad(Af, ((0, n2 - n), (0, n2 - n)))
    Ap = Ap + jnp.diag(
        jnp.concatenate([jnp.zeros(n), jnp.ones(n2 - n)]).astype(Af.dtype)
    )
    du = _butterfly_diags(n2, depth, 1729, jnp.float64)
    if A.is_complex:
        # complex phases: a real congruence cannot break the structure of
        # purely-imaginary Hermitian matrices (i*K keeps a zero diagonal
        # under any real U^T A U)
        from ..matgen.philox import random_jnp

        idx = jnp.arange(depth * n2, dtype=jnp.int64).reshape(depth, n2)
        ph = random_jnp(
            "uniform_signed", 4242, idx, jnp.zeros_like(idx), jnp.float64
        )
        du = (du * jnp.exp(1j * np.pi * ph)).astype(Af.dtype)
    else:
        du = du.astype(Af.dtype)
    Ar = _apply_butterfly(Ap, jnp.conj(du), transpose=True)  # U^H A
    Ar = _apply_butterfly(Ar.T, du, transpose=True).T  # (U^H A) U
    Lr, dr, info_r = _ldl_nopiv(Ar, min(lay.mb, n2), A.grid, opts)
    Lr._rbt = (du, n)
    return Lr, dr, info_r


@instrumented("hetrs")
def hetrs(
    L: TriangularMatrix, d: jnp.ndarray, B: Matrix, opts: Optional[Options] = None
) -> Matrix:
    """Solve A X = B from the L D L^H factor (reference: src/hetrs.cc).

    Handles the plain factor, the Aasen LTL^H factor (L._aasen), and
    the butterfly-randomized fallback (L._rbt set by hetrf):
    A x = b <=> (U^H A U) y = U^H b, x = U y."""
    from . import blas3

    aasen_fac = getattr(L, "_aasen", None)
    if aasen_fac is not None:
        from ..ops.aasen import aasen_solve

        al, be, perm = aasen_fac
        Lnp = np.asarray(L._with(op=Op.NoTrans).to_global())
        X = aasen_solve(np.tril(Lnp), al, be, perm, np.asarray(B.to_global()))
        return B._with(
            data=tiles_from_global(jnp.asarray(X).astype(B.dtype), B.layout)
        )

    rbt = getattr(L, "_rbt", None)
    if rbt is None:
        Y = blas3.trsm(Side.Left, 1.0, L, B, opts)
        Yg = Y.to_global() / jnp.where(d == 0, 1, d)[:, None].astype(B.dtype)
        Ym = B._with(data=tiles_from_global(Yg.astype(B.dtype), B.layout))
        return blas3.trsm(Side.Left, 1.0, conj_transpose(L), Ym, opts)

    du, n = rbt
    n2 = L.n
    B2 = B.to_global()
    Bp = jnp.pad(B2, ((0, n2 - n), (0, 0)))
    Rp = _apply_butterfly(Bp, jnp.conj(du), transpose=True)  # U^H b
    Lg = L._with(op=Op.NoTrans).to_global()
    Y = lax.linalg.triangular_solve(
        Lg, Rp, left_side=True, lower=True, unit_diagonal=True
    )
    Y = Y / jnp.where(d == 0, 1, d)[:, None].astype(B.dtype)
    Z = lax.linalg.triangular_solve(
        jnp.conj(Lg).T if L.is_complex else Lg.T,
        Y,
        left_side=True,
        lower=False,
    )
    X = _apply_butterfly(Z, du, transpose=False)[:n]
    return B._with(data=tiles_from_global(X.astype(B.dtype), B.layout))


@instrumented("hesv")
def hesv(
    A: HermitianMatrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, TriangularMatrix, jnp.ndarray, jnp.ndarray]:
    """Hermitian-indefinite solve (reference: src/hesv.cc = hetrf + hetrs)
    with iterative-refinement cleanup of the pivot-free factorization."""
    L, d, info = hetrf(A, opts)
    X = hetrs(L, d, B, opts)
    Af = A.full_global()
    B2 = B.to_global()
    for _ in range(2):
        R = B2 - Af @ X.to_global()
        Rm = B._with(data=tiles_from_global(R.astype(B.dtype), B.layout))
        C = hetrs(L, d, Rm, opts)
        X = X._with(data=X.data + C.data)
    return X, L, d, info
