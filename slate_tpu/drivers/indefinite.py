"""Hermitian-indefinite solvers (reference: src/hetrf.cc Aasen two-stage
LTL^H to band, hetrs.cc, hesv.cc).

The reference's Aasen algorithm (panel factor + band reduction with
partial pivoting inside the panel sub-communicator) is built around
fine-grained row exchanges that map poorly to static TPU schedules.  Here
hetrf computes a blocked LDL^H without pivoting, optionally after a
random butterfly randomization (gesv_rbt rationale: randomization replaces
pivoting on schedule-hostile hardware); one step of iterative refinement
in hesv restores accuracy.  The factor object matches the L D L^H
contract, so hetrs is two unit-triangular solves + a diagonal scale.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

from ..enums import Option, Side, Uplo
from ..exceptions import slate_assert
from ..matrix.base import conj_transpose
from ..matrix.matrix import HermitianMatrix, Matrix, TriangularMatrix
from ..options import Options, get_option
from ..parallel.layout import tiles_from_global
from . import lu as lu_mod


def hetrf(
    A: HermitianMatrix, opts: Optional[Options] = None
) -> Tuple[TriangularMatrix, jnp.ndarray, jnp.ndarray]:
    """Factor A = L D L^H, L unit lower, D real diagonal
    (reference contract: src/hetrf.cc; see module docstring for the
    pivot-free TPU algorithm).

    Returns (L, d, info)."""
    slate_assert(A.m == A.n, "hetrf requires square")
    Af = A.full_global()
    lay = A.layout
    Am = Matrix.from_global(Af, lay.mb, lay.nb, grid=A.grid)
    LU, info = lu_mod.getrf_nopiv(Am, opts)
    G = LU.to_global()
    # A = L U with U = D L^H for Hermitian A  =>  D = diag(U)
    d = jnp.real(jnp.diagonal(G))
    L = TriangularMatrix.from_global(
        jnp.tril(G, -1) + jnp.eye(A.n, dtype=G.dtype),
        lay.mb,
        lay.nb,
        grid=A.grid,
        uplo=Uplo.Lower,
    )
    bad = (d == 0) | ~jnp.isfinite(d)
    info = jnp.maximum(info, jnp.where(jnp.any(bad), 1, 0)).astype(jnp.int32)
    return L, d, info


def hetrs(
    L: TriangularMatrix, d: jnp.ndarray, B: Matrix, opts: Optional[Options] = None
) -> Matrix:
    """Solve A X = B from the L D L^H factor (reference: src/hetrs.cc)."""
    from . import blas3

    Y = blas3.trsm(Side.Left, 1.0, L, B, opts)
    Yg = Y.to_global() / jnp.where(d == 0, 1, d)[:, None].astype(B.dtype)
    Ym = B._with(data=tiles_from_global(Yg.astype(B.dtype), B.layout))
    return blas3.trsm(Side.Left, 1.0, conj_transpose(L), Ym, opts)


def hesv(
    A: HermitianMatrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, TriangularMatrix, jnp.ndarray, jnp.ndarray]:
    """Hermitian-indefinite solve (reference: src/hesv.cc = hetrf + hetrs)
    with iterative-refinement cleanup of the pivot-free factorization."""
    L, d, info = hetrf(A, opts)
    X = hetrs(L, d, B, opts)
    Af = A.full_global()
    B2 = B.to_global()
    for _ in range(2):
        R = B2 - Af @ X.to_global()
        Rm = B._with(data=tiles_from_global(R.astype(B.dtype), B.layout))
        C = hetrs(L, d, Rm, opts)
        X = X._with(data=X.data + C.data)
    return X, L, d, info
