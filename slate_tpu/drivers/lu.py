"""LU family drivers (reference: src/getrf.cc, getrf_nopiv.cc,
getrf_tntpiv.cc, getrs.cc, getrs_nopiv.cc, gesv.cc, gesv_nopiv.cc,
gesv_rbt.cc + gerbt.cc + internal_rbt_generate.cc, gesv_mixed.cc,
gesv_mixed_gmres.cc, getri.cc, getriOOP.cc, gecondest.cc, trcondest.cc).

Pivoted LU under a static schedule is hard part (1) of SURVEY §7; the
global path hands the panel-pivot search to XLA's lu, the spmd path runs
the explicit mesh algorithm (parallel/spmd_lu.py).  The schedule-friendly
alternatives the reference offers — no-pivot LU and the random butterfly
transform — are first-class here for the same reason they exist there.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..enums import Diag, MethodLU, Norm, Op, Option, Side, Uplo
from ..exceptions import slate_assert
from ..matrix.base import BaseMatrix
from ..matrix.matrix import Matrix, TriangularMatrix
from ..options import Options, get_option, resolve_schedule_opts
from ..ops import lu_kernels
from ..parallel import spmd_lu, spmd_trsm
from ..parallel.layout import eye_splice, tiles_from_global, tiles_to_global
from ..types import Pivots
from . import blas3
from .aux import norm as _norm

from ..aux import metrics
from ..aux.metrics import instrumented


from ..matrix.base import is_distributed as _is_distributed
from ..internal import fallbacks

# metrics-gated jitted kernel: attributes the eager global LU's
# compile/run split + cost_analysis to "getrf.kernel" (unjitted original
# call with metrics off).  The padded-global operand (always a fresh
# temporary) is donated on accelerators when this jit dispatches —
# getrf overwrites A in place like the reference; under an outer jit
# (serve cores) the outer boundary donates instead (serve/cache.py).
_lu_global_kernel = metrics.gated_jit(
    lu_kernels.lu_global, "getrf.kernel",
    static_argnums=(1, 2, 3, 4), donate_argnums=(0,),
)


def _padded_global(A: BaseMatrix, splice_diag=True) -> jnp.ndarray:
    Ar = A.resolved()
    lay = Ar.layout
    G = Ar.to_global()
    mp, np_ = lay.P * lay.mb, lay.Q * lay.nb
    Gp = jnp.pad(G, ((0, mp - lay.m), (0, np_ - lay.n)))
    if splice_diag:
        d = jnp.zeros(min(mp, np_), dtype=G.dtype)
        d = d.at[min(lay.m, lay.n):].set(1)
        Gp = Gp + jnp.zeros_like(Gp).at[
            jnp.arange(min(mp, np_)), jnp.arange(min(mp, np_))
        ].set(d)
    return Gp


def _udiag_info(LU: Matrix, lay) -> jnp.ndarray:
    """info code: exact zero / non-finite on U's diagonal.

    Evaluated as a masked reduction over the storage tile array — on a
    mesh GSPMD lowers it to a local reduction + psum, never a gather
    (the reference's internal::reduce_info, potrf.cc:208; the old
    to_global() here round-tripped the whole matrix to check n scalars)."""
    dmin = min(lay.m, lay.n)
    gr = jnp.asarray(lay.global_rows_np)[:, None, :, None]
    gc = jnp.asarray(lay.global_cols_np)[None, :, None, :]
    dmask = (gr == gc) & (gr < dmin)
    T = LU.data
    bad = (T == 0) | ~jnp.isfinite(T)
    if jnp.issubdtype(T.dtype, jnp.complexfloating):
        bad = (T == 0) | ~(jnp.isfinite(jnp.real(T)) & jnp.isfinite(jnp.imag(T)))
    return jnp.where(jnp.any(bad & dmask), 1, 0).astype(jnp.int32)


@instrumented("getrf")
def getrf(
    A: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, Pivots, jnp.ndarray]:
    """LU with partial pivoting: P A = L U (reference: src/getrf.cc).

    Returns (LU, pivots, info): LU holds unit-lower L below the diagonal
    and U on/above (LAPACK layout); pivots is the net forward row
    permutation; info > 0 flags an exactly-singular U diagonal.
    """
    slate_assert(A.op == Op.NoTrans, "getrf expects a non-transposed view")
    lay = A.layout
    method = get_option(opts, Option.MethodLU, MethodLU.Auto)
    if isinstance(method, str):
        method = MethodLU.from_string(method)
    if method in (MethodLU.CALU, MethodLU.BEAM):
        # tournament pivoting (reference: getrf_tntpiv.cc; BEAM maps to
        # the tournament too — both trade the per-column pivot search for
        # a communication-free reduction, the fit for static schedules)
        if (
            _is_distributed(A)
            and get_option(opts, Option.UseShardMap)
            and lay.mb == lay.nb
        ):
            # mesh tournament: local election per process row + one
            # winner all_gather over 'p' (parallel/spmd_lu.py)
            T = eye_splice(lay, A.data)
            Td, perm = spmd_lu.spmd_getrf_tntpiv(A.grid, T, lay)
            LU = A._with(data=Td)
            return LU, Pivots(perm), _udiag_info(LU, lay)
        if _is_distributed(A):
            import warnings

            warnings.warn(
                "getrf(MethodLU.CALU) on a distributed matrix gathers to a "
                "global array (non-square tiles or UseShardMap disabled)",
                stacklevel=2,
            )
            fallbacks.record("getrf_tntpiv", opts, "tournament gathers")
        Gp = _padded_global(A)
        lu2d, perm = lu_kernels.blocked_getrf_tntpiv(Gp, lay.nb)
        LU = A._with(data=tiles_from_global(lu2d[: lay.m, : lay.n], lay)).shard()
        return LU, Pivots(perm), _udiag_info(LU, lay)
    use_spmd = _is_distributed(A) and get_option(opts, Option.UseShardMap)
    if use_spmd and lay.mb == lay.nb:
        T = eye_splice(lay, A.data)
        Td, perm = spmd_lu.spmd_getrf(A.grid, T, lay)
        LU = A._with(data=Td)
        m_valid = lay.m
    else:
        if _is_distributed(A):
            fallbacks.record("getrf", opts, "non-square tiles")
        Gp = _padded_global(A)
        # schedule-dispatched kernel: vendor LU when auto on a backend
        # that supports the dtype (TPU: f32/c64 only), recursive divide
        # & conquer at large n / on request, else the flat blocked
        # right-looking kernel (ops/lu_kernels.py; src/getrf.cc:85-214)
        sched, nb_switch, lookahead = resolve_schedule_opts(opts)
        mp, np_ = Gp.shape
        if metrics.is_on():
            route = lu_kernels.resolve_lu_schedule(mp, np_, Gp.dtype, sched)
            metrics.record_factor_flops(
                "getrf",
                lu_kernels.getrf_schedule_flops(
                    mp, np_, lay.nb, route, nb_switch, lookahead,
                    m_true=lay.m, n_true=lay.n,
                ),
            )
        lu2d, perm = _lu_global_kernel(
            Gp, lay.nb, sched, nb_switch, lookahead
        )
        LU = A._with(data=tiles_from_global(lu2d[: lay.m, : lay.n], lay)).shard()
        m_valid = lay.m

    return LU, Pivots(perm), _udiag_info(LU, lay)


@instrumented("getrf_nopiv")
def getrf_nopiv(
    A: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, jnp.ndarray]:
    """LU without pivoting (reference: src/getrf_nopiv.cc) — the
    schedule-friendly variant: one triangular recursion, no row traffic."""
    slate_assert(A.m == A.n, "getrf_nopiv requires square A")
    slate_assert(A.layout.mb == A.layout.nb, "getrf_nopiv requires square tiles")
    lay = A.layout
    Gp = _padded_global(A)
    n = Gp.shape[0]

    # blocked right-looking no-pivot LU via scan-free recursion: XLA's lu
    # always pivots, so build L/U from it only when the permutation is
    # identity; otherwise do the blocked elimination directly.
    def nopiv_lu(G):
        nb = lay.nb

        def body(k, G):
            # diag block
            akk = lax.dynamic_slice(G, (k * nb, k * nb), (nb, nb))
            # factor diag block without pivoting: unrolled nb Gauss steps
            # via triangular solves against the strictly-lower recursion:
            lkk_ukk = _nopiv_block(akk)
            G = lax.dynamic_update_slice(G, lkk_ukk, (k * nb, k * nb))
            Lkk = jnp.tril(lkk_ukk, -1) + jnp.eye(nb, dtype=G.dtype)
            Ukk = jnp.triu(lkk_ukk)
            # panel below: A(i,k) Ukk^-1
            col = lax.dynamic_slice(G, (0, k * nb), (n, nb))
            col_solved = lax.linalg.triangular_solve(
                Ukk, col, left_side=False, lower=False
            )
            row_sel = (jnp.arange(n) >= (k + 1) * nb)[:, None]
            col = jnp.where(row_sel, col_solved, col)
            G = lax.dynamic_update_slice(G, col, (0, k * nb))
            # row to the right: Lkk^-1 A(k,j)
            row = lax.dynamic_slice(G, (k * nb, 0), (nb, n))
            row_solved = lax.linalg.triangular_solve(
                Lkk, row, left_side=True, lower=True, unit_diagonal=True
            )
            col_sel = (jnp.arange(n) >= (k + 1) * nb)[None, :]
            row = jnp.where(col_sel, row_solved, row)
            G = lax.dynamic_update_slice(G, row, (k * nb, 0))
            # trailing update
            Lpan = jnp.where(row_sel, lax.dynamic_slice(G, (0, k * nb), (n, nb)), 0)
            Urow = jnp.where(col_sel, lax.dynamic_slice(G, (k * nb, 0), (nb, n)), 0)
            return G - Lpan @ Urow

        return lax.fori_loop(0, n // nb, body, G)

    lu2d = nopiv_lu(Gp)
    LU = A._with(data=tiles_from_global(lu2d[: lay.m, : lay.n], lay)).shard()
    return LU, _udiag_info(LU, lay)


def _nopiv_block(a: jnp.ndarray) -> jnp.ndarray:
    """Unblocked no-pivot LU of one tile via Schur-complement scan."""
    nb = a.shape[0]

    def body(j, a):
        pivot = a[j, j]
        col = a[:, j] / jnp.where(pivot == 0, 1, pivot)
        below = jnp.arange(nb) > j
        lcol = jnp.where(below, col, a[:, j] * 0)
        a = a.at[:, j].set(jnp.where(below, lcol, a[:, j]))
        right = jnp.arange(nb) > j
        upd = jnp.outer(lcol, jnp.where(right, a[j], 0))
        return a - upd

    return lax.fori_loop(0, nb, body, a)


@instrumented("getrs")
def getrs(
    LU: Matrix,
    pivots: Optional[Pivots],
    B: Matrix,
    opts: Optional[Options] = None,
) -> Matrix:
    """Solve A X = B from getrf factors (reference: src/getrs.cc:
    permuteRows forward, trsm L, trsm U).

    Distributed path: SPMD permute-rows + two shard_map trsm pipelines
    over the LU-packed tile array — B never gathers to a global array
    (reference: internal::permuteRows + work::trsm, getrs.cc)."""
    lay = LU.layout
    layB = B.layout
    if (
        _is_distributed(B)
        and get_option(opts, Option.UseShardMap)
        and lay.mb == lay.nb == layB.mb
        and (lay.p, lay.q) == (layB.p, layB.q)
        and layB.mt == lay.mt
        and LU.op == Op.NoTrans
        and B.op == Op.NoTrans
        and (pivots is None or pivots.perm.shape[0] == lay.P * lay.mb)
    ):
        TBd = B.data
        if pivots is not None:
            TBd = spmd_trsm.spmd_permute_rows(B.grid, TBd, layB, pivots.perm)
        TT = eye_splice(lay, LU.data)
        Y = spmd_trsm.spmd_trsm_left(
            B.grid, TT, lay, TBd, layB,
            lower=True, trans=False, conj=False, unit_diag=True,
        )
        X = spmd_trsm.spmd_trsm_left(
            B.grid, TT, lay, Y, layB,
            lower=False, trans=False, conj=False, unit_diag=False,
        )
        return B._with(data=X)
    if _is_distributed(B):
        fallbacks.record("getrs", opts, "layout/view not spmd-conformable")
    G = LU.to_global()
    B2 = B.to_global()
    if pivots is not None:
        B2 = pivots.apply(jnp.pad(B2, ((0, pivots.perm.shape[0] - B2.shape[0]), (0, 0))))[
            : B.m
        ]
    Y = lax.linalg.triangular_solve(
        G, B2, left_side=True, lower=True, unit_diagonal=True
    )
    X = lax.linalg.triangular_solve(G, Y, left_side=True, lower=False)
    return B._with(data=tiles_from_global(X.astype(B.dtype), B.layout)).shard()


def getrs_nopiv(LU: Matrix, B: Matrix, opts=None) -> Matrix:
    """(reference: src/getrs_nopiv.cc)"""
    return getrs(LU, None, B, opts)


def getrs_from_global(
    LUg: jnp.ndarray, Bg: jnp.ndarray, schedule: str = "auto"
) -> jnp.ndarray:
    """getrs-style solve-only entry point over global arrays: two trsm
    sweeps against a packed LU (unit-lower L below the diagonal, U on
    and above), B already row-permuted (P B).  This is the O(n^2)
    steady-state kernel of the serve factor cache's trsm-only
    (``phase="solve"``) bucket family — the factorization's row
    permutation is a host-side gather, so the traced program is pure
    triangular algebra and exports custom-call-free under the
    recursive schedule's jax lowering.  Fully traceable (jit/vmap).
    ``schedule="pallas"`` (or ``auto`` on an accelerator above the
    crossover) runs both sweeps through the fused Pallas trsm pair —
    the kernels read only their own triangle, so the packed storage
    needs no unpacking."""
    from .chol import _solve_trsm_route

    if _solve_trsm_route(LUg.shape[0], schedule) == "pallas":
        from ..ops.pallas import panel_kernels as pk

        Y = pk.trsm_lower(LUg, Bg, unit=True)
        return pk.trsm_upper(LUg, Y)
    Y = lax.linalg.triangular_solve(
        LUg, Bg, left_side=True, lower=True, unit_diagonal=True
    )
    return lax.linalg.triangular_solve(LUg, Y, left_side=True, lower=False)


@instrumented("gesv")
def gesv(
    A: Matrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, Matrix, Pivots, jnp.ndarray]:
    """Solve A X = B (reference: src/gesv.cc; method dispatch
    MethodLU Partial/NoPiv/RBT per gesv.cc + enums MethodLU)."""
    method = get_option(opts, Option.MethodLU, MethodLU.Auto)
    if isinstance(method, str):
        method = MethodLU.from_string(method)
    if method == MethodLU.NoPiv:
        LU, info = getrf_nopiv(A, opts)
        return getrs_nopiv(LU, B, opts), LU, Pivots(jnp.arange(0)), info
    if method == MethodLU.RBT:
        return gesv_rbt(A, B, opts)
    LU, piv, info = getrf(A, opts)
    X = getrs(LU, piv, B, opts)
    return X, LU, piv, info


def gesv_nopiv(A: Matrix, B: Matrix, opts=None):
    """(reference: src/gesv_nopiv.cc)"""
    return gesv(A, B, {**(dict(opts) if opts else {}), Option.MethodLU: MethodLU.NoPiv})


# ---------------------------------------------------------------------------
# Random butterfly transform (reference: src/gerbt.cc +
# src/internal/internal_rbt_generate.cc, gesv_rbt.cc).
# ---------------------------------------------------------------------------


def _butterfly_diags(n: int, depth: int, seed: int, dtype) -> jnp.ndarray:
    """Random diagonals for the recursive butterflies, from the Philox
    counter RNG so the transform is reproducible across distributions
    (reference: internal_rbt_generate.cc uses the same matgen RNG)."""
    from ..matgen.philox import random_jnp

    i = jnp.arange(depth * n, dtype=jnp.int64).reshape(depth, n)
    r = random_jnp("uniform_signed", seed, i, jnp.zeros_like(i), jnp.float64)
    # scale into [~0.9, ~1.1] exponentials like the reference's e^{r/10}
    vals = jnp.exp(r / 10.0)
    return vals.astype(dtype)


def _apply_butterfly(X: jnp.ndarray, diags: jnp.ndarray, transpose: bool) -> jnp.ndarray:
    """Y = B^T X (transpose=True) or B X, B = recursive butterfly of depth d.

    One depth-ell butterfly on vector x of even length 2h:
      B = 1/sqrt(2) [[D1, D2], [D1, -D2]]  (diagonal blocks)
      B^T x = 1/sqrt(2) [D1 (x1 + x2); D2 (x1 - x2)]
      B x   = 1/sqrt(2) [D1' x1 + D2' x2 ...]  -- with B orthogonal-like.
    Applied blockwise at each recursion level (reference gerbt.cc kernel
    structure).
    """
    from ..ops.pallas.kernels import butterfly_level

    d, n = diags.shape
    Y = X
    levels = range(d) if transpose else range(d - 1, -1, -1)
    for ell in levels:
        blocks = 2**ell
        h = n // (2 * blocks)
        if h == 0:
            continue
        D = diags[ell]
        Yr = Y.reshape(blocks, 2 * h, -1)
        Dr = D[: blocks * 2 * h].reshape(blocks, 2 * h)
        # per recursion block: the device butterfly pair kernel
        # (ops/pallas: one VMEM pass; jnp twin elsewhere)
        out = jax.vmap(
            lambda x, dr: butterfly_level(x, dr[:h], dr[h:], transpose)
        )(Yr, Dr)
        Y = out.reshape(n, -1)
    return Y


def _gerbt_full(A: Matrix, depth: int, seed: int):
    """Full power-of-2-padded two-sided butterfly transform.

    Returns (A'_2d of size n2, du, dv, n2).  The whole n2 x n2 transformed
    matrix must be kept: the butterfly mixes the identity padding into the
    valid block, so truncating before factoring breaks the algebra."""
    slate_assert(A.m == A.n, "rbt requires square A")
    n2 = 1 << int(np.ceil(np.log2(max(A.n, 1))))
    G = A.to_global()
    Gp = jnp.pad(G, ((0, n2 - A.n), (0, n2 - A.n)))
    Gp = Gp + jnp.diag(
        jnp.concatenate([jnp.zeros(A.n), jnp.ones(n2 - A.n)]).astype(G.dtype)
    )
    du = _butterfly_diags(n2, depth, seed, G.dtype)
    dv = _butterfly_diags(n2, depth, seed + 1, G.dtype)
    # A' = U^T A V: columns through U^T on the left, rows through V
    Gp = _apply_butterfly(Gp, du, transpose=True)
    Gp = _apply_butterfly(Gp.T, dv, transpose=True).T
    return Gp, du, dv, n2


def gerbt(
    A: Matrix, depth: int = 2, seed: int = 42, opts: Optional[Options] = None
) -> Tuple[Matrix, jnp.ndarray, jnp.ndarray]:
    """Two-sided random butterfly transform A' = U^T A V (reference:
    src/gerbt.cc); returns (A', diags_U, diags_V)."""
    Gp, du, dv, _ = _gerbt_full(A, depth, seed)
    out = Matrix.from_global(Gp[: A.n, : A.n], A.layout.mb, A.layout.nb, grid=A.grid)
    return out, du, dv


@instrumented("gesv_rbt")
def gesv_rbt(
    A: Matrix, B: Matrix, opts: Optional[Options] = None
) -> Tuple[Matrix, Matrix, Pivots, jnp.ndarray]:
    """RBT solve: butterfly-randomize, factor without pivoting, solve,
    then iterative refinement (reference: src/gesv_rbt.cc)."""
    depth = int(get_option(opts, Option.Depth, 2))
    seed = 42
    Gp, du, dv, n2 = _gerbt_full(A, depth, seed)
    mb = min(A.layout.mb, n2)
    Arbt = Matrix.from_global(Gp, mb, grid=A.grid)
    LU, info = getrf_nopiv(Arbt, opts)
    G_lu = LU.to_global()  # n2 x n2
    A2 = A.to_global()
    B2 = B.to_global()

    def solve(Rhs):
        Rp = jnp.pad(Rhs, ((0, n2 - A.n), (0, 0)))
        Rp = _apply_butterfly(Rp, du, transpose=True)
        Y = lax.linalg.triangular_solve(
            G_lu, Rp, left_side=True, lower=True, unit_diagonal=True
        )
        Z = lax.linalg.triangular_solve(G_lu, Y, left_side=True, lower=False)
        Z = _apply_butterfly(Z, dv, transpose=False)
        return Z[: A.n]

    X = solve(B2)
    # refinement steps (gesv_rbt.cc does IR to recover accuracy)
    for _ in range(2):
        R = B2 - A2 @ X
        X = X + solve(R)
    Xm = B._with(data=tiles_from_global(X.astype(B.dtype), B.layout)).shard()
    return Xm, LU, Pivots(jnp.arange(0)), info


# ---------------------------------------------------------------------------
# Inverse, mixed precision, condition estimation
# ---------------------------------------------------------------------------


@instrumented("getri")
def getri(LU: Matrix, pivots: Pivots, opts: Optional[Options] = None) -> Matrix:
    """Matrix inverse from LU factors (reference: src/getri.cc /
    getriOOP.cc): A^-1 = U^-1 L^-1 P."""
    eye = Matrix.from_global(
        jnp.eye(LU.m, dtype=LU.dtype), LU.layout.mb, LU.layout.nb, grid=LU.grid
    )
    return getrs(LU, pivots, eye, opts)


# Mixed-precision solvers: implementations live in drivers/mixed.py,
# routed through the refine/ subsystem (policy + IR/GMRES-IR cores);
# re-exported here for reference-parity import paths (lu.gesv_mixed).
from .mixed import gesv_mixed, gesv_mixed_gmres  # noqa: E402,F401

# Back-compat shim for the pre-refine/ helper name (the IR while_loop
# used to live here; chol.py and external callers imported it).
from ..refine.ir import ir_refine_while  # noqa: E402,F401


@instrumented("gecondest")
def gecondest(
    LU: Matrix, pivots: Pivots, anorm, norm_type: Norm = Norm.One, opts=None
):
    """Reciprocal condition estimate from LU (reference: src/gecondest.cc
    via the Hager/Higham 1-norm estimator, internal_norm1est.cc:1-511):
    O(n^2) factor solves instead of an explicit inverse."""
    from ..internal.norm1est import norm1est

    G = LU.to_global()
    n = G.shape[0]
    perm = jnp.clip(pivots.perm[:n], 0, n - 1)
    inv_perm = jnp.zeros((n,), perm.dtype).at[perm].set(
        jnp.arange(n, dtype=perm.dtype)
    )

    def solve(R):  # A^-1 R  (A = P^T L U)
        Y = lax.linalg.triangular_solve(
            G, R[perm], left_side=True, lower=True, unit_diagonal=True
        )
        return lax.linalg.triangular_solve(G, Y, left_side=True, lower=False)

    conj_a = LU.is_complex

    def solve_h(R):  # A^-H R
        Y = lax.linalg.triangular_solve(
            G, R, left_side=True, lower=False, transpose_a=True,
            conjugate_a=conj_a,
        )
        Z = lax.linalg.triangular_solve(
            G, Y, left_side=True, lower=True, unit_diagonal=True,
            transpose_a=True, conjugate_a=conj_a,
        )
        return Z[inv_perm]

    if norm_type == Norm.Inf:
        # ||A^-1||_inf = ||A^-H||_1
        est = norm1est(solve_h, solve, n, LU.dtype)
    else:
        est = norm1est(solve, solve_h, n, LU.dtype)
    rcond = 1.0 / (jnp.asarray(anorm) * est)
    return jnp.where(jnp.isfinite(rcond), rcond, 0.0)


def trcondest(T: TriangularMatrix, norm_type: Norm = Norm.One, opts=None):
    """Triangular condition estimate (reference: src/trcondest.cc via
    internal_norm1est.cc) — Hager/Higham on T^-1 with O(n^2) solves."""
    from ..internal.norm1est import norm1est

    anorm = _norm(norm_type, T)
    G = T._with(op=Op.NoTrans).to_global()
    n = G.shape[0]
    st_lower = T.uplo == Uplo.Lower
    unit = T.diag == Diag.Unit
    cplx = T.is_complex

    def tri(R, *, trans, conj):
        if conj and not trans:  # solve conj(G) X = R
            return jnp.conj(
                lax.linalg.triangular_solve(
                    G, jnp.conj(R), left_side=True, lower=st_lower,
                    unit_diagonal=unit,
                )
            )
        return lax.linalg.triangular_solve(
            G, R, left_side=True, lower=st_lower, unit_diagonal=unit,
            transpose_a=trans, conjugate_a=conj and cplx,
        )

    # op(T) X = R and op(T)^H X = R, expressed in storage (trans, conj)
    vt = T.op != Op.NoTrans
    vc = T.op == Op.ConjTrans

    def solve(R):
        return tri(R, trans=vt, conj=vc)

    def solve_h(R):
        return tri(R, trans=not vt, conj=cplx and not vc)

    if norm_type == Norm.Inf:
        # ||T^-1||_inf = ||T^-H||_1
        est = norm1est(solve_h, solve, n, T.dtype)
    else:
        est = norm1est(solve, solve_h, n, T.dtype)
    rcond = 1.0 / (jnp.asarray(anorm) * est)
    return jnp.where(jnp.isfinite(rcond), rcond, 0.0)
