"""Buffer-based bridge for the C API (reference: src/c_api/wrappers.cc —
the mutate-caller-buffers LAPACK ABI the C surface must honor).

Every function receives writable memoryviews of the caller's
column-major buffers (created by c_api/slate_tpu_c.c with
PyMemoryView_FromMemory), wraps them zero-copy as Fortran-ordered numpy
views, routes through compat.lapack, and writes results back IN PLACE.
Returns the LAPACK info code.
"""

from __future__ import annotations

import functools

import jax
import numpy as np

from . import lapack as lp


try:  # jax >= 0.6 top-level spelling; 0.4.x keeps it in experimental
    _enable_x64 = jax.enable_x64
except AttributeError:  # pragma: no cover - older spelling
    from jax.experimental import enable_x64 as _enable_x64


def _with_x64(fn):
    """Run a bridge call with x64 enabled, scoped to the call: the C ABI
    traffics in doubles, but a host Python process that dlopens the
    library must not have its global dtype promotion flipped."""

    @functools.wraps(fn)
    def wrapper(*a, **kw):
        with _enable_x64(True):
            return fn(*a, **kw)

    return wrapper


def _mat(mv, rows, cols, ld, dtype=np.float64):
    """Column-major (ld, cols) buffer -> writable (rows, cols) view."""
    buf = np.frombuffer(mv, dtype=dtype)
    return buf.reshape((int(ld), int(cols)), order="F")[: int(rows), :]


perm_to_swap_list = lp.perm_to_swap_list


@_with_x64
def dgesv(n, nrhs, a_mv, lda, ipiv_mv, b_mv, ldb) -> int:
    A = _mat(a_mv, n, n, lda)
    B = _mat(b_mv, n, nrhs, ldb)
    from ..drivers import lu as lu_drv
    from ..matrix.matrix import Matrix

    nb = lp._nb(n)
    Am = Matrix.from_global(np.ascontiguousarray(A), nb)
    LU, piv, info = lu_drv.getrf(Am)
    X = lu_drv.getrs(LU, piv, Matrix.from_global(np.ascontiguousarray(B), nb))
    A[:, :] = np.asarray(LU.to_global())
    B[:, :] = np.asarray(X.to_global())
    perm = np.asarray(piv.perm)
    ipiv = np.frombuffer(ipiv_mv, dtype=np.int64)
    ipiv[: int(n)] = perm_to_swap_list(perm, int(n))
    return int(info)


@_with_x64
def dposv(uplo, n, nrhs, a_mv, lda, b_mv, ldb) -> int:
    A = _mat(a_mv, n, n, lda)
    B = _mat(b_mv, n, nrhs, ldb)
    # factor explicitly so the caller's 'a' receives it (the LAPACK
    # dposv contract: a <- factor, b <- X)
    F, info = lp.potrf(chr(uplo), np.ascontiguousarray(A))
    if info != 0:
        return int(info)
    lo = chr(uplo).lower().startswith("l")
    Fm = np.asarray(F)
    nn = int(n)
    tri = np.tril_indices(nn) if lo else np.triu_indices(nn)
    A[tri] = Fm[tri]
    X = lp.trsm("l", chr(uplo), "n" if lo else "t", "n", 1.0, Fm,
                np.ascontiguousarray(B))
    X = lp.trsm("l", chr(uplo), "t" if lo else "n", "n", 1.0, Fm,
                np.asarray(X))
    B[:, :] = np.asarray(X)
    return 0


@_with_x64
def dgels(m, n, nrhs, a_mv, lda, b_mv, ldb) -> int:
    A = _mat(a_mv, m, n, lda)
    B = _mat(b_mv, max(m, n), nrhs, ldb)
    X = lp.gels(np.ascontiguousarray(A), np.ascontiguousarray(B[: int(m), :]))
    B[: int(n), :] = np.asarray(X)[: int(n), :]
    return 0


@_with_x64
def dgetrf(m, n, a_mv, lda, ipiv_mv) -> int:
    A = _mat(a_mv, m, n, lda)
    LU, perm, info = lp.getrf(np.ascontiguousarray(A))
    A[:, :] = LU
    k = min(int(m), int(n))
    ipiv = np.frombuffer(ipiv_mv, dtype=np.int64)
    ipiv[:k] = perm_to_swap_list(np.asarray(perm), k)
    return int(info)


@_with_x64
def dpotrf(uplo, n, a_mv, lda) -> int:
    A = _mat(a_mv, n, n, lda)
    F, info = lp.potrf(chr(uplo), np.ascontiguousarray(A))
    if info == 0:
        A[:, :] = F
    return int(info)


@_with_x64
def dgeqrf(m, n, a_mv, lda, tau_mv) -> int:
    A = _mat(a_mv, m, n, lda)
    fac, taus = lp.geqrf(np.ascontiguousarray(A))
    A[:, :] = np.asarray(fac)
    tau = np.frombuffer(tau_mv, dtype=np.float64)
    k = min(int(m), int(n))
    tau[:k] = np.asarray(taus)[:k]
    return 0


@_with_x64
def dsyev(jobz, uplo, n, a_mv, lda, w_mv) -> int:
    A = _mat(a_mv, n, n, lda)
    w, Z, info = lp.heev(chr(jobz), chr(uplo), np.ascontiguousarray(A))
    w = np.asarray(w)
    if info == 0 and not np.isfinite(w).all():
        info = 1  # honor the header's '>0 = numerical failure' channel
    wout = np.frombuffer(w_mv, dtype=np.float64)
    wout[: int(n)] = w
    if chr(jobz).lower() == "v" and Z is not None:
        Zv = np.asarray(Z)
        if not np.isfinite(Zv).all():
            info = info or 1
        A[:, :] = Zv
    return int(info)


@_with_x64
def dgesvd(jobu, jobvt, m, n, a_mv, lda, s_mv, u_mv, ldu, vt_mv, ldvt) -> int:
    A = _mat(a_mv, m, n, lda)
    k = min(int(m), int(n))
    want_u = chr(jobu).lower() != "n" and u_mv is not None
    want_vt = chr(jobvt).lower() != "n" and vt_mv is not None
    job = "s" if (want_u or want_vt) else "n"
    s, U, Vt = lp.gesvd(job if want_u else "n", job if want_vt else "n",
                        np.ascontiguousarray(A))
    np.frombuffer(s_mv, dtype=np.float64)[:k] = np.asarray(s)[:k]
    if want_u:
        Um = _mat(u_mv, m, k, ldu)
        Um[:, :] = np.asarray(U)[:, :k]
    if want_vt:
        Vm = _mat(vt_mv, k, n, ldvt)
        Vm[:, :] = np.asarray(Vt)[:k, :]
    return 0


@_with_x64
def dgemm(transa, transb, m, n, k, alpha, a_mv, lda, b_mv, ldb, beta,
          c_mv, ldc) -> int:
    ta, tb = chr(transa).lower(), chr(transb).lower()
    A = _mat(a_mv, m if ta == "n" else k, k if ta == "n" else m, lda)
    B = _mat(b_mv, k if tb == "n" else n, n if tb == "n" else k, ldb)
    C = _mat(c_mv, m, n, ldc)
    out = lp.gemm(ta, tb, alpha, np.ascontiguousarray(A),
                  np.ascontiguousarray(B), beta, np.ascontiguousarray(C))
    C[:, :] = np.asarray(out)
    return 0
