"""LAPACK compatibility surface (reference: lapack_api/lapack_slate.hh:
34-92, lapack_api/lapack_*.cc — the single-node `slate_dgetrf` etc. ABI).

Each entry point takes plain numpy arrays in LAPACK's calling shapes,
routes through the slate_tpu drivers on the default (single-chip) layout,
and returns results functionally (no aliasing surprises; the reference
shim mutates user buffers because LAPACK's ABI demands it — a Python
surface does not).  Tile size comes from SLATE_LAPACK_NB (reference env
singletons, lapack_slate.hh:60-78), default 256.

Typed aliases slate_sgemm / slate_dgemm / ... are generated for all
routines, mirroring the reference's macro-expanded symbols.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..enums import Diag, Norm, Op, Side, Uplo

_OP = {"n": Op.NoTrans, "t": Op.Trans, "c": Op.ConjTrans}
_UPLO = {"l": Uplo.Lower, "u": Uplo.Upper}
_SIDE = {"l": Side.Left, "r": Side.Right}
_DIAG = {"n": Diag.NonUnit, "u": Diag.Unit}


def perm_to_swap_list(perm, k: int) -> np.ndarray:
    """Net forward permutation -> LAPACK 1-based sequential swap list
    (the O(m) swap-target chase): under LAPACK swaps rows only move
    forward, and a row is evicted from position p exactly at step p (to
    the recorded target), so the position of row perm[i] is found by
    chasing recorded targets from its home.  Pure numpy — shared by the
    C bridge and compat.scalapack."""
    pl = np.asarray(perm).tolist()
    out = [0] * k
    for i in range(k):
        p_ = pl[i]
        while p_ < i:
            p_ = out[p_]
        out[i] = p_
    return np.asarray(out, dtype=np.int64) + 1


def _nb(n: int) -> int:
    return min(int(os.environ.get("SLATE_LAPACK_NB", 256)), max(int(n), 1))


def _op_apply(M, trans):
    from ..matrix.base import conj_transpose, transpose

    op = _OP[trans.lower()[0]]
    if op == Op.Trans:
        return transpose(M)
    if op == Op.ConjTrans:
        return conj_transpose(M)
    return M


def gemm(transa, transb, alpha, A: np.ndarray, B: np.ndarray, beta, C: np.ndarray):
    """C = alpha op(A) op(B) + beta C (reference: lapack_api/lapack_gemm.cc)."""
    from ..drivers import blas3
    from ..matrix.matrix import Matrix

    nb = _nb(max(C.shape))
    Am = _op_apply(Matrix.from_global(np.asarray(A), nb), transa)
    Bm = _op_apply(Matrix.from_global(np.asarray(B), nb), transb)
    Cm = Matrix.from_global(np.asarray(C), nb)
    return np.asarray(blas3.gemm(alpha, Am, Bm, beta, Cm).to_global())


def getrf(A: np.ndarray) -> Tuple[np.ndarray, np.ndarray, int]:
    """LU: returns (LU, perm, info) (reference: lapack_api/lapack_getrf.cc)."""
    from ..drivers import lu
    from ..matrix.matrix import Matrix

    Am = Matrix.from_global(np.asarray(A), _nb(min(A.shape)))
    LU, piv, info = lu.getrf(Am)
    return np.asarray(LU.to_global()), np.asarray(piv.perm), int(info)


def getrs(trans, LU: np.ndarray, perm: np.ndarray, B: np.ndarray) -> np.ndarray:
    from jax import lax

    from ..drivers import lu
    from ..matrix.matrix import Matrix
    from ..types import Pivots

    n = LU.shape[0]
    op = _OP[trans.lower()[0]]
    if op == Op.NoTrans:
        nb = _nb(n)
        LUm = Matrix.from_global(np.asarray(LU), nb)
        Bm = Matrix.from_global(np.asarray(B), nb)
        X = lu.getrs(LUm, Pivots(np.asarray(perm)), Bm)
        return np.asarray(X.to_global())
    # op(A) X = B with A = P^T L U:  A^T = U^T L^T P, so solve
    # U^T Y = B, L^T Z = Y, X = P^T Z (inverse permutation).
    import jax.numpy as jnp

    G = jnp.asarray(LU)
    conj = op == Op.ConjTrans and np.iscomplexobj(LU)
    Y = lax.linalg.triangular_solve(
        G, jnp.asarray(B), left_side=True, lower=False,
        transpose_a=True, conjugate_a=conj,
    )
    Z = lax.linalg.triangular_solve(
        G, Y, left_side=True, lower=True, unit_diagonal=True,
        transpose_a=True, conjugate_a=conj,
    )
    p = np.asarray(perm)[:n]
    inv = np.empty_like(p)
    inv[p] = np.arange(n, dtype=p.dtype)
    return np.asarray(Z)[inv]


def gesv(A: np.ndarray, B: np.ndarray) -> Tuple[np.ndarray, int]:
    """Solve AX=B; returns (X, info)."""
    from ..drivers import lu
    from ..matrix.matrix import Matrix

    nb = _nb(A.shape[0])
    X, LU, piv, info = lu.gesv(
        Matrix.from_global(np.asarray(A), nb), Matrix.from_global(np.asarray(B), nb)
    )
    return np.asarray(X.to_global()), int(info)


def potrf(uplo, A: np.ndarray) -> Tuple[np.ndarray, int]:
    from ..drivers import chol
    from ..matrix.matrix import HermitianMatrix

    up = _UPLO[uplo.lower()[0]]
    Am = HermitianMatrix.from_global(np.asarray(A), _nb(A.shape[0]), uplo=up)
    L, info = chol.potrf(Am)
    Lg = np.asarray(L.to_global())
    return (np.tril(Lg) if up == Uplo.Lower else np.triu(Lg)), int(info)


def posv(uplo, A: np.ndarray, B: np.ndarray) -> Tuple[np.ndarray, int]:
    from ..drivers import chol
    from ..matrix.matrix import HermitianMatrix, Matrix

    up = _UPLO[uplo.lower()[0]]
    nb = _nb(A.shape[0])
    X, L, info = chol.posv(
        HermitianMatrix.from_global(np.asarray(A), nb, uplo=up),
        Matrix.from_global(np.asarray(B), nb),
    )
    return np.asarray(X.to_global()), int(info)


def trsm(side, uplo, transa, diag, alpha, A: np.ndarray, B: np.ndarray) -> np.ndarray:
    from ..drivers import blas3
    from ..matrix.matrix import Matrix, TriangularMatrix

    nb = _nb(A.shape[0])
    Am = TriangularMatrix.from_global(
        np.asarray(A), nb, uplo=_UPLO[uplo.lower()[0]], diag=_DIAG[diag.lower()[0]]
    )
    Am = _op_apply(Am, transa)
    Bm = Matrix.from_global(np.asarray(B), nb)
    return np.asarray(blas3.trsm(_SIDE[side.lower()[0]], alpha, Am, Bm).to_global())


def geqrf(A: np.ndarray):
    """Returns (QR-packed, T-factors) (reference: lapack_api/lapack_geqrf.cc)."""
    from ..drivers import qr
    from ..matrix.matrix import Matrix

    fac, T = qr.geqrf(Matrix.from_global(np.asarray(A), _nb(min(A.shape))))
    return np.asarray(fac.to_global()), T


def gels(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    from ..drivers import qr
    from ..matrix.matrix import Matrix

    nb = _nb(min(A.shape))
    X = qr.gels(Matrix.from_global(np.asarray(A), nb),
                Matrix.from_global(np.asarray(B), nb))
    return np.asarray(X.to_global())


def heev(jobz, uplo, A: np.ndarray):
    """Returns (w, Z or None, info) (reference: lapack_api/lapack_heev.cc)."""
    from ..drivers import eig
    from ..matrix.matrix import HermitianMatrix

    Am = HermitianMatrix.from_global(
        np.asarray(A), _nb(A.shape[0]), uplo=_UPLO[uplo.lower()[0]]
    )
    vectors = jobz.lower().startswith("v")
    w, Z = eig.heev(Am, vectors=vectors)
    return np.asarray(w), (np.asarray(Z.to_global()) if Z is not None else None), 0


def syev(jobz, uplo, A):
    return heev(jobz, uplo, A)


def gesvd(jobu, jobvt, A: np.ndarray):
    """Returns (s, U or None, VH or None) (reference: lapack_api svd)."""
    from ..drivers import svd as svd_mod
    from ..matrix.matrix import Matrix

    want_u = jobu.lower().startswith(("a", "s"))
    want_vt = jobvt.lower().startswith(("a", "s"))
    s, U, Vh = svd_mod.svd(
        Matrix.from_global(np.asarray(A), _nb(min(A.shape))),
        vectors=want_u or want_vt,
    )
    return (
        np.asarray(s),
        np.asarray(U.to_global()) if (want_u and U is not None) else None,
        np.asarray(Vh.to_global()) if (want_vt and Vh is not None) else None,
    )


def lange(norm, A: np.ndarray) -> float:
    from ..drivers import aux
    from ..matrix.matrix import Matrix

    Am = Matrix.from_global(np.asarray(A), _nb(max(A.shape)))
    nt = {"m": Norm.Max, "1": Norm.One, "o": Norm.One, "i": Norm.Inf,
          "f": Norm.Fro, "e": Norm.Fro}[norm.lower()[0]]
    return float(aux.norm(nt, Am))


def _typed(name: str, fn):
    """slate_sgemm / slate_dgemm / ... aliases (the reference's generated
    lapack_api symbol set, lapack_slate.hh:34-92)."""

    def make(tc):
        def wrapper(*args, **kw):
            return fn(*args, **kw)

        wrapper.__name__ = f"slate_{tc}{name}"
        wrapper.__doc__ = f"Typed LAPACK shim slate_{tc}{name} -> {fn.__name__}."
        return wrapper

    return {f"slate_{tc}{name}": make(tc) for tc in "sdcz"}


_g = globals()
for _name, _fn in [
    ("gemm", gemm), ("getrf", getrf), ("getrs", getrs), ("gesv", gesv),
    ("potrf", potrf), ("posv", posv), ("trsm", trsm), ("geqrf", geqrf),
    ("gels", gels), ("heev", heev), ("gesvd", gesvd), ("lange", lange),
]:
    _g.update(_typed(_name, _fn))
