"""ScaLAPACK compatibility surface (reference: scalapack_api/
scalapack_slate.hh:144-372, scalapack_gemm.cc:24-148, scalapack_*.cc).

The reference's shim runs inside each MPI rank: it reads the BLACS grid
with Cblacs_gridinfo, wraps the rank's local block-cyclic buffer zero-copy
via Matrix::fromScaLAPACK, and calls SLATE.  On TPU there is one host
process driving the mesh, so the shim ingests *all* per-process local
buffers (or one replicated global array), assembles the matrix onto the
slate_tpu block-cyclic layout, runs the driver, and scatters results back
into ScaLAPACK-layout buffers:

    grid = BlacsGrid(p=2, q=2)
    desc = descinit(m, n, mb, nb, grid)
    locs = to_scalapack(desc, A_global)        # dict {(pr,pc): buffer}
    info = pdpotrf("L", n, locs, desc)          # in-place, like ScaLAPACK

Index math (numroc / l2g maps) follows the ScaLAPACK TOOLS conventions so
buffers round-trip bit-exactly with real ScaLAPACK layouts.  Env
configuration mirrors the reference shim: SLATE_SCALAPACK_VERBOSE and
SLATE_SCALAPACK_NB (scalapack_slate.hh:325, :144-372).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from ..enums import Diag, Norm, Op, Side, Uplo
from ..exceptions import DimensionError, slate_assert

_TYPE_CHAR = {"s": np.float32, "d": np.float64, "c": np.complex64, "z": np.complex128}


def _verbose() -> bool:
    return os.environ.get("SLATE_SCALAPACK_VERBOSE", "0") not in ("", "0")


@dataclass(frozen=True)
class BlacsGrid:
    """A p x q BLACS-style process grid (reference: Cblacs_gridinfo use in
    scalapack_gemm.cc:36-44).  Row-major process numbering by default,
    matching BLACS 'R' ordering."""

    p: int
    q: int

    @property
    def size(self) -> int:
        return self.p * self.q


@dataclass(frozen=True)
class Desc:
    """ScaLAPACK array descriptor (DESC_) — dtype tag omitted; the numpy
    buffers carry their dtype."""

    m: int
    n: int
    mb: int
    nb: int
    rsrc: int
    csrc: int
    grid: BlacsGrid

    def __post_init__(self):
        slate_assert(self.rsrc == 0 and self.csrc == 0, "rsrc/csrc != 0 unsupported")


def descinit(m: int, n: int, mb: int, nb: int, grid: BlacsGrid) -> Desc:
    """descinit_ analogue (rsrc = csrc = 0)."""
    return Desc(m, n, mb, nb, 0, 0, grid)


def numroc(n: int, nb: int, iproc: int, isrc: int, nprocs: int) -> int:
    """Number of rows/cols of a distributed array owned by process iproc
    (ScaLAPACK TOOLS/numroc.f semantics)."""
    mydist = (nprocs + iproc - isrc) % nprocs
    nblocks = n // nb
    num = (nblocks // nprocs) * nb
    extrablks = nblocks % nprocs
    if mydist < extrablks:
        num += nb
    elif mydist == extrablks:
        num += n % nb
    return num


def _local_rows(desc: Desc, pr: int) -> int:
    return numroc(desc.m, desc.mb, pr, desc.rsrc, desc.grid.p)


def _local_cols(desc: Desc, pc: int) -> int:
    return numroc(desc.n, desc.nb, pc, desc.csrc, desc.grid.q)


def _global_indices(n: int, nb: int, iproc: int, nprocs: int) -> np.ndarray:
    """Global indices (0-based) of the local rows/cols owned by iproc, in
    local storage order (ScaLAPACK INDXL2G)."""
    loc = numroc(n, nb, iproc, 0, nprocs)
    lidx = np.arange(loc)
    lblk = lidx // nb
    return (lblk * nprocs + iproc) * nb + lidx % nb


def alloc_locals(desc: Desc, dtype) -> Dict[Tuple[int, int], np.ndarray]:
    """Allocate zeroed local buffers for every grid process (column-major,
    shape (lld, nloc) like ScaLAPACK's lld x locc storage)."""
    out = {}
    for pr in range(desc.grid.p):
        for pc in range(desc.grid.q):
            out[(pr, pc)] = np.zeros(
                (_local_rows(desc, pr), _local_cols(desc, pc)), dtype=dtype, order="F"
            )
    return out


def to_scalapack(desc: Desc, A: np.ndarray) -> Dict[Tuple[int, int], np.ndarray]:
    """Scatter a global (m, n) array into per-process ScaLAPACK buffers."""
    if A.shape != (desc.m, desc.n):
        raise DimensionError(f"expected {(desc.m, desc.n)}, got {A.shape}")
    out = {}
    for pr in range(desc.grid.p):
        gi = _global_indices(desc.m, desc.mb, pr, desc.grid.p)
        for pc in range(desc.grid.q):
            gj = _global_indices(desc.n, desc.nb, pc, desc.grid.q)
            out[(pr, pc)] = np.asfortranarray(A[np.ix_(gi, gj)])
    return out


def from_scalapack(
    desc: Desc, locals_: Dict[Tuple[int, int], np.ndarray]
) -> np.ndarray:
    """Assemble per-process ScaLAPACK buffers into the global array
    (Matrix::fromScaLAPACK semantics, reference Matrix.hh:73-99)."""
    dtype = next(iter(locals_.values())).dtype
    A = np.zeros((desc.m, desc.n), dtype=dtype)
    for pr in range(desc.grid.p):
        gi = _global_indices(desc.m, desc.mb, pr, desc.grid.p)
        for pc in range(desc.grid.q):
            gj = _global_indices(desc.n, desc.nb, pc, desc.grid.q)
            buf = locals_[(pr, pc)]
            slate_assert(
                buf.shape == (len(gi), len(gj)),
                f"local buffer {(pr, pc)} shape {buf.shape} != {(len(gi), len(gj))}",
            )
            A[np.ix_(gi, gj)] = buf
    return A


def _scatter_back(desc, locals_, A):
    new = to_scalapack(desc, A)
    for k, buf in new.items():
        locals_[k][...] = buf


def _nb_env(nb: int) -> int:
    return int(os.environ.get("SLATE_SCALAPACK_NB", nb))


_OP = {"n": Op.NoTrans, "t": Op.Trans, "c": Op.ConjTrans}
_UPLO = {"l": Uplo.Lower, "u": Uplo.Upper}
_SIDE = {"l": Side.Left, "r": Side.Right}
_DIAG = {"n": Diag.NonUnit, "u": Diag.Unit}


def pgemm(transa, transb, m, n, k, alpha, a, desca, b, descb, beta, c, descc):
    """p?gemm: C = alpha op(A) op(B) + beta C (reference:
    scalapack_api/scalapack_gemm.cc:24-148)."""
    from ..drivers import blas3
    from ..matrix.base import conj_transpose, transpose
    from ..matrix.matrix import Matrix

    opa0 = transa.lower()[0]
    am, ak = (desca.m, desca.n) if opa0 == "n" else (desca.n, desca.m)
    bk, bn = (descb.m, descb.n) if transb.lower()[0] == "n" else (descb.n, descb.m)
    slate_assert(
        (m, n, k) == (descc.m, descc.n, ak) and (am, bk, bn) == (m, k, n),
        "pgemm dims must match the descriptors (submatrix ops unsupported)",
    )
    A = from_scalapack(desca, a)
    B = from_scalapack(descb, b)
    C = from_scalapack(descc, c)
    opa = _OP[transa.lower()[0]]
    opb = _OP[transb.lower()[0]]
    Am = Matrix.from_global(A, desca.mb, desca.nb)
    Bm = Matrix.from_global(B, descb.mb, descb.nb)
    Cm = Matrix.from_global(C, descc.mb, descc.nb)
    if opa == Op.Trans:
        Am = transpose(Am)
    elif opa == Op.ConjTrans:
        Am = conj_transpose(Am)
    if opb == Op.Trans:
        Bm = transpose(Bm)
    elif opb == Op.ConjTrans:
        Bm = conj_transpose(Bm)
    out = blas3.gemm(alpha, Am, Bm, beta, Cm)
    _scatter_back(descc, c, np.asarray(out.to_global()))
    return 0


def ppotrf(uplo, n, a, desca) -> int:
    """p?potrf: in-place Cholesky of the distributed buffers (reference:
    scalapack_api/scalapack_potrf.cc)."""
    from ..drivers import chol
    from ..matrix.matrix import HermitianMatrix

    slate_assert(n == desca.m == desca.n, "ppotrf n must match the descriptor")
    A = from_scalapack(desca, a)
    up = _UPLO[uplo.lower()[0]]
    Am = HermitianMatrix.from_global(A, _nb_env(desca.nb), uplo=up)
    L, info = chol.potrf(Am)
    Lg = np.asarray(L.to_global())
    tri = np.tril(Lg) if up == Uplo.Lower else np.triu(Lg)
    keep = np.triu(A, 1) if up == Uplo.Lower else np.tril(A, -1)
    _scatter_back(desca, a, tri + keep)
    return int(info)


def pgetrf(m, n, a, desca, ipiv=None) -> Tuple[np.ndarray, int]:
    """p?getrf: in-place LU; returns (perm, info).  slate_tpu records the
    net forward permutation (types.Pivots), which is what p?getrs
    consumes here.  A caller-supplied ipiv buffer is filled with the
    LAPACK/ScaLAPACK 1-based swap list (row i swapped with ipiv[i]-1),
    reconstructed from the net permutation, so the buffer stays valid if
    handed to foreign LAPACK-convention code."""
    from ..drivers import lu
    from ..matrix.matrix import Matrix

    slate_assert((m, n) == (desca.m, desca.n), "pgetrf dims must match the descriptor")
    A = from_scalapack(desca, a)
    Am = Matrix.from_global(A, desca.mb, desca.nb)
    LU, piv, info = lu.getrf(Am)
    _scatter_back(desca, a, np.asarray(LU.to_global()))
    perm = np.asarray(piv.perm)
    if ipiv is not None:
        # net forward perm -> LAPACK 1-based sequential swap list via
        # the O(m) swap-target chase (shared with the C ABI bridge)
        from .lapack import perm_to_swap_list

        k = min(len(ipiv), len(perm))
        ipiv[:k] = perm_to_swap_list(perm, k).astype(ipiv.dtype)
    return perm, int(info)


def pgesv(n, nrhs, a, desca, b, descb) -> int:
    """p?gesv: solve AX=B in place (B <- X) (reference:
    scalapack_api/scalapack_gesv.cc)."""
    from ..drivers import lu
    from ..matrix.matrix import Matrix

    slate_assert(
        n == desca.m == desca.n and (n, nrhs) == (descb.m, descb.n),
        "pgesv dims must match the descriptors",
    )
    A = from_scalapack(desca, a)
    B = from_scalapack(descb, b)
    Am = Matrix.from_global(A, desca.mb, desca.nb)
    Bm = Matrix.from_global(B, descb.mb, descb.nb)
    X, LU, piv, info = lu.gesv(Am, Bm)
    _scatter_back(desca, a, np.asarray(LU.to_global()))
    _scatter_back(descb, b, np.asarray(X.to_global()))
    return int(info)


def pposv(uplo, n, nrhs, a, desca, b, descb) -> int:
    from ..drivers import chol
    from ..matrix.matrix import HermitianMatrix, Matrix

    slate_assert(
        n == desca.m == desca.n and (n, nrhs) == (descb.m, descb.n),
        "pposv dims must match the descriptors",
    )
    A = from_scalapack(desca, a)
    B = from_scalapack(descb, b)
    up = _UPLO[uplo.lower()[0]]
    Am = HermitianMatrix.from_global(A, _nb_env(desca.nb), uplo=up)
    Bm = Matrix.from_global(B, descb.mb, descb.nb)
    X, L, info = chol.posv(Am, Bm)
    _scatter_back(descb, b, np.asarray(X.to_global()))
    Lg = np.asarray(L.to_global())
    tri = np.tril(Lg) if up == Uplo.Lower else np.triu(Lg)
    keep = np.triu(A, 1) if up == Uplo.Lower else np.tril(A, -1)
    _scatter_back(desca, a, tri + keep)
    return int(info)


def pgeqrf(m, n, a, desca):
    """p?geqrf: in-place QR; returns the TriangularFactors (the TPU
    analogue of ScaLAPACK's tau array)."""
    from ..drivers import qr
    from ..matrix.matrix import Matrix

    slate_assert((m, n) == (desca.m, desca.n), "pgeqrf dims must match the descriptor")
    A = from_scalapack(desca, a)
    Am = Matrix.from_global(A, desca.mb, desca.nb)
    fac, T = qr.geqrf(Am)
    _scatter_back(desca, a, np.asarray(fac.to_global()))
    return T, 0


def ptrsm(side, uplo, transa, diag, m, n, alpha, a, desca, b, descb) -> int:
    from ..drivers import blas3
    from ..matrix.base import conj_transpose, transpose
    from ..matrix.matrix import Matrix, TriangularMatrix

    slate_assert(
        (m, n) == (descb.m, descb.n) and desca.m == desca.n,
        "ptrsm dims must match the descriptors",
    )
    A = from_scalapack(desca, a)
    B = from_scalapack(descb, b)
    up = _UPLO[uplo.lower()[0]]
    Am = TriangularMatrix.from_global(
        A, _nb_env(desca.nb), uplo=up, diag=_DIAG[diag.lower()[0]]
    )
    op = _OP[transa.lower()[0]]
    if op == Op.Trans:
        Am = transpose(Am)
    elif op == Op.ConjTrans:
        Am = conj_transpose(Am)
    Bm = Matrix.from_global(B, descb.mb, descb.nb)
    X = blas3.trsm(_SIDE[side.lower()[0]], alpha, Am, Bm)
    _scatter_back(descb, b, np.asarray(X.to_global()))
    return 0


def plange(norm, m, n, a, desca) -> float:
    from ..drivers import aux
    from ..matrix.matrix import Matrix

    slate_assert((m, n) == (desca.m, desca.n), "plange dims must match the descriptor")
    A = from_scalapack(desca, a)
    Am = Matrix.from_global(A, desca.mb, desca.nb)
    nt = {"m": Norm.Max, "1": Norm.One, "o": Norm.One, "i": Norm.Inf,
          "f": Norm.Fro, "e": Norm.Fro}[norm.lower()[0]]
    return float(aux.norm(nt, Am))


def _typed(prefix: str, fn):
    """Generate the s/d/c/z-typed ScaLAPACK entry points (reference: the
    SLATE_PDGEMM etc. macro expansions in scalapack_gemm.cc:24-108)."""

    def make(tc):
        def wrapper(*args, **kw):
            if _verbose():
                print(f"slate_tpu compat: p{tc}{prefix}")
            return fn(*args, **kw)

        wrapper.__name__ = f"p{tc}{prefix}"
        wrapper.__doc__ = f"Typed ScaLAPACK shim p{tc}{prefix} -> {fn.__name__}."
        return wrapper

    return {f"p{tc}{prefix}": make(tc) for tc in "sdcz"}


_g = globals()
for _name, _fn in [
    ("gemm", pgemm), ("potrf", ppotrf), ("getrf", pgetrf), ("gesv", pgesv),
    ("posv", pposv), ("geqrf", pgeqrf), ("trsm", ptrsm), ("lange", plange),
]:
    _g.update(_typed(_name, _fn))
