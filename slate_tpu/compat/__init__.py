"""Compatibility surfaces (reference: scalapack_api/, lapack_api/).

- compat.scalapack: BLACS grid + descriptor ingestion, p?gemm/p?potrf/
  p?getrf/p?gesv/p?posv/p?geqrf/p?trsm/p?lange over ScaLAPACK-layout
  per-process buffers.
- compat.lapack: slate_?gemm/... single-node LAPACK-style entry points
  over plain numpy arrays.
"""

from . import lapack, scalapack  # noqa: F401
