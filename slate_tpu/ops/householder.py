"""Householder compact-WY utilities (reference: the T factors of
geqrf/unmqr — slate's TriangularFactors Tlocal/Treduce, src/geqrf.cc:150-200,
internal_unmqr.cc; LAPACK larft/larfb semantics).

Q = H_0 H_1 ... H_{nb-1} = I - V T V^H with V unit-lower, T upper
triangular.  T is built from the identity

    T^{-1} = diag(1/tau) + strict_upper(V^H V)

(one small triangular inverse, MXU-friendly) instead of LAPACK's column
recurrence — mathematically identical, verified against the recurrence in
tests.  tau == 0 (no reflector) columns are handled by a large-diagonal
limit, zeroing the corresponding T row/column.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

# HIGHEST-precision matmul: the TPU f64 emulation default accumulates
# at ~f32 grade (shared convention with ops/chol_kernels.py et al.)
from ..internal.precision import hdot as _dot

try:  # fast path: XLA's geqrf primitive (private module path in jax 0.9)
    from jax._src.lax.linalg import geqrf as _geqrf_xla
except Exception:  # pragma: no cover
    _geqrf_xla = None


def geqrf(a: jnp.ndarray):
    """LAPACK-style QR: returns (a_factored, taus) with V unit-lower below
    the diagonal and R above.

    CPU keeps the vendor (LAPACK) kernel; on accelerators, large panels
    run the native three-level schedule (ops/qr_fast.py — the vendor
    geqrf lowering measures ~27 GF/s f64 on the chip, the same
    schedule-bound story as cholesky/LU)."""
    import jax

    m, n = a.shape
    if jax.default_backend() != "cpu" and m >= n and n >= 1024:
        from .qr_fast import geqrf_fast

        for nbf in (512, 256, 128):
            if n % nbf == 0:
                fac, taus = geqrf_fast(a, nbf)
                return fac, taus[: min(m, n)]
    if _geqrf_xla is not None:
        return _geqrf_xla(a)
    return geqrf_blocked(a)


def _larfg(alpha, xnorm_sq, dtype):
    """Reflector scalar generation (LAPACK larfg): returns (beta, tau,
    scale) with v = (alpha_vec) * scale, v[0] := 1 implicit."""
    complex_t = jnp.issubdtype(dtype, jnp.complexfloating)
    a_re = jnp.real(alpha)
    norm = jnp.sqrt(jnp.real(alpha * jnp.conj(alpha)) + xnorm_sq)
    beta = -jnp.sign(jnp.where(a_re == 0, 1.0, a_re)) * norm
    live = norm > 0
    beta = jnp.where(live, beta, a_re)
    if complex_t:
        tau = jnp.where(live, (beta - alpha) / beta, 0.0 + 0.0j)
    else:
        tau = jnp.where(live, (beta - alpha) / beta, 0.0)
    scale = jnp.where(live, 1.0 / jnp.where(alpha == beta, 1, alpha - beta), 0.0)
    return beta.astype(dtype), tau.astype(dtype), scale.astype(dtype)


def _geqrf_panel(P: jnp.ndarray):
    """Unblocked right-looking Householder QR of a panel (m x w)."""
    m, w = P.shape
    rows = jnp.arange(m)
    cols = jnp.arange(w)

    def step(j, carry):
        P, taus = carry
        x = P[:, j]
        below = rows > j
        alpha = P[j, j]
        xnorm_sq = jnp.sum(jnp.where(below, jnp.abs(x) ** 2, 0.0))
        beta, tau, scale = _larfg(alpha, xnorm_sq, P.dtype)
        v = jnp.where(below, x * scale, 0.0).at[j].set(1.0)
        # eliminate with H^H = I - conj(tau) v v^H (LAPACK zgeqr2 applies
        # H(i)^H, passing conj(tau) to zlarf)
        w_row = jnp.conj(v) @ P  # (w,)
        right = cols >= j
        upd = jnp.conj(tau) * v[:, None] * w_row[None, :]
        P = P - jnp.where(right[None, :], upd, 0.0)
        # store beta on the diagonal, v below it
        P = P.at[:, j].set(jnp.where(below, v, P[:, j]).at[j].set(beta))
        taus = taus.at[j].set(tau)
        return P, taus

    taus0 = jnp.zeros((w,), P.dtype)
    return lax.fori_loop(0, w, step, (P, taus0))


def geqrf_blocked(a: jnp.ndarray, nb: int = 128):
    """Blocked Householder QR (the reference's geqrf panel+larfb structure,
    src/geqrf.cc, entirely in XLA ops)."""
    m, n = a.shape
    taus = jnp.zeros((min(m, n),), a.dtype)
    kmax = min(m, n)
    for k0 in range(0, kmax, nb):
        w = min(nb, kmax - k0)
        panel = a[:, k0 : k0 + w]
        rows = jnp.arange(m)
        panel = jnp.where((rows >= k0)[:, None], panel, 0.0)
        pfac, ptaus = _geqrf_panel(
            jnp.roll(panel, -k0, axis=0)
        )
        pfac = jnp.roll(pfac, k0, axis=0)
        # merge: rows < k0 keep original (they belong to earlier R rows)
        merged = jnp.where((rows >= k0)[:, None], pfac, a[:, k0 : k0 + w])
        a = a.at[:, k0 : k0 + w].set(merged)
        taus = taus.at[k0 : k0 + w].set(ptaus)
        if k0 + w < n:
            V = materialize_v(merged, offset=k0)
            V = jnp.where((rows >= k0)[:, None], V, 0.0)
            T = larft(V, ptaus)
            C = a[:, k0 + w :]
            C = apply_block_reflector(V, T, C, trans=True)
            a = a.at[:, k0 + w :].set(C)
    return a, taus


def larft(V: jnp.ndarray, taus: jnp.ndarray) -> jnp.ndarray:
    """Build the nb x nb T factor from unit-lower V (m x nb) and taus.

    V must have the unit diagonal materialized (V[j, j] == 1, zeros above).

    taus may be shorter than V.shape[1] (XLA geqrf returns min(m, n) taus
    for a short panel with fewer rows than columns); missing reflectors are
    treated as absent (tau == 0), which zeroes their T rows/columns.
    """
    nb = V.shape[1]
    if taus.shape[0] < nb:
        taus = jnp.concatenate(
            [taus, jnp.zeros((nb - taus.shape[0],), taus.dtype)]
        )
    complex_t = jnp.issubdtype(V.dtype, jnp.complexfloating)
    VhV = _dot(jnp.conj(V).T if complex_t else V.T, V)
    U = jnp.triu(VhV, 1)
    big = jnp.asarray(1e30, V.dtype)
    d = jnp.where(taus != 0, 1.0 / jnp.where(taus == 0, 1, taus), big)
    M = U + jnp.diag(d.astype(V.dtype))
    T = lax.linalg.triangular_solve(
        M, jnp.eye(nb, dtype=V.dtype), left_side=True, lower=False
    )
    # exact zeros for absent reflectors
    live = (taus != 0)[None, :] & (taus != 0)[:, None]
    return jnp.where(live, T, jnp.zeros_like(T))


def materialize_v(panel: jnp.ndarray, offset: int = 0) -> jnp.ndarray:
    """Unit-lower V from a geqrf-factored panel (m x nb): zeros on/above
    the diagonal of the block starting at row `offset`, implicit ones."""
    m, nb = panel.shape
    rows = jnp.arange(m)[:, None]
    cols = jnp.arange(nb)[None, :]
    below = rows > (cols + offset)
    V = jnp.where(below, panel, jnp.zeros_like(panel))
    return V + jnp.where(rows == cols + offset, jnp.ones_like(panel), 0)


def apply_block_reflector(
    V: jnp.ndarray, T: jnp.ndarray, C: jnp.ndarray, trans: bool
) -> jnp.ndarray:
    """C <- (I - V T V^H) C (trans=False) or (I - V T^H V^H) C (True)
    — LAPACK larfb, left side.  HIGHEST precision: the TPU default f64
    emulation accumulates at ~f32 grade."""
    complex_t = jnp.issubdtype(V.dtype, jnp.complexfloating)
    Vh = jnp.conj(V).T if complex_t else V.T
    W = _dot(Vh, C)  # (nb, n)
    Tm = (jnp.conj(T).T if complex_t else T.T) if trans else T
    return C - _dot(V, _dot(Tm, W))
