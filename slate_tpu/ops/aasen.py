"""Aasen's LTL^H factorization with partial pivoting (host algorithm).

The reference's hetrf is the two-stage Aasen method (reference:
src/hetrf.cc — panel factor + band reduction with partial pivoting in
the panel sub-communicator; hetrs.cc solves through the L/T factors).
This module provides the pivoted-stability algorithm for the framework:
P A P^H = L T L^H with L unit lower triangular (first column e_0), T
Hermitian TRIDIAGONAL, and rows pivoted by |column residual| — Aasen's
1971 recurrences, evaluated column-at-a-time with the O(n^2)-per-column
work in BLAS-2 calls.

Like the reference's, this is a host-driven factorization (the driver's
pivot-free LDL^H + breakdown detection remains the accelerator fast
path; hetrf falls back here when it breaks down).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def aasen_ltl(A: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                      np.ndarray, int]:
    """Factor P A P^H = L T L^H (A Hermitian, lower data referenced).

    Returns (L, alpha, beta, perm, info): L unit lower with L[:, 0] =
    e_0; T = tridiag(conj(beta), alpha, beta) with real alpha; perm the
    pivot row order (A[perm][:, perm] = L T L^H); info = 0 (the
    factorization cannot break down — a zero pivot just decouples)."""
    A = np.array(A)  # working copy, both triangles used
    A = np.tril(A) + np.tril(A, -1).conj().T
    n = A.shape[0]
    dt = A.dtype
    cplx = np.iscomplexobj(A)
    L = np.eye(n, dtype=dt)
    alpha = np.zeros(n, dtype=np.float64)
    beta = np.zeros(max(n - 1, 0), dtype=dt)
    perm = np.arange(n)

    def swap(i, j, ncols):
        """Exchange rows/cols i, j of A and rows i, j of L's COMPUTED
        columns (:ncols) — the identity tail of L must stay put."""
        if i == j:
            return
        A[[i, j], :] = A[[j, i], :]
        A[:, [i, j]] = A[:, [j, i]]
        L[[i, j], :ncols] = L[[j, i], :ncols]
        perm[[i, j]] = perm[[j, i]]

    if n == 0:
        return L, alpha, beta, perm, 0
    alpha[0] = A[0, 0].real
    if n == 1:
        return L, alpha, beta, perm, 0

    # column 0: A[1:, 0] = beta_0 * L[1:, 1]
    v = A[1:, 0].copy()
    r = int(np.argmax(np.abs(v)))
    swap(1, 1 + r, 1)
    v = A[1:, 0].copy()
    beta[0] = v[0]
    if v[0] != 0:
        L[2:, 1] = v[1:] / v[0]

    for j in range(1, n):
        lj = np.conj(L[j, : j + 1])  # row j of L, conjugated
        # h[k] = (T L^H)[k, j] for k < j: the three T terms per row
        h = np.zeros(j, dtype=dt)
        ks = np.arange(j)
        h += alpha[ks].astype(dt) * lj[ks]
        if j >= 1:
            h[1:] += beta[: j - 1] * lj[: j - 1]  # T[k, k-1] l[k-1]
            h[: j] += np.conj(beta[:j]) * lj[1 : j + 1]  # T[k, k+1] l[k+1]
        w = A[j:, j] - L[j:, :j] @ h
        # w[0] = alpha_j + beta_{j-1} conj(L[j, j-1])
        alpha[j] = (w[0] - beta[j - 1] * lj[j - 1]).real
        if j + 1 < n:
            # u = L[j+1:, j+1] beta_j
            u = w[1:] - L[j + 1 :, j] * w[0]
            r = int(np.argmax(np.abs(u)))
            if r != 0:
                swap(j + 1, j + 1 + r, j + 1)
                u[[0, r]] = u[[r, 0]]
            beta[j] = u[0]
            if u[0] != 0:
                L[j + 2 :, j + 1] = u[1:] / u[0]
            else:
                L[j + 2 :, j + 1] = 0.0
    return L, alpha, beta, perm, 0


def tridiag_solve_piv(alpha: np.ndarray, beta: np.ndarray,
                      B: np.ndarray) -> np.ndarray:
    """Solve T X = B for Hermitian tridiagonal T = tridiag(conj(beta),
    alpha, beta) with partial pivoting (dgtsv-style; fill-in limited to
    a second superdiagonal)."""
    n = alpha.shape[0]
    B = np.array(B, dtype=np.result_type(alpha, beta, B))
    # beta is the SUBdiagonal (T[k+1, k], aasen_ltl's convention); the
    # Hermitian superdiagonal is its conjugate
    dl = beta.astype(B.dtype).copy() if n > 1 else np.zeros(0, B.dtype)
    d = alpha.astype(B.dtype).copy()
    du = np.conj(beta).astype(B.dtype) if n > 1 else np.zeros(0, B.dtype)
    du2 = np.zeros(max(n - 2, 0), B.dtype)
    for k in range(n - 1):
        if abs(dl[k]) > abs(d[k]):
            # swap rows k, k+1
            d[k], dl[k] = dl[k], d[k]
            du_k = du[k]
            du[k] = d[k + 1]
            d[k + 1] = du_k
            if k + 1 < n - 1:
                du2[k] = du[k + 1]
                du[k + 1] = 0.0
            B[[k, k + 1]] = B[[k + 1, k]]
        piv = d[k] if d[k] != 0 else np.finfo(np.float64).tiny
        m = dl[k] / piv
        d[k + 1] = d[k + 1] - m * du[k]
        if k + 1 < n - 1:
            du[k + 1] = du[k + 1] - m * du2[k]
        B[k + 1] = B[k + 1] - m * B[k]
    # back substitution with two superdiagonals
    X = np.zeros_like(B)
    for k in range(n - 1, -1, -1):
        acc = B[k].copy()
        if k + 1 < n:
            acc -= du[k] * X[k + 1]
        if k + 2 < n:
            acc -= du2[k] * X[k + 2]
        piv = d[k] if d[k] != 0 else np.finfo(np.float64).tiny
        X[k] = acc / piv
    return X


def aasen_solve(L: np.ndarray, alpha: np.ndarray, beta: np.ndarray,
                perm: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Solve A X = B from the Aasen factors of P A P^H."""
    Bp = B[perm]
    Y = _unit_lower_solve(L, Bp)
    Z = tridiag_solve_piv(alpha, beta, Y)
    W = _unit_lower_solve_h(L, Z)
    X = np.zeros_like(W)
    X[perm] = W
    return X


def _unit_lower_solve(L, B):
    n = L.shape[0]
    X = np.array(B, dtype=np.result_type(L, B))
    for k in range(n):
        X[k] -= L[k, :k] @ X[:k]
    return X


def _unit_lower_solve_h(L, B):
    """Solve L^H X = B."""
    n = L.shape[0]
    X = np.array(B, dtype=np.result_type(L, B))
    Lh = np.conj(L).T
    for k in range(n - 1, -1, -1):
        X[k] -= Lh[k, k + 1 :] @ X[k + 1 :]
    return X
