"""Pallas TPU kernels for the device tile-kernel layer.

TPU-native re-implementations of the reference's CUDA device kernels
(reference: src/cuda/device_genorm.cu, device_transpose.cu,
device_geadd.cu, device_gescale.cu, src/internal/internal_rbt_generate +
gerbt butterfly kernels; interface include/slate/internal/device.hh:92-282).

Most elementwise tile ops fuse perfectly under plain XLA (see
internal/tile_ops.py) — Pallas is reserved for the patterns XLA schedules
poorly:

  * batched tile norms with per-tile reductions and a fro (scale, sumsq)
    update — one VMEM pass per tile instead of XLA's multi-kernel
    reduce chains (device_genorm.cu's per-block reductions);
  * the recursive butterfly (RBT) pair transform — strided pair access
    that XLA turns into gather/scatter, here a single VMEM pass;
  * batched tile transpose feeding MXU-unfriendly layouts.

Every kernel has a jnp reference implementation; `use_pallas()` gates on
the actual platform, and tests run the Pallas path in interpreter mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform not in ("cpu",)
    except Exception:  # pragma: no cover
        return False


# ---------------------------------------------------------------------------
# Batched tile norms (device_genorm.cu analogue)
# ---------------------------------------------------------------------------


def _norm_kernel(t_ref, out_ref, *, kind: str):
    """One grid step = one tile; writes the tile's norm statistic."""
    t = t_ref[...]
    a = jnp.abs(t)
    if kind == "max":
        out_ref[0] = jnp.max(a)
    elif kind == "fro_sumsq":
        out_ref[0] = jnp.sum(a * a)
    elif kind == "one":  # max column sum within the tile -> still needs
        # cross-tile accumulation; emit per-column sums
        out_ref[...] = jnp.sum(a, axis=0)
    elif kind == "inf":
        out_ref[...] = jnp.sum(a, axis=1)


def pallas_norm_ok(T, kind: str) -> bool:
    """Mosaic lowering constraints for the norm kernels: 32-bit real
    dtype (the TPU VPU has no f64 vectors) and (8, 128)-divisible tile
    dims.  This toolchain also aborts on *gridded* pallas_call, so the
    kernels run grid-free over VMEM-sized chunks under lax.map."""
    if T.dtype != jnp.float32:
        return False
    N, mb, nb = T.shape
    if mb % 8 != 0 or nb % 128 != 0:
        return False
    if kind == "inf" and mb % 128 != 0:
        return False
    return True


def tile_norms_pallas(T: jnp.ndarray, kind: str, interpret: bool = False):
    """Per-tile norm statistics over a (N, mb, nb) tile stack.

    kind: 'max' -> (N,); 'fro_sumsq' -> (N,) sum of squares;
    'one' -> (N, nb) per-column sums; 'inf' -> (N, mb) per-row sums.

    One grid-free pallas_call per ~2 MiB chunk of tiles (mapped with
    lax.map): each invocation reduces its whole chunk in VMEM in a
    single pass — the analogue of device_genorm.cu's one-block-per-tile
    reductions.  Scalar statistics broadcast across the output lane dim
    and are sliced outside.
    """
    from jax import lax

    N, mb, nb = T.shape
    CH = max(1, min(64, (1 << 21) // max(mb * nb * 4, 1)))
    Np = -(-N // CH) * CH
    if Np != N:
        T = jnp.pad(T, ((0, Np - N), (0, 0), (0, 0)))
    real = T.dtype
    out_cols = mb if kind == "inf" else nb

    def kernel(t_ref, o_ref):
        a = jnp.abs(t_ref[...]).reshape(CH, mb, nb)
        if kind == "max":
            s = jnp.max(jnp.max(a, axis=2), axis=1)
            o_ref[...] = jnp.broadcast_to(s[:, None], (CH, out_cols))
        elif kind == "fro_sumsq":
            s = jnp.sum(jnp.sum(a * a, axis=2), axis=1)
            o_ref[...] = jnp.broadcast_to(s[:, None], (CH, out_cols))
        elif kind == "one":
            o_ref[...] = jnp.sum(a, axis=1)
        else:
            o_ref[...] = jnp.sum(a, axis=2)

    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((CH, out_cols), real),
        interpret=interpret,
    )
    chunks = T.reshape(Np // CH, CH * mb, nb)
    out = lax.map(call, chunks).reshape(Np, out_cols)[:N]
    if kind in ("max", "fro_sumsq"):
        return out[:, 0]
    return out


def tile_norms_reference(T: jnp.ndarray, kind: str):
    """jnp twin of tile_norms_pallas."""
    a = jnp.abs(T)
    if kind == "max":
        return a.max(axis=(1, 2))
    if kind == "fro_sumsq":
        return (a * a).sum(axis=(1, 2))
    if kind == "one":
        return a.sum(axis=1)
    return a.sum(axis=2)


def tile_norms(T: jnp.ndarray, kind: str):
    """Dispatch: Pallas on TPU for Mosaic-compatible shapes/dtypes
    (f32, (8,128)-divisible tiles), jnp elsewhere."""
    if on_tpu() and _HAS_PLTPU and pallas_norm_ok(T, kind):
        return tile_norms_pallas(T, kind)
    return tile_norms_reference(T, kind)


# ---------------------------------------------------------------------------
# Batched tile transpose (device_transpose.cu analogue)
# ---------------------------------------------------------------------------


def tile_transpose_pallas(T: jnp.ndarray, conj: bool = False, interpret: bool = False):
    """(N, mb, nb) -> (N, nb, mb), per-tile (conj-)transpose."""
    N, mb, nb = T.shape

    def kernel(t_ref, out_ref):
        t = t_ref[0]
        if conj and jnp.issubdtype(t.dtype, jnp.complexfloating):
            t = jnp.conj(t)
        out_ref[0, :, :] = t.T

    return pl.pallas_call(
        kernel,
        grid=(N,),
        in_specs=[pl.BlockSpec((1, mb, nb), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, nb, mb), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((N, nb, mb), T.dtype),
        interpret=interpret,
    )(T)


# gridded pallas_call aborts this toolchain's compiler; XLA handles
# batched transposes well, so the Pallas transpose stays test-only
_PALLAS_TRANSPOSE_ENABLED = False


def tile_transpose(T: jnp.ndarray, conj: bool = False):
    if (
        _PALLAS_TRANSPOSE_ENABLED
        and on_tpu()
        and _HAS_PLTPU
        and not jnp.issubdtype(T.dtype, jnp.complexfloating)
    ):
        return tile_transpose_pallas(T, conj)
    out = T.transpose(0, 2, 1)
    if conj and jnp.issubdtype(T.dtype, jnp.complexfloating):
        out = jnp.conj(out)
    return out


# ---------------------------------------------------------------------------
# Butterfly (RBT) pair transform (gerbt kernel analogue)
# ---------------------------------------------------------------------------


def butterfly_level_pallas(
    X: jnp.ndarray, D1: jnp.ndarray, D2: jnp.ndarray, transpose: bool,
    interpret: bool = False,
):
    """One butterfly level over paired row blocks.

    X: (2h, w); D1, D2: (h,).  transpose=True:
        top = D1 x1 + D2 x2 ; bot = D1 x1 - D2 x2
    else:
        top = D1 (x1 + x2) ; bot = D2 (x1 - x2)
    (matches drivers/lu._apply_butterfly; all rows in one VMEM pass).
    """
    two_h, w = X.shape
    h = two_h // 2
    s = float(np.sqrt(0.5))  # python scalar: weak-typed, not a captured const

    def kernel(x_ref, d1_ref, d2_ref, out_ref):
        x1 = x_ref[:h, :]
        x2 = x_ref[h:, :]
        d1 = d1_ref[:][:, None]
        d2 = d2_ref[:][:, None]
        if transpose:
            top = d1 * x1 + d2 * x2
            bot = d1 * x1 - d2 * x2
        else:
            top = d1 * (x1 + x2)
            bot = d2 * (x1 - x2)
        out_ref[:h, :] = s * top
        out_ref[h:, :] = s * bot

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(X.shape, X.dtype),
        interpret=interpret,
    )(X, D1, D2)


def butterfly_level_reference(X, D1, D2, transpose: bool):
    h = X.shape[0] // 2
    s = np.sqrt(0.5)
    x1, x2 = X[:h], X[h:]
    d1, d2 = D1[:, None], D2[:, None]
    if transpose:
        return s * jnp.concatenate([d1 * x1 + d2 * x2, d1 * x1 - d2 * x2])
    return s * jnp.concatenate([d1 * (x1 + x2), d2 * (x1 - x2)])


def butterfly_level(X, D1, D2, transpose: bool):
    # Mosaic has no f64 vector support; 32-bit floats only on the chip
    if on_tpu() and _HAS_PLTPU and X.dtype == jnp.float32:
        return butterfly_level_pallas(X, D1, D2, transpose)
    return butterfly_level_reference(X, D1, D2, transpose)


# ---------------------------------------------------------------------------
# Fused masked geadd/scale (device_geadd.cu / device_gescale.cu analogue)
# ---------------------------------------------------------------------------


def tile_geadd_pallas(
    alpha, A: jnp.ndarray, beta, B: jnp.ndarray, interpret: bool = False
):
    """B = alpha A + beta B over a (N, mb, nb) stack, one VMEM pass."""
    N, mb, nb = A.shape

    def kernel(a_ref, b_ref, out_ref):
        out_ref[...] = alpha * a_ref[...] + beta * b_ref[...]

    return pl.pallas_call(
        kernel,
        grid=(N,),
        in_specs=[
            pl.BlockSpec((1, mb, nb), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, mb, nb), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, mb, nb), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(A.shape, B.dtype),
        interpret=interpret,
    )(A, B)
