"""Pallas panel kernels for the factorization schedules (the third
``Option.Schedule`` family, ``pallas``).

The recursive schedules in ops/chol_kernels.py, ops/lu_kernels.py and
ops/qr_fast.py bottom out in panel/small-tile base cases below
``nb_switch`` — exactly the layer the reference delegates to hand-tuned
device tile kernels and that Elmroth & Gustavson identify as the bound
on recursive factorizations.  This module re-implements those base
cases as fused Pallas kernels, following the norm/RBT/transpose pattern
of ops/pallas/kernels.py: every kernel is a single GRID-FREE
``pl.pallas_call`` (gridded pallas_call aborts this toolchain's
compiler — see kernels.py), has a jnp reference twin, and takes an
``interpret`` flag so the CPU CI runs the identical kernel bodies via
``pl.pallas_call(..., interpret=True)``.

Kernel families:

* ``chol_base``   — fused unblocked Cholesky of one diagonal block:
  in-register column loop (sqrt, scale, masked rank-1 trailing update)
  in one VMEM pass, replacing the ib-strip ``chol_unblocked``.
* ``panel_lu``    — fused unblocked partial-pivot LU of one (M, nb)
  panel with the in-register pivot search and act-masked eligibility of
  ``ops/lu_kernels.panel_lu`` (identical arithmetic, so the pivot
  order matches ``lax.linalg.lu`` exactly).
* ``larft``       — compact-WY T assembly for the QR panel base case:
  the Gram matrix V^H V, strict-upper extraction, and the
  diag(1/tau)-with-big-limit splice fused into one kernel building
  T^{-1}; the small (<= nb) triangular inverse stays on the vendor
  solve, the same convention as the recursive trsm's <= nb diagonal
  blocks.
* ``syrk_diag`` / ``gemm_sub`` — triangle-aware syrk pieces for the
  Cholesky trailing update: only diagonal nb-blocks pay the
  full-square gemm in-kernel (masked to the lower triangle in the same
  VMEM pass); off-diagonal blocks are fused multiply-subtract gemms.
* ``trsm_lower`` / ``trsm_upper`` — the solve-phase trsm pair behind
  the serve ``phase="solve"`` buckets (the factor cache's top-traffic
  hit family): blocked forward/backward substitution in one kernel,
  diagonal KB-blocks inverted by an exact Newton iteration (the
  residual is strictly-triangular nilpotent, so ceil(log2(KB)) steps
  reproduce the substitution result exactly in exact arithmetic).

Compiled (non-interpret) dispatch is gated like the norm kernels:
TPU platform + pltpu import + f32 + (8, 128)-aligned operands.  On any
other backend/dtype the SAME kernel body runs in interpret mode, which
lowers to plain XLA ops — this is how ``schedule="pallas"`` reaches
driver parity on CPU and how artifacts stay custom-call-free.

Toolchain caveats: besides the gridded-pallas_call abort, this jax
build's interpret mode cannot initialize COMPLEX pallas outputs
(``primitives.uninitialized_value`` only handles float/int), so the
``_run_kernel`` adapter below writes complex results as exact
real/imag pairs inside the kernel and recombines them outside —
lossless, and the compiled Mosaic path (f32-only) never sees it.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .kernels import _HAS_PLTPU, on_tpu

_HIGHEST = lax.Precision.HIGHEST


def _conj(x):
    return jnp.conj(x) if jnp.iscomplexobj(x) else x


def _mxu_dot(a, b):
    """In-kernel matmul at HIGHEST precision, accumulating at the
    operand dtype (the ops-layer ``hdot`` convention)."""
    return jnp.dot(a, b, precision=_HIGHEST)


def pallas_panel_ok(*arrays) -> bool:
    """Whether the compiled (non-interpret) Mosaic path supports these
    operands: f32 only (no f64/complex vector support), every 2-D dim
    (8, 128)-aligned — the same constraint set as pallas_norm_ok."""
    for a in arrays:
        if a.dtype != jnp.float32:
            return False
        if a.ndim != 2:
            return False
        if a.shape[0] % 8 != 0 or a.shape[1] % 128 != 0:
            return False
    return True


def _resolve_interpret(interpret: Optional[bool], *arrays) -> bool:
    """None = auto: compiled Mosaic only on TPU with eligible operands,
    interpret mode (plain XLA lowering) everywhere else."""
    if interpret is not None:
        return bool(interpret)
    return not (on_tpu() and _HAS_PLTPU and pallas_panel_ok(*arrays))


def _real_dtype(dt):
    return jnp.float32 if dt == jnp.dtype(jnp.complex64) else jnp.float64


def _run_kernel(body, out_shapes, args, interpret: bool):
    """Grid-free pallas_call adapter: ``body`` maps input VALUES to
    output VALUES; refs stay an implementation detail here.  Complex
    outputs are written as exact real/imag pairs (see the module
    docstring's toolchain caveat) and recombined outside the kernel."""
    single = not isinstance(out_shapes, (tuple, list))
    outs = [out_shapes] if single else list(out_shapes)
    expanded = []  # ShapeDtypeStructs handed to pallas_call
    plan = []  # per logical output: ("plain"|"cplx", first_slot, dtype)
    for o in outs:
        if jnp.issubdtype(o.dtype, jnp.complexfloating):
            rt = _real_dtype(o.dtype)
            plan.append(("cplx", len(expanded), o.dtype))
            expanded.append(jax.ShapeDtypeStruct(o.shape, rt))
            expanded.append(jax.ShapeDtypeStruct(o.shape, rt))
        else:
            plan.append(("plain", len(expanded), o.dtype))
            expanded.append(o)

    def kern(*refs):
        in_refs = refs[: len(args)]
        out_refs = refs[len(args):]
        vals = body(*[r[...] for r in in_refs])
        if not isinstance(vals, tuple):
            vals = (vals,)
        for (kind, i, _dt), v in zip(plan, vals):
            if kind == "cplx":
                out_refs[i][...] = jnp.real(v)
                out_refs[i + 1][...] = jnp.imag(v)
            else:
                out_refs[i][...] = v

    raw = pl.pallas_call(
        kern, out_shape=tuple(expanded), interpret=interpret
    )(*args)
    if not isinstance(raw, (tuple, list)):
        raw = (raw,)
    results = []
    for kind, i, dt in plan:
        if kind == "cplx":
            results.append(lax.complex(raw[i], raw[i + 1]).astype(dt))
        else:
            results.append(raw[i])
    return results[0] if single else tuple(results)


# ---------------------------------------------------------------------------
# Fused unblocked Cholesky base case (chol_unblocked analogue)
# ---------------------------------------------------------------------------


def _chol_base_body(a):
    b = a.shape[0]
    rows = lax.broadcasted_iota(jnp.int32, (b, b), 0)
    cols = lax.broadcasted_iota(jnp.int32, (b, b), 1)

    def body(j, a):
        pv = jnp.sqrt(lax.dynamic_slice(a, (j, j), (1, 1))[0, 0])
        col = lax.dynamic_slice(a, (0, j), (b, 1))
        rvec = lax.broadcasted_iota(jnp.int32, (b, 1), 0)
        l = jnp.where(rvec > j, col / pv, jnp.zeros_like(col))
        # masked rank-1 trailing update in the same pass
        upd = _mxu_dot(l, _conj(l).T)
        a = jnp.where((rows > j) & (cols > j), a - upd, a)
        # write the factored column: pivot on the diagonal, l below;
        # entries above the diagonal pass through (callers tril)
        newcol = jnp.where(rvec == j, pv.astype(a.dtype), l)
        return jnp.where((cols == j) & (rows >= j), newcol, a)

    return lax.fori_loop(0, b, body, a)


def chol_base_reference(G: jnp.ndarray) -> jnp.ndarray:
    """jnp twin: the ib-strip unblocked Cholesky the recursive schedule
    uses (entries above the diagonal pass through untouched)."""
    from ..chol_kernels import chol_unblocked

    return chol_unblocked(G)


def chol_base_pallas(G: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Fused unblocked Cholesky of a (b, b) block, one VMEM pass."""
    return _run_kernel(
        _chol_base_body,
        jax.ShapeDtypeStruct(G.shape, G.dtype),
        (G,),
        interpret,
    )


def chol_base(G: jnp.ndarray, interpret: Optional[bool] = None) -> jnp.ndarray:
    return chol_base_pallas(G, interpret=_resolve_interpret(interpret, G))


# ---------------------------------------------------------------------------
# Fused panel LU with in-register partial-pivot search (panel_lu analogue)
# ---------------------------------------------------------------------------


def _panel_lu_body(a, *, pivot: bool, act):
    """Arithmetic replicates ops/lu_kernels.panel_lu exactly (same op
    sequence -> identical pivot order, identical floats)."""
    M, nb = a.shape
    rows = jnp.arange(M)

    def body(j, carry):
        a, perm = carry
        col = a[:, j]
        if pivot:
            elig = rows >= j if act is None else (rows >= j) & (rows < act)
            mag = jnp.where(elig, jnp.abs(col), -jnp.inf)
            piv = jnp.argmax(mag)
        else:
            piv = j
        rj = a[j]
        rp = a[piv]
        a = a.at[j].set(rp).at[piv].set(rj)
        pj = perm[j]
        pp = perm[piv]
        perm = perm.at[j].set(pp).at[piv].set(pj)
        pv = a[j, j]
        safe = jnp.where(pv == 0, jnp.ones_like(pv), pv)
        l = jnp.where(
            (rows > j) & (pv != 0), a[:, j] / safe, jnp.zeros(M, a.dtype)
        )
        a = a.at[:, j].set(jnp.where(rows > j, l, a[:, j]))
        urow = jnp.where(jnp.arange(nb) > j, a[j], jnp.zeros(nb, a.dtype))
        return a - jnp.outer(l, urow), perm

    perm0 = jnp.arange(M, dtype=jnp.int32)
    return lax.fori_loop(0, min(M, nb), body, (a, perm0))


def panel_lu_reference(
    panel: jnp.ndarray, pivot: bool = True, act: Optional[int] = None
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """jnp twin: the fori_loop panel factor the recursive schedule uses."""
    from ..lu_kernels import panel_lu as _panel_lu

    return _panel_lu(panel, pivot=pivot, act=act)


def panel_lu_pallas(
    panel: jnp.ndarray,
    pivot: bool = True,
    act: Optional[int] = None,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused partial-pivot panel LU: per-column pivot search, row swap,
    scale and rank-1 update all inside one kernel invocation.  ``act``
    is static (the recursive schedule's canonical-height pad rows must
    never pivot)."""
    M, nb = panel.shape
    return _run_kernel(
        functools.partial(_panel_lu_body, pivot=pivot, act=act),
        (
            jax.ShapeDtypeStruct((M, nb), panel.dtype),
            jax.ShapeDtypeStruct((M,), jnp.int32),
        ),
        (panel,),
        interpret,
    )


def panel_lu(
    panel: jnp.ndarray,
    pivot: bool = True,
    act: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    return panel_lu_pallas(
        panel, pivot=pivot, act=act,
        interpret=_resolve_interpret(interpret, panel),
    )


# ---------------------------------------------------------------------------
# Compact-WY T assembly (householder.larft analogue)
# ---------------------------------------------------------------------------


def _larft_tinv_body(V, taus):
    """T^{-1} = strict_upper(V^H V) + diag(1/tau) fused in one pass
    (the tau == 0 large-diagonal limit included)."""
    nb = V.shape[1]
    complex_t = jnp.issubdtype(V.dtype, jnp.complexfloating)
    VhV = _mxu_dot(jnp.conj(V).T if complex_t else V.T, V)
    rows = lax.broadcasted_iota(jnp.int32, (nb, nb), 0)
    cols = lax.broadcasted_iota(jnp.int32, (nb, nb), 1)
    U = jnp.where(cols > rows, VhV, jnp.zeros_like(VhV))
    big = jnp.asarray(1e30, V.dtype)
    d = jnp.where(taus != 0, 1.0 / jnp.where(taus == 0, 1, taus), big)
    return U + jnp.where(
        rows == cols, d.astype(V.dtype)[None, :], jnp.zeros_like(U)
    )


def larft_reference(V: jnp.ndarray, taus: jnp.ndarray) -> jnp.ndarray:
    """jnp twin: the compact-WY identity in ops/householder.larft."""
    from ..householder import larft as _larft

    return _larft(V, taus)


def larft_pallas(
    V: jnp.ndarray, taus: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """Compact-WY T for the QR panel base case: the Gram/assembly stage
    (the MXU-heavy 2 M nb^2 part) fused in one kernel; the <= nb
    triangular inverse stays on the vendor solve, the same convention
    as the recursive schedules' <= nb diagonal trsm blocks."""
    nb = V.shape[1]
    if taus.shape[0] < nb:
        taus = jnp.concatenate(
            [taus, jnp.zeros((nb - taus.shape[0],), taus.dtype)]
        )
    Tinv = _run_kernel(
        _larft_tinv_body,
        jax.ShapeDtypeStruct((nb, nb), V.dtype),
        (V, taus),
        interpret,
    )
    T = lax.linalg.triangular_solve(
        Tinv, jnp.eye(nb, dtype=V.dtype), left_side=True, lower=False
    )
    live = (taus != 0)[None, :] & (taus != 0)[:, None]
    return jnp.where(live, T, jnp.zeros_like(T))


def larft(
    V: jnp.ndarray, taus: jnp.ndarray, interpret: Optional[bool] = None
) -> jnp.ndarray:
    return larft_pallas(V, taus, interpret=_resolve_interpret(interpret, V))


# ---------------------------------------------------------------------------
# Triangle-aware syrk pieces (the Cholesky trailing update)
# ---------------------------------------------------------------------------


def _syrk_diag_body(C, A):
    t = C.shape[0]
    rows = lax.broadcasted_iota(jnp.int32, (t, t), 0)
    cols = lax.broadcasted_iota(jnp.int32, (t, t), 1)
    upd = _mxu_dot(A, _conj(A).T)
    # entries above the diagonal pass through untouched (_syrk_lower's
    # contract: callers only consume the lower triangle)
    return jnp.where(rows >= cols, C - upd, C)


def syrk_diag_reference(C: jnp.ndarray, A: jnp.ndarray) -> jnp.ndarray:
    """jnp twin of the diagonal-block base case of _syrk_lower."""
    from ...internal.precision import hdot as _dot

    t = C.shape[0]
    rows = jnp.arange(t)[:, None]
    cols = jnp.arange(t)[None, :]
    return jnp.where(rows >= cols, C - _dot(A, _conj(A).T), C)


def syrk_diag_pallas(
    C: jnp.ndarray, A: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """Diagonal nb-block of the trailing update: the one place that
    pays a full-square gemm, fused with the lower-triangle mask in a
    single VMEM pass."""
    return _run_kernel(
        _syrk_diag_body,
        jax.ShapeDtypeStruct(C.shape, C.dtype),
        (C, A),
        interpret,
    )


def syrk_diag(
    C: jnp.ndarray, A: jnp.ndarray, interpret: Optional[bool] = None
) -> jnp.ndarray:
    return syrk_diag_pallas(
        C, A, interpret=_resolve_interpret(interpret, C, A)
    )


def _gemm_sub_body(C, A, B):
    return C - _mxu_dot(A, _conj(B).T)


def gemm_sub_reference(
    C: jnp.ndarray, A: jnp.ndarray, B: jnp.ndarray
) -> jnp.ndarray:
    """jnp twin: C - A B^H (the off-diagonal syrk block)."""
    from ...internal.precision import hdot as _dot

    return C - _dot(A, _conj(B).T)


def gemm_sub_pallas(
    C: jnp.ndarray, A: jnp.ndarray, B: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """Off-diagonal syrk block: fused multiply-subtract C - A B^H."""
    return _run_kernel(
        _gemm_sub_body,
        jax.ShapeDtypeStruct(C.shape, C.dtype),
        (C, A, B),
        interpret,
    )


def gemm_sub(
    C: jnp.ndarray, A: jnp.ndarray, B: jnp.ndarray,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    return gemm_sub_pallas(
        C, A, B, interpret=_resolve_interpret(interpret, C, A, B)
    )


# ---------------------------------------------------------------------------
# The solve-phase trsm pair (serve phase="solve" buckets)
# ---------------------------------------------------------------------------

#: diagonal-block size of the in-kernel substitution; serve bucket
#: sizes are multiples of 128 so 32 always divides them
_TRSM_KB = 32


def _trsm_kb(n: int) -> int:
    for kb in (_TRSM_KB, 16, 8, 4, 2, 1):
        if n % kb == 0:
            return kb
    return 1


def _newton_tri_inv(D, rows, cols, lower: bool, unit: bool, kb: int):
    """Exact inverse of a triangular (kb, kb) block by Newton iteration:
    X0 = diag(1/diag), residual I - D X strictly triangular (nilpotent),
    squared each step -> ceil(log2(kb)) iterations reach it exactly."""
    keep = cols <= rows if lower else cols >= rows
    D = jnp.where(keep, D, jnp.zeros_like(D))
    if unit:
        D = jnp.where(rows == cols, jnp.ones_like(D), D)
        X = jnp.where(rows == cols, jnp.ones_like(D), jnp.zeros_like(D))
    else:
        dg = jnp.sum(
            jnp.where(rows == cols, D, jnp.zeros_like(D)), axis=1,
            keepdims=True,
        )
        X = jnp.where(rows == cols, 1.0 / dg, jnp.zeros_like(D))
    eye2 = jnp.where(rows == cols, jnp.ones_like(D), jnp.zeros_like(D))
    iters = int(math.ceil(math.log2(kb))) if kb > 1 else 0
    for _ in range(iters):
        X = _mxu_dot(X, 2.0 * eye2 - _mxu_dot(D, X))
    return X


def _trsm_body(L, B, *, lower: bool, unit: bool, kb: int):
    n, nrhs = B.shape
    nblk = n // kb
    rows = lax.broadcasted_iota(jnp.int32, (kb, kb), 0)
    cols = lax.broadcasted_iota(jnp.int32, (kb, kb), 1)

    def blk(i, X):
        k = i if lower else nblk - 1 - i
        r0 = k * kb
        # full-width update: rows of X not yet solved are still zero,
        # so the unsolved columns of this row block contribute nothing
        # (packed-LU storage included: the other triangle multiplies
        # zero rows)
        Lrow = lax.dynamic_slice(L, (r0, 0), (kb, n))
        rhs = lax.dynamic_slice(B, (r0, 0), (kb, nrhs)) - _mxu_dot(Lrow, X)
        D = lax.dynamic_slice(L, (r0, r0), (kb, kb))
        Dinv = _newton_tri_inv(D, rows, cols, lower, unit, kb)
        return lax.dynamic_update_slice(X, _mxu_dot(Dinv, rhs), (r0, 0))

    return lax.fori_loop(0, nblk, blk, jnp.zeros_like(B))


def trsm_lower_reference(
    L: jnp.ndarray, B: jnp.ndarray, unit: bool = False
) -> jnp.ndarray:
    """jnp twin: the vendor lower-triangular solve."""
    return lax.linalg.triangular_solve(
        L, B, left_side=True, lower=True, unit_diagonal=unit
    )


def trsm_upper_reference(U: jnp.ndarray, B: jnp.ndarray) -> jnp.ndarray:
    """jnp twin: the vendor upper-triangular solve."""
    return lax.linalg.triangular_solve(U, B, left_side=True, lower=False)


def _trsm_pallas_call(T, B, lower, unit, interpret):
    body = functools.partial(
        _trsm_body, lower=lower, unit=unit, kb=_trsm_kb(T.shape[0])
    )
    return _run_kernel(
        body, jax.ShapeDtypeStruct(B.shape, B.dtype), (T, B), interpret
    )


def trsm_lower_pallas(
    L: jnp.ndarray, B: jnp.ndarray, unit: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """L X = B by blocked forward substitution in one kernel (reads
    only the lower triangle, so packed-LU storage is fine)."""
    return _trsm_pallas_call(L, B, lower=True, unit=unit, interpret=interpret)


def trsm_upper_pallas(
    U: jnp.ndarray, B: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """U X = B by blocked backward substitution in one kernel (reads
    only the upper triangle)."""
    return _trsm_pallas_call(U, B, lower=False, unit=False,
                             interpret=interpret)


def trsm_lower(
    L: jnp.ndarray, B: jnp.ndarray, unit: bool = False,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    return trsm_lower_pallas(
        L, B, unit=unit, interpret=_resolve_interpret(interpret, L, B)
    )


def trsm_upper(
    U: jnp.ndarray, B: jnp.ndarray, interpret: Optional[bool] = None
) -> jnp.ndarray:
    return trsm_upper_pallas(
        U, B, interpret=_resolve_interpret(interpret, U, B)
    )
