"""Windowed band factorization/solve kernels: O(n kd^2) work instead of
O(n^3) (reference: src/pbtrf.cc, gbtrf.cc, tbsm.cc — the reference
restricts its task loops to in-band tiles; here the same restriction is a
lax.fori_loop over fixed-size diagonal windows, each a static-shape
slice of the dense-stored band, so XLA compiles ONE window body reused
n/w times).

All kernels take the dense (n, n) global array of a band matrix (the
repo's band storage) and touch only O(kd + w)-sized windows per step:
the asymptotic cost matches true band storage while keeping the uniform
dense tile layout everywhere else.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .chol_kernels import cholesky as _chol_tile
from .lu_kernels import panel_lu


def _ceil_div(a, b):
    return -(-a // b)


def _win_size(kd: int) -> int:
    """Window step: big enough to amortize the per-step dispatch, small
    enough to keep window FLOPs ~ O(w kd^2)."""
    return int(min(max(kd, 32), 512))


def band_potrf_lower(G: jnp.ndarray, kd: int) -> jnp.ndarray:
    """Cholesky of a Hermitian band matrix with lower bandwidth kd
    (lower triangle of G valid).  Returns the lower band factor L
    (dense (n, n), zero outside the band).

    Per window: w x w diagonal Cholesky, a kd x w triangular solve, and
    the kd x kd trailing update — the pbtrf.cc loop restricted to the
    band, one fori_loop body (reference: src/pbtrf.cc:40-108).
    """
    n = G.shape[0]
    complex_t = jnp.issubdtype(G.dtype, jnp.complexfloating)

    def C(x):
        return jnp.conj(x) if complex_t else x

    if kd >= n - 1:
        return jnp.tril(_chol_tile(jnp.where(
            jnp.tril(jnp.ones((n, n), bool)), G, C(G).T), 512))
    w = _win_size(kd)
    steps = _ceil_div(n, w)
    npad = steps * w + w + kd
    Gp = jnp.pad(G, ((0, npad - n), (0, npad - n)))
    idx = jnp.arange(npad)
    splice = jnp.where(idx >= n, 1.0, 0.0).astype(G.dtype)
    Gp = Gp.at[idx, idx].add(splice)
    W = w + kd
    tri = jnp.tril(jnp.ones((w, w), bool))

    def step(k, Gp):
        off = k * w
        Wd = lax.dynamic_slice(Gp, (off, off), (W, W))
        A11 = Wd[:w, :w]
        A11 = jnp.where(tri, A11, C(jnp.swapaxes(A11, 0, 1)))
        L11 = _chol_tile(A11, min(w, 512))
        L11 = jnp.tril(L11)
        A21 = Wd[w:, :w]
        L21 = lax.linalg.triangular_solve(
            L11, A21, left_side=False, lower=True,
            transpose_a=True, conjugate_a=complex_t,
        )
        A22 = Wd[w:, w:] - L21 @ C(L21).T
        Wn = jnp.zeros_like(Wd)
        Wn = Wn.at[:w, :w].set(L11)
        Wn = Wn.at[w:, :w].set(L21)
        Wn = Wn.at[w:, w:].set(A22)
        return lax.dynamic_update_slice(Gp, Wn, (off, off))

    Gp = lax.fori_loop(0, steps, step, Gp)
    out = jnp.tril(Gp[:n, :n])
    i = jnp.arange(n)
    band = (i[:, None] - i[None, :]) <= kd
    return jnp.where(band, out, jnp.zeros_like(out))


def band_trsm_lower(
    L: jnp.ndarray, B: jnp.ndarray, kd: int,
    unit_diag: bool = False, conj: bool = False,
) -> jnp.ndarray:
    """Solve L X = B with L lower band (bandwidth kd): forward windowed
    substitution, O(n kd nrhs) (reference: src/tbsm.cc's in-band task
    loop).  Upper/transposed solves reduce to this by the index-reversal
    J U J = lower-band (see drivers/band.py::tbsm)."""
    n, nrhs = B.shape
    complex_t = jnp.issubdtype(L.dtype, jnp.complexfloating)
    do_conj = conj and complex_t
    w = _win_size(kd)
    steps = _ceil_div(n, w)
    npad = steps * w
    # shifted storage: column c of L at column c + kd, so every window's
    # left dependency strip is an in-bounds static slice
    Lp = jnp.pad(L, ((0, npad - n), (kd, npad - n)))
    idx = jnp.arange(npad)
    Lp = Lp.at[idx, idx + kd].add(
        jnp.where(idx >= n, 1.0, 0.0).astype(L.dtype)
    )
    if do_conj:
        Lp = jnp.conj(Lp)
    # X rows at row r + kd (kd zero rows on top = the "no earlier X"
    # boundary for the first window)
    Xp = jnp.pad(B.astype(L.dtype), ((kd, npad - n), (0, 0)))

    def step(k, Xp):
        off = k * w
        Wd = lax.dynamic_slice(Lp, (off, off), (w, kd + w))
        xprev = lax.dynamic_slice(Xp, (off, 0), (kd, nrhs))
        bwin = lax.dynamic_slice(Xp, (off + kd, 0), (w, nrhs))
        rhs = bwin - Wd[:, :kd] @ xprev
        Xw = lax.linalg.triangular_solve(
            jnp.tril(Wd[:, kd:]), rhs, left_side=True, lower=True,
            unit_diagonal=unit_diag,
        )
        return lax.dynamic_update_slice(Xp, Xw, (off + kd, 0))

    Xp = lax.fori_loop(0, steps, step, Xp)
    return Xp[kd : kd + n].astype(B.dtype)


def band_getrf(
    G: jnp.ndarray, kl: int, ku: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """Pivoted LU of a band matrix (dense-stored, bandwidths kl/ku):
    windowed gbtrf (reference: src/gbtrf.cc — panel + in-band trailing
    update with pivot fill-in of kl extra superdiagonals).

    Uses LAPACK's banded-pivot convention: row swaps act only on the
    current window (the multipliers of earlier columns stay in place),
    which keeps L banded — under fully-swapped rows a deferred row can
    drift arbitrarily far, scattering L outside any band.  The solve
    must therefore replay the window swaps interleaved with the window
    eliminations (band_getrs).

    Returns (LU, lperms, perm, w): LU holds in-place unit-lower
    multipliers (per-column span < w + kl) and U with bandwidth
    kl + ku; lperms is (steps, w + kl) window-local pivot orders; perm
    the net forward row permutation; w the window step.  Each window
    touches (w + kl) x (w + kl + ku) entries: O(n (kl + w)(kl + ku + w))
    total work.
    """
    n = G.shape[0]
    w = _win_size(max(kl, ku, 1))
    steps = _ceil_div(n, w)
    W1 = w + kl  # rows a panel can pivot over
    W2 = w + kl + ku  # columns those rows touch
    npad = steps * w + W1 + W2
    Gp = jnp.pad(G, ((0, npad - n), (0, npad - n)))
    idx = jnp.arange(npad)
    Gp = Gp.at[idx, idx].add(jnp.where(idx >= n, 1.0, 0.0).astype(G.dtype))
    perm0 = jnp.arange(npad, dtype=jnp.int32)
    lperms0 = jnp.zeros((steps, W1), jnp.int32)

    def step(k, carry):
        Gp, perm, lperms = carry
        off = k * w
        Wd = lax.dynamic_slice(Gp, (off, off), (W1, W2))
        pan = Wd[:, :w]
        lu_pan, lperm = panel_lu(pan)
        L11 = jnp.tril(lu_pan[:w, :w], -1) + jnp.eye(w, dtype=G.dtype)
        right = Wd[lperm, w:]
        U12 = lax.linalg.triangular_solve(
            L11, right[:w], left_side=True, lower=True, unit_diagonal=True
        )
        trail = right[w:] - lu_pan[w:, :w] @ U12
        Wn = jnp.concatenate(
            [lu_pan, jnp.concatenate([U12, trail], axis=0)], axis=1
        )
        Gp = lax.dynamic_update_slice(Gp, Wn, (off, off))
        pwin = lax.dynamic_slice(perm, (off,), (W1,))
        perm = lax.dynamic_update_slice(perm, pwin[lperm], (off,))
        lperms = lperms.at[k].set(lperm)
        return Gp, perm, lperms

    Gp, perm, lperms = lax.fori_loop(0, steps, step, (Gp, perm0, lperms0))
    return Gp[:n, :n], lperms, perm[:n], w


def band_getrs(
    LU: jnp.ndarray,
    lperms: jnp.ndarray,
    w: int,
    kl: int,
    ku: int,
    B: jnp.ndarray,
) -> jnp.ndarray:
    """Solve A X = B from band_getrf's interleaved-pivot factorization
    (reference: src/gbtrs.cc): the forward sweep replays, per window,
    the local row swap followed by the window's unit-L elimination; the
    back sweep is the U band solve via index reversal."""
    n, nrhs = B.shape
    steps, W1 = lperms.shape
    npad = steps * w + W1
    Lp = jnp.pad(LU, ((0, npad - n), (0, npad - n)))
    idx = jnp.arange(npad)
    Lp = Lp.at[idx, idx].add(jnp.where(idx >= n, 1.0, 0.0).astype(LU.dtype))
    Yp = jnp.pad(B.astype(LU.dtype), ((0, npad - n), (0, 0)))

    def fwd(k, Yp):
        off = k * w
        lperm = lperms[k]
        ywin = lax.dynamic_slice(Yp, (off, 0), (W1, nrhs))[lperm]
        Wd = lax.dynamic_slice(Lp, (off, off), (W1, w))
        y1 = lax.linalg.triangular_solve(
            jnp.tril(Wd[:w]),
            ywin[:w],
            left_side=True,
            lower=True,
            unit_diagonal=True,
        )
        y2 = ywin[w:] - Wd[w:] @ y1
        Yp2 = jnp.concatenate([y1, y2], axis=0)
        return lax.dynamic_update_slice(Yp, Yp2, (off, 0))

    Yp = lax.fori_loop(0, steps, fwd, Yp)
    Y = Yp[:n]
    U = jnp.triu(LU)
    X = band_trsm_lower(U[::-1, ::-1], Y[::-1], kl + ku)[::-1]
    return X.astype(B.dtype)
