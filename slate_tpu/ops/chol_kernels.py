"""Native blocked Cholesky kernels for TPU.

The vendor ``lax.linalg.cholesky`` lowers to a near-sequential schedule on
this TPU toolchain (measured ~1-5 GF/s at panel sizes, 52 GF/s at n=4096,
against a ~2.6 TF/s f64 matmul rate on the same chip), so the driver-level
potrf was stuck at ~3.5% of gemm speed.  These kernels rebuild the
reference's blocked right-looking schedule (reference: src/potrf.cc:84-209
— panel factor, trsm, trailing herk with the trailing gemm dominating)
out of the ops that ARE fast here:

* ``chol_unblocked``  — column-at-a-time fori_loop Cholesky of one
  nb x nb diagonal block.  The masked rank-1 update is a VPU
  elementwise op (measured ~6 us/column at nb=512), two orders faster
  than the vendor kernel's schedule.
* ``chol_fori``       — single-level blocked loop: one ``lax.fori_loop``
  over nb-wide panels with full-height masked trsm + trailing gemm.
  A compile-lean alternative (one compiled shape regardless of n; the
  default schedule below is ~20% faster but compiles one shape set per
  panel count) — kept off the default path, available to callers that
  factor many distinct sizes.
* ``blocked_potrf``   — two-level schedule for large n: at most
  ``coarse_panels`` Python-unrolled panels of width NB (exact shrinking
  shapes, so the trailing update is a full-rate gemm), each diagonal
  block factored by recursing into ``_chol_panels``/``chol_unblocked``,
  the panel solve done MAGMA-style as an explicit small triangular
  inverse + gemm so the bulk work rides the MXU instead of the slow
  vendor trsm path.

Everything is static-shape; distinct XLA shapes per n are bounded by
O(coarse_panels) to keep compile time in check (measured ~25 s per
distinct f64 trsm shape, ~10 s per gemm shape on this toolchain).

Used by drivers/chol.py for the single-chip (global-path) potrf on
non-CPU backends; the CPU backend keeps the vendor (LAPACK) kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# All matmuls in these kernels run at HIGHEST precision: the TPU default
# for the f64 emulation drops to ~f32-grade accumulation (measured 1e-8
# Cholesky residual vs 1e-12 with HIGHEST), and f32 inputs would drop to
# one bf16 pass (internal/precision.py's policy, applied here directly
# since these kernels are used inside jit where the context manager at
# call sites may not be active).
from ..internal.precision import hdot as _dot


def _conj(x):
    return jnp.conj(x) if jnp.iscomplexobj(x) else x


def chol_unblocked(a: jnp.ndarray, ib: int = 16) -> jnp.ndarray:
    """Cholesky of one (b, b) block: L L^H = a, b a multiple of ib.

    fori_loop over b//ib column strips: the ib columns of a strip are
    eliminated by an unrolled micro-loop touching only the (b, ib)
    strip, then one VPU rank-ib update fixes the trailing columns.
    This keeps the per-iteration memory traffic at O(b*ib) for the
    micro-steps and O(b^2) only once per strip — the column-at-a-time
    variant's O(b^2) *per column* made it bandwidth-bound (~80 us per
    column at b=512 on the chip).

    Non-SPD input yields NaN columns (sqrt of a negative pivot), which
    the caller's info check detects — same contract as the vendor
    kernel.
    """
    b = a.shape[0]
    if b % ib != 0:
        ib = 8 if b % 8 == 0 else 1
    idx = jnp.arange(b)
    nsteps = b // ib

    def body(i, a):
        j0 = i * ib
        P = lax.dynamic_slice(a, (0, j0), (b, ib))
        for c in range(ib):
            jc = j0 + c
            pj = jnp.sqrt(jnp.real(lax.dynamic_slice(P, (jc, c), (1, 1))[0, 0]))
            pj = pj.astype(a.dtype)
            col = jnp.where(idx > jc, P[:, c] / pj, jnp.zeros((), a.dtype))
            P = P.at[:, c].set(jnp.where(idx == jc, pj, col))
            if c + 1 < ib:
                # multipliers for the strip's remaining columns are the
                # scaled L entries at the strip's own pivot rows
                lrow = lax.dynamic_slice(P, (j0, c), (ib, 1))[:, 0]
                lrow = jnp.where(jnp.arange(ib) > c, _conj(lrow), 0)
                P = P - jnp.outer(col, lrow)
        a = lax.dynamic_update_slice(a, P, (0, j0))
        # rank-ib trailing update, restricted to columns >= j0+ib via a
        # row mask on the second operand (upper-triangle junk is dropped
        # by the final tril)
        Q = jnp.where((idx >= j0 + ib)[:, None], P, jnp.zeros((), a.dtype))
        return a - _dot(P, _conj(Q).T)

    return jnp.tril(lax.fori_loop(0, nsteps, body, a))


def chol_fori(G: jnp.ndarray, nb: int = 512) -> jnp.ndarray:
    """Single-level blocked Cholesky of (n, n), n a multiple of nb.

    One fori_loop over the n//nb panels; every step runs at full array
    shape with row masks (one compile unit).  The trailing update is a
    (n, nb) x (nb, n) gemm at EVERY step, so the executed FLOPs are
    ~2 n^3 + n^2 nb against the n^3/3 model — ~6x the model, ~3x the
    exact-shape blocked schedule (see ``chol_schedule_flops``), the
    price of the single compiled shape.  Large-n callers should prefer
    ``chol_recursive``: exact halving-lattice shapes, near-model FLOPs,
    O(log n) compile units.
    """
    n = G.shape[0]
    if n == nb:
        return chol_unblocked(G)
    assert n % nb == 0, "chol_fori requires n % nb == 0"
    rows = jnp.arange(n)

    def step(k, G):
        Akk = lax.dynamic_slice(G, (k * nb, k * nb), (nb, nb))
        Lkk = chol_unblocked(Akk)
        col = lax.dynamic_slice(G, (0, k * nb), (n, nb))
        sol = lax.linalg.triangular_solve(
            Lkk, col, left_side=False, lower=True, transpose_a=True,
            conjugate_a=jnp.iscomplexobj(G),
        )
        below = (rows >= (k + 1) * nb)[:, None]
        Lpan = jnp.where(below, sol, jnp.zeros((), G.dtype))
        diag_rows = ((rows >= k * nb) & (rows < (k + 1) * nb))[:, None]
        Lkk_tall = jnp.pad(Lkk, ((0, n - nb), (0, 0)))
        Lkk_placed = jnp.where(diag_rows, jnp.roll(Lkk_tall, k * nb, axis=0), 0)
        above = (rows < k * nb)[:, None]
        newcol = jnp.where(above, jnp.zeros((), G.dtype), Lkk_placed + Lpan)
        G = lax.dynamic_update_slice(G, newcol, (0, k * nb))
        return G - _dot(Lpan, _conj(Lpan).T)

    return jnp.tril(lax.fori_loop(0, n // nb, step, G))


def _chol_panels(G: jnp.ndarray, nb: int) -> jnp.ndarray:
    """Python-unrolled blocked Cholesky of (n, n), n a multiple of nb,
    intended for n/nb <= ~4 panels.

    Per panel: chol_unblocked diag, ONE full-height trsm (a single XLA
    shape reused by every panel — each distinct f64 trsm shape costs
    ~15-25 s of compile on this toolchain), then exact-shape trailing
    syrk (full MXU rate where the FLOPs are)."""
    n = G.shape[0]
    cplx = jnp.iscomplexobj(G)
    cols = []
    T = G
    k0 = 0
    while k0 < n:
        w = min(nb, n - k0)
        D = chol_unblocked(T[:w, :w])
        rest = n - k0 - w
        if rest > 0:
            # explicit (w, w) inverse + MXU gemm instead of a
            # full-height vendor trsm: the vendor triangular_solve with
            # a fat RHS is schedule-bound on this toolchain (~10-25 ms
            # per panel) while the small trsm + gemm ride the MXU —
            # the same MAGMA recipe blocked_potrf uses at the coarse
            # level
            Dinv = lax.linalg.triangular_solve(
                D, jnp.eye(w, dtype=G.dtype), left_side=True, lower=True
            )
            L21 = _dot(T[w:, :w], _conj(Dinv).T)
            T = T[w:, w:] - _dot(L21, _conj(L21).T)
            colk = jnp.concatenate(
                [jnp.zeros((k0, w), G.dtype), D, L21], axis=0
            )
        else:
            colk = jnp.concatenate([jnp.zeros((k0, w), G.dtype), D], axis=0)
        cols.append(colk)
        k0 += w
    return jnp.concatenate(cols, axis=1)


def blocked_potrf(
    G: jnp.ndarray, nb: int = 512, coarse_panels: int = 4
) -> jnp.ndarray:
    """Blocked Cholesky factor L (lower) of an SPD (n, n) array.

    n must be a multiple of 128 (callers pad with a unit-diagonal
    splice).  Schedule (reference: src/potrf.cc:84-209, with the
    lookahead pipeline replaced by XLA's own overlap inside one
    compiled program):

      for each of <= coarse_panels column panels of width NB:
        D    = recursive factor of T[:NB,:NB]      # exact-shape panels
        Dinv = trsm(D, I)                          # one small trsm
        L21  = T[NB:,:NB] @ Dinv^H                 # MXU gemm
        T    = T[NB:,NB:] - L21 @ L21^H            # MXU gemm (dominant)

    Exact shrinking shapes per panel (full-rate gemms); the explicit
    panel inverse trades one (NB,NB) trsm for MXU gemms, the MAGMA
    recipe.  Distinct XLA shapes stay O(coarse_panels + recursion
    depth): the diag-block shapes repeat across panels.
    """
    n = G.shape[0]
    if n <= 256:
        return chol_unblocked(G)
    nb = min(nb, n)
    if n % nb != 0:
        nb = 256 if n % 256 == 0 else 128
    assert n % nb == 0, f"blocked_potrf: n={n} not a multiple of 128"
    nt = n // nb
    if nt <= coarse_panels:
        return _chol_panels(G, nb)

    NB = nb * (-(-nt // coarse_panels))
    cols = []
    T = G
    k0 = 0
    eyeNB = None
    while k0 < n:
        w = min(NB, n - k0)
        D = blocked_potrf(T[:w, :w], nb, coarse_panels)
        rest = n - k0 - w
        if rest > 0:
            if eyeNB is None or eyeNB.shape[0] != w:
                eyeNB = jnp.eye(w, dtype=G.dtype)
            Dinv = lax.linalg.triangular_solve(
                D, eyeNB, left_side=True, lower=True
            )
            L21 = _dot(T[w:, :w], _conj(Dinv).T)
            T = T[w:, w:] - _dot(L21, _conj(L21).T)
            colk = jnp.concatenate(
                [jnp.zeros((k0, w), G.dtype), D, L21], axis=0
            )
        else:
            colk = jnp.concatenate([jnp.zeros((k0, w), G.dtype), D], axis=0)
        cols.append(colk)
        k0 += w
    return jnp.concatenate(cols, axis=1)


def tri_inv_blocked(L: jnp.ndarray, nb: int = 512) -> jnp.ndarray:
    """Explicit inverse of a lower-triangular matrix by recursive
    2x2 blocking: inv([[A,0],[B,C]]) = [[inv(A),0],[-inv(C) B inv(A),
    inv(C)]] — two half-size inverses + two MXU gemms per level; the
    vendor triangular_solve only ever sees <= nb-sized blocks (the
    full-size vendor trsm is schedule-bound on this toolchain, the
    same finding as _chol_panels')."""
    n = L.shape[0]
    if n <= nb:
        return lax.linalg.triangular_solve(
            L, jnp.eye(n, dtype=L.dtype), left_side=True, lower=True
        )
    h = max(((n + 1) // 2 + 127) // 128 * 128, 128)
    h = min(h, n - 1)
    A = L[:h, :h]
    B = L[h:, :h]
    C = L[h:, h:]
    Ai = tri_inv_blocked(A, nb)
    Ci = tri_inv_blocked(C, nb)
    lowblk = -_dot(Ci, _dot(B, Ai))
    top = jnp.concatenate([Ai, jnp.zeros((h, n - h), L.dtype)], axis=1)
    bot = jnp.concatenate([lowblk, Ci], axis=1)
    return jnp.concatenate([top, bot], axis=0)


# ---------------------------------------------------------------------------
# Recursive (divide & conquer) schedule: exact shapes on the halving
# lattice.  The flat loops above pay for their single compiled shape in
# raw FLOPs (chol_fori: ~6x the n^3/3 model); the recursion factors the
# top-left half, solves/updates the off-diagonal block and trailing half
# at their *exact* static shapes (n, n/2, n/4, ... — at most O(log n)
# distinct compile units), with the flat kernels kept as the small-n
# base case below the ``nb_switch`` crossover.
# ---------------------------------------------------------------------------


# auto-schedule crossover: below this the flat/blocked schedules win
# (recursion overhead + compile-unit count buy nothing at small n)
RECURSIVE_MIN_N = 2048


def split_point(n: int) -> int:
    """Top-half size of the recursion: ceil(n/2) rounded up to the best
    MXU alignment that still leaves a nonempty trailing half.  For the
    serve halving-lattice sizes (2^k * 64/128) this is exactly n/2, so
    every recursion shape lands back on the lattice the warmup manifest
    already covers."""
    h = (n + 1) // 2
    for a in (128, 64, 32, 16, 8):
        ha = -(-h // a) * a
        if ha < n:
            return ha
    return h


def _lat_height(M: int) -> int:
    """Round M up to the nearest 2^k or 3*2^(k-1) (1.0x or 1.5x a power
    of two — exactly two values per octave).  The tall LU/QR recursions
    produce operand heights m - k*nb — O(n/nb) distinct values;
    snapping them to this lattice keeps distinct compiled shapes
    O(log^2) at <= 33% zero-row padding, and halving-lattice sizes map
    to themselves."""
    if M <= 0:
        return 0
    k = M.bit_length() - 1
    if M == 1 << k:
        return M
    c15 = 3 << (k - 1)  # 1.5 * 2^k
    return c15 if M <= c15 else 1 << (k + 1)


def _trsm_right_lh(L: jnp.ndarray, A: jnp.ndarray, nb: int) -> jnp.ndarray:
    """X L^H = A with L lower triangular, by recursive 2x2 splitting:
    the vendor triangular_solve only ever sees <= nb diagonal blocks
    (the full-size vendor trsm is schedule-bound on this toolchain, the
    _chol_panels finding) and the bulk work rides exact-shape MXU gemms
    at exactly the model FLOP count (t h^2)."""
    h = L.shape[0]
    if h <= nb:
        return lax.linalg.triangular_solve(
            L, A, left_side=False, lower=True, transpose_a=True,
            conjugate_a=jnp.iscomplexobj(A),
        )
    s = split_point(h)
    X1 = _trsm_right_lh(L[:s, :s], A[:, :s], nb)
    X2 = _trsm_right_lh(
        L[s:, s:], A[:, s:] - _dot(X1, _conj(L[s:, :s]).T), nb
    )
    return jnp.concatenate([X1, X2], axis=1)


def _base_chol(G: jnp.ndarray, family: str) -> jnp.ndarray:
    """Base-case dispatch: the ib-strip chol_unblocked (recursive
    family) or the fused Pallas kernel (pallas family)."""
    if family == "pallas":
        from .pallas import panel_kernels as pk

        return pk.chol_base(G)
    return chol_unblocked(G)


def _syrk_lower(
    C: jnp.ndarray, A: jnp.ndarray, nb: int, family: str = "recursive"
) -> jnp.ndarray:
    """Lower triangle of C - A A^H by triangle recursion: only the
    diagonal nb-blocks pay the full-square gemm, the off-diagonal
    blocks are plain exact-shape gemms — executed FLOPs t^2 h + O(nb t h)
    against the t^2 h syrk model, killing the 2x a full-square gemm
    would cost.  Entries above the diagonal pass through untouched
    (callers only consume the lower triangle).  The pallas family fuses
    the diagonal-block triangle mask and the off-diagonal
    multiply-subtract into single kernels at identical shapes/FLOPs."""
    t = C.shape[0]
    if family == "pallas":
        from .pallas import panel_kernels as pk

        if t <= nb:
            return pk.syrk_diag(C, A)
        s = split_point(t)
        C11 = _syrk_lower(C[:s, :s], A[:s], nb, family)
        C21 = pk.gemm_sub(C[s:, :s], A[s:], A[:s])
        C22 = _syrk_lower(C[s:, s:], A[s:], nb, family)
        top = jnp.concatenate([C11, C[:s, s:]], axis=1)
        bot = jnp.concatenate([C21, C22], axis=1)
        return jnp.concatenate([top, bot], axis=0)
    if t <= nb:
        return C - _dot(A, _conj(A).T)
    s = split_point(t)
    C11 = _syrk_lower(C[:s, :s], A[:s], nb)
    C21 = C[s:, :s] - _dot(A[s:], _conj(A[:s]).T)
    C22 = _syrk_lower(C[s:, s:], A[s:], nb)
    top = jnp.concatenate([C11, C[:s, s:]], axis=1)
    bot = jnp.concatenate([C21, C22], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def _chol_rec(G: jnp.ndarray, nb: int, family: str = "recursive") -> jnp.ndarray:
    n = G.shape[0]
    if n <= nb:
        return _base_chol(G, family)
    s = split_point(n)
    L11 = _chol_rec(G[:s, :s], nb, family)
    L21 = _trsm_right_lh(L11, G[s:, :s], nb)
    L22 = _chol_rec(_syrk_lower(G[s:, s:], L21, nb, family), nb, family)
    top = jnp.concatenate([L11, jnp.zeros((s, n - s), G.dtype)], axis=1)
    bot = jnp.concatenate([L21, L22], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def chol_recursive(
    G: jnp.ndarray, nb_switch: int = 256, lookahead: int = 1,
    family: str = "recursive",
) -> jnp.ndarray:
    """Divide & conquer Cholesky factor L (lower) of an SPD (n, n) array.

    Schedule: factor the top-left half, solve the off-diagonal block
    (recursive trsm, vendor solves only at <= nb_switch), subtract the
    exact-shape triangle-recursive syrk, recurse on the trailing half.
    Shapes shrink statically down the halving lattice (n, n/2, n/4, ...)
    so the dominant gemms run at their exact shapes — executed FLOPs stay
    within ~1.3x of the n^3/3 model at n/nb_switch >= 8 (the flat
    ``chol_fori`` runs ~6x; see ``chol_schedule_flops``) from O(log n)
    distinct compile units.

    ``lookahead`` follows the reference potrf convention (lookahead=1 is
    the baseline pipeline): k > 1 peels k-1 eager ``nb_switch``-wide
    panels ahead of the halving split at the top level, each with
    exact-shape trsm + syrk updates (Option.Lookahead wiring).

    ``family`` selects the base-case/update kernels on the same
    lattice: ``"recursive"`` (the jnp strip kernels) or ``"pallas"``
    (the fused panel kernels in ops/pallas/panel_kernels.py).
    """
    n = G.shape[0]
    if n <= nb_switch:
        return jnp.tril(_base_chol(G, family))
    cols = []
    T = G
    k0 = 0
    peel = max(int(lookahead) - 1, 0)
    while peel > 0 and (n - k0) > 2 * nb_switch:
        w = nb_switch
        D = _base_chol(T[:w, :w], family)
        L21 = _trsm_right_lh(D, T[w:, :w], nb_switch)
        T = _syrk_lower(T[w:, w:], L21, nb_switch, family)
        cols.append(
            jnp.concatenate([jnp.zeros((k0, w), G.dtype), D, L21], axis=0)
        )
        k0 += w
        peel -= 1
    Lr = _chol_rec(T, nb_switch, family)
    if not cols:
        return jnp.tril(Lr)
    Lr = jnp.concatenate(
        [jnp.zeros((k0, n - k0), G.dtype), Lr], axis=0
    )
    return jnp.tril(jnp.concatenate(cols + [Lr], axis=1))


# ---------------------------------------------------------------------------
# Rank-k Cholesky up/downdate: L' L'^H = L L^H ± U U^H in O(k n^2),
# the incremental-edit path of the serve factor cache (a rank-k change
# to A re-keys a cached factor without the O(n^3) refactor).
# ---------------------------------------------------------------------------


def chol_rank1_update(
    L: jnp.ndarray, u: jnp.ndarray, downdate: bool = False
) -> jnp.ndarray:
    """Rank-1 update (``downdate=False``: A + u u^H) or downdate
    (A - u u^H) of a lower Cholesky factor, column-at-a-time with
    full-vector masks (one fori_loop, static shapes — O(n^2) work).

    Per column k (lkk = L[k,k] real-positive, sigma = ±1):
    ``t = u[k]/lkk``, ``c = sqrt(1 + sigma |t|^2)``, then
    ``L'[j,k] = (L[j,k] + sigma conj(t) u[j]) / c`` for j > k,
    ``L'[k,k] = c lkk``, and ``u <- (u - t L[:,k]) / c`` (the OLD
    column) — the hyperbolic analogue of the Givens sweep, valid for
    complex Hermitian A since the diagonal stays real.

    A downdate past positive definiteness (1 - |t|^2 <= 0) yields NaN
    columns via the sqrt, the same breakdown contract as
    ``chol_unblocked`` — callers check finiteness and refactor.
    """
    n = L.shape[0]
    sigma = -1.0 if downdate else 1.0
    idx = jnp.arange(n)
    rdt = jnp.finfo(L.dtype).dtype  # real dtype of (possibly complex) L

    def body(k, carry):
        L, u = carry
        lkk = jnp.real(L[k, k])
        t = u[k] / lkk.astype(L.dtype)
        c = jnp.sqrt(
            jnp.asarray(1.0, rdt) + sigma * jnp.real(t * jnp.conj(t))
        )
        colk = L[:, k]
        below = idx > k
        newcol = jnp.where(
            below,
            (colk + (sigma * jnp.conj(t)) * u) / c.astype(L.dtype),
            colk,
        )
        newcol = newcol.at[k].set((c * lkk).astype(L.dtype))
        u = jnp.where(
            below, (u - t * colk) / c.astype(L.dtype),
            jnp.zeros((), L.dtype),
        )
        return L.at[:, k].set(newcol), u

    L, _ = lax.fori_loop(0, n, body, (L, u.astype(L.dtype)))
    return jnp.tril(L)


def chol_update(
    L: jnp.ndarray, U: jnp.ndarray, downdate: bool = False
) -> jnp.ndarray:
    """Rank-k Cholesky up/downdate: ``L' L'^H = L L^H ± U U^H`` with U
    of shape (n, k) or (n,) — k sequential rank-1 sweeps (each column's
    sweep transforms only L; the columns are independent updates of the
    running factor).  O(k n^2) total; ``downdate`` is static."""
    U2 = U if U.ndim == 2 else U[:, None]
    n, k = U2.shape

    def body(i, L):
        u = lax.dynamic_slice(U2, (0, i), (n, 1))[:, 0]
        return chol_rank1_update(L, u, downdate)

    return lax.fori_loop(0, k, body, L)


# ---------------------------------------------------------------------------
# FLOP accounting.  Pure-python structural mirrors of the schedules
# above: every gemm/trsm/base-case the traced program will execute is
# counted at the shape it executes at (masked full-shape ops count at
# full shape — that IS the waste being measured).  The drivers feed
# these into the ``factor.flops_model`` / ``factor.flops_exec`` metric
# counters so the waste ratio is observable per routine; the ``units``
# set of distinct (op, shape) tuples bounds the schedule's compile-unit
# count (the recursive paths stay O(log n) vs the data-dependent-free
# but FLOP-hungry flat loops' O(1)).
# ---------------------------------------------------------------------------


def _chol_unblocked_flops(b: int, ib: int = 16):
    if b % ib != 0:
        ib = 8 if b % 8 == 0 else 1
    nsteps = max(b // ib, 1)
    # per strip: one full-shape rank-ib trailing gemm + ib masked rank-1
    # micro-updates on the (b, ib) strip
    return nsteps * (2.0 * b * ib * b + 2.0 * b * ib * ib), {
        ("chol_base", b)
    }


def _chol_base_flops(b: int, family: str = "recursive"):
    if family == "pallas":
        # fused column loop: b masked rank-1 trailing updates on the
        # (b, b) block — no per-strip overhead, strictly below the
        # ib-strip count
        return 2.0 * float(b) ** 3, {("pallas_chol_base", b)}
    return _chol_unblocked_flops(b)


def _trsm_flops(t: int, h: int, nb: int):
    """Executed FLOPs of _trsm_right_lh / the unit-lower left variant in
    lu_kernels (identical split structure): exactly the t h^2 model."""
    if h <= nb:
        return float(t) * h * h, {("trsm", h, t)}
    s = split_point(h)
    f1, u1 = _trsm_flops(t, s, nb)
    f2, u2 = _trsm_flops(t, h - s, nb)
    return f1 + f2 + 2.0 * t * s * (h - s), u1 | u2 | {("gemm", t, s, h - s)}


def _syrk_flops(t: int, h: int, nb: int, family: str = "recursive"):
    diag = "pallas_syrk" if family == "pallas" else "gemm"
    offd = "pallas_gemm" if family == "pallas" else "gemm"
    if t <= nb:
        return 2.0 * t * t * h, {(diag, t, h, t)}
    s = split_point(t)
    f1, u1 = _syrk_flops(s, h, nb, family)
    f2, u2 = _syrk_flops(t - s, h, nb, family)
    return f1 + f2 + 2.0 * (t - s) * h * s, u1 | u2 | {
        (offd, t - s, h, s)
    }


def _chol_rec_flops(n: int, nb: int, family: str = "recursive"):
    if n <= nb:
        return _chol_base_flops(n, family)
    s = split_point(n)
    f1, u1 = _chol_rec_flops(s, nb, family)
    ft, ut = _trsm_flops(n - s, s, nb)
    fs, us = _syrk_flops(n - s, s, nb, family)
    f2, u2 = _chol_rec_flops(n - s, nb, family)
    return f1 + ft + fs + f2, u1 | ut | us | u2


def _chol_panels_flops(n: int, nb: int):
    """_chol_panels / blocked_potrf coarse level: exact shapes but the
    explicit panel inverse (MAGMA recipe) and full-square trailing gemm
    both cost real FLOPs."""
    fl, units = 0.0, set()
    k0 = 0
    while k0 < n:
        w = min(nb, n - k0)
        fb, ub = _chol_unblocked_flops(w)
        fl += fb
        units |= ub
        rest = n - k0 - w
        if rest > 0:
            fl += w**3 / 2.0  # Dinv trsm vs identity
            fl += 2.0 * rest * w * w  # L21 gemm
            fl += 2.0 * rest * rest * w  # full-square trailing gemm
            units |= {("trsm", w, w), ("gemm", rest, w, w),
                      ("gemm", rest, w, rest)}
        k0 += w
    return fl, units


def _blocked_potrf_flops(n: int, nb: int = 512, coarse_panels: int = 4):
    if n <= 256:
        return _chol_unblocked_flops(n)
    nb = min(nb, n)
    if n % nb != 0:
        nb = 256 if n % 256 == 0 else 128
    nt = n // nb
    if nt <= coarse_panels:
        return _chol_panels_flops(n, nb)
    NB = nb * (-(-nt // coarse_panels))
    fl, units = 0.0, set()
    k0 = 0
    while k0 < n:
        w = min(NB, n - k0)
        fd, ud = _blocked_potrf_flops(w, nb, coarse_panels)
        fl += fd
        units |= ud
        rest = n - k0 - w
        if rest > 0:
            fl += w**3 / 2.0 + 2.0 * rest * w * w + 2.0 * rest * rest * w
            units |= {("trsm", w, w), ("gemm", rest, w, w),
                      ("gemm", rest, w, rest)}
        k0 += w
    return fl, units


def _chol_fori_flops(n: int, nb: int):
    if n == nb:
        return _chol_unblocked_flops(n)
    steps = n // nb
    fb, ub = _chol_unblocked_flops(nb)
    # per step: full-height trsm + full (n, nb) x (nb, n) trailing gemm
    per = float(n) * nb * nb + 2.0 * n * nb * n
    return steps * (fb + per), ub | {("trsm", nb, n), ("gemm", n, nb, n)}


def chol_schedule_flops(
    n: int, nb: int = 512, schedule: str = "recursive",
    nb_switch: int = 256, lookahead: int = 1,
) -> dict:
    """(model, exec, units) FLOP accounting for one Cholesky of size n
    under the given schedule (after the dispatcher's pad to a multiple
    of 128).  ``model`` is the textbook n^3/3; ``exec`` counts what the
    traced program actually issues; ``units`` is the set of distinct
    (op, shape) compile units in the schedule."""
    npad = -(-n // 128) * 128
    model = n**3 / 3.0
    if schedule == "vendor":
        return {"model": model, "exec": float(model),
                "units": {("vendor_potrf", n)}}
    if schedule == "flat":
        ex, units = _blocked_potrf_flops(npad, nb)
    elif schedule == "flat_fori":
        ex, units = _chol_fori_flops(npad, nb if npad % nb == 0 else 128)
    else:
        fam = "pallas" if schedule == "pallas" else "recursive"
        ex, units = 0.0, set()
        k0, peel = 0, max(int(lookahead) - 1, 0)
        if npad <= nb_switch:
            ex, units = _chol_base_flops(npad, fam)
        else:
            while peel > 0 and (npad - k0) > 2 * nb_switch:
                w = nb_switch
                fb, ub = _chol_base_flops(w, fam)
                ft, ut = _trsm_flops(npad - k0 - w, w, nb_switch)
                fs, us = _syrk_flops(npad - k0 - w, w, nb_switch, fam)
                ex += fb + ft + fs
                units |= ub | ut | us
                k0 += w
                peel -= 1
            fr, ur = _chol_rec_flops(npad - k0, nb_switch, fam)
            ex += fr
            units |= ur
    return {"model": model, "exec": ex, "units": units}


def resolve_schedule(n: int, schedule: str = "auto") -> str:
    """Resolve an ``auto`` schedule request against the backend and
    size: vendor LAPACK on CPU, the pallas panel-kernel family above
    the crossover on accelerators, the flat/blocked schedule below it.
    Explicit ``flat``/``recursive``/``pallas`` are honored on every
    backend (tests exercise the native schedules on CPU — pallas runs
    its kernels in interpret mode there)."""
    if schedule in ("flat", "recursive", "pallas"):
        return schedule
    if jax.default_backend() == "cpu":
        return "vendor"
    return "pallas" if n >= RECURSIVE_MIN_N else "flat"


def cholesky(
    G: jnp.ndarray,
    nb: int = 512,
    schedule: str = "auto",
    nb_switch: int = 256,
    lookahead: int = 1,
) -> jnp.ndarray:
    """Schedule-dispatched Cholesky: vendor kernel on CPU under ``auto``
    (LAPACK — already optimal), native blocked (``flat``) or divide &
    conquer (``recursive``, crossover ``nb_switch``) schedule otherwise.

    Accepts any n: pads to a multiple of 128 with a unit-diagonal
    splice (chol of blockdiag(A, I) is blockdiag(L, I)) and slices the
    factor back out."""
    n = G.shape[0]
    route = resolve_schedule(n, schedule)
    if route == "vendor":
        return lax.linalg.cholesky(G)
    npad = -(-n // 128) * 128
    if npad != n:
        # pad first even at small n so chol_unblocked keeps its ib=16
        # strips (odd n would degrade it to column-at-a-time)
        Gp = jnp.pad(G, ((0, npad - n), (0, npad - n)))
        idx = jnp.arange(npad)
        splice = jnp.where(idx >= n, 1.0, 0.0).astype(G.dtype)
        Gp = Gp.at[idx, idx].add(splice)
        if route in ("recursive", "pallas"):
            return chol_recursive(Gp, nb_switch, lookahead, route)[:n, :n]
        return blocked_potrf(Gp, nb)[:n, :n]
    if route in ("recursive", "pallas"):
        return chol_recursive(G, nb_switch, lookahead, route)
    return blocked_potrf(G, nb)
