"""Native blocked Cholesky kernels for TPU.

The vendor ``lax.linalg.cholesky`` lowers to a near-sequential schedule on
this TPU toolchain (measured ~1-5 GF/s at panel sizes, 52 GF/s at n=4096,
against a ~2.6 TF/s f64 matmul rate on the same chip), so the driver-level
potrf was stuck at ~3.5% of gemm speed.  These kernels rebuild the
reference's blocked right-looking schedule (reference: src/potrf.cc:84-209
— panel factor, trsm, trailing herk with the trailing gemm dominating)
out of the ops that ARE fast here:

* ``chol_unblocked``  — column-at-a-time fori_loop Cholesky of one
  nb x nb diagonal block.  The masked rank-1 update is a VPU
  elementwise op (measured ~6 us/column at nb=512), two orders faster
  than the vendor kernel's schedule.
* ``chol_fori``       — single-level blocked loop: one ``lax.fori_loop``
  over nb-wide panels with full-height masked trsm + trailing gemm.
  A compile-lean alternative (one compiled shape regardless of n; the
  default schedule below is ~20% faster but compiles one shape set per
  panel count) — kept off the default path, available to callers that
  factor many distinct sizes.
* ``blocked_potrf``   — two-level schedule for large n: at most
  ``coarse_panels`` Python-unrolled panels of width NB (exact shrinking
  shapes, so the trailing update is a full-rate gemm), each diagonal
  block factored by recursing into ``_chol_panels``/``chol_unblocked``,
  the panel solve done MAGMA-style as an explicit small triangular
  inverse + gemm so the bulk work rides the MXU instead of the slow
  vendor trsm path.

Everything is static-shape; distinct XLA shapes per n are bounded by
O(coarse_panels) to keep compile time in check (measured ~25 s per
distinct f64 trsm shape, ~10 s per gemm shape on this toolchain).

Used by drivers/chol.py for the single-chip (global-path) potrf on
non-CPU backends; the CPU backend keeps the vendor (LAPACK) kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# All matmuls in these kernels run at HIGHEST precision: the TPU default
# for the f64 emulation drops to ~f32-grade accumulation (measured 1e-8
# Cholesky residual vs 1e-12 with HIGHEST), and f32 inputs would drop to
# one bf16 pass (internal/precision.py's policy, applied here directly
# since these kernels are used inside jit where the context manager at
# call sites may not be active).
from ..internal.precision import hdot as _dot


def _conj(x):
    return jnp.conj(x) if jnp.iscomplexobj(x) else x


def chol_unblocked(a: jnp.ndarray, ib: int = 16) -> jnp.ndarray:
    """Cholesky of one (b, b) block: L L^H = a, b a multiple of ib.

    fori_loop over b//ib column strips: the ib columns of a strip are
    eliminated by an unrolled micro-loop touching only the (b, ib)
    strip, then one VPU rank-ib update fixes the trailing columns.
    This keeps the per-iteration memory traffic at O(b*ib) for the
    micro-steps and O(b^2) only once per strip — the column-at-a-time
    variant's O(b^2) *per column* made it bandwidth-bound (~80 us per
    column at b=512 on the chip).

    Non-SPD input yields NaN columns (sqrt of a negative pivot), which
    the caller's info check detects — same contract as the vendor
    kernel.
    """
    b = a.shape[0]
    if b % ib != 0:
        ib = 8 if b % 8 == 0 else 1
    idx = jnp.arange(b)
    nsteps = b // ib

    def body(i, a):
        j0 = i * ib
        P = lax.dynamic_slice(a, (0, j0), (b, ib))
        for c in range(ib):
            jc = j0 + c
            pj = jnp.sqrt(jnp.real(lax.dynamic_slice(P, (jc, c), (1, 1))[0, 0]))
            pj = pj.astype(a.dtype)
            col = jnp.where(idx > jc, P[:, c] / pj, jnp.zeros((), a.dtype))
            P = P.at[:, c].set(jnp.where(idx == jc, pj, col))
            if c + 1 < ib:
                # multipliers for the strip's remaining columns are the
                # scaled L entries at the strip's own pivot rows
                lrow = lax.dynamic_slice(P, (j0, c), (ib, 1))[:, 0]
                lrow = jnp.where(jnp.arange(ib) > c, _conj(lrow), 0)
                P = P - jnp.outer(col, lrow)
        a = lax.dynamic_update_slice(a, P, (0, j0))
        # rank-ib trailing update, restricted to columns >= j0+ib via a
        # row mask on the second operand (upper-triangle junk is dropped
        # by the final tril)
        Q = jnp.where((idx >= j0 + ib)[:, None], P, jnp.zeros((), a.dtype))
        return a - _dot(P, _conj(Q).T)

    return jnp.tril(lax.fori_loop(0, nsteps, body, a))


def chol_fori(G: jnp.ndarray, nb: int = 512) -> jnp.ndarray:
    """Single-level blocked Cholesky of (n, n), n a multiple of nb.

    One fori_loop over the n//nb panels; every step runs at full array
    shape with row masks (one compile unit).  The trailing update is a
    (n, nb) x (nb, n) gemm — within ~2x of the exact-shape FLOP count,
    the price of the single compiled shape.
    """
    n = G.shape[0]
    if n == nb:
        return chol_unblocked(G)
    assert n % nb == 0, "chol_fori requires n % nb == 0"
    rows = jnp.arange(n)

    def step(k, G):
        Akk = lax.dynamic_slice(G, (k * nb, k * nb), (nb, nb))
        Lkk = chol_unblocked(Akk)
        col = lax.dynamic_slice(G, (0, k * nb), (n, nb))
        sol = lax.linalg.triangular_solve(
            Lkk, col, left_side=False, lower=True, transpose_a=True,
            conjugate_a=jnp.iscomplexobj(G),
        )
        below = (rows >= (k + 1) * nb)[:, None]
        Lpan = jnp.where(below, sol, jnp.zeros((), G.dtype))
        diag_rows = ((rows >= k * nb) & (rows < (k + 1) * nb))[:, None]
        Lkk_tall = jnp.pad(Lkk, ((0, n - nb), (0, 0)))
        Lkk_placed = jnp.where(diag_rows, jnp.roll(Lkk_tall, k * nb, axis=0), 0)
        above = (rows < k * nb)[:, None]
        newcol = jnp.where(above, jnp.zeros((), G.dtype), Lkk_placed + Lpan)
        G = lax.dynamic_update_slice(G, newcol, (0, k * nb))
        return G - _dot(Lpan, _conj(Lpan).T)

    return jnp.tril(lax.fori_loop(0, n // nb, step, G))


def _chol_panels(G: jnp.ndarray, nb: int) -> jnp.ndarray:
    """Python-unrolled blocked Cholesky of (n, n), n a multiple of nb,
    intended for n/nb <= ~4 panels.

    Per panel: chol_unblocked diag, ONE full-height trsm (a single XLA
    shape reused by every panel — each distinct f64 trsm shape costs
    ~15-25 s of compile on this toolchain), then exact-shape trailing
    syrk (full MXU rate where the FLOPs are)."""
    n = G.shape[0]
    cplx = jnp.iscomplexobj(G)
    cols = []
    T = G
    k0 = 0
    while k0 < n:
        w = min(nb, n - k0)
        D = chol_unblocked(T[:w, :w])
        rest = n - k0 - w
        if rest > 0:
            # explicit (w, w) inverse + MXU gemm instead of a
            # full-height vendor trsm: the vendor triangular_solve with
            # a fat RHS is schedule-bound on this toolchain (~10-25 ms
            # per panel) while the small trsm + gemm ride the MXU —
            # the same MAGMA recipe blocked_potrf uses at the coarse
            # level
            Dinv = lax.linalg.triangular_solve(
                D, jnp.eye(w, dtype=G.dtype), left_side=True, lower=True
            )
            L21 = _dot(T[w:, :w], _conj(Dinv).T)
            T = T[w:, w:] - _dot(L21, _conj(L21).T)
            colk = jnp.concatenate(
                [jnp.zeros((k0, w), G.dtype), D, L21], axis=0
            )
        else:
            colk = jnp.concatenate([jnp.zeros((k0, w), G.dtype), D], axis=0)
        cols.append(colk)
        k0 += w
    return jnp.concatenate(cols, axis=1)


def blocked_potrf(
    G: jnp.ndarray, nb: int = 512, coarse_panels: int = 4
) -> jnp.ndarray:
    """Blocked Cholesky factor L (lower) of an SPD (n, n) array.

    n must be a multiple of 128 (callers pad with a unit-diagonal
    splice).  Schedule (reference: src/potrf.cc:84-209, with the
    lookahead pipeline replaced by XLA's own overlap inside one
    compiled program):

      for each of <= coarse_panels column panels of width NB:
        D    = recursive factor of T[:NB,:NB]      # exact-shape panels
        Dinv = trsm(D, I)                          # one small trsm
        L21  = T[NB:,:NB] @ Dinv^H                 # MXU gemm
        T    = T[NB:,NB:] - L21 @ L21^H            # MXU gemm (dominant)

    Exact shrinking shapes per panel (full-rate gemms); the explicit
    panel inverse trades one (NB,NB) trsm for MXU gemms, the MAGMA
    recipe.  Distinct XLA shapes stay O(coarse_panels + recursion
    depth): the diag-block shapes repeat across panels.
    """
    n = G.shape[0]
    if n <= 256:
        return chol_unblocked(G)
    nb = min(nb, n)
    if n % nb != 0:
        nb = 256 if n % 256 == 0 else 128
    assert n % nb == 0, f"blocked_potrf: n={n} not a multiple of 128"
    nt = n // nb
    if nt <= coarse_panels:
        return _chol_panels(G, nb)

    NB = nb * (-(-nt // coarse_panels))
    cols = []
    T = G
    k0 = 0
    eyeNB = None
    while k0 < n:
        w = min(NB, n - k0)
        D = blocked_potrf(T[:w, :w], nb, coarse_panels)
        rest = n - k0 - w
        if rest > 0:
            if eyeNB is None or eyeNB.shape[0] != w:
                eyeNB = jnp.eye(w, dtype=G.dtype)
            Dinv = lax.linalg.triangular_solve(
                D, eyeNB, left_side=True, lower=True
            )
            L21 = _dot(T[w:, :w], _conj(Dinv).T)
            T = T[w:, w:] - _dot(L21, _conj(L21).T)
            colk = jnp.concatenate(
                [jnp.zeros((k0, w), G.dtype), D, L21], axis=0
            )
        else:
            colk = jnp.concatenate([jnp.zeros((k0, w), G.dtype), D], axis=0)
        cols.append(colk)
        k0 += w
    return jnp.concatenate(cols, axis=1)


def tri_inv_blocked(L: jnp.ndarray, nb: int = 512) -> jnp.ndarray:
    """Explicit inverse of a lower-triangular matrix by recursive
    2x2 blocking: inv([[A,0],[B,C]]) = [[inv(A),0],[-inv(C) B inv(A),
    inv(C)]] — two half-size inverses + two MXU gemms per level; the
    vendor triangular_solve only ever sees <= nb-sized blocks (the
    full-size vendor trsm is schedule-bound on this toolchain, the
    same finding as _chol_panels')."""
    n = L.shape[0]
    if n <= nb:
        return lax.linalg.triangular_solve(
            L, jnp.eye(n, dtype=L.dtype), left_side=True, lower=True
        )
    h = max(((n + 1) // 2 + 127) // 128 * 128, 128)
    h = min(h, n - 1)
    A = L[:h, :h]
    B = L[h:, :h]
    C = L[h:, h:]
    Ai = tri_inv_blocked(A, nb)
    Ci = tri_inv_blocked(C, nb)
    lowblk = -_dot(Ci, _dot(B, Ai))
    top = jnp.concatenate([Ai, jnp.zeros((h, n - h), L.dtype)], axis=1)
    bot = jnp.concatenate([lowblk, Ci], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def cholesky(G: jnp.ndarray, nb: int = 512) -> jnp.ndarray:
    """Platform-dispatched Cholesky: vendor kernel on CPU (LAPACK —
    already optimal), native blocked schedule on accelerators.

    Accepts any n: pads to a multiple of 128 with a unit-diagonal
    splice (chol of blockdiag(A, I) is blockdiag(L, I)) and slices the
    factor back out."""
    if jax.default_backend() == "cpu":
        return lax.linalg.cholesky(G)
    n = G.shape[0]
    npad = -(-n // 128) * 128
    if npad != n:
        # pad first even at small n so chol_unblocked keeps its ib=16
        # strips (odd n would degrade it to column-at-a-time)
        Gp = jnp.pad(G, ((0, npad - n), (0, npad - n)))
        idx = jnp.arange(npad)
        splice = jnp.where(idx >= n, 1.0, 0.0).astype(G.dtype)
        Gp = Gp.at[idx, idx].add(splice)
        return blocked_potrf(Gp, nb)[:n, :n]
    return blocked_potrf(G, nb)
