"""Native blocked LU kernels (no vendor LuDecomposition).

The XLA TPU backend implements lax.linalg.lu only for f32/c64; the
reference's own blocked right-looking getrf (reference: src/getrf.cc:85-214
— panel factor, pivot broadcast, row swaps, trailing update) is the model
for the f64/c128 path here.  Everything is static-shape fori_loop code:

* ``panel_lu``     — unblocked partial-pivot LU of one (M, nb) panel,
  the analogue of the reference's threaded panel kernel
  (Tile_getrf.hh:164-452) with the per-column argmax done by lax.argmax
  over the whole gathered panel instead of a thread/MPI reduction tree.
* ``blocked_getrf`` — right-looking blocked LU over the padded global
  array: per step the panel is rolled to the top (static shapes), factored
  redundantly, row swaps applied as one gather, then one triangular solve
  + one matmul for the trailing update (getrf.cc:183-214's permuteRows +
  trsm + gemm fused into three XLA ops).

Used by drivers/lu.py whenever the platform lacks a vendor LU for the
dtype, and by parallel/spmd_lu.py for the in-loop panel factor.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def panel_lu(
    panel: jnp.ndarray, pivot: bool = True, act: int | None = None
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Unblocked LU of an (M, nb) panel, partial pivoting by default.

    Returns (lu, perm) with lu holding unit-lower L below the diagonal and
    U on/above, and perm the forward permutation: lu rows correspond to
    panel[perm].  Matches lax.linalg.lu's (lu, _, permutation) contract.
    Zero pivot columns produce zero L columns (flagged by the caller's
    info check), not NaNs.  pivot=False runs the no-exchange elimination
    (used after tournament pivoting has already ordered the rows).

    ``act`` (static) restricts the pivot search to rows < act: the
    recursive schedule pads panels with zero rows up to a canonical
    height so distinct compiled shapes stay O(log), and those pad rows
    must never be chosen as pivots (they stay exact fixed points of
    perm).
    """
    M, nb = panel.shape
    rows = jnp.arange(M)

    def body(j, carry):
        a, perm = carry
        col = a[:, j]
        if pivot:
            elig = rows >= j if act is None else (rows >= j) & (rows < act)
            mag = jnp.where(elig, jnp.abs(col), -jnp.inf)
            piv = jnp.argmax(mag)
        else:
            piv = j
        # swap rows j <-> piv (gather-free: two dynamic row updates)
        rj = a[j]
        rp = a[piv]
        a = a.at[j].set(rp).at[piv].set(rj)
        pj = perm[j]
        pp = perm[piv]
        perm = perm.at[j].set(pp).at[piv].set(pj)
        pv = a[j, j]
        safe = jnp.where(pv == 0, jnp.ones_like(pv), pv)
        l = jnp.where((rows > j) & (pv != 0), a[:, j] / safe, jnp.zeros(M, a.dtype))
        a = a.at[:, j].set(jnp.where(rows > j, l, a[:, j]))
        urow = jnp.where(jnp.arange(nb) > j, a[j], jnp.zeros(nb, a.dtype))
        return a - jnp.outer(l, urow), perm

    perm0 = jnp.arange(M, dtype=jnp.int32)
    lu, perm = lax.fori_loop(0, min(M, nb), body, (panel, perm0))
    return lu, perm


def blocked_getrf(
    Gp: jnp.ndarray, nb: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked right-looking LU with partial pivoting of a padded array.

    Gp: (Mp, Np) with Mp, Np multiples of nb and the padding diagonal
    spliced to 1 (layout.eye_splice semantics).  Returns (LU, perm) with
    perm the net forward row permutation: LU = (L\\U) of Gp[perm].
    Reference: src/getrf.cc:85-214.

    Every one of the min(Mp, Np)/nb steps runs the panel factor, row
    swaps, trsm row and trailing gemm at the FULL padded array shape
    (one compile unit): the trailing gemms alone execute
    2 Mp Np min(Mp, Np) FLOPs — ~3x the square-shape 2n^3/3 model, plus
    the full-shape panel/trsm terms on top (see
    ``getrf_schedule_flops``).  Large-n callers should prefer
    ``getrf_recursive``: exact halving-lattice shapes, near-model
    FLOPs, O(log n) compile units.
    """
    Mp, Np = Gp.shape
    kt = min(Mp, Np) // nb
    rows = jnp.arange(Mp)
    cols = jnp.arange(Np)

    def step(k, carry):
        G, perm = carry
        # -- panel: roll active rows to the top, factor ----------------
        col = lax.dynamic_slice(G, (0, k * nb), (Mp, nb))
        colr = jnp.roll(col, -k * nb, axis=0)
        active_len = Mp - k * nb
        colr = jnp.where((rows < active_len)[:, None], colr, jnp.zeros_like(colr))
        lu_pan, piv = panel_lu(colr)
        # step permutation in global row space (identity above the panel)
        act = rows - k * nb
        mapped = piv[jnp.clip(act, 0, Mp - 1)] + k * nb
        step_perm = jnp.where(act >= 0, mapped, rows)
        # -- row exchange across the whole matrix ----------------------
        G = G[step_perm]
        perm = perm[step_perm]
        # -- write the factored panel back (rows >= k*nb) ---------------
        lu_nat = jnp.roll(lu_pan, k * nb, axis=0)
        col_cur = lax.dynamic_slice(G, (0, k * nb), (Mp, nb))
        col_new = jnp.where((rows >= k * nb)[:, None], lu_nat, col_cur)
        G = lax.dynamic_update_slice(G, col_new, (0, k * nb))
        # -- U row: Lkk^-1 A(k, j>k) ------------------------------------
        Lkk = jnp.tril(lu_pan[:nb], -1) + jnp.eye(nb, dtype=G.dtype)
        row = lax.dynamic_slice(G, (k * nb, 0), (nb, Np))
        rs = lax.linalg.triangular_solve(
            Lkk, row, left_side=True, lower=True, unit_diagonal=True
        )
        row_new = jnp.where((cols >= (k + 1) * nb)[None, :], rs, row)
        G = lax.dynamic_update_slice(G, row_new, (k * nb, 0))
        # -- trailing update --------------------------------------------
        Lpan = jnp.where((rows >= (k + 1) * nb)[:, None], col_new, 0)
        Urow = jnp.where((cols >= (k + 1) * nb)[None, :], row_new, 0)
        return G - Lpan @ Urow, perm

    perm0 = jnp.arange(Mp, dtype=jnp.int32)
    return lax.fori_loop(0, kt, step, (Gp, perm0))


def tournament_pivots(
    panel: jnp.ndarray, nb: int, chunk: int
) -> jnp.ndarray:
    """Tournament (CALU) pivot selection on an (M, nb) panel (reference:
    src/getrf_tntpiv.cc:1-498, internal_getrf_tntpiv.cc): every `chunk`
    rows elect nb candidate pivot rows with a local partial-pivot LU, and
    winners advance up a binary reduction tree — one communication-free
    pass per level, the LU variant built for static schedules.

    Returns the nb winning row indices (into panel), in pivot order.
    """
    M, nbp = panel.shape
    assert nbp == nb and chunk >= nb and M % chunk == 0
    K = M // chunk
    chunks = panel.reshape(K, chunk, nb)
    base = jnp.arange(K)[:, None] * chunk

    def elect(ch):
        _, perm = panel_lu(ch)
        return ch[perm[:nb]], perm[:nb]

    cands, local_idx = jax.vmap(elect)(chunks)  # (K, nb, nb), (K, nb)
    idxs = base + local_idx

    while K > 1:
        if K % 2 == 1:  # odd: last bracket gets a zero-rows bye
            cands = jnp.concatenate(
                [cands, jnp.zeros((1, nb, nb), cands.dtype)]
            )
            idxs = jnp.concatenate([idxs, jnp.full((1, nb), M, idxs.dtype)])
            K += 1
        merged = cands.reshape(K // 2, 2 * nb, nb)
        midx = idxs.reshape(K // 2, 2 * nb)

        def play(ch, ix):
            _, perm = panel_lu(ch)
            return ch[perm[:nb]], ix[perm[:nb]]

        cands, idxs = jax.vmap(play)(merged, midx)
        K //= 2
    return idxs[0]


def blocked_getrf_tntpiv(
    Gp: jnp.ndarray, nb: int, chunk: int = 0
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked LU with tournament pivoting (reference: getrf_tntpiv.cc,
    MethodLU.CALU).  Same right-looking structure as blocked_getrf; the
    panel's pivot rows come from the communication-free tournament, after
    which the panel eliminates without further exchanges.
    """
    Mp, Np = Gp.shape
    kt = min(Mp, Np) // nb
    chunk = chunk or max(4 * nb, nb)
    # pad rows so every (rolled) panel splits into whole chunks
    Mc = -(-Mp // chunk) * chunk
    Gw = jnp.pad(Gp, ((0, Mc - Mp), (0, 0)))
    rows = jnp.arange(Mc)
    cols = jnp.arange(Np)

    def step(k, carry):
        G, perm = carry
        col = lax.dynamic_slice(G, (0, k * nb), (Mc, nb))
        colr = jnp.roll(col, -k * nb, axis=0)
        active_len = Mp - k * nb
        colr = jnp.where((rows < active_len)[:, None], colr, jnp.zeros_like(colr))
        # -- tournament pivot selection over the active panel ----------
        win = tournament_pivots(colr, nb, chunk)  # rows in active frame
        # step permutation: winners to the top (in order), others keep
        # their relative order behind them
        is_win = jnp.zeros((Mc,), jnp.int32).at[win].set(1, mode="drop")
        win_pos = jnp.zeros((Mc,), jnp.int32).at[win].set(
            jnp.arange(nb, dtype=jnp.int32), mode="drop"
        )
        rest_rank = jnp.cumsum(1 - is_win) - 1
        key = jnp.where(is_win == 1, win_pos, nb + rest_rank)
        step_perm_act = jnp.argsort(key)  # active-frame permutation
        mapped = jnp.where(
            rows - k * nb >= 0,
            step_perm_act[jnp.clip(rows - k * nb, 0, Mc - 1)] + k * nb,
            rows,
        )
        step_perm = jnp.where(mapped < Mc, mapped, mapped - Mc)
        G = G[step_perm]
        perm = perm[step_perm]
        # -- panel factor, no further pivoting -------------------------
        col2 = lax.dynamic_slice(G, (0, k * nb), (Mc, nb))
        colr2 = jnp.roll(col2, -k * nb, axis=0)
        colr2 = jnp.where(
            (rows < active_len)[:, None], colr2, jnp.zeros_like(colr2)
        )
        lu_pan, _ = panel_lu(colr2, pivot=False)
        lu_nat = jnp.roll(lu_pan, k * nb, axis=0)
        col_cur = lax.dynamic_slice(G, (0, k * nb), (Mc, nb))
        col_new = jnp.where((rows >= k * nb)[:, None], lu_nat, col_cur)
        G = lax.dynamic_update_slice(G, col_new, (0, k * nb))
        # -- U row + trailing update (as blocked_getrf) ----------------
        Lkk = jnp.tril(lu_pan[:nb], -1) + jnp.eye(nb, dtype=G.dtype)
        row = lax.dynamic_slice(G, (k * nb, 0), (nb, Np))
        rs = lax.linalg.triangular_solve(
            Lkk, row, left_side=True, lower=True, unit_diagonal=True
        )
        row_new = jnp.where((cols >= (k + 1) * nb)[None, :], rs, row)
        G = lax.dynamic_update_slice(G, row_new, (k * nb, 0))
        Lpan = jnp.where((rows >= (k + 1) * nb)[:, None], col_new, 0)
        Urow = jnp.where((cols >= (k + 1) * nb)[None, :], row_new, 0)
        return G - Lpan @ Urow, perm

    perm0 = jnp.arange(Mc, dtype=jnp.int32)
    G, perm = lax.fori_loop(0, kt, step, (Gw, perm0))
    return G[:Mp], perm[:Mp]


# ---------------------------------------------------------------------------
# Recursive (divide & conquer) schedule: exact shapes on the halving
# lattice, pivoted, with permutation composition across the halves
# (Toledo-style recursive LU).  The flat blocked_getrf above pays ~3x
# the model FLOPs for its single compiled shape; the recursion factors
# the left column half at its exact (shrinking) height, solves/updates
# the right half at exact shapes, and composes the half permutations.
# ---------------------------------------------------------------------------

from .chol_kernels import RECURSIVE_MIN_N, _lat_height, split_point


def _trsm_left_unit(L: jnp.ndarray, B: jnp.ndarray, nb: int) -> jnp.ndarray:
    """L X = B with L unit-lower (diagonal implicit — only the strict
    lower triangle of L is read), by recursive 2x2 splitting: vendor
    solves only at <= nb diagonal blocks, exact-shape MXU gemms carry
    the bulk at exactly the model FLOP count (r h^2)."""
    h = L.shape[0]
    if h <= nb:
        return lax.linalg.triangular_solve(
            L, B, left_side=True, lower=True, unit_diagonal=True
        )
    s = split_point(h)
    B1 = _trsm_left_unit(L[:s, :s], B[:s], nb)
    B2 = _trsm_left_unit(
        L[s:, s:], B[s:] - L[s:, :s] @ B1, nb
    )
    return jnp.concatenate([B1, B2], axis=0)


def getrf_recursive(
    G: jnp.ndarray, nb_switch: int = 256, lookahead: int = 1,
    family: str = "recursive",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Recursive blocked LU with partial pivoting of an (m, n) array,
    m >= n.  Returns (LU, perm): LU = (L\\U) of G[perm], the
    blocked_getrf contract.

    Schedule: factor the left n1 = split_point(n) columns recursively
    (exact full-height panels), permute the right half by the left
    half's pivots, solve U12 with the recursive unit-lower trsm, one
    exact-shape Schur gemm, recurse on the trailing (m-n1, n-n1) block,
    then compose the two half permutations — the pivot order matches
    LAPACK partial pivoting exactly (the base case is ``panel_lu``).

    ``lookahead`` follows the reference getrf convention (1 = baseline
    pipeline): k > 1 peels k-1 eager nb_switch-wide panels ahead of the
    halving split at the top level (Option.Lookahead wiring).

    ``family`` selects the panel base case: ``"recursive"`` (the jnp
    fori_loop ``panel_lu``) or ``"pallas"`` (the fused in-register
    pivot-search kernel — identical arithmetic, identical pivot order).
    """
    m, n = G.shape
    assert m >= n, f"getrf_recursive requires m >= n, got {(m, n)}"
    if family == "pallas":
        from .pallas import panel_kernels as pk

        _panel = pk.panel_lu
    else:
        _panel = panel_lu

    def canon(X, act):
        """Snap X's height to the canonical ``_lat_height(act)``:
        truncate (rows >= act are exact zeros by construction) or
        zero-pad.  Returns (X', restore) with restore mapping a child
        (LU, perm) over X' back to X's frame — safe because rows >= act
        are never pivoted, hence fixed points of the child perm."""
        M = X.shape[0]
        Mc = _lat_height(act)
        if Mc == M:
            return X, lambda LU, p: (LU, p)
        if Mc < M:  # drop all-zero tail rows for the child

            def restore(LU, p):
                LU = jnp.concatenate(
                    [LU, jnp.zeros((M - Mc, LU.shape[1]), LU.dtype)]
                )
                return LU, jnp.concatenate(
                    [p, jnp.arange(Mc, M, dtype=p.dtype)]
                )

            return X[:Mc], restore

        def restore(LU, p):  # Mc > M: child's pad rows are fixed points
            return LU[:M], p[:M]

        return jnp.pad(X, ((0, Mc - M), (0, 0))), restore

    def rec(G, act):
        # invariant: rows >= act of G are exact zeros (never pivotable)
        M, n = G.shape
        if n <= nb_switch:
            return _panel(G, act=None if act >= M else act)
        s = split_point(n)
        LU1, p1 = rec(G[:, :s], act)
        R = G[:, s:][p1]
        U12 = _trsm_left_unit(LU1[:s, :s], R[:s], nb_switch)
        S2, restore = canon(
            jnp.concatenate([LU1[s:, :s], R[s:]], axis=1), act - s
        )
        S = S2[:, s:] - S2[:, :s] @ U12
        LU2, p2 = rec(S, act - s)
        LU2, p2 = restore(LU2, p2)
        top = jnp.concatenate([LU1[:s], U12], axis=1)
        bot = jnp.concatenate([LU1[s:][p2], LU2], axis=1)
        perm = jnp.concatenate([p1[:s], p1[s:][p2]])
        return jnp.concatenate([top, bot], axis=0), perm

    if n <= nb_switch:
        return _panel(G)
    peel = max(int(lookahead) - 1, 0)
    frames = []  # (top_row_block, L_below, step perm), outermost first
    T, act = G, m
    while peel > 0 and (T.shape[1]) > 2 * nb_switch:
        w = nb_switch
        LU1, p1 = _panel(T[:, :w], act=None if act >= T.shape[0] else act)
        R = T[:, w:][p1]
        U12 = _trsm_left_unit(LU1[:w, :w], R[:w], nb_switch)
        S = R[w:] - LU1[w:, :w] @ U12
        frames.append((jnp.concatenate([LU1[:w], U12], axis=1),
                       LU1[w:], p1))
        T, act = S, act - w
        peel -= 1
    LUr, pr = rec(T, act)
    # stitch the peeled frames back around the recursed trailing factor,
    # composing permutations innermost-out (each frame nests exactly
    # like a recursion half)
    bot, p = LUr, pr
    for top, Lw, p1 in reversed(frames):
        w = top.shape[0]
        bot = jnp.concatenate([Lw[p], bot], axis=1)
        bot = jnp.concatenate([top, bot], axis=0)
        p = jnp.concatenate([p1[:w], p1[w:][p]])
    return bot, p


def getrf_schedule_flops(
    m: int,
    n: int,
    nb: int = 512,
    schedule: str = "recursive",
    nb_switch: int = 256,
    lookahead: int = 1,
    m_true: int | None = None,
    n_true: int | None = None,
) -> dict:
    """(model, exec, units) FLOP accounting for one pivoted LU of
    (m, n), m >= n, mirroring the executed schedule (masked full-shape
    ops counted at full shape).  model = n^2 (m - n/3), the LAPACK
    getrf count — computed from (m_true, n_true) when given, so drivers
    passing padded kernel shapes still report waste against the TRUE
    problem size (pad rows/columns are waste, the same convention as
    chol_schedule_flops)."""
    from .chol_kernels import _trsm_flops

    mt, nt_ = (m_true or m), (n_true or n)
    model = float(nt_) * nt_ * (mt - nt_ / 3.0)
    # pallas panel kernel replicates panel_lu's arithmetic exactly, so
    # the executed count is identical — only the compile unit differs
    panel_unit = "pallas_lu_panel" if schedule == "pallas" else "lu_panel"

    def panel_flops(M, b):
        # panel_lu: per eliminated column one full-height rank-1 on the
        # whole (M, b) panel
        return 2.0 * M * b * min(M, b), {(panel_unit, M, b)}

    if schedule == "vendor":
        # the vendor kernel still runs on the PADDED array
        return {"model": model,
                "exec": float(n) * n * (m - n / 3.0),
                "units": {("vendor_lu", m, n)}}
    if schedule == "flat":
        # blocked_getrf: every step at the full (m, n) padded shape
        kt = max(min(m, n) // max(nb, 1), 1)
        fp, up = panel_flops(m, nb)
        per_step = fp + float(n) * nb * nb + 2.0 * m * n * nb
        return {
            "model": model,
            "exec": kt * per_step,
            "units": up | {("trsm", nb, n), ("gemm", m, nb, n)},
        }
    if schedule == "flat_fast":
        # lu_fast.blocked_getrf_fast: <= 4 coarse panels at exact
        # shapes, _block_lu's inner loops masked at full block shape
        nbf = _lu_fast_nb(n) or max(nb, 1)
        nt = max(n // nbf, 1)
        NB = nbf * (-(-nt // 4))
        ex, units = 0.0, set()
        k0 = 0
        while k0 < n:
            W = min(NB, n - k0)
            mk = m - k0
            # _block_lu(mk, W): strips + in-block trsm/gemm, all masked
            # to the full (mk, W) block per panel
            ex += 2.0 * mk * nbf * W + 2.0 * nbf * W * W + 2.0 * mk * W * W
            units |= {("lu_block", mk, W)}
            rest = n - k0 - W
            if rest > 0:
                ex += W**3 / 2.0 + 2.0 * W * W * rest
                ex += 2.0 * (mk - W) * W * rest
                units |= {("trsm", W, W), ("gemm", W, W, rest),
                          ("gemm", mk - W, W, rest)}
            k0 += W
        return {"model": model, "exec": ex, "units": units}

    from .chol_kernels import _lat_height

    def rec(M, act, n):
        # M: physical (canonical) height, act: true rows — mirrors
        # getrf_recursive's canon() exactly
        if n <= nb_switch:
            return panel_flops(M, n)
        s = split_point(n)
        f1, u1 = rec(M, act, s)
        ft, ut = _trsm_flops(n - s, s, nb_switch)
        Mc = _lat_height(act - s)
        fg = 2.0 * Mc * s * (n - s)
        f2, u2 = rec(Mc, act - s, n - s)
        return f1 + ft + fg + f2, u1 | ut | u2 | {("gemm", Mc, s, n - s)}

    ex, units = 0.0, set()
    k0, peel = 0, max(int(lookahead) - 1, 0)
    while peel > 0 and (n - k0) > 2 * nb_switch:
        w = nb_switch
        fp, up = panel_flops(m - k0, w)
        ft, ut = _trsm_flops(n - k0 - w, w, nb_switch)
        fg = 2.0 * (m - k0 - w) * w * (n - k0 - w)
        ex += fp + ft + fg
        units |= up | ut | {("gemm", m - k0 - w, w, n - k0 - w)}
        k0 += w
        peel -= 1
    fr, ur = rec(m - k0, m - k0, n - k0)
    return {"model": model, "exec": ex + fr, "units": units | ur}


def lu_supported(dtype) -> bool:
    """Whether the vendor lax.linalg.lu compiles for this dtype on the
    current default backend (TPU: f32/c64 only)."""
    import jax

    if jax.default_backend() == "cpu":
        return True
    dt = jnp.dtype(dtype)
    return dt in (jnp.dtype(jnp.float32), jnp.dtype(jnp.complex64))


def _lu_fast_nb(n: int) -> int:
    """Block size the three-level lu_fast schedule uses, 0 when the
    shape does not admit it — shared by dispatch and accounting."""
    for nbf in (512, 256, 128):
        if n % nbf == 0:
            return nbf
    return 0


def resolve_lu_schedule(m: int, n: int, dtype, schedule: str = "auto") -> str:
    """The route ``lu_global`` will take for this shape/dtype/backend —
    shared with the drivers' FLOP accounting so the recorded
    ``factor.getrf.*`` counters describe the program actually traced.

    ``flat`` is the pre-recursion native family (same convention as the
    chol/QR flat routes, which map to the tuned coarse kernels): the
    three-level ``lu_fast`` schedule for large divisible squares
    (``flat_fast``), the single-level ``blocked_getrf`` otherwise."""
    import jax

    if schedule in ("recursive", "pallas") and m >= n:
        return schedule
    if schedule in ("flat", "recursive", "pallas"):
        if m == n and n >= 2048 and _lu_fast_nb(n):
            return "flat_fast"
        return "flat"
    if jax.default_backend() != "cpu" and m == n and n >= RECURSIVE_MIN_N:
        return "pallas"
    if lu_supported(dtype):
        return "vendor"
    return "flat"


def lu_global(
    Gp: jnp.ndarray,
    nb: int,
    schedule: str = "auto",
    nb_switch: int = 256,
    lookahead: int = 1,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Schedule-dispatched LU of the padded global array.

    Returns (LU, perm), perm over Gp's (padded) rows.  ``auto``: CPU
    keeps the vendor (LAPACK) kernel; on accelerators large square
    arrays run the recursive divide & conquer schedule (the vendor
    lowering and the single-level blocked_getrf are both schedule-bound
    at a few % of the chip's gemm rate, and the flat loops burn ~3x the
    model FLOPs), with blocked_getrf as the unsupported-dtype /
    rectangular fallback.  Explicit ``recursive``/``flat`` are honored
    on every backend (tests exercise the native schedules on CPU).
    Dispatch and the drivers' FLOP accounting share
    ``resolve_lu_schedule``, so the recorded route is always the traced
    one.
    """
    route = resolve_lu_schedule(*Gp.shape, Gp.dtype, schedule)
    if route in ("recursive", "pallas"):
        return getrf_recursive(Gp, nb_switch, lookahead, route)
    if route == "vendor":
        lu2d, _, perm = lax.linalg.lu(Gp)
        return lu2d, perm.astype(jnp.int32)
    if route == "flat_fast":
        from .lu_fast import blocked_getrf_fast

        return blocked_getrf_fast(Gp, _lu_fast_nb(Gp.shape[1]))
    return blocked_getrf(Gp, nb)
