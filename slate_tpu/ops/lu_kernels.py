"""Native blocked LU kernels (no vendor LuDecomposition).

The XLA TPU backend implements lax.linalg.lu only for f32/c64; the
reference's own blocked right-looking getrf (reference: src/getrf.cc:85-214
— panel factor, pivot broadcast, row swaps, trailing update) is the model
for the f64/c128 path here.  Everything is static-shape fori_loop code:

* ``panel_lu``     — unblocked partial-pivot LU of one (M, nb) panel,
  the analogue of the reference's threaded panel kernel
  (Tile_getrf.hh:164-452) with the per-column argmax done by lax.argmax
  over the whole gathered panel instead of a thread/MPI reduction tree.
* ``blocked_getrf`` — right-looking blocked LU over the padded global
  array: per step the panel is rolled to the top (static shapes), factored
  redundantly, row swaps applied as one gather, then one triangular solve
  + one matmul for the trailing update (getrf.cc:183-214's permuteRows +
  trsm + gemm fused into three XLA ops).

Used by drivers/lu.py whenever the platform lacks a vendor LU for the
dtype, and by parallel/spmd_lu.py for the in-loop panel factor.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def panel_lu(
    panel: jnp.ndarray, pivot: bool = True
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Unblocked LU of an (M, nb) panel, partial pivoting by default.

    Returns (lu, perm) with lu holding unit-lower L below the diagonal and
    U on/above, and perm the forward permutation: lu rows correspond to
    panel[perm].  Matches lax.linalg.lu's (lu, _, permutation) contract.
    Zero pivot columns produce zero L columns (flagged by the caller's
    info check), not NaNs.  pivot=False runs the no-exchange elimination
    (used after tournament pivoting has already ordered the rows).
    """
    M, nb = panel.shape
    rows = jnp.arange(M)

    def body(j, carry):
        a, perm = carry
        col = a[:, j]
        if pivot:
            mag = jnp.where(rows >= j, jnp.abs(col), -jnp.inf)
            piv = jnp.argmax(mag)
        else:
            piv = j
        # swap rows j <-> piv (gather-free: two dynamic row updates)
        rj = a[j]
        rp = a[piv]
        a = a.at[j].set(rp).at[piv].set(rj)
        pj = perm[j]
        pp = perm[piv]
        perm = perm.at[j].set(pp).at[piv].set(pj)
        pv = a[j, j]
        safe = jnp.where(pv == 0, jnp.ones_like(pv), pv)
        l = jnp.where((rows > j) & (pv != 0), a[:, j] / safe, jnp.zeros(M, a.dtype))
        a = a.at[:, j].set(jnp.where(rows > j, l, a[:, j]))
        urow = jnp.where(jnp.arange(nb) > j, a[j], jnp.zeros(nb, a.dtype))
        return a - jnp.outer(l, urow), perm

    perm0 = jnp.arange(M, dtype=jnp.int32)
    lu, perm = lax.fori_loop(0, min(M, nb), body, (panel, perm0))
    return lu, perm


def blocked_getrf(
    Gp: jnp.ndarray, nb: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked right-looking LU with partial pivoting of a padded array.

    Gp: (Mp, Np) with Mp, Np multiples of nb and the padding diagonal
    spliced to 1 (layout.eye_splice semantics).  Returns (LU, perm) with
    perm the net forward row permutation: LU = (L\\U) of Gp[perm].
    Reference: src/getrf.cc:85-214.
    """
    Mp, Np = Gp.shape
    kt = min(Mp, Np) // nb
    rows = jnp.arange(Mp)
    cols = jnp.arange(Np)

    def step(k, carry):
        G, perm = carry
        # -- panel: roll active rows to the top, factor ----------------
        col = lax.dynamic_slice(G, (0, k * nb), (Mp, nb))
        colr = jnp.roll(col, -k * nb, axis=0)
        active_len = Mp - k * nb
        colr = jnp.where((rows < active_len)[:, None], colr, jnp.zeros_like(colr))
        lu_pan, piv = panel_lu(colr)
        # step permutation in global row space (identity above the panel)
        act = rows - k * nb
        mapped = piv[jnp.clip(act, 0, Mp - 1)] + k * nb
        step_perm = jnp.where(act >= 0, mapped, rows)
        # -- row exchange across the whole matrix ----------------------
        G = G[step_perm]
        perm = perm[step_perm]
        # -- write the factored panel back (rows >= k*nb) ---------------
        lu_nat = jnp.roll(lu_pan, k * nb, axis=0)
        col_cur = lax.dynamic_slice(G, (0, k * nb), (Mp, nb))
        col_new = jnp.where((rows >= k * nb)[:, None], lu_nat, col_cur)
        G = lax.dynamic_update_slice(G, col_new, (0, k * nb))
        # -- U row: Lkk^-1 A(k, j>k) ------------------------------------
        Lkk = jnp.tril(lu_pan[:nb], -1) + jnp.eye(nb, dtype=G.dtype)
        row = lax.dynamic_slice(G, (k * nb, 0), (nb, Np))
        rs = lax.linalg.triangular_solve(
            Lkk, row, left_side=True, lower=True, unit_diagonal=True
        )
        row_new = jnp.where((cols >= (k + 1) * nb)[None, :], rs, row)
        G = lax.dynamic_update_slice(G, row_new, (k * nb, 0))
        # -- trailing update --------------------------------------------
        Lpan = jnp.where((rows >= (k + 1) * nb)[:, None], col_new, 0)
        Urow = jnp.where((cols >= (k + 1) * nb)[None, :], row_new, 0)
        return G - Lpan @ Urow, perm

    perm0 = jnp.arange(Mp, dtype=jnp.int32)
    return lax.fori_loop(0, kt, step, (Gp, perm0))


def tournament_pivots(
    panel: jnp.ndarray, nb: int, chunk: int
) -> jnp.ndarray:
    """Tournament (CALU) pivot selection on an (M, nb) panel (reference:
    src/getrf_tntpiv.cc:1-498, internal_getrf_tntpiv.cc): every `chunk`
    rows elect nb candidate pivot rows with a local partial-pivot LU, and
    winners advance up a binary reduction tree — one communication-free
    pass per level, the LU variant built for static schedules.

    Returns the nb winning row indices (into panel), in pivot order.
    """
    M, nbp = panel.shape
    assert nbp == nb and chunk >= nb and M % chunk == 0
    K = M // chunk
    chunks = panel.reshape(K, chunk, nb)
    base = jnp.arange(K)[:, None] * chunk

    def elect(ch):
        _, perm = panel_lu(ch)
        return ch[perm[:nb]], perm[:nb]

    cands, local_idx = jax.vmap(elect)(chunks)  # (K, nb, nb), (K, nb)
    idxs = base + local_idx

    while K > 1:
        if K % 2 == 1:  # odd: last bracket gets a zero-rows bye
            cands = jnp.concatenate(
                [cands, jnp.zeros((1, nb, nb), cands.dtype)]
            )
            idxs = jnp.concatenate([idxs, jnp.full((1, nb), M, idxs.dtype)])
            K += 1
        merged = cands.reshape(K // 2, 2 * nb, nb)
        midx = idxs.reshape(K // 2, 2 * nb)

        def play(ch, ix):
            _, perm = panel_lu(ch)
            return ch[perm[:nb]], ix[perm[:nb]]

        cands, idxs = jax.vmap(play)(merged, midx)
        K //= 2
    return idxs[0]


def blocked_getrf_tntpiv(
    Gp: jnp.ndarray, nb: int, chunk: int = 0
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked LU with tournament pivoting (reference: getrf_tntpiv.cc,
    MethodLU.CALU).  Same right-looking structure as blocked_getrf; the
    panel's pivot rows come from the communication-free tournament, after
    which the panel eliminates without further exchanges.
    """
    Mp, Np = Gp.shape
    kt = min(Mp, Np) // nb
    chunk = chunk or max(4 * nb, nb)
    # pad rows so every (rolled) panel splits into whole chunks
    Mc = -(-Mp // chunk) * chunk
    Gw = jnp.pad(Gp, ((0, Mc - Mp), (0, 0)))
    rows = jnp.arange(Mc)
    cols = jnp.arange(Np)

    def step(k, carry):
        G, perm = carry
        col = lax.dynamic_slice(G, (0, k * nb), (Mc, nb))
        colr = jnp.roll(col, -k * nb, axis=0)
        active_len = Mp - k * nb
        colr = jnp.where((rows < active_len)[:, None], colr, jnp.zeros_like(colr))
        # -- tournament pivot selection over the active panel ----------
        win = tournament_pivots(colr, nb, chunk)  # rows in active frame
        # step permutation: winners to the top (in order), others keep
        # their relative order behind them
        is_win = jnp.zeros((Mc,), jnp.int32).at[win].set(1, mode="drop")
        win_pos = jnp.zeros((Mc,), jnp.int32).at[win].set(
            jnp.arange(nb, dtype=jnp.int32), mode="drop"
        )
        rest_rank = jnp.cumsum(1 - is_win) - 1
        key = jnp.where(is_win == 1, win_pos, nb + rest_rank)
        step_perm_act = jnp.argsort(key)  # active-frame permutation
        mapped = jnp.where(
            rows - k * nb >= 0,
            step_perm_act[jnp.clip(rows - k * nb, 0, Mc - 1)] + k * nb,
            rows,
        )
        step_perm = jnp.where(mapped < Mc, mapped, mapped - Mc)
        G = G[step_perm]
        perm = perm[step_perm]
        # -- panel factor, no further pivoting -------------------------
        col2 = lax.dynamic_slice(G, (0, k * nb), (Mc, nb))
        colr2 = jnp.roll(col2, -k * nb, axis=0)
        colr2 = jnp.where(
            (rows < active_len)[:, None], colr2, jnp.zeros_like(colr2)
        )
        lu_pan, _ = panel_lu(colr2, pivot=False)
        lu_nat = jnp.roll(lu_pan, k * nb, axis=0)
        col_cur = lax.dynamic_slice(G, (0, k * nb), (Mc, nb))
        col_new = jnp.where((rows >= k * nb)[:, None], lu_nat, col_cur)
        G = lax.dynamic_update_slice(G, col_new, (0, k * nb))
        # -- U row + trailing update (as blocked_getrf) ----------------
        Lkk = jnp.tril(lu_pan[:nb], -1) + jnp.eye(nb, dtype=G.dtype)
        row = lax.dynamic_slice(G, (k * nb, 0), (nb, Np))
        rs = lax.linalg.triangular_solve(
            Lkk, row, left_side=True, lower=True, unit_diagonal=True
        )
        row_new = jnp.where((cols >= (k + 1) * nb)[None, :], rs, row)
        G = lax.dynamic_update_slice(G, row_new, (k * nb, 0))
        Lpan = jnp.where((rows >= (k + 1) * nb)[:, None], col_new, 0)
        Urow = jnp.where((cols >= (k + 1) * nb)[None, :], row_new, 0)
        return G - Lpan @ Urow, perm

    perm0 = jnp.arange(Mc, dtype=jnp.int32)
    G, perm = lax.fori_loop(0, kt, step, (Gw, perm0))
    return G[:Mp], perm[:Mp]


def lu_supported(dtype) -> bool:
    """Whether the vendor lax.linalg.lu compiles for this dtype on the
    current default backend (TPU: f32/c64 only)."""
    import jax

    if jax.default_backend() == "cpu":
        return True
    dt = jnp.dtype(dtype)
    return dt in (jnp.dtype(jnp.float32), jnp.dtype(jnp.complex64))


def lu_global(Gp: jnp.ndarray, nb: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Platform-dispatched LU of the padded global array.

    Returns (LU, perm), perm over Gp's (padded) rows.  CPU keeps the
    vendor (LAPACK) kernel; on accelerators large square arrays run the
    three-level native schedule (ops/lu_fast.py — the vendor lowering
    and the single-level blocked_getrf are both schedule-bound at a few
    % of the chip's gemm rate), with blocked_getrf as the small-size /
    rectangular fallback.
    """
    import jax

    m, n = Gp.shape
    if jax.default_backend() != "cpu" and m == n and n >= 2048:
        from .lu_fast import blocked_getrf_fast

        for nbf in (512, 256, 128):
            if n % nbf == 0:
                return blocked_getrf_fast(Gp, nbf)
    if lu_supported(Gp.dtype):
        lu2d, _, perm = lax.linalg.lu(Gp)
        return lu2d, perm.astype(jnp.int32)
    LU, perm = blocked_getrf(Gp, nb)
    return LU, perm
