"""2D-level BLAS kernels on (padded) global arrays — the "global path".

These are the L0/L1 vendor-kernel layer of the TPU build (reference
analogue: blaspp/vendor BLAS called per tile, Tile_blas.hh:19-941).  On a
single chip the best schedule for a tiled BLAS3 op is simply the one big
XLA op — the MXU gets maximal tile sizes and XLA fuses the epilogue — so
drivers route here whenever the matrix lives on one device, and internals
reuse these for panel-sized subproblems.

All functions take/return plain jnp arrays.  Padding conventions: operands
are zero-padded (products unaffected); triangular solves require the
padding diagonal spliced to 1 (see layout.eye_splice) so the padded system
stays nonsingular.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..enums import Diag, Op, Side, Uplo


def apply_op(A: jnp.ndarray, op: Op) -> jnp.ndarray:
    if op == Op.Trans:
        return A.T
    if op == Op.ConjTrans:
        return jnp.conj(A).T if jnp.issubdtype(A.dtype, jnp.complexfloating) else A.T
    return A


def gemm2d(alpha, A, B, beta, C):
    """C = alpha A B + beta C (reference: tile::gemm, Tile_blas.hh:30)."""
    acc = jnp.promote_types(A.dtype, jnp.float32)
    out = alpha * jnp.matmul(A, B, preferred_element_type=acc) + beta * C
    return out.astype(C.dtype)


def syrk2d(alpha, A, beta, C):
    """C = alpha A A^T + beta C (reference: tile::syrk, Tile_blas.hh:523)."""
    return gemm2d(alpha, A, A.T, beta, C)


def herk2d(alpha, A, beta, C):
    """C = alpha A A^H + beta C (reference: tile::herk)."""
    AH = jnp.conj(A).T if jnp.issubdtype(A.dtype, jnp.complexfloating) else A.T
    return gemm2d(alpha, A, AH, beta, C)


def syr2k2d(alpha, A, B, beta, C):
    return gemm2d(alpha, A, B.T, 1, gemm2d(alpha, B, A.T, beta, C))


def her2k2d(alpha, A, B, beta, C):
    conj = jnp.issubdtype(A.dtype, jnp.complexfloating)
    BH = jnp.conj(B).T if conj else B.T
    AH = jnp.conj(A).T if conj else A.T
    alpha_c = jnp.conj(alpha) if conj else alpha
    return gemm2d(alpha, A, BH, 1, gemm2d(alpha_c, B, AH, beta, C))


def _tri_take(A, uplo: Uplo, diag: Diag):
    """Materialize the referenced triangle of A (unit diag -> ones)."""
    T = jnp.tril(A) if uplo == Uplo.Lower else jnp.triu(A)
    if diag == Diag.Unit:
        n = A.shape[0]
        eye = jnp.eye(n, dtype=A.dtype)
        strict = T - jnp.diag(jnp.diag(T))
        T = strict + eye
    return T


def trmm2d(side: Side, uplo: Uplo, op: Op, diag: Diag, alpha, A, B):
    """B = alpha op(T(A)) B or alpha B op(T(A)) (reference: tile::trmm)."""
    T = apply_op(_tri_take(A, uplo, diag), op)
    if side == Side.Left:
        return alpha * jnp.matmul(T, B)
    return alpha * jnp.matmul(B, T)


def trsm2d(side: Side, uplo: Uplo, op: Op, diag: Diag, alpha, A, B):
    """Solve op(T(A)) X = alpha B (or right variant)
    (reference: tile::trsm, Tile_blas.hh:682) via XLA triangular_solve."""
    conj = op == Op.ConjTrans and jnp.issubdtype(A.dtype, jnp.complexfloating)
    X = lax.linalg.triangular_solve(
        A,
        alpha * B,
        left_side=(side == Side.Left),
        lower=(uplo == Uplo.Lower),
        transpose_a=(op != Op.NoTrans),
        conjugate_a=conj,
        unit_diagonal=(diag == Diag.Unit),
    )
    return X
