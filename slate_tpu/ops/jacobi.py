"""Parallel-order Jacobi polishing for eigen/SVD accuracy on TPU.

XLA's TPU eigh/svd are Jacobi-type iterations that stop around 1e-7-1e-8
relative residual in f64 — five orders short of the reference's LAPACK
accuracy (reference acceptance: test_heev.cc residual <= tol*eps).  These
kernels polish a vendor (or any) approximate decomposition to full working
precision with round-robin parallel-order Jacobi sweeps: each round
rotates n/2 *disjoint* index pairs simultaneously, so one round is two
row/column pair-updates over the whole matrix — vectorized, static-shape,
MXU/VPU friendly.  Near-diagonal input converges in 1-3 sweeps
(quadratic convergence).

This is the TPU answer to SURVEY §7 hard-part (5) (f64 parity on
low-precision-first hardware) for the spectral routines; the reference
gets it for free from LAPACK steqr/bdsqr on the host.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _round_robin(n: int) -> np.ndarray:
    """Static (n-1, n//2, 2) round-robin pairing schedule (n even):
    every round is a perfect matching; over n-1 rounds every pair meets."""
    assert n % 2 == 0
    arr = list(range(1, n))
    rounds = []
    for _ in range(n - 1):
        cur = [0] + arr
        pairs = [
            (min(cur[i], cur[n - 1 - i]), max(cur[i], cur[n - 1 - i]))
            for i in range(n // 2)
        ]
        rounds.append(pairs)
        arr = arr[-1:] + arr[:-1]
    return np.asarray(rounds, dtype=np.int32)


def _rotation(app, aqq, apq):
    """Jacobi rotation (c, s, u) zeroing the (p, q) coupling of the 2x2
    [[app, apq], [conj(apq), aqq]]: G = [[c, s*u], [-s*conj(u), c]].

    TPU note: f64 emulation keeps float32's exponent range, so tau*tau
    overflows to NaN already around |tau| ~ 1e19.  Large tau takes the
    asymptotic branch t = 1/(2 tau) (relative error ~ 1/(4 tau^2), below
    eps for |tau| > 1e8), and couplings below eps * (|app| + |aqq|) are
    skipped outright — their rotation angle is under eps anyway.
    """
    absa = jnp.abs(apq)
    real_t = absa.dtype
    eps = jnp.finfo(real_t).eps
    diag_mag = jnp.abs(jnp.real(app)) + jnp.abs(jnp.real(aqq))
    negligible = absa <= 0.25 * eps * diag_mag
    skip = (absa == 0) | negligible
    safe = jnp.where(skip, jnp.ones_like(absa), absa)
    u = jnp.where(skip, jnp.ones_like(apq), apq / safe)
    tau = (jnp.real(aqq) - jnp.real(app)) / (2 * safe)
    big = jnp.abs(tau) > 1e8
    tau_s = jnp.where(big, jnp.ones_like(tau), tau)
    t_small = jnp.sign(tau_s) / (jnp.abs(tau_s) + jnp.sqrt(1 + tau_s * tau_s))
    t_big = 1.0 / (2.0 * jnp.where(big, tau, jnp.ones_like(tau)))
    t = jnp.where(big, t_big, t_small)
    t = jnp.where(tau == 0, jnp.ones_like(t), t)
    c = 1.0 / jnp.sqrt(1 + t * t)
    s = t * c
    c = jnp.where(skip, jnp.ones_like(c), c).astype(real_t)
    s = jnp.where(skip, jnp.zeros_like(s), s).astype(real_t)
    return c, s, u


def _offdiag_norm(M):
    off = M - jnp.diag(jnp.diag(M))
    return jnp.linalg.norm(off)


@partial(jax.jit, static_argnames=("max_sweeps", "want_vectors"))
def jacobi_eigh_polish(
    A: jnp.ndarray, V0: jnp.ndarray, max_sweeps: int = 12,
    want_vectors: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Polish an approximate eigenbasis V0 of Hermitian A to working
    precision.  Returns (w ascending, V with matching columns).

    M = V0^H A V0 is near-diagonal; parallel-order Jacobi sweeps drive the
    off-diagonal below n*eps*||A|| while accumulating rotations into V.
    """
    n = A.shape[0]
    complex_t = jnp.issubdtype(A.dtype, jnp.complexfloating)
    npad = n + (n % 2)
    sched = jnp.asarray(_round_robin(npad))
    R, m, _ = sched.shape

    M = V0.conj().T @ A @ V0 if complex_t else V0.T @ A @ V0
    M = 0.5 * (M + M.conj().T)
    V = V0
    if npad != n:
        big = 2.0 * jnp.max(jnp.abs(jnp.diag(M))) + 1.0
        M = jnp.pad(M, ((0, 1), (0, 1)))
        M = M.at[n, n].set(big.astype(M.dtype))
        V = jnp.pad(V, ((0, 1), (0, 1)))
        V = V.at[n, n].set(1.0)

    eps = jnp.finfo(jnp.real(M).dtype).eps
    scale = jnp.linalg.norm(M)
    tol = eps * scale * npad

    def conj_u(u):
        return jnp.conj(u) if complex_t else u

    def one_round(r, carry):
        M, V = carry
        pq = sched[r]
        p, q = pq[:, 0], pq[:, 1]
        c, s, u = _rotation(M[p, p], M[q, q], M[p, q])
        cu = c if not complex_t else c.astype(M.dtype)
        su_r = (s * u) if complex_t else s * jnp.real(u)
        # columns: M G, V G
        Mp, Mq = M[:, p], M[:, q]
        M = M.at[:, p].set(cu * Mp - s * conj_u(u) * Mq)
        M = M.at[:, q].set(su_r * Mp + cu * Mq)
        if want_vectors:
            Vp, Vq = V[:, p], V[:, q]
            V = V.at[:, p].set(cu * Vp - s * conj_u(u) * Vq)
            V = V.at[:, q].set(su_r * Vp + cu * Vq)
        # rows: G^H M (coefficients broadcast over the row axis)
        Rp, Rq = M[p, :], M[q, :]
        M = M.at[p, :].set(cu[:, None] * Rp - su_r[:, None] * Rq)
        M = M.at[q, :].set((s * conj_u(u))[:, None] * Rp + cu[:, None] * Rq)
        return M, V

    def one_sweep(carry):
        M, V, it = carry
        M, V = lax.fori_loop(0, R, one_round, (M, V))
        return M, V, it + 1

    def keep_going(carry):
        M, _, it = carry
        return (it < max_sweeps) & (_offdiag_norm(M) > tol)

    M, V, _ = lax.while_loop(keep_going, one_sweep, (M, V, 0))

    w = jnp.real(jnp.diag(M))[:n]
    V = V[:n, :n]
    order = jnp.argsort(w)
    return w[order], V[:, order]


@partial(jax.jit, static_argnames=("max_sweeps",))
def jacobi_svd_polish(
    A: jnp.ndarray, V0: jnp.ndarray, max_sweeps: int = 12
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Polish an approximate right singular basis V0 of square A.

    One-sided Jacobi on B = A V0: rotate column pairs of B (and V)
    until mutually orthogonal; then s = ||b_j||, U = B diag(1/s).
    Returns (U, s descending, V).
    """
    n = A.shape[0]
    complex_t = jnp.issubdtype(A.dtype, jnp.complexfloating)
    npad = n + (n % 2)
    sched = jnp.asarray(_round_robin(npad))
    R, m, _ = sched.shape

    B = A @ V0
    V = V0
    if npad != n:
        B = jnp.pad(B, ((0, 1), (0, 1)))
        B = B.at[n, n].set(1.0)
        V = jnp.pad(V, ((0, 1), (0, 1)))
        V = V.at[n, n].set(1.0)

    eps = jnp.finfo(jnp.real(B).dtype).eps
    fro = jnp.linalg.norm(B)
    tol2 = eps * fro * fro * npad  # <bp,bq> scale threshold

    def conj_u(u):
        return jnp.conj(u) if complex_t else u

    def one_round(r, carry):
        B, V = carry
        pq = sched[r]
        p, q = pq[:, 0], pq[:, 1]
        Bp, Bq = B[:, p], B[:, q]
        x = jnp.sum(jnp.abs(Bp) ** 2, axis=0)
        y = jnp.sum(jnp.abs(Bq) ** 2, axis=0)
        z = jnp.sum(jnp.conj(Bp) * Bq, axis=0)
        c, s, u = _rotation(x, y, z)
        cu = c if not complex_t else c.astype(B.dtype)
        su_r = (s * u) if complex_t else s * jnp.real(u)
        B = B.at[:, p].set(cu * Bp - s * conj_u(u) * Bq)
        B = B.at[:, q].set(su_r * Bp + cu * Bq)
        Vp, Vq = V[:, p], V[:, q]
        V = V.at[:, p].set(cu * Vp - s * conj_u(u) * Vq)
        V = V.at[:, q].set(su_r * Vp + cu * Vq)
        return B, V

    def gram_off(B):
        G = B.conj().T @ B
        return jnp.linalg.norm(G - jnp.diag(jnp.diag(G)))

    def one_sweep(carry):
        B, V, it = carry
        B, V = lax.fori_loop(0, R, one_round, (B, V))
        return B, V, it + 1

    def keep_going(carry):
        B, _, it = carry
        return (it < max_sweeps) & (gram_off(B) > tol2)

    B, V, _ = lax.while_loop(keep_going, one_sweep, (B, V, 0))

    # U from a QR of the (orthogonal-columned) B: R is diagonal to within
    # the sweep tolerance, and QR's orthonormal completion covers zero
    # columns (rank-deficient A), unlike a plain column normalization.
    Q, Rr = lax.linalg.qr(B, full_matrices=False)
    rd = jnp.diagonal(Rr)
    s = jnp.abs(rd)
    phase = jnp.where(s == 0, jnp.ones_like(rd), rd / jnp.where(s == 0, 1, s))
    U = Q * phase[None, :]
    s, U, V = s[:n], U[:n, :n], V[:n, :n]
    order = jnp.argsort(-s)
    return U[:, order], s[order], V[:, order]


def eigh_accurate(
    A: jnp.ndarray, vectors: bool = True
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Vendor eigh + Jacobi polish when the backend's eigh is inexact
    (the TPU QDWH eigensolver stops short of working precision in both
    f32 and f64); plain vendor eigh/eigvalsh on CPU."""
    if jax.default_backend() == "cpu":
        if vectors:
            return jnp.linalg.eigh(A)
        return jnp.linalg.eigvalsh(A), None
    w, V = jnp.linalg.eigh(A)
    w, V = jacobi_eigh_polish(A, V, want_vectors=vectors)
    return (w, V) if vectors else (w, None)


def svd_accurate(A: jnp.ndarray, compute_uv: bool = True):
    """Vendor svd + one-sided Jacobi polish on TPU f64.

    Rectangular inputs are QR/LQ-pre-reduced to the square core first
    (the TPU vendor QR is full-accuracy, unlike its svd); returns
    (U, s, Vh) matching jnp.linalg.svd(full_matrices=False), or just s
    when compute_uv=False (the vendor's singular *values* are already
    accurate; only the vectors need polishing).
    """
    if not compute_uv:
        # vendor singular *values* are accurate in f64 (measured ~1e-13
        # rel); f32 values fall short, but upcasting the values-only call
        # is far cheaper than computing polished vectors
        if jax.default_backend() == "cpu":
            return jnp.linalg.svd(A, compute_uv=False)
        if jnp.finfo(jnp.real(A).dtype).bits <= 32:
            up = (
                jnp.complex128
                if jnp.issubdtype(A.dtype, jnp.complexfloating)
                else jnp.float64
            )
            return jnp.linalg.svd(A.astype(up), compute_uv=False).astype(
                jnp.finfo(jnp.real(A).dtype).dtype
            )
        return jnp.linalg.svd(A, compute_uv=False)
    if jax.default_backend() == "cpu":
        return jnp.linalg.svd(A, full_matrices=False)
    if jnp.finfo(jnp.real(A).dtype).bits <= 32:
        # the TPU backend's f32 SVD-with-vectors aborts its compiler
        # (f64 compiles and is polished to full precision): upcast,
        # solve, downcast — exceeds f32 accuracy requirements anyway
        up = jnp.complex128 if jnp.issubdtype(A.dtype, jnp.complexfloating) else jnp.float64
        U, s, Vh = svd_accurate(A.astype(up), compute_uv=True)
        return U.astype(A.dtype), s.astype(jnp.real(A).dtype), Vh.astype(A.dtype)
    m, n = A.shape
    if m > n:
        Q, R = lax.linalg.qr(A, full_matrices=False)
        U2, s, Vh = svd_accurate(R)
        return Q @ U2, s, Vh
    if m < n:
        U2, s, Vh2 = svd_accurate(A.conj().T)
        return Vh2.conj().T, s, U2.conj().T
    _, _, Vh = jnp.linalg.svd(A, full_matrices=False)
    U2, s2, V2 = jacobi_svd_polish(A, Vh.conj().T)
    return U2, s2, V2.conj().T
