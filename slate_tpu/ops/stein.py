"""Tridiagonal eigenvectors by batched inverse iteration (stein).

The de-risking fallback for the flagship stedc path (reference role:
src/steqr_impl.cc's implicit-QR-with-vectors — LAPACK's other
tridiagonal vector path; algorithmically this module is the
dstebz+dstein pairing: eigenvalues from the parallel Sturm bisection,
vectors from shifted inverse iteration).

TPU-native structure: one batched tridiagonal LU with partial pivoting
(a single lax.scan over the matrix, vmapped over ALL n shifts), two
batched solve sweeps per iteration (forward/backward scans), then one
CholQR2 orthonormalization of the whole vector block.  Sequential
per-vector rotations (the steqr Givens stream) never appear; cluster
handling falls out of the final orthonormalization — mixing inverse
iterates WITHIN a numerical cluster still spans the right invariant
subspace, so the CholQR basis is a valid eigenbasis for it (the same
contract dstein's cluster reorthogonalization provides).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..internal.precision import hdot as _dot


def _factor_shifted(d, e, lam, pivmin):
    """Partial-pivot LU of (T - lam I) for one shift: returns per-row
    (u1, u2, u3) (U's three stored diagonals), multipliers m and swap
    flags — LAPACK dgttrf's recurrence as one scan.  ``pivmin`` is the
    zero-pivot replacement (scale-relative, kept far above the TPU f64
    emulation's ~1e-38 flush-to-zero line)."""
    n = d.shape[0]
    dt = d.dtype
    tiny = pivmin
    ep = jnp.concatenate([e, jnp.zeros((1,), dt)])

    def step(carry, xs):
        p1, p2, p3 = carry  # pending pivot row (cols k, k+1, k+2)
        ek, dk1, ek1 = xs  # sub-diag e_k, next diagonal, next sub-diag
        swap = jnp.abs(ek) > jnp.abs(p1)
        r1 = jnp.where(swap, ek, p1)
        r2 = jnp.where(swap, dk1, p2)
        r3 = jnp.where(swap, ek1, p3)
        s1 = jnp.where(swap, p1, ek)
        s2 = jnp.where(swap, p2, dk1)
        s3 = jnp.where(swap, p3, ek1)
        piv = jnp.where(jnp.abs(r1) < tiny, tiny, r1)
        m = s1 / piv
        n2 = s2 - m * r2
        n3 = s3 - m * r3
        return (n2, n3, jnp.zeros((), dt)), (piv, r2, r3, m, swap)

    d0 = d - lam
    xs = (ep[:-1], d0[1:] if n > 1 else jnp.zeros((0,), dt),
          ep[1:] if n > 1 else jnp.zeros((0,), dt))
    init = (d0[0], ep[0], jnp.zeros((), dt))
    (fin1, _, _), rows = lax.scan(step, init, xs)
    u1 = jnp.concatenate([rows[0], jnp.where(
        jnp.abs(fin1) < tiny, tiny, fin1)[None]])
    u2 = jnp.concatenate([rows[1], jnp.zeros((1,), dt)])
    u3 = jnp.concatenate([rows[2], jnp.zeros((1,), dt)])
    m = jnp.concatenate([rows[3], jnp.zeros((1,), dt)])
    swap = jnp.concatenate([rows[4], jnp.zeros((1,), bool)])
    return u1, u2, u3, m, swap


def _solve_factored(u1, u2, u3, m, swap, b):
    """Solve L U x = P b given the factor streams."""
    n = b.shape[0]
    dt = b.dtype

    def fwd(carry, xs):
        bk = carry  # current rhs entry at row k (pre-elimination)
        bk1, mk, sk = xs
        hi = jnp.where(sk, bk1, bk)
        lo = jnp.where(sk, bk, bk1)
        lo = lo - mk * hi
        return lo, hi

    last, y = lax.scan(fwd, b[0], (b[1:], m[:-1], swap[:-1]))
    y = jnp.concatenate([y, last[None]])

    def bwd(carry, xs):
        x1, x2 = carry  # x[k+1], x[k+2]
        yk, a1, a2, a3 = xs
        xk = (yk - a2 * x1 - a3 * x2) / a1
        return (xk, x1), xk

    z = jnp.zeros((), dt)
    (x0, _), xs_r = lax.scan(
        bwd, (z, z),
        (y[::-1], u1[::-1], u2[::-1], u3[::-1]),
    )
    return xs_r[::-1]


@partial(jax.jit, static_argnames=("iters",))
def stein(
    d: jnp.ndarray, e: jnp.ndarray, w: jnp.ndarray, iters: int = 2
) -> jnp.ndarray:
    """Eigenvectors of tridiag(d, e) for the eigenvalues w by batched
    inverse iteration + CholQR2 orthonormalization.  Returns Z (n, n)
    with T Z ~= Z diag(w)."""
    n = d.shape[0]
    dt = d.dtype
    if n == 1:
        return jnp.ones((1, 1), dt)
    # separate equal shifts a hair so iterates within an exact cluster
    # are not numerically identical columns (the orthonormalization
    # needs an independent basis to work with)
    scale = jnp.maximum(jnp.abs(d).max(), jnp.abs(e).max())
    scale = jnp.where(scale > 0, scale, 1.0)
    jitter = (jnp.arange(n, dtype=dt) - 0.5 * n) * (
        4.0 * jnp.finfo(dt).eps * scale
    )
    lam = w + jitter

    pivmin = scale * jnp.asarray(1e-30, dt)
    u1, u2, u3, m, swap = jax.vmap(
        lambda l: _factor_shifted(d, e, l, pivmin)
    )(lam)

    # deterministic pseudo-random start vectors: the counter-based
    # Philox stream (structured starts like sin-grids can be nearly
    # orthogonal to whole eigenvector families — e.g. the Toeplitz
    # sin-basis — and stall the iteration)
    from ..matgen.philox import _bits_to_unit_jnp, philox_2x64_jnp

    ii = jnp.broadcast_to(jnp.arange(n)[:, None], (n, n))
    jj = jnp.broadcast_to(jnp.arange(n)[None, :], (n, n))
    Lbits, _Rbits = philox_2x64_jnp(ii.reshape(-1), jj.reshape(-1), 0x5E17)
    B0 = _bits_to_unit_jnp(Lbits, dt).reshape(n, n) - 0.5

    def iterate(_, V):
        V = jax.vmap(_solve_factored)(u1, u2, u3, m, swap, V)
        # max-scale first: a dead-on shift amplifies by ~1/pivmin and
        # the squared norm would overflow to inf (zeroing the iterate)
        mx = jnp.max(jnp.abs(V), axis=1, keepdims=True)
        V = V / jnp.where(mx == 0, 1.0, mx)
        nrm = jnp.sqrt((V * V).sum(axis=1, keepdims=True))
        return V / jnp.where(nrm == 0, 1.0, nrm)

    V = lax.fori_loop(0, iters, iterate, B0)  # rows indexed by shift
    Z = V.T
    # CholQR2: orthonormalize while preserving (cluster) spans
    for _ in range(2):
        G = _dot(Z.T, Z)
        G = G + jnp.finfo(dt).eps * 4 * jnp.trace(G) / n * jnp.eye(n, dtype=dt)
        L = lax.linalg.cholesky(G)
        Z = lax.linalg.triangular_solve(
            L, Z, left_side=False, lower=True, transpose_a=True
        )
    return Z
