"""Stage-2 bulge chasing: band -> tridiagonal, and tridiagonal
eigenvalues by bisection.

TPU-native re-design of the reference's hb2st wavefront (reference:
src/hb2st.cc:44-187 — task types per (sweep, step), static thread
scheduling over a lock-free atomic ProgressVector; the kernels are the
PLASMA-style Householder chase).  The reference's fine-grained
thread+atomics pipeline becomes a *superstep wavefront*: task (sweep s,
chase step j) runs at superstep t = 3s + j, so every superstep executes a
diagonal of independent tasks whose working windows are provably disjoint
(3 supersteps of sweep spacing puts consecutive windows 3b-1 columns
apart while a task only writes a 2b-wide row/column strip).  One
lax.fori_loop over supersteps, a vmapped window kernel per step — no
locks, no atomics, static shapes throughout.

The tridiagonal eigenvalues use bisection with vectorized Sturm counts
(all n eigenvalues bisected simultaneously; one scan over the matrix per
iteration) — the TPU replacement for LAPACK sterf's sequential QL/QR
iteration (reference: src/sterf.cc).

Band storage here is lower-diagonal-major: W[d, c] = A[c+d, c] for
d = 0..2b (2b diagonals hold the transient bulges).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..internal.precision import hdot as hp
from .householder import _larfg


def band_to_storage(G: jnp.ndarray, b: int, n_pad: int) -> jnp.ndarray:
    """Pack a (n, n) Hermitian band matrix (lower data) into (2b+1, n_pad)
    diagonal-major storage."""
    n = G.shape[0]
    W = jnp.zeros((2 * b + 1, n_pad), G.dtype)
    for d in range(min(b, n - 1) + 1):
        W = W.at[d, : n - d].set(jnp.diagonal(G, -d))
    return W


@partial(jax.jit, static_argnames=("n", "b"))
def hb2st(
    W: jnp.ndarray, n: int, b: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Reduce a Hermitian band matrix (bandwidth b) to real symmetric
    tridiagonal by Householder bulge chasing.

    W: (2b+1, n_pad) diagonal-major lower band storage, n_pad >= n + 4b+8.
    Returns (d, e, phase, VS, TAUS): real tridiagonal diagonal/
    subdiagonal, the unit diagonal phase u making it real (complex
    Hermitian input leaves a complex subdiagonal e_c; the similarity
    D_u^H T_c D_u with u_{i+1} = u_i e_c[i]/|e_c[i]| realifies it, so
    eigenvectors back-transform as Z_band = Q (u * Z_real) — LAPACK
    zhbtrd does the same scaling), and the chase reflectors for
    unmtr_hb2st — VS[s, j] is the length-b reflector of sweep s, step j
    (v[0] = 1), acting on rows s + j*b + 1 .. s + (j+1)*b.
    """
    dtype = W.dtype
    real_t = jnp.real(W[:1, :1]).dtype
    n_pad = W.shape[1]
    L = 3 * b + 1
    complex_t = jnp.issubdtype(dtype, jnp.complexfloating)

    def conj(x):
        return jnp.conj(x) if complex_t else x

    def realify(d, e_c):
        """Diagonal phase similarity making the subdiagonal real."""
        mag = jnp.abs(e_c)
        if not complex_t:
            return d, e_c, jnp.ones((n,), dtype)
        unit = jnp.where(mag == 0, jnp.ones_like(e_c), e_c / jnp.where(mag == 0, 1, mag))
        u = jnp.concatenate([jnp.ones((1,), dtype), jnp.cumprod(unit)])
        return d, mag.astype(real_t), u

    if n <= 2 or b <= 1:
        d = jnp.real(W[0, :n])
        e_c = W[1, : n - 1] if n > 1 else jnp.zeros((0,), dtype)
        d, e, u = realify(d, e_c)
        return d, e, u, jnp.zeros((1, 1, max(b, 1)), dtype), jnp.zeros((1, 1), dtype)

    n_sweeps = n - 2
    Jmax = (n - 3) // b + 1  # max chase step index over all sweeps
    NSLOT = Jmax // 3 + 2
    T_total = 3 * (n_sweeps - 1) + Jmax + 1

    # static index helpers for densify/bandify
    rr = jnp.arange(L)[:, None]
    cc = jnp.arange(L)[None, :]
    dmat = rr - cc
    lower_m = (dmat >= 0) & (dmat <= 2 * b)
    upper_m = (dmat < 0) & (-dmat <= 2 * b)
    idx_d = jnp.clip(jnp.abs(dmat), 0, 2 * b)
    idx_c = jnp.where(dmat >= 0, cc, rr)
    dd = jnp.arange(2 * b + 1)[:, None]
    cc2 = jnp.arange(L)[None, :]
    in_win = dd + cc2 <= L - 1

    def densify(strip):
        vals = strip[idx_d, idx_c]
        return jnp.where(lower_m, vals, jnp.where(upper_m, conj(vals), 0))

    def bandify(DW, strip):
        vals = DW[jnp.clip(cc2 + dd, 0, L - 1), cc2]
        return jnp.where(in_win, vals, strip)

    def chase_window(DW, r0):
        """Eliminate window-column 0 rows r0+1..r0+b-1 and apply the
        two-sided update (the PLASMA hb2st type-1/2/3 kernels fused:
        window-relative r0 is 1 for the sweep head, b for chase steps)."""
        x = lax.dynamic_slice(DW, (r0, 0), (b, 1))[:, 0]
        alpha = x[0]
        xnorm_sq = jnp.sum(jnp.abs(x[1:]) ** 2).astype(real_t)
        beta, tau, scale = _larfg(alpha, xnorm_sq, dtype)
        v = (x * scale).at[0].set(1.0)
        # left: rows r0..r0+b-1  <-  H^H rows  (H = I - tau v v^H)
        S = lax.dynamic_slice(DW, (r0, 0), (b, L))
        S = S - conj(tau) * v[:, None] * (conj(v) @ S)[None, :]
        DW = lax.dynamic_update_slice(DW, S, (r0, 0))
        # right: cols r0..r0+b-1  <-  cols H
        S2 = lax.dynamic_slice(DW, (0, r0), (L, b))
        S2 = S2 - tau * (S2 @ v)[:, None] * conj(v)[None, :]
        DW = lax.dynamic_update_slice(DW, S2, (0, r0))
        # exact eliminated-column pattern
        newcol = jnp.zeros((b,), dtype).at[0].set(beta)
        DW = lax.dynamic_update_slice(DW, newcol[:, None], (r0, 0))
        DW = lax.dynamic_update_slice(DW, conj(newcol)[None, :], (0, r0))
        return DW, v, tau

    VS0 = jnp.zeros((n_sweeps, Jmax + 1, b), dtype)
    TAUS0 = jnp.zeros((n_sweeps, Jmax + 1), dtype)

    def superstep(t, carry):
        W, VS, TAUS = carry
        i = jnp.arange(NSLOT)
        s = t // 3 - i
        j = t - 3 * s
        row0 = s + j * b + 1  # first reflector row
        valid = (s >= 0) & (s < n_sweeps) & (row0 <= n - 2)
        r0 = jnp.where(j == 0, 1, b)
        w0 = jnp.where(j == 0, s, s + (j - 1) * b + 1)
        w0c = jnp.where(valid, w0, n_pad - L)  # clamped dummy gather
        strips = jax.vmap(
            lambda w: lax.dynamic_slice(W, (0, w), (2 * b + 1, L))
        )(w0c)
        DW = jax.vmap(densify)(strips)
        DW2, v, tau = jax.vmap(chase_window)(DW, r0)
        strips2 = jax.vmap(bandify)(DW2, strips)
        # Write back ONLY the 2b stored columns a task can modify: a
        # task writes rows/cols R = [w0+r0, w0+r0+b-1] (r0 <= b), so its
        # modified stored entries W[d, c] all have c <= w0 + 2b - 1.
        # Concurrent windows sit 3b-1 columns apart, so these truncated
        # ranges are disjoint — writing the full L-wide strip would
        # re-deposit stale copies of the 2 overlap columns a neighboring
        # task just updated.  Each window writes with ONE contiguous
        # dynamic_update_slice (NSLOT static) instead of one big
        # elementwise scatter: TPU scatters move ~an element per cycle,
        # and this write was the dominant superstep cost at large n.
        # Invalid windows were clamped to w0 = n_pad - L; they must
        # write ZEROS there (not their dummy chase output): the clamp
        # region overlaps the read range of late valid windows for
        # b > 8, and it is zero-initialized padding.
        for i in range(NSLOT):
            blk = jnp.where(valid[i], strips2[i][:, : 2 * b], 0.0)
            W = lax.dynamic_update_slice(W, blk, (0, w0c[i]))
        s_w = jnp.where(valid, s, n_sweeps + 1)
        VS = VS.at[s_w, j].set(v, mode="drop")
        TAUS = TAUS.at[s_w, j].set(tau, mode="drop")
        return W, VS, TAUS

    W, VS, TAUS = lax.fori_loop(0, T_total, superstep, (W, VS0, TAUS0))
    d, e, u = realify(jnp.real(W[0, :n]), W[1, : n - 1])
    return d, e, u, VS, TAUS


@partial(jax.jit, static_argnames=("n", "b", "trans"))
def _unmtr_hb2st_sweep(
    VS: jnp.ndarray, TAUS: jnp.ndarray, Z: jnp.ndarray, n: int, b: int,
    trans: bool = False,
) -> jnp.ndarray:
    """Per-sweep rank-1 hb2st back-transform (the pre-round-5 kernel,
    kept as the parity reference for the diamond-blocked path below).

    Reflectors of one sweep act on pairwise-disjoint row blocks, so each
    sweep is ONE batched block-reflector application; sweeps run in a
    fori_loop (reverse order for Q Z).
    """
    if VS.shape[0] <= 1 and n <= 2:
        return Z
    n_sweeps, J1, _ = VS.shape
    m = Z.shape[1]
    dtype = Z.dtype
    complex_t = jnp.issubdtype(dtype, jnp.complexfloating)

    def conj(x):
        return jnp.conj(x) if complex_t else x

    def apply_panel(Zp, w):
        # one Z column panel of width w through ALL sweeps
        def sweep_apply(k, Zp):
            s = (n_sweeps - 1 - k) if not trans else k
            # sweep s's reflector rows s+1+j*b+arange(b) tile the
            # CONTIGUOUS range [s+1, s+1+J1*b): one dynamic_slice +
            # update_slice instead of a row gather/scatter pair (the
            # gather form was the stage-3 wall-clock bottleneck at
            # n=4096 on-chip).  Rows past n-1 fall in the zero padding
            # where VS/TAUS are zero, so the update is an exact no-op
            # there — no masking needed.
            v = VS[s]  # (J1, b)
            tau = TAUS[s]  # (J1,)
            tau = conj(tau) if trans else tau
            Zr = lax.dynamic_slice(Zp, (s + 1, 0), (J1 * b, w)).reshape(
                J1, b, w
            )
            wrow = jnp.einsum("jb,jbm->jm", conj(v), Zr)
            Zr = Zr - tau[:, None, None] * v[:, :, None] * wrow[:, None, :]
            return lax.dynamic_update_slice(
                Zp, Zr.reshape(-1, w), (s + 1, 0)
            )

        return lax.fori_loop(0, n_sweeps, sweep_apply, Zp)

    pad = b + J1 * b + 8
    # column blocking: running every sweep over one Z panel before
    # moving to the next keeps the streamed working set per sweep at
    # O(J1 b w) instead of O(J1 b m) — measured 50.7 s -> ~33 s for the
    # full n=4096 back-transform on-chip (tools/profile_unmtr.py)
    wpan = 512
    if m <= wpan:
        Zp = jnp.pad(Z, ((0, pad), (0, 0)))
        return apply_panel(Zp, m)[: Z.shape[0]]
    panels = []
    for c0 in range(0, m, wpan):
        w = min(wpan, m - c0)  # narrow last panel keeps the blocking
        Zp = jnp.pad(Z[:, c0 : c0 + w], ((0, pad), (0, 0)))
        panels.append(apply_panel(Zp, w)[: Z.shape[0]])
    return jnp.concatenate(panels, axis=1)


@partial(jax.jit, static_argnames=("n", "b", "trans"))
def unmtr_hb2st(
    VS: jnp.ndarray, TAUS: jnp.ndarray, Z: jnp.ndarray, n: int, b: int,
    trans: bool = False,
) -> jnp.ndarray:
    """Apply the hb2st back-transform: Z <- Q Z (trans=False) or Q^H Z
    (reference: src/unmtr_hb2st.cc), Q = product of all chase reflectors
    in execution order.

    Diamond-blocked compact-WY apply (the MAGMA/PLASMA bulge
    back-transform blocking): the reflectors of ``nbl = b`` consecutive
    sweeps at the SAME chase step j start on consecutive rows, so they
    form a trapezoidal (b+nbl-1, nbl) block reflector ("diamond") whose
    T factor turns nbl rank-1 updates into two GEMMs of arithmetic
    intensity nbl — the per-sweep kernel above streams all of Z once per
    sweep (intensity ~1) and was the stage-3 wall-clock ceiling at
    n=4096 on-chip (~25 s; this path does the same flops at GEMM rate).

    Ordering: same-sweep reflectors act on disjoint rows and commute, so
    the only constraints are cross-sweep: (s, j) before (s+1, j) [b-1
    overlapping rows] and (s, j+1) before (s+1, j) [one overlapping
    row].  Both are satisfied — and every other conflicting pair shown
    disjoint — by the schedule: sweep-blocks ascending, chase step j
    DESCENDING within a block, sweeps ascending inside a diamond, for
    Q^H Z; the exact reverse for Q Z.

    T factors come from the compact-WY identity T^{-1} = diag(1/tau) +
    striu(V^H V) (one batched gram GEMM + one batched triangular solve)
    rather than the sequential larft recurrence; tau == 0 padding
    columns get v = 0 and a placeholder unit diagonal, making them exact
    identity factors.
    """
    n_sweeps, J1, _ = VS.shape
    # placeholder VS from hb2st's n<=2 / b<=1 early exit: Q == I
    if n_sweeps < 1 or n <= 2 or b <= 1:
        return Z
    m = Z.shape[1]
    dtype = Z.dtype
    complex_t = jnp.issubdtype(dtype, jnp.complexfloating)

    def conj(x):
        return jnp.conj(x) if complex_t else x

    nbl = b
    nblk = -(-n_sweeps // nbl)
    ns_pad = nblk * nbl
    h = b + nbl - 1
    VSp = jnp.pad(VS, ((0, ns_pad - n_sweeps), (0, 0), (0, 0)))
    TAUSp = jnp.pad(TAUS, ((0, ns_pad - n_sweeps), (0, 0)))
    # tau == 0 (padding or H == I) must contribute an exact identity:
    # zero its v so the T^{-1} identity below holds with a unit diagonal
    VSp = jnp.where(TAUSp[:, :, None] != 0, VSp, 0)
    VSb = VSp.reshape(nblk, nbl, J1, b).transpose(0, 2, 1, 3)
    TB = TAUSp.reshape(nblk, nbl, J1).transpose(0, 2, 1)  # (nblk, J1, nbl)
    # shift sweep i of a diamond down i rows: out[i, i + r] = in[i, r].
    # Padding the rows to width h+1 and re-flattening IS that shift
    # (out flat index i*h + (i+r) == in flat index i*(h+1) + r), so the
    # trapezoid builds with zero scatters.
    Vsh = jnp.pad(VSb, ((0, 0), (0, 0), (0, 0), (0, nbl)))
    Vsh = Vsh.reshape(nblk, J1, nbl * (h + 1))[:, :, : nbl * h]
    DVt = Vsh.reshape(nblk, J1, nbl, h)  # (.., i, rows)
    DV = DVt.swapaxes(-1, -2)  # (nblk, J1, h, nbl)
    # T^{-1} = diag(1/tau) + striu(V^H V); G's contraction length is
    # h < 4096, safely under the emulation's k-chunk threshold
    G = jnp.einsum(
        "kjhi,kjhl->kjil", conj(DV), DV,
        precision=lax.Precision.HIGHEST,
    )
    safe = jnp.where(TB == 0, jnp.ones_like(TB), TB)
    invtau = jnp.where(TB == 0, jnp.ones_like(TB), 1.0 / safe)
    Tinv = jnp.triu(G, 1) + invtau[..., None] * jnp.eye(nbl, dtype=dtype)
    eye = jnp.broadcast_to(jnp.eye(nbl, dtype=dtype), Tinv.shape)
    Tf = jax.scipy.linalg.solve_triangular(Tinv, eye, lower=False)

    rows_needed = ns_pad + J1 * b + h
    Zp = jnp.pad(Z, ((0, rows_needed - Z.shape[0]), (0, 0)))
    total = nblk * J1

    def step(t, Zp):
        if trans:
            k = t // J1
            j = (J1 - 1) - t % J1
        else:
            k = (nblk - 1) - t // J1
            j = t % J1
        r0 = k * nbl + 1 + j * b
        V = lax.dynamic_slice(DV, (k, j, 0, 0), (1, 1, h, nbl))[0, 0]
        Tm = lax.dynamic_slice(Tf, (k, j, 0, 0), (1, 1, nbl, nbl))[0, 0]
        Tm = conj(Tm).T if trans else Tm  # P^H = I - V T^H V^H
        S = lax.dynamic_slice(Zp, (r0, 0), (h, m))
        Y = hp(conj(V).T, S)
        S = S - hp(V, hp(Tm, Y))
        return lax.dynamic_update_slice(Zp, S, (r0, 0))

    Zp = lax.fori_loop(0, total, step, Zp)
    return Zp[: Z.shape[0]]


@partial(jax.jit, static_argnames=("max_iter",))
def tridiag_eigvals_bisect(
    d: jnp.ndarray, e: jnp.ndarray, max_iter: int = 64
) -> jnp.ndarray:
    """All eigenvalues of a real symmetric tridiagonal by bisection with
    vectorized Sturm counts (reference: sterf.cc's role; the algorithm is
    LAPACK dstebz's, restructured so every eigenvalue bisects in parallel
    and each iteration is one scan over the matrix)."""
    n = d.shape[0]
    real_t = d.dtype
    if n == 1:
        return d
    e2 = (e * e).astype(real_t)
    # pivot floor (LAPACK dstebz's pivmin role).  NOT finfo.tiny: the
    # TPU f64 emulation's f32-grade exponent range flushes ~1e-307 to
    # zero, which would defeat the guard entirely on-chip.
    scale_p = jnp.maximum(
        jnp.maximum(jnp.abs(d).max(), e2.max() if n > 1 else 0.0), 1.0
    )
    pivmin = scale_p * jnp.asarray(np.float64(1e-30), real_t)
    # Gershgorin bounds
    ae = jnp.abs(e)
    rad = jnp.concatenate([ae, jnp.zeros(1, real_t)]) + jnp.concatenate(
        [jnp.zeros(1, real_t), ae]
    )
    lo0 = jnp.min(d - rad)
    hi0 = jnp.max(d + rad)
    span = jnp.maximum(hi0 - lo0, 1.0)
    lo = jnp.full((n,), lo0 - 1e-3 * span, real_t)
    hi = jnp.full((n,), hi0 + 1e-3 * span, real_t)
    ks = jnp.arange(n)

    def count_less(sig):
        """Sturm count: #eigenvalues < sig[k] for each k, one scan.

        The pivot guard is applied to the pivot BEFORE it is counted
        (dstebz convention): an exactly-zero pivot is an eigenvalue of
        a leading minor and must tally as negative — counting the raw
        qn < 0 silently dropped one count per zero pivot (periodic
        spectra like the free Toeplitz chain hit this every 3 rows)."""

        def body(q, de):
            di, e2i = de
            qn = (di - sig) - e2i / q
            qn = jnp.where(jnp.abs(qn) < pivmin, -pivmin, qn)
            return qn, qn < 0

        xs = (d, jnp.concatenate([jnp.zeros(1, real_t), e2]))
        _, neg = lax.scan(body, jnp.full_like(sig, 1.0), xs)
        # first step must not subtract: q1 = d0 - sig (e2 prepended 0, q0=1)
        return jnp.sum(neg, axis=0)

    def it(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        cnt = count_less(mid)
        go_left = cnt >= ks + 1
        return jnp.where(go_left, lo, mid), jnp.where(go_left, mid, hi)

    lo, hi = lax.fori_loop(0, max_iter, it, (lo, hi))
    return 0.5 * (lo + hi)
