"""Fast native blocked Householder QR for TPU.

The vendor geqrf lowering runs at ~27 GF/s in f64 on this chip (same
schedule-bound story as the vendor cholesky/LU — see ops/chol_kernels.py
and ops/lu_fast.py).  This module rebuilds the reference's CAQR-style
blocked schedule (reference: src/geqrf.cc:150-220 — local panel factor,
compact-WY T, trailing larfb with the trailing gemms dominating) as the
same three-level TPU schedule as lu_fast:

* micro level (``_qr_panel_strips``): fori_loop over ib-wide strips of
  an (m, nb) panel; per column a larfg reflector + rank-1 update of the
  strip tail; per strip a compact-WY T (larft) and one block-reflector
  application to the rest of the panel (two MXU gemms).
* panel level (``_block_qr``): fori_loop over the nb-wide panels of an
  (m, W) coarse block (rolled active region, single compiled shape);
  per panel a (nb, nb) T and a block-reflector application to the rest
  of the block; the per-panel T factors are stacked and returned.
* coarse level (``geqrf_fast``): <= coarse_panels Python-unrolled
  blocks with exact shrinking shapes; each finished block's panels are
  applied to the remaining global columns as exact-shape gemm pairs.

Returns LAPACK geqrf layout: V unit-lower below the diagonal, R on and
above, plus taus — drop-in for the vendor kernel in ops/householder.py.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .householder import _larfg, larft, materialize_v, apply_block_reflector

from ..internal.precision import hdot as _dot


def _conj(x):
    return jnp.conj(x) if jnp.iscomplexobj(x) else x


def _qr_panel_strips(
    P: jnp.ndarray, ib: int = 32
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Householder QR of an (m, w) panel whose elimination rows coincide
    with column indices (callers roll the active region to the top).
    w must be a multiple of ib.  Returns (P, taus): V below the
    diagonal, R on/above."""
    m, w = P.shape
    rows = jnp.arange(m)
    colsw = jnp.arange(w)

    def strip(s, carry):
        P, taus = carry
        j0 = s * ib
        S = lax.dynamic_slice(P, (0, j0), (m, ib))
        staus = jnp.zeros((ib,), P.dtype)
        for c in range(ib):
            jc = j0 + c
            x = S[:, c]
            below = rows > jc
            alpha = x[jc]
            xnorm_sq = jnp.sum(jnp.where(below, jnp.abs(x) ** 2, 0.0))
            beta, tau, scale = _larfg(alpha, xnorm_sq, P.dtype)
            v = jnp.where(below, x * scale, jnp.zeros((), P.dtype)).at[jc].set(1.0)
            if c + 1 < ib:
                # apply H^H to the strip tail only (static slice).  The
                # contraction is written as a broadcast-multiply-reduce:
                # the (1, m) x (m, t) matmul form lowers to a ~3x slower
                # MXU path on this toolchain.
                tail = S[:, c + 1 :]
                wrow = (tail * _conj(v)[:, None]).sum(0)
                tail = tail - _conj(tau) * v[:, None] * wrow[None, :]
                S = S.at[:, c + 1 :].set(tail)
            S = S.at[:, c].set(jnp.where(below, v, x).at[jc].set(beta))
            staus = staus.at[c].set(tau)
        P = lax.dynamic_update_slice(P, S, (0, j0))
        taus = lax.dynamic_update_slice(taus, staus, (j0,))
        # block-reflector application to the rest of the panel: V from
        # the strip (zeros on/above each column's elimination row)
        V = jnp.where(rows[:, None] > (jnp.arange(ib)[None, :] + j0), S, 0)
        V = V + jnp.where(
            rows[:, None] == (jnp.arange(ib)[None, :] + j0),
            jnp.ones((), P.dtype),
            0,
        )
        T = larft(V, staus)
        cmask = (colsw >= j0 + ib)[None, :]
        W1 = _dot(_conj(V).T, jnp.where(cmask, P, jnp.zeros((), P.dtype)))
        upd = _dot(V, _dot(_conj(T).T, W1))
        return P - jnp.where(cmask, upd, jnp.zeros((), P.dtype)), taus

    taus0 = jnp.zeros((w,), P.dtype)
    return lax.fori_loop(0, w // ib, strip, (P, taus0))


def _block_qr(
    B: jnp.ndarray, nb: int, ib: int = 32
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Householder QR of the first W columns of an (m, W) block, m >= W.
    One fori_loop over the W//nb panels (rolled active region keeps a
    single compiled shape).

    Returns (B, taus, Tstack): B in geqrf layout, Tstack the (W//nb,
    nb, nb) compact-WY factors (reused by the coarse trailing applies).
    """
    m, W = B.shape
    rows = jnp.arange(m)
    colsW = jnp.arange(W)
    nt = W // nb

    def panel(s, carry):
        B, taus, Tstack = carry
        j0 = s * nb
        colblk = lax.dynamic_slice(B, (0, j0), (m, nb))
        rolled = jnp.roll(colblk, -j0, axis=0)
        act = m - j0
        rolled = jnp.where((rows < act)[:, None], rolled, jnp.zeros((), B.dtype))
        Pf, ptaus = _qr_panel_strips(rolled, ib)
        Pn = jnp.roll(Pf, j0, axis=0)
        cur = lax.dynamic_slice(B, (0, j0), (m, nb))
        neu = jnp.where((rows >= j0)[:, None], Pn, cur)
        B = lax.dynamic_update_slice(B, neu, (0, j0))
        taus = lax.dynamic_update_slice(taus, ptaus, (j0,))
        # panel V/T in the block frame
        V = jnp.where(rows[:, None] > (jnp.arange(nb)[None, :] + j0), neu, 0)
        V = V + jnp.where(
            rows[:, None] == (jnp.arange(nb)[None, :] + j0),
            jnp.ones((), B.dtype),
            0,
        )
        T = larft(V, ptaus)
        Tstack = lax.dynamic_update_index_in_dim(Tstack, T, s, 0)
        # apply to the rest of the block
        cmask = (colsW >= j0 + nb)[None, :]
        W1 = _dot(_conj(V).T, jnp.where(cmask, B, jnp.zeros((), B.dtype)))
        upd = _dot(V, _dot(_conj(T).T, W1))
        return B - jnp.where(cmask, upd, jnp.zeros((), B.dtype)), taus, Tstack

    taus0 = jnp.zeros((W,), B.dtype)
    Tstack0 = jnp.zeros((nt, nb, nb), B.dtype)
    return lax.fori_loop(0, nt, panel, (B, taus0, Tstack0))


def geqrf_fast(
    G: jnp.ndarray, nb: int = 512, ib: int = 128, coarse_panels: int = 4
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked Householder QR of an (m, n) array, m >= n, n a multiple
    of nb.  Returns (G_factored, taus) in LAPACK geqrf layout — the
    drop-in contract of the vendor kernel, ~15-20x its measured f64
    rate on the chip."""
    m, n = G.shape
    assert m >= n and n % nb == 0, f"geqrf_fast: bad shape {(m, n)} nb={nb}"
    # ib=128 is tuned at nb=512 (tools/profile_geqrf_ib.py, n=8192:
    # 280 -> 346 GF/s over ib=32); smaller-nb fallback panels must keep
    # at least 4 strips per panel or the strip-level compact-WY applies
    # degenerate into the slow per-column tail path
    ib = min(ib, max(nb // 4, 32))
    nt = n // nb
    taus = jnp.zeros((n,), G.dtype)
    if nt <= 1:
        Gf, taus = _qr_panel_strips(G, ib)
        return Gf, taus

    NB = nb * (-(-nt // coarse_panels))
    k0 = 0
    while k0 < n:
        W = min(NB, n - k0)
        B = G[k0:, k0 : k0 + W]
        Bf, btaus, Tstack = _block_qr(B, nb, ib)
        G = G.at[k0:, k0 : k0 + W].set(Bf)
        taus = taus.at[k0 : k0 + W].set(btaus)
        rest = n - k0 - W
        if rest > 0:
            C = G[k0:, k0 + W :]
            for p in range(W // nb):
                Vp = materialize_v(Bf[:, p * nb : (p + 1) * nb], offset=p * nb)
                C = apply_block_reflector(Vp, Tstack[p], C, trans=True)
            G = G.at[k0:, k0 + W :].set(C)
        k0 += W
    return G, taus
