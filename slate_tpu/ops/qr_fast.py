"""Fast native blocked Householder QR for TPU.

The vendor geqrf lowering runs at ~27 GF/s in f64 on this chip (same
schedule-bound story as the vendor cholesky/LU — see ops/chol_kernels.py
and ops/lu_fast.py).  This module rebuilds the reference's CAQR-style
blocked schedule (reference: src/geqrf.cc:150-220 — local panel factor,
compact-WY T, trailing larfb with the trailing gemms dominating) as the
same three-level TPU schedule as lu_fast:

* micro level (``_qr_panel_strips``): fori_loop over ib-wide strips of
  an (m, nb) panel; per column a larfg reflector + rank-1 update of the
  strip tail; per strip a compact-WY T (larft) and one block-reflector
  application to the rest of the panel (two MXU gemms).
* panel level (``_block_qr``): fori_loop over the nb-wide panels of an
  (m, W) coarse block (rolled active region, single compiled shape);
  per panel a (nb, nb) T and a block-reflector application to the rest
  of the block; the per-panel T factors are stacked and returned.
* coarse level (``geqrf_fast``): <= coarse_panels Python-unrolled
  blocks with exact shrinking shapes; each finished block's panels are
  applied to the remaining global columns as exact-shape gemm pairs.

Returns LAPACK geqrf layout: V unit-lower below the diagonal, R on and
above, plus taus — drop-in for the vendor kernel in ops/householder.py.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .chol_kernels import RECURSIVE_MIN_N, _lat_height, split_point
from .householder import _larfg, larft, materialize_v, apply_block_reflector

from ..internal.precision import hdot as _dot


def _conj(x):
    return jnp.conj(x) if jnp.iscomplexobj(x) else x


def _qr_panel_strips(
    P: jnp.ndarray, ib: int = 32
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Householder QR of an (m, w) panel whose elimination rows coincide
    with column indices (callers roll the active region to the top).
    w must be a multiple of ib.  Returns (P, taus): V below the
    diagonal, R on/above."""
    m, w = P.shape
    rows = jnp.arange(m)
    colsw = jnp.arange(w)

    def strip(s, carry):
        P, taus = carry
        j0 = s * ib
        S = lax.dynamic_slice(P, (0, j0), (m, ib))
        staus = jnp.zeros((ib,), P.dtype)
        for c in range(ib):
            jc = j0 + c
            x = S[:, c]
            below = rows > jc
            alpha = x[jc]
            xnorm_sq = jnp.sum(jnp.where(below, jnp.abs(x) ** 2, 0.0))
            beta, tau, scale = _larfg(alpha, xnorm_sq, P.dtype)
            v = jnp.where(below, x * scale, jnp.zeros((), P.dtype)).at[jc].set(1.0)
            if c + 1 < ib:
                # apply H^H to the strip tail only (static slice).  The
                # contraction is written as a broadcast-multiply-reduce:
                # the (1, m) x (m, t) matmul form lowers to a ~3x slower
                # MXU path on this toolchain.
                tail = S[:, c + 1 :]
                wrow = (tail * _conj(v)[:, None]).sum(0)
                tail = tail - _conj(tau) * v[:, None] * wrow[None, :]
                S = S.at[:, c + 1 :].set(tail)
            S = S.at[:, c].set(jnp.where(below, v, x).at[jc].set(beta))
            staus = staus.at[c].set(tau)
        P = lax.dynamic_update_slice(P, S, (0, j0))
        taus = lax.dynamic_update_slice(taus, staus, (j0,))
        # block-reflector application to the rest of the panel: V from
        # the strip (zeros on/above each column's elimination row)
        V = jnp.where(rows[:, None] > (jnp.arange(ib)[None, :] + j0), S, 0)
        V = V + jnp.where(
            rows[:, None] == (jnp.arange(ib)[None, :] + j0),
            jnp.ones((), P.dtype),
            0,
        )
        T = larft(V, staus)
        cmask = (colsw >= j0 + ib)[None, :]
        W1 = _dot(_conj(V).T, jnp.where(cmask, P, jnp.zeros((), P.dtype)))
        upd = _dot(V, _dot(_conj(T).T, W1))
        return P - jnp.where(cmask, upd, jnp.zeros((), P.dtype)), taus

    taus0 = jnp.zeros((w,), P.dtype)
    return lax.fori_loop(0, w // ib, strip, (P, taus0))


def _block_qr(
    B: jnp.ndarray, nb: int, ib: int = 32
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Householder QR of the first W columns of an (m, W) block, m >= W.
    One fori_loop over the W//nb panels (rolled active region keeps a
    single compiled shape).

    Returns (B, taus, Tstack): B in geqrf layout, Tstack the (W//nb,
    nb, nb) compact-WY factors (reused by the coarse trailing applies).
    """
    m, W = B.shape
    rows = jnp.arange(m)
    colsW = jnp.arange(W)
    nt = W // nb

    def panel(s, carry):
        B, taus, Tstack = carry
        j0 = s * nb
        colblk = lax.dynamic_slice(B, (0, j0), (m, nb))
        rolled = jnp.roll(colblk, -j0, axis=0)
        act = m - j0
        rolled = jnp.where((rows < act)[:, None], rolled, jnp.zeros((), B.dtype))
        Pf, ptaus = _qr_panel_strips(rolled, ib)
        Pn = jnp.roll(Pf, j0, axis=0)
        cur = lax.dynamic_slice(B, (0, j0), (m, nb))
        neu = jnp.where((rows >= j0)[:, None], Pn, cur)
        B = lax.dynamic_update_slice(B, neu, (0, j0))
        taus = lax.dynamic_update_slice(taus, ptaus, (j0,))
        # panel V/T in the block frame
        V = jnp.where(rows[:, None] > (jnp.arange(nb)[None, :] + j0), neu, 0)
        V = V + jnp.where(
            rows[:, None] == (jnp.arange(nb)[None, :] + j0),
            jnp.ones((), B.dtype),
            0,
        )
        T = larft(V, ptaus)
        Tstack = lax.dynamic_update_index_in_dim(Tstack, T, s, 0)
        # apply to the rest of the block
        cmask = (colsW >= j0 + nb)[None, :]
        W1 = _dot(_conj(V).T, jnp.where(cmask, B, jnp.zeros((), B.dtype)))
        upd = _dot(V, _dot(_conj(T).T, W1))
        return B - jnp.where(cmask, upd, jnp.zeros((), B.dtype)), taus, Tstack

    taus0 = jnp.zeros((W,), B.dtype)
    Tstack0 = jnp.zeros((nt, nb, nb), B.dtype)
    return lax.fori_loop(0, nt, panel, (B, taus0, Tstack0))


def geqrf_fast(
    G: jnp.ndarray, nb: int = 512, ib: int = 128, coarse_panels: int = 4
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked Householder QR of an (m, n) array, m >= n, n a multiple
    of nb.  Returns (G_factored, taus) in LAPACK geqrf layout — the
    drop-in contract of the vendor kernel, ~15-20x its measured f64
    rate on the chip."""
    m, n = G.shape
    assert m >= n and n % nb == 0, f"geqrf_fast: bad shape {(m, n)} nb={nb}"
    # ib=128 is tuned at nb=512 (tools/profile_geqrf_ib.py, n=8192:
    # 280 -> 346 GF/s over ib=32); smaller-nb fallback panels must keep
    # at least 4 strips per panel or the strip-level compact-WY applies
    # degenerate into the slow per-column tail path
    ib = min(ib, max(nb // 4, 32))
    nt = n // nb
    taus = jnp.zeros((n,), G.dtype)
    if nt <= 1:
        Gf, taus = _qr_panel_strips(G, ib)
        return Gf, taus

    NB = nb * (-(-nt // coarse_panels))
    k0 = 0
    while k0 < n:
        W = min(NB, n - k0)
        B = G[k0:, k0 : k0 + W]
        Bf, btaus, Tstack = _block_qr(B, nb, ib)
        G = G.at[k0:, k0 : k0 + W].set(Bf)
        taus = taus.at[k0 : k0 + W].set(btaus)
        rest = n - k0 - W
        if rest > 0:
            C = G[k0:, k0 + W :]
            for p in range(W // nb):
                Vp = materialize_v(Bf[:, p * nb : (p + 1) * nb], offset=p * nb)
                C = apply_block_reflector(Vp, Tstack[p], C, trans=True)
            G = G.at[k0:, k0 + W :].set(C)
        k0 += W
    return G, taus


# ---------------------------------------------------------------------------
# Recursive (divide & conquer) schedule (Elmroth & Gustavson, "Applying
# recursion to serial and parallel QR factorization", IBM JRD 44(4),
# 2000 — see PAPERS.md): factor the left column half recursively, apply
# its nb_switch-wide compact-WY panels to the right half at exact
# shapes, recurse on the trailing (m-n1, n-n1) block.  Following E&G's
# hybrid finding, the compact-WY T factors are kept at panel width
# (nb_switch) rather than combined across halves — a combined
# half-width T costs O(n^3) extra gemm FLOPs at the top split, which is
# exactly the waste this schedule exists to remove.
# ---------------------------------------------------------------------------


def _pick_ib(w: int, ib: int) -> int:
    for d in (ib, 32, 16, 8, 4, 2, 1):
        if d <= ib and w % d == 0:
            return d
    return 1


def _geqrf_rec(G, nb_switch, ib, family="recursive"):
    """Returns (G_factored, taus, panels): panels = [(offset, w, T)]
    for each nb_switch-wide base panel, T its compact-WY factor in the
    frame of G (reflector j of the panel eliminates row offset+j).
    ``family="pallas"`` assembles T through the fused compact-WY kernel
    (ops/pallas/panel_kernels.larft) instead of the jnp assembly."""
    m, n = G.shape
    if n <= nb_switch:
        P, taus = _qr_panel_strips(G, _pick_ib(n, ib))
        if family == "pallas":
            from .pallas import panel_kernels as pk

            T = pk.larft(materialize_v(P), taus)
        else:
            T = larft(materialize_v(P), taus)
        return P, taus, [(0, n, T)]
    s = split_point(n)
    F1, t1, P1 = _geqrf_rec(G[:, :s], nb_switch, ib, family)
    # apply the left half's panels to the right half, oldest first
    # (Q^H C applies the leftmost panel's reflectors first).  V is kept
    # full height (zeros above the panel offset) so the gemm shapes stay
    # on the lattice — the zero-row waste is O(nb/n) and accounted.
    C = G[:, s:]
    for off, w, T in P1:
        V = materialize_v(F1[:, off : off + w], offset=off)
        C = apply_block_reflector(V, T, C, trans=True)
    # canonical-lattice height for the trailing block: zero row pad
    # keeps R/taus/reflectors identical and the distinct compiled
    # heights O(log) (see chol_kernels._lat_height)
    mc = _lat_height(m - s)
    C2 = C[s:]
    if mc > m - s:
        C2 = jnp.pad(C2, ((0, mc - (m - s)), (0, 0)))
    F2, t2, P2 = _geqrf_rec(C2, nb_switch, ib, family)
    F2 = F2[: m - s]
    out = jnp.concatenate(
        [F1, jnp.concatenate([C[:s], F2], axis=0)], axis=1
    )
    panels = P1 + [(off + s, w, T) for off, w, T in P2]
    return out, jnp.concatenate([t1, t2]), panels


def geqrf_recursive(
    G: jnp.ndarray, nb_switch: int = 256, ib: int = 32,
    family: str = "recursive",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Divide & conquer blocked Householder QR of (m, n), m >= n, any n.
    Returns (G_factored, taus) in LAPACK geqrf layout — the drop-in
    contract of ``geqrf_fast`` / the vendor kernel.

    Shapes shrink statically down the halving lattice: base panels
    factor at exact (canonical-lattice) heights, trailing applies are
    exact-width gemm pairs — executed FLOPs land within ~1.4x of the
    2 n^2 (m - n/3) model (the flat ``_block_qr`` inner loop runs every
    apply at full block width), from O(log) distinct width shapes and
    O(log) canonical heights (``geqrf_schedule_flops`` accounts both).
    """
    m, n = G.shape
    assert m >= n, f"geqrf_recursive: need m >= n, got {(m, n)}"
    mc = _lat_height(m)
    if mc != m:
        # zero pad rows: QR of [A; 0] has the same R and taus, reflector
        # entries in pad rows are exact zeros (larfg of a zero tail)
        Gp = jnp.pad(G, ((0, mc - m), (0, 0)))
        F, taus, _ = _geqrf_rec(Gp, nb_switch, ib, family)
        return F[:m], taus
    F, taus, _ = _geqrf_rec(G, nb_switch, ib, family)
    return F, taus


def geqrf_pallas(
    G: jnp.ndarray, nb_switch: int = 256, ib: int = 32
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The recursive lattice with the compact-WY base case on the fused
    Pallas kernel — a positional-only entry point the drivers can wrap
    in a gated jit with static (nb_switch, ib)."""
    return geqrf_recursive(G, nb_switch, ib, family="pallas")


def flat_nb(n: int) -> int:
    """The block size the flat schedule uses for width n — one picker
    shared by the kernel dispatch and the FLOP accounting (the same
    512/256/128 ladder as householder.geqrf)."""
    for nbf in (512, 256, 128):
        if n % nbf == 0:
            return nbf
    return 0  # no flat tiling exists for this width


def geqrf_flat(G: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The flat three-level schedule at its own block-size pick — the
    explicit Option.Schedule=flat entry point (honored on every
    backend, like the chol/lu flat routes)."""
    return geqrf_fast(G, flat_nb(G.shape[1]))


def resolve_qr_schedule(m: int, n: int, schedule: str = "auto") -> str:
    """The route the eager QR dispatch takes for this shape/backend —
    one resolver shared by the driver's kernel choice and its FLOP
    accounting so the recorded factor.geqrf.* counters always describe
    the program actually traced.  Explicit flat/recursive are honored
    on every backend (when the shape admits them); auto mirrors
    householder.geqrf: vendor LAPACK on CPU and at small/rectangular
    shapes, the native schedules at large n on accelerators."""
    import jax

    from .householder import _geqrf_xla

    if schedule in ("recursive", "pallas") and m >= n:
        return schedule
    tiled = m >= n and flat_nb(n) > 0
    if schedule == "flat" and tiled:
        return "flat"
    if schedule == "auto":
        if jax.default_backend() != "cpu" and m >= n and n >= RECURSIVE_MIN_N:
            return "pallas"
        if jax.default_backend() != "cpu" and n >= 1024 and tiled:
            return "flat"
    if _geqrf_xla is not None:
        return "vendor"
    # no XLA geqrf primitive: householder.geqrf_blocked runs — book the
    # tiled case as flat (it is a masked blocked loop); the untiled
    # corner keeps the vendor model (unreachable on this toolchain)
    return "flat" if tiled else "vendor"


def _rec_widths(n: int, nb_switch: int):
    """Base-panel widths of the column recursion, left to right."""
    if n <= nb_switch:
        return [n]
    s = split_point(n)
    return _rec_widths(s, nb_switch) + _rec_widths(n - s, nb_switch)


def geqrf_schedule_flops(
    m: int,
    n: int,
    nb: int = 512,
    schedule: str = "recursive",
    nb_switch: int = 256,
    ib: int = 32,
    m_true: int | None = None,
    n_true: int | None = None,
) -> dict:
    """(model, exec, units) FLOP accounting for one QR of (m, n),
    m >= n, mirroring the executed schedule.  model = 2 n^2 (m - n/3),
    the LAPACK geqrf count (compact-WY T formation is schedule
    overhead, counted in exec only) — computed from (m_true, n_true)
    when given so padded kernel shapes report waste against the TRUE
    problem size."""
    mt, nt_ = (m_true or m), (n_true or n)
    model = 2.0 * float(nt_) * nt_ * (mt - nt_ / 3.0)
    if schedule == "vendor":
        # the vendor kernel still runs on the PADDED array
        return {"model": model,
                "exec": 2.0 * float(n) * n * (m - n / 3.0),
                "units": {("vendor_qr", m, n)}}

    # the pallas compact-WY kernel fuses the same Gram + assembly FLOPs
    # (vendor solve stays at <= nb both ways) — only the unit differs
    panel_unit = "pallas_qr_panel" if schedule == "pallas" else "qr_panel"

    def base_flops(M, w):
        ibb = _pick_ib(w, ib)
        strips = max(w // ibb, 1)
        # per strip: micro rank-1s + two full-panel-width masked WY gemms
        ex = strips * (2.0 * M * ibb * ibb + 4.0 * M * ibb * w)
        ex += 2.0 * M * w * w + w**3 / 3.0  # larft (VhV + solve)
        return ex, {(panel_unit, M, w)}

    if schedule == "flat":
        # geqrf_fast at the dispatch's own block-size pick (flat_nb —
        # NOT the driver's lay.nb): <= 4 coarse blocks; _block_qr
        # applies every panel at the full block width (masked), coarse
        # applies exact
        nbf = flat_nb(n) or (nb if n % nb == 0 else 128)
        nt = max(n // nbf, 1)
        NB = nbf * (-(-nt // 4))
        ex, units = 0.0, set()
        k0 = 0
        while k0 < n:
            W = min(NB, n - k0)
            M = m - k0
            for _ in range(W // nbf):
                fb, ub = base_flops(M, nbf)
                ex += fb + 4.0 * M * nbf * W  # full-width masked apply
                units |= ub
            units |= {("qr_apply", M, nbf, W)}
            rest = n - k0 - W
            if rest > 0:
                ex += (W // nbf) * 4.0 * M * nbf * rest
                units |= {("qr_apply", M, nbf, rest)}
            k0 += W
        return {"model": model, "exec": ex, "units": units}

    def rec(M, n):
        if n <= nb_switch:
            return base_flops(M, n)
        s = split_point(n)
        f1, u1 = rec(M, s)
        fa, ua = 0.0, set()
        for w in _rec_widths(s, nb_switch):
            # full-height apply: 2 gemms at (w, M, n-s) + the T multiply
            fa += 4.0 * M * w * (n - s) + 2.0 * w * w * (n - s)
            ua |= {("qr_apply", M, w, n - s)}
        Mc = _lat_height(M - s)
        f2, u2 = rec(Mc, n - s)
        return f1 + fa + f2, u1 | ua | u2

    Mc0 = _lat_height(m)
    ex, units = rec(Mc0, n)
    return {"model": model, "exec": ex, "units": units}
