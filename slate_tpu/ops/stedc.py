"""Native divide & conquer symmetric tridiagonal eigensolver (stedc).

The reference implements Cuppen's D&C across ~2.5 kLoC
(reference: src/stedc.cc, stedc_deflate.cc:1-595, stedc_merge.cc:23-31
laed4 secular roots, stedc_secular.cc, stedc_solve.cc, stedc_sort.cc,
stedc_z_vector.cc).  This is the TPU-native redesign: the merge tree is
a bottom-up loop over log2(N) levels, every level's merges run as ONE
vmapped batch, the laed4 secular roots are found by vectorized
bisection+Newton (all roots of all merges in parallel — pure VPU work),
deflation is masked compaction-free arithmetic (static shapes), and the
O(n^3) back-rotation Q @ U is a batched MXU gemm — which is where the
FLOPs land, exactly as in the reference.

Key numerical devices (same as LAPACK dlaed3/dlaed4):

* secular roots are solved in pole-shifted coordinates mu = lambda -
  d_i, so lambda - d_j = (d_i - d_j) + mu stays accurate for the
  eigenvector assembly;
* the z-vector is *recomputed* from the computed roots via the Lowner
  formula (Gu-Eisenstat), which makes the assembled eigenvectors
  numerically orthogonal even for clustered poles;
* deflation: (a) tiny rho*|z_j| passes the eigenpair through directly,
  (b) near-equal pole pairs are combined by Givens rotations in
  alternating even/odd passes (vectorized; handles clusters up to
  ~2^passes wide — degenerate wider clusters still deflate via (a)
  after the rotations concentrate their weight).

The subproblem boundary adjustment (Cuppen subtracts |e_m| from both
boundary diagonals before recursing) telescopes: in a full binary tree
every interior edge is cut exactly once, so the size-1 leaves start
from d_j - |e_{j-1}| - |e_j| and each merge's rank-one term restores
its own edge.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# the emulation-safe HIGHEST matmul: k-chunks cancellation-sensitive
# f64 contractions at k >= 4096 (the chip's emulation loses its
# compensation there — see internal/precision.py).  The merge
# back-rotations and the final polish are exactly such products.
from ..internal.precision import hdot as _dot

_BISECT = 18  # geometric bisection phase: localizes to ~2e-4 relative
_NEWTON = 10  # hybrid Newton/geometric phase: eps from there


_barrier_warned = False


def _opt_barrier(xs):
    """lax.optimization_barrier, degrading to identity where the
    toolchain lacks its batching rule (the merges are vmapped; some jax
    versions cannot batch the barrier).  The barrier only defends the
    chip's f64-emulated reductions against log->sum fusion — on real-f64
    backends dropping it is numerically harmless; on emulated-f64
    backends the degradation is surfaced via a one-time warning and the
    `stedc.barrier_dropped` metrics counter."""
    global _barrier_warned
    try:
        return lax.optimization_barrier(xs)
    except NotImplementedError:
        from ..aux import metrics as _metrics

        _metrics.inc("stedc.barrier_dropped")
        if not _barrier_warned and jax.default_backend() not in ("cpu", "gpu"):
            import warnings

            warnings.warn(
                "optimization_barrier unsupported under vmap on this jax; "
                "stedc's emulated-f64 fusion guard is dropped — "
                "eigenvector orthogonality may degrade (BENCH_NOTES r5)",
                stacklevel=2,
            )
            _barrier_warned = True
        return xs


def _secular_roots(D, z2, rho, nondefl, nxt_idx, gap_hi):
    """Vectorized secular roots with nearest-pole shifting (the laed4
    numerics, reference: src/stedc_merge.cc:23-31 / LAPACK dlaed4).

    For each nondeflated i the root of
        f(lam) = 1 + rho * sum_j z2_j / (D_j - lam)
    in (D_i, D_i + gap_hi_i) is located as lam = D[k_i] + sgn_i * x_i,
    where k_i is the nearer bracket pole (decided by the sign of f at
    the interval midpoint) and x_i > 0 the offset from it.  x is found
    by *geometric* bisection (midpoint sqrt(lo*hi)), which delivers
    RELATIVE precision — a root can sit many orders of magnitude closer
    to its pole than the interval width (small z_i), where arithmetic
    bisection and Newton both stall — followed by a keep-best Newton
    polish.

    Returns (kshift, sgn, x): lam_i = D[kshift_i] + sgn_i * x_i.
    nxt_idx[i] = index of the next nondeflated pole (n2 if none).
    """
    n2 = D.shape[0]
    dt = D.dtype
    # NOT finfo.tiny: the TPU f64 emulation carries an f32 exponent
    # range — values below ~1e-38 flush to zero — so floors must stay
    # well above it (stedc() normalizes the problem to O(1) scale).
    tiny = jnp.asarray(np.float64(1e-30), dt)
    idx = jnp.arange(n2)

    # decide the shift side with one arithmetic-midpoint evaluation
    mid = D + 0.5 * gap_hi

    def f_at(lam):  # lam: (n2,) candidate per root -> f values
        den = D[None, :] - lam[:, None]
        safe = jnp.where(den == 0, tiny, den)
        terms = jnp.where(nondefl[None, :], z2[None, :] / safe, 0.0)
        return 1.0 + rho * terms.sum(axis=1)

    has_upper = nxt_idx < n2
    f_mid = f_at(mid)
    right = has_upper & (f_mid < 0)  # root in the upper half
    kshift = jnp.where(right, jnp.minimum(nxt_idx, n2 - 1), idx)
    sgn = jnp.where(right, -1.0, 1.0).astype(dt)
    Ds = D[kshift]
    # span of the offset variable x: distance from the shift pole to the
    # midpoint (the root is on this side of the midpoint by choice);
    # the last root (no upper pole) keeps its full interval
    span = jnp.where(right, Ds - mid, jnp.where(has_upper, mid - D, gap_hi))
    span = jnp.maximum(span, tiny)

    # f evaluated ENTIRELY in shifted coordinates: den = (D_j - D_s) -
    # sgn*x.  Reconstructing lam = D_s + sgn*x first would round away
    # sub-ulp offsets and flip the own-pole sign (z^2/+0 = +inf).
    deltaS = D[None, :] - Ds[:, None]  # (i, j) -> D_j - D_shift_i

    def fx(x):  # offset -> f values (n2,)
        den = deltaS - (sgn * x)[:, None]
        safe = jnp.where(den == 0, tiny, den)
        terms = jnp.where(nondefl[None, :], z2[None, :] / safe, 0.0)
        return 1.0 + rho * terms.sum(axis=1)

    def fpx(x):  # |df/dx| = f'(lam) (positive), for Newton in x
        den = deltaS - (sgn * x)[:, None]
        safe = jnp.where(den == 0, tiny, den)
        terms = jnp.where(nondefl[None, :], z2[None, :] / (safe * safe), 0.0)
        return rho * terms.sum(axis=1)

    # x-space: f moves away from the pole singularity; at x -> 0+ the
    # own-pole term dominates: left shift -> -inf, right shift -> +inf.
    # "root is above x" <=> f(x) has the sign it takes near the pole.
    pole_sign = jnp.where(right, 1.0, -1.0).astype(dt)

    # absolute floor 1e-34: span*1e-25 can drop below the chip's ~1e-38
    # flush-to-zero line when the pole gap is itself tiny (deflation
    # guarantees nondeflated gaps > tol ~ 8 eps, so the floor is safe)
    lo = jnp.maximum(
        span * jnp.asarray(np.float64(1e-25), dt),
        jnp.asarray(np.float64(1e-34), dt),
    )
    hi = span

    def gbisect(_, carry):
        lo, hi = carry
        x = jnp.sqrt(lo) * jnp.sqrt(hi)
        fm = fx(x)
        toward = fm * pole_sign > 0  # still on the pole side of the root
        lo = jnp.where(toward, x, lo)
        hi = jnp.where(toward, hi, x)
        return lo, hi

    lo, hi = lax.fori_loop(0, _BISECT, gbisect, (lo, hi))
    x = jnp.sqrt(lo) * jnp.sqrt(hi)

    # bracket-maintained hybrid Newton with geometric fallback and
    # keep-best answer: the short geometric phase localizes to ~1e-4
    # relative, Newton squares that to eps in a few steps, and any
    # escape from the bracket falls back to the geometric midpoint.
    # (keep-best matters: once an iterate lands on the root, the
    # bracket pins it to an endpoint and the fallback jumps away.)
    def hybrid(_, carry):
        x, lo, hi, x_best, fbest = carry
        fm = fx(x)
        toward = fm * pole_sign > 0
        lo = jnp.where(toward, x, lo)
        hi = jnp.where(toward, hi, x)
        ab = jnp.abs(fm)
        better = ab < fbest
        x_best = jnp.where(better, x, x_best)
        fbest = jnp.where(better, ab, fbest)
        xn = x - sgn * fm / jnp.maximum(fpx(x), tiny)
        bad = ~jnp.isfinite(xn) | (xn <= lo) | (xn >= hi)
        xn = jnp.where(bad, jnp.sqrt(lo) * jnp.sqrt(hi), xn)
        return xn, lo, hi, x_best, fbest

    inf0 = jnp.full_like(x, jnp.asarray(np.float64(1e30), dt))
    x, lo, hi, x_best, fbest = lax.fori_loop(
        0, _NEWTON, hybrid, (x, lo, hi, x, inf0)
    )
    fm = jnp.abs(fx(x))
    x = jnp.where(fm < fbest, x, x_best)
    return kshift, sgn, x


def _merge_setup(w1, QT1, w2, QT2, e_r, eps):
    """Phase 0 of a Cuppen merge: build (D, z, QT) for the rank-one
    coupled problem and sort the poles ascending.  Eigenvector blocks
    are carried in TRANSPOSED form across the whole tree (row i of QT
    is the eigenvector belonging to w[i]): every permutation and Givens
    pass then gathers/updates ROWS — the TPU-friendly (sublane) axis —
    instead of lanes, which is what made the n=4096 top merges
    pathologically slow on-chip."""
    s = w1.shape[0]
    n2 = 2 * s
    dt = w1.dtype

    sigma = jnp.where(e_r < 0, -1.0, 1.0).astype(dt)
    rho = jnp.abs(e_r)

    D = jnp.concatenate([w1, w2])
    # z = (sigma * last row of Q1, first row of Q2) = (sigma * last
    # column of QT1, first column of QT2): static lane slices, cheap
    z = jnp.concatenate([sigma * QT1[:, -1], QT2[:, 0]])
    QT = jnp.zeros((n2, n2), dt)
    QT = QT.at[:s, :s].set(QT1).at[s:, s:].set(QT2)

    # sort poles ascending
    order = jnp.argsort(D)
    D = D[order]
    z = z[order]
    QT = QT[order, :]

    scale = jnp.maximum(jnp.abs(D).max(), rho * (z * z).sum())
    tol = 8.0 * eps * jnp.maximum(scale, jnp.asarray(np.float64(1e-30), dt))
    return D, z, QT, rho, tol


def _deflate(D, z, QT, rho, tol):
    """Deflation phases (a) + (b): drop negligible coupling weight and
    combine near-equal pole pairs by Givens passes (vectorized; rank
    pairing halves an equal-pole run per pass).  QT rows are the
    eigenvector columns."""
    n2 = D.shape[0]
    # --- deflation (a): negligible coupling weight --------------------
    nondefl = rho * jnp.abs(z) > tol
    # --- deflation (b): near-equal poles, Givens passes ---------------
    idx = jnp.arange(n2)

    def defl_pass(carry):
        p, D, z, QT, nondefl, _, prev = carry
        # pair nondeflated entries by their rank among the nondeflated
        # (even rank leads, its next nondeflated neighbour follows) —
        # index-adjacent pairing would stall on equal-pole runs once the
        # in-between entries deflate; rank pairing halves a run per
        # pass, so log2(n2) passes clear any cluster
        rank = jnp.cumsum(nondefl.astype(jnp.int32)) - 1
        posn = jnp.where(nondefl, idx, n2)
        suf = lax.cummin(posn[::-1])[::-1]
        nxt_nd = jnp.concatenate([suf[1:], jnp.full((1,), n2, jnp.int32)])
        posp = jnp.where(nondefl, idx, -1)
        prv_nd = jnp.concatenate(
            [jnp.full((1,), -1, jnp.int32), lax.cummax(posp)[:-1]]
        )
        # alternate pairing parity: a cluster starting at odd rank would
        # otherwise never align with the even-rank leads
        is_lead = nondefl & (rank % 2 == (p % 2)) & (nxt_nd < n2)
        nxt_c = jnp.clip(nxt_nd, 0, n2 - 1)
        act_lead = is_lead & (jnp.abs(D[nxt_c] - D) <= tol)
        is_fol = nondefl & (rank % 2 != (p % 2))
        prv_c = jnp.clip(prv_nd, 0, n2 - 1)
        lead = jnp.where(is_fol, prv_c, idx)
        act = jnp.where(is_fol, act_lead[prv_c] & (prv_nd >= 0), act_lead)
        act = act & (is_lead | is_fol)
        fol = jnp.clip(nxt_nd[lead], 0, n2 - 1)
        zl = z[lead]
        zf = z[fol]
        r = jnp.sqrt(zl * zl + zf * zf)
        rsafe = jnp.where(r == 0, 1.0, r)
        c = zl / rsafe
        sn = zf / rsafe
        # z: lead <- r, follower <- 0
        z = jnp.where(act, jnp.where(is_lead, r, 0.0), z)
        # diagonal mix (the dropped off-diagonal (D_l - D_f) c s <= tol)
        Dl = D[lead]
        Df = D[fol]
        D = jnp.where(
            act,
            jnp.where(
                is_lead, c * c * Dl + sn * sn * Df, sn * sn * Dl + c * c * Df
            ),
            D,
        )
        # rotate eigenvector pairs (rows of QT):
        #   lead <- c q_l + s q_f, fol <- -s q_l + c q_f
        ql = QT[lead, :]
        qf = QT[fol, :]
        Qrot = jnp.where(
            is_lead[:, None],
            c[:, None] * ql + sn[:, None] * qf,
            -sn[:, None] * ql + c[:, None] * qf,
        )
        QT = jnp.where(act[:, None], Qrot, QT)
        nondefl = nondefl & ~(act & is_fol)
        return p + 1, D, z, QT, nondefl, jnp.any(act), carry[5]

    # early-exit after TWO consecutive quiet passes (the parities
    # alternate, and one parity being quiet says nothing about the
    # other); most merges need 0-2 passes, only degenerate clusters use
    # the full 2*log2(n2) budget (each pass halves a run)
    npass = max(4, 2 * int(np.ceil(np.log2(n2))) + 2)
    _, D, z, QT, nondefl, _, _ = lax.while_loop(
        lambda c: (c[0] < npass) & (c[5] | c[6]),
        defl_pass,
        (jnp.int32(0), D, z, QT, nondefl, jnp.bool_(True), jnp.bool_(True)),
    )
    # re-apply deflation (a) after rotations moved the weight
    nondefl = nondefl & (rho * jnp.abs(z) > tol)
    z = jnp.where(nondefl, z, 0.0)
    return D, z, QT, nondefl


def _solve_secular(D, z, rho, nondefl, tol):
    """Secular-equation phase: bracket construction + vectorized laed4
    roots.  Returns (kshift, sgn, x, lam)."""
    n2 = D.shape[0]
    dt = D.dtype
    idx = jnp.arange(n2)
    z2 = z * z
    # --- secular solve ------------------------------------------------
    # index of the next nondeflated pole above i (n2 if none)
    posn2 = jnp.where(nondefl, idx, n2).astype(jnp.int32)
    suf2 = lax.cummin(posn2[::-1])[::-1]
    nxt_idx = jnp.concatenate([suf2[1:], jnp.full((1,), n2, jnp.int32)])
    nxt_c = jnp.clip(nxt_idx, 0, n2 - 1)
    top_gap = rho * z2.sum() + tol
    gap_hi = jnp.where(nxt_idx < n2, D[nxt_c] - D, top_gap)
    gap_hi = jnp.maximum(gap_hi, jnp.asarray(np.float64(1e-30), dt))
    kshift, sgn, x = _secular_roots(D, z2, rho, nondefl, nxt_idx, gap_hi)
    kshift = jnp.where(nondefl, kshift, idx)
    sgn = jnp.where(nondefl, sgn, 1.0)
    x = jnp.where(nondefl, x, 0.0)
    lam = jnp.where(nondefl, D[kshift] + sgn * x, D)
    return kshift, sgn, x, lam


def _assemble_u(D, z, nondefl, kshift, sgn, x):
    """Lowner z-hat recomputation + eigenvector assembly.  Returns Ur
    with ROWS indexed by root i (Ur = U^T of the classical U), ready
    for the transposed back-rotation QT_out = Ur @ QT."""
    n2 = D.shape[0]
    dt = D.dtype
    # --- Lowner z-hat (Gu-Eisenstat) ----------------------------------
    # zhat_j^2 = prod_i (lam_i - D_j) / prod_{i != j} (D_i - D_j), over
    # nondeflated i, j.  lam_i - D_j = (D[kshift_i] - D_j) + sgn_i x_i
    # — the nearest-pole representation keeps this difference accurate
    # even when lam_i hugs a pole.
    delta = D[:, None] - D[None, :]  # (i, j) -> D_i - D_j
    lam_minus_d = (D[kshift][:, None] - D[None, :]) + (sgn * x)[:, None]
    both = nondefl[:, None] & nondefl[None, :]
    num = jnp.where(both, lam_minus_d, 1.0)
    offdiag = both & (jnp.arange(n2)[:, None] != jnp.arange(n2)[None, :])
    den = jnp.where(offdiag, delta, 1.0)
    logmag = jnp.where(both, jnp.log(jnp.abs(jnp.where(num == 0, 1.0, num))), 0.0)
    logden = jnp.where(offdiag, jnp.log(jnp.abs(jnp.where(den == 0, 1.0, den))), 0.0)
    # optimization_barrier: when the log producers FUSE into the column
    # sums below, the chip's f64-emulated reduction accumulates at f32
    # grade and zhat loses ~7 digits — this single fusion was the whole
    # stedc orthogonality budget at n=4096 (97 n eps jitted vs 36 with
    # the logs materialized first; round-5 bisection, the per-phase and
    # norm-sum barriers moved nothing).  Forcing materialization keeps
    # the jitted tree at eager-grade accuracy for ~16 MB of extra HBM
    # traffic per merge.
    logmag, logden = _opt_barrier((logmag, logden))
    logzhat = 0.5 * (logmag.sum(axis=0) - logden.sum(axis=0))
    zsign = jnp.where(z < 0, -1.0, 1.0).astype(dt)

    # --- eigenvector assembly (log-space, underflow-proof) ------------
    # column i (nondeflated): u_j = zhat_j / (lam_i - D_j), normalized.
    # Assembled as exp(log|zhat_j| - log|lam_i - D_j| - max_col) so that
    # tiny zhat magnitudes (exp of a large negative sum) cannot flush
    # to zero inside the chip's f32-grade f64 exponent range — a direct
    # exp(logzhat) underflow zeroes whole columns there.
    absd = jnp.abs(lam_minus_d)
    logd = jnp.log(jnp.where(absd == 0, 1.0, absd))
    logU = jnp.where(both, logzhat[None, :] - logd, -jnp.inf)  # (i, j)
    sgn_u = zsign[None, :] * jnp.where(lam_minus_d < 0, -1.0, 1.0)
    M = jnp.max(logU, axis=1, keepdims=True)
    Msafe = jnp.where(jnp.isfinite(M), M, 0.0)
    Ur = jnp.where(both, sgn_u * jnp.exp(logU - Msafe), 0.0)  # (root i, j)
    norms = jnp.sqrt((Ur * Ur).sum(axis=1))
    Ur = Ur / jnp.where(norms == 0, 1.0, norms)[:, None]
    # deflated roots: unit vectors
    eye = jnp.eye(n2, dtype=dt)
    Ur = jnp.where(nondefl[:, None], Ur, eye)
    return Ur


def _merge(w1, QT1, w2, QT2, e_r, eps):
    """One Cuppen merge: children (w1, QT1), (w2, QT2) of size s each
    (QT in row-eigenvector form), coupled by off-diagonal e_r.  Returns
    (w, QT) of size 2s, ascending.

    Composed of the phase functions above (setup/sort -> deflate ->
    secular -> assemble -> back-rotate); tools/profile_stedc.py times
    each phase separately on-chip."""
    D, z, QT, rho, tol = _merge_setup(w1, QT1, w2, QT2, e_r, eps)
    D, z, QT, nondefl = _deflate(D, z, QT, rho, tol)
    kshift, sgn, x, lam = _solve_secular(D, z, rho, nondefl, tol)
    Ur = _assemble_u(D, z, nondefl, kshift, sgn, x)

    # --- back-rotation + final sort (all in transposed form): the
    # classical Q @ U becomes QT_out = U^T @ QT, still one MXU gemm ----
    QT = _dot(Ur, QT)
    order2 = jnp.argsort(lam)
    return lam[order2], QT[order2, :]


def stedc(d: jnp.ndarray, e: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Eigendecomposition of the symmetric tridiagonal (d, e):
    returns (w ascending, Q) with T = Q diag(w) Q^T.

    Bottom-up Cuppen tree over a power-of-two padding; every level's
    merges run as one vmapped batch (reference: src/stedc.cc's recursive
    driver + stedc_merge/stedc_secular; see module docstring)."""
    n = d.shape[0]
    dt = d.dtype
    eps = float(jnp.finfo(dt).eps)
    if jax.default_backend() != "cpu":
        # the TPU f64 emulation's effective unit roundoff is ~10x the
        # IEEE one (measured ~2.5e-15 on gemm); deflation calibrated to
        # IEEE eps leaves degenerate clusters undeflated with pole
        # differences that are pure emulation noise, which destroys
        # eigenvector orthogonality.  The n-growth keeps large merges'
        # root interlacing robust.  (The ~100 n eps orthogonality this
        # calibration used to be blamed for was actually the
        # _assemble_u log->sum fusion defect, fixed round 5 by the
        # optimization_barrier there: with it, orthogonality is ~3
        # n eps at n=4096 under this same 32x sqrt(n) factor.)
        eps *= 32.0 * max(1.0, float(np.sqrt(n / 2048.0)))
    if n == 1:
        return d, jnp.ones((1, 1), dt)

    # normalize to O(1) scale (LAPACK dlaed0 does the same): keeps every
    # internal quantity inside the TPU f64 emulation's f32-grade
    # exponent range (values under ~1e-38 flush to zero on this chip)
    scale0 = jnp.maximum(
        jnp.abs(d).max(), jnp.abs(e).max() if e.shape[0] else jnp.zeros((), dt)
    )
    scale = jnp.where(scale0 > 0, scale0, 1.0)
    d = d / scale
    e = e / scale

    N = 1 << int(np.ceil(np.log2(n)))
    # pad with decoupled, well-separated poles above the spectrum
    bound = jnp.abs(d).max() + 2 * (jnp.abs(e).max() if e.shape[0] else 0.0) + 1.0
    dpad = jnp.concatenate([d, bound * (2.0 + jnp.arange(N - n, dtype=dt))])
    epad = jnp.concatenate([e, jnp.zeros((N - 1 - e.shape[0],), dt)])

    # leaf adjustment: every interior edge is cut once in the full tree
    eabs = jnp.abs(epad)
    left = jnp.concatenate([jnp.zeros((1,), dt), eabs])
    right = jnp.concatenate([eabs, jnp.zeros((1,), dt)])
    w = (dpad - left - right)[:, None]  # (N, 1) block eigenvalues
    QT = jnp.ones((N, 1, 1), dt)  # row-eigenvector (transposed) form
    w = w.reshape(N, 1)

    merge_b = jax.vmap(_merge, in_axes=(0, 0, 0, 0, 0, None))

    s = 1
    while s < N:
        nm = N // (2 * s)
        w_pairs = w.reshape(nm, 2, s)
        Q_pairs = QT.reshape(nm, 2, s, s)
        e_r = epad[s - 1 :: 2 * s][:nm]
        w, QT = merge_b(
            w_pairs[:, 0], Q_pairs[:, 0], w_pairs[:, 1], Q_pairs[:, 1],
            e_r, eps,
        )
        s *= 2
        w = w.reshape(nm, s)
        QT = QT.reshape(nm, s, s)

    w = w.reshape(N)
    QT = QT.reshape(N, N)
    QT = QT[:n, :n]
    # Orthogonality on-chip: ~3 n eps at n=4096 since the
    # optimization_barrier in _assemble_u (the log->sum fusion was the
    # whole ~100 n eps budget; round-5 bisection).  The previously
    # attempted Newton-Schulz/CholQR output polish was a symptom-level
    # workaround for that same fused-reduction defect and stays absent.
    # single transpose back to column-eigenvector convention
    return w[:n] * scale, QT.T
