"""Fast native blocked LU with partial pivoting for TPU.

The f64 LU in ops/lu_kernels.py (blocked_getrf) keeps every step at the
full padded array shape, so each of its n/nb steps pays a full-width
masked trailing matmul — measured 38 GF/s at n=2048 against a ~1.7 TF/s
f64 gemm rate on the same chip.  This module rebuilds the reference's
right-looking schedule (reference: src/getrf.cc:85-214 — threaded panel
with per-column pivot search, pivot broadcast, row exchange, trsm row,
trailing gemm) as a three-level TPU schedule, the LU analogue of
ops/chol_kernels.py:

* micro level (``_lu_panel_strips``): one fori_loop over ib-wide column
  strips of an (m, nb) panel.  Per column: VPU argmax pivot search,
  two-row swap, rank-1 update restricted to the strip; per strip: a
  unit-lower strip inverse by nilpotent squaring ((I+N)^-1 =
  (I-N)(I+N^2)(I+N^4)... exact because N^ib = 0) and one rank-ib MXU
  update of the rest of the panel.  This bounds the bandwidth-bound
  per-column traffic at O(m*ib) instead of O(m*nb).
* sub-panel level (``_block_lu``): one fori_loop over the nb-wide
  panels of an (m, NB) coarse block; the active region is rolled to the
  top so every iteration keeps one static shape.  Row exchanges are one
  gather of the block per panel; the trailing-in-block update is an
  explicit nb-inverse + two MXU gemms.
* coarse level (``blocked_getrf_fast``): <= coarse_panels Python-
  unrolled panels of width NB with exact shrinking shapes, so the
  dominant trailing gemms run at full MXU rate; the panel solve uses an
  explicit unit-lower inverse (MAGMA recipe).

Pivot choice matches LAPACK partial pivoting (maximal |entry| wins) up
to tie-breaking: exact-magnitude ties resolve to the lowest ORIGINAL
row index, where LAPACK scans in swapped order — the factorization is
equally valid but perm can differ on tied (structured/integer) inputs.
Used by lu_kernels.lu_global for large square matrices on non-CPU
backends.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..internal.precision import hdot as _dot


def _unit_lower_inv(L: jnp.ndarray) -> jnp.ndarray:
    """Exact inverse of a unit-lower (b, b) matrix by nilpotent squaring:
    (I + N)^-1 = (I - N)(I + N^2)(I + N^4)...  — log2(b) small matmuls,
    no triangular-solve lowering."""
    b = L.shape[0]
    eye = jnp.eye(b, dtype=L.dtype)
    N = jnp.tril(L, -1)
    inv = eye - N
    P = N
    k = 2
    while k < b:
        P = _dot(P, P)  # N^(2^j)
        inv = _dot(inv, eye + P)
        k *= 2
    return inv


def _lu_panel_strips(
    P: jnp.ndarray, act, ib: int = 32
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Partial-pivot LU of an (m, w) panel; only rows < act are eligible
    pivots (the rest is padding).  w must be a multiple of ib.

    No row is moved during elimination: pivoting is tracked with an
    eligibility mask (the Schur update is row-order independent), so the
    per-column work touches only the (m, ib) strip — the swap-based
    variant's four full-panel row updates per column dominated its
    runtime.  One ordering gather at the end produces the same row
    order (and net forward permutation) as LAPACK's swap sequence.

    Returns (P, perm): P holds unit-lower L below the diagonal and U
    on/above for the w eliminated columns, rows in LAPACK pivot order;
    P rows correspond to input rows perm."""
    m, w = P.shape
    rows = jnp.arange(m)
    colsw = jnp.arange(w)
    ibr = jnp.arange(ib)

    def strip(s, carry):
        P, unpiv, pivrows = carry
        j0 = s * ib
        S = lax.dynamic_slice(P, (0, j0), (m, ib))
        for c in range(ib):
            colc = S[:, c]
            mag = jnp.where(unpiv, jnp.abs(colc), -jnp.inf)
            piv = jnp.argmax(mag)
            pv = colc[piv]
            safe = jnp.where(pv == 0, jnp.ones_like(pv), pv)
            elig = unpiv & (rows != piv) & (pv != 0)
            l = jnp.where(elig, colc / safe, jnp.zeros((), P.dtype))
            # pivoted rows keep their U entries; the pivot row keeps pv
            S = S.at[:, c].set(jnp.where(unpiv & (rows != piv), l, colc))
            unpiv = unpiv.at[piv].set(False)
            pivrows = pivrows.at[j0 + c].set(piv.astype(jnp.int32))
            if c + 1 < ib:
                # rank-1 on the strip's remaining columns only (c is a
                # Python int, so the tail slice is static — halves the
                # bandwidth-bound micro traffic vs updating all of S)
                tail = S[:, c + 1 :]
                urow = tail[piv]
                S = S.at[:, c + 1 :].set(tail - jnp.outer(l, urow))
        P = lax.dynamic_update_slice(P, S, (0, j0))
        # rank-ib update of the rest of the panel: gather the strip's
        # pivot rows, exact unit-lower inverse by nilpotent squaring,
        # one MXU gemm.  Lss[j, c] for j > c is the column-c multiplier
        # of pivot row p_j (recorded in S before p_j was pivoted).
        stripiv = lax.dynamic_slice(pivrows, (j0,), (ib,))
        Srows = P[stripiv]  # (ib, w)
        D = lax.dynamic_slice(Srows, (0, j0), (ib, ib))
        Linv = _unit_lower_inv(D)
        U12 = _dot(Linv, Srows)
        cmask = (colsw >= j0 + ib)[None, :]
        P = P.at[stripiv].set(jnp.where(cmask, U12, Srows))
        L21 = jnp.where(unpiv[:, None], S, jnp.zeros((), P.dtype))
        return (
            P - jnp.where(cmask, _dot(L21, U12), jnp.zeros((), P.dtype)),
            unpiv,
            pivrows,
        )

    unpiv0 = rows < act
    pivrows0 = jnp.zeros((w,), jnp.int32)
    P, unpiv, pivrows = lax.fori_loop(
        0, w // ib, strip, (P, unpiv0, pivrows0)
    )

    # Reconstruct LAPACK's row order: replay the swap sequence
    # (column j swaps positions j <-> current position of pivrows[j])
    # on an index vector.  O(w) scalar steps — tiny next to the strips.
    def replay(j, carry):
        perm, pos = carry
        p = pos[pivrows[j]]
        rj = perm[j]
        rp = perm[p]
        perm = perm.at[j].set(rp).at[p].set(rj)
        pos = pos.at[rp].set(j).at[rj].set(p)
        return perm, pos

    perm0 = jnp.arange(m, dtype=jnp.int32)
    perm, _ = lax.fori_loop(0, w, replay, (perm0, perm0))
    return P[perm], perm


def _block_lu(
    B: jnp.ndarray, nb: int, ib: int = 32
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Partial-pivot LU of the first W columns of an (m, W) block,
    m >= W, W a multiple of nb.  One fori_loop over the W//nb panels
    (active region rolled to the top keeps a single compiled shape).

    Returns (B, perm): L\\U packed in the first W columns, perm the net
    forward row permutation over the m rows."""
    m, W = B.shape
    rows = jnp.arange(m)
    colsW = jnp.arange(W)
    eye_nb = jnp.eye(nb, dtype=B.dtype)

    def panel(s, carry):
        B, perm = carry
        j0 = s * nb
        colblk = lax.dynamic_slice(B, (0, j0), (m, nb))
        rolled = jnp.roll(colblk, -j0, axis=0)
        act = m - j0
        rolled = jnp.where((rows < act)[:, None], rolled, jnp.zeros((), B.dtype))
        Pf, perm_loc = _lu_panel_strips(rolled, act, ib)
        # unroll the panel permutation into the block frame (identity
        # above j0) and exchange rows across the whole block
        mapped = jnp.where(
            rows >= j0,
            perm_loc[jnp.clip(rows - j0, 0, m - 1)] + j0,
            rows,
        )
        B = B[mapped]
        perm = perm[mapped]
        # write the factored panel back
        Pn = jnp.roll(Pf, j0, axis=0)
        cur = lax.dynamic_slice(B, (0, j0), (m, nb))
        neu = jnp.where((rows >= j0)[:, None], Pn, cur)
        B = lax.dynamic_update_slice(B, neu, (0, j0))
        # U rows to the right + trailing update inside the block; the
        # nb-block inverse via one small trsm (cheaper than nilpotent
        # squaring at nb=512: log2(nb) full matmuls vs one solve)
        Lnb = jnp.tril(Pf[:nb], -1) + eye_nb
        Linv = lax.linalg.triangular_solve(
            Lnb, eye_nb, left_side=True, lower=True, unit_diagonal=True
        )
        Rtop = lax.dynamic_slice(B, (j0, 0), (nb, W))
        U12 = _dot(Linv, Rtop)
        cmask = (colsW >= j0 + nb)[None, :]
        B = lax.dynamic_update_slice(B, jnp.where(cmask, U12, Rtop), (j0, 0))
        L21 = jnp.where((rows >= j0 + nb)[:, None], neu, jnp.zeros((), B.dtype))
        U12m = jnp.where(cmask, U12, jnp.zeros((), B.dtype))
        return B - _dot(L21, U12m), perm

    perm0 = jnp.arange(m, dtype=jnp.int32)
    return lax.fori_loop(0, W // nb, panel, (B, perm0))


def blocked_getrf_fast(
    G: jnp.ndarray, nb: int = 512, ib: int = 32, coarse_panels: int = 4
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked right-looking LU with partial pivoting of a square padded
    array (n a multiple of nb).  Returns (LU, perm): LU = (L\\U) of
    G[perm].  Same contract as lu_kernels.blocked_getrf; ~15x faster at
    n >= 4096 on the chip (exact-shape trailing gemms at MXU rate).
    """
    n = G.shape[0]
    assert n % nb == 0, f"blocked_getrf_fast: n={n} not a multiple of nb={nb}"
    nt = n // nb
    perm = jnp.arange(n, dtype=jnp.int32)
    if nt <= 1:
        act = jnp.int32(n)
        LU, perm = _lu_panel_strips(G, act, ib)
        return LU, perm

    NB = nb * (-(-nt // coarse_panels))
    eyes = {}
    k0 = 0
    while k0 < n:
        W = min(NB, n - k0)
        B = G[k0:, k0 : k0 + W]
        Bf, permB = _block_lu(B, nb, ib)
        step = jnp.concatenate(
            [jnp.arange(k0, dtype=jnp.int32), permB + k0]
        )
        G = G[step]
        perm = perm[step]
        G = G.at[k0:, k0 : k0 + W].set(Bf)
        rest = n - k0 - W
        if rest > 0:
            LW = jnp.tril(Bf[:W], -1) + eyes.setdefault(
                W, jnp.eye(W, dtype=G.dtype)
            )
            # one (W, W) unit-lower trsm (single shape reused by every
            # coarse panel), then MXU gemms carry the bulk work
            Linv = lax.linalg.triangular_solve(
                LW, eyes[W], left_side=True, lower=True, unit_diagonal=True
            )
            U12 = _dot(Linv, G[k0 : k0 + W, k0 + W :])
            G = G.at[k0 : k0 + W, k0 + W :].set(U12)
            L21 = Bf[W:, :W]
            G = G.at[k0 + W :, k0 + W :].add(-_dot(L21, U12))
        k0 += W
    return G, perm
