"""Trace-driven soak fabric: record real workload shape, replay it
deterministically at scale with every serve plane armed at once, and
watch the whole system through one time-series lens.

The serve tier is traced, fair, certified, quarantine-capable, and
race-checked — but each plane is judged by its own gate in isolation.
This package closes the loop (ROADMAP item 7b; Dapper-style workload
reconstruction from the span ring, PAPERS.md "Tracing"; the
tail-at-scale effect only accumulates under sustained mixed load,
Dean & Barroso):

* :mod:`slate_tpu.soak.record` — workload recorder: a delivery-hook
  tap on a live :class:`~slate_tpu.serve.service.SolverService` (or
  the PR9 span ring) becomes a durable, replayable JSONL load spec.
  Operands are never persisted — matrices regenerate deterministically
  from ``matgen`` philox seeds, and ``repeat_fp`` preserves same-A
  burst structure for the factor cache.
* :mod:`slate_tpu.soak.replay` — replay engine: drives a recorded or
  synthesized spec (bundled generators: multitenant burst, repeated-A
  stream, adversarial flood, deadline storm) against a live service
  with open-loop pacing at ``speed`` x, seeded end to end.
* :mod:`slate_tpu.soak.timeline` — health timeline: samples
  ``health()`` + devmon gauges on a background cadence into
  ``{"type": "timeline"}`` JSONL rows (the registry's first
  time-series view — every other row type is end-of-run aggregate).

``tools/soak_report.py`` joins the timeline with the metric families
into one judged verdict; ``run_tests.py --soak`` is the gate.

Zero overhead off, like every other plane: nothing here hooks the
serve tier until a recorder/sampler is explicitly armed, and the
delivery tap costs the hot path one truthiness check on an empty
list.
"""

from . import record, replay, timeline  # noqa: F401

__all__ = ["record", "replay", "timeline"]
