"""Workload recorder: a live serve stream (or the span-ring flight
recorder) becomes a durable, replayable JSONL load spec.

One spec row per client request::

    {"type": "load", "t_offset": 0.0123, "routine": "gesv",
     "bucket_shape": [12, 12, 2], "dtype": "float64",
     "tenant": "gold", "priority": "high", "deadline_s": 0.5,
     "matrix_seed": 912883, "rhs_seed": 7, "repeat_fp": "a1b2..."}

Operands are NEVER persisted: ``matrix_seed`` feeds the deterministic
``matgen.philox`` generator at replay (``soak/replay.materialize``),
so a spec is a few hundred bytes per request regardless of problem
size.  ``repeat_fp`` is the factor-cache matrix fingerprint when the
request carried one — rows sharing a ``repeat_fp`` replay with the
SAME regenerated matrix bytes, preserving same-A burst structure (the
factor cache hits on the replayed stream exactly where it hit on the
recorded one).  ``matrix_seed`` derives from ``repeat_fp`` when
present (stable across processes), from the row ordinal otherwise.

Two capture paths:

* :class:`Recorder` — a delivery tap
  (``serve.service.add_delivery_tap``) on a live service: exact
  shapes, tenants, deadlines, and fingerprints, straight off the
  resolving ``_Request``.  Armed explicitly; detaching restores the
  hot path to one empty-list truthiness check.
* :func:`from_ring` — reconstruction from the span ring's completed
  ``request`` root spans (the Dapper move: the flight recorder IS a
  workload sample).  Shapes come from the bucket label, so they are
  bucket-rounded, and deadlines/fingerprints are not recoverable —
  check ``spans.pressure()`` (or ``health()["trace_ring"]``) first: a
  ring that has been evicting yields a truncated window.
"""

from __future__ import annotations

import json
import threading
import weakref
import zlib
from typing import Dict, List, Optional

import numpy as np

from ..aux import spans
from . import replay as _rp  # canonical row schema lives with the consumer

SPEC_VERSION = 1

#: row fields every writer emits (readers tolerate extras)
SPEC_FIELDS = (
    "t_offset", "routine", "bucket_shape", "dtype", "tenant", "priority",
    "deadline_s", "matrix_seed", "rhs_seed", "repeat_fp",
)


def matrix_seed_for(repeat_fp: Optional[str], ordinal: int) -> int:
    """Stable philox seed for one spec row: a hash of the matrix
    fingerprint when the request carried one (same A -> same seed ->
    byte-identical regenerated A, so repeat structure survives the
    round trip), the row ordinal otherwise."""
    key = repeat_fp if repeat_fp else f"req-{ordinal}"
    return zlib.crc32(key.encode("utf-8")) & 0x7FFFFFFF


class Recorder:
    """Delivery-tap workload recorder.  ``attach()`` hooks request
    resolution on every live service in the process; ``detach()``
    unhooks.  Hedge twins and duplicate resolutions are deduped on the
    client future's identity, so the spec has one row per *submitted*
    request that resolved (requests refused at ``submit()`` never
    construct a future and are not recorded — they are the admission
    plane's output, not the workload's shape)."""

    def __init__(self) -> None:
        self._rows: List[dict] = []
        # WeakSet, not a set of id()s: a client that drops its future
        # after .result() lets CPython reuse the freed id, and an
        # id-keyed dedup would silently swallow the NEXT request that
        # allocates at the same address
        self._seen: "weakref.WeakSet" = weakref.WeakSet()
        self._t0: Optional[float] = None
        self._lock = threading.Lock()
        self._attached = False

    # -- capture -----------------------------------------------------------

    def attach(self) -> "Recorder":
        from ..serve import service as _svc

        _svc.add_delivery_tap(self._tap)
        self._attached = True
        return self

    def detach(self) -> "Recorder":
        from ..serve import service as _svc

        _svc.remove_delivery_tap(self._tap)
        self._attached = False
        return self

    def __enter__(self) -> "Recorder":
        return self.attach()

    def __exit__(self, *exc) -> bool:
        self.detach()
        return False

    def _tap(self, req, outcome: str) -> None:
        if getattr(req, "is_hedge", False):
            return  # the twin shares the primary's future and identity
        with self._lock:
            fut = req.future
            if fut in self._seen:
                return
            self._seen.add(fut)
            if self._t0 is None:
                self._t0 = req.t_submit
            ordinal = len(self._rows)
            from ..serve import buckets as _bk

            self._rows.append({
                "t_offset": round(max(req.t_submit - self._t0, 0.0), 6),
                "routine": req.routine,
                "bucket_shape": [int(req.m), int(req.n), int(req.nrhs)],
                "dtype": np.dtype(req.A.dtype).name,
                "tenant": req.tenant,
                "priority": _bk.priority_name(req.priority),
                "deadline_s": (
                    round(req.deadline - req.t_submit, 6)
                    if req.deadline is not None else None
                ),
                "matrix_seed": matrix_seed_for(req.factor_fp, ordinal),
                "rhs_seed": ordinal,
                "repeat_fp": req.factor_fp,
            })

    # -- results -----------------------------------------------------------

    def rows(self) -> List[dict]:
        """Recorded spec rows, submit-time order."""
        with self._lock:
            return sorted(
                (dict(r) for r in self._rows), key=lambda r: r["t_offset"]
            )

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def save(self, path: str) -> str:
        return save(self.rows(), path, source="tap")


def from_ring(items: Optional[List[spans.Span]] = None) -> List[dict]:
    """Spec rows reconstructed from completed ``request`` root spans
    (the ring snapshot by default).  Bucket-label shapes (rounded, not
    raw), no deadlines, no fingerprints — the tap path records all
    three exactly; this path works on any flight recording after the
    fact."""
    if items is None:
        items = spans.snapshot()
    roots = [
        sp for sp in items
        if sp.kind == "span" and sp.name == "request"
        and sp.attrs.get("routine") and sp.attrs.get("bucket")
    ]
    roots.sort(key=lambda sp: sp.t_start)
    rows: List[dict] = []
    t0 = roots[0].t_start if roots else 0.0
    for ordinal, sp in enumerate(roots):
        # bucket label: <routine>.<m>x<n>x<nrhs>.<dtype>[...]
        parts = str(sp.attrs["bucket"]).split(".")
        if len(parts) < 3:
            continue
        try:
            m, n, nrhs = (int(x) for x in parts[1].split("x"))
        except ValueError:
            continue
        rows.append({
            "t_offset": round(sp.t_start - t0, 6),
            "routine": str(sp.attrs["routine"]),
            "bucket_shape": [m, n, nrhs],
            "dtype": parts[2],
            "tenant": str(sp.attrs.get("tenant", "default")),
            "priority": str(sp.attrs.get("priority", "normal")),
            "deadline_s": None,
            "matrix_seed": matrix_seed_for(None, ordinal),
            "rhs_seed": ordinal,
            "repeat_fp": None,
        })
    return rows


# ---------------------------------------------------------------------------
# spec persistence (JSONL; one meta line + one "load" row per request)
# ---------------------------------------------------------------------------


def save(rows: List[dict], path: str, source: str = "synth") -> str:
    """Write a load spec: a ``spec_meta`` line, then one ``load`` row
    per request in ``t_offset`` order."""
    rows = sorted(rows, key=lambda r: r.get("t_offset", 0.0))
    with open(path, "w") as f:
        f.write(json.dumps({
            "type": "spec_meta", "version": SPEC_VERSION,
            "count": len(rows), "source": source,
            "duration_s": rows[-1]["t_offset"] if rows else 0.0,
        }) + "\n")
        for r in rows:
            f.write(json.dumps({"type": "load", **r}) + "\n")
    return path


def load(path: str) -> List[dict]:
    """Read a load spec back into replayable rows (t_offset order)."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            if r.get("type") == "spec_meta":
                v = r.get("version", 0)
                if v > SPEC_VERSION:
                    raise ValueError(
                        f"{path}: spec version {v} is newer than this "
                        f"reader ({SPEC_VERSION})"
                    )
            elif r.get("type") == "load":
                rows.append(r)
    rows.sort(key=lambda r: r.get("t_offset", 0.0))
    return rows


def mix_histogram(rows: List[dict]) -> Dict[str, Dict[str, int]]:
    """Workload-shape histograms of a spec: request counts per tenant,
    per priority, per bucket shape, plus the repeat structure (rows
    per ``repeat_fp`` group).  The round-trip gate compares these
    between the driving spec and the recorded one — the two must agree
    on the admitted traffic's shape even though individual outcomes
    (shed, deadline-missed) differ run to run."""
    tenants: Dict[str, int] = {}
    prios: Dict[str, int] = {}
    shapes: Dict[str, int] = {}
    repeats: Dict[str, int] = {}
    for r in rows:
        tenants[r["tenant"]] = tenants.get(r["tenant"], 0) + 1
        prios[r["priority"]] = prios.get(r["priority"], 0) + 1
        s = "x".join(str(x) for x in r["bucket_shape"]) + ":" + r["routine"]
        shapes[s] = shapes.get(s, 0) + 1
        fp = r.get("repeat_fp")
        if fp:
            repeats[fp] = repeats.get(fp, 0) + 1
    return {
        "tenants": tenants, "priorities": prios, "shapes": shapes,
        "repeat_groups": repeats,
    }


# re-exported for symmetry with the replay module's materialize()
materialize = _rp.materialize
