"""Replay engine: drive a recorded or synthesized load spec against a
live ``SolverService`` with open-loop pacing, seeded end to end.

Open loop (the load-testing contract): the pacer sleeps to each row's
``t_offset / speed`` and submits regardless of how many earlier
requests are still in flight — a service that falls behind builds a
real queue, exactly like production traffic, instead of the
closed-loop coordinated-omission artifact where a slow server
throttles its own load.  ``speed`` scales recorded time (``2.0`` =
twice as fast); a huge speed degenerates to max-rate submission.

Operands regenerate deterministically per row from ``matgen.philox``
(:func:`materialize`): same spec + same ``seed`` -> byte-identical
operand streams, so admission/shed/hedge/quarantine decisions
reproduce within scheduling tolerance across runs.  Rows sharing a
``repeat_fp`` share ``matrix_seed`` and therefore regenerate the SAME
matrix bytes — the factor cache hits on the replayed stream where it
hit on the recorded one.

Every replay emits the ``soak.*`` counter family the unified verdict
(``tools/soak_report.py``) reconciles::

    soak.submitted == soak.delivered + soak.typed_errors + soak.refused
    serve.requests (admitted) == soak.submitted - soak.refused

plus ``soak.bad_results`` (client-side residual check: a delivered X
that does not solve its system — the integrity plane's escape
counter, measured from the OUTSIDE) and the ``soak.orphan_spans``
gauge (:func:`orphan_spans`).

Bundled spec generators (deterministic in their seed) synthesize the
workload shapes the serve planes were built for: multitenant burst,
repeated-A factor-cache stream, adversarial flood, deadline storm.
"""

from __future__ import annotations

import random
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..aux import metrics, spans
from ..exceptions import SlateError
from ..matgen import philox

#: per-future wait bound — a hang turns into a loud verdict, never a
#: wedged gate
DEFAULT_TIMEOUT_S = 300.0

_SEED_MIX = 0x9E3779B1  # Fisher/Knuth multiplicative mix, fits int32 keys


def _mix(seed: int, key: int) -> int:
    return (int(seed) * _SEED_MIX + int(key) * 2654435761 + 1) & 0x7FFFFFFF


def materialize(row: dict, seed: int = 0,
                cache: Optional[dict] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic operands for one spec row.  ``A`` depends only on
    ``(routine, shape, dtype, matrix_seed, seed)`` — rows sharing a
    ``repeat_fp`` (same ``matrix_seed``) get byte-identical matrices —
    while ``B`` varies per row via ``rhs_seed`` (same-A burst = one
    factor, many right-hand sides).  gesv matrices are made diagonally
    dominant and posv SPD, so every generated system is solvable and a
    delivered X can be residual-checked client-side.  ``cache`` (a
    plain dict the caller owns) memoizes A per matrix seed."""
    m, n, nrhs = (int(x) for x in row["bucket_shape"])
    routine = row["routine"]
    dtype = np.dtype(row.get("dtype", "float64"))
    akey = (routine, m, n, str(dtype), _mix(seed, row["matrix_seed"]))
    A = cache.get(akey) if cache is not None else None
    if A is None:
        aseed = akey[-1]
        i, j = np.arange(m)[:, None], np.arange(n)[None, :]
        G = philox.random_np("normal", aseed, i + 0 * j, j + 0 * i, dtype)
        if routine == "posv":
            A = G @ G.conj().T + n * np.eye(n, dtype=dtype)
        elif routine == "gesv":
            A = G + n * np.eye(n, dtype=dtype)
        else:  # gels: tall well-conditioned-enough random systems
            A = G
        if cache is not None:
            cache[akey] = A
    bseed = _mix(seed + 1, int(row.get("rhs_seed", row["matrix_seed"])))
    i, j = np.arange(m)[:, None], np.arange(nrhs)[None, :]
    B = philox.random_np("normal", bseed, i + 0 * j, j + 0 * i, dtype)
    return A, B


def _residual_ok(routine: str, A: np.ndarray, B: np.ndarray,
                 X: np.ndarray) -> bool:
    X = np.asarray(X)
    if not np.all(np.isfinite(X)):
        return False
    if routine not in ("gesv", "posv"):
        return True  # least-squares residual is not ~0 by construction
    if routine == "posv":
        A = np.tril(A) + np.conj(np.tril(A, -1)).T  # the solved operand
    scale = np.abs(A).max() * np.abs(X).max() + np.abs(B).max() + 1e-30
    return np.abs(A @ X - B).max() <= 1e-6 * scale


def replay(svc, rows: List[dict], speed: float = 1.0, seed: int = 0,
           timeout_s: float = DEFAULT_TIMEOUT_S,
           check_results: bool = True) -> dict:
    """Drive ``rows`` (t_offset order) against ``svc``; block until
    every submitted future resolves; return the client-side tally.

    The tally's invariant — ``submitted == delivered + typed_errors +
    refused`` with zero unaccounted futures — IS the delivery
    completeness the soak verdict gates on; the same counts are
    emitted as ``soak.*`` counters so the verdict works from the
    metrics JSONL alone."""
    rows = sorted(rows, key=lambda r: r.get("t_offset", 0.0))
    cache: dict = {}
    pending = []  # (row, A, B, future)
    refused = 0
    speed = max(float(speed), 1e-9)
    done_at: Dict[int, float] = {}  # id(future) -> resolution time

    def _stamp(fut) -> None:
        # done-callback, fires AT resolution: client latency must be
        # submit->resolve, not submit->when-the-drain-loop-gets-there
        done_at.setdefault(id(fut), time.monotonic())

    t0 = time.monotonic()
    for row in rows:
        target = t0 + float(row.get("t_offset", 0.0)) / speed
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)  # open loop: pace, never await completions
        A, B = materialize(row, seed=seed, cache=cache)
        metrics.inc("soak.submitted")
        ts = time.monotonic()
        try:
            fut = svc.submit(
                row["routine"], A, B,
                deadline=row.get("deadline_s"),
                tenant=row.get("tenant"),
                priority=row.get("priority"),
            )
        except SlateError:
            # admission refusal (shed / quota / share / invalid): the
            # plane's synchronous no — counted, never retried (the
            # recorded workload already reflects any client retries)
            metrics.inc("soak.refused")
            refused += 1
            continue
        fut.add_done_callback(_stamp)
        pending.append((row, A, B, fut, ts))
    t_submit_done = time.monotonic()
    delivered = typed = bad = 0
    latencies: List[float] = []
    for row, A, B, fut, ts in pending:
        try:
            X = fut.result(timeout=timeout_s)
        except SlateError:
            metrics.inc("soak.typed_errors")
            typed += 1
            continue
        latencies.append(done_at.get(id(fut), time.monotonic()) - ts)
        metrics.inc("soak.delivered")
        delivered += 1
        if check_results and not _residual_ok(row["routine"], A, B, X):
            metrics.inc("soak.bad_results")
            bad += 1
    wall = time.monotonic() - t0
    latencies.sort()

    def pct(p: float) -> Optional[float]:
        if not latencies:
            return None
        k = min(len(latencies) - 1, max(0, int(p / 100.0 * len(latencies))))
        return latencies[k]

    return {
        "submitted": len(rows),
        "delivered": delivered,
        "typed_errors": typed,
        "refused": refused,
        "bad_results": bad,
        "wall_s": round(wall, 3),
        "submit_wall_s": round(t_submit_done - t0, 3),
        "requests_per_s": round(len(rows) / max(wall, 1e-9), 1),
        "p50_s": pct(50), "p95_s": pct(95), "p99_s": pct(99),
    }


def orphan_spans() -> int:
    """Traces on the span ring with spans but no completed ``request``
    root — a request whose lifecycle never closed (the hang/leak
    signal the soak verdict requires to be zero).  Size the ring above
    the replayed request count: an evicting ring drops old roots and
    fabricates orphans (``spans.pressure()`` says whether it did).
    Publishes the count as the ``soak.orphan_spans`` gauge so the
    verdict tool can audit it from the dump alone."""
    orphans = 0
    for _tr, sps in spans.by_trace().items():
        if not any(
            sp.name == "request" and sp.t_end is not None for sp in sps
        ):
            orphans += 1
    metrics.gauge("soak.orphan_spans", orphans)
    return orphans


# ---------------------------------------------------------------------------
# synthesized specs (deterministic generators; seeds in, rows out)
# ---------------------------------------------------------------------------


def _arrivals(rng: random.Random, count: int, rate_rps: float) -> List[float]:
    """Poisson arrival offsets (exponential gaps), deterministic in rng."""
    t, out = 0.0, []
    for _ in range(count):
        out.append(round(t, 6))
        t += rng.expovariate(rate_rps)
    return out


def _row(t, routine, n, nrhs, tenant, priority, mseed, rseed,
         deadline_s=None, repeat_fp=None, dtype="float64", m=None):
    return {
        "t_offset": t, "routine": routine,
        "bucket_shape": [m if m is not None else n, n, nrhs],
        "dtype": dtype, "tenant": tenant, "priority": priority,
        "deadline_s": deadline_s, "matrix_seed": mseed & 0x7FFFFFFF,
        "rhs_seed": rseed, "repeat_fp": repeat_fp,
    }


def gen_multitenant(requests: int = 200, seed: int = 0, *,
                    rate_rps: float = 200.0, n_small: int = 12,
                    n_large: int = 24, nrhs: int = 2,
                    distinct: int = 8) -> List[dict]:
    """A paying tenant's steady small-solve stream interleaved with a
    free tier's heavier, lower-priority traffic (3:1 mix) — the
    fairness plane's bread and butter.  Each tenant re-solves against
    a pool of ``distinct`` matrices (fresh right-hand sides every
    request), the real multitenant shape — and the reason the factor
    cache mostly hits instead of paying a direct factorization per
    arrival."""
    rng = random.Random(seed)
    rows = []
    for k, t in enumerate(_arrivals(rng, requests, rate_rps)):
        if k % 4 == 3:
            fp = f"mt-{seed}-free-{k % max(distinct, 1)}"
            rows.append(_row(t, "gesv", n_large, nrhs, "free", "low",
                             _seed_of(fp), k, repeat_fp=fp))
        else:
            rt = "posv" if k % 8 == 1 else "gesv"
            fp = f"mt-{seed}-gold-{rt}-{k % max(distinct, 1)}"
            rows.append(_row(t, rt, n_small, nrhs, "gold", "high",
                             _seed_of(fp), k, repeat_fp=fp))
    return rows


def gen_repeated_a(requests: int = 200, seed: int = 0, *,
                   rate_rps: float = 300.0, n: int = 12, nrhs: int = 2,
                   distinct: int = 4, routine: str = "gesv") -> List[dict]:
    """Factor-once solve-many: ``distinct`` matrices, each arriving as
    a consecutive burst of fresh right-hand sides (rows in a burst
    share ``repeat_fp`` and hence matrix bytes at replay) — the factor
    cache must hit on everything after each burst's head."""
    rng = random.Random(seed)
    rows = []
    per = max(1, requests // max(distinct, 1))
    ts = _arrivals(rng, requests, rate_rps)
    for k in range(requests):
        g = min(k // per, distinct - 1)
        fp = f"synthA-{seed}-{g}"
        rows.append(_row(ts[k], routine, n, nrhs, "gold", "normal",
                         _seed_of(fp), k, repeat_fp=fp))
    return rows


def _seed_of(fp: str) -> int:
    import zlib

    return zlib.crc32(fp.encode("utf-8")) & 0x7FFFFFFF


def gen_adversarial_flood(requests: int = 200, seed: int = 0, *,
                          rate_rps: float = 150.0, n_flood: int = 24,
                          n_victim: int = 12, nrhs: int = 2,
                          flood_frac: float = 0.6,
                          distinct: int = 4) -> List[dict]:
    """One abusive tenant floods in tight bursts while a well-behaved
    tenant keeps a steady stream — the shed/quota path under real
    pressure.  Flood rows arrive in near-zero-gap clumps; both sides
    draw from ``distinct``-matrix pools (an abuser hammering the same
    few problems is the canonical flood)."""
    rng = random.Random(seed)
    n_fl = int(requests * flood_frac)
    rows = []
    t = 0.0
    k = 0
    while k < n_fl:
        clump = min(8, n_fl - k)
        for c in range(clump):
            fp = f"fl-{seed}-ab-{(k + c) % max(distinct, 1)}"
            rows.append(_row(round(t + c * 1e-4, 6), "gesv", n_flood, nrhs,
                             "abuser", "low", _seed_of(fp), k + c,
                             repeat_fp=fp))
        k += clump
        t += rng.expovariate(rate_rps / 8.0)
    for i, t in enumerate(_arrivals(rng, requests - n_fl, rate_rps / 2.0)):
        fp = f"fl-{seed}-good-{i % max(distinct, 1)}"
        rows.append(_row(t, "gesv", n_victim, nrhs, "good", "high",
                         _seed_of(fp), n_fl + i, repeat_fp=fp))
    rows.sort(key=lambda r: r["t_offset"])
    return rows


def gen_deadline_storm(requests: int = 100, seed: int = 0, *,
                       rate_rps: float = 200.0, n: int = 12,
                       nrhs: int = 2, tight_s: float = 0.002,
                       slack_s: float = 5.0) -> List[dict]:
    """Deadline-carrying traffic where a third of the deadlines are
    near-infeasible — the slo-burn tiers and queued/late miss split
    must account for every one of them."""
    rng = random.Random(seed)
    rows = []
    for k, t in enumerate(_arrivals(rng, requests, rate_rps)):
        dl = tight_s if k % 3 == 0 else slack_s
        fp = f"ds-{seed}-{k % 4}"
        rows.append(_row(t, "gesv", n, nrhs, "gold", "normal",
                         _seed_of(fp), k, deadline_s=dl, repeat_fp=fp))
    return rows


def gen_burst(requests: int = 300, seed: int = 0, *,
              base_rps: float = 40.0, burst_rps: float = 400.0,
              burst_start_s: float = 1.0, burst_len_s: float = 1.5,
              n: int = 12, nrhs: int = 2, distinct: int = 4,
              routine: str = "gesv") -> List[dict]:
    """A quiet baseline stream with one hard traffic step in the
    middle — the elastic capacity plane's canonical input.  Arrivals
    run at ``base_rps`` until ``burst_start_s``, jump to ``burst_rps``
    for ``burst_len_s``, then fall back to ``base_rps`` until the
    request budget is spent.  A static fleet sized for the baseline
    builds queue (and misses its tail budget) inside the burst; an
    elastic fleet must scale up through it and give the lanes back
    after — ``run_tests.py --scale`` replays exactly this shape twice.

    Rows draw from a ``distinct``-matrix pool with fresh right-hand
    sides (bursts of same-A traffic, the factor cache's steady state),
    so burst latency measures dispatch capacity, not factorization."""
    rng = random.Random(seed)
    rows = []
    t = 0.0
    for k in range(requests):
        in_burst = burst_start_s <= t < burst_start_s + burst_len_s
        rate = burst_rps if in_burst else base_rps
        fp = f"burst-{seed}-{k % max(distinct, 1)}"
        rows.append(_row(round(t, 6), routine, n, nrhs, "gold", "normal",
                         _seed_of(fp), k, repeat_fp=fp))
        t += rng.expovariate(rate)
    return rows


def warm_spec(rows: List[dict], gap_s: float = 0.025) -> List[dict]:
    """A pool-warming prelude for ``rows``: the first row of every
    ``repeat_fp`` group, re-paced serially ``gap_s`` apart.  Replaying
    it (same ``seed``!) before the measured phase factors each pool
    matrix once, so the soak measures the steady state the factor
    cache was built for instead of a cold-start miss storm — the exact
    analogue of ``warmup()`` for executables.  Deadlines are stripped
    (a warm pass must populate, not shed)."""
    seen: set = set()
    out = []
    for r in sorted(rows, key=lambda r: r.get("t_offset", 0.0)):
        fp = r.get("repeat_fp")
        if not fp or fp in seen:
            continue
        seen.add(fp)
        w = dict(r)
        w["t_offset"] = round(len(out) * gap_s, 6)
        w["deadline_s"] = None
        out.append(w)
    return out


def merge_specs(*specs: List[dict]) -> List[dict]:
    """Overlay several generated streams onto one shared timeline
    (rows keep their offsets; the result is sorted)."""
    out: List[dict] = []
    for s in specs:
        out.extend(dict(r) for r in s)
    out.sort(key=lambda r: r.get("t_offset", 0.0))
    return out


GENERATORS: Dict[str, object] = {
    "multitenant": gen_multitenant,
    "repeated_a": gen_repeated_a,
    "adversarial_flood": gen_adversarial_flood,
    "deadline_storm": gen_deadline_storm,
    "burst": gen_burst,
}
