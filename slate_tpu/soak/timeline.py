"""Health timeline: background-cadence sampling of ``health()`` +
devmon gauges into ``{"type": "timeline"}`` metrics rows.

Every existing report row is an end-of-run aggregate — a quarantine
storm that engaged and recovered mid-soak, a queue that spiked and
drained, an adaptive window that collapsed and re-widened are all
invisible by dump time.  The sampler turns the probe surface into a
bounded time series (``metrics.record_timeline`` caps rows like the
event buffer) that ``tools/soak_report.py`` scans for disruption and
recovery intervals.

Zero overhead off: nothing samples until a sampler is constructed and
started; the serve tier itself is untouched (the sampler is a reader
of ``health()``, which was already designed to be polled)."""

from __future__ import annotations

import threading
from typing import Optional

from ..aux import metrics


def sample_row(svc) -> dict:
    """One timeline row from a service's ``health()`` + the registry:
    queue/inflight depth, breaker and quarantine state, burn tiers,
    span-ring pressure, factor-cache bytes, adaptive windows, HBM
    gauges — the scalars whose TRAJECTORY the verdict reads (recovery
    times per disruption), alongside cumulative shed/hedge/integrity
    counters so rates are one difference away."""
    h = svc.health()
    c = metrics.counters()
    g = metrics.gauges()
    row = {
        "ready": bool(h.get("ready")),
        "phase": h.get("phase"),
        "queue_depth": int(h.get("queue_depth") or 0),
        "inflight": int(h.get("inflight") or 0),
        "breakers_open": len(h.get("open_buckets") or ()),
        "worker_restarts": int(h.get("worker_restarts") or 0),
        "failures_60s": int(h.get("failures_60s") or 0),
        "shed": int(c.get("serve.shed", 0)),
        "deadline_miss": int(c.get("serve.deadline_miss", 0)),
        "hedge_sent": int(c.get("serve.hedge.sent", 0)),
        "integrity_fail": int(c.get("serve.integrity.fail", 0)),
        "burn_exhausted": int(c.get("serve.slo_burn.exhausted", 0)),
    }
    integ = h.get("integrity")
    if integ is not None:
        row["quarantined"] = len(integ.get("quarantined") or ())
    ring = h.get("trace_ring")
    if ring is not None:
        row["ring_evicted"] = int(ring.get("evicted", 0))
    fc = h.get("factor_cache")
    if fc is not None:
        row["factor_cache_bytes"] = int(fc.get("bytes", 0) or 0)
        row["factor_cache_entries"] = int(fc.get("entries", 0) or 0)
    adm = h.get("admission")
    if adm is not None:
        row["overload_level"] = adm.get("overload_level")
        windows = [
            v for name, v in g.items()
            if name.startswith("serve.adaptive.")
            and name.endswith(".window_s")
        ]
        if windows:
            row["adaptive_window_min_s"] = round(min(windows), 6)
    hbm = [
        v for name, v in g.items()
        if name.startswith("devmon.") and name.endswith(".bytes_in_use")
    ]
    if hbm:
        row["hbm_bytes_in_use"] = int(sum(hbm))
    return row


class TimelineSampler:
    """Daemon-thread sampler: one :func:`sample_row` every
    ``period_s`` into the registry's timeline buffer.  ``stop()``
    takes a final sample (the run's terminal state always lands in
    the dump) and joins.  Sampling failures are counted, never
    raised — a mid-soak probe hiccup (e.g. racing a worker restart)
    must not kill the soak."""

    def __init__(self, svc, period_s: float = 0.05):
        self.svc = svc
        self.period_s = max(float(period_s), 0.001)
        self.errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _sample_once(self) -> None:
        try:
            metrics.record_timeline(sample_row(self.svc))
        except Exception:
            self.errors += 1

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            self._sample_once()

    def start(self) -> "TimelineSampler":
        if self._thread is None:
            self._stop.clear()
            self._sample_once()  # t=0 baseline row
            self._thread = threading.Thread(
                target=self._loop, name="soak-timeline", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> "TimelineSampler":
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=10.0)
            self._thread = None
            self._sample_once()  # terminal state
        return self

    def __enter__(self) -> "TimelineSampler":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
