"""Capacity-signal aggregator: everything the repo already emits
about pressure, folded into one smoothed :class:`PressureSnapshot`.

The serve tier publishes its load story piecemeal — the admission
plane's budget-burn EWMA and overload level, the integrity plane's
hedge counter, the batcher's ``serve.bucket_pad_waste``, each lane's
queue depth and head-of-line age, devmon's HBM headroom.  None of
those is a fleet-sizing signal by itself: a deep queue with a young
head is a burst the batcher will absorb, a high burn with an empty
queue is a latency-budget problem, not a capacity one.  This module
samples all of them on one clock and reduces them to a single
composite ``pressure`` scalar (1.0 = "at capacity") that the
:mod:`~slate_tpu.scale.controller` thresholds against.

Determinism is the design constraint (the controller gate replays
decisions): sampling (:func:`read_raw`, which touches the live
service) is split from reduction (:class:`SignalAggregator.update`,
a pure fold over raw dicts).  Feed the same raw stream twice and the
aggregator produces byte-identical snapshots — no wall-clock reads,
no randomness, all smoothing state explicit.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Optional

from ..aux import metrics

#: counters sampled for rate signals (cumulative -> smoothed delta/s)
_RATE_COUNTERS = {
    "requests": "serve.requests",
    "hedges": "serve.hedge.sent",
    "pad_rows": "serve.bucket_pad_waste",
}


@dataclass(frozen=True)
class PressureSnapshot:
    """One smoothed observation of serve-tier pressure.

    ``pressure`` is the composite the controller acts on: the max of
    the normalized component signals, so ANY saturated dimension
    (queue depth, head-of-line age, budget burn, overload level)
    pushes it past 1.0.  Everything else is carried for the decision
    record — ``tools/capacity_report.py`` refuses scale-ups whose
    snapshot shows no driving signal.
    """

    t: float
    replicas: int
    queue_depth: int
    inflight: int
    queue_per_replica: float  # smoothed depth / replica
    oldest_queued_s: float  # smoothed max head-of-line age
    burn_ewma: float  # admission budget burn (0 when plane off)
    overload_level: int  # admission overload level (0 when off)
    request_rate: float  # smoothed submits/s
    hedge_rate: float  # smoothed hedges/s
    pad_waste_rate: float  # smoothed padded rows/s
    hbm_headroom_frac: Optional[float]  # min over devices; None on CPU
    pressure: float  # composite; 1.0 = at capacity


def read_raw(svc, now: Optional[float] = None) -> Dict[str, float]:
    """Sample the live service into one raw (unsmoothed) observation.

    Cheap by construction: one pass over the lanes under the service
    condition lock, one admission snapshot (self-locked), one
    counter-registry read, one devmon sample.  Returns plain floats so
    the aggregator — and the tests — never need the service itself.
    """
    if now is None:
        now = time.monotonic()
    raw: Dict[str, float] = {"t": now}
    with svc._cond:
        reps = list(svc._replicas)
        raw["replicas"] = float(len(reps))
        raw["queue_depth"] = float(sum(len(r.q) for r in reps))
        raw["inflight"] = float(sum(len(r.inflight) for r in reps))
        oldest = 0.0
        mono = time.monotonic()  # t_submit's clock, not the caller's
        for r in reps:
            if r.q:
                oldest = max(
                    oldest, mono - min(x.t_submit for x in r.q)
                )
        raw["oldest_queued_s"] = oldest
    if svc._admission is not None:
        adm = svc._admission.snapshot()
        raw["burn_ewma"] = float(adm.get("burn_ewma") or 0.0)
        raw["overload_level"] = float(adm.get("overload_level") or 0)
    else:
        raw["burn_ewma"] = 0.0
        raw["overload_level"] = 0.0
    counters = metrics.counters() if metrics.is_on() else {}
    for field, name in _RATE_COUNTERS.items():
        raw[field] = float(counters.get(name, 0))
    raw["hbm_headroom_frac"] = _hbm_headroom(svc)
    return raw


def _hbm_headroom(svc) -> Optional[float]:
    """Min free-HBM fraction across the service's devices (None when
    the backend does not report memory, e.g. XLA:CPU)."""
    try:
        from ..aux import devmon

        devs = [r.device for r in svc._replicas if r.device is not None]
        rows = devmon.sample_devices(devs or None)
    except Exception:
        return None
    frac = None
    for row in rows:
        used, limit = row.get("bytes_in_use"), row.get("bytes_limit")
        if used is None or not limit:
            continue
        f = max(0.0, 1.0 - used / limit)
        frac = f if frac is None else min(frac, f)
    return frac


class SignalAggregator:
    """Pure fold from raw observations to :class:`PressureSnapshot`.

    EWMA-smooths the level signals (queue depth per replica, oldest
    age) and converts the cumulative counters to smoothed rates.  The
    composite ``pressure`` is the max of each signal over its
    reference scale — the references define "at capacity":

    * ``depth_ref``   — queued requests per replica worth one unit
    * ``age_ref``     — head-of-line seconds worth one unit
    * ``burn_ref``    — admission burn EWMA worth one unit
    * ``hedge_ref``   — hedged fraction of traffic worth one unit

    The overload level feeds in directly (level 1 == pressure 1.0):
    when the admission plane is already shedding, capacity is the
    answer regardless of what the local signals say.
    """

    def __init__(
        self,
        alpha: float = 0.4,
        depth_ref: float = 4.0,
        age_ref: float = 0.5,
        burn_ref: float = 0.5,
        hedge_ref: float = 0.25,
    ) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1]: {alpha}")
        self.alpha = float(alpha)
        self.depth_ref = float(depth_ref)
        self.age_ref = float(age_ref)
        self.burn_ref = float(burn_ref)
        self.hedge_ref = float(hedge_ref)
        self._prev: Optional[Dict[str, float]] = None
        self._ew: Dict[str, float] = {}

    def _smooth(self, key: str, value: float) -> float:
        prev = self._ew.get(key)
        cur = value if prev is None else (
            self.alpha * value + (1.0 - self.alpha) * prev
        )
        self._ew[key] = cur
        return cur

    def reset(self) -> None:
        self._prev = None
        self._ew.clear()

    def update(self, raw: Dict[str, float]) -> PressureSnapshot:
        now = float(raw["t"])
        replicas = max(int(raw.get("replicas", 1)), 1)
        depth = int(raw.get("queue_depth", 0))
        inflight = int(raw.get("inflight", 0))
        qpr = self._smooth("qpr", depth / replicas)
        oldest = self._smooth(
            "oldest", float(raw.get("oldest_queued_s", 0.0))
        )
        burn = float(raw.get("burn_ewma", 0.0))
        level = int(raw.get("overload_level", 0))
        rates = {f: 0.0 for f in _RATE_COUNTERS}
        if self._prev is not None:
            dt = now - float(self._prev["t"])
            if dt > 0:
                for f in _RATE_COUNTERS:
                    d = float(raw.get(f, 0.0)) - float(
                        self._prev.get(f, 0.0)
                    )
                    rates[f] = self._smooth(f, max(d, 0.0) / dt)
        self._prev = dict(raw)
        req_rate = rates["requests"]
        hedge_share = (
            rates["hedges"] / req_rate if req_rate > 0 else 0.0
        )
        pressure = max(
            qpr / self.depth_ref,
            oldest / self.age_ref,
            burn / self.burn_ref,
            float(level),
            hedge_share / self.hedge_ref,
        )
        return PressureSnapshot(
            t=now,
            replicas=replicas,
            queue_depth=depth,
            inflight=inflight,
            queue_per_replica=round(qpr, 6),
            oldest_queued_s=round(oldest, 6),
            burn_ewma=burn,
            overload_level=level,
            request_rate=round(req_rate, 6),
            hedge_rate=round(rates["hedges"], 6),
            pad_waste_rate=round(rates["pad_rows"], 6),
            hbm_headroom_frac=raw.get("hbm_headroom_frac"),
            pressure=round(pressure, 6),
        )
