"""Elastic capacity plane: the fourth control loop, from observed
pressure to replica count.

The serve tier already *measures* everything that matters — the
admission plane's budget-burn EWMA and overload level (PR12), hedge
and pad-waste counters, per-lane queue depth and head-of-line age,
devmon HBM headroom — and PR16's workload recorder made the traffic
itself replayable.  What was missing is the actuator: capacity stayed
whatever ``replicas=N`` said at construction (ROADMAP item 3; Clipper
shows the adaptive-serving control shape, and Dean & Barroso's
tail-at-scale argument makes p99 misses a *fleet-sizing* signal, not
just a hedging one — PAPERS.md).

Three modules close the loop:

* :mod:`slate_tpu.scale.signals` — capacity-signal aggregator: one
  clock, every pressure source, smoothed into a deterministic
  :class:`~slate_tpu.scale.signals.PressureSnapshot` with a single
  composite ``pressure`` scalar (1.0 = at capacity).
* :mod:`slate_tpu.scale.controller` — hysteresis policy
  (min/max replicas, separate up/down thresholds and cool-downs,
  AIMD step sizing) driving the service's new ``add_replica()`` /
  ``remove_replica()`` hooks.  A scale-up lane comes live warm: the
  artifact store + ``_bring_live`` device priming mean its first
  steady-state request compiles nothing.  Scale-down quiesces
  through the drain path and re-homes lane-affine factor-cache
  entries before teardown.
* :mod:`slate_tpu.scale.warmup_plan` — predictive warmup: replay a
  recorded trace offline into a warmup manifest subset + factor
  preload ranked by traffic-weighted compile cost.

``tools/capacity_report.py`` judges the decision record;
``run_tests.py --scale`` is the gate.  Zero overhead off, like every
other plane: with ``SLATE_TPU_SCALE`` unset the service never
constructs a scaler and the hot path is byte-identical to before.
"""

from . import controller, signals, warmup_plan  # noqa: F401
from .controller import (  # noqa: F401
    AutoScaler,
    ScaleController,
    ScaleDecision,
    ScalePolicy,
    parse_spec,
    policy_from_options,
)
from .signals import PressureSnapshot, SignalAggregator  # noqa: F401
from .warmup_plan import WarmupPlan, plan_from_trace  # noqa: F401

__all__ = [
    "AutoScaler", "ScaleController", "ScaleDecision", "ScalePolicy",
    "PressureSnapshot", "SignalAggregator", "WarmupPlan",
    "parse_spec", "policy_from_options", "plan_from_trace",
    "controller", "signals", "warmup_plan",
]

# `slate_tpu` exports the aux *routine* `scale` (A *= numer/denom,
# reference src/scale.cc) at top level; importing this subpackage
# rebinds the `slate_tpu.scale` attribute to the module, which would
# silently break `slate_tpu.scale(2.0, 1.0, A)` callers.  Keep the
# routine reachable through the module by making the module callable —
# both worlds work, whichever import happened first.
import sys as _sys
import types as _types


class _CallableScaleModule(_types.ModuleType):
    def __call__(self, numer, denom, A, opts=None):
        from ..drivers.aux import scale as _scale_routine

        return _scale_routine(numer, denom, A, opts)


_sys.modules[__name__].__class__ = _CallableScaleModule
