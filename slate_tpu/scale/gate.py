"""Capacity-gate gauge publication — the one emitter of the
``scale.gate.*`` family.

The ``run_tests.py --scale`` gate replays the same recorded burst
against a static fleet and an elastic one, then publishes the verdict
inputs here so :mod:`tools/capacity_report` can judge the run from the
metrics JSONL alone (the report process never touches the service).
Keeping the emitter inside ``slate_tpu/`` — with every name a literal
— is what lets the metric-drift lint hold the gate driver and the
report to the same spelling.
"""

from typing import Dict

from ..aux import metrics

#: every gauge the capacity gate publishes; tools/capacity_report.py
#: joins exactly these names.  static/elastic_p99_s are the two legs'
#: tail latencies, budget_s the SLO both are judged against,
#: replica_peak/replicas_end the fleet's high-water mark and final
#: size, min/max_replicas + up_threshold the policy bounds the verdict
#: checks them against, and new_lane_compiles the steady-state compile
#: count (total jit.compilations minus the counted pre-traffic
#: device_primes inside add_replica).
GATE_GAUGES = (
    "scale.gate.static_p99_s",
    "scale.gate.elastic_p99_s",
    "scale.gate.budget_s",
    "scale.gate.replica_peak",
    "scale.gate.replicas_end",
    "scale.gate.min_replicas",
    "scale.gate.max_replicas",
    "scale.gate.up_threshold",
    "scale.gate.new_lane_compiles",
    "scale.gate.device_primes",
)

_PREFIX = "scale.gate."


def publish(values: Dict[str, float]) -> None:
    """Publish the gate verdict inputs as ``scale.gate.*`` gauges.

    ``values`` keys are the un-prefixed gauge names (``"budget_s"``,
    not ``"scale.gate.budget_s"``).  Every known gauge must be present
    and no unknown key is accepted — a silently dropped or misspelled
    column is exactly the drift that would make the capacity report
    judge a different run than the one that happened."""
    want = {g[len(_PREFIX):] for g in GATE_GAUGES}
    missing = want - set(values)
    extra = set(values) - want
    if missing or extra:
        raise KeyError(
            f"capacity gate gauges: missing={sorted(missing)} "
            f"unknown={sorted(extra)}"
        )
    for name in GATE_GAUGES:
        metrics.gauge(name, float(values[name[len(_PREFIX):]]))
