"""Hysteresis autoscale policy: pressure snapshots in, replica
add/remove decisions out, with the flap-prevention machinery
(separate up/down thresholds, per-direction cool-downs, AIMD step
sizing) the Clipper / tail-at-scale literature prescribes.

Three layers, so each is testable alone:

* :class:`ScalePolicy` — the knobs (min/max replicas, thresholds,
  cool-downs), plus the ``SLATE_TPU_SCALE`` env grammar
  (:func:`parse_spec`).
* :class:`ScaleController` — a PURE decision function over
  :class:`~slate_tpu.scale.signals.PressureSnapshot` streams: no
  clock reads, no service handle, all state explicit.  Same snapshot
  stream in, same decision stream out — the seeded-determinism test
  and the capacity report both lean on this.
* :class:`AutoScaler` — the actuator: a background sampling loop
  (or an externally driven :meth:`AutoScaler.step`) that reads
  signals, runs the controller, and drives the service's
  ``add_replica()`` / ``remove_replica()`` hooks, emitting the
  ``scale.*`` metric family and ``scale_up`` / ``scale_down`` span
  events as it goes.

Scale-up is multiplicative-increase (1, 2, 4, ... lanes per decision
while pressure stays above threshold, capped by ``step_max`` and
``max_replicas``); scale-down is additive-decrease (one lane at a
time) — the asymmetric AIMD shape that reacts fast to saturation and
gives back capacity cautiously.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import List, Optional

from ..aux import metrics, spans
from . import signals as _sig

SCALE_ENV = "SLATE_TPU_SCALE"

#: decision actions
UP, DOWN, HOLD = "up", "down", "hold"


@dataclass
class ScalePolicy:
    """Autoscale knobs.  ``up_threshold`` / ``down_threshold`` are in
    composite-pressure units (1.0 = at capacity); the gap between them
    is the hysteresis band — a fleet sitting anywhere inside it holds.
    Cool-downs are per direction: scale-up must wait ``up_cooldown_s``
    after ANY change (so a fresh lane's effect is observed before
    adding another), scale-down waits the longer ``down_cooldown_s``
    (giving back capacity is the cheap direction to be slow in)."""

    min_replicas: int = 1
    max_replicas: int = 4
    up_threshold: float = 1.0
    down_threshold: float = 0.25
    up_cooldown_s: float = 2.0
    down_cooldown_s: float = 6.0
    step_max: int = 2
    period_s: float = 0.25  # AutoScaler sampling cadence

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1: {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas {self.max_replicas} < min_replicas "
                f"{self.min_replicas}"
            )
        if self.down_threshold >= self.up_threshold:
            raise ValueError(
                f"down_threshold {self.down_threshold} must sit below "
                f"up_threshold {self.up_threshold} (hysteresis band)"
            )
        if self.step_max < 1:
            raise ValueError(f"step_max must be >= 1: {self.step_max}")


def parse_spec(spec: str) -> Optional[ScalePolicy]:
    """Parse the ``SLATE_TPU_SCALE`` grammar: empty/``0``/``off`` ->
    None (plane off, zero overhead), ``1``/``on`` -> defaults, or a
    comma list of ``min=<n>``, ``max=<n>``, ``up=<p>``, ``down=<p>``,
    ``up_cooldown=<s>``, ``down_cooldown=<s>``, ``step=<n>``,
    ``period=<s>`` overrides."""
    spec = (spec or "").strip()
    if not spec or spec.lower() in ("0", "off", "false", "no"):
        return None
    if spec.lower() in ("1", "on", "true", "yes"):
        return ScalePolicy()
    keys = {
        "min": ("min_replicas", int),
        "max": ("max_replicas", int),
        "up": ("up_threshold", float),
        "down": ("down_threshold", float),
        "up_cooldown": ("up_cooldown_s", float),
        "down_cooldown": ("down_cooldown_s", float),
        "step": ("step_max", int),
        "period": ("period_s", float),
    }
    kw: dict = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        k, sep, v = item.partition("=")
        k, v = k.strip().lower(), v.strip()
        if not sep or k not in keys:
            raise ValueError(
                f"{SCALE_ENV}={spec!r}: expected k=v with k in "
                f"{sorted(keys)}, got {item!r}"
            )
        name, conv = keys[k]
        kw[name] = conv(v)
    return ScalePolicy(**kw)


def policy_from_options(opts=None) -> Optional[ScalePolicy]:
    """Resolve the process/service default: ``SLATE_TPU_SCALE`` wins
    (grammar above), else ``Option.ServeScale``.  None = plane off —
    the service never constructs a scaler."""
    from ..enums import Option
    from ..options import get_option

    spec = os.environ.get(SCALE_ENV)
    if spec is None:
        spec = str(get_option(opts, Option.ServeScale) or "")
    return parse_spec(spec)


@dataclass(frozen=True)
class ScaleDecision:
    """One controller output: what to do, by how much, and the
    evidence (the driving snapshot rides along so the decision record
    is self-certifying — the capacity report flags any ``up`` whose
    snapshot shows sub-threshold pressure)."""

    action: str  # up | down | hold
    delta: int  # lanes to add (up) or remove (down); 0 on hold
    reason: str
    snapshot: _sig.PressureSnapshot


class ScaleController:
    """Pure hysteresis policy over a snapshot stream.  Deterministic:
    cool-down clocks come from ``snapshot.t``, never the wall."""

    def __init__(self, policy: Optional[ScalePolicy] = None) -> None:
        self.policy = policy or ScalePolicy()
        self._last_change_t: Optional[float] = None
        self._up_step = 1  # doubles on consecutive ups (AIMD)

    def reset(self) -> None:
        self._last_change_t = None
        self._up_step = 1

    def _cooling(self, t: float, window_s: float) -> bool:
        return (
            self._last_change_t is not None
            and (t - self._last_change_t) < window_s
        )

    def decide(self, snap: _sig.PressureSnapshot) -> ScaleDecision:
        p = self.policy
        if snap.pressure >= p.up_threshold:
            if snap.replicas >= p.max_replicas:
                return ScaleDecision(
                    HOLD, 0, "at max_replicas", snap
                )
            if self._cooling(snap.t, p.up_cooldown_s):
                return ScaleDecision(HOLD, 0, "up cooldown", snap)
            delta = min(
                self._up_step,
                p.step_max,
                p.max_replicas - snap.replicas,
            )
            self._up_step = min(self._up_step * 2, p.step_max)
            self._last_change_t = snap.t
            return ScaleDecision(
                UP, delta,
                f"pressure {snap.pressure} >= {p.up_threshold}", snap,
            )
        # below the up threshold: the next saturation starts gently
        self._up_step = 1
        if snap.pressure <= p.down_threshold:
            if snap.replicas <= p.min_replicas:
                return ScaleDecision(HOLD, 0, "at min_replicas", snap)
            if self._cooling(snap.t, p.down_cooldown_s):
                return ScaleDecision(HOLD, 0, "down cooldown", snap)
            self._last_change_t = snap.t
            return ScaleDecision(
                DOWN, 1,
                f"pressure {snap.pressure} <= {p.down_threshold}",
                snap,
            )
        return ScaleDecision(HOLD, 0, "in hysteresis band", snap)


class AutoScaler:
    """The actuator: samples signals, runs the controller, drives the
    service's replica lifecycle hooks.  ``start()`` spawns a daemon
    sampling thread at ``policy.period_s``; tests (and the gate
    drivers) may instead call :meth:`step` on their own clock.

    Every applied decision lands in three places: the ``scale.*``
    metric family (counters ``scale.decisions`` / ``scale.up`` /
    ``scale.down``, gauges ``scale.pressure`` / ``scale.replicas``),
    a ``{"kind": "scale"}`` timeline row carrying the full driving
    snapshot, and a ``scale_up`` / ``scale_down`` span event on the
    ring."""

    def __init__(
        self,
        svc,
        policy: Optional[ScalePolicy] = None,
        aggregator: Optional[_sig.SignalAggregator] = None,
    ) -> None:
        self.svc = svc
        self.policy = policy or ScalePolicy()
        self.controller = ScaleController(self.policy)
        self.aggregator = aggregator or _sig.SignalAggregator()
        self.decisions: List[ScaleDecision] = []  # applied up/down only
        self.last: Optional[ScaleDecision] = None  # most recent step()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def describe(self) -> dict:
        """The health()["capacity"] block: policy knobs + the latest
        decision's evidence."""
        import dataclasses

        last = self.last
        return {
            "policy": dataclasses.asdict(self.policy),
            "running": self._thread is not None,
            "decisions": len(self.decisions),
            "pressure": (
                last.snapshot.pressure if last is not None else None
            ),
            "replicas": (
                last.snapshot.replicas if last is not None else None
            ),
            "last_action": last.action if last is not None else None,
            "last_reason": last.reason if last is not None else None,
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "AutoScaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="slate-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.policy.period_s):
            try:
                self.step()
            except Exception:
                metrics.inc("scale.step_errors")

    # -- one control step --------------------------------------------------

    def step(self, now: Optional[float] = None) -> ScaleDecision:
        """Sample -> decide -> act, once.  Returns the decision (which
        carries its driving snapshot)."""
        raw = _sig.read_raw(self.svc, now)
        snap = self.aggregator.update(raw)
        dec = self.controller.decide(snap)
        self.last = dec
        metrics.inc("scale.decisions")
        metrics.gauge("scale.pressure", snap.pressure)
        metrics.gauge("scale.replicas", snap.replicas)
        if dec.action == UP:
            self._scale_up(dec)
        elif dec.action == DOWN:
            self._scale_down(dec)
        return dec

    def _record(self, dec: ScaleDecision, applied: int) -> None:
        self.decisions.append(dec)
        snap = dec.snapshot
        metrics.record_timeline({
            "kind": "scale", "t_mono": snap.t, "action": dec.action,
            "delta": applied, "reason": dec.reason,
            "pressure": snap.pressure, "replicas": snap.replicas,
            "queue_depth": snap.queue_depth,
            "oldest_queued_s": snap.oldest_queued_s,
            "burn_ewma": snap.burn_ewma,
            "overload_level": snap.overload_level,
        })

    def _scale_up(self, dec: ScaleDecision) -> None:
        applied = 0
        for _ in range(dec.delta):
            try:
                name = self.svc.add_replica()
            except Exception:
                metrics.inc("scale.add_failed")
                break
            applied += 1
            metrics.inc("scale.up")
            if spans.is_on():
                spans.event(
                    "scale_up", lane=f"replica-{name}",
                    pressure=dec.snapshot.pressure, reason=dec.reason,
                )
        if applied:
            self._record(dec, applied)

    def _scale_down(self, dec: ScaleDecision) -> None:
        applied = 0
        for _ in range(dec.delta):
            try:
                name = self.svc.remove_replica()
            except Exception:
                metrics.inc("scale.remove_failed")
                break
            applied += 1
            metrics.inc("scale.down")
            if spans.is_on():
                spans.event(
                    "scale_down", lane=f"replica-{name}",
                    pressure=dec.snapshot.pressure, reason=dec.reason,
                )
        if applied:
            self._record(dec, applied)
