"""Predictive warmup: a recorded workload trace replayed *offline*
into a ranked warmup plan, replacing "warm whatever was seen".

The cold-start story so far warms the whole manifest (``restore()``)
or whatever an operator hand-listed.  Both ignore what the traffic
actually was.  This module folds a recorded soak/span load spec
(:mod:`slate_tpu.soak.record` JSONL rows) into a
:class:`WarmupPlan` — the (bucket, batch) entries worth priming,
ranked by traffic-weighted compile cost::

    score = traffic_share x compile_cost

so the executables that would hurt most to compile under live load
(hot AND expensive) prime first, and a budget (``top(k)``, or a
scale-up lane's priming deadline) truncates from the bottom.  The
model mirrors what the serve tier would really dispatch:

* rows bucket through the same ``bucket_for`` lattice the service
  uses (same floors, schedule, precision);
* a bucket whose arrivals burst back-to-back gets its coalesced
  batch point planned alongside batch 1;
* repeat-``repeat_fp`` groups (the factor cache's hit population)
  plan the ``phase="solve"`` sibling too — on a warm cache the hits
  dispatch the trsm-only family, and omitting it re-compiles mid-run
  (the soak driver learned this the hard way);
* the same repeat groups rank the factor-cache *preload*: biggest
  (group_size - 1) x factor-cost first.

Compile cost comes from the executable cache's captured cost
registry when present (``cache.cost()``: real build evidence) and
falls back to the ``phase_flops`` hand model, so planning works on a
bare trace with no cache at all.  Everything is deterministic: same
rows in, same plan out.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..serve import buckets as _bk
from ..serve.buckets import BucketKey

PLAN_VERSION = 1


@dataclass(frozen=True)
class PlanEntry:
    """One (bucket, batch) executable worth priming."""

    key: BucketKey
    batch: int
    rows: int  # trace rows that would dispatch this bucket
    share: float  # rows / total_rows
    cost: float  # compile-cost estimate (model FLOPs or captured)
    score: float  # share x cost — the ranking unit

    def to_json(self) -> dict:
        return {
            "key": self.key.to_json(), "batch": self.batch,
            "rows": self.rows, "share": self.share,
            "cost": self.cost, "score": self.score,
        }


@dataclass(frozen=True)
class FactorPreload:
    """One repeat-A group worth pre-factoring into the cache."""

    repeat_fp: str
    rows: int  # group size in the trace
    n: int
    score: float  # (rows - 1) x factor flops — hits it would buy

    def to_json(self) -> dict:
        return {
            "repeat_fp": self.repeat_fp, "rows": self.rows,
            "n": self.n, "score": self.score,
        }


@dataclass
class WarmupPlan:
    """Ranked warmup manifest subset + factor-cache preload."""

    entries: List[PlanEntry]  # score-descending
    preload: List[FactorPreload]  # score-descending
    total_rows: int

    def top(self, k: int) -> List[PlanEntry]:
        return self.entries[: max(int(k), 0)]

    def pairs(self, k: Optional[int] = None) -> List[Tuple[BucketKey, int]]:
        """The (key, batch) list ``ExecutableCache.prime`` consumes,
        plan order."""
        ents = self.entries if k is None else self.top(k)
        return [(e.key, e.batch) for e in ents]

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(json.dumps({
                "type": "plan_meta", "version": PLAN_VERSION,
                "total_rows": self.total_rows,
                "entries": len(self.entries),
                "preload": len(self.preload),
            }) + "\n")
            for e in self.entries:
                f.write(json.dumps(
                    {"type": "entry", **e.to_json()}) + "\n")
            for p in self.preload:
                f.write(json.dumps(
                    {"type": "preload", **p.to_json()}) + "\n")
        return path

    @staticmethod
    def load(path: str) -> "WarmupPlan":
        entries: List[PlanEntry] = []
        preload: List[FactorPreload] = []
        total = 0
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                r = json.loads(line)
                t = r.get("type")
                if t == "plan_meta":
                    v = r.get("version", 0)
                    if v > PLAN_VERSION:
                        raise ValueError(
                            f"{path}: plan version {v} is newer than "
                            f"this reader ({PLAN_VERSION})"
                        )
                    total = int(r.get("total_rows", 0))
                elif t == "entry":
                    entries.append(PlanEntry(
                        key=BucketKey.from_json(r["key"]),
                        batch=int(r["batch"]), rows=int(r["rows"]),
                        share=float(r["share"]), cost=float(r["cost"]),
                        score=float(r["score"]),
                    ))
                elif t == "preload":
                    preload.append(FactorPreload(
                        repeat_fp=str(r["repeat_fp"]),
                        rows=int(r["rows"]), n=int(r["n"]),
                        score=float(r["score"]),
                    ))
        return WarmupPlan(
            entries=entries, preload=preload, total_rows=total
        )


def _burst_batch(offsets: List[float], batch_max: int,
                 window_s: float) -> int:
    """Largest same-bucket arrival burst within one coalescing
    window — the batch point the service would actually dispatch."""
    best = run = 1
    start = 0
    for i in range(1, len(offsets)):
        while offsets[i] - offsets[start] > window_s:
            start += 1
        run = i - start + 1
        best = max(best, run)
    return min(best, max(int(batch_max), 1))


def plan_from_trace(
    rows: List[dict],
    cache=None,
    batch_max: int = 8,
    batch_window_s: float = 0.005,
    dim_floor: int = _bk.DIM_FLOOR,
    nrhs_floor: int = _bk.NRHS_FLOOR,
    schedule: str = "auto",
    precision: str = "full",
) -> WarmupPlan:
    """Fold recorded load-spec rows into a ranked :class:`WarmupPlan`.

    ``rows`` is the :mod:`soak.record` schema (``record.load()``
    output, a live :class:`~slate_tpu.soak.record.Recorder`'s rows, or
    ``from_ring()`` reconstruction).  ``cache`` (optional) supplies
    captured compile costs; without it the ``phase_flops`` model
    ranks alone."""
    total = len(rows)
    # bucket the trace through the service's own lattice
    counts: Dict[Tuple[BucketKey, str], int] = {}
    offsets: Dict[BucketKey, List[float]] = {}
    repeats: Dict[str, dict] = {}
    for r in rows:
        m, n, nrhs = (int(x) for x in r["bucket_shape"])
        key = _bk.bucket_for(
            r["routine"], m, n, nrhs, r.get("dtype", "float64"),
            floor=dim_floor, nrhs_floor=nrhs_floor,
            schedule=schedule, precision=precision,
        )
        counts[(key, "full")] = counts.get((key, "full"), 0) + 1
        offsets.setdefault(key, []).append(
            float(r.get("t_offset", 0.0)))
        fp = r.get("repeat_fp")
        if fp:
            g = repeats.setdefault(fp, {"rows": 0, "n": key.n,
                                        "key": key})
            g["rows"] += 1
    # repeat groups of >= 2 hit the factor cache at replay: their
    # traffic dispatches the solve-phase sibling, so plan it too
    for fp, g in repeats.items():
        if g["rows"] < 2:
            continue
        key = g["key"]
        sib = key.solve_sibling()
        counts[(sib, "solve")] = (
            counts.get((sib, "solve"), 0) + int(g["rows"]) - 1
        )
        offsets.setdefault(sib, offsets.get(key, []))
    entries: List[PlanEntry] = []
    for (key, _phase), cnt in counts.items():
        share = cnt / total if total else 0.0
        batches = {1}
        b = _burst_batch(
            sorted(offsets.get(key, [])), batch_max, batch_window_s
        )
        if b > 1:
            batches.add(b)
        for batch in sorted(batches):
            cost = _compile_cost(cache, key, batch)
            entries.append(PlanEntry(
                key=key, batch=batch, rows=cnt,
                share=round(share, 6), cost=cost,
                score=round(share * cost, 3),
            ))
    # rank: score desc, then label/batch for a deterministic tiebreak
    entries.sort(key=lambda e: (-e.score, e.key.label, e.batch))
    preload = [
        FactorPreload(
            repeat_fp=fp, rows=int(g["rows"]), n=int(g["n"]),
            score=round(
                (g["rows"] - 1) * _factor_flops(g["key"]), 3),
        )
        for fp, g in repeats.items() if g["rows"] >= 2
    ]
    preload.sort(key=lambda p: (-p.score, p.repeat_fp))
    return WarmupPlan(
        entries=entries, preload=preload, total_rows=total
    )


def _compile_cost(cache, key: BucketKey, batch: int) -> float:
    """Captured build cost when the cache has evidence, model FLOPs
    otherwise — one consistent unit (FLOPs) either way."""
    if cache is not None:
        rec = cache.cost(key, batch)
        if rec:
            fl = rec.get("flops") or rec.get("flops_model")
            if fl:
                return float(fl)
    return _bk.phase_flops(key, batch)


def _factor_flops(key: BucketKey) -> float:
    """The factorization-only share of one full dispatch — what a
    cache hit saves."""
    return max(
        _bk.phase_flops(key, 1)
        - _bk.phase_flops(key.solve_sibling(), 1),
        0.0,
    )
