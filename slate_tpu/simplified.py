"""Simplified verb-named API (reference: include/slate/simplified_api.hh:
15-848 — multiply, rank_k_update, triangular_solve, lu_solve, chol_solve,
least_squares_solve, eig_vals, svd_vals, ...).

Thin overload layer over the drivers, dispatching on matrix kind like the
reference's C++ overload set.  Functional: outputs are returned.
"""

from __future__ import annotations

from typing import Optional, Tuple

from .enums import Diag, Norm, Op, Side, Uplo
from .matrix.matrix import (
    BandMatrix,
    HermitianMatrix,
    Matrix,
    SymmetricMatrix,
    TriangularMatrix,
)
from .drivers import band as _band
from .drivers import blas3 as _blas3
from .drivers import chol as _chol
from .drivers import eig as _eig
from .drivers import indefinite as _indef
from .drivers import lu as _lu
from .drivers import mixed as _mixed
from .drivers import qr as _qr
from .drivers import svd as _svd


# ----- level 3 -------------------------------------------------------------


def multiply(alpha, A, B, beta, C, opts=None):
    """C = alpha A B + beta C, dispatched on A/B kind (simplified_api.hh
    multiply overloads for gemm/hemm/symm/gbmm/hbmm)."""
    if isinstance(A, BandMatrix):
        return _band.gbmm(alpha, A, B, beta, C, opts)
    if isinstance(A, HermitianMatrix):
        return _blas3.hemm(Side.Left, alpha, A, B, beta, C, opts)
    if isinstance(B, HermitianMatrix):
        return _blas3.hemm(Side.Right, alpha, B, A, beta, C, opts)
    if isinstance(A, SymmetricMatrix):
        return _blas3.symm(Side.Left, alpha, A, B, beta, C, opts)
    if isinstance(B, SymmetricMatrix):
        return _blas3.symm(Side.Right, alpha, B, A, beta, C, opts)
    return _blas3.gemm(alpha, A, B, beta, C, opts)


def rank_k_update(alpha, A, beta, C, opts=None):
    """C = alpha A A^H/T + beta C (herk/syrk overloads)."""
    if isinstance(C, HermitianMatrix):
        return _blas3.herk(alpha, A, beta, C, opts)
    return _blas3.syrk(alpha, A, beta, C, opts)


def rank_2k_update(alpha, A, B, beta, C, opts=None):
    if isinstance(C, HermitianMatrix):
        return _blas3.her2k(alpha, A, B, beta, C, opts)
    return _blas3.syr2k(alpha, A, B, beta, C, opts)


def triangular_multiply(alpha, A: TriangularMatrix, B, side=Side.Left, opts=None):
    return _blas3.trmm(side, alpha, A, B, opts)


def triangular_solve(alpha, A, B, side=Side.Left, pivots=None, opts=None):
    """trsm / tbsm overloads."""
    from .matrix.matrix import TriangularBandMatrix

    if isinstance(A, TriangularBandMatrix):
        return _band.tbsm(side, alpha, A, B, pivots, opts)
    return _blas3.trsm(side, alpha, A, B, opts)


def band_multiply(alpha, A: BandMatrix, B, beta, C, opts=None):
    return _band.gbmm(alpha, A, B, beta, C, opts)


# ----- LU ------------------------------------------------------------------


def lu_factor(A: Matrix, opts=None):
    return _lu.getrf(A, opts)


def lu_factor_nopiv(A: Matrix, opts=None):
    return _lu.getrf_nopiv(A, opts)


def lu_solve(A, B, opts=None):
    """Solve A X = B (gesv / gbsv overloads)."""
    if isinstance(A, BandMatrix):
        X, *_ = _band.gbsv(A, B, opts)
        return X
    X, *_ = _lu.gesv(A, B, opts)
    return X


def lu_solve_using_factor(LU, pivots, B, opts=None):
    if isinstance(LU, BandMatrix):
        return _band.gbtrs(LU, pivots, B, opts)
    return _lu.getrs(LU, pivots, B, opts)


def lu_solve_using_factor_nopiv(LU, B, opts=None):
    return _lu.getrs_nopiv(LU, B, opts)


def lu_inverse_using_factor(LU, pivots, opts=None):
    return _lu.getri(LU, pivots, opts)


def lu_inverse_using_factor_out_of_place(LU, pivots, opts=None):
    """(reference: getriOOP — out-of-place is the only mode in the
    functional API)"""
    return _lu.getri(LU, pivots, opts)


# ----- Cholesky ------------------------------------------------------------


def chol_factor(A, opts=None):
    from .matrix.matrix import HermitianBandMatrix

    if isinstance(A, HermitianBandMatrix):
        return _band.pbtrf(A, opts)
    return _chol.potrf(A, opts)


def chol_solve(A, B, opts=None):
    from .matrix.matrix import HermitianBandMatrix

    if isinstance(A, HermitianBandMatrix):
        X, *_ = _band.pbsv(A, B, opts)
        return X
    X, *_ = _chol.posv(A, B, opts)
    return X


def chol_solve_using_factor(L, B, opts=None):
    from .matrix.matrix import TriangularBandMatrix

    if isinstance(L, TriangularBandMatrix):
        return _band.pbtrs(L, B, opts)
    return _chol.potrs(L, B, opts)


def chol_inverse_using_factor(L, opts=None):
    return _chol.potri(L, opts)


def solve_mixed(A, B, opts=None):
    """Mixed-precision solve with iterative refinement, dispatched on
    matrix kind (HermitianMatrix -> posv_mixed, else gesv_mixed; the
    verb-API face of the refine/ subsystem).  Returns only X, so it
    demands the success contract itself: with the fallback solver on
    (the default) a non-converging system is re-solved at full
    precision; with it off, non-convergence raises NumericalError —
    never a silently-wrong finite X."""
    from .exceptions import NumericalError

    if isinstance(A, HermitianMatrix):
        X, info, _iters = _mixed.posv_mixed(A, B, opts)
    else:
        X, info, _iters = _mixed.gesv_mixed(A, B, opts)
    if int(info) != 0:
        raise NumericalError(
            f"solve_mixed: refinement did not converge (info={int(info)})",
            int(info),
        )
    return X


# ----- indefinite ----------------------------------------------------------


def indefinite_factor(A: HermitianMatrix, opts=None):
    return _indef.hetrf(A, opts)


def indefinite_solve(A: HermitianMatrix, B, opts=None):
    """Solve with breakdown surfaced: this wrapper returns only X, so
    it demands the success flag itself (the lazy-info contract) —
    eager breakdown raises NumericalError; inside a trace, where no
    host value exists, X is NaN-poisoned when info != 0 so a traced
    caller can never consume a silently-wrong solution."""
    import jax
    import jax.numpy as jnp

    from .exceptions import NumericalError

    X, _L, _d, info = _indef.hesv(A, B, opts)
    if isinstance(info, jax.core.Tracer):
        nan = jnp.asarray(jnp.nan, X.data.dtype)
        return X._with(data=jnp.where(info != 0, nan, X.data))
    if int(info) != 0:
        raise NumericalError(
            f"indefinite_solve: factorization breakdown (info={int(info)})",
            int(info),
        )
    return X


def indefinite_solve_using_factor(L, d, B, opts=None):
    return _indef.hetrs(L, d, B, opts)


# ----- least squares / QR / LQ --------------------------------------------


def least_squares_solve(A: Matrix, B: Matrix, opts=None):
    return _qr.gels(A, B, opts)


def qr_factor(A: Matrix, opts=None):
    return _qr.geqrf(A, opts)


def lq_factor(A: Matrix, opts=None):
    return _qr.gelqf(A, opts)


def multiply_by_q(side, op, fac, T, C, from_lq=False, opts=None):
    """Apply Q from qr_factor / lq_factor (unmqr/unmlq overloads)."""
    if from_lq:
        return _qr.unmlq(side, op, fac, T, C, opts)
    return _qr.unmqr(side, op, fac, T, C, opts)


# ----- eigen / svd ---------------------------------------------------------


def eig(A: HermitianMatrix, opts=None):
    """Eigenvalues + vectors (simplified_api.hh eig)."""
    return _eig.heev(A, opts, vectors=True)


def eig_vals(A: HermitianMatrix, opts=None):
    w, _ = _eig.heev(A, opts, vectors=False)
    return w


def svd(A: Matrix, opts=None):
    return _svd.svd(A, opts, vectors=True)


def svd_vals(A: Matrix, opts=None):
    s, _, _ = _svd.svd(A, opts, vectors=False)
    return s
