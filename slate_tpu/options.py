"""Per-call Options map (reference: types.hh:32-80 OptionValue/Options,
option defaults resolved at use-site, e.g. gemmC.cc:55).

Options are a plain dict {Option|str: value}; `get_option` resolves defaults
exactly like the reference's use-site `get_option( opts, Option::X, default )`.
String keys are accepted for ergonomics ("lookahead" == Option.Lookahead).
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Union

from .enums import Option, RefineMethod, Schedule
from .exceptions import OptionError
from .serve.buckets import DEFAULT_SHARD_THRESHOLD  # import-pure module

OptionKey = Union[Option, str]
Options = Mapping[OptionKey, Any]

_DEFAULTS = {
    Option.ChunkSize: 1,
    # Lookahead follows the reference convention: 1 = the baseline
    # pipeline (one panel in flight — no extra eager panels); k > 1
    # peels k-1 exact-shape panels ahead of the recursion split in the
    # recursive factorization schedules (drivers/chol.py, drivers/lu.py).
    Option.Lookahead: 1,
    Option.BlockSize: 256,
    Option.InnerBlocking: 16,
    Option.MaxPanelThreads: 1,
    Option.Tolerance: None,  # resolved per-dtype at use site (epsilon-based)
    Option.Target: None,  # Target.Devices at use site
    Option.HoldLocalWorkspace: False,
    Option.Depth: 2,
    Option.MaxIterations: 30,
    Option.UseFallbackSolver: True,
    Option.PivotThreshold: 1.0,
    Option.PrintVerbose: 0,
    Option.PrintEdgeItems: 16,
    Option.PrintWidth: 10,
    Option.PrintPrecision: 4,
    Option.MaxUnrolledTiles: 256,
    Option.UseShardMap: True,
    Option.RequireSpmd: False,
    Option.Schedule: Schedule.Auto,
    Option.RefineMethod: RefineMethod.Auto,
    Option.ServeQueueLimit: 128,
    Option.ServeBatchMax: 8,
    Option.ServeBatchWindow: 0.002,
    # decorrelated-jitter base: first retry waits ~this, later ones up
    # to 3x the previous (service.decorrelated_backoff)
    Option.ServeRetryBackoff: 0.01,
    # how long an open bucket breaker waits before a half-open probe
    Option.ServeBreakerCooldown: 5.0,
    Option.ServeValidate: True,
    Option.ServePrecision: "full",  # bucket solve precision (full|mixed)
    Option.ServeArtifacts: "",  # executable artifact dir ("" = env/off)
    # placement (serve/placement.py): 1 replica + no mesh = the
    # single-device service, bit-identical to the pre-placement tier
    Option.ServeReplicas: 1,  # data-parallel replica workers
    Option.ServeMesh: "",  # "PxQ" spmd submesh ("" = sharded routing off)
    # requests with n >= this route to the spmd drivers when a mesh is
    # configured (the Clipper-style split: small -> replicas for
    # throughput, large -> the SLATE process grid for capability);
    # one value with PlacementPolicy's constructor default
    Option.ServeShardThreshold: DEFAULT_SHARD_THRESHOLD,
    # factor cache (serve/factor_cache.py): OFF by default — the
    # repeated-A trsm-only fast path is an opt-in workload declaration
    # (SLATE_TPU_FACTOR_CACHE env overrides; one branch on the hot
    # path when off)
    Option.ServeFactorCache: False,
    Option.ServeFactorCacheEntries: 32,  # LRU entry cap
    Option.ServeFactorCacheBytes: 1 << 30,  # LRU byte budget (1 GiB)
    # device factor arena (fabric/arena.py): "" = off — hot factors
    # stay host numpy and every solve-phase hit re-uploads; armed, the
    # arena keeps them device-resident under a per-lane HBM byte
    # budget (SLATE_TPU_FACTOR_ARENA env overrides; grammar
    # off|1|bytes=<N>)
    Option.ServeFactorArena: "",
    # admission control (serve/admission.py): all three default
    # degenerate — no tenant spec, static batch window, no latency
    # budget — which keeps the service byte-identical to the
    # pre-admission tier (one `is None` branch per submit)
    Option.ServeTenantQuota: "",  # tenant spec ("" = tenancy off)
    Option.ServeAdaptiveWindow: False,  # AIMD window controller off
    Option.ServeLatencyBudget: 0.0,  # service-wide p99 budget (s; 0 = off)
    # silent-data-corruption defense (integrity/): "" = plane off —
    # zero-overhead default, one `is None` branch per delivery
    # (SLATE_TPU_INTEGRITY env overrides; grammar off|sample=<p>|full
    # with optional ,abft for checksummed bucket cores)
    Option.ServeIntegrity: "",
    # stop(drain=True) completes already-admitted requests for at most
    # this many seconds before abandoning the rest (rolling restarts)
    Option.ServeDrainTimeout: 30.0,
    # elastic capacity plane (scale/): "" = plane off — zero-overhead
    # default, the service never constructs a scaler (SLATE_TPU_SCALE
    # env overrides; grammar on|min=<n>,max=<n>,up=<p>,down=<p>,...)
    Option.ServeScale: "",
    Option.Faults: "",  # empty = no injection (aux/faults spec grammar)
}


def _canon(key: OptionKey) -> Option:
    if isinstance(key, Option):
        return key
    k = str(key).strip().lower()
    for opt in Option:
        if opt.value == k or opt.name.lower() == k:
            return opt
    raise OptionError(f"unknown option key: {key!r}")


def normalize_options(opts: Optional[Options]) -> dict:
    """Canonicalize user-provided option keys to Option enum members."""
    out: dict = {}
    for key, val in (opts or {}).items():
        out[_canon(key)] = val
    return out


def resolve_schedule_opts(opts: Optional[Options]):
    """(schedule, nb_switch, lookahead) for the factorization drivers:
    the Option.Schedule route (flat|recursive|auto), the recursion
    crossover (Option.BlockSize), and the eager-panel peel count
    (Option.Lookahead — reference semantics: 1 = baseline pipeline,
    k > 1 peels k-1 exact-shape panels ahead of the recursion split)."""
    sched = get_option(opts, Option.Schedule, Schedule.Auto)
    if isinstance(sched, str):
        sched = Schedule.from_string(sched)
    nb_switch = int(get_option(opts, Option.BlockSize, 256))
    lookahead = int(get_option(opts, Option.Lookahead, 1))
    return sched.value, nb_switch, lookahead


def get_option(opts: Optional[Options], key: OptionKey, default: Any = None) -> Any:
    """Use-site default resolution (reference pattern: get_option(opts, k, d)).

    Unknown keys in ``opts`` are ignored here; use ``normalize_options`` at
    driver entry to reject typos loudly.
    """
    key = _canon(key)
    if opts:
        if key in opts:
            return opts[key]
        for k, v in opts.items():
            try:
                kc = _canon(k)
            except OptionError:
                continue
            if kc is key:
                return v
    if default is not None:
        return default
    return _DEFAULTS.get(key)
