"""Distributed matrix classes.

TPU-native re-design of the reference's BaseMatrix hierarchy (reference:
include/slate/BaseMatrix.hh:40, Matrix.hh, *Matrix.hh headers).  Differences
by design:

* **Functional, not mutating**: routines return new matrices; there is no
  MOSI coherence, tile insert/erase, or hold machinery (BaseMatrix.hh
  tileGet*/tileAcquire) because XLA owns placement and staging on TPU.
* **One array, not a tile map**: storage is a single jax array
  (P, Q, mb, nb) in owner-major block-cyclic order (see parallel/layout.py)
  instead of std::map<(i,j) -> TileNode> (MatrixStorage.hh:151).
* **Transpose is a flag** resolved lazily, like the reference's op flag
  (BaseMatrix.hh:770-781): `transpose(A)` is O(1); internals materialize.

Matrices are registered pytrees, so they pass through jit/scan/shard_map.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..enums import Diag, Op, Uplo
from ..exceptions import DimensionError, slate_assert
from ..parallel.grid import ProcessGrid
from ..parallel.layout import (
    TileLayout,
    tiles_from_global,
    tiles_to_global,
)


class BaseMatrix:
    """Shared behavior for all matrix kinds.

    Attributes:
        data:   (P, Q, mb, nb) storage-order tile array (may be sharded).
        layout: static TileLayout index math.
        grid:   ProcessGrid or None (single-device semantics).
        op:     Op flag of this view (NoTrans/Trans/ConjTrans).
    """

    uplo: Uplo = Uplo.General
    diag: Diag = Diag.NonUnit

    def __init__(
        self,
        data: jnp.ndarray,
        layout: TileLayout,
        grid: Optional[ProcessGrid] = None,
        op: Op = Op.NoTrans,
    ):
        slate_assert(
            tuple(data.shape) == layout.storage_shape,
            f"data shape {data.shape} != layout {layout.storage_shape}",
        )
        self.data = data
        self.layout = layout
        self.grid = grid
        self.op = op

    # -- pytree protocol ----------------------------------------------------

    def tree_flatten(self):
        aux = (
            self.layout,
            self.grid,
            self.op,
            type(self),
            getattr(self, "uplo", Uplo.General),
            getattr(self, "diag", Diag.NonUnit),
        )
        return (self.data,), aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        layout, grid, op, klass, uplo, diag = aux
        obj = object.__new__(klass)
        obj.data = children[0]
        obj.layout = layout
        obj.grid = grid
        obj.op = op
        obj.uplo = uplo
        obj.diag = diag
        return obj

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        jax.tree_util.register_pytree_node_class(cls)

    # -- basic queries (reference: BaseMatrix.hh:211-223, mt/nt/m/n) --------

    @property
    def m(self) -> int:
        return self.layout.n if self.op != Op.NoTrans else self.layout.m

    @property
    def n(self) -> int:
        return self.layout.m if self.op != Op.NoTrans else self.layout.n

    @property
    def mt(self) -> int:
        return self.layout.nt if self.op != Op.NoTrans else self.layout.mt

    @property
    def nt(self) -> int:
        return self.layout.mt if self.op != Op.NoTrans else self.layout.nt

    @property
    def mb(self) -> int:
        return self.layout.nb if self.op != Op.NoTrans else self.layout.mb

    @property
    def nb(self) -> int:
        return self.layout.mb if self.op != Op.NoTrans else self.layout.nb

    @property
    def dtype(self):
        return self.data.dtype

    def tileMb(self, i: int) -> int:
        return self.layout.tileNb(i) if self.op != Op.NoTrans else self.layout.tileMb(i)

    def tileNb(self, j: int) -> int:
        return self.layout.tileMb(j) if self.op != Op.NoTrans else self.layout.tileNb(j)

    def tileRank(self, i: int, j: int) -> Tuple[int, int]:
        if self.op != Op.NoTrans:
            r, c = self.layout.tileRank(j, i)
            return (c, r)
        return self.layout.tileRank(i, j)

    @property
    def is_complex(self) -> bool:
        return jnp.issubdtype(self.dtype, jnp.complexfloating)

    # -- op handling (reference: BaseMatrix.hh transpose/conj_transpose) ----

    def _with(self, **kw) -> "BaseMatrix":
        """Copy with overridden fields; preserves every subclass attribute
        (uplo/diag/kl/ku/kd/...)."""
        out = object.__new__(type(self))
        out.__dict__.update(self.__dict__)
        for k, v in kw.items():
            setattr(out, k, v)
        return out

    def resolved(self) -> "BaseMatrix":
        """Materialize the op flag into the data (internals see NoTrans).

        Transposing swaps the storage grid roles (p <-> q), implemented as
        one XLA transpose of the tile array plus the static permutations
        natural <-> storage on both axes.
        """
        if self.op == Op.NoTrans:
            return self
        lay = self.layout
        # storage -> natural on both axes, swap, natural -> storage of A^T
        T = self.data[lay.row_scatter][:, lay.col_scatter]
        T = T.transpose(1, 0, 3, 2)
        if self.op == Op.ConjTrans and jnp.issubdtype(T.dtype, jnp.complexfloating):
            T = jnp.conj(T)
        lay_t = lay.transposed()
        T = T[lay_t.row_gather][:, lay_t.col_gather]
        out = self._with(data=T, layout=lay_t, op=Op.NoTrans)
        if getattr(self, "uplo", Uplo.General) == Uplo.Lower:
            out.uplo = Uplo.Upper
        elif getattr(self, "uplo", Uplo.General) == Uplo.Upper:
            out.uplo = Uplo.Lower
        return out

    # -- conversions --------------------------------------------------------

    def to_global(self) -> jnp.ndarray:
        """Gather to the (m, n) global array, honoring the op flag."""
        A = tiles_to_global(self.data, self.layout)
        if self.op == Op.Trans:
            A = A.T
        elif self.op == Op.ConjTrans:
            A = jnp.conj(A).T
        return A

    def to_padded_global(self) -> jnp.ndarray:
        """(P*mb, Q*nb) padded global array of the un-op'd storage.

        The workhorse of the single-chip "global path": one reshape away
        from the tile array, so XLA sees full-size MXU-friendly operands.
        """
        lay = self.layout
        Tn = (
            self.data
            if lay.trivial_perm
            else self.data[lay.row_scatter][:, lay.col_scatter]
        )
        return Tn.transpose(0, 2, 1, 3).reshape(lay.P * lay.mb, lay.Q * lay.nb)

    @classmethod
    def _pack_padded_global(cls, A_pad, layout, grid=None, **kw):
        T = A_pad.reshape(layout.P, layout.mb, layout.Q, layout.nb)
        T = T.transpose(0, 2, 1, 3)
        if not layout.trivial_perm:
            T = T[layout.row_gather][:, layout.col_gather]
        return cls(T, layout, grid=grid, **kw)

    def shard(self) -> "BaseMatrix":
        """Place the tile array on the grid's mesh with cyclic sharding."""
        if self.grid is None or self.grid.size == 1:
            return self
        return self._with(data=jax.device_put(self.data, self.grid.tile_sharding()))

    # -- slicing ------------------------------------------------------------

    def sub(self, i1: int, i2: int, j1: int, j2: int) -> "BaseMatrix":
        """Materialized sub-matrix of tile rows [i1, i2] x cols [j1, j2]
        (inclusive, like the reference BaseMatrix::sub, BaseMatrix.hh:770).

        Unlike the reference this copies (functional design); the returned
        matrix is laid out on the same grid.
        """
        slate_assert(self.op == Op.NoTrans, "sub() requires resolved() view")
        lay = self.layout
        slate_assert(0 <= i1 <= i2 < lay.mt and 0 <= j1 <= j2 < lay.nt, "sub range")
        rows = lay.row_scatter[np.arange(i1, i2 + 1)]
        cols = lay.col_scatter[np.arange(j1, j2 + 1)]
        Tn = self.data[rows][:, cols]  # natural-order tile block
        m = min(self.m - i1 * lay.mb, (i2 - i1 + 1) * lay.mb)
        n = min(self.n - j1 * lay.nb, (j2 - j1 + 1) * lay.nb)
        sub_lay = TileLayout(m, n, lay.mb, lay.nb, lay.p, lay.q)
        pad_r = sub_lay.P - Tn.shape[0]
        pad_c = sub_lay.Q - Tn.shape[1]
        Tn = jnp.pad(Tn, ((0, pad_r), (0, pad_c), (0, 0), (0, 0)))
        Ts = Tn[sub_lay.row_gather][:, sub_lay.col_gather]
        return self._with(data=Ts, layout=sub_lay)

    def __repr__(self):
        return (
            f"{type(self).__name__}({self.m}x{self.n}, tiles {self.mb}x{self.nb}, "
            f"grid {self.layout.p}x{self.layout.q}, op={self.op.name}, "
            f"dtype={self.dtype})"
        )


jax.tree_util.register_pytree_node_class(BaseMatrix)


def is_distributed(M: BaseMatrix) -> bool:
    """True when M lives on a multi-process grid (the spmd-dispatch
    predicate shared by every driver)."""
    return M.grid is not None and M.grid.size > 1


def transpose(A: BaseMatrix) -> BaseMatrix:
    """O(1) transposed view (reference: slate::transpose, BaseMatrix.hh)."""
    new_op = {Op.NoTrans: Op.Trans, Op.Trans: Op.NoTrans, Op.ConjTrans: Op.NoTrans}[A.op]
    if A.op == Op.ConjTrans and A.is_complex:
        # transpose(conj_transpose(A)) = conj(A): materialize the conj
        out = A._with(data=jnp.conj(A.data), op=Op.NoTrans)
        return out
    return A._with(op=new_op)


def conj_transpose(A: BaseMatrix) -> BaseMatrix:
    new_op = {Op.NoTrans: Op.ConjTrans, Op.ConjTrans: Op.NoTrans, Op.Trans: Op.NoTrans}[A.op]
    if A.op == Op.Trans and A.is_complex:
        out = A._with(data=jnp.conj(A.data), op=Op.NoTrans)
        return out
    return A._with(op=new_op)
