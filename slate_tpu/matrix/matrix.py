"""Concrete matrix classes (reference: include/slate/Matrix.hh,
TrapezoidMatrix.hh, TriangularMatrix.hh, SymmetricMatrix.hh,
HermitianMatrix.hh, BandMatrix.hh, TriangularBandMatrix.hh,
HermitianBandMatrix.hh).

All kinds share the full (P, Q, mb, nb) tile-grid storage; triangular /
symmetric / Hermitian kinds logically reference one triangle and carry
masks for it.  The reference instead stores only the referenced triangle's
tiles (BaseTrapezoidMatrix.hh); on TPU uniform dense storage wins — static
shapes, no per-tile map, and XLA DCEs whatever a routine doesn't touch.
Band kinds add (kl, ku) bandwidth metadata; out-of-band tiles are
zero and masked, matching BandMatrix.hh semantics.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..enums import Diag, Op, Uplo
from ..exceptions import slate_assert
from ..parallel.grid import ProcessGrid, default_grid
from ..parallel.layout import TileLayout, tiles_from_global
from .base import BaseMatrix, conj_transpose, transpose  # noqa: F401 (re-export)


def _make_layout(m, n, mb, nb, grid: Optional[ProcessGrid]) -> TileLayout:
    if grid is None:
        return TileLayout(m, n, mb, nb, 1, 1)
    return TileLayout(m, n, mb, nb, grid.p, grid.q)


class Matrix(BaseMatrix):
    """General m x n matrix (reference: Matrix.hh)."""

    @staticmethod
    def from_global(
        A, mb: int, nb: Optional[int] = None, grid: Optional[ProcessGrid] = None
    ) -> "Matrix":
        """Build from a host/device (m, n) array — the TPU-native analogue
        of Matrix::fromLAPACK (Matrix.hh:58): tile + distribute."""
        nb = nb if nb is not None else mb
        A = jnp.asarray(A)
        m, n = A.shape
        layout = _make_layout(m, n, mb, nb, grid)
        T = tiles_from_global(A, layout)
        return Matrix(T, layout, grid=grid).shard()

    @staticmethod
    def zeros(
        m: int,
        n: int,
        mb: int,
        nb: Optional[int] = None,
        dtype=jnp.float32,
        grid: Optional[ProcessGrid] = None,
    ) -> "Matrix":
        nb = nb if nb is not None else mb
        layout = _make_layout(m, n, mb, nb, grid)
        return Matrix(jnp.zeros(layout.storage_shape, dtype), layout, grid=grid).shard()

    def emptyLike(self, dtype=None) -> "Matrix":
        dt = dtype or self.dtype
        return Matrix(jnp.zeros_like(self.data, dtype=dt), self.layout, grid=self.grid)


class BaseTrapezoidMatrix(BaseMatrix):
    """Upper/lower trapezoid storage semantics (reference:
    BaseTrapezoidMatrix.hh)."""

    def __init__(self, data, layout, grid=None, op=Op.NoTrans,
                 uplo=Uplo.Lower, diag=Diag.NonUnit):
        super().__init__(data, layout, grid=grid, op=op)
        self.uplo = uplo
        self.diag = diag

    @classmethod
    def from_global(cls, A, mb, nb=None, grid=None, uplo=Uplo.Lower,
                    diag=Diag.NonUnit):
        nb = nb if nb is not None else mb
        A = jnp.asarray(A)
        m, n = A.shape
        layout = _make_layout(m, n, mb, nb, grid)
        T = tiles_from_global(A, layout)
        return cls(T, layout, grid=grid, uplo=uplo, diag=diag).shard()

    def tri_mask(self) -> jnp.ndarray:
        """(P, Q, mb, nb) bool mask of the referenced triangle's elements
        (valid region only), honoring Diag.Unit exclusion of the diagonal."""
        lay = self.layout
        gr = jnp.asarray(lay.global_rows_np)[:, None, :, None]
        gc = jnp.asarray(lay.global_cols_np)[None, :, None, :]
        if self.uplo == Uplo.Lower:
            mask = gr >= gc if self.diag == Diag.NonUnit else gr > gc
        elif self.uplo == Uplo.Upper:
            mask = gr <= gc if self.diag == Diag.NonUnit else gr < gc
        else:
            mask = jnp.ones_like(gr, dtype=bool) != False  # noqa: E712
        return mask & lay.element_mask()


class TrapezoidMatrix(BaseTrapezoidMatrix):
    """m x n trapezoid (reference: TrapezoidMatrix.hh)."""


class TriangularMatrix(BaseTrapezoidMatrix):
    """Square triangular (reference: TriangularMatrix.hh)."""

    @classmethod
    def from_global(cls, A, mb, nb=None, grid=None, uplo=Uplo.Lower,
                    diag=Diag.NonUnit):
        A = jnp.asarray(A)
        slate_assert(A.shape[0] == A.shape[1], "TriangularMatrix must be square")
        return super().from_global(A, mb, nb, grid, uplo, diag)


class SymmetricMatrix(BaseTrapezoidMatrix):
    """Symmetric, one triangle referenced (reference: SymmetricMatrix.hh)."""

    def __init__(self, data, layout, grid=None, op=Op.NoTrans,
                 uplo=Uplo.Lower, diag=Diag.NonUnit):
        super().__init__(data, layout, grid=grid, op=op, uplo=uplo, diag=Diag.NonUnit)

    def full_global(self) -> jnp.ndarray:
        """Materialize the full symmetric matrix from the stored triangle."""
        A = self.to_global()
        lay = self.layout
        i = np.arange(lay.m)[:, None]
        j = np.arange(lay.n)[None, :]
        keep = (i >= j) if self.uplo == Uplo.Lower else (i <= j)
        Ak = jnp.where(jnp.asarray(keep), A, 0)
        diag_part = jnp.diag(jnp.diag(Ak))
        return Ak + Ak.T - diag_part


class HermitianMatrix(SymmetricMatrix):
    """Hermitian, one triangle referenced (reference: HermitianMatrix.hh)."""

    def full_global(self) -> jnp.ndarray:
        A = self.to_global()
        lay = self.layout
        i = np.arange(lay.m)[:, None]
        j = np.arange(lay.n)[None, :]
        keep = (i >= j) if self.uplo == Uplo.Lower else (i <= j)
        Ak = jnp.where(jnp.asarray(keep), A, 0)
        diag_part = jnp.diag(jnp.real(jnp.diag(Ak)).astype(A.dtype))
        return Ak + jnp.conj(Ak).T - diag_part


# ---------------------------------------------------------------------------
# Band kinds (reference: BandMatrix.hh, TriangularBandMatrix.hh,
# HermitianBandMatrix.hh).  Dense tile storage + bandwidth metadata; tiles
# wholly outside the band are zero.  he2hbGather/ge2tbGather analogues live
# in drivers/eig.py and drivers/svd.py.
# ---------------------------------------------------------------------------


class BandMatrix(Matrix):
    """General band matrix with lower/upper bandwidth (kl, ku)."""

    def __init__(self, data, layout, grid=None, op=Op.NoTrans, kl=0, ku=0):
        super().__init__(data, layout, grid=grid, op=op)
        self.kl = kl
        self.ku = ku

    def tree_flatten(self):
        children, aux = super().tree_flatten()
        return children, aux + (self.kl, self.ku)

    @classmethod
    def tree_unflatten(cls, aux, children):
        obj = super().tree_unflatten(aux[:-2], children)
        obj.kl, obj.ku = aux[-2], aux[-1]
        return obj

    @staticmethod
    def from_global(A, kl, ku, mb, nb=None, grid=None):
        nb = nb if nb is not None else mb
        A = jnp.asarray(A)
        m, n = A.shape
        i = np.arange(m)[:, None]
        j = np.arange(n)[None, :]
        band = (j - i <= ku) & (i - j <= kl)
        A = jnp.where(jnp.asarray(band), A, 0)
        layout = _make_layout(m, n, mb, nb, grid)
        T = tiles_from_global(A, layout)
        return BandMatrix(T, layout, grid=grid, kl=kl, ku=ku).shard()

    def band_mask(self) -> jnp.ndarray:
        lay = self.layout
        gr = jnp.asarray(lay.global_rows_np)[:, None, :, None]
        gc = jnp.asarray(lay.global_cols_np)[None, :, None, :]
        band = ((gc - gr) <= self.ku) & ((gr - gc) <= self.kl)
        return band & lay.element_mask()


class TriangularBandMatrix(BandMatrix):
    """Triangular band (reference: TriangularBandMatrix.hh)."""

    def __init__(self, data, layout, grid=None, op=Op.NoTrans, kd=0,
                 uplo=Uplo.Lower, diag=Diag.NonUnit):
        kl, ku = (kd, 0) if uplo == Uplo.Lower else (0, kd)
        super().__init__(data, layout, grid=grid, op=op, kl=kl, ku=ku)
        self.uplo = uplo
        self.diag = diag
        self.kd = kd

    def tree_flatten(self):
        children, aux = super().tree_flatten()
        return children, aux + (self.kd,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        obj = BaseMatrix.tree_unflatten.__func__(cls, aux[:-3], children)
        obj.kl, obj.ku, obj.kd = aux[-3], aux[-2], aux[-1]
        return obj


class HermitianBandMatrix(TriangularBandMatrix):
    """Hermitian band, one triangle stored (reference: HermitianBandMatrix.hh)."""

    def full_global(self) -> jnp.ndarray:
        """Materialize the full Hermitian band from the stored triangle
        (entries outside the referenced triangle are not read — the spmd
        he2hb pipeline leaves them stale)."""
        A = self.to_global()
        if self.uplo == Uplo.Lower:
            kept = jnp.tril(A)
            strict = jnp.tril(A, -1)
        else:
            kept = jnp.triu(A)
            strict = jnp.triu(A, 1)
        mirror = jnp.conj(strict).T if self.is_complex else strict.T
        return kept + mirror
