"""Philox-2x64 counter-based RNG keyed by global element index (i, j).

Reproduces the reference generator's semantics exactly (reference:
matgen/random.cc:43-100 philox_2x64, rand_to_real, generate_float): the
value of element (i, j) depends only on (seed, i, j), never on tiling or
process count, which is what makes rank-count-independent verification
possible (SURVEY §4).

Implemented twice:
  * numpy (vectorized uint64) — host-side generation for compat buffers;
  * jax (uint32-pair arithmetic) — device-side generation inside jit,
    usable under shard_map so every process generates only its local tiles.

Bit-exactness between the two paths holds for the uniform/binary
families (pure integer pipeline + one exact float scale).  The
transcendental distributions (normal, unit_circle, unit_disk) agree only
to a few ULPs (libm vs XLA transcendentals), and accelerator backends may
round the final f64 scale differently (~1e-16 relative); verification
comparisons for those families must be tolerance-based, not bitwise.

The jax path avoids uint64 entirely (TPUs have no native 64-bit integer
units) by carrying each 64-bit lane as a (hi32, lo32) pair.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# Constants from Salmon et al. 2011 (reference: random.cc:55-58).
SEED_INC = 0xD2B74407B1CE6E93
MULTIPLIER = 0x9E3779B97F4A7C15
ROUNDS = 10
_MASK32 = 0xFFFFFFFF

# ---------------------------------------------------------------------------
# numpy path (uint64)
# ---------------------------------------------------------------------------


def _mul64_np(a: np.ndarray, b: int):
    """Exact 64x64 -> 128 product as (lo, hi), overflow-free in uint64."""
    b = np.uint64(b)
    mask = np.uint64(_MASK32)
    s32 = np.uint64(32)
    ah, al = a >> s32, a & mask
    bh, bl = b >> s32, b & mask
    albl = al * bl
    mid = ah * bl + (albl >> s32)
    mid2 = al * bh + (mid & mask)
    hi = ah * bh + (mid >> s32) + (mid2 >> s32)
    lo = a * b  # wrapping
    return lo, hi


def philox_2x64_np(i: np.ndarray, j: np.ndarray, seed: int):
    """128 pseudorandom bits per counter {i, j} (reference: random.cc:43-77)."""
    with np.errstate(over="ignore"):
        L = np.asarray(i, dtype=np.uint64)
        R = np.asarray(j, dtype=np.uint64)
        L, R = np.broadcast_arrays(L, R)
        key = np.uint64(seed)
        inc = np.uint64(SEED_INC)
        for r in range(ROUNDS):
            if r != 0:
                key = key + inc
            lo, hi = _mul64_np(R, MULTIPLIER)
            L, R = lo, hi ^ key ^ L
    return L, R


def _bits_to_unit_np(bits: np.ndarray, dtype) -> np.ndarray:
    """bits -> [0, 1) keeping the top `digits` bits (reference: random.cc:82-90)."""
    digits = np.finfo(dtype).nmant + 1
    shifted = (bits >> np.uint64(64 - digits)).astype(np.float64)
    return (shifted / float(1 << digits)).astype(dtype)


# ---------------------------------------------------------------------------
# jax path: 64-bit lanes as (hi, lo) uint32 pairs
# ---------------------------------------------------------------------------


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def _mul32_wide(a, b):
    """32x32 -> 64 product of uint32 arrays as (hi, lo) uint32."""
    a_hi, a_lo = a >> 16, a & 0xFFFF
    b_hi, b_lo = b >> 16, b & 0xFFFF
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    mid = (ll >> 16) + (lh & 0xFFFF) + (hl & 0xFFFF)
    lo = (ll & 0xFFFF) | ((mid & 0xFFFF) << 16)
    hi = hh + (lh >> 16) + (hl >> 16) + (mid >> 16)
    return hi, lo


def _add64(a, b):
    """(hi,lo) + (hi,lo) with carry, mod 2^64."""
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(jnp.uint32)
    hi = a[0] + b[0] + carry
    return hi, lo


def _mul64_pair(a, b_const):
    """64x64 -> 128 as (hi,lo) pairs; a is a (hi,lo) pair, b a python int."""
    bh = _u32((b_const >> 32) & _MASK32)
    bl = _u32(b_const & _MASK32)
    ah, al = a
    # partial products
    p0h, p0l = _mul32_wide(al, bl)  # al*bl -> bits [0,64)
    p1h, p1l = _mul32_wide(al, bh)  # al*bh -> bits [32,96)
    p2h, p2l = _mul32_wide(ah, bl)  # ah*bl -> bits [32,96)
    p3h, p3l = _mul32_wide(ah, bh)  # ah*bh -> bits [64,128)
    # low 64: p0 + (p1l + p2l) << 32
    lo_hi, lo_lo = _add64((p0h, p0l), (p1l, jnp.zeros_like(p0l)))
    lo_hi2, lo_lo2 = _add64((lo_hi, lo_lo), (p2l, jnp.zeros_like(p0l)))
    # carries into high 64 from the two (x << 32) adds
    c1 = (lo_hi < p0h).astype(jnp.uint32)
    c2 = (lo_hi2 < lo_hi).astype(jnp.uint32)
    hi_hi, hi_lo = _add64((p3h, p3l), (jnp.zeros_like(p0l), p1h))
    hi_hi, hi_lo = _add64((hi_hi, hi_lo), (jnp.zeros_like(p0l), p2h))
    hi_hi, hi_lo = _add64((hi_hi, hi_lo), (jnp.zeros_like(p0l), c1 + c2))
    return (hi_hi, hi_lo), (lo_hi2, lo_lo2)


def _split64(x):
    """int array -> (hi32, lo32) uint32 pair; works with x64 on or off."""
    x = jnp.asarray(x)
    lo = x.astype(jnp.uint32)  # wrapping cast, no 0xFFFFFFFF literal needed
    if x.dtype.itemsize == 8:
        hi = (x >> 32).astype(jnp.uint32)
    else:
        hi = jnp.zeros(x.shape, jnp.uint32)
    return hi, lo


def philox_2x64_jnp(i, j, seed: int):
    """jax version of philox_2x64; i, j int arrays (< 2^63 as pairs).

    Returns ((L_hi, L_lo), (R_hi, R_lo)) uint32 pairs.
    """
    i, j = jnp.broadcast_arrays(jnp.asarray(i), jnp.asarray(j))
    L = _split64(i)
    R = _split64(j)
    key = (seed >> 32) & _MASK32, seed & _MASK32
    for r in range(ROUNDS):
        if r != 0:
            # key += SEED_INC (python-side 64-bit constant fold per round)
            k64 = (((key[0] << 32) | key[1]) + SEED_INC) & 0xFFFFFFFFFFFFFFFF
            key = (k64 >> 32, k64 & _MASK32)
        hi128, lo128 = _mul64_pair(R, MULTIPLIER)
        new_R = (hi128[0] ^ _u32(key[0]) ^ L[0], hi128[1] ^ _u32(key[1]) ^ L[1])
        L, R = lo128, new_R
    return L, R


def _bits_to_unit_jnp(bits_pair, dtype) -> jnp.ndarray:
    """(hi, lo) uint32 pair -> [0, 1) float of `dtype`, bit-matching numpy."""
    hi, lo = bits_pair
    digits = jnp.finfo(dtype).nmant + 1
    if digits <= 32:
        kept = hi >> (32 - digits)
        return (kept.astype(jnp.float32) / np.float32(1 << digits)).astype(dtype)
    # float64 path: 53 kept bits = hi (32) + top 21 of lo
    kept_hi = hi.astype(jnp.float64) * float(1 << 21)
    kept_lo = (lo >> (64 - digits)).astype(jnp.float64)
    return ((kept_hi + kept_lo) / float(1 << digits)).astype(dtype)


# ---------------------------------------------------------------------------
# Distribution sampling (reference: random.cc:110-160 generate_float)
# ---------------------------------------------------------------------------

DISTS = (
    "uniform",         # [0, 1)
    "uniform_signed",  # (-1, 1)
    "normal",          # Box-Muller
    "unit_disk",
    "unit_circle",
    "binary",
    "binary_signed",
)


def _apply_dist(f1, f2, dist: str, dtype, xp):
    two_pi = xp.asarray(2 * np.pi, dtype=dtype)
    two = xp.asarray(2, dtype=dtype)
    one_c = xp.asarray(1, dtype=dtype)
    if dist == "uniform":
        re, im = f1, f2
    elif dist == "uniform_signed":
        re, im = two * f1 - one_c, two * f2 - one_c
    elif dist == "normal":
        mag = xp.sqrt(-two * xp.log1p(-f1))
        arg = two_pi * f2
        re, im = mag * xp.cos(arg), mag * xp.sin(arg)
    elif dist == "unit_disk":
        mag = xp.sqrt(f1)
        arg = two_pi * f2
        re, im = mag * xp.cos(arg), mag * xp.sin(arg)
    elif dist == "unit_circle":
        arg = two_pi * f2
        re, im = xp.cos(arg), xp.sin(arg)
    elif dist == "binary":
        one = xp.ones_like(f1)
        re, im = xp.where(f1 >= 0.5, one, 0 * one), xp.where(f2 >= 0.5, one, 0 * one)
    elif dist == "binary_signed":
        one = xp.ones_like(f1)
        re, im = xp.where(f1 >= 0.5, one, -one), xp.where(f2 >= 0.5, one, -one)
    else:
        raise ValueError(f"unknown distribution {dist!r}")
    return re, im


def random_np(dist: str, seed: int, i, j, dtype=np.float64) -> np.ndarray:
    """Element values at global indices (i, j); real or complex dtype.

    Matches reference generate_float<scalar_t, dist>(seed, i, j)
    (random.cc:104-160): one philox call per element; float1 -> re,
    float2 -> im (imaginary discarded for real types).
    """
    dtype = np.dtype(dtype)
    if dtype.kind == "c":
        real_t = np.float32 if dtype == np.complex64 else np.float64
    else:
        real_t = dtype.type
    bits1, bits2 = philox_2x64_np(i, j, seed)
    f1 = _bits_to_unit_np(bits1, real_t)
    f2 = _bits_to_unit_np(bits2, real_t)
    re, im = _apply_dist(f1, f2, dist, real_t, np)
    if dtype.kind == "c":
        return (re + 1j * im).astype(dtype)
    return re.astype(dtype)


def random_jnp(dist: str, seed: int, i, j, dtype=jnp.float32) -> jnp.ndarray:
    """jax twin of random_np; bit-identical for f32/f64 (complex composed)."""
    dtype = jnp.dtype(dtype)
    if dtype.kind == "c":
        real_t = jnp.float32 if dtype == jnp.complex64 else jnp.float64
    else:
        real_t = dtype
    bits1, bits2 = philox_2x64_jnp(i, j, seed)
    f1 = _bits_to_unit_jnp(bits1, real_t)
    f2 = _bits_to_unit_jnp(bits2, real_t)
    re, im = _apply_dist(f1, f2, dist, real_t, jnp)
    if dtype.kind == "c":
        return (re + 1j * im).astype(dtype)
    return re.astype(dtype)
