"""slate_tpu.matgen — deterministic test-matrix generation (reference:
matgen/; Philox counter RNG keyed by global (i, j), so every kind is
bit-reproducible for a given seed regardless of tiling or process
count).  See :mod:`.generate` for the kind grammar and
:func:`.generate.cond_matrix` for the specified-condition-number
construction the mixed-precision tests are built on."""

from .generate import (  # noqa: F401
    cond_matrix,
    generate,
    generate_2d,
    generate_matrix,
    generate_tiles,
    parse_kind,
)

__all__ = [
    "cond_matrix",
    "generate",
    "generate_2d",
    "generate_matrix",
    "generate_tiles",
    "parse_kind",
]
