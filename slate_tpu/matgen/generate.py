"""Deterministic test-matrix generator (reference: matgen/ — ~30 named
kinds with condition-controlled spectra; kind grammar parsed in
generate_matrix_utils.cc:211-360; special-matrix formulas
generate_matrix_ge.cc:80-465; sigma distributions generate_sigma.hh:39-130;
svd/heev constructions generate_type_svd.hh / generate_type_heev.hh).

Kind grammar (identical to the reference):

    base[_dist][_scale][_modifier...]   tokens split on '_' or '-'

      base:     zeros ones identity ij jordan jordanT chebspec circul
                fiedler gfpp kms orthog riemann ris zielkeNS diag svd poev
                heev geev geevx minij hilb frank lehmer lotkin redheff triw
                tridiag toeppen pei parter moler cauchy chow clement gcdmat
                rand rands randn randb randr
      dist:     rand rands randn logrand arith geo cluster0 cluster1
                rarith rgeo rcluster0 rcluster1 specified
                (only for diag/svd/poev/heev/geev/geevx; default logrand)
      scale:    small large ufl ofl
      modifier: dominant, zerocol<N|fraction>

All element values come from the Philox (i, j)-keyed RNG, so any kind is
bit-reproducible for a given seed regardless of tiling or process count.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..exceptions import SlateError
from ..matrix.base import BaseMatrix
from ..matrix.matrix import Matrix
from ..parallel.layout import tiles_from_global
from . import philox

_RAND_KINDS = {
    "rand": "uniform",
    "rands": "uniform_signed",
    "randn": "normal",
    "randb": "binary",
    "randr": "binary_signed",
}

_DISTS = (
    "rand", "rands", "randn", "logrand", "arith", "geo", "cluster0",
    "cluster1", "rarith", "rgeo", "rcluster0", "rcluster1", "specified",
)

_SPECTRUM_KINDS = ("diag", "svd", "poev", "heev", "geev", "geevx")


def _ij_grids(m, n):
    i = jnp.arange(m, dtype=jnp.float64)[:, None]
    j = jnp.arange(n, dtype=jnp.float64)[None, :]
    return jnp.broadcast_arrays(i + 0 * j, 0 * i + j)


def _special_entry(base: str, m: int, n: int, dtype) -> jnp.ndarray:
    """Elementwise special matrices (generate_matrix_ge.cc:80-465)."""
    i, j = _ij_grids(m, n)
    mx = max(m, n)
    pi = np.pi
    one = 1.0
    if base == "zeros":
        G = jnp.zeros((m, n))
    elif base == "ones":
        G = jnp.ones((m, n))
    elif base == "identity":
        G = jnp.where(i == j, 1.0, 0.0)
    elif base == "ij":
        s = 1.0 / 10 ** math.ceil(math.log10(max(n, 2)))
        G = i + j * s
    elif base == "jordan":
        G = jnp.where((i == j) | (i + 1 == j), 1.0, 0.0)
    elif base == "jordanT":
        G = jnp.where((i == j) | (i == j + 1), 1.0, 0.0)
    elif base == "chebspec":
        x_i = jnp.cos(pi * (i + 1) / mx)
        x_j = jnp.cos(pi * (j + 1) / mx)
        c_i = jnp.where(i == mx - 1, 2.0, 1.0)
        c_j = jnp.where(j == mx - 1, 2.0, 1.0)
        sgn = jnp.where((i + j) % 2 == 0, 1.0, -1.0)
        off = sgn * c_i / (c_j * (x_j - x_i + jnp.where(i == j, 1.0, 0.0)))
        last = (2.0 * mx * mx + 1) / -6.0
        diag = jnp.where(j + 1 == mx, last, -0.5 * x_i / (one - x_i * x_i))
        G = jnp.where(i == j, diag, off)
    elif base == "circul":
        diff = j - i
        G = diff + jnp.where(diff < 0, float(mx), 0.0) + 1
    elif base == "fiedler":
        G = jnp.abs(j - i)
    elif base == "gfpp":
        G = jnp.where(
            j == n - 1, 1.0, jnp.where(i > j, -1.0, jnp.where(i == j, 0.5, 0.0))
        )
    elif base == "kms":
        G = 0.5 ** jnp.abs(j - i)
    elif base == "orthog":
        G = jnp.sqrt(2.0 / (mx + 1)) * jnp.sin((i + 1) * (j + 1) * pi / (mx + 1))
    elif base == "riemann":
        bi, bj = i + 2, j + 2
        G = jnp.where(bj % bi == 0, bj - 1.0, -1.0)
    elif base == "ris":
        G = 0.5 / (mx - j - i - 0.5)
    elif base == "zielkeNS":
        G = jnp.where(
            j < i, 1.0, jnp.where((j + 1 == mx) & (i == 0), -1.0, 0.0)
        )
    elif base == "minij":
        G = jnp.minimum(i, j) + 1
    elif base == "hilb":
        G = 1.0 / (i + j + 1)
    elif base == "frank":
        G = jnp.where(
            i - j > 1, 0.0, jnp.where(i - j == 1, mx - j - 1.0, mx - j + 0.0)
        )
    elif base == "lehmer":
        G = (jnp.minimum(i, j) + 1) / (jnp.maximum(i, j) + 1)
    elif base == "lotkin":
        G = jnp.where(i == 0, 1.0, 1.0 / (i + j + 1))
    elif base == "redheff":
        G = jnp.where(((j + 1) % (i + 1) == 0) | (j == 0), 1.0, 0.0)
    elif base == "triw":
        G = jnp.where(i == j, 1.0, jnp.where(i > j, 0.0, -1.0))
    elif base == "tridiag":
        G = jnp.where(i == j, 2.0, jnp.where(jnp.abs(i - j) == 1, -1.0, 0.0))
    elif base == "toeppen":
        G = jnp.where(
            jnp.abs(j - i) == 1,
            (j - i) * 10.0,
            jnp.where(jnp.abs(i - j) == 2, 1.0, 0.0),
        )
    elif base == "pei":
        G = jnp.where(i == j, 2.0, 1.0)
    elif base == "parter":
        G = 1.0 / (i - j + 0.5)
    elif base == "moler":
        G = jnp.where(i == j, i + 1.0, jnp.minimum(i, j) - 1.0)
    elif base == "cauchy":
        G = 1.0 / (i + j + 2)
    elif base == "chow":
        G = jnp.where(i - j < -1, 0.0, 1.0)
    elif base == "clement":
        G = jnp.where(
            i - j == 1, mx - j - 1.0, jnp.where(i - j == -1, j + 0.0, 0.0)
        )
    elif base == "gcdmat":
        ii = np.arange(1, m + 1)[:, None]
        jj = np.arange(1, n + 1)[None, :]
        G = jnp.asarray(np.gcd(ii, jj).astype(np.float64))
    else:
        raise SlateError(f"unknown matrix kind base: {base!r}")
    return G.astype(dtype)


def _sigma(dist: str, min_mn: int, cond: float, sigma_max: float, seed: int,
           real_t, specified=None) -> jnp.ndarray:
    """Singular/eigen value distribution (generate_sigma.hh:39-130)."""
    idx = jnp.arange(min_mn, dtype=jnp.float64)
    denom = max(min_mn - 1, 1)
    if dist == "arith":
        s = 1 - idx / denom * (1 - 1 / cond)
    elif dist == "rarith":
        s = 1 - (min_mn - 1 - idx) / denom * (1 - 1 / cond)
    elif dist == "geo":
        s = cond ** (-idx / denom)
    elif dist == "rgeo":
        s = cond ** (-(min_mn - 1 - idx) / denom)
    elif dist == "cluster0":
        s = jnp.where(idx == 0, 1.0, 1 / cond)
    elif dist == "rcluster0":
        s = jnp.where(idx == min_mn - 1, 1.0, 1 / cond)
    elif dist == "cluster1":
        s = jnp.where(idx == min_mn - 1, 1 / cond, 1.0)
    elif dist == "rcluster1":
        s = jnp.where(idx == 0, 1 / cond, 1.0)
    elif dist == "logrand":
        u = philox.random_jnp(
            "uniform", seed, jnp.arange(min_mn, dtype=jnp.int64), jnp.zeros(min_mn, jnp.int64),
            jnp.float64,
        )
        rng_span = math.log(1 / cond)
        s = jnp.exp(u * rng_span)
    elif dist in ("rand", "rands", "randn"):
        s = philox.random_jnp(
            {"rand": "uniform", "rands": "uniform_signed", "randn": "normal"}[dist],
            seed,
            jnp.arange(min_mn, dtype=jnp.int64),
            jnp.zeros(min_mn, jnp.int64),
            jnp.float64,
        )
    elif dist == "specified":
        if specified is None:
            raise SlateError("dist 'specified' requires sigma values")
        s = jnp.asarray(specified, jnp.float64)
    else:
        raise SlateError(f"unknown sigma distribution {dist!r}")
    return (s * sigma_max).astype(real_t)


def _random_orthogonal(m: int, k: int, seed: int, dtype) -> jnp.ndarray:
    """Random Householder-based orthogonal factor (generate_type_svd.hh:
    90-123: randn matrix -> geqrf -> Q)."""
    from ..ops.householder import geqrf as _geqrf, larft, materialize_v

    i, j = np.arange(m)[:, None], np.arange(k)[None, :]
    X = philox.random_np("normal", seed, i + 0 * j, j + 0 * i,
                         np.complex128 if jnp.dtype(dtype).kind == "c" else np.float64)
    vr, taus = _geqrf(jnp.asarray(X))
    Q = jnp.eye(m, k, dtype=vr.dtype)
    # Q = H_0 ... H_{k-1} I  via blocked application
    nb = min(32, k)
    for k0 in range(((k + nb - 1) // nb) - 1, -1, -1):
        w = min(nb, k - k0 * nb)
        Vk = materialize_v(vr[:, k0 * nb : k0 * nb + w], offset=k0 * nb)
        Tk = larft(Vk, taus[k0 * nb : k0 * nb + w])
        W = jnp.conj(Vk).T @ Q
        Q = Q - Vk @ (Tk @ W)
    return Q.astype(dtype)


def parse_kind(kind: str):
    """Kind-string parsing (generate_matrix_utils.cc:211-360)."""
    tokens = [t for t in kind.replace("-", "_").split("_")]
    if not tokens or not tokens[0]:
        raise SlateError("empty matrix kind")
    base, *mods = tokens
    dist = None
    sigma_max = 1.0
    dominant = False
    zero_col = None
    eps = np.finfo(np.float64).eps
    ufl = np.finfo(np.float64).tiny
    ofl = 1 / ufl
    for tok in mods:
        if tok in _DISTS:
            dist = tok
        elif tok == "small":
            sigma_max = math.sqrt(ufl)
        elif tok == "large":
            sigma_max = math.sqrt(ofl)
        elif tok == "ufl":
            sigma_max = ufl
        elif tok == "ofl":
            sigma_max = ofl
        elif tok == "dominant":
            dominant = True
        elif tok.startswith("zerocol"):
            v = tok[7:]
            zero_col = float(v) if "." in v else int(v)
        else:
            raise SlateError(f"in {kind!r}: unknown suffix {tok!r}")
    if dist is not None and base not in _SPECTRUM_KINDS:
        raise SlateError(f"in {kind!r}: base {base!r} doesn't support distribution")
    if dist is None:
        dist = "logrand"
    return base, dist, sigma_max, dominant, zero_col


def generate_2d(
    kind: str,
    m: int,
    n: int,
    dtype=np.float64,
    seed: int = 42,
    cond: Optional[float] = None,
    sigma_specified=None,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Generate the (m, n) global array for `kind`; returns (A, Sigma)."""
    base, dist, sigma_max, dominant, zero_col = parse_kind(kind)
    dtype = jnp.dtype(dtype)
    real_t = (
        np.float32
        if dtype in (jnp.dtype("float32"), jnp.dtype("complex64"))
        else np.float64
    )
    if cond is None:
        cond = float(1.0 / math.sqrt(np.finfo(real_t).eps))
    min_mn = min(m, n)
    Sigma = None

    if base in _RAND_KINDS:
        i, j = np.arange(m)[:, None], np.arange(n)[None, :]
        G = jnp.asarray(
            philox.random_np(
                _RAND_KINDS[base], seed, i + 0 * j, j + 0 * i, np.dtype(dtype.name)
            )
        )
        if sigma_max != 1.0:
            G = G * sigma_max
        if dominant:
            # generate_rand: diag += row-sum bound (max_mn) to dominate
            rowsum = jnp.sum(jnp.abs(G), axis=1)
            idx = jnp.arange(min_mn)
            G = G.at[idx, idx].set(rowsum[:min_mn].astype(G.dtype))
            dominant = False
    elif base == "diag":
        Sigma = _sigma(dist, min_mn, cond, sigma_max, seed, real_t, sigma_specified)
        G = jnp.zeros((m, n), dtype).at[
            jnp.arange(min_mn), jnp.arange(min_mn)
        ].set(Sigma.astype(dtype))
    elif base in ("svd", "poev", "heev", "geev", "geevx"):
        Sigma = _sigma(dist, min_mn, cond, sigma_max, seed, real_t, sigma_specified)
        if base == "heev":
            # signed spectrum (generate_heev rand_sign)
            signs = philox.random_np(
                "binary_signed", seed + 3, np.arange(min_mn), np.zeros(min_mn)
            )
            Sigma = (Sigma * jnp.asarray(signs)).astype(real_t)
        U = _random_orthogonal(m, min_mn, seed + 1, dtype)
        if base == "svd":
            V = _random_orthogonal(n, min_mn, seed + 2, dtype)
            G = (U * Sigma.astype(dtype)[None, :]) @ jnp.conj(V).T
        elif base in ("poev", "heev"):
            G = (U * Sigma.astype(dtype)[None, :]) @ jnp.conj(U).T
        else:  # geev/geevx: known spectrum, non-normal: A = U T U^H,
            # T upper triangular with Sigma diagonal (Schur-form based,
            # generate_type_geev.hh)
            i, j = np.arange(min_mn)[:, None], np.arange(min_mn)[None, :]
            N = philox.random_np(
                "normal", seed + 4, i + 0 * j, j + 0 * i, np.dtype(dtype.name)
            )
            # mild non-normality: keep the eigenproblem well-conditioned so
            # the spectrum is numerically recoverable
            noise = float(jnp.abs(Sigma).max()) / (4.0 * math.sqrt(min_mn))
            T = noise * jnp.triu(jnp.asarray(N), 1) + jnp.diag(Sigma.astype(dtype))
            G = U @ T @ jnp.conj(U).T
        G = G.astype(dtype)
    else:
        G = _special_entry(base, m, n, dtype)

    if dominant:
        rowsum = jnp.sum(jnp.abs(G), axis=1)
        idx = jnp.arange(min_mn)
        G = G.at[idx, idx].set(rowsum[:min_mn].astype(G.dtype))
    if zero_col is not None:
        col = int(zero_col * (n - 1)) if isinstance(zero_col, float) else zero_col
        if not (0 <= col < n):
            raise SlateError(f"zerocol {col} outside [0, {n})")
        G = G.at[:, col].set(0)
    return G, Sigma


def generate_tiles(
    kind: str, layout, dtype, seed: int = 42
) -> Optional[jnp.ndarray]:
    """Device-side generation of the (P, Q, mb, nb) storage-order tile
    array for the plain rand kinds: every element draws from the Philox
    counter RNG keyed by its *global* (i, j), so the result is invariant
    to tiling and process count (reference: matgen/random.cc:43-100) —
    and under a sharded mesh each device generates only its local tiles,
    with no host round-trip.  Returns None for kinds that need global
    structure (spectra, special matrices, dominant/zerocol suffixes);
    callers fall back to the host path."""
    from . import philox

    base, dist, sigma_max, dominant, zero_col = parse_kind(kind)
    if base not in _RAND_KINDS or dominant or zero_col is not None:
        return None
    dtype = jnp.dtype(dtype)
    gr = jnp.asarray(layout.global_rows_np.astype(np.int64))  # (P, mb)
    gc = jnp.asarray(layout.global_cols_np.astype(np.int64))  # (Q, nb)
    i = jnp.broadcast_to(
        gr[:, None, :, None], (layout.P, layout.Q, layout.mb, layout.nb)
    )
    j = jnp.broadcast_to(gc[None, :, None, :], i.shape)
    T = philox.random_jnp(_RAND_KINDS[base], seed, i, j, dtype)
    if sigma_max != 1.0:
        T = T * sigma_max
    return jnp.where(layout.element_mask(), T, 0)


def generate_matrix(
    kind: str,
    A: BaseMatrix,
    seed: int = 42,
    cond: Optional[float] = None,
    sigma_specified=None,
) -> Tuple[BaseMatrix, Optional[jnp.ndarray]]:
    """Fill an existing matrix's shape/layout with `kind` (reference:
    slate::generate_matrix, include/slate/generate_matrix.hh:29-60).

    Plain rand kinds generate directly on-device per tile
    (generate_tiles); structured kinds assemble on the host."""
    lay = A.resolved().layout
    T = generate_tiles(kind, lay, A.dtype, seed)
    if T is not None:
        return A._with(data=T).shard(), None
    G, Sigma = generate_2d(
        kind, A.m, A.n, A.dtype, seed=seed, cond=cond,
        sigma_specified=sigma_specified,
    )
    out = A._with(data=tiles_from_global(G, lay))
    return out.shard(), Sigma


def generate(
    kind: str,
    m: int,
    n: int,
    mb: int,
    nb: Optional[int] = None,
    dtype=np.float64,
    grid=None,
    seed: int = 42,
    cond: Optional[float] = None,
) -> Matrix:
    """Convenience constructor: generate a fresh distributed Matrix."""
    G, _ = generate_2d(kind, m, n, dtype, seed=seed, cond=cond)
    return Matrix.from_global(G, mb, nb, grid=grid)


def cond_matrix(
    n: int,
    cond: float,
    dtype=np.float64,
    seed: int = 42,
    spd: bool = False,
) -> np.ndarray:
    """Deterministic n x n matrix with **specified 2-norm condition
    number** via scaled-singular-value construction: A = U diag(s) V^H
    with s geometrically spaced from 1 down to 1/cond (``geo``
    distribution, generate_sigma.hh:39-130) and Philox-seeded random
    orthogonal factors — so sigma_max = 1, sigma_min = 1/cond and
    cond_2(A) = cond *exactly by construction*, bit-reproducible for a
    given seed.

    ``spd=True`` uses one orthogonal factor (A = U diag(s) U^H, the
    ``poev`` construction): symmetric/Hermitian positive definite with
    the same 2-norm condition number.

    The knob the refine/ tests are built on: iterative-refinement
    convergence (cond such that cond * eps_factor << 1), stall
    (~1/eps_factor — where GMRES-IR still converges), and divergence +
    fallback (>> 1/eps_factor) become deterministic properties of the
    requested cond instead of luck-of-the-draw spectra."""
    if cond < 1:
        raise SlateError(f"cond must be >= 1, got {cond}")
    kind = "poev_geo" if spd else "svd_geo"
    G, _ = generate_2d(kind, n, n, dtype, seed=seed, cond=float(cond))
    return np.asarray(G)
