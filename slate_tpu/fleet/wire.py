"""Length-prefixed socket framing for the fleet tier's RPC.

One message = a 4-byte big-endian header length, the JSON header, then
the raw bytes of each array the header's ``arrays`` manifest declares
(name, dtype string, shape — in manifest order, C-contiguous).  Both
directions use the same frame, so the router and worker share one
codec and one failure taxonomy:

* EOF mid-frame raises :class:`ConnectionError` — the peer died (the
  ``host_death`` signature: a SIGKILLed worker's kernel sends RST/FIN
  and the router's in-flight ``recv`` breaks immediately, not at the
  timeout).
* A frame exceeding the sanity caps raises :class:`ProtocolError` —
  garbage on the port must fail loudly, never allocate unbounded.
* Timeouts are the *socket's* (``settimeout`` by the caller): the
  router bounds every RPC, the worker bounds idle connections.

JSON carries control only; operands travel as raw buffers (no base64,
no pickling — pickles from a socket would be an RCE surface and the
operands dominate the payload anyway).
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Dict, Optional, Tuple

import numpy as np

from ..exceptions import SlateError

#: frame sanity caps — a corrupt length prefix must not OOM the reader
MAX_HEADER_BYTES = 16 << 20
MAX_ARRAY_BYTES = 1 << 31

_LEN = struct.Struct(">I")


class ProtocolError(SlateError):
    """Malformed fleet RPC frame (bad length, manifest, or dtype)."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise ConnectionError on EOF (the
    socket's timeout applies per chunk; a stalled peer surfaces as
    ``socket.timeout`` from ``recv``)."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError(
                f"fleet peer closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf += chunk
    return bytes(buf)


def send_msg(
    sock: socket.socket,
    header: dict,
    arrays: Optional[Dict[str, np.ndarray]] = None,
) -> None:
    """Send one frame: header JSON (with an ``arrays`` manifest added)
    followed by each array's raw C-contiguous bytes."""
    arrays = arrays or {}
    manifest = []
    payloads = []
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        manifest.append([name, a.dtype.str, list(a.shape)])
        payloads.append(a.tobytes())
    head = dict(header)
    head["arrays"] = manifest
    hb = json.dumps(head).encode("utf-8")
    if len(hb) > MAX_HEADER_BYTES:
        raise ProtocolError(f"fleet header too large ({len(hb)} bytes)")
    sock.sendall(_LEN.pack(len(hb)) + hb + b"".join(payloads))


def recv_msg(
    sock: socket.socket,
) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Receive one frame; returns ``(header, arrays)``.  The header's
    ``arrays`` manifest is consumed into real ndarrays and removed."""
    (hlen,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if hlen > MAX_HEADER_BYTES:
        raise ProtocolError(f"fleet header length {hlen} over cap")
    try:
        header = json.loads(_recv_exact(sock, hlen).decode("utf-8"))
    except ValueError as e:
        raise ProtocolError(f"fleet header is not JSON: {e}") from e
    if not isinstance(header, dict):
        raise ProtocolError("fleet header is not an object")
    arrays: Dict[str, np.ndarray] = {}
    for entry in header.pop("arrays", ()):
        try:
            name, dtype, shape = entry
            dt = np.dtype(dtype)
            shape = tuple(int(d) for d in shape)
        except (TypeError, ValueError) as e:
            raise ProtocolError(
                f"fleet array manifest entry {entry!r} malformed"
            ) from e
        nbytes = dt.itemsize * int(np.prod(shape, dtype=np.int64))
        if not 0 <= nbytes <= MAX_ARRAY_BYTES:
            raise ProtocolError(
                f"fleet array {name!r} size {nbytes} over cap"
            )
        arrays[name] = np.frombuffer(
            _recv_exact(sock, nbytes), dtype=dt
        ).reshape(shape)
    return header, arrays
