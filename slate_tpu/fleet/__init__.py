"""Fleet tier: the cross-PROCESS defense fabric.

One router process fronting N worker processes (each running its own
replica pool) over a small length-prefixed socket RPC, extending every
single-process defense plane across the process boundary:

* **global admission** — per-tenant token buckets and the burn-EWMA
  overload controller aggregated at the router (the single clock), fed
  by periodic host burn reports, so a flooding tenant is refused
  fleet-wide, not per-host.
* **host lifecycle** — heartbeat liveness, bounded RPC timeouts with
  decorrelated-jitter retry, typed fail-fast plus counted re-dispatch
  when a host dies with requests inflight, graceful drain, and
  breaker-shaped host states (live -> suspect -> dead -> rejoined).
* **cross-host hedging + SDC quarantine** — certificate failures and
  deadline-risk stragglers re-execute on a DIFFERENT host; per-host
  integrity scores quarantine whole hosts with probe recovery.
* **stitched observability** — per-host metrics JSONL and span-ring
  dumps, host-tagged fan-in (``tools/metrics_merge.py --tag``), and
  cross-process trace joins (``tools/trace_stitch.py``).

Activation is ``SLATE_TPU_FLEET`` (grammar in :mod:`.router`); with
the env unset, :func:`FleetRouter.from_env` returns None and the
serve api's single-process path is byte-identical — one ``is None``
branch, the repo-wide zero-overhead-off contract.
"""

from .router import (  # noqa: F401
    FLEET_ENV,
    FleetError,
    FleetRouter,
    FleetTimeout,
    HostDead,
    note_bad_result,
    note_trace_orphans,
    parse_fleet,
)
from .wire import ProtocolError  # noqa: F401
from .worker import FleetWorker  # noqa: F401

__all__ = [
    "FLEET_ENV",
    "FleetError",
    "FleetRouter",
    "FleetTimeout",
    "FleetWorker",
    "HostDead",
    "ProtocolError",
    "note_bad_result",
    "note_trace_orphans",
    "parse_fleet",
]
