"""One fleet host: a socket front-end over this process's
:class:`~slate_tpu.serve.service.SolverService`.

Runnable as ``python -m slate_tpu.fleet.worker [--port N]``.  The
worker binds (``SLATE_TPU_FLEET_ADDR``, default loopback), announces
``FLEET_WORKER_PORT=<port>`` on stdout (how the router's ``spawn=``
mode learns an ephemeral port), and serves one RPC per connection,
thread-per-connection — the service underneath does the real
concurrency, a handler thread just parks in ``Future.result()``.

Ops (``header["op"]``):

* ``solve`` — arrays A, B + routine/deadline/retries/precision/tenant/
  priority/trace.  Runs ``service.submit(...).result()`` and replies
  ``{"ok": True}`` + X, or ``{"ok": False, "error": <class>,
  "message": ..., "context": {...}}`` for typed failures — the error
  taxonomy crosses the wire by name, so the router re-raises the same
  exception class the single-process path would.  The router's trace
  id is adopted via ``submit(trace_id=)``: this host's spans join the
  router's chain and ``tools/trace_stitch.py`` can render one request
  as one cross-process Perfetto track.
* ``report`` — heartbeat + stats: queue depth, inflight, phase, the
  local admission plane's burn EWMA (None when the plane is off).
* ``dump`` — write this process's metrics JSONL and span ring to the
  paths the router names (host-tagged observability fan-in).
* ``drain`` — ``service.stop(drain=True)``: admission closes now,
  admitted work finishes, then the process exits.
* ``ping`` — liveness only.

The worker deliberately has NO fleet-specific defense logic: quotas,
quarantine, hedging and host lifecycle live at the router; this module
is a dumb, bounded adapter so a host's failure modes stay the
service's own (plus death, which the router owns).
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import threading
import time
from typing import Optional

from ..aux import metrics, spans
from ..exceptions import SlateError
from . import wire

#: worker bind address (the router address knob's worker half);
#: spawned workers inherit it from the router's environment
ADDR_ENV = "SLATE_TPU_FLEET_ADDR"

#: stdout announce line prefix (the spawn handshake contract)
ANNOUNCE = "FLEET_WORKER_PORT="

#: idle-connection bound: a peer that opens a socket and never sends a
#: full frame must not pin a handler thread forever
IDLE_TIMEOUT_S = 120.0


class FleetWorker:
    """Socket front-end over one process-wide serve service."""

    def __init__(
        self,
        host: Optional[str] = None,
        port: int = 0,
        service=None,
    ):
        self.host = host or os.environ.get(ADDR_ENV) or "127.0.0.1"
        self.port = int(port)
        self._service = service
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()

    @property
    def service(self):
        # lazy: importing jax/building replicas happens on first use,
        # not at construction (tests build workers without serving)
        if self._service is None:
            from ..serve import api as serve_api

            self._service = serve_api.get_service()
        return self._service

    # -- serving ------------------------------------------------------------

    def bind(self) -> int:
        """Bind + listen; returns the (possibly ephemeral) port."""
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(64)
        self._sock = s
        self.port = s.getsockname()[1]
        return self.port

    def serve_forever(self, announce: bool = True) -> None:
        if self._sock is None:
            self.bind()
        if announce:
            print(f"{ANNOUNCE}{self.port}", flush=True)
        self._sock.settimeout(0.25)  # poll the stop flag
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed under us (drain)
            t = threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            )
            t.start()
        self._sock.close()

    def shutdown(self) -> None:
        self._stop.set()

    # -- one RPC ------------------------------------------------------------

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(IDLE_TIMEOUT_S)
            header, arrays = wire.recv_msg(conn)
            op = header.get("op")
            if op == "solve":
                reply, out = self._solve(header, arrays)
            elif op == "report":
                reply, out = self._report(), {}
            elif op == "dump":
                reply, out = self._dump(header), {}
            elif op == "drain":
                reply, out = {"ok": True, "op": "drain"}, {}
            elif op == "ping":
                reply, out = {"ok": True, "op": "ping"}, {}
            else:
                reply, out = {
                    "ok": False, "error": "ProtocolError",
                    "message": f"unknown fleet op {op!r}",
                }, {}
            wire.send_msg(conn, reply, out)
            if op == "drain":
                # reply first (the router is waiting on it), then stop:
                # admission closes immediately, admitted work finishes
                self._drain_and_exit(header)
        except (ConnectionError, OSError, wire.ProtocolError):
            pass  # peer vanished mid-frame; nothing to answer
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _solve(self, header: dict, arrays: dict):
        deadline = header.get("deadline")
        if deadline is not None:
            # the router ships REMAINING budget: rebase on this host's
            # clock (wall-clock offsets between processes cancel out)
            deadline = max(0.0, float(deadline))
        try:
            fut = self.service.submit(
                header["routine"],
                arrays["A"],
                arrays["B"],
                deadline=deadline,
                retries=int(header.get("retries", 0)),
                precision=header.get("precision"),
                tenant=header.get("tenant"),
                priority=header.get("priority"),
                trace_id=header.get("trace"),
            )
            X = fut.result()
        except Exception as e:  # typed taxonomy crosses by name
            metrics.inc("fleet.worker.typed_errors")
            reply = {
                "ok": False,
                "error": type(e).__name__,
                "message": str(e.args[0]) if e.args else str(e),
            }
            if isinstance(e, SlateError):
                reply["context"] = e.context()
            return reply, {}
        metrics.inc("fleet.worker.solved")
        return {"ok": True, "op": "solve"}, {"X": X}

    def _report(self) -> dict:
        h = self.service.health()
        adm = h.get("admission") or {}
        return {
            "ok": True,
            "op": "report",
            "pid": os.getpid(),
            "phase": h.get("phase"),
            "queue_depth": int(h.get("queue_depth", 0)),
            "inflight": int(h.get("inflight", 0)),
            "burn": adm.get("burn_ewma"),
            "t": time.time(),
        }

    def _dump(self, header: dict) -> dict:
        out = {"ok": True, "op": "dump", "metrics": None, "trace": None}
        mpath = header.get("metrics")
        if mpath and metrics.is_on():
            out["metrics"] = metrics.dump(mpath)
        tpath = header.get("trace")
        if tpath and spans.is_on():
            out["trace"] = spans.export_chrome(
                tpath, process_name=header.get("label")
            )
        return out

    def _drain_and_exit(self, header: dict) -> None:
        self.shutdown()
        svc = self._service
        if svc is not None:
            svc.stop(
                drain=True,
                drain_timeout=float(header.get("timeout", 10.0)),
            )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="slate_tpu fleet worker (one host process)"
    )
    ap.add_argument("--host", default=None, help="bind address")
    ap.add_argument("--port", type=int, default=0,
                    help="bind port (0 = ephemeral, announced on stdout)")
    args = ap.parse_args(argv)
    w = FleetWorker(host=args.host, port=args.port)
    w.bind()
    w.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
