"""The fleet tier: a least-loaded router over N worker processes that
extends every single-process defense plane across the process
boundary.

``FleetRouter`` fronts workers (spawned subprocesses or pre-started
``connect=`` addresses) behind the same ``submit()/health()/stop()``
surface as :class:`~slate_tpu.serve.service.SolverService`, speaking
the length-prefixed RPC in :mod:`slate_tpu.fleet.wire`.  The planes it
adds on top — and where each reuses the single-process machinery:

* **Global admission** — ONE :class:`~slate_tpu.serve.admission.
  AdmissionControl` lives at the router (its token buckets tick on the
  router's monotonic clock), so a tenant's quota is fleet-wide: an
  abuser refused here never reaches any host, instead of getting a
  fresh bucket per process.  Worker heartbeat reports carry each
  host's local burn EWMA; the router folds them into its own overload
  controller (``observe_burn``) beside the burn it measures directly
  on deliveries, so sustained overload anywhere sheds fleet-wide,
  lowest priority first.
* **Host lifecycle** — breaker-shaped states per host: ``live`` →
  (one RPC/heartbeat failure) → ``suspect`` → (``dead_after``
  consecutive failures) → ``dead`` → (a heartbeat answered again) →
  ``rejoined`` → (first certified delivery) → ``live``.  Inflight
  requests on a host that dies are failed fast and re-dispatched to a
  live host within a counted budget (``fleet.redispatched``); RPC
  timeouts retry with ``decorrelated_backoff`` jitter
  (``fleet.rpc_retries``).  Late stat reports from a host marked dead
  update stats only — state transitions flow ONLY through the
  heartbeat/failure paths, so a stale report cannot resurrect a dead
  host.  ``stop(drain=True)`` closes admission immediately, lets
  admitted work finish (re-dispatches included), resolves any
  leftovers typed, then drains each host through the worker's
  ``stop(drain=True)`` path.
* **Cross-host hedging + SDC quarantine** — deliveries are certified
  at the router with the factor-cache residual fence
  (:func:`~slate_tpu.integrity.policy.residual_certificate`), sampled
  per an :class:`~slate_tpu.integrity.policy.IntegrityPolicy`; a
  failed certificate re-executes on a *different* host.  Per-host
  :class:`~slate_tpu.integrity.policy.IntegrityScore` aggregation
  quarantines a whole host (excluded from dispatch while cooling
  down) and probe-recovers it: a rejoined/quarantined host's next
  delivery is certified regardless of the sampling rate.  Stragglers
  older than ``hedge_s`` are cloned onto a different host; the first
  member to deliver wins, exactly once.
* **Stitched observability** — the router mints the trace id, workers
  adopt it via ``submit(trace_id=)``, and per-host ``dump`` RPCs +
  ``tools/trace_stitch.py`` / ``tools/metrics_merge.py --tag`` join
  the pieces back into one fleet-wide view.

Configuration (``SLATE_TPU_FLEET`` or constructor args)::

    spawn=2                       # spawn N local worker processes
    connect=127.0.0.1:7701+...    # or join pre-started workers
    cert=0.25 | cert=full | cert=off    # router-side certification
    hedge=0.5                     # straggler hedge age, s (0 = off)
    retries=2                     # transient RPC retries per dispatch
    redispatch=2                  # cross-host re-dispatch budget
    dead_after=3                  # consecutive failures -> dead
    threshold=0.6,cooldown=2.0,alpha=0.5   # host quarantine knobs
    respawn                       # respawn spawned workers that die
    seed=0

plus ``SLATE_TPU_FLEET_TENANTS`` (the ``admission.parse_tenants``
grammar, applied fleet-wide), ``SLATE_TPU_FLEET_HEARTBEAT`` (period,
s) and ``SLATE_TPU_FLEET_TIMEOUT`` (per-RPC bound, s).

Zero overhead off: with no fleet configured, ``serve.api`` never
constructs this class and single-process serving is byte-identical
(one ``is None`` branch at submit).
"""

from __future__ import annotations

import os
import random
import socket
import subprocess
import sys
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..aux import faults, metrics, spans, sync
from ..exceptions import NumericalError, SlateError
from ..integrity.policy import IntegrityScore, parse_spec as parse_integrity
from ..serve import admission as _adm
from ..serve.service import (
    Rejected,
    Shed,
    decorrelated_backoff,
)
from . import wire
from .worker import ADDR_ENV, ANNOUNCE

FLEET_ENV = "SLATE_TPU_FLEET"
FLEET_TENANTS_ENV = "SLATE_TPU_FLEET_TENANTS"
HEARTBEAT_ENV = "SLATE_TPU_FLEET_HEARTBEAT"
TIMEOUT_ENV = "SLATE_TPU_FLEET_TIMEOUT"

#: breaker-shaped host states (health()["hosts"] vocabulary)
HOST_LIVE = "live"
HOST_SUSPECT = "suspect"
HOST_DEAD = "dead"
HOST_REJOINED = "rejoined"

#: first backoff step for transient-RPC retry jitter, seconds
RPC_BACKOFF_BASE_S = 0.05

#: how long a spawned worker gets to announce its port, seconds (cold
#: jax import dominates)
SPAWN_ANNOUNCE_TIMEOUT_S = 90.0


class FleetError(SlateError):
    """Fleet-tier failure (RPC, routing, drain) — typed so a client
    can distinguish fabric trouble from numerical/admission errors."""


class HostDead(FleetError):
    """The request's host died (or no live host remains) and the
    re-dispatch budget is exhausted — fail-fast, never a hang."""


class FleetTimeout(FleetError):
    """An RPC exceeded its bound after transient retries."""


def note_bad_result(n: int = 1) -> None:
    """Count a client-verified wrong answer (``fleet.bad_results``) —
    the fleet drill's reference checks report through here so the
    counter has one in-library spelling for ``fleet_report`` to join
    (zero silent wrong answers is the gate's core claim)."""
    metrics.inc("fleet.bad_results", n)


def note_trace_orphans(n: int) -> None:
    """Record the stitched-trace orphan count (``fleet.trace_orphans``
    gauge) — set by the drill from ``tools/trace_stitch.py`` output."""
    metrics.gauge("fleet.trace_orphans", n)


# ---------------------------------------------------------------------------
# spec parsing
# ---------------------------------------------------------------------------


def parse_fleet(spec: str) -> dict:
    """``SLATE_TPU_FLEET`` grammar -> FleetRouter kwargs (module
    docstring).  Malformed specs fail naming the knob."""
    kw: dict = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        k, sep, v = item.partition("=")
        if k == "spawn" and sep:
            kw["spawn"] = int(v)
        elif k == "connect" and sep:
            addrs = []
            for a in v.split("+"):
                host, _, port = a.rpartition(":")
                addrs.append((host or "127.0.0.1", int(port)))
            kw["connect"] = tuple(addrs)
        elif k == "cert" and sep:
            kw["cert"] = (
                v if v in ("full", "off") or v.startswith("sample=")
                else f"sample={float(v)}"
            )
        elif k == "hedge" and sep:
            kw["hedge_s"] = float(v)
        elif k == "retries" and sep:
            kw["rpc_retries"] = int(v)
        elif k == "redispatch" and sep:
            kw["redispatch_max"] = int(v)
        elif k == "dead_after" and sep:
            kw["dead_after"] = int(v)
        elif k == "threshold" and sep:
            kw["quarantine_threshold"] = float(v)
        elif k == "cooldown" and sep:
            kw["quarantine_cooldown_s"] = float(v)
        elif k == "alpha" and sep:
            kw["quarantine_alpha"] = float(v)
        elif k == "seed" and sep:
            kw["seed"] = int(v)
        elif k == "respawn" and not sep:
            kw["respawn"] = True
        else:
            raise ValueError(
                f"{FLEET_ENV}={spec!r}: unknown key {item!r} "
                "(spawn=|connect=|cert=|hedge=|retries=|redispatch=|"
                "dead_after=|threshold=|cooldown=|alpha=|seed=|respawn)"
            )
    if not kw.get("spawn") and not kw.get("connect"):
        raise ValueError(
            f"{FLEET_ENV}={spec!r}: need spawn=<n> or connect=<addrs>"
        )
    return kw


# ---------------------------------------------------------------------------
# host + request records
# ---------------------------------------------------------------------------


class _Host:
    """One worker process as the router sees it.  All mutable fields
    advance under the router's ``_lock`` except ``score``, which is
    self-locked (IntegrityScore)."""

    __slots__ = (
        "name", "addr", "proc", "spawn_env", "state", "fails",
        "inflight", "queue_depth", "burn", "probe_pending",
        "last_report", "died_at", "score",
    )

    def __init__(self, name: str, addr: Tuple[str, int],
                 proc=None, spawn_env=None, score: IntegrityScore = None):
        self.name = name
        self.addr = addr
        self.proc = proc  # guarded by: _lock (external)
        self.spawn_env = spawn_env
        self.state = HOST_LIVE  # guarded by: _lock (external)
        self.fails = 0  # consecutive  # guarded by: _lock (external)
        self.inflight = 0  # guarded by: _lock (external)
        self.queue_depth = 0  # guarded by: _lock (external)
        self.burn = None  # guarded by: _lock (external)
        self.probe_pending = False  # guarded by: _lock (external)
        self.last_report = 0.0  # guarded by: _lock (external)
        self.died_at = 0.0  # guarded by: _lock (external)
        self.score = score if score is not None else IntegrityScore()


class _FleetRequest:
    """One client submit: future + dispatch bookkeeping.  Mutable
    fields advance under the router's ``_lock``; the future resolves
    outside it, exactly once (``done`` is the gate)."""

    __slots__ = (
        "rid", "routine", "A", "B", "deadline_s", "t_deadline",
        "retries", "precision", "tenant", "prio", "future", "trace",
        "root", "t_submit", "attempts", "hedged", "settled",
        "members",
        "hosts_tried",
    )

    def __init__(self, rid, routine, A, B, deadline_s, retries,
                 precision, tenant, prio, trace, root, now):
        self.rid = rid
        self.routine = routine
        self.A = A
        self.B = B
        self.deadline_s = deadline_s
        self.t_deadline = (
            now + deadline_s if deadline_s is not None else None
        )
        self.retries = retries
        self.precision = precision
        self.tenant = tenant
        self.prio = prio
        self.future = Future()
        self.trace = trace
        self.root = root
        self.t_submit = now
        self.attempts = 0  # dispatches so far  # guarded by: _lock (external)
        self.hedged = False  # guarded by: _lock (external)
        self.settled = False  # guarded by: _lock (external)
        self.members = []  # every dispatch  # guarded by: _lock (external)
        self.hosts_tried = set()  # guarded by: _lock (external)

    def alive_locked(self, but=None) -> bool:
        """A member other than ``but`` is still running and not yet
        compensated — its outcome will resolve this request, so the
        caller must not."""
        return any(
            m is not but and not m.finished and not m.doomed
            for m in self.members
        )


class _Member:
    """One dispatch of one request onto one host.  ``doomed`` marks a
    member the failure machinery already compensated for (host-death
    fail-fast re-dispatch, typed resolution) — its own eventual RPC
    error must not spend budget again."""

    __slots__ = ("host", "hedge", "doomed", "finished")

    def __init__(self, host: _Host, hedge: bool):
        self.host = host
        self.hedge = hedge
        self.doomed = False  # guarded by: _lock (external)
        self.finished = False  # guarded by: _lock (external)


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


class FleetRouter:
    """Least-loaded cross-process router (module docstring)."""

    def __init__(
        self,
        spawn: int = 0,
        connect: Tuple[Tuple[str, int], ...] = (),
        tenants=None,
        cert: str = "sample=0.25",
        hedge_s: float = 0.0,
        rpc_retries: int = 2,
        redispatch_max: int = 2,
        dead_after: int = 3,
        heartbeat_s: Optional[float] = None,
        rpc_timeout_s: Optional[float] = None,
        quarantine_threshold: float = 0.6,
        quarantine_cooldown_s: float = 2.0,
        quarantine_alpha: float = 0.5,
        respawn: bool = False,
        spawn_env=None,
        seed: int = 0,
        max_dispatch_threads: int = 32,
    ):
        if spawn <= 0 and not connect:
            raise ValueError("FleetRouter needs spawn>0 or connect addrs")
        self.spawn = int(spawn)
        self.connect = tuple(connect)
        self.hedge_s = float(hedge_s)
        self.rpc_retries = int(rpc_retries)
        self.redispatch_max = int(redispatch_max)
        self.dead_after = max(1, int(dead_after))
        self.heartbeat_s = (
            float(heartbeat_s) if heartbeat_s is not None
            else float(os.environ.get(HEARTBEAT_ENV, "") or 0.5)
        )
        self.rpc_timeout_s = (
            float(rpc_timeout_s) if rpc_timeout_s is not None
            else float(os.environ.get(TIMEOUT_ENV, "") or 30.0)
        )
        self.respawn = bool(respawn)
        self.seed = int(seed)
        self._quarantine_kw = dict(
            alpha=float(quarantine_alpha),
            threshold=float(quarantine_threshold),
            cooldown_s=float(quarantine_cooldown_s),
        )
        # router-side certification policy (None = off; the escape
        # leg's disarmed configuration)
        self.policy = parse_integrity(cert)
        self._tenant_keys = None  # lazily a metrics.CappedKeys
        if tenants is None:
            tenants = os.environ.get(FLEET_TENANTS_ENV, "")
        if isinstance(tenants, str):
            tenants = (
                _adm.parse_tenants(tenants) if tenants.strip() else None
            )
        # the GLOBAL admission plane: one instance, the router's clock
        self._admission = (
            _adm.AdmissionControl(tenants=tenants) if tenants else None
        )
        self._spawn_env = spawn_env
        # sync.Lock: plain threading.Lock unless SLATE_TPU_SYNC_CHECK
        # armed the race plane (zero overhead off)
        self._lock = sync.Lock(name="fleet.FleetRouter._lock")
        self._hosts: Dict[str, _Host] = {}  # guarded by: _lock
        self._pending: Dict[int, _FleetRequest] = {}  # guarded by: _lock
        self._rid = 0  # guarded by: _lock
        self._started = False  # guarded by: _lock
        self._draining = False  # guarded by: _lock
        self._stopped = False  # guarded by: _lock
        self._pool: Optional[ThreadPoolExecutor] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._max_dispatch_threads = int(max_dispatch_threads)

    @staticmethod
    def from_env() -> Optional["FleetRouter"]:
        """Build from ``SLATE_TPU_FLEET`` (None when unset/empty —
        the zero-overhead-off decision ``serve.api`` branches on)."""
        spec = os.environ.get(FLEET_ENV, "").strip()
        if not spec:
            return None
        return FleetRouter(**parse_fleet(spec))

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FleetRouter":
        """Spawn/connect the hosts and start the heartbeat (idempotent;
        ``submit`` calls it lazily)."""
        with self._lock:
            if self._started:
                return self
            self._started = True
        for i in range(self.spawn):
            env = self._env_for(i)
            proc, addr = self._spawn_worker(env)
            self._add_host(str(i), addr, proc=proc, spawn_env=env)
        for j, addr in enumerate(self.connect):
            self._add_host(str(self.spawn + j), addr)
        self._pool = ThreadPoolExecutor(
            max_workers=self._max_dispatch_threads,
            thread_name_prefix="fleet-dispatch",
        )
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="fleet-heartbeat",
            daemon=True,
        )
        self._hb_thread.start()
        return self

    def _env_for(self, i: int) -> dict:
        env = dict(os.environ)
        # a worker must never build its own fleet tier (recursion), and
        # its bind address comes from the router's address knob
        env.pop(FLEET_ENV, None)
        env.pop(FLEET_TENANTS_ENV, None)
        env.setdefault(ADDR_ENV, "127.0.0.1")
        overrides = self._spawn_env
        if isinstance(overrides, (list, tuple)):
            overrides = overrides[i] if i < len(overrides) else None
        for k, v in (overrides or {}).items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = str(v)
        return env

    def _spawn_worker(self, env: dict):
        proc = subprocess.Popen(
            # -c, not -m: runpy would re-execute the worker module as
            # __main__ next to the already-imported copy
            [sys.executable, "-c",
             "import sys; from slate_tpu.fleet.worker import main; "
             "sys.exit(main())"],
            env=env, stdout=subprocess.PIPE, text=True,
        )
        port = None
        deadline = time.monotonic() + SPAWN_ANNOUNCE_TIMEOUT_S
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break  # worker died before announcing
            if line.startswith(ANNOUNCE):
                port = int(line[len(ANNOUNCE):].strip())
                break
        if port is None:
            proc.kill()
            raise FleetError(
                "fleet worker failed to announce a port "
                f"(rc={proc.poll()})"
            )
        # keep draining stdout so the pipe can never block the worker
        threading.Thread(
            target=_drain_pipe, args=(proc.stdout,), daemon=True
        ).start()
        return proc, (env.get(ADDR_ENV, "127.0.0.1"), port)

    def _add_host(self, name, addr, proc=None, spawn_env=None) -> _Host:
        h = _Host(
            name, addr, proc=proc, spawn_env=spawn_env,
            score=IntegrityScore(**self._quarantine_kw),
        )
        with self._lock:
            self._hosts[name] = h
        return h

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the fleet.  ``drain=True``: admission closes NOW
        (submits refuse typed), admitted work — re-dispatches included
        — finishes within ``timeout``, leftovers resolve typed
        (``fleet.drain_abandoned``), then every live host drains via
        its worker's ``stop(drain=True)`` path and spawned processes
        are reaped.  No future ever hangs across a stop."""
        with self._lock:
            if self._stopped:
                return
            self._draining = True
            started = self._started
            self._stopped = not started
        if not started:
            return
        deadline = time.monotonic() + max(0.0, timeout)
        if drain:
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._pending:
                        break
                time.sleep(0.02)
        # resolve anything still inflight typed — bounded, not hung
        with self._lock:
            leftovers = [
                p for p in self._pending.values() if not p.settled
            ]
            for p in leftovers:
                p.settled = True
            self._pending.clear()
            # snapshot state + proc under the lock; after _stopped no
            # path mutates them, so the loop below reads its own copy
            hosts = [
                (h, h.state != HOST_DEAD, h.proc)
                for h in self._hosts.values()
            ]
            self._stopped = True
        for p in leftovers:
            metrics.inc("fleet.drain_abandoned")
            metrics.inc("fleet.typed_errors")
            self._finish_spans(p, "FleetError")
            p.future.set_exception(
                FleetError(
                    "fleet stopped before this request finished"
                ).with_context(routine=p.routine, tenant=p.tenant)
            )
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
        for h, alive, proc in hosts:
            if alive and drain:
                try:
                    self._rpc(h, {"op": "drain", "timeout": 5.0},
                              timeout=10.0, retries=0)
                    metrics.inc("fleet.drained")
                except (OSError, SlateError):
                    pass  # a host that cannot drain gets reaped below
            if proc is not None:
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5.0)
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    # -- submit -------------------------------------------------------------

    def submit(
        self,
        routine: str,
        A,
        B,
        deadline: Optional[float] = None,
        retries: int = 0,
        precision: Optional[str] = None,
        sharded: Optional[bool] = None,
        tenant: Optional[str] = None,
        priority=None,
    ) -> Future:
        """Enqueue one solve fleet-wide; returns a Future (same
        contract as ``SolverService.submit``, same typed taxonomy —
        plus :class:`HostDead`/:class:`FleetTimeout` for fabric
        failures).  Global admission runs HERE: quota and shed
        decisions are fleet-wide, on the router's single clock."""
        del sharded  # placement inside each host decides (size-routed)
        self.start()
        tname, prio = _adm.resolve_identity(tenant, priority)
        with self._lock:
            draining = self._draining
        if draining:
            metrics.inc("fleet.refused")
            raise Rejected(
                "fleet is draining — admission closed"
            ).with_context(routine=routine, tenant=tname)
        adm = self._admission
        now = time.monotonic()
        if adm is not None:
            adm.tick(now)
            if adm.sheds(prio):
                adm.tenant_event(tname, "shed")
                metrics.inc("fleet.shed")
                metrics.inc("fleet.refused")
                raise Shed(
                    "fleet overload: priority class refused"
                ).with_context(
                    routine=routine, tenant=tname,
                    priority=_adm.PRIORITIES[prio],
                )
            if not adm.quota_take(tname, now):
                adm.tenant_event(tname, "rejected")
                metrics.inc("fleet.rejected_quota")
                metrics.inc("fleet.refused")
                raise Rejected(
                    f"tenant {tname!r} over fleet-wide quota"
                ).with_context(routine=routine, tenant=tname)
            adm.tenant_event(tname, "admitted")
        A = np.asarray(A)
        B = np.asarray(B)
        if B.ndim == 1:
            B = B[:, None]
        if A.ndim != 2 or B.ndim != 2 or A.shape[0] != B.shape[0]:
            raise ValueError(
                f"{routine}: bad shapes A{A.shape} B{B.shape}"
            )
        metrics.inc("fleet.submitted")
        trace = root = None
        if spans.is_on():
            trace = spans.new_trace()
            root = spans.start(
                "request", trace=trace, lane="router", routine=routine,
            )
        with self._lock:
            self._rid += 1
            p = _FleetRequest(
                self._rid, routine, A, B, deadline, int(retries),
                precision, tname, prio, trace, root, now,
            )
            self._pending[p.rid] = p
            host = self._pick_host_locked(exclude=())
        if host is None:
            self._resolve_exc(
                p,
                HostDead("no live fleet host").with_context(
                    routine=routine, tenant=tname
                ),
            )
        else:
            self._spawn_run(p, host, hedge=False)
        return p.future

    # -- host selection -----------------------------------------------------

    def _pick_host_locked(self, exclude=()) -> Optional[_Host]:
        """Least-loaded eligible host (router inflight + last reported
        queue depth).  Eligible = live/rejoined, not quarantine-
        excluded, not in ``exclude``; when quarantine excludes every
        candidate the least-loaded non-dead host still serves (degraded
        capacity must not become zero capacity)."""
        now = time.monotonic()
        candidates = [
            h for h in self._hosts.values()
            if h.state in (HOST_LIVE, HOST_REJOINED)
            and h.name not in exclude
        ]
        healthy = [h for h in candidates if not h.score.excluded(now)]
        pool = healthy or candidates
        best = None
        best_load = 0
        for h in pool:
            load = h.inflight + h.queue_depth
            if best is None or load < best_load:
                best, best_load = h, load
        return best

    # -- dispatch -----------------------------------------------------------

    def _spawn_run(self, p: _FleetRequest, host: _Host,
                   hedge: bool) -> None:
        m = _Member(host, hedge)
        with self._lock:
            if p.settled:
                return
            p.attempts += 1
            p.members.append(m)
            p.hosts_tried.add(host.name)
            host.inflight += 1
        self._pool.submit(self._run, p, m)

    def _run(self, p: _FleetRequest, m: _Member) -> None:
        host = m.host
        try:
            self._run_inner(p, m)
        except BaseException as e:  # belt: a dispatch thread must
            # never die with the member unaccounted (the future would
            # wait on a ghost member) — resolve through the same path
            self._member_failed(p, m, e)
        finally:
            with self._lock:
                host.inflight = max(0, host.inflight - 1)
                m.finished = True

    def _run_inner(self, p: _FleetRequest, m: _Member) -> None:
        host = m.host
        now = time.monotonic()
        if p.t_deadline is not None and now >= p.t_deadline:
            from ..serve.service import DeadlineExceeded

            self._member_failed(
                p, m,
                DeadlineExceeded(
                    "deadline passed before fleet dispatch"
                ).with_context(routine=p.routine, tenant=p.tenant),
            )
            return
        if faults.is_on() and faults.fire("host_death") is not None:
            # chaos: SIGKILL the worker mid-stream (connect-mode hosts
            # get the router-side signature of the same event)
            with self._lock:
                proc = host.proc
            if proc is not None:
                proc.kill()
            else:
                self._note_host_failure(host, hard=True)
                self._member_failed(
                    p, m, ConnectionError("injected host_death")
                )
                return
        header = {
            "op": "solve",
            "routine": p.routine,
            "retries": p.retries,
            "precision": p.precision,
            "tenant": p.tenant,
            "priority": _adm.PRIORITIES[p.prio],
            "trace": p.trace,
            "deadline": (
                None if p.t_deadline is None
                else max(0.0, p.t_deadline - now)
            ),
        }
        dsp = None
        if spans.is_on():
            dsp = spans.start(
                "dispatch", trace=p.trace, parent=p.root,
                lane=f"host{host.name}", host=host.name, hedge=m.hedge,
            )
        try:
            reply, arrays = self._rpc(
                host, header, {"A": p.A, "B": p.B},
                timeout=self.rpc_timeout_s, retries=self.rpc_retries,
                solve=True,
            )
        except (OSError, SlateError) as e:
            spans.end(dsp, outcome=type(e).__name__)
            self._note_host_failure(host)
            self._member_failed(p, m, e)
            return
        self._note_host_ok(host)
        if not reply.get("ok"):
            spans.end(dsp, outcome=reply.get("error") or "error")
            self._member_typed(p, m, reply)
            return
        X = arrays.get("X")
        if X is None:
            spans.end(dsp, outcome="ProtocolError")
            self._member_failed(
                p, m,
                wire.ProtocolError("solve reply carried no X"),
            )
            return
        verdict = self._certify(p, host, X)
        spans.end(dsp, outcome="ok" if verdict else "cert_fail")
        if not verdict:
            # certified-wrong: never deliver — re-execute on a
            # DIFFERENT host (the member-failure path excludes every
            # host this request already tried)
            self._member_failed(
                p, m,
                NumericalError(
                    "fleet integrity certificate failed"
                ).with_context(routine=p.routine, tenant=p.tenant),
            )
            return
        self._deliver(p, m, X)

    # -- certification + quarantine -----------------------------------------

    def _certify(self, p: _FleetRequest, host: _Host,
                 X: np.ndarray) -> bool:
        """Router-side residual certificate, sampled per policy; a
        quarantined or rejoined host's delivery is certified
        REGARDLESS of the sampling rate (the probe must be the very
        next delivery, not the next sampled one)."""
        if p.routine not in ("gesv", "posv"):
            return True
        with self._lock:
            forced = host.probe_pending
        pol = self.policy
        if not forced:
            forced = host.score.suspect()
        if pol is None:
            if not forced:
                return True
            # defenses disarmed: a forced probe still certifies so a
            # rejoined host cannot silently serve garbage forever
        elif not forced and not pol.should_check():
            return True
        from ..integrity.policy import residual_certificate

        ok = residual_certificate(p.routine, p.A, X, p.B)
        metrics.inc("fleet.cert.checked")
        moved = host.score.observe(ok, time.monotonic())
        if moved == "quarantined":
            metrics.inc("fleet.quarantined")
            if spans.is_on():
                spans.event(
                    "host_quarantined", trace=p.trace, lane="router",
                    host=host.name,
                )
        elif moved == "recovered":
            metrics.inc("fleet.unquarantined")
        if ok:
            with self._lock:
                if host.probe_pending:
                    host.probe_pending = False
                    if host.state == HOST_REJOINED:
                        host.state = HOST_LIVE
                        metrics.inc("fleet.host_recovered")
        else:
            metrics.inc("fleet.cert.fail")
        return ok

    # -- delivery / failure (exactly-once) ----------------------------------

    def _deliver(self, p: _FleetRequest, m: _Member,
                 X: np.ndarray) -> None:
        with self._lock:
            if p.settled:
                won = False
            else:
                p.settled = True
                won = True
                self._pending.pop(p.rid, None)
            hedged = p.hedged
        if not won:
            if hedged:
                metrics.inc("fleet.hedge.wasted")
            return
        if hedged and m.hedge:
            metrics.inc("fleet.hedge.won")
        metrics.inc("fleet.delivered")
        now = time.monotonic()
        total_s = now - p.t_submit
        if metrics.is_on():
            metrics.observe_hist("fleet.latency.total", total_s)
            if self._tenant_tracked(p.tenant):
                metrics.observe_hist(
                    f"fleet.latency.tenant.{p.tenant}.total", total_s
                )
        adm = self._admission
        if adm is not None:
            # the router-measured burn feeds the global overload EWMA
            adm.observe_finish(
                None, p.tenant, p.prio, total_s, p.deadline_s, now,
                trace=p.trace, lane="router", windowed=False,
            )
        self._finish_spans(p, "ok")
        sync.hb_publish(p.future)
        p.future.set_result(X)

    def _member_typed(self, p: _FleetRequest, m: _Member,
                      reply: dict) -> None:
        """A worker answered with a typed error: deterministic, so it
        resolves the request (no cross-host retry) — EXCEPT a host-
        local Rejected, which re-dispatches: one full host must not
        refuse work the fleet has capacity for."""
        exc = _rebuild_exc(reply)
        if reply.get("error") == "Rejected":
            self._member_failed(p, m, exc)
            return
        self._resolve_exc(p, exc)

    def _member_failed(self, p: _FleetRequest, m: _Member,
                       exc: BaseException) -> None:
        """One member's dispatch failed (RPC error, cert failure, host
        Rejected).  Marks the member compensated, then re-dispatches or
        resolves through :meth:`_compensate` — exactly once per
        member, however many paths observe the same failure."""
        with self._lock:
            if p.settled or m.doomed:
                return
            m.doomed = True
        self._compensate(p, exc)

    def _compensate(self, p: _FleetRequest,
                    exc: BaseException) -> None:
        """Re-dispatch to an untried live host within budget; else let
        a surviving member finish; else resolve typed — a fleet future
        NEVER hangs."""
        with self._lock:
            if p.settled:
                return
            draining = self._draining
            budget_left = p.attempts <= self.redispatch_max
            other = (
                self._pick_host_locked(exclude=p.hosts_tried)
                if budget_left and not draining else None
            )
            survivors = other is None and p.alive_locked()
        if other is not None:
            metrics.inc("fleet.redispatched")
            if spans.is_on():
                spans.event(
                    "redispatch", trace=p.trace, lane="router",
                    to_host=other.name, cause=type(exc).__name__,
                )
            self._spawn_run(p, other, hedge=False)
            return
        if survivors:
            return  # the surviving member will deliver or fail
        if draining and not isinstance(exc, SlateError):
            exc = FleetError(
                "fleet draining: re-dispatch refused"
            ).with_context(routine=p.routine, tenant=p.tenant)
        elif isinstance(exc, (OSError, ConnectionError)):
            exc = HostDead(
                f"fleet host failed ({type(exc).__name__}) and no "
                "re-dispatch budget/host remains"
            ).with_context(routine=p.routine, tenant=p.tenant)
        self._resolve_exc(p, exc)

    def _resolve_exc(self, p: _FleetRequest, exc: BaseException) -> None:
        with self._lock:
            if p.settled:
                return
            p.settled = True
            self._pending.pop(p.rid, None)
        metrics.inc("fleet.typed_errors")
        self._finish_spans(p, type(exc).__name__)
        sync.hb_publish(p.future)
        p.future.set_exception(exc)

    def _finish_spans(self, p: _FleetRequest, outcome: str) -> None:
        spans.end(p.root, outcome=outcome)

    def _tenant_tracked(self, tenant: str) -> bool:
        if self._tenant_keys is None:
            self._tenant_keys = metrics.CappedKeys(64)
        return self._tenant_keys.track(tenant)

    # -- RPC ----------------------------------------------------------------

    def _rpc(self, host: _Host, header: dict, arrays=None,
             timeout: Optional[float] = None, retries: int = 0,
             solve: bool = False):
        """One bounded request/response round-trip.  Transient
        timeouts retry in place with decorrelated jitter
        (``fleet.rpc_retries``); connection errors propagate
        immediately (the dead-host fast path — retrying a refused
        connect just delays the fail-fast)."""
        timeout = self.rpc_timeout_s if timeout is None else timeout
        # seeded per (router, host): PYTHONHASHSEED-independent, so a
        # seeded drill's backoff sequence replays exactly
        rng = random.Random(
            (self.seed << 20) ^ sum(ord(c) for c in host.name)
        )
        prev = RPC_BACKOFF_BASE_S
        attempt = 0
        while True:
            try:
                if faults.is_on():
                    if faults.fire("host_partition") is not None:
                        # RPC blackhole: bytes vanish, no RST returns —
                        # indistinguishable from a timeout by design
                        raise socket.timeout("injected host_partition")
                    if solve and faults.fire("rpc_timeout") is not None:
                        raise socket.timeout("injected rpc_timeout")
                with socket.create_connection(
                    host.addr, timeout=timeout
                ) as s:
                    s.settimeout(timeout)
                    wire.send_msg(s, header, arrays)
                    return wire.recv_msg(s)
            except socket.timeout as e:
                attempt += 1
                if attempt > retries:
                    raise FleetTimeout(
                        f"fleet RPC to host {host.name} timed out "
                        f"after {attempt} attempts"
                    ) from e
                metrics.inc("fleet.rpc_retries")
                prev = decorrelated_backoff(rng, prev,
                                            RPC_BACKOFF_BASE_S)
                time.sleep(prev)

    # -- host lifecycle -----------------------------------------------------

    def _note_host_ok(self, host: _Host) -> None:
        with self._lock:
            host.fails = 0
            if host.state == HOST_SUSPECT:
                host.state = HOST_LIVE
                metrics.inc("fleet.host_recovered")
            elif host.state == HOST_DEAD:
                # answered again after death: rejoined — its next
                # delivery is the certification probe
                host.state = HOST_REJOINED
                host.probe_pending = True
                metrics.inc("fleet.host_rejoined")

    def _note_host_failure(self, host: _Host,
                           hard: bool = False) -> None:
        to_failfast: List[_FleetRequest] = []
        with self._lock:
            host.fails += 1
            if host.state in (HOST_LIVE, HOST_REJOINED):
                host.state = HOST_SUSPECT
                metrics.inc("fleet.host_suspect")
            if host.state == HOST_SUSPECT and (
                hard or host.fails >= self.dead_after
            ):
                host.state = HOST_DEAD
                host.died_at = time.monotonic()
                metrics.inc("fleet.host_dead")
                # typed fail-fast: every member inflight on this host
                # is doomed and compensated NOW (re-dispatch or typed
                # error), not at its RPC timeout; the stuck RPC
                # thread's own eventual failure finds doomed=True and
                # spends no further budget
                for p in self._pending.values():
                    if p.settled:
                        continue
                    doomed_any = False
                    for m in p.members:
                        if m.host is host and not m.finished \
                                and not m.doomed:
                            m.doomed = True
                            doomed_any = True
                    if doomed_any:
                        to_failfast.append(p)
        for p in to_failfast:
            self._compensate(
                p,
                HostDead(
                    f"fleet host {host.name} died with the request "
                    "inflight"
                ).with_context(routine=p.routine, tenant=p.tenant),
            )

    def _note_report(self, host: _Host, report: dict) -> None:
        """Fold one heartbeat report's stats in.  Stats ONLY: a report
        racing (or arriving after) a death transition must not
        resurrect the host — liveness flows through
        ``_note_host_ok``/``_note_host_failure`` alone."""
        with self._lock:
            host.queue_depth = int(report.get("queue_depth", 0))
            host.burn = report.get("burn")
            host.last_report = time.monotonic()
            burn = host.burn
        adm = self._admission
        if adm is not None and burn:
            # host-local burn EWMAs aggregate into the global
            # controller: overload anywhere sheds fleet-wide
            adm.observe_burn(float(burn), time.monotonic())

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.heartbeat_s):
            with self._lock:
                hosts = list(self._hosts.values())
            for h in hosts:
                try:
                    reply, _ = self._rpc(
                        h, {"op": "report"},
                        timeout=max(1.0, 2.0 * self.heartbeat_s),
                        retries=0,
                    )
                except (OSError, SlateError):
                    self._note_host_failure(h)
                    continue
                self._note_host_ok(h)
                self._note_report(h, reply)
            self._respawn_dead()
            self._hedge_sweep()

    def _respawn_dead(self) -> None:
        if not self.respawn:
            return
        with self._lock:
            dead = [
                h for h in self._hosts.values()
                if h.state == HOST_DEAD and h.proc is not None
                and h.proc.poll() is not None
                and time.monotonic() - h.died_at > self.heartbeat_s
            ]
        for h in dead:
            try:
                proc, addr = self._spawn_worker(
                    h.spawn_env or self._env_for(int(h.name))
                )
            except (OSError, ValueError, SlateError):
                continue  # next sweep retries
            with self._lock:
                h.proc = proc
                h.addr = addr
                # still DEAD until a heartbeat answers — rejoin (and
                # the probe) flow through _note_host_ok like any other
                # recovery
            metrics.inc("fleet.host_respawned")

    def _hedge_sweep(self) -> None:
        if self.hedge_s <= 0:
            return
        now = time.monotonic()
        targets: List[Tuple[_FleetRequest, _Host]] = []
        with self._lock:
            for p in self._pending.values():
                if p.settled or p.hedged or not p.alive_locked():
                    continue
                if now - p.t_submit < self.hedge_s:
                    continue
                other = self._pick_host_locked(exclude=p.hosts_tried)
                if other is None:
                    continue
                p.hedged = True
                targets.append((p, other))
        for p, other in targets:
            metrics.inc("fleet.hedge.sent")
            if spans.is_on():
                spans.event(
                    "hedge", trace=p.trace, lane="router",
                    to_host=other.name,
                )
            self._spawn_run(p, other, hedge=True)

    # -- observability ------------------------------------------------------

    def health(self) -> dict:
        """Fleet snapshot: per-host breaker state + stats + integrity
        score, pending count, and the global admission plane."""
        now = time.monotonic()
        with self._lock:
            hosts = {
                h.name: {
                    "state": h.state,
                    "addr": list(h.addr),
                    "inflight": h.inflight,
                    "queue_depth": h.queue_depth,
                    "fails": h.fails,
                    "probe_pending": h.probe_pending,
                    "burn": h.burn,
                    "score": h.score.snapshot(now),
                }
                for h in self._hosts.values()
            }
            pending = len(self._pending)
            draining = self._draining
        adm = self._admission
        return {
            "hosts": hosts,
            "pending": pending,
            "draining": draining,
            "admission": adm.snapshot() if adm is not None else None,
            "tenants": (
                adm.tenants_health({}, now=now)
                if adm is not None else None
            ),
        }

    def dump_hosts(self, directory: str,
                   timeout: float = 15.0) -> List[dict]:
        """Ask every non-dead host to dump its metrics JSONL + span
        ring into ``directory`` (``host<i>.metrics.jsonl`` /
        ``host<i>.trace.json``) — the fan-in half of stitched
        observability.  Returns the per-host dump replies."""
        with self._lock:
            hosts = [
                h for h in self._hosts.values() if h.state != HOST_DEAD
            ]
        out = []
        for h in hosts:
            try:
                reply, _ = self._rpc(
                    h,
                    {
                        "op": "dump",
                        "label": f"host{h.name}",
                        "metrics": os.path.join(
                            directory, f"host{h.name}.metrics.jsonl"
                        ),
                        "trace": os.path.join(
                            directory, f"host{h.name}.trace.json"
                        ),
                    },
                    timeout=timeout, retries=0,
                )
            except (OSError, SlateError):
                continue
            reply["host"] = h.name
            out.append(reply)
        return out


def _drain_pipe(pipe) -> None:
    try:
        for _ in pipe:
            pass
    except (OSError, ValueError):
        pass


def _rebuild_exc(reply: dict) -> SlateError:
    """Re-raise a worker's typed error as the same class (by name,
    from the serve taxonomy) with its structured context attached."""
    from ..serve import service as _svc
    from .. import exceptions as _exc

    name = reply.get("error") or "SlateError"
    cls = getattr(_svc, name, None)
    if not (isinstance(cls, type) and issubclass(cls, SlateError)):
        cls = getattr(_exc, name, None)
    if not (isinstance(cls, type) and issubclass(cls, SlateError)):
        cls = FleetError
    e = cls(reply.get("message") or name)
    ctx = reply.get("context") or {}
    return e.with_context(**{
        k: ctx[k]
        for k in ("routine", "bucket", "attempt", "tenant", "priority")
        if ctx.get(k) is not None
    })
