"""Gather-fallback accounting (reference behavior: SLATE either runs the
distributed algorithm or fails loudly — it never silently gathers a
distributed matrix to one rank; cf. the redistribution asserts in
src/work/work_trsm.cc and the MPI-collective structure of every driver).

On TPU the gathered-global path is always *available* (GSPMD will insert
collectives), which makes accidental scaling cliffs easy to ship: a
distributed input quietly round-trips through one device's memory.  Every
driver route that abandons the explicit SPMD path for a gathered-global
evaluation on a distributed operand calls :func:`record`:

* by default the fallback is tallied in a process-wide counter
  (:func:`counters`), so tests and the multichip dryrun can assert
  gather-freedom;
* with ``Option.RequireSpmd`` the record raises ``DistributedException``
  instead — the SLATE-style fail-loud contract.

Accounting is TRACE-TIME: it reflects the routing decision taken while
the driver Python executed (eagerly, or during a jit trace).  A cached
jitted executable re-runs whatever route was traced without touching
the counters — so assert gather-freedom on a fresh trace (as
__graft_entry__.dryrun_multichip does), not after warm cache replays.
"""

from __future__ import annotations

from collections import Counter
from typing import Optional

_COUNTS: Counter = Counter()


def record(route: str, opts=None, detail: str = "") -> None:
    """Note that `route` fell back to a gathered global evaluation for a
    distributed operand; raise if the caller demanded SPMD execution."""
    from ..aux import metrics
    from ..enums import Option
    from ..options import get_option

    _COUNTS[route] += 1
    # mirror into the metrics registry (no-op when metrics are off):
    # `fallbacks.gathered` is the aggregate the multichip dryrun greps for
    metrics.inc("fallbacks.gathered")
    metrics.inc(f"fallbacks.{route}")
    if get_option(opts, Option.RequireSpmd, False):
        from ..exceptions import DistributedException

        raise DistributedException(
            f"Option.RequireSpmd: '{route}' would gather a distributed "
            "matrix to a global array"
            + (f" ({detail})" if detail else "")
        )


def counters() -> dict:
    """Snapshot of fallback tallies since the last reset()."""
    return dict(_COUNTS)


def reset() -> None:
    _COUNTS.clear()
