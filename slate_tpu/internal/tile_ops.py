"""Batched tile operations over storage-order tile arrays.

TPU-native equivalents of the reference device kernel set (reference:
src/cuda/device_{geadd,gecopy,gescale,gescale_row_col,geset,transpose,
tzadd,tzcopy,tzscale,tzset}.cu; interface include/slate/internal/device.hh:
92-282).  Where the reference launches one batched CUDA kernel over pointer
arrays grouped by uniform tile size (internal_batch.hh:197-304), here every
op is a single fused XLA elementwise expression over the whole (P, Q, mb,
nb) array — uniform padding makes the batch trivially regular, XLA fuses
the mask logic, and under a sharded array each device touches only its
local tiles.

The tz* (trapezoid) variants take an element mask computed from the
layout's global index maps, generalizing the reference's per-tile uplo +
offset logic to the distributed tile grid in one shot.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..enums import Diag, Uplo
from ..parallel.layout import TileLayout


# -- masks ------------------------------------------------------------------


def tri_mask(
    layout: TileLayout,
    uplo: Uplo,
    diag: Diag = Diag.NonUnit,
    include_valid_only: bool = True,
) -> jnp.ndarray:
    """(P, Q, mb, nb) mask of the uplo triangle (device tz* kernels' uplo
    handling, device_util.cuh / tzset.cu)."""
    gr = jnp.asarray(layout.global_rows_np)[:, None, :, None]
    gc = jnp.asarray(layout.global_cols_np)[None, :, None, :]
    if uplo == Uplo.Lower:
        mask = gr >= gc if diag == Diag.NonUnit else gr > gc
    elif uplo == Uplo.Upper:
        mask = gr <= gc if diag == Diag.NonUnit else gr < gc
    else:
        mask = jnp.ones(np.broadcast_shapes(gr.shape, gc.shape), dtype=bool)
    if include_valid_only:
        mask = mask & layout.element_mask()
    return mask


def diag_mask(layout: TileLayout) -> jnp.ndarray:
    gr = jnp.asarray(layout.global_rows_np)[:, None, :, None]
    gc = jnp.asarray(layout.global_cols_np)[None, :, None, :]
    return (gr == gc) & layout.element_mask()


# -- ge (general) kernels ---------------------------------------------------


def geadd(alpha, A: jnp.ndarray, beta, B: jnp.ndarray) -> jnp.ndarray:
    """B = alpha*A + beta*B (reference: device_geadd.cu; device.hh:92)."""
    return alpha * A + beta * B


def gecopy(A: jnp.ndarray, dtype=None) -> jnp.ndarray:
    """Copy with optional precision conversion (device_gecopy.cu)."""
    return A.astype(dtype) if dtype is not None else A


def gescale(numer, denom, A: jnp.ndarray) -> jnp.ndarray:
    """A *= numer/denom (device_gescale.cu)."""
    return A * (numer / denom)


def gescale_row_col(
    layout: TileLayout, R: Optional[jnp.ndarray], C: Optional[jnp.ndarray], A: jnp.ndarray
) -> jnp.ndarray:
    """A = diag(R) @ A @ diag(C) with global row/col scaling vectors
    (device_gescale_row_col.cu; Equed row/col/both).

    R has length >= m, C length >= n (padded); indexed via the layout's
    global index maps so it works directly on the distributed tile array.
    """
    out = A
    if R is not None:
        gr = jnp.asarray(layout.global_rows_np)  # (P, mb)
        Rt = jnp.take(R, jnp.clip(gr, 0, R.shape[0] - 1), axis=0)
        out = out * Rt[:, None, :, None].astype(A.dtype)
    if C is not None:
        gc = jnp.asarray(layout.global_cols_np)  # (Q, nb)
        Ct = jnp.take(C, jnp.clip(gc, 0, C.shape[0] - 1), axis=0)
        out = out * Ct[None, :, None, :].astype(A.dtype)
    return out


def geset(layout: TileLayout, offdiag_value, diag_value, A: jnp.ndarray) -> jnp.ndarray:
    """Set off-diagonal / diagonal elements (device_geset.cu); padding
    stays zero so norms/gemm on padded arrays remain correct."""
    valid = layout.element_mask()
    dm = diag_mask(layout)
    out = jnp.where(valid, jnp.asarray(offdiag_value, A.dtype), A * 0)
    out = jnp.where(dm, jnp.asarray(diag_value, A.dtype), out)
    return out


# -- tz (trapezoid) kernels -------------------------------------------------


def tzadd(mask, alpha, A, beta, B):
    """B = alpha*A + beta*B on masked region only (device_tzadd.cu)."""
    return jnp.where(mask, alpha * A + beta * B, B)


def tzcopy(mask, A, B, dtype=None):
    """B[mask] = A[mask] (device_tzcopy.cu)."""
    Ac = A.astype(B.dtype if dtype is None else dtype)
    return jnp.where(mask, Ac, B)


def tzscale(mask, numer, denom, A):
    return jnp.where(mask, A * (numer / denom), A)


def tzset(layout: TileLayout, uplo: Uplo, offdiag_value, diag_value, A):
    """Set the uplo triangle (off-diag) + diagonal (device_tzset.cu)."""
    tm = tri_mask(layout, uplo, Diag.Unit)  # strict triangle
    dm = diag_mask(layout)
    out = jnp.where(tm, jnp.asarray(offdiag_value, A.dtype), A)
    out = jnp.where(dm, jnp.asarray(diag_value, A.dtype), out)
    return out


# -- transpose kernels ------------------------------------------------------


def batch_transpose(T: jnp.ndarray, conj: bool = False) -> jnp.ndarray:
    """Per-tile (conj-)transpose of all tiles (device_transpose.cu
    in/out-of-place square + rectangular variants collapse to one XLA op)."""
    out = T.transpose(0, 1, 3, 2)
    if conj and jnp.issubdtype(T.dtype, jnp.complexfloating):
        out = jnp.conj(out)
    return out
