"""Distributed matrix norms over tile arrays.

TPU-native equivalent of the reference's norm stack: device kernels
(src/cuda/device_genorm.cu, device_henorm.cu, device_synorm.cu,
device_trnorm.cu: batched per-tile max/one/inf/fro with per-block
reductions) + internal::norm (src/internal/internal_genorm.cc) + the
MPI allreduce in the norm drivers (src/norm.cc).

Here each norm is one masked XLA reduction over the (P, Q, mb, nb) array;
under a sharded array GSPMD turns the reduction into the ICI psum/pmax
automatically, which replaces the reference's per-device partial reduction
followed by MPI_Allreduce.

fro norms use the scaled ssq (scale, sumsq) update exactly like LAPACK
zlassq (referenced by device_genorm.cu add_sumsq) to avoid overflow.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..enums import Diag, Norm, NormScope, Uplo
from ..exceptions import SlateError
from ..parallel.layout import TileLayout
from .tile_ops import diag_mask, tri_mask


def _abs(A):
    return jnp.abs(A)


def _masked(A, mask, fill=0):
    return jnp.where(mask, A, jnp.asarray(fill, A.dtype))


def _tile_stats(absA, kind: str, pallas_ok: bool):
    """Per-tile norm statistics over the (P, Q, mb, nb) array via the
    Pallas tile-kernel layer (reference: device_genorm.cu's one-pass
    per-block reductions) when the array lives on one TPU chip; plain jnp
    reductions otherwise (multi-device arrays stay on the GSPMD path)."""
    from ..ops.pallas import kernels as pk

    P, Q, mb, nb = absA.shape
    stack = absA.reshape(P * Q, mb, nb)
    if pallas_ok and pk.on_tpu():
        out = pk.tile_norms(stack, kind)
    else:
        out = pk.tile_norms_reference(stack, kind)
    if kind in ("max", "fro_sumsq"):
        return out.reshape(P, Q)
    if kind == "one":
        return out.reshape(P, Q, nb)
    return out.reshape(P, Q, mb)


def _col_sums(absA, layout: TileLayout, pallas_ok: bool = False):
    """Per-global-column sums -> (n,) vector. Tile cols scatter back to
    natural order via the static permutation."""
    sums = _tile_stats(absA, "one", pallas_ok).sum(axis=0)  # (Q, nb)
    nat = sums[layout.col_scatter]  # natural tile order
    return nat.reshape(-1)[: layout.n]


def _row_sums(absA, layout: TileLayout, pallas_ok: bool = False):
    sums = _tile_stats(absA, "inf", pallas_ok).sum(axis=1)  # (P, mb)
    nat = sums[layout.row_scatter]
    return nat.reshape(-1)[: layout.m]


def genorm(
    norm: Norm,
    T: jnp.ndarray,
    layout: TileLayout,
    scope: NormScope = NormScope.Matrix,
    mask: Optional[jnp.ndarray] = None,
    pallas_ok: bool = False,
):
    """General matrix norm (reference: slate::norm -> internal::genorm,
    src/internal/internal_genorm.cc; NormScope enums.hh:514).  With
    pallas_ok (single-chip TPU arrays) the per-tile statistics run in the
    Pallas tile-kernel layer."""
    mask = layout.element_mask() if mask is None else mask
    absA = _masked(_abs(T), mask)
    if scope == NormScope.Columns:
        if norm != Norm.One:
            raise SlateError("column-scope norm supports Norm.One (colNorms)")
        return _col_sums(absA, layout, pallas_ok)
    if scope == NormScope.Rows:
        if norm != Norm.Inf:
            raise SlateError("row-scope norm supports Norm.Inf")
        return _row_sums(absA, layout, pallas_ok)

    if norm == Norm.Max:
        return _tile_stats(absA, "max", pallas_ok).max()
    if norm == Norm.One:
        return _col_sums(absA, layout, pallas_ok).max()
    if norm == Norm.Inf:
        return _row_sums(absA, layout, pallas_ok).max()
    if norm == Norm.Fro:
        # scaled ssq for overflow safety (LAPACK lassq semantics)
        amax = _tile_stats(absA, "max", pallas_ok).max()
        safe = jnp.where(amax == 0, 1, amax)
        scaled = absA / safe
        ssq = _tile_stats(scaled, "fro_sumsq", pallas_ok).sum()
        return jnp.where(
            amax == 0, jnp.asarray(0, safe.dtype), safe * jnp.sqrt(ssq)
        )
    raise SlateError(f"unsupported norm {norm}")


def trnorm(
    norm: Norm,
    T: jnp.ndarray,
    layout: TileLayout,
    uplo: Uplo,
    diag: Diag = Diag.NonUnit,
):
    """Trapezoid/triangular norm (reference: internal_trnorm.cc,
    device_trnorm.cu).  Diag.Unit counts the diagonal as 1."""
    mask = tri_mask(layout, uplo, Diag.NonUnit)
    absA = _masked(_abs(T), mask)
    if diag == Diag.Unit:
        dm = diag_mask(layout)
        absA = jnp.where(dm, jnp.asarray(1, absA.dtype), absA)
    if norm == Norm.Max:
        return absA.max()
    if norm == Norm.One:
        return _col_sums(absA, layout).max()
    if norm == Norm.Inf:
        return _row_sums(absA, layout).max()
    if norm == Norm.Fro:
        amax = absA.max()
        safe = jnp.where(amax == 0, 1, amax)
        scaled = absA / safe
        return jnp.where(
            amax == 0, jnp.asarray(0, safe.dtype), safe * jnp.sqrt((scaled * scaled).sum())
        )
    raise SlateError(f"unsupported norm {norm}")


def synorm(norm: Norm, T: jnp.ndarray, layout: TileLayout, uplo: Uplo):
    """Symmetric norm from one stored triangle (reference:
    internal_synorm.cc, device_synorm.cu).  One == Inf by symmetry; the
    off-diagonal triangle contributes mirrored entries."""
    strict = tri_mask(layout, uplo, Diag.Unit)  # strict triangle
    dm = diag_mask(layout)
    absS = _masked(_abs(T), strict)
    absD = _masked(_abs(T), dm)
    if norm == Norm.Max:
        return jnp.maximum(absS.max(), absD.max())
    if norm in (Norm.One, Norm.Inf):
        # col sums of strict triangle + row sums (mirror) + diagonal
        cs = _col_sums(absS, layout) + _row_sums(absS, layout) + _col_sums(absD, layout)
        return cs.max()
    if norm == Norm.Fro:
        amax = jnp.maximum(absS.max(), absD.max())
        safe = jnp.where(amax == 0, 1, amax)
        s2 = ((absS / safe) ** 2).sum() * 2 + ((absD / safe) ** 2).sum()
        return jnp.where(amax == 0, jnp.asarray(0, safe.dtype), safe * jnp.sqrt(s2))
    raise SlateError(f"unsupported norm {norm}")


def henorm(norm: Norm, T: jnp.ndarray, layout: TileLayout, uplo: Uplo):
    """Hermitian norm (reference: internal_henorm.cc, device_henorm.cu);
    same structure as synorm with |.| of complex entries."""
    return synorm(norm, T, layout, uplo)
