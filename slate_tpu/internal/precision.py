"""Matmul precision policy (SURVEY §7 hard-part (5) for f32).

On TPU the default f32 matmul is a single bf16x bf16 MXU pass (~8e-3
relative unit roundoff) — fine for ML, but LAPACK-parity residual bounds
(error <= tol * eps_f32) require true f32 accumulation, which XLA
provides via precision=HIGHEST (multi-pass).  The reference never faces
this: cuBLAS SGEMM is full f32 by default.

``accurate_matmul`` wraps a driver so every jnp matmul/einsum traced
inside it uses HIGHEST precision whenever a 32-bit float operand is
involved; f64/c128 paths are unaffected (TPU f64 emulation is already
exact-width).  Opt out per-process with SLATE_TPU_FAST_F32=1 to trade
accuracy for the single-pass MXU rate (the TF32-style mode GPUs opt
*into*).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

_F32 = (jnp.dtype("float32"), jnp.dtype("complex64"))


def _has32(x) -> bool:
    dt = getattr(x, "dtype", None)
    if dt is None:
        return False
    try:
        return jnp.dtype(dt) in _F32
    except TypeError:
        return False


def _any32(x) -> bool:
    """Matrix-aware 32-bit scan that also looks INSIDE sequences: the
    mixed drivers pass factor tuples / lists of matrices, which the old
    top-level-only scan treated as "no 32-bit operand" — silently
    running an f32 refinement at bf16-pass precision (the displaced-
    decorator failure mode the activation counter exists to catch)."""
    if isinstance(x, (list, tuple)):
        return any(_any32(e) for e in x)
    if isinstance(x, dict):
        return any(_any32(e) for e in x.values())
    return _has32(x)


def fast_f32() -> bool:
    return os.environ.get("SLATE_TPU_FAST_F32", "0") not in ("", "0")


def accurate_matmul(fn):
    """Decorator: run the driver under default_matmul_precision('highest')
    when any argument (or matrix argument's data) is f32/c64.

    Each activation bumps the ``precision.accurate_matmul_activations``
    metrics counter (a no-op with metrics off), so a displaced decorator
    — an f32 driver silently running at bf16-pass precision, the round-5
    eig.py regression — is visible as a missing count."""

    @functools.wraps(fn)
    def wrapper(*args, **kw):
        if not fast_f32() and any(
            _any32(a) for a in list(args) + list(kw.values())
        ):
            from ..aux import metrics

            metrics.inc("precision.accurate_matmul_activations")
            with jax.default_matmul_precision("highest"):
                return fn(*args, **kw)
        return fn(*args, **kw)

    # marker so tests can assert the policy is attached to a driver
    wrapper._accurate_matmul = True
    return wrapper


# HIGHEST-precision matmul: the TPU f64 emulation's default accumulation
# is ~f32 grade, so every kernel that owes LAPACK-parity accuracy
# contracts through this helper (single point for the precision policy).
import functools as _functools

from jax import lax as _lax

_hdot_raw = _functools.partial(jnp.matmul, precision=_lax.Precision.HIGHEST)

# The chip's f64 emulation additionally LOSES ITS COMPENSATION TERMS on
# cancellation-heavy contractions once the contraction length reaches
# 4096: Q^T Q off-diagonals (sums of +-1e-2 terms cancelling to ~1e-16)
# measure 6.5e-7 ABSOLUTE error at k=4096 vs 1e-15 at k=2048, while
# non-cancelling random products stay at ~1e-13 (round-5 diagnosis;
# tools/profile_* reproduce it).  Chunking the contraction at 2048 and
# accumulating in f64 restores 3.8e-15.  hdot therefore k-chunks every
# emulated-f64 matmul with k >= 4096 — the chunk loop is python-static,
# two extra adds per 8192-contraction, MXU throughput unaffected.
_KCHUNK = 2048
KCHUNK = _KCHUNK  # public alias: sites that chunk non-matmul einsums
_F64 = (jnp.dtype("float64"), jnp.dtype("complex128"))


def emulated_f64(dtype) -> bool:
    """True when `dtype` runs through the TPU f64 emulation (i.e. the
    k-chunk cliff workaround applies); False on real-f64 backends
    (CPU, GPU)."""
    try:
        return (
            jnp.dtype(dtype) in _F64
            and jax.default_backend() not in ("cpu", "gpu")
        )
    except TypeError:
        return False


def hdot(a, b, **kw):
    k = a.shape[-1]
    emul64 = emulated_f64(getattr(a, "dtype", None))
    if not emul64 or k < 2 * _KCHUNK or a.ndim != 2 or b.ndim != 2:
        return _hdot_raw(a, b, **kw)
    acc = None
    for s in range(0, k, _KCHUNK):
        part = _hdot_raw(a[:, s : s + _KCHUNK], b[s : s + _KCHUNK, :], **kw)
        acc = part if acc is None else acc + part
    return acc
