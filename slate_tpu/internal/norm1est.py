"""Hager/Higham 1-norm estimator (reference: src/internal/
internal_norm1est.cc:1-511, used by gecondest/pocondest/trcondest).

Estimates ||B||_1 for an implicitly-given B (e.g. A^-1 via factor solves)
with a handful of solves instead of an explicit O(n^3) inverse — Higham's
algorithm 4.1 (the LAPACK xLACON iteration) as a lax.while_loop: each
iteration is one B-apply and one B^H-apply, both O(n^2) triangular
solves, entirely on-device.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax


def norm1est(
    apply_b: Callable,
    apply_bh: Callable,
    n: int,
    dtype,
    max_iter: int = 5,
) -> jnp.ndarray:
    """Estimate ||B||_1 given x -> B x and x -> B^H x (column vectors).

    Mirrors internal_norm1est.cc's iteration: start from the uniform
    vector, alternate B / B^H applies walking toward a maximizing unit
    column, stop on stagnation; the alternating-sign safeguard vector
    guards against underestimates on special structures.
    """
    complex_t = jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)
    real_t = jnp.zeros((), dtype).real.dtype

    def csign(y):
        if complex_t:
            a = jnp.abs(y)
            return jnp.where(a == 0, jnp.ones_like(y), y / jnp.where(a == 0, 1, a))
        return jnp.where(y >= 0, 1.0, -1.0).astype(dtype)

    x0 = jnp.full((n, 1), 1.0 / n, dtype)
    y0 = apply_b(x0)
    est0 = jnp.sum(jnp.abs(y0)).astype(real_t)

    def cond(state):
        _, _, est, est_old, j, j_old, k = state
        return (k < max_iter) & (est > est_old) & (j != j_old)

    def body(state):
        x, y, est, _, j_old2, _, k = state
        xi = csign(y)
        z = apply_bh(xi)
        j = jnp.argmax(jnp.abs(z))
        x_new = jnp.zeros((n, 1), dtype).at[j, 0].set(1.0)
        y_new = apply_b(x_new)
        est_new = jnp.sum(jnp.abs(y_new)).astype(real_t)
        return (x_new, y_new, jnp.maximum(est_new, est), est, j, j_old2, k + 1)

    state = (x0, y0, est0, jnp.asarray(-1.0, real_t),
             jnp.asarray(-1), jnp.asarray(-2), 0)
    _, _, est, *_ = lax.while_loop(cond, body, state)

    # alternating-sign safeguard (Higham 4.1 final test)
    i = jnp.arange(n, dtype=real_t)
    b = ((-1.0) ** i * (1.0 + i / max(n - 1, 1))).astype(dtype)[:, None]
    v = apply_b(b)
    alt = 2.0 * jnp.sum(jnp.abs(v)).astype(real_t) / (3.0 * n)
    return jnp.maximum(est, alt)
