"""slate_tpu — TPU-native distributed dense linear algebra.

A from-scratch JAX/XLA/Pallas framework with the capabilities of the
reference SLATE library (distributed tiled BLAS3, LU/Cholesky/QR solvers,
least squares, two-stage eigenvalue/SVD, mixed-precision refinement, matrix
generation, ScaLAPACK-compatible surface), designed for TPU pods: tiles are
mesh-sharded arrays, MPI broadcast/reduce becomes ICI collectives under
shard_map, CUDA tile kernels become Pallas/XLA kernels, and the OpenMP
lookahead DAG becomes XLA-pipelined static schedules.
"""

from . import func
from .enums import (
    Diag,
    GridOrder,
    Layout,
    MethodCholQR,
    MethodEig,
    MethodGels,
    MethodGemm,
    MethodHemm,
    MethodLU,
    MethodSVD,
    MethodTrsm,
    Norm,
    NormScope,
    Op,
    Option,
    RefineMethod,
    Schedule,
    Side,
    Target,
    TileKind,
    Uplo,
)
from .exceptions import (
    DimensionError,
    DistributedException,
    NumericalError,
    OptionError,
    SlateError,
)
from .options import get_option, normalize_options
from .parallel.grid import ProcessGrid, default_grid, set_default_grid
from .parallel.layout import TileLayout
from .types import Pivots, TriangularFactors

# matrix classes (reference: include/slate/*Matrix.hh)
from .matrix.base import conj_transpose, transpose
from .matrix.matrix import (
    BandMatrix,
    BaseTrapezoidMatrix,
    HermitianBandMatrix,
    HermitianMatrix,
    Matrix,
    SymmetricMatrix,
    TrapezoidMatrix,
    TriangularBandMatrix,
    TriangularMatrix,
)

# routine surface (reference: include/slate/slate.hh:179-1225)
from .drivers.blas3 import (
    gemm, hemm, symm, herk, her2k, syrk, syr2k, trmm, trsm,
)
from .drivers.aux import (
    add, colNorms, copy, norm, print_matrix, redistribute, scale,
    scale_row_col, set, set_lambdas,
)
from .drivers.chol import (
    pocondest, posv, potrf, potri, potrs, trtri, trtrm,
)
from .drivers.lu import (
    gecondest, gerbt, gesv, gesv_nopiv, gesv_rbt, getrf, getrf_nopiv,
    getri, getrs, getrs_nopiv, trcondest,
)
from .drivers.mixed import (
    gesv_mixed, gesv_mixed_gmres, posv_mixed, posv_mixed_gmres,
)
from .drivers.qr import (
    cholqr, gelqf, gels, geqrf, ungqr, unmlq, unmqr,
)
from .drivers.eig import (
    he2hb, heev, hegst, hegv, stedc, steqr, sterf, sygv, unmtr_he2hb,
)
from .drivers.svd import bdsqr, ge2tb, svd, tb2bd, unmbr_ge2tb_left, unmbr_ge2tb_right
from .drivers.band import (
    gbmm, gbsv, gbtrf, gbtrs, hbmm, pbsv, pbtrf, pbtrs, tbsm,
)
from .drivers.indefinite import hesv, hetrf, hetrs

# matgen (reference: include/slate/generate_matrix.hh)
from .matgen.generate import generate_matrix

# simplified verb API (reference: include/slate/simplified_api.hh)
from . import simplified

# mixed-precision refinement subsystem (policy / IR / GMRES-IR cores)
from . import refine

# serving layer (lazy package: costs nothing until the first request)
from . import serve

# silent-data-corruption defense (ABFT certification, quarantine,
# hedged re-execution — enforcement threads through serve/)
from . import integrity

__version__ = "0.1.0"

__all__ = [name for name in dir() if not name.startswith("_")]
