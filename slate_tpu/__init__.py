"""slate_tpu — TPU-native distributed dense linear algebra.

A from-scratch JAX/XLA/Pallas framework with the capabilities of the
reference SLATE library (distributed tiled BLAS3, LU/Cholesky/QR solvers,
least squares, two-stage eigenvalue/SVD, mixed-precision refinement, matrix
generation, ScaLAPACK-compatible surface), designed for TPU pods: tiles are
mesh-sharded arrays, MPI broadcast/reduce becomes ICI collectives under
shard_map, CUDA tile kernels become Pallas/XLA kernels, and the OpenMP
lookahead DAG becomes XLA-pipelined static schedules.
"""

from . import func
from .enums import (
    Diag,
    GridOrder,
    Layout,
    MethodCholQR,
    MethodEig,
    MethodGels,
    MethodGemm,
    MethodHemm,
    MethodLU,
    MethodSVD,
    MethodTrsm,
    Norm,
    NormScope,
    Op,
    Option,
    Side,
    Target,
    TileKind,
)
from .exceptions import (
    DimensionError,
    DistributedException,
    NumericalError,
    OptionError,
    SlateError,
)
from .options import get_option, normalize_options
from .parallel.grid import ProcessGrid, default_grid, set_default_grid
from .parallel.layout import TileLayout

__version__ = "0.1.0"

__all__ = [name for name in dir() if not name.startswith("_")]
