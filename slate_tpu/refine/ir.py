"""Classical iterative refinement core (reference: the IR loop of
src/gesv_mixed.cc:90-160; Carson & Higham SISC 2018 for the
three-precision convergence analysis the stopping test follows).

Device-resident: one ``lax.while_loop`` instead of ~2 dispatches per
iteration (each of which pays the ~100 ms tunnel latency on this chip);
the host reads back only the final ``(X, iters, converged, berr)``.
Fully traceable — the serve mixed-bucket executables inline this loop
into their jit (the lazy-info contract: nothing here forces a host
sync; the eager drivers in ``drivers/mixed.py`` do the one readback).

Stopping test: the **componentwise backward error** (Oettli–Prager;
Carson & Higham eq. (1.2))

    berr = max_ij |B - A X|_ij / (|A| |X| + |B|)_ij

which, unlike the normwise test the reference uses, certifies the
solution column-by-column and is scale-invariant per entry.  Both the
residual and the denominator are evaluated in the working precision
under ``accurate_matmul`` semantics (``internal.precision.hdot`` —
``Precision.HIGHEST`` plus the emulated-f64 k-chunking), the closest
this hardware has to Carson & Higham's wider residual precision.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax.numpy as jnp
from jax import lax

from ..internal.precision import hdot


class RefineResult(NamedTuple):
    """Device-resident refinement outcome (lazy-info: every field is a
    jax array until a caller forces it)."""

    X: jnp.ndarray  # working-precision solution estimate
    iters: jnp.ndarray  # int32 count of correction steps taken
    converged: jnp.ndarray  # bool: berr <= tol before the budget ran out
    berr: jnp.ndarray  # final componentwise backward error (real scalar)


def residual_berr(
    A2: jnp.ndarray, X: jnp.ndarray, B2: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(R, berr): the working-precision residual B - A X (HIGHEST-
    precision accumulation) and its componentwise backward error
    max |R| / (|A||X| + |B|).  The single definition of the stopping
    test — ir and gmres loop bodies both call it, so the two methods
    cannot drift apart on what "converged" means.  An exactly-zero
    denominator entry (identity padding in serve buckets, zero RHS
    columns) means that entry's residual is exactly zero too, so it
    contributes 0, not 0/0 — guarded with a where, NOT an absolute
    floor (a float literal floor underflows to 0.0 in float32 working
    precision and would NaN every f32/bf16 solve with a zero row)."""
    R = B2 - hdot(A2, X)
    denom = hdot(jnp.abs(A2), jnp.abs(X)) + jnp.abs(B2)
    ratio = jnp.where(denom == 0, 0, jnp.abs(R) / jnp.where(denom == 0, 1, denom))
    return R, ratio.max()


def backward_error(A2: jnp.ndarray, X: jnp.ndarray, B2: jnp.ndarray) -> jnp.ndarray:
    """Componentwise (Oettli–Prager) backward error of X; see
    :func:`residual_berr`."""
    return residual_berr(A2, X, B2)[1]


def refine_while(
    A2: jnp.ndarray,
    B2: jnp.ndarray,
    solve_factor: Callable[[jnp.ndarray], jnp.ndarray],
    tol: float,
    max_it: int,
) -> RefineResult:
    """Classical IR: ``X <- X + solve_factor(B - A X)`` until the
    componentwise backward error drops below ``tol`` or ``max_it``
    correction steps are spent.

    ``solve_factor`` applies the low-precision factors (cast in, solve,
    cast back to working precision).  A run that passes the test on the
    first residual check reports ``iters == 0``; a stalled or diverging
    run reports ``converged == False`` with the last (possibly
    non-finite) berr — the caller owns the fallback decision."""

    def cond(carry):
        _X, it, done, _b = carry
        return (~done) & (it < max_it)

    def body(carry):
        X, it, _done, _b = carry
        R, berr = residual_berr(A2, X, B2)
        conv = berr <= tol
        Xn = jnp.where(conv, X, X + solve_factor(R))
        # count only actual correction steps (parity with the old
        # host-loop accounting in drivers/lu.py)
        return Xn, it + jnp.where(conv, 0, 1), conv, berr

    X0 = solve_factor(B2)
    X, iters, converged, berr = lax.while_loop(
        cond, body, (X0, jnp.int32(0), jnp.bool_(False),
                     jnp.asarray(jnp.inf, jnp.abs(B2).dtype))
    )
    # a budget-exhausted loop exits with the berr of its LAST CHECK, one
    # correction behind X — recheck so `converged` never under-reports.
    # Guarded by cond: the converged (common) path must not pay two
    # extra O(n^2 nrhs) products for a value the select would discard
    # (under vmap — the serve cores — cond lowers to both-branches
    # select, which is no worse than the unconditional recompute).
    final_berr = lax.cond(
        converged, lambda _: berr, lambda _: backward_error(A2, X, B2), None
    )
    return RefineResult(
        X=X,
        iters=iters,
        converged=converged | (final_berr <= tol),
        berr=final_berr,
    )


def ir_refine_while(
    A2, B2, solve_lo, tol, anorm, max_it
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Back-compat shim for the pre-refine/ call sites (drivers/lu.py
    exported this normwise-test loop): same signature, same
    ``(X, iters, converged)`` triple.  NOTE the stopping semantics
    changed with the refine/ extraction: ``tol`` now bounds the
    componentwise backward error ``max |R| / (|A||X| + |B|)``, not the
    old normwise ``|R|max <= tol * anorm * |X|max`` (``anorm`` is kept
    for signature parity and ignored).  The two tests are close for
    well-scaled systems but neither implies the other in general — a
    caller with a normwise-calibrated ``tol`` should migrate to
    :func:`refine_while` and pick ``tol`` for the componentwise test
    (the refine.policy defaults).  A DeprecationWarning fires so the
    semantic change is visible at the call site, not just here."""
    import warnings

    warnings.warn(
        "ir_refine_while now stops on the componentwise backward error "
        "(anorm is ignored); migrate to refine.ir.refine_while and "
        "calibrate tol for the componentwise test",
        DeprecationWarning,
        stacklevel=2,
    )
    del anorm
    res = refine_while(A2, B2, solve_lo, tol, max_it)
    return res.X, res.iters, res.converged
