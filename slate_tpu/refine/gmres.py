"""Restarted GMRES-IR core (reference: src/gesv_mixed_gmres.cc:110-165
— right-preconditioned GMRES per column, restart 30, residual
acceptance test; Carson & Higham SISC 2018 §4 for why preconditioned
GMRES survives ~1/eps_factor more ill-conditioning than classical IR:
the Krylov solve only needs the preconditioned operator
U^-1 L^-1 A ~ I + E to be *solvable*, not the stationary iteration
matrix E to be contractive).

Shape: an outer refinement loop (``lax.while_loop`` — traceable, like
``ir.refine_while``) whose correction step is one GMRES(restart) cycle
per RHS column (vmapped), preconditioned by the low-precision factors
*applied in working precision* (the drivers upcast them once): a
preconditioner applied at eps_factor perturbs the Krylov operator
enough to stall GMRES at berr ~ eps_factor.
The outer loop stops on the same componentwise backward-error test as
classical IR, so the two methods are drop-in interchangeable behind
``Option.RefineMethod``.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..internal.precision import hdot
from .ir import backward_error, residual_berr


class GmresResult(NamedTuple):
    X: jnp.ndarray
    cycles: jnp.ndarray  # int32 GMRES(restart) cycles taken
    converged: jnp.ndarray
    berr: jnp.ndarray


def _gmres_cycle(A2: jnp.ndarray, precond: Callable, r: jnp.ndarray,
                 restart: int) -> jnp.ndarray:
    """One right-preconditioned GMRES(restart) cycle for a single
    column: returns the correction d ~ A^-1 r (zero when r is zero)."""
    n = r.shape[0]
    beta = jnp.linalg.norm(r)
    V = jnp.zeros((restart + 1, n), r.dtype)
    H = jnp.zeros((restart + 1, restart), r.dtype)
    V = V.at[0].set(r / jnp.where(beta == 0, 1, beta))

    def arnoldi(j, carry):
        V, H = carry
        w = hdot(A2, precond(V[j][:, None]))[:, 0]

        def mgs(i, wh):  # modified Gram-Schmidt
            w, H = wh
            hij = jnp.vdot(V[i], w)
            H = H.at[i, j].set(hij)
            return w - hij * V[i], H

        w, H = lax.fori_loop(0, j + 1, mgs, (w, H))
        hn = jnp.linalg.norm(w)
        H = H.at[j + 1, j].set(hn.astype(H.dtype))
        V = V.at[j + 1].set(w / jnp.where(hn == 0, 1, hn))
        return V, H

    V, H = lax.fori_loop(0, restart, arnoldi, (V, H))
    e1 = jnp.zeros(restart + 1, r.dtype).at[0].set(beta.astype(r.dtype))
    y, *_ = jnp.linalg.lstsq(H, e1)
    return precond((V[:restart].T @ y)[:, None])[:, 0]


def gmres_refine(
    A2: jnp.ndarray,
    B2: jnp.ndarray,
    precond: Callable[[jnp.ndarray], jnp.ndarray],
    tol: float,
    restart: int = 30,
    max_cycles: int = 4,
) -> GmresResult:
    """Restarted GMRES-IR: start from X = precond(B), then per cycle
    correct every column with one GMRES(restart) solve of A d = r until
    the componentwise backward error passes ``tol`` or ``max_cycles``
    cycles are spent.  Traceable end to end; the caller owns the
    fallback decision on ``converged == False``."""

    def cond(carry):
        _X, c, done, _b = carry
        return (~done) & (c < max_cycles)

    def body(carry):
        X, c, _done, _b = carry
        R, berr = residual_berr(A2, X, B2)  # the shared stopping test
        conv = berr <= tol
        # a converged check must not pay a dead correction cycle
        # (restart preconditioned matvecs + an lstsq per column —
        # jnp.where would evaluate both operands); lax.cond keeps the
        # final pass O(residual) only
        D = lax.cond(
            conv,
            lambda R: jnp.zeros_like(R),
            lambda R: jax.vmap(
                lambda r: _gmres_cycle(A2, precond, r, restart),
                in_axes=1, out_axes=1,
            )(R),
            R,
        )
        return X + D, c + jnp.where(conv, 0, 1), conv, berr

    X0 = precond(B2)
    X, cycles, converged, berr = lax.while_loop(
        cond, body,
        (X0, jnp.int32(0), jnp.bool_(False),
         jnp.asarray(jnp.inf, jnp.abs(B2).dtype)),
    )
    # recheck only the budget-exhausted exit (see ir.refine_while)
    final_berr = lax.cond(
        converged, lambda _: berr, lambda _: backward_error(A2, X, B2), None
    )
    return GmresResult(
        X=X,
        cycles=cycles,
        converged=converged | (final_berr <= tol),
        berr=final_berr,
    )
