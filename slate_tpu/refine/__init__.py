"""slate_tpu.refine — mixed-precision iterative-refinement solvers.

The fourth major subsystem (alongside ``aux/``, ``serve/``,
``parallel/``): factor once in a cheap precision, refine the solution
in the working precision (reference: SLATE's gesv_mixed /
gesv_mixed_gmres / posv_mixed family, src/gesv_mixed.cc; Carson &
Higham SISC 2018 for the three-precision framework).  TPUs are the
single best target for the idea — the MXU runs bf16/f32 passes several
times faster than the emulated-f64 path the full-precision drivers pay
end to end.

Layout:

* :mod:`.policy` — precision-pair selection (working/factor/residual),
  backend-aware, routed through ``Option.MaxIterations`` /
  ``Option.Tolerance`` / ``Option.UseFallbackSolver`` /
  ``Option.RefineMethod``.
* :mod:`.ir` — classical IR: jit-able ``while_loop`` with the residual
  under ``accurate_matmul`` semantics and a componentwise
  backward-error stopping test.
* :mod:`.gmres` — restarted GMRES-IR preconditioned by the
  low-precision factors (survives ~1/eps_factor more ill-conditioning
  than classical IR).

The user-facing drivers live in :mod:`slate_tpu.drivers.mixed`
(``gesv_mixed``, ``posv_mixed``, ``*_mixed_gmres``); the serving layer
solves warmed buckets in mixed precision via
``BucketKey(precision="mixed")`` with the circuit breaker demoting to
the full-precision direct path on repeated non-convergence.
"""

from .gmres import GmresResult, gmres_refine
from .ir import RefineResult, backward_error, refine_while
from .policy import (
    GMRES_RESTART,
    Policy,
    default_tolerance,
    factor_dtype,
    select,
)

__all__ = [
    "GMRES_RESTART",
    "GmresResult",
    "Policy",
    "RefineResult",
    "backward_error",
    "default_tolerance",
    "factor_dtype",
    "gmres_refine",
    "refine_while",
    "select",
]
