"""Precision-pair selection for mixed-precision refinement.

A mixed-precision solve is shaped by three dtypes (Carson & Higham,
SIAM SISC 2018 — "iterative refinement in three precisions", and the
reference's gesv_mixed.cc which fixes the pair f64/f32):

* **working** — the dtype of the inputs and of the returned solution;
  the accuracy contract is stated in this precision's eps.
* **factor**  — the dtype the O(n^3) factorization runs in.  The whole
  point: on TPU the MXU runs bf16/f32 passes several times faster than
  the emulated-f64 path the full-precision drivers pay end to end.
* **residual** — the dtype the O(n^2) residual is evaluated in.  No
  wider-than-working dtype exists on this hardware, so the residual is
  computed *in* working precision but under ``accurate_matmul``
  semantics (``Precision.HIGHEST`` / ``internal.precision.hdot``),
  which restores the exact-width accumulation Carson & Higham's
  u_r <= u^2 analysis wants from a wider format.

Pairs are backend-aware (:func:`factor_dtype`):

    working      TPU/accelerator factor   CPU factor
    f64 / c128   f32 / c64                f32 / c64
    f32          bfloat16                 f32 (degenerate pair)
    c64          c64 (no complex bf16)    c64 (degenerate pair)

A *degenerate* pair (factor == working) is still well-defined: the
refinement loop converges on the first residual check and the driver
behaves like the direct solver plus one verification matmul — so
``gesv_mixed`` is always safe to call, and the serving layer can key
buckets by precision without per-backend special cases.

Everything is routed through the per-call Options the reference uses
for its mixed drivers: ``Option.MaxIterations`` (default 30),
``Option.Tolerance`` (componentwise-backward-error threshold; default
sqrt(n) * eps_working), ``Option.UseFallbackSolver`` (demote to a
full-precision direct solve on non-convergence, gesv_mixed_gmres.cc:
100-106), plus the slate_tpu extension ``Option.RefineMethod``
(ir | gmres | auto).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..enums import Option, RefineMethod
from ..options import Options, get_option

#: GMRES restart length (reference: gesv_mixed_gmres.cc restart = 30)
GMRES_RESTART = 30

_FACTOR_ACCEL = {
    "float64": "float32",
    "complex128": "complex64",
    "float32": "bfloat16",
    # no complex half format exists; keep the pair degenerate
    "complex64": "complex64",
}
_FACTOR_CPU = {
    "float64": "float32",
    "complex128": "complex64",
    # CPU has no fast bf16 pipe worth a precision cut: degenerate pair
    "float32": "float32",
    "complex64": "complex64",
}


def _backend() -> str:
    import jax

    return jax.default_backend()


def factor_dtype(working, backend: Optional[str] = None):
    """The factorization dtype paired with ``working`` on ``backend``
    (default: the current jax backend).  Returns a numpy dtype for the
    real/complex pairs and the string ``"bfloat16"`` for the f32
    accelerator pair (numpy has no bf16; jnp resolves the name)."""
    name = np.dtype(working).name
    table = _FACTOR_CPU if (backend or _backend()) == "cpu" else _FACTOR_ACCEL
    lo = table.get(name)
    if lo is None:
        raise ValueError(f"no mixed-precision pair for dtype {name!r}")
    return lo if lo == "bfloat16" else np.dtype(lo)


def default_tolerance(working, n: int) -> float:
    """Componentwise-backward-error stopping threshold:
    sqrt(n) * eps_working (the reference's gesv_mixed tolerance scaling;
    the refined berr settles at ~eps, so sqrt(n) headroom is ample
    without admitting an unconverged solution)."""
    return float(math.sqrt(max(n, 1)) * np.finfo(np.dtype(working)).eps)


@dataclass(frozen=True)
class Policy:
    """One resolved mixed-precision solve configuration."""

    working: str  # canonical numpy dtype name, e.g. "float64"
    factor: str  # factorization dtype name (may be "bfloat16")
    residual: str  # residual dtype name (== working on this hardware)
    method: str  # "ir" | "gmres"
    max_iterations: int
    tolerance: float  # componentwise backward-error threshold
    use_fallback: bool
    restart: int = GMRES_RESTART

    @property
    def degenerate(self) -> bool:
        """factor == working: no precision cut (CPU f32/c64 pairs)."""
        return self.factor == self.working

    def factor_cast(self, x):
        """Cast an array to the factor dtype (resolves "bfloat16"
        through jnp, which numpy cannot spell)."""
        import jax.numpy as jnp

        return x.astype(jnp.dtype(self.factor))


def select(
    working,
    n: int,
    opts: Optional[Options] = None,
    method_default: RefineMethod = RefineMethod.Auto,
    backend: Optional[str] = None,
) -> Policy:
    """Resolve the full policy for one solve: the precision pair for
    ``working`` on the current backend plus the Option-routed knobs.
    ``method_default`` lets the ``*_mixed_gmres`` drivers force GMRES
    while still honoring an explicit ``Option.RefineMethod``."""
    wname = np.dtype(working).name
    lo = factor_dtype(working, backend)
    method = get_option(opts, Option.RefineMethod, None)
    if method is None or method is RefineMethod.Auto or method == "auto":
        method = method_default
    if isinstance(method, str):
        method = RefineMethod.from_string(method)
    if method is RefineMethod.Auto:
        method = RefineMethod.IR
    max_it = int(get_option(opts, Option.MaxIterations, 30))
    tol = get_option(opts, Option.Tolerance, None)
    if tol is None:
        tol = default_tolerance(working, n)
    return Policy(
        working=wname,
        factor=lo if isinstance(lo, str) else np.dtype(lo).name,
        residual=wname,
        method=method.value,
        max_iterations=max_it,
        tolerance=float(tol),
        use_fallback=bool(get_option(opts, Option.UseFallbackSolver, True)),
    )
