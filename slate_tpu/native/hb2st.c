/* Native CPU bulge-chasing stage 2: Hermitian band -> symmetric
 * tridiagonal (real double).
 *
 * TPU-framework analogue of the reference's CPU-threaded hb2st
 * (reference: src/hb2st.cc:44-187 runs the chase with host threads over
 * a band GATHERED TO ONE NODE — stage 2 is deliberately a single-node
 * CPU stage there too, heev.cc:135).  On this toolchain the on-chip
 * superstep wavefront (ops/bulge.py) is dispatch-latency-bound at
 * ~4 ms x 3n supersteps, while the same arithmetic on the host core is
 * a few seconds: this file is the default stage-2 engine for real f64;
 * ops/bulge.py remains the jittable/portable fallback.
 *
 * Semantics mirror ops/bulge.py's chase_window exactly (same task grid,
 * same larfg, same eliminated-column overwrite), so VS/TAUS feed the
 * SAME on-chip unmtr_hb2st back-transform.
 *
 * Band storage (column-major band, C layout): Wt[c*ldw + d] = A[c+d, c]
 * for d in [0, 2b] (ldw = 2b+1) — the transpose of ops/bulge.py's
 * diagonal-major W, chosen so a column's band entries are contiguous.
 *
 * Task (s, j):  j = 0 head: w0 = s,              r0 = 1
 *               j >= 1:     w0 = s + (j-1)b + 1, r0 = b
 * Reflector rows R = [R0, R0 + b), R0 = w0 + r0 = s + j b + 1; tasks
 * exist while R0 <= n - 2.  The window is cols [w0, w0 + L), L = 3b+1.
 * The two-sided update H A H with H = I - tau v v^T (v on R) touches
 * stored entries only in cols [w0, w0 + 2b) and rows < w0 + L (entries
 * beyond stay zero — same invariant the jax wavefront's truncated
 * write-back relies on).
 *
 * Correct execution order here is the plain sequential one (sweep s
 * fully chased before sweep s+1) — the wavefront in ops/bulge.py is
 * just a parallel-safe reordering of this.
 */

#include <math.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

static void chase_task_d(double *restrict Wt, int64_t ldw, int64_t n_pad,
                         int64_t b, int64_t w0, int64_t r0,
                         double *restrict S, double *restrict v,
                         double *restrict wvec, double *restrict tau_out) {
  const int64_t L = 3 * b + 1;
  const int64_t R0 = w0 + r0;
  const int64_t twob = 2 * b;

  /* -- reflector of column w0, rows [R0, R0+b) (contiguous in Wt) -- */
  const double *colw0 = Wt + w0 * ldw;
  double alpha = colw0[r0];
  double xnorm_sq = 0.0;
  for (int64_t k = 1; k < b; ++k) {
    double xk = colw0[r0 + k];
    xnorm_sq += xk * xk;
  }
  double norm = sqrt(alpha * alpha + xnorm_sq);
  double beta = (alpha == 0.0 ? 1.0 : (alpha > 0.0 ? 1.0 : -1.0)) * -norm;
  int live = norm > 0.0;
  if (!live) beta = alpha;
  double tau = live ? (beta - alpha) / beta : 0.0;
  double scale =
      live ? 1.0 / (alpha == beta ? 1.0 : alpha - beta) : 0.0;
  v[0] = 1.0;
  for (int64_t k = 1; k < b; ++k) v[k] = colw0[r0 + k] * scale;
  *tau_out = tau;

  /* -- S = A[R, w0 : w0+L) from band storage (symmetry for upper).
   * Gathered in two CONTIGUOUS-band passes: the row-major elementwise
   * gather read Wt at stride ldw (a fresh cache line per element) and
   * was the measured runtime of the whole chase (~85% at n=4096).
   * Lower part (r >= cg): each window column holds one contiguous d-run
   * of the rows in R.  Upper part (r < cg): A[r, cg] = A[cg, r], read
   * straight down stored column r.  S writes in the lower pass walk b
   * distinct lines (stride L) that consecutive columns re-hit, so they
   * stay L1-resident. -- */
  for (int64_t c = 0; c < r0 + b; ++c) {
    const int64_t cg = w0 + c;
    const double *col = Wt + cg * ldw;
    const int64_t k_lo = c > r0 ? c - r0 : 0;
    int64_t d = r0 + k_lo - c; /* = max(r0 - c, 0), <= 2b always */
    for (int64_t k = k_lo; k < b; ++k, ++d) S[k * L + c] = col[d];
  }
  for (int64_t k = 0; k < b; ++k) {
    const int64_t r = R0 + k;
    const double *col = Wt + r * ldw;
    double *Sk = S + k * L;
    const int64_t c0 = r0 + k + 1; /* first upper column, < L */
    int64_t cend = c0 + twob - 1;  /* last in-band column */
    if (cend > L - 1) cend = L - 1;
    int64_t dd = 1;
    for (int64_t c = c0; c <= cend; ++c, ++dd) Sk[c] = col[dd];
    for (int64_t c = cend + 1; c < L; ++c) Sk[c] = 0.0;
  }

  /* -- left update S <- (I - tau v v^T) S -- */
  for (int64_t c = 0; c < L; ++c) wvec[c] = 0.0;
  for (int64_t k = 0; k < b; ++k) {
    const double vk = v[k];
    const double *Sk = S + k * L;
    for (int64_t c = 0; c < L; ++c) wvec[c] += vk * Sk[c];
  }
  for (int64_t k = 0; k < b; ++k) {
    const double tvk = tau * v[k];
    double *Sk = S + k * L;
    for (int64_t c = 0; c < L; ++c) Sk[c] -= tvk * wvec[c];
  }

  /* -- right update on the R x R block B = S[:, r0 : r0+b) -- */
  for (int64_t k = 0; k < b; ++k) {
    double *Bk = S + k * L + r0;
    double y = 0.0;
    for (int64_t m = 0; m < b; ++m) y += Bk[m] * v[m];
    const double ty = tau * y;
    for (int64_t m = 0; m < b; ++m) Bk[m] -= ty * v[m];
  }

  /* -- write back modified stored entries (cols [w0, w0+2b)) -- */
  /* cols left of R: rows in R got the left update */
  for (int64_t c = 0; c < r0; ++c) {
    const int64_t cg = w0 + c;
    double *col = Wt + cg * ldw;
    /* stored rows r = cg + d with r in [R0, R0+b): d = R0-cg+k <= 2b */
    const int64_t d0 = R0 - cg;
    const int64_t kmax = (twob - d0 < b - 1) ? twob - d0 : b - 1;
    for (int64_t k = 0; k <= kmax; ++k) col[d0 + k] = S[k * L + c];
  }
  /* cols in R: rows in R from the two-sided block; rows below R_end
   * from the right-update fill via symmetry (S row c-r0, col r-w0) */
  for (int64_t c = r0; c < r0 + b; ++c) {
    const int64_t cg = w0 + c;
    double *col = Wt + cg * ldw;
    const int64_t rend = R0 + b; /* first row past R */
    for (int64_t d = 0; d <= twob; ++d) {
      const int64_t r = cg + d;
      if (r < rend) {
        col[d] = S[(r - R0) * L + c];
      } else if (r - w0 < L) {
        col[d] = S[(c - r0) * L + (r - w0)];
      } else {
        break; /* beyond the window: provably still zero */
      }
    }
  }
  /* exact eliminated-column pattern (numerics hygiene, as in jax) */
  {
    double *col = Wt + w0 * ldw;
    col[r0] = beta;
    for (int64_t k = 1; k < b; ++k) col[r0 + k] = 0.0;
  }
}

/* Chase sweeps [s_begin, s_end) of the band in Wt.  VS: (n_sweeps,
 * jmax1, b), TAUS: (n_sweeps, jmax1), both zero-initialized by the
 * caller.  Sequential ranged calls over a persistent Wt reproduce the
 * full chase exactly (the chase state IS the band; sweeps are chased
 * in order), letting the caller overlap uploads of completed VS/TAUS
 * rows with the next range's compute.  Returns 0 on success. */
int slate_hb2st_range_d(double *restrict Wt, int64_t n, int64_t n_pad,
                        int64_t b, double *restrict VS,
                        double *restrict TAUS, int64_t n_sweeps,
                        int64_t jmax1, int64_t s_begin, int64_t s_end) {
  if (n <= 2 || b <= 1) return 0;
  const int64_t ldw = 2 * b + 1;
  const int64_t L = 3 * b + 1;
  if (n_pad < n + 3 * b) return 1;
  if (s_begin < 0 || s_end > n_sweeps || s_begin > s_end) return 3;
  double *S = (double *)malloc((size_t)(b * L) * sizeof(double));
  double *v = (double *)malloc((size_t)b * sizeof(double));
  double *wvec = (double *)malloc((size_t)L * sizeof(double));
  if (!S || !v || !wvec) {
    free(S); free(v); free(wvec);
    return 2;
  }
  /* Multi-sweep blocking: chase NSW staggered sweeps per block in the
   * proven wavefront order (task (s, j) at t = 3 s + j).  Plain
   * sweep-major order streams the whole O(n b) band once per sweep
   * (~34 GB of strided traffic at n=4096); inside a block the NSW
   * staggered windows overlap (offset b columns), so the band streams
   * roughly once per BLOCK.  Only disjoint-window tasks are reordered
   * relative to sweep-major, so results are bit-identical. */
  const int64_t NSW = 8;
  for (int64_t s0 = s_begin; s0 < s_end; s0 += NSW) {
    const int64_t smax = (s_end - s0 < NSW) ? s_end - s0 : NSW;
    const int64_t tmax = 3 * (smax - 1) + jmax1 - 1;
    for (int64_t t = 0; t <= tmax; ++t) {
      for (int64_t i = (t >= jmax1) ? (t - jmax1) / 3 + 1 : 0;
           i < smax && t - 3 * i >= 0; ++i) {
        const int64_t s = s0 + i;
        const int64_t j = t - 3 * i;
        const int64_t R0 = s + j * b + 1;
        if (R0 > n - 2) continue;
        const int64_t w0 = (j == 0) ? s : s + (j - 1) * b + 1;
        const int64_t r0 = (j == 0) ? 1 : b;
        double tau;
        chase_task_d(Wt, ldw, n_pad, b, w0, r0, S, v, wvec, &tau);
        /* OVERLAP CONTRACT (pairs with the assertion at the async
         * device_put in native/__init__.py): s ranges over
         * [s_begin, s_end) only, so this memcpy writes only VS/TAUS
         * rows of sweeps in [s_begin, s_end) — rows of earlier sweeps
         * are final and may be uploading concurrently. */
        memcpy(VS + (s * jmax1 + j) * b, v, (size_t)b * sizeof(double));
        TAUS[s * jmax1 + j] = tau;
      }
    }
  }
  free(S); free(v); free(wvec);
  return 0;
}

/* Whole-chase convenience wrapper (the original entry point). */
int slate_hb2st_d(double *restrict Wt, int64_t n, int64_t n_pad, int64_t b,
                  double *restrict VS, double *restrict TAUS,
                  int64_t n_sweeps, int64_t jmax1) {
  return slate_hb2st_range_d(Wt, n, n_pad, b, VS, TAUS, n_sweeps, jmax1,
                             0, n_sweeps);
}
