"""On-demand compiled native (host CPU) kernels.

The reference runs its stage-2 eigensolver kernels as CPU-threaded
native code over a gathered band (reference: src/hb2st.cc:44-187,
src/heev.cc:135); this package holds the framework's equivalents,
compiled from C at first use with the system compiler and loaded via
ctypes.  Every entry degrades gracefully: if no compiler is available
the callers fall back to the jittable on-device implementations.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import tempfile
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_lib = None
_lib_tried = False


def _build_dir() -> str:
    d = os.environ.get("SLATE_TPU_NATIVE_CACHE")
    if not d:
        d = os.path.join(
            os.path.expanduser("~"), ".cache", "slate_tpu_native"
        )
    os.makedirs(d, exist_ok=True)
    return d


def load() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the native kernel library, or None."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("SLATE_TPU_NO_NATIVE"):
        return None
    cc = shutil.which("cc") or shutil.which("gcc") or shutil.which("clang")
    if cc is None:
        return None
    src = os.path.join(_DIR, "hb2st.c")
    # key the cache by source + compiler + flags + microarchitecture:
    # -march=native binaries must not be shared across hosts (NFS homes)
    # and must rebuild when the source or toolchain changes
    import hashlib
    import platform

    flags = ["-O3", "-march=native", "-fPIC", "-shared"]
    with open(src, "rb") as f:
        key = hashlib.sha256(
            f.read()
            + cc.encode()
            + " ".join(flags).encode()
            + platform.machine().encode()
            + platform.node().encode()
        ).hexdigest()[:16]
    out = os.path.join(_build_dir(), f"libslate_tpu_native_{key}.so")
    try:
        if not os.path.exists(out):
            fd, tmp = tempfile.mkstemp(
                suffix=".so", dir=os.path.dirname(out)
            )
            os.close(fd)
            cmd = [cc, *flags, src, "-lm", "-o", tmp]
            r = subprocess.run(cmd, capture_output=True, text=True)
            if r.returncode != 0:
                os.unlink(tmp)
                return None
            os.replace(tmp, out)
        lib = ctypes.CDLL(out)
        lib.slate_hb2st_d.restype = ctypes.c_int
        lib.slate_hb2st_d.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_int64,
        ]
        lib.slate_hb2st_range_d.restype = ctypes.c_int
        lib.slate_hb2st_range_d.argtypes = (
            lib.slate_hb2st_d.argtypes + [ctypes.c_int64, ctypes.c_int64]
        )
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def hb2st_available() -> bool:
    return load() is not None


def hb2st_host(W, n: int, b: int):
    """Run the native bulge chase on diagonal-major band storage W
    ((2b+1, n_pad) numpy f64).  Returns (d, e, VS, TAUS) as numpy
    arrays with the exact shapes/semantics of ops.bulge.hb2st's real
    path.  Raises RuntimeError if the native library is unavailable.
    """
    import numpy as np

    lib = load()
    if lib is None:
        raise RuntimeError("native hb2st unavailable")
    W = np.asarray(W, dtype=np.float64)
    n_pad = W.shape[1]
    # column-major band (contiguous columns) for the C kernel
    Wt = np.ascontiguousarray(W.T)
    n_sweeps = max(n - 2, 1)
    jmax1 = (n - 3) // b + 2 if n > 2 else 1  # Jmax + 1
    VS = np.zeros((n_sweeps, jmax1, b), np.float64)
    TAUS = np.zeros((n_sweeps, jmax1), np.float64)
    if n > 2 and b >= 2:
        rc = lib.slate_hb2st_d(
            Wt.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            n, n_pad, b,
            VS.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            TAUS.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            n_sweeps, jmax1,
        )
        if rc != 0:
            raise RuntimeError(f"slate_hb2st_d failed rc={rc}")
    d = Wt[:n, 0].copy()
    e = Wt[: n - 1, 1].copy()
    return d, e, VS, TAUS


def hb2st_host_device(W, n: int, b: int, chunk_sweeps: int = 1024):
    """Chunked chase with the reflector uploads OVERLAPPED: after each
    sweep range completes, its VS/TAUS rows go to an async
    jax.device_put while the next range chases (the transfer drains
    during the GIL-releasing ctypes call).  The upload is the larger
    half of stage 2 at n=8192 (537 MB over the tunnel vs ~24 s of
    chase); sequential ranged calls over the persistent band are
    exactly the full chase.  Returns (d, e, VS_dev, TAUS_dev) with the
    reflectors already device-resident."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    lib = load()
    if lib is None:
        raise RuntimeError("native hb2st unavailable")
    W = np.asarray(W, dtype=np.float64)
    n_pad = W.shape[1]
    Wt = np.ascontiguousarray(W.T)
    n_sweeps = max(n - 2, 1)
    jmax1 = (n - 3) // b + 2 if n > 2 else 1
    VS = np.zeros((n_sweeps, jmax1, b), np.float64)
    TAUS = np.zeros((n_sweeps, jmax1), np.float64)
    from ..aux import metrics

    vs_parts, tau_parts = [], []
    if n > 2 and b >= 2:
        for s0 in range(0, n_sweeps, chunk_sweeps):
            s1 = min(n_sweeps, s0 + chunk_sweeps)
            rc = lib.slate_hb2st_range_d(
                Wt.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                n, n_pad, b,
                VS.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                TAUS.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                n_sweeps, jmax1, s0, s1,
            )
            if rc != 0:
                raise RuntimeError(f"slate_hb2st_range_d failed rc={rc}")
            # OVERLAP CONTRACT (pairs with the VS memcpy in hb2st.c's
            # chase loop): slate_hb2st_range_d writes reflector rows only
            # for sweeps s in [s_begin, s_end), so rows [s0, s1) are
            # final here and the async upload below can drain while the
            # NEXT range computes rows >= s1.  Guard the contract on the
            # cheap TAUS proxy: any nonzero tau at a sweep >= s1 means
            # the C kernel wrote outside its range and the uploaded VS
            # rows may be racing the chase.
            assert s1 >= n_sweeps or not TAUS[s1:].any(), (
                "hb2st range contract violated: tau written beyond "
                f"sweep {s1}"
            )
            vs_parts.append(jax.device_put(VS[s0:s1]))
            tau_parts.append(jax.device_put(TAUS[s0:s1]))
            metrics.inc(
                "transfer.h2d_bytes", VS[s0:s1].nbytes + TAUS[s0:s1].nbytes
            )
    if not vs_parts:
        VSd, TAUSd = jnp.asarray(VS), jnp.asarray(TAUS)
    elif len(vs_parts) == 1:
        VSd, TAUSd = vs_parts[0], tau_parts[0]
    else:
        VSd = jnp.concatenate(vs_parts, axis=0)
        TAUSd = jnp.concatenate(tau_parts, axis=0)
    d = Wt[:n, 0].copy()
    e = Wt[: n - 1, 1].copy()
    return d, e, VSd, TAUSd
