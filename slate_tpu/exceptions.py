"""Exception hierarchy (reference: include/slate/Exception.hh).

The MPI/CUDA-specific exception subclasses of the reference map onto a
single DistributedException here: XLA collective failures surface as jax
runtime errors and are wrapped where we can add context.
"""

from __future__ import annotations

from typing import Optional


class SlateError(Exception):
    """Base error for slate_tpu (reference: slate::Exception, Exception.hh).

    Structured context: layers that resolve request futures (serve/) or
    dispatch drivers attach ``routine``, ``bucket`` (BucketKey label),
    ``attempt``, and — on a tenancy-enabled service — ``tenant`` /
    ``priority`` via :meth:`with_context` wherever an exception is
    set, so operators can triage a failure from the exception object
    alone instead of scraping logs.  The fields render in ``str(e)``
    and stay machine-readable on the instance (:meth:`context`).
    """

    routine: Optional[str] = None
    bucket: Optional[str] = None
    attempt: Optional[int] = None
    tenant: Optional[str] = None
    priority: Optional[str] = None  # class name: high | normal | low

    def with_context(
        self,
        routine: Optional[str] = None,
        bucket: Optional[str] = None,
        attempt: Optional[int] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
    ) -> "SlateError":
        """Attach structured context; returns ``self`` for chaining
        (``raise InvalidInput(msg).with_context(routine="gesv")``)."""
        if routine is not None:
            self.routine = routine
        if bucket is not None:
            self.bucket = bucket
        if attempt is not None:
            self.attempt = int(attempt)
        if tenant is not None:
            self.tenant = tenant
        if priority is not None:
            self.priority = str(priority)
        return self

    def context(self) -> dict:
        """The context fields that are set (empty dict when none are)."""
        return {
            k: v
            for k, v in (
                ("routine", self.routine),
                ("bucket", self.bucket),
                ("attempt", self.attempt),
                ("tenant", self.tenant),
                ("priority", self.priority),
            )
            if v is not None
        }

    def __str__(self) -> str:
        base = super().__str__()
        ctx = self.context()
        if not ctx:
            return base
        tail = " ".join(f"{k}={v}" for k, v in ctx.items())
        return f"{base} [{tail}]"


class DimensionError(SlateError):
    """Shape/conformability violation in a routine's arguments."""


class OptionError(SlateError):
    """Bad Option key/value."""


class DistributedException(SlateError):
    """Failure in the distributed runtime (mesh/collective layer).

    Reference analogue: slate::MpiException (mpi.hh:16-35)."""


class InvalidInput(SlateError):
    """Admission-time rejection: malformed or non-finite operands,
    refused before any queue/compile cost is paid (serve layer)."""


class NumericalError(SlateError):
    """Numerical failure carrying an `info` code, e.g. a non-SPD matrix in
    potrf or a singular U(i,i) in getrf (reference: internal::reduce_info +
    info returns, src/internal/internal_reduce_info.cc)."""

    def __init__(self, message: str, info: int = 0):
        super().__init__(message)
        self.info = int(info)


def slate_assert(cond: bool, message: str = "assertion failed") -> None:
    """Host-side invariant check (reference: slate_assert, Exception.hh)."""
    if not cond:
        raise SlateError(message)
