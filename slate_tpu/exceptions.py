"""Exception hierarchy (reference: include/slate/Exception.hh).

The MPI/CUDA-specific exception subclasses of the reference map onto a
single DistributedException here: XLA collective failures surface as jax
runtime errors and are wrapped where we can add context.
"""

from __future__ import annotations


class SlateError(Exception):
    """Base error for slate_tpu (reference: slate::Exception, Exception.hh)."""


class DimensionError(SlateError):
    """Shape/conformability violation in a routine's arguments."""


class OptionError(SlateError):
    """Bad Option key/value."""


class DistributedException(SlateError):
    """Failure in the distributed runtime (mesh/collective layer).

    Reference analogue: slate::MpiException (mpi.hh:16-35)."""


class NumericalError(SlateError):
    """Numerical failure carrying an `info` code, e.g. a non-SPD matrix in
    potrf or a singular U(i,i) in getrf (reference: internal::reduce_info +
    info returns, src/internal/internal_reduce_info.cc)."""

    def __init__(self, message: str, info: int = 0):
        super().__init__(message)
        self.info = int(info)


def slate_assert(cond: bool, message: str = "assertion failed") -> None:
    """Host-side invariant check (reference: slate_assert, Exception.hh)."""
    if not cond:
        raise SlateError(message)
