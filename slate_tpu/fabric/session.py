"""Streaming least-squares sessions: factor once, append rows, solve
on demand.

A :class:`FactorSession` owns one evolving tall system ``min ||A x -
B||``.  Its lifecycle has two regimes:

- **pristine** (no rows appended yet): every ``solve`` routes through
  the owning service's normal ``submit("gels", ...)`` path, so the
  repeated-A factor cache, the warmed ``phase="solve"`` bucket, and
  the device arena (:mod:`~slate_tpu.fabric.arena`) all apply — the
  steady state is compile-free and upload-free.
- **streamed** (after ``append``): the session maintains the n x n
  triangular factor R of the growing A host-side and folds each
  appended row block in via Householder reflections restricted to the
  new rows — O(k n^2) per k-row append instead of the O(m n^2)
  refactor.  Dirty solves use the corrected seminormal equations
  (R^H y = A^H B, R x = y, plus one refinement sweep), which is
  backward-stable for the well-conditioned systems the fence admits.

Every dirty solve is fenced by the same componentwise-backward-error
residual check the serving tier uses
(:func:`~slate_tpu.serve.factor_cache.residual_ok`, gels branch).  A
fence failure — or an update breakdown (non-finite / collapsed
diagonal) — triggers a **counted refactor** (``fabric.session.
refactor``) and a retry; the session never returns a wrong X.  If even
the fresh factor fails the fence the solve raises
:class:`~slate_tpu.exceptions.NumericalError`.

Metrics (all under ``fabric.session.``): ``factor`` (full R builds),
``update`` (append calls), ``update_rows`` (rows folded in),
``solve``, ``refactor``, ``fence_fail``.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..aux import faults, metrics, sync
from ..exceptions import DimensionError, InvalidInput, NumericalError

__all__ = ["FactorSession"]


def _solve_upper(R: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Back-substitution X for upper-triangular R X = B (B: n x nrhs)."""
    n = R.shape[0]
    X = np.array(B, dtype=np.result_type(R.dtype, B.dtype))
    for i in range(n - 1, -1, -1):
        if i + 1 < n:
            X[i] -= R[i, i + 1:] @ X[i + 1:]
        X[i] /= R[i, i]
    return X


def _solve_upper_h(R: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Forward-substitution X for R^H X = B (R upper => R^H lower)."""
    n = R.shape[0]
    X = np.array(B, dtype=np.result_type(R.dtype, B.dtype))
    for i in range(n):
        if i:
            X[i] -= R[:i, i].conj() @ X[:i]
        X[i] /= np.conj(R[i, i])
    return X


def _update_r(R: np.ndarray, C: np.ndarray) -> None:
    """Fold k appended rows C into the triangular factor R in place.

    One Householder reflection per column, restricted to the pivot
    R[j, j] and the k new rows — the classical row-append QR update:
    after the sweep, R is the triangular factor of [[R_old], [C]]
    (equivalently of the grown A), and C is destroyed.  O(k n^2).
    """
    n = R.shape[1]
    for j in range(n):
        alpha = R[j, j]
        x = C[:, j]
        xnorm2 = float(np.vdot(x, x).real)
        if xnorm2 == 0.0:
            continue
        mu = math.sqrt(abs(alpha) ** 2 + xnorm2)
        if alpha == 0:
            beta = -mu
            tau = 1.0
        else:
            beta = -(alpha / abs(alpha)) * mu
            tau = (beta - alpha) / beta
        v2 = x / (alpha - beta)
        if j + 1 < n:
            s = R[j, j + 1:] + v2.conj() @ C[:, j + 1:]
            R[j, j + 1:] -= tau * s
            C[:, j + 1:] -= np.outer(v2, tau * s)
        R[j, j] = beta


class FactorSession:
    """One streaming gels system bound to a serving tier.

    Created via ``serve.session(routine="gels")`` (serve/api.py) or
    directly with a :class:`~slate_tpu.serve.service.SolverService`.
    Thread-safe: one lock serializes append/solve/refactor.
    """

    def __init__(self, service, A, routine: str = "gels",
                 schedule: str = "auto"):
        if routine != "gels":
            raise InvalidInput(
                f"session: routine must be 'gels', got {routine!r} "
                "(streaming row appends are a least-squares notion)"
            ).with_context(routine=routine)
        A = np.array(A)  # owned host copy — the session's A grows
        if A.ndim != 2 or A.shape[0] < A.shape[1]:
            raise DimensionError(
                "session: A must be 2-D with m >= n (tall least "
                f"squares), got shape {A.shape}"
            ).with_context(routine="gels")
        if not np.all(np.isfinite(A)):
            raise InvalidInput(
                "session: A contains non-finite entries"
            ).with_context(routine="gels")
        self._svc = service
        self._schedule = schedule
        self._lock = sync.RLock(name="fabric.FactorSession._lock")
        # guarded by: _lock
        self._A = A
        self._R: Optional[np.ndarray] = None  # lazy — built on append
        self._pristine = True
        self._solves = 0
        self._updates = 0
        self._refactors = 0

    # -- introspection -----------------------------------------------------

    @property
    def shape(self):
        with self._lock:
            return tuple(self._A.shape)

    @property
    def pristine(self) -> bool:
        """True until the first ``append`` — pristine solves ride the
        service's factor-cache/arena fast path."""
        with self._lock:
            return self._pristine

    def stats(self) -> dict:
        with self._lock:
            return {
                "rows": int(self._A.shape[0]),
                "n": int(self._A.shape[1]),
                "pristine": self._pristine,
                "solves": self._solves,
                "updates": self._updates,
                "refactors": self._refactors,
            }

    # -- factor maintenance ------------------------------------------------

    def _factor_locked(self) -> None:
        """(Re)build R from the full current A — the counted fallback."""
        # mode="r" gives the n x n triangle; sign conventions are
        # irrelevant downstream (CSNE only uses R^H R = A^H A)
        self._R = np.array(np.linalg.qr(self._A, mode="r")[:self._A.shape[1]])
        metrics.inc("fabric.session.factor")

    def _breakdown_locked(self) -> bool:
        """True when the maintained R can no longer be trusted: a
        non-finite entry or a collapsed diagonal (rank loss the
        Householder sweep cannot see across columns)."""
        R = self._R
        if R is None:
            return False
        if not np.all(np.isfinite(R)):
            return True
        d = np.abs(np.diagonal(R))
        scale = float(np.max(np.abs(R))) if R.size else 0.0
        eps = float(np.finfo(R.dtype).eps)
        return bool(d.size and float(np.min(d)) <= R.shape[1] * eps * scale)

    def _refactor_locked(self) -> None:
        metrics.inc("fabric.session.refactor")
        self._refactors += 1
        self._factor_locked()

    def append(self, C) -> None:
        """Append k rows to A and fold them into R in O(k n^2).

        Marks the session dirty: subsequent solves use the maintained
        factor host-side (fenced) instead of the service bucket path.
        An update breakdown is repaired immediately by a counted
        refactor — ``append`` never leaves a corrupt R behind.
        """
        C = np.atleast_2d(np.asarray(C))
        with self._lock:
            n = self._A.shape[1]
            if C.ndim != 2 or C.shape[1] != n:
                raise DimensionError(
                    f"session.append: rows must have {n} columns, got "
                    f"shape {C.shape}"
                ).with_context(routine="gels")
            if not np.all(np.isfinite(C)):
                raise InvalidInput(
                    "session.append: rows contain non-finite entries"
                ).with_context(routine="gels")
            dt = np.result_type(self._A.dtype, C.dtype)
            if self._R is None:
                if self._A.dtype != dt:
                    self._A = self._A.astype(dt)
                self._factor_locked()
            elif self._R.dtype != dt:
                self._R = self._R.astype(dt)
                self._A = self._A.astype(dt)
            self._A = np.vstack([self._A, C.astype(dt, copy=False)])
            _update_r(self._R, np.array(C, dtype=dt))  # destroys its C copy
            if faults.is_on():
                self._R = faults.perturb("session_update", self._R)
            metrics.inc("fabric.session.update")
            metrics.inc("fabric.session.update_rows", C.shape[0])
            self._updates += 1
            self._pristine = False
            if self._breakdown_locked():
                self._refactor_locked()

    def refactor(self) -> None:
        """Force a counted full refactor of the maintained R."""
        with self._lock:
            self._refactor_locked()

    # -- solves ------------------------------------------------------------

    def solve(self, B) -> np.ndarray:
        """Least-squares solve against the session's current A.

        Pristine sessions dispatch through the owning service (factor
        cache + arena + warmed solve bucket); streamed sessions solve
        host-side via corrected seminormal equations against the
        O(k n^2)-maintained R.  Every streamed solve passes the
        componentwise residual fence or escalates refactor -> raise —
        a wrong X is never returned.
        """
        B = np.asarray(B)
        vec = B.ndim == 1
        Bm = B[:, None] if vec else B
        with self._lock:
            m = self._A.shape[0]
            if Bm.ndim != 2 or Bm.shape[0] != m:
                raise DimensionError(
                    f"session.solve: B must have {m} rows (current A "
                    f"is {self._A.shape}), got shape {B.shape}"
                ).with_context(routine="gels")
            metrics.inc("fabric.session.solve")
            self._solves += 1
            if self._pristine:
                X = self._svc.submit("gels", self._A, Bm).result()
            else:
                X = self._solve_dirty_locked(Bm)
        return X[:, 0] if vec else X

    def _solve_dirty_locked(self, B: np.ndarray) -> np.ndarray:
        from ..serve.factor_cache import residual_ok

        if self._breakdown_locked():
            self._refactor_locked()
        X = self._csne_locked(B)
        if residual_ok(self._A, B, X, routine="gels"):
            return X
        metrics.inc("fabric.session.fence_fail")
        self._refactor_locked()
        X = self._csne_locked(B)
        if residual_ok(self._A, B, X, routine="gels"):
            return X
        metrics.inc("fabric.session.fence_fail")
        raise NumericalError(
            "session solve failed the residual fence even after a "
            "full refactor — the streamed system is numerically "
            "unservable", info=1,
        ).with_context(routine="gels")

    def _csne_locked(self, B: np.ndarray) -> np.ndarray:
        """Corrected seminormal equations against the maintained R:
        R^H y = A^H B, R x = y, then one refinement sweep — recovers
        (nearly) QR-grade backward error without Q."""
        A, R = self._A, self._R
        dt = np.result_type(A.dtype, B.dtype, R.dtype)
        B = B.astype(dt, copy=False)
        Ah = A.conj().T
        X = _solve_upper(R, _solve_upper_h(R, Ah @ B))
        # one CSNE refinement: r = B - A X, R^H w = A^H r, R dx = w
        r = B - A @ X
        X = X + _solve_upper(R, _solve_upper_h(R, Ah @ r))
        return np.asarray(X, dtype=dt)
