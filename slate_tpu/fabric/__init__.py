"""Streaming factor fabric: device-resident factor reuse for the
serving tier.

Two halves (ROADMAP item 4, the repeated-A perf frontier):

- :mod:`~slate_tpu.fabric.arena` — a byte-budgeted per-lane HBM cache
  beside the host :class:`~slate_tpu.serve.factor_cache.FactorCache`
  LRU.  The host cache answers *what* factor serves a hit; the arena
  answers *where it already lives*: a hot factor stays device-resident
  so the warmed ``phase="solve"`` bucket dispatches with zero
  host->device factor transfer.
- :mod:`~slate_tpu.fabric.session` — first-class streaming
  least-squares sessions (``serve.session(routine="gels")``): factor
  once, append rows in O(k n^2) via Householder updates on R, solve on
  demand — with a residual fence on every solve and breakdown ->
  counted refactor, never a wrong X.

Both are OFF by default: a service without an arena has
``service.arena is None`` (one branch on the hot path), and sessions
are created only by explicit API calls.
"""

from .arena import (  # noqa: F401
    ARENA_ENV,
    FactorArena,
    arena_from_options,
    parse_arena_spec,
)
from .session import FactorSession  # noqa: F401
