"""Device factor arena: byte-budgeted per-lane HBM residency for hot
factors.

The serve factor cache (serve/factor_cache.py) made repeated-A solves
O(n^2) — but its entries are host numpy, so every hit still pays one
host->device transfer of an O(n^2) factor before a trsm-only solve.
For a hot factor that transfer IS the latency.  The arena is the
Clipper lesson (PAPERS.md) applied one level down: cache where the
consumer runs.  Each replica lane keeps an LRU of device-resident
factor buffers keyed by the host cache's fingerprint; a hit hands the
solve dispatch the buffer already on the lane's device
(``serve.arena.upload_avoided_bytes``), a miss uploads once and
installs.

Budget & pressure
-----------------
Per-lane byte ledger (``bytes=<N>`` in the ``SLATE_TPU_FACTOR_ARENA``
grammar): inserting past the budget evicts LRU buffers
(``serve.arena.evict``).  Independently, :meth:`FactorArena.pressure`
consults the devmon HBM gauge (``aux/devmon.bytes_in_use``) and spills
the lane's LRU half back to host-only when the DEVICE — not just the
arena — is under memory pressure (``serve.arena.spill``); on backends
without memory stats (XLA:CPU) the probe degrades to a no-op.  Spill
and evict both only drop device residency: the host FactorCache entry
survives, so the next hit re-uploads — never a refactor, never a
wrong X.

Cross-replica sharing
---------------------
A factor homed on a cooling/quarantined lane can serve from a healthy
one: :meth:`FactorArena.get` with ``any_lane=True`` finds the buffer
on a peer lane and installs a device->device copy on the requesting
lane (``serve.arena.cross_replica``) — no host round trip.

Activation: ``SLATE_TPU_FACTOR_ARENA=1`` / ``bytes=2e9`` env, or
``Option.ServeFactorArena`` — default OFF; the service hot path pays
one ``is None`` branch.  Metrics: ``serve.arena.{hit,miss,
upload_avoided_bytes,upload_bytes,spill,evict,cross_replica,drop}``
global + per-lane (``serve.arena.lane.<lane>.*``), plus the
``serve.arena.bytes`` / ``serve.arena.lane.<lane>.bytes`` gauges —
the ``tools/factor_report.py`` arena columns.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from ..aux import devmon, metrics, sync

ARENA_ENV = "SLATE_TPU_FACTOR_ARENA"

DEFAULT_MAX_BYTES = 1 << 30  # 1 GiB of device-resident factors per lane

#: devmon pressure threshold: spill when the device reports more than
#: this fraction of its HBM limit in use (the arena sheds residency
#: BEFORE the allocator starts failing dispatches)
PRESSURE_FRAC = 0.9


def _record(event: str, lane: Optional[str] = None, n: int = 1) -> None:
    """One arena event: global + per-lane, mirroring the factor-cache
    naming scheme (lane cardinality is the replica count — bounded)."""
    if not metrics.is_on():
        return  # hit-path caller: no f-string names built while off
    metrics.inc(f"serve.arena.{event}", n)
    if lane is not None:
        metrics.inc(f"serve.arena.lane.{lane}.{event}", n)


@dataclass(eq=False)
class _Slot:
    """One device-resident factor buffer (identity, not value —
    ``eq=False`` for the same ndarray-truthiness hazard FactorEntry
    documents)."""

    buf: object  # jax.Array committed to the lane's device
    nbytes: int


class FactorArena:
    """Per-lane LRU of device-resident factor buffers under one byte
    budget per lane.  Thread-safe: every replica worker (and the
    service's invalidation paths) touch it."""

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES):
        self.max_bytes = max(int(max_bytes), 1)
        # sync.RLock: plain threading.RLock unless SLATE_TPU_SYNC_CHECK
        # armed the race plane.  The annotations are ground truth for
        # the lock-discipline / race-guarded-by lint rules
        self._lock = sync.RLock(name="fabric.FactorArena._lock")
        self._lane_slots: Dict[str, "OrderedDict[str, _Slot]"] = {}  # guarded by: _lock
        self._bytes: Dict[str, int] = {}  # guarded by: _lock

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return sum(len(d) for d in self._lane_slots.values())

    def stats(self) -> dict:
        with self._lock:
            return {
                "max_bytes": self.max_bytes,
                "bytes": sum(self._bytes.values()),
                "entries": sum(len(d) for d in self._lane_slots.values()),
                "lanes": {
                    lane: {
                        "entries": len(d),
                        "bytes": self._bytes.get(lane, 0),
                    }
                    for lane, d in self._lane_slots.items()
                },
            }

    def _gauges_locked(self, lane: str) -> None:
        if not metrics.is_on():
            return
        metrics.gauge(
            "serve.arena.bytes", sum(self._bytes.values())
        )
        metrics.gauge(
            f"serve.arena.lane.{lane}.bytes", self._bytes.get(lane, 0)
        )

    # -- core --------------------------------------------------------------

    def get(self, fp: str, lane: str, device=None, any_lane: bool = True):
        """The device-resident buffer for one fingerprint on one lane
        (refreshing its LRU position), or None.  A same-lane hit counts
        ``hit`` + ``upload_avoided_bytes`` — the factor bytes that did
        NOT cross the host->device link.  When ``any_lane`` and a peer
        lane holds the buffer, a device->device copy installs it here
        (``cross_replica``; requires ``device``, the requesting lane's
        placement) — still no host round trip."""
        with self._lock:
            sync.guarded(self, "_lane_slots")  # race-plane probe (no-op off)
            slots = self._lane_slots.get(lane)
            if slots is not None:
                slot = slots.get(fp)
                if slot is not None:
                    slots.move_to_end(fp)
                    _record("hit", lane)
                    _record("upload_avoided_bytes", lane, slot.nbytes)
                    return slot.buf
            src = None
            if any_lane:
                for peer, pslots in self._lane_slots.items():
                    if peer != lane and fp in pslots:
                        src = pslots[fp]
                        break
        if src is not None and device is not None:
            import jax

            buf = jax.device_put(src.buf, device)
            _record("cross_replica", lane)
            self._install(fp, lane, buf, int(src.nbytes))
            return buf
        _record("miss", lane)
        return None

    def put(self, fp: str, lane: str, F: np.ndarray, device=None):
        """Upload one host factor to the lane's device and install it
        (``upload_bytes``); returns the committed device buffer — the
        caller dispatches THIS, so the upload it just paid is the last
        one the fingerprint pays on this lane.  A buffer alone past the
        byte budget is returned uncached (the next hit re-uploads:
        the budget doing its job)."""
        import jax

        nbytes = int(np.asarray(F).nbytes)
        buf = (
            jax.device_put(F, device) if device is not None
            else jax.numpy.asarray(F)
        )
        _record("upload_bytes", lane, nbytes)
        if nbytes <= self.max_bytes:
            self._install(fp, lane, buf, nbytes)
        return buf

    def _install(self, fp: str, lane: str, buf, nbytes: int) -> None:
        with self._lock:
            sync.guarded(self, "_lane_slots")  # race-plane probe (no-op off)
            slots = self._lane_slots.setdefault(lane, OrderedDict())
            old = slots.pop(fp, None)
            if old is not None:
                self._bytes[lane] = self._bytes.get(lane, 0) - old.nbytes
            slots[fp] = _Slot(buf=buf, nbytes=nbytes)
            self._bytes[lane] = self._bytes.get(lane, 0) + nbytes
            while slots and self._bytes.get(lane, 0) > self.max_bytes:
                vfp, victim = slots.popitem(last=False)
                self._bytes[lane] -= victim.nbytes
                _record("evict", lane)
            self._gauges_locked(lane)

    # -- pressure / lifecycle ----------------------------------------------

    def pressure(self, lane: str, device=None) -> int:
        """Devmon-driven spill: sample the device's HBM gauge and, past
        :data:`PRESSURE_FRAC` of its reported limit, drop the LRU half
        of the lane's residency back to host-only (``spill``; the host
        FactorCache entries survive — a later hit re-uploads).  Returns
        the number of buffers spilled; 0 on backends without memory
        stats (XLA:CPU) — graceful degradation, never a crash."""
        in_use = devmon.bytes_in_use(device)
        if in_use is None:
            return 0
        limit = None
        try:
            fn = getattr(device, "memory_stats", None)
            stats = fn() if fn is not None else None
            if stats:
                limit = stats.get("bytes_limit")
        except Exception:  # noqa: BLE001 — telemetry must never crash
            limit = None
        if metrics.is_on():
            metrics.gauge(
                f"serve.arena.lane.{lane}.hbm_bytes_in_use", in_use
            )
        if limit is None or in_use <= PRESSURE_FRAC * int(limit):
            return 0
        return self.spill(lane)

    def spill(self, lane: str, keep_frac: float = 0.5) -> int:
        """Drop the LRU ``1 - keep_frac`` of one lane's residency
        (``spill`` per buffer dropped); returns the count."""
        spilled = 0
        with self._lock:
            slots = self._lane_slots.get(lane)
            if not slots:
                return 0
            target = int(len(slots) * float(keep_frac))
            while len(slots) > target:
                _, victim = slots.popitem(last=False)
                self._bytes[lane] -= victim.nbytes
                _record("spill", lane)
                spilled += 1
            self._gauges_locked(lane)
        return spilled

    def drop(self, fp: str) -> int:
        """Drop one fingerprint's buffers on EVERY lane (``drop``) —
        the invalidation/staleness hook: a host-cache invalidate must
        take the device copies with it or a stale factor would keep
        serving from HBM.  Returns the count dropped."""
        dropped = 0
        with self._lock:
            for lane, slots in self._lane_slots.items():
                slot = slots.pop(fp, None)
                if slot is not None:
                    self._bytes[lane] -= slot.nbytes
                    _record("drop", lane)
                    self._gauges_locked(lane)
                    dropped += 1
        return dropped

    def drop_lane(self, lane: str) -> int:
        """Drop one lane's entire residency (scale-down: the device is
        leaving the fleet).  Returns the count dropped."""
        with self._lock:
            slots = self._lane_slots.pop(lane, None)
            self._bytes.pop(lane, None)
            if not slots:
                return 0
            n = len(slots)
            _record("drop", lane, n)
            self._gauges_locked(lane)
            return n

    def clear(self) -> int:
        """Drop everything on every lane; returns the count dropped."""
        with self._lock:
            n = sum(len(d) for d in self._lane_slots.values())
            lanes = list(self._lane_slots)
            self._lane_slots.clear()
            self._bytes.clear()
            for lane in lanes:
                self._gauges_locked(lane)
            return n


# ---------------------------------------------------------------------------
# env/options activation: SLATE_TPU_FACTOR_ARENA=1 | bytes=N
# ---------------------------------------------------------------------------


def parse_arena_spec(spec: str) -> Optional[dict]:
    """Parse the ``SLATE_TPU_FACTOR_ARENA`` grammar: empty/``0``/``off``
    -> None (disabled), ``1``/``on`` -> enabled with defaults, or a
    comma list of ``bytes=<float>`` overrides — the factor cache's env
    grammar, one knob."""
    spec = (spec or "").strip()
    if not spec or spec.lower() in ("0", "off", "false", "no"):
        return None
    if spec.lower() in ("1", "on", "true", "yes"):
        return {}
    out: dict = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        k, sep, v = item.partition("=")
        k, v = k.strip().lower(), v.strip()
        if not sep:
            raise ValueError(
                f"{ARENA_ENV}={spec!r}: expected k=v, got {item!r}"
            )
        if k == "bytes":
            out["max_bytes"] = int(float(v))
        else:
            raise ValueError(
                f"{ARENA_ENV}={spec!r}: unknown key {k!r} (bytes)"
            )
    return out


def arena_from_options(opts=None) -> Optional[FactorArena]:
    """Resolve the process/service default: ``SLATE_TPU_FACTOR_ARENA``
    wins, else the ``Option.ServeFactorArena`` spec string (same
    grammar).  None = disabled — the service hot path stays one
    branch."""
    from ..enums import Option
    from ..options import get_option

    env = os.environ.get(ARENA_ENV, "")
    kw = parse_arena_spec(env)
    if kw is None:
        if env.strip():
            return None  # env explicitly off: it wins over options
        kw = parse_arena_spec(str(get_option(opts, Option.ServeFactorArena)))
        if kw is None:
            return None
    return FactorArena(**kw)
