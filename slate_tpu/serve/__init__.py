"""slate_tpu.serve — batching solver service above the drivers.

Shape-bucketed dispatch (`buckets`), an executable cache with a
persistent warmup manifest (`cache`, ``SLATE_TPU_WARMUP=/path.json``),
a durable executable artifact store for crash-safe cold starts
(`artifacts`, ``SLATE_TPU_ARTIFACTS=/dir``), a mesh-aware placement
tier — replica scale-out + spmd submesh routing (`placement`,
``Option.ServeReplicas/ServeMesh/ServeShardThreshold``) — a
factor-once/solve-many factorization cache dispatching trsm-only
executables on repeated-A traffic (`factor_cache`,
``SLATE_TPU_FACTOR_CACHE``) — an overload-resilient admission plane:
tenant fairness/quotas, priority shedding, and an AIMD-adaptive batch
window (`admission`, ``SLATE_TPU_TENANTS``/``SLATE_TPU_ADAPTIVE``) — a
deadline-aware batching service with a cold/restoring/ready readiness
phase (`service`), and thin sync wrappers (`api`):
``serve.gesv/posv/gels``, ``serve.submit``, ``serve.warmup``,
``serve.restore``.

Attribute access is lazy (PEP 562): importing ``slate_tpu.serve`` (or
``serve.buckets`` from the drivers) never pulls the driver stack, so
``drivers/eig.py -> serve.buckets`` stays acyclic and module import
costs nothing until the first request.
"""

from __future__ import annotations

import importlib

_API = (
    "gesv", "posv", "gels", "submit", "warmup", "restore", "wait_ready",
    "configure", "shutdown", "get_service", "get_cache", "health",
    "get_fleet", "InvalidInput",
    # factor cache (factor once, solve many)
    "get_factor_cache", "factor_fingerprint", "invalidate",
    "invalidate_all", "update_factor",
)
_SERVICE = (
    "SolverService", "Rejected", "DeadlineExceeded", "Shed",
    "decorrelated_backoff",
    "PHASE_COLD", "PHASE_RESTORING", "PHASE_READY",
)
_CACHE = ("ExecutableCache", "direct_call", "WARMUP_ENV")
_BUCKETS = (
    "BucketKey", "Breaker", "bucket_for", "bucket_dim", "halving_bucket",
    "size_bucket_runs", "batch_bucket",
)
_ARTIFACTS = ("ArtifactStore", "ARTIFACTS_ENV", "store_from_env")
_PLACEMENT = ("PlacementPolicy",)
_ADMISSION = (
    "AdmissionControl", "TenantConfig", "parse_tenants", "FairQueue",
    "AdaptiveWindow", "OverloadController", "TokenBucket", "TENANTS_ENV",
    "ADAPTIVE_ENV",
)
_FACTOR = (
    "FactorCache", "FactorEntry", "matrix_fingerprint",
    "FACTOR_CACHE_ENV",
)
_SUBMODULES = (
    "api", "buckets", "cache", "service", "artifacts", "placement",
    "factor_cache", "admission",
)

__all__ = list(
    _API + _SERVICE + _CACHE + _BUCKETS + _ARTIFACTS + _PLACEMENT + _FACTOR
    + _ADMISSION
) + list(_SUBMODULES)


def __getattr__(name: str):
    if name in _API:
        return getattr(importlib.import_module(".api", __name__), name)
    if name in _SERVICE:
        return getattr(importlib.import_module(".service", __name__), name)
    if name in _CACHE:
        return getattr(importlib.import_module(".cache", __name__), name)
    if name in _BUCKETS:
        return getattr(importlib.import_module(".buckets", __name__), name)
    if name in _ARTIFACTS:
        return getattr(
            importlib.import_module(".artifacts", __name__), name
        )
    if name in _PLACEMENT:
        return getattr(
            importlib.import_module(".placement", __name__), name
        )
    if name in _ADMISSION:
        return getattr(
            importlib.import_module(".admission", __name__), name
        )
    if name in _FACTOR:
        return getattr(
            importlib.import_module(".factor_cache", __name__), name
        )
    if name in _SUBMODULES:
        # the advertised submodules themselves (serve.placement,
        # serve.buckets, ...) — lazily importable like the names
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
