"""slate_tpu.serve — batching solver service above the drivers.

Shape-bucketed dispatch (`buckets`), an executable cache with a
persistent warmup manifest (`cache`, ``SLATE_TPU_WARMUP=/path.json``),
a deadline-aware batching service (`service`), and thin sync wrappers
(`api`): ``serve.gesv/posv/gels``, ``serve.submit``, ``serve.warmup``.

Attribute access is lazy (PEP 562): importing ``slate_tpu.serve`` (or
``serve.buckets`` from the drivers) never pulls the driver stack, so
``drivers/eig.py -> serve.buckets`` stays acyclic and module import
costs nothing until the first request.
"""

from __future__ import annotations

import importlib

_API = (
    "gesv", "posv", "gels", "submit", "warmup", "configure", "shutdown",
    "get_service", "get_cache", "health", "InvalidInput",
)
_SERVICE = (
    "SolverService", "Rejected", "DeadlineExceeded", "decorrelated_backoff",
)
_CACHE = ("ExecutableCache", "direct_call", "WARMUP_ENV")
_BUCKETS = (
    "BucketKey", "Breaker", "bucket_for", "bucket_dim", "halving_bucket",
    "size_bucket_runs", "batch_bucket",
)

__all__ = list(_API + _SERVICE + _CACHE + _BUCKETS) + ["api", "buckets"]


def __getattr__(name: str):
    if name in _API:
        return getattr(importlib.import_module(".api", __name__), name)
    if name in _SERVICE:
        return getattr(importlib.import_module(".service", __name__), name)
    if name in _CACHE:
        return getattr(importlib.import_module(".cache", __name__), name)
    if name in _BUCKETS:
        return getattr(importlib.import_module(".buckets", __name__), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
