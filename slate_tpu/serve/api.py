"""Thin sync serving API over one process-wide :class:`SolverService`.

Usage::

    from slate_tpu import serve

    serve.warmup("warmup.json")        # pre-compile the manifest's buckets
    X = serve.gesv(A, B)               # sync; pads/crops + batches under the hood
    fut = serve.submit("posv", S, B, deadline=0.2, retries=1)
    ...
    X2 = fut.result()

Semantics:

* Inputs are plain (m, n)/(m, nrhs) host or device arrays — the serving
  boundary is arrays, not Matrix objects (clients shouldn't know the
  tile layout; the bucket decides it).
* ``gesv``/``posv`` require square A; ``posv`` solves with the LOWER
  triangle of A (SPD).  ``gels`` with m < n is served by the direct
  driver (minimum-norm path is not vmap-batched).
* A nonzero driver ``info`` raises NumericalError from ``.result()`` /
  the sync wrapper; deadline misses raise DeadlineExceeded; a full
  queue raises Rejected and non-finite operands raise InvalidInput
  from ``submit`` itself (admission checks; every error carries
  structured ``routine``/``bucket``/``attempt`` context).
* Self-healing: executable failures retry with decorrelated-jitter
  backoff, then fall back to the direct driver (``serve.fallbacks``);
  a bucket failing ``degrade_after`` times in a row opens its circuit
  breaker (routed direct), half-opens after a cooldown, and one
  healthy probe restores the batched path.  A dead worker thread is
  respawned with its in-flight futures re-enqueued or failed fast —
  no future ever hangs.  ``serve.health()`` snapshots all of it.

The default service reads :class:`~slate_tpu.enums.Option` defaults
(``ServeQueueLimit``, ``ServeBatchMax``, ``ServeBatchWindow``) through
``options.get_option``; ``configure()`` overrides them per process.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import Future
from typing import Optional

import numpy as np

from ..aux import sync
from ..enums import Option
from ..exceptions import InvalidInput  # noqa: F401  (re-export: taxonomy)
from ..options import Options, get_option
from .cache import ExecutableCache
from .service import (  # noqa: F401  (re-export: taxonomy)
    DeadlineExceeded,
    Rejected,
    Shed,
    SolverService,
)

_lock = threading.Lock()
_service: Optional[SolverService] = None

# fleet tier (SLATE_TPU_FLEET): with the env unset this stays None and
# every call below pays exactly one ``is None`` branch — the fleet
# package is not even imported, so single-process serving is
# byte-identical to a build without the tier
_fleet = None
if os.environ.get("SLATE_TPU_FLEET"):
    from ..fleet.router import FleetRouter

    _fleet = FleetRouter.from_env()


def get_service() -> SolverService:
    """The process-wide service (lazily started on first use)."""
    global _service
    with _lock:
        if _service is None:
            _service = _make_service(None)
        return _service


def _make_service(opts: Optional[Options], **kw) -> SolverService:
    from .placement import PlacementPolicy

    cfg = dict(
        max_queue=int(get_option(opts, Option.ServeQueueLimit)),
        batch_max=int(get_option(opts, Option.ServeBatchMax)),
        batch_window_s=float(get_option(opts, Option.ServeBatchWindow)),
        retry_backoff_s=float(get_option(opts, Option.ServeRetryBackoff)),
        breaker_cooldown_s=float(
            get_option(opts, Option.ServeBreakerCooldown)
        ),
        validate=bool(get_option(opts, Option.ServeValidate)),
        schedule=get_option(opts, Option.Schedule),
        precision=str(get_option(opts, Option.ServePrecision) or "full"),
        faults_spec=str(get_option(opts, Option.Faults) or ""),
    )
    # admission-plane options pass through only when EXPLICITLY set:
    # collapsing an explicit off value ("", False, 0.0) to None would
    # let AdmissionControl.from_options re-resolve the env, so a
    # baseline/AB caller could never disable env-armed tenancy from
    # here (the env-override trap factor_cache=False exists for)
    _unset = object()
    tq = get_option(opts, Option.ServeTenantQuota, _unset)
    aw = get_option(opts, Option.ServeAdaptiveWindow, _unset)
    lb = get_option(opts, Option.ServeLatencyBudget, _unset)
    si = get_option(opts, Option.ServeIntegrity, _unset)
    cfg.update(
        tenants=None if tq is _unset else tq,
        adaptive=None if aw is _unset else bool(aw),
        latency_budget_s=None if lb is _unset else float(lb),
        # an explicitly-empty integrity spec is the explicit
        # off-switch (False) — collapsing it to None would let the
        # service re-resolve SLATE_TPU_INTEGRITY, making env-armed
        # certification un-disablable from opts (the factor_cache
        # env-override trap)
        integrity=None if si is _unset else (si or False),
    )
    cfg.update(kw)
    if cfg.get("factor_cache") is None:
        # per-call opts can enable the factor cache too (the service's
        # own fallback resolution only sees process defaults + env)
        from .factor_cache import cache_from_options

        cfg["factor_cache"] = cache_from_options(opts)
    if cfg.get("factor_arena") is None and opts:
        # same shape for the device arena: an explicit opts spec builds
        # (or explicitly disables) the arena here; otherwise the
        # service resolves env/process defaults itself
        fa = get_option(opts, Option.ServeFactorArena, _unset)
        if fa is not _unset:
            if isinstance(fa, str):
                from ..fabric.arena import FactorArena, parse_arena_spec

                spec = parse_arena_spec(fa)
                cfg["factor_arena"] = (
                    FactorArena(**spec) if spec is not None else False
                )
            else:
                cfg["factor_arena"] = fa or False
    if cfg.get("placement") is None:
        # build the policy AFTER kw lands so the replicas shorthand is
        # honored (an eager placement= in cfg would make SolverService
        # ignore it — the policy argument wins by contract)
        cfg["placement"] = PlacementPolicy.from_options(
            opts, replicas=cfg.pop("replicas", None)
        )
    return SolverService(**cfg)


def configure(opts: Optional[Options] = None, **kw) -> SolverService:
    """Rebuild the process service (stops the old one).  ``kw`` are
    :class:`SolverService` arguments; ``opts`` resolves the Serve*
    options.  Returns the new service."""
    global _service
    with _lock:
        if _service is not None:
            _service.stop()
        _service = _make_service(opts, **kw)
        return _service


def shutdown() -> None:
    """Stop the process service (idempotent; a later call re-creates).
    With the fleet tier on, drains the router (and its workers) too."""
    global _service
    if _fleet is not None:
        _fleet.stop(drain=True)
    with _lock:
        if _service is not None:
            _service.stop()
            _service = None


def warmup(
    path: Optional[str] = None, verbose: bool = False
) -> int:
    """Pre-compile the warmup manifest's executables (``path`` or the
    service cache's configured ``SLATE_TPU_WARMUP`` manifest).  Returns
    the number compiled.  After this, requests whose buckets are in the
    manifest are steady-state compile-free."""
    svc = get_service()
    return svc.warmup(path=path, verbose=verbose)


def restore(verbose: bool = False, timeout: Optional[float] = None) -> dict:
    """Bring the warmed executable set live artifact-first (the
    cold-start path: each manifest entry is loaded from the
    ``SLATE_TPU_ARTIFACTS`` store where a verified artifact exists,
    compiled otherwise, and primed).  Returns the cache's restore
    summary ``{"entries", "restored", "compiled", "failed",
    "skipped"}``.  A
    service with an artifact store runs this automatically on start —
    poll ``health()["phase"]`` (cold -> restoring -> ready) or call
    :func:`wait_ready` to gate traffic on it.  Any start-time pass is
    waited out first — bounded by ``timeout`` (None = wait forever) —
    so this never races it (already-live entries make the explicit
    pass a cheap no-op).  If the bound expires while a start-time pass
    is still RUNNING (a wedged restore thread —
    ``health()["restore_stuck_s"]`` says for how long), raises
    :class:`TimeoutError` instead of launching a second pass
    concurrently with the stuck one.  A service with no pass in
    flight (built paused, or restore never configured) just runs the
    synchronous pass as before."""
    svc = get_service()
    if not svc.wait_ready(timeout):
        h = svc.health()
        if h["phase"] == "restoring":
            raise TimeoutError(
                "start-time restore still running after "
                f"{timeout:g}s (restore_stuck_s="
                f"{h['restore_stuck_s']}); not starting a concurrent "
                "pass"
            )
        # cold / never-started: nothing in flight to race — fall
        # through to the synchronous pass (pre-timeout behavior)
    return svc.restore(verbose=verbose)


def wait_ready(timeout: Optional[float] = None) -> bool:
    """Block until the process service reaches the ``ready`` phase
    (its start-time restore pass finished); False on timeout."""
    return get_service().wait_ready(timeout)


def submit(
    routine: str,
    A,
    B,
    deadline: Optional[float] = None,
    retries: int = 0,
    precision: Optional[str] = None,
    sharded: Optional[bool] = None,
    tenant: Optional[str] = None,
    priority=None,
) -> Future:
    """Async entry: enqueue and return the Future (see
    :meth:`SolverService.submit`).  ``precision`` ("full"|"mixed")
    overrides the service-wide solve path for this request;
    ``sharded`` overrides the placement policy (True forces the spmd
    submesh, False the replicated tier, None routes by size).
    ``tenant``/``priority`` ("high"|"normal"|"low") tag the request
    for the admission plane (``SLATE_TPU_TENANTS`` /
    ``Option.ServeTenantQuota``): per-tenant fair queueing and quotas,
    priority-ordered overload shedding (typed :class:`Shed`).

    With ``SLATE_TPU_FLEET`` set the request routes through the
    process's :class:`~slate_tpu.fleet.FleetRouter` instead — same
    Future contract and typed taxonomy, plus the fabric's own
    :class:`~slate_tpu.fleet.HostDead` /
    :class:`~slate_tpu.fleet.FleetTimeout`."""
    if _fleet is not None:
        return _fleet.submit(
            routine, A, B, deadline=deadline, retries=retries,
            precision=precision, sharded=sharded, tenant=tenant,
            priority=priority,
        )
    return get_service().submit(
        routine, A, B, deadline=deadline, retries=retries,
        precision=precision, sharded=sharded, tenant=tenant,
        priority=priority,
    )


def _sync(routine, A, B, deadline, retries, precision=None,
          sharded=None, tenant=None, priority=None) -> np.ndarray:
    fut = submit(
        routine, A, B, deadline=deadline, retries=retries,
        precision=precision, sharded=sharded, tenant=tenant,
        priority=priority,
    )
    # no result-timeout: the worker resolves every admitted future
    # (deadline expiry included), so blocking here cannot hang
    try:
        return fut.result()
    finally:
        # race plane: pair the worker's hb_publish at resolution, so a
        # guarded field the client touches after result() is ordered
        # after the worker's writes (one bool when off)
        sync.hb_receive(fut)


def gesv(A, B, deadline: Optional[float] = None, retries: int = 0,
         precision: Optional[str] = None,
         sharded: Optional[bool] = None,
         tenant: Optional[str] = None, priority=None) -> np.ndarray:
    """Solve A X = B (square, LU with partial pivoting) through the
    service; returns X (n x nrhs).  ``precision="mixed"`` routes the
    request through a mixed-precision bucket (low-precision factor +
    iterative refinement; non-converged solves are transparently
    re-solved on the full-precision direct path).  ``sharded=True``
    forces the spmd submesh (Option.ServeMesh) — large-n requests
    route there automatically past Option.ServeShardThreshold.
    ``tenant``/``priority`` tag the request for the admission plane."""
    return _sync("gesv", A, B, deadline, retries, precision, sharded,
                 tenant, priority)


def posv(A, B, deadline: Optional[float] = None, retries: int = 0,
         precision: Optional[str] = None,
         sharded: Optional[bool] = None,
         tenant: Optional[str] = None, priority=None) -> np.ndarray:
    """Solve SPD A X = B (Cholesky, lower triangle referenced)."""
    return _sync("posv", A, B, deadline, retries, precision, sharded,
                 tenant, priority)


def gels(A, B, deadline: Optional[float] = None, retries: int = 0,
         tenant: Optional[str] = None, priority=None) -> np.ndarray:
    """Least-squares solve min ||A X - B|| (m >= n batched; m < n direct)."""
    return _sync("gels", A, B, deadline, retries, tenant=tenant,
                 priority=priority)


def health() -> dict:
    """Liveness/readiness snapshot of the process service for external
    probes: queue depth, worker liveness + restarts, per-bucket circuit
    breaker states, recent failure rate, per-replica oldest-queued-age,
    and — with metrics on — the SLO surface: per-bucket p50/p95/p99
    total latency under ``"latency"`` and the deadline-budget burn
    tiers under ``"slo_burn"`` (see :meth:`SolverService.health`).
    With the fleet tier on, returns the ROUTER's snapshot instead:
    per-host breaker states + integrity scores, pending count, and the
    global admission plane (see :meth:`FleetRouter.health`)."""
    if _fleet is not None:
        return _fleet.health()
    return get_service().health()


def get_fleet():
    """The process's :class:`~slate_tpu.fleet.FleetRouter`, or None
    without ``SLATE_TPU_FLEET`` (the single-process path)."""
    return _fleet


def get_cache() -> ExecutableCache:
    """The process service's executable cache (manifest control)."""
    return get_service().cache


# -- factor cache (factor once, solve many) ---------------------------------


def get_factor_cache():
    """The process service's :class:`~slate_tpu.serve.factor_cache.
    FactorCache`, or None when disabled (the default —
    ``SLATE_TPU_FACTOR_CACHE=1`` / ``Option.ServeFactorCache`` turn it
    on)."""
    return get_service().factor_cache


def factor_fingerprint(routine: str, A) -> str:
    """The matrix fingerprint ``submit(routine, A, ...)`` will key the
    factor cache by (A's bytes + dtype + shape + routine + the
    service's schedule) — the handle for :func:`invalidate` /
    :func:`update_factor`."""
    from .factor_cache import matrix_fingerprint

    svc = get_service()
    return matrix_fingerprint(np.asarray(A), routine,
                              schedule=svc.schedule)


def invalidate(fp: str) -> bool:
    """Drop one fingerprint's cached factor — the next same-A request
    pays a counted refactor (``serve.factor_cache.invalidate``).
    Drops the fingerprint's device-arena residency too.  Returns
    whether it was cached; False too when the cache is off."""
    svc = get_service()
    if svc.arena is not None:
        svc.arena.drop(fp)
    fc = svc.factor_cache
    return fc.invalidate(fp) if fc is not None else False


def invalidate_all() -> int:
    """Drop every cached factor (and all device-arena residency);
    returns the count dropped (0 when the cache is off)."""
    svc = get_service()
    if svc.arena is not None:
        svc.arena.clear()
    fc = svc.factor_cache
    return fc.invalidate_all() if fc is not None else 0


# -- factor fabric (device arena + streaming sessions) -----------------------


def get_arena():
    """The process service's :class:`~slate_tpu.fabric.arena.
    FactorArena`, or None when unarmed (the default —
    ``SLATE_TPU_FACTOR_ARENA=1`` / ``bytes=<N>`` /
    ``Option.ServeFactorArena`` turn it on; requires the factor cache
    to be enabled too)."""
    return get_service().arena


def session(A, routine: str = "gels", schedule: Optional[str] = None):
    """Open a streaming factor-reuse session on the process service
    (:class:`~slate_tpu.fabric.session.FactorSession`)::

        s = serve.session(A)          # min ||A x - b||, m >= n
        x0 = s.solve(b)               # pristine: factor-cache/arena path
        s.append(rows)                # O(k n^2) Householder update on R
        x1 = s.solve(b_grown)         # fenced CSNE against updated R

    Every streamed solve passes the componentwise residual fence or
    pays a counted refactor (``fabric.session.refactor``) — never a
    wrong X."""
    from ..fabric.session import FactorSession

    svc = get_service()
    return FactorSession(
        svc, A, routine=routine,
        schedule=svc.schedule if schedule is None else schedule,
    )


def update_factor(fp: str, A_new, U, downdate: bool = False):
    """Rank-k up/downdate of a cached factor for an incrementally
    edited A (``A_new = A ± U U^H``): posv entries update the Cholesky
    factor in O(k n^2), gesv entries refactor (counted).  Returns the
    NEW fingerprint the entry is re-keyed to (what ``submit(A_new,..)``
    will hit), or None when ``fp`` is not cached / the cache is off —
    just submit A_new and let the miss path factor it."""
    fc = get_service().factor_cache
    if fc is None:
        return None
    return fc.update(fp, np.asarray(A_new), np.asarray(U),
                     downdate=downdate)
