"""Thin sync serving API over one process-wide :class:`SolverService`.

Usage::

    from slate_tpu import serve

    serve.warmup("warmup.json")        # pre-compile the manifest's buckets
    X = serve.gesv(A, B)               # sync; pads/crops + batches under the hood
    fut = serve.submit("posv", S, B, deadline=0.2, retries=1)
    ...
    X2 = fut.result()

Semantics:

* Inputs are plain (m, n)/(m, nrhs) host or device arrays — the serving
  boundary is arrays, not Matrix objects (clients shouldn't know the
  tile layout; the bucket decides it).
* ``gesv``/``posv`` require square A; ``posv`` solves with the LOWER
  triangle of A (SPD).  ``gels`` with m < n is served by the direct
  driver (minimum-norm path is not vmap-batched).
* A nonzero driver ``info`` raises NumericalError from ``.result()`` /
  the sync wrapper; deadline misses raise DeadlineExceeded; a full
  queue raises Rejected from ``submit`` itself.
* Graceful degradation: when a bucket's batched executable keeps
  failing, its requests transparently fall back to the direct driver
  (counted in ``serve.fallbacks``; the bucket is marked degraded after
  ``degrade_after`` consecutive failures and stops being batched).

The default service reads :class:`~slate_tpu.enums.Option` defaults
(``ServeQueueLimit``, ``ServeBatchMax``, ``ServeBatchWindow``) through
``options.get_option``; ``configure()`` overrides them per process.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Optional

import numpy as np

from ..enums import Option
from ..options import Options, get_option
from .cache import ExecutableCache
from .service import DeadlineExceeded, Rejected, SolverService  # noqa: F401

_lock = threading.Lock()
_service: Optional[SolverService] = None


def get_service() -> SolverService:
    """The process-wide service (lazily started on first use)."""
    global _service
    with _lock:
        if _service is None:
            _service = _make_service(None)
        return _service


def _make_service(opts: Optional[Options], **kw) -> SolverService:
    cfg = dict(
        max_queue=int(get_option(opts, Option.ServeQueueLimit)),
        batch_max=int(get_option(opts, Option.ServeBatchMax)),
        batch_window_s=float(get_option(opts, Option.ServeBatchWindow)),
        schedule=get_option(opts, Option.Schedule),
    )
    cfg.update(kw)
    return SolverService(**cfg)


def configure(opts: Optional[Options] = None, **kw) -> SolverService:
    """Rebuild the process service (stops the old one).  ``kw`` are
    :class:`SolverService` arguments; ``opts`` resolves the Serve*
    options.  Returns the new service."""
    global _service
    with _lock:
        if _service is not None:
            _service.stop()
        _service = _make_service(opts, **kw)
        return _service


def shutdown() -> None:
    """Stop the process service (idempotent; a later call re-creates)."""
    global _service
    with _lock:
        if _service is not None:
            _service.stop()
            _service = None


def warmup(
    path: Optional[str] = None, verbose: bool = False
) -> int:
    """Pre-compile the warmup manifest's executables (``path`` or the
    service cache's configured ``SLATE_TPU_WARMUP`` manifest).  Returns
    the number compiled.  After this, requests whose buckets are in the
    manifest are steady-state compile-free."""
    svc = get_service()
    return svc.cache.warmup(
        path=path, batch_max=svc.batch_max, verbose=verbose
    )


def submit(
    routine: str,
    A,
    B,
    deadline: Optional[float] = None,
    retries: int = 0,
) -> Future:
    """Async entry: enqueue and return the Future (see
    :meth:`SolverService.submit`)."""
    return get_service().submit(routine, A, B, deadline=deadline, retries=retries)


def _sync(routine, A, B, deadline, retries) -> np.ndarray:
    fut = submit(routine, A, B, deadline=deadline, retries=retries)
    # no result-timeout: the worker resolves every admitted future
    # (deadline expiry included), so blocking here cannot hang
    return fut.result()


def gesv(A, B, deadline: Optional[float] = None, retries: int = 0) -> np.ndarray:
    """Solve A X = B (square, LU with partial pivoting) through the
    service; returns X (n x nrhs)."""
    return _sync("gesv", A, B, deadline, retries)


def posv(A, B, deadline: Optional[float] = None, retries: int = 0) -> np.ndarray:
    """Solve SPD A X = B (Cholesky, lower triangle referenced)."""
    return _sync("posv", A, B, deadline, retries)


def gels(A, B, deadline: Optional[float] = None, retries: int = 0) -> np.ndarray:
    """Least-squares solve min ||A X - B|| (m >= n batched; m < n direct)."""
    return _sync("gels", A, B, deadline, retries)


def get_cache() -> ExecutableCache:
    """The process service's executable cache (manifest control)."""
    return get_service().cache
