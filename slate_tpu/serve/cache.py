"""Executable cache + on-disk warmup manifest.

The cache maps ``(BucketKey, batch)`` to a compiled, metrics-
instrumented executable over padded global arrays.  Executables are
built lazily on first use; every build is appended to the warmup
manifest (``SLATE_TPU_WARMUP=/path.json`` or an explicit path), so a
deployment's steady-state bucket set accumulates across runs and
``warmup()`` can pre-compile the whole set at startup — after which a
stream of requests in warmed buckets is compile-free (the
``jit.compilations`` counter stays flat).

Executable shape: ``fn(A_batch, B_batch) -> (X_batch, info_batch)``
with ``A: (batch, Mb, Nb)``, ``B: (batch, Mb, nrhs_b)`` — the drivers
vmapped over the leading axis (Matrix construction from the padded
globals happens inside the trace; tile layouts are static per bucket).
Only two batch points exist per key (1 and batch_max, see
``buckets.batch_bucket``), so the executable set stays bounded and
deterministic.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from ..aux import faults, metrics
from ..exceptions import NumericalError
from .buckets import BucketKey, manifest_dumps, manifest_loads

WARMUP_ENV = "SLATE_TPU_WARMUP"


def _build_core(key: BucketKey) -> Callable:
    """The unbatched core over padded globals for one bucket.  Driver
    imports are local: serve must stay importable before drivers are
    (the lazy ``serve/__init__`` keeps ``drivers/eig -> serve.buckets``
    acyclic).  The key's factorization schedule is threaded into the
    drivers via Option.Schedule, so a manifest captured from a
    recursive-schedule deployment precompiles the recursion shapes."""
    from ..drivers import chol as _chol
    from ..drivers import lu as _lu
    from ..drivers import qr as _qr
    from ..enums import Option, Uplo
    from ..matrix.matrix import HermitianMatrix, Matrix

    nb = key.nb
    opts = {Option.Schedule: key.schedule}

    if key.precision == "mixed":
        # mixed-precision bucket: low-precision factor + device-resident
        # IR (drivers/mixed.serve_mixed_core — fully traceable, classical
        # IR only).  Non-converged solves come back NaN-poisoned; the
        # service's corrupt-result validation re-solves those items on
        # the full-precision direct path and the bucket breaker demotes
        # persistently non-converging buckets — the fallback policy
        # lives in the service, never in the executable.
        from ..drivers import mixed as _mixed

        if key.routine not in ("gesv", "posv"):
            raise ValueError(
                "mixed-precision serving supports gesv/posv, "
                f"not {key.routine!r}"
            )

        def core(Ag, Bg):
            return _mixed.serve_mixed_core(
                key.routine, Ag, Bg, nb, key.schedule
            )

        return core

    if key.routine == "gesv":

        def core(Ag, Bg):
            A = Matrix.from_global(Ag, nb)
            B = Matrix.from_global(Bg, nb)
            X, _LU, _piv, info = _lu.gesv(A, B, opts)
            return X.to_global(), info

        return core

    if key.routine == "posv":

        def core(Ag, Bg):
            A = HermitianMatrix.from_global(Ag, nb, uplo=Uplo.Lower)
            B = Matrix.from_global(Bg, nb)
            X, _L, info = _chol.posv(A, B, opts)
            return X.to_global(), info

        return core

    if key.routine == "gels":
        import jax.numpy as jnp

        def core(Ag, Bg):
            A = Matrix.from_global(Ag, nb)
            B = Matrix.from_global(Bg, nb)
            X = _qr.gels(A, B, opts)
            return X.to_global(), jnp.zeros((), jnp.int32)

        return core

    raise ValueError(f"unknown serving routine: {key.routine!r}")


def direct_call(routine: str, A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Unpadded, unbatched driver call — the reference result and the
    graceful-degradation fallback path.  Raises NumericalError on a
    nonzero info."""
    from ..drivers import chol as _chol
    from ..drivers import lu as _lu
    from ..drivers import qr as _qr
    from ..enums import Uplo
    from ..matrix.matrix import HermitianMatrix, Matrix

    faults.sleep("latency")
    faults.check("execute")
    nb = min(64, A.shape[1])
    if routine == "gesv":
        Bm = Matrix.from_global(B, nb)
        X, _LU, _piv, info = _lu.gesv(Matrix.from_global(A, nb), Bm)
        if int(info) != 0:
            raise NumericalError(f"gesv: singular U({int(info)})", int(info))
        return np.asarray(X.to_global())
    if routine == "posv":
        Bm = Matrix.from_global(B, nb)
        X, _L, info = _chol.posv(
            HermitianMatrix.from_global(A, nb, uplo=Uplo.Lower), Bm
        )
        if int(info) != 0:
            raise NumericalError(f"posv: not SPD at {int(info)}", int(info))
        return np.asarray(X.to_global())
    if routine == "gels":
        nbm = min(64, max(A.shape))
        X = _qr.gels(Matrix.from_global(A, nbm), Matrix.from_global(B, nbm))
        return np.asarray(X.to_global())
    raise ValueError(f"unknown serving routine: {routine!r}")


def _warm_inputs(key: BucketKey, batch: int) -> Tuple[np.ndarray, np.ndarray]:
    """Well-conditioned dummy operands for a warmup compile: identity A
    (SPD, pivot-free, full rank) and zero B."""
    dt = np.dtype(key.dtype)
    A = np.zeros((batch, key.m, key.n), dtype=dt)
    d = min(key.m, key.n)
    A[:, np.arange(d), np.arange(d)] = 1
    B = np.zeros((batch, key.m, key.nrhs), dtype=dt)
    return A, B


class ExecutableCache:
    """(BucketKey, batch) -> compiled executable, with manifest
    persistence.  Thread-safe: the service worker and warmup() may race
    on first build."""

    def __init__(self, manifest_path: Optional[str] = None):
        self._lock = threading.RLock()
        self._exes: Dict[Tuple[BucketKey, int], Callable] = {}
        self._entries: Set[Tuple[BucketKey, int]] = set()
        self.manifest_path = (
            manifest_path
            if manifest_path is not None
            else os.environ.get(WARMUP_ENV) or None
        )
        if self.manifest_path and os.path.exists(self.manifest_path):
            try:
                with open(self.manifest_path) as f:
                    self._entries.update(manifest_loads(f.read()))
            except (OSError, ValueError, KeyError):
                pass  # a corrupt manifest must never block serving

    # -- manifest ----------------------------------------------------------

    def entries(self) -> List[Tuple[BucketKey, int]]:
        with self._lock:
            return sorted(self._entries, key=lambda e: (e[0].label, e[1]))

    def _record(self, key: BucketKey, batch: int) -> None:
        with self._lock:
            if (key, batch) in self._entries:
                return
            self._entries.add((key, batch))
            self._flush_locked()

    def ensure_manifest(self, key: BucketKey, batches) -> None:
        """Record every batch point of a bucket's working set (the
        service registers both 1 and batch_max on first traffic, so a
        manifest captured after ANY dispatch warms both — lone and
        coalesced steady state alike)."""
        with self._lock:
            new = [b for b in batches if (key, int(b)) not in self._entries]
            if not new:
                return
            for b in new:
                self._entries.add((key, int(b)))
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self.manifest_path:
            return
        tmp = f"{self.manifest_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(manifest_dumps(self._entries) + "\n")
            os.replace(tmp, self.manifest_path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def save_manifest(self, path: Optional[str] = None) -> Optional[str]:
        """Write the current bucket set to ``path`` (or the configured
        manifest path).  Returns the path written."""
        with self._lock:
            if path is not None:
                self.manifest_path = path
            self._flush_locked()
            return self.manifest_path

    # -- executables -------------------------------------------------------

    def executable(self, key: BucketKey, batch: int) -> Callable:
        """Get (building + recording on miss) the compiled executable."""
        with self._lock:
            exe = self._exes.get((key, batch))
            if exe is not None:
                return exe
        faults.check("compile")  # cold builds only: a cache hit never fires
        import jax

        core = _build_core(key)
        name = f"serve.{key.label}.b{batch}"
        # donate the padded batch operands on accelerators: run() always
        # builds them fresh from the request's host arrays, so the
        # factorizations work in place instead of paying a batch-sized
        # copy per dispatch (XLA:CPU has no donation and would warn).
        jit_kw = {}
        if jax.default_backend() != "cpu":
            jit_kw["donate_argnums"] = (0, 1)
        # capture_cost=False: the AOT second compile would double every
        # warmup (metrics still splits compile-vs-run wall per bucket)
        exe = metrics.instrument_jit(
            jax.jit(jax.vmap(core), **jit_kw), name, capture_cost=False
        )
        with self._lock:
            exe = self._exes.setdefault((key, batch), exe)
        self._record(key, batch)
        return exe

    def run(self, key: BucketKey, A_batch: np.ndarray, B_batch: np.ndarray):
        """Execute one padded batch; returns host (X_batch, info_batch).

        Fault sites (aux/faults; every check is one bool when off):
        ``latency`` sleeps before dispatch, ``execute`` raises in place
        of the dispatch, ``result_corrupt`` NaN-poisons item 0 of X,
        ``info_nonzero`` forces item 0's info nonzero."""
        import jax.numpy as jnp

        faults.sleep("latency")
        faults.check("execute")
        exe = self.executable(key, A_batch.shape[0])
        X, info = exe(jnp.asarray(A_batch), jnp.asarray(B_batch))
        X = faults.corrupt("result_corrupt", np.asarray(X))
        info = faults.poison_info(
            "info_nonzero", np.atleast_1d(np.asarray(info))
        )
        return np.asarray(X), info

    # -- warmup ------------------------------------------------------------

    def warmup(
        self,
        path: Optional[str] = None,
        batch_max: Optional[int] = None,
        verbose: bool = False,
    ) -> int:
        """Pre-compile every manifest entry (plus ``path``'s entries if
        given).  Returns the number of executables compiled.  Per-bucket
        compile walls land in the ``serve.<bucket>.b<batch>.compile``
        timers; the whole pass under the ``serve.warmup`` timer."""
        with self._lock:  # the worker may add entries concurrently
            todo = list(self._entries)
        if path is not None and os.path.exists(path):
            with open(path) as f:
                for e in manifest_loads(f.read()):
                    if e not in todo:
                        todo.append(e)
        compiled = 0
        with metrics.phase("serve.warmup", always=True) as ph:
            for key, batch in sorted(todo, key=lambda e: (e[0].label, e[1])):
                if batch_max is not None and batch > batch_max:
                    continue
                with self._lock:
                    if (key, batch) in self._exes:
                        continue
                t0 = time.perf_counter()
                A, B = _warm_inputs(key, batch)
                X, info = self.run(key, A, B)
                compiled += 1
                if verbose:
                    print(
                        f"[serve.warmup] {key.label} b{batch}: "
                        f"{time.perf_counter() - t0:.2f}s"
                    )
        metrics.gauge("serve.warmup_s", ph.seconds)
        metrics.inc("serve.warmup_compiles", compiled)
        return compiled
