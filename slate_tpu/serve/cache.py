"""Executable cache + on-disk warmup manifest.

The cache maps ``(BucketKey, batch)`` to a compiled, metrics-
instrumented executable over padded global arrays.  Executables are
built lazily on first use; every build is appended to the warmup
manifest (``SLATE_TPU_WARMUP=/path.json`` or an explicit path), so a
deployment's steady-state bucket set accumulates across runs and
``warmup()`` can pre-compile the whole set at startup — after which a
stream of requests in warmed buckets is compile-free (the
``jit.compilations`` counter stays flat).

With ``SLATE_TPU_ARTIFACTS=/dir`` (or an explicit ``artifact_dir``)
the cache also consults a durable
:class:`~slate_tpu.serve.artifacts.ArtifactStore` before every cold
build and persists every build back to it, so a *fresh process*
pointed at the same directory restores the warmed executable set
(``restore()``) instead of recompiling it — the manifest stays the
recipe, the artifact store is the baked result.

Executable shape: ``fn(A_batch, B_batch) -> (X_batch, info_batch)``
with ``A: (batch, Mb, Nb)``, ``B: (batch, Mb, nrhs_b)`` — the drivers
vmapped over the leading axis (Matrix construction from the padded
globals happens inside the trace; tile layouts are static per bucket).
Only two batch points exist per key (1 and batch_max, see
``buckets.batch_bucket``), so the executable set stays bounded and
deterministic.
"""

from __future__ import annotations

import os
import threading
import time
import warnings
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from ..aux import faults, metrics
from ..exceptions import NumericalError
from .artifacts import ArtifactStore, store_from_env
from .buckets import BucketKey, manifest_dumps, manifest_loads

WARMUP_ENV = "SLATE_TPU_WARMUP"

#: manifest paths already warned about this process (warn once, not per
#: ExecutableCache — a fleet of services sharing one bad path should
#: not spam)
_warned_manifests: Set[str] = set()


def _build_core(key: BucketKey) -> Callable:
    """The unbatched core over padded globals for one bucket.  Driver
    imports are local: serve must stay importable before drivers are
    (the lazy ``serve/__init__`` keeps ``drivers/eig -> serve.buckets``
    acyclic).  The key's factorization schedule is threaded into the
    drivers via Option.Schedule, so a manifest captured from a
    recursive-schedule deployment precompiles the recursion shapes."""
    from ..drivers import chol as _chol
    from ..drivers import lu as _lu
    from ..drivers import qr as _qr
    from ..enums import Option, Uplo
    from ..matrix.matrix import HermitianMatrix, Matrix

    nb = key.nb
    opts = {Option.Schedule: key.schedule}

    if key.precision == "mixed":
        # mixed-precision bucket: low-precision factor + device-resident
        # IR (drivers/mixed.serve_mixed_core — fully traceable, classical
        # IR only).  Non-converged solves come back NaN-poisoned; the
        # service's corrupt-result validation re-solves those items on
        # the full-precision direct path and the bucket breaker demotes
        # persistently non-converging buckets — the fallback policy
        # lives in the service, never in the executable.
        from ..drivers import mixed as _mixed

        if key.routine not in ("gesv", "posv"):
            raise ValueError(
                "mixed-precision serving supports gesv/posv, "
                f"not {key.routine!r}"
            )

        def core(Ag, Bg):
            return _mixed.serve_mixed_core(
                key.routine, Ag, Bg, nb, key.schedule
            )

        return core

    if key.routine == "gesv":

        def core(Ag, Bg):
            A = Matrix.from_global(Ag, nb)
            B = Matrix.from_global(Bg, nb)
            X, _LU, _piv, info = _lu.gesv(A, B, opts)
            return X.to_global(), info

        return core

    if key.routine == "posv":

        def core(Ag, Bg):
            A = HermitianMatrix.from_global(Ag, nb, uplo=Uplo.Lower)
            B = Matrix.from_global(Bg, nb)
            X, _L, info = _chol.posv(A, B, opts)
            return X.to_global(), info

        return core

    if key.routine == "gels":
        import jax.numpy as jnp

        def core(Ag, Bg):
            A = Matrix.from_global(Ag, nb)
            B = Matrix.from_global(Bg, nb)
            X = _qr.gels(A, B, opts)
            return X.to_global(), jnp.zeros((), jnp.int32)

        return core

    raise ValueError(f"unknown serving routine: {key.routine!r}")


def direct_call(routine: str, A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Unpadded, unbatched driver call — the reference result and the
    graceful-degradation fallback path.  Raises NumericalError on a
    nonzero info."""
    from ..drivers import chol as _chol
    from ..drivers import lu as _lu
    from ..drivers import qr as _qr
    from ..enums import Uplo
    from ..matrix.matrix import HermitianMatrix, Matrix

    faults.sleep("latency")
    faults.check("execute")
    nb = min(64, A.shape[1])
    if routine == "gesv":
        Bm = Matrix.from_global(B, nb)
        X, _LU, _piv, info = _lu.gesv(Matrix.from_global(A, nb), Bm)
        if int(info) != 0:
            raise NumericalError(f"gesv: singular U({int(info)})", int(info))
        return np.asarray(X.to_global())
    if routine == "posv":
        Bm = Matrix.from_global(B, nb)
        X, _L, info = _chol.posv(
            HermitianMatrix.from_global(A, nb, uplo=Uplo.Lower), Bm
        )
        if int(info) != 0:
            raise NumericalError(f"posv: not SPD at {int(info)}", int(info))
        return np.asarray(X.to_global())
    if routine == "gels":
        nbm = min(64, max(A.shape))
        X = _qr.gels(Matrix.from_global(A, nbm), Matrix.from_global(B, nbm))
        return np.asarray(X.to_global())
    raise ValueError(f"unknown serving routine: {routine!r}")


def _warm_inputs(key: BucketKey, batch: int) -> Tuple[np.ndarray, np.ndarray]:
    """Well-conditioned dummy operands for a warmup compile: identity A
    (SPD, pivot-free, full rank) and zero B."""
    dt = np.dtype(key.dtype)
    A = np.zeros((batch, key.m, key.n), dtype=dt)
    d = min(key.m, key.n)
    A[:, np.arange(d), np.arange(d)] = 1
    B = np.zeros((batch, key.m, key.nrhs), dtype=dt)
    return A, B


class ExecutableCache:
    """(BucketKey, batch) -> compiled executable, with manifest
    persistence and (``artifact_dir`` / ``SLATE_TPU_ARTIFACTS``) an
    :class:`~slate_tpu.serve.artifacts.ArtifactStore` consulted
    *before* every cold build — restore beats recompile.  Thread-safe:
    the service worker, warmup() and restore() may race on first
    build."""

    def __init__(
        self,
        manifest_path: Optional[str] = None,
        artifact_dir: Optional[str] = None,
    ):
        self._lock = threading.RLock()
        self._exes: Dict[Tuple[BucketKey, int], Callable] = {}
        self._entries: Set[Tuple[BucketKey, int]] = set()
        # how each live executable came to be: "artifact" (export blob
        # deserialized) or "compile" (built here) — restore() reports it
        self._origin: Dict[Tuple[BucketKey, int], str] = {}
        self.artifacts: Optional[ArtifactStore] = store_from_env(artifact_dir)
        self.manifest_path = (
            manifest_path
            if manifest_path is not None
            else os.environ.get(WARMUP_ENV) or None
        )
        if self.manifest_path and os.path.exists(self.manifest_path):
            try:
                with open(self.manifest_path) as f:
                    self._entries.update(manifest_loads(f.read()))
            except (OSError, ValueError, KeyError, TypeError) as e:
                # a corrupt manifest must never block serving — but a
                # silently ignored one hides that every bucket will pay
                # a cold compile: count it and warn once per path
                metrics.inc("serve.manifest_corrupt")
                if self.manifest_path not in _warned_manifests:
                    _warned_manifests.add(self.manifest_path)
                    warnings.warn(
                        f"corrupt warmup manifest at {self.manifest_path!r}"
                        f" ({type(e).__name__}: {e}); starting with an "
                        "empty bucket set — steady state will recompile",
                        RuntimeWarning,
                        stacklevel=2,
                    )

    # -- manifest ----------------------------------------------------------

    def entries(self) -> List[Tuple[BucketKey, int]]:
        with self._lock:
            return sorted(self._entries, key=lambda e: (e[0].label, e[1]))

    def _record(self, key: BucketKey, batch: int) -> None:
        with self._lock:
            if (key, batch) in self._entries:
                return
            self._entries.add((key, batch))
            self._flush_locked()

    def ensure_manifest(self, key: BucketKey, batches) -> None:
        """Record every batch point of a bucket's working set (the
        service registers both 1 and batch_max on first traffic, so a
        manifest captured after ANY dispatch warms both — lone and
        coalesced steady state alike)."""
        with self._lock:
            new = [b for b in batches if (key, int(b)) not in self._entries]
            if not new:
                return
            for b in new:
                self._entries.add((key, int(b)))
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self.manifest_path:
            return
        tmp = f"{self.manifest_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(manifest_dumps(self._entries) + "\n")
            os.replace(tmp, self.manifest_path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def save_manifest(self, path: Optional[str] = None) -> Optional[str]:
        """Write the current bucket set to ``path`` (or the configured
        manifest path).  Returns the path written."""
        with self._lock:
            if path is not None:
                self.manifest_path = path
            self._flush_locked()
            return self.manifest_path

    # -- executables -------------------------------------------------------

    def _arg_specs(self, key: BucketKey, batch: int):
        """ShapeDtypeStructs of one executable's padded batch operands
        (the jax.export symbol table for save/load)."""
        import jax

        dt = np.dtype(key.dtype)
        return (
            jax.ShapeDtypeStruct((batch, key.m, key.n), dt),
            jax.ShapeDtypeStruct((batch, key.m, key.nrhs), dt),
        )

    def executable(self, key: BucketKey, batch: int) -> Callable:
        """Get the compiled executable: memory cache, then the artifact
        store (a verified ``jax.export`` blob re-jits without retracing
        the drivers), then a cold build — which is persisted back to
        the store so the *next* replica restores instead.  Every
        artifact-verification failure (stale/corrupt/load_fail) has
        already been counted by the store and lands here on the build
        path: the degradation is a recompile, never an error."""
        with self._lock:
            exe = self._exes.get((key, batch))
            if exe is not None:
                return exe
        import jax

        name = f"serve.{key.label}.b{batch}"
        origin = "compile"
        jitted = None
        if self.artifacts is not None:
            call = self.artifacts.load(key, batch)
            if call is not None:
                # re-jit of deserialized StableHLO: no Python retrace,
                # no jax lowering; the backend compile is served by the
                # store-seeded persistent XLA cache.  (Donation is not
                # re-applied — exported modules own their buffers.)
                jitted = jax.jit(call)
                origin = "artifact"
        if jitted is None:
            faults.check("compile")  # cold builds only: loads never fire
            core = _build_core(key)
            # donate the padded batch operands on accelerators: run()
            # always builds them fresh from the request's host arrays,
            # so the factorizations work in place instead of paying a
            # batch-sized copy per dispatch (XLA:CPU has no donation
            # and would warn).
            jit_kw = {}
            if jax.default_backend() != "cpu":
                jit_kw["donate_argnums"] = (0, 1)
            jitted = jax.jit(jax.vmap(core), **jit_kw)
            if self.artifacts is not None and not (
                self.artifacts.verified_cache_seed(key, batch)
            ):
                # persist for the next replica — exporting a NON-donated
                # jit of the same core (jax.export refuses donated
                # computations, which would demote every accelerator
                # bucket to the cache_seed rung; the loaded artifact
                # re-jits without donation anyway).  A load that just
                # verified a cache_seed entry for this fingerprint is
                # NOT re-saved: the rewrite would be byte-identical and
                # the export attempt is a full retrace on the worker
                # thread.
                export_target = (
                    jax.jit(jax.vmap(core)) if jit_kw else jitted
                )
                self.artifacts.save(
                    key, batch, export_target, self._arg_specs(key, batch)
                )
        # capture_cost=False: the AOT second compile would double every
        # warmup (metrics still splits compile-vs-run wall per bucket)
        exe = metrics.instrument_jit(jitted, name, capture_cost=False)
        with self._lock:
            prev = self._exes.setdefault((key, batch), exe)
            if prev is exe:
                self._origin[(key, batch)] = origin
            exe = prev
        self._record(key, batch)
        return exe

    def run(self, key: BucketKey, A_batch: np.ndarray, B_batch: np.ndarray):
        """Execute one padded batch; returns host (X_batch, info_batch).

        Fault sites (aux/faults; every check is one bool when off):
        ``latency`` sleeps before dispatch, ``execute`` raises in place
        of the dispatch, ``result_corrupt`` NaN-poisons item 0 of X,
        ``info_nonzero`` forces item 0's info nonzero."""
        import jax.numpy as jnp

        faults.sleep("latency")
        faults.check("execute")
        exe = self.executable(key, A_batch.shape[0])
        X, info = exe(jnp.asarray(A_batch), jnp.asarray(B_batch))
        X = faults.corrupt("result_corrupt", np.asarray(X))
        info = faults.poison_info(
            "info_nonzero", np.atleast_1d(np.asarray(info))
        )
        return np.asarray(X), info

    # -- warmup ------------------------------------------------------------

    def warmup(
        self,
        path: Optional[str] = None,
        batch_max: Optional[int] = None,
        verbose: bool = False,
    ) -> int:
        """Pre-compile every manifest entry (plus ``path``'s entries if
        given).  Returns the number of executables compiled — entries
        that ``executable()`` served from the artifact store instead
        are not counted (zero compiles happened; ``restore()`` is the
        pass that reports restores).  Per-bucket compile walls land in
        the ``serve.<bucket>.b<batch>.compile`` timers; the whole pass
        under the ``serve.warmup`` timer."""
        with self._lock:  # the worker may add entries concurrently
            todo = list(self._entries)
        if path is not None and os.path.exists(path):
            with open(path) as f:
                for e in manifest_loads(f.read()):
                    if e not in todo:
                        todo.append(e)
        compiled = 0
        with metrics.phase("serve.warmup", always=True) as ph:
            for key, batch in sorted(todo, key=lambda e: (e[0].label, e[1])):
                if batch_max is not None and batch > batch_max:
                    continue
                with self._lock:
                    if (key, batch) in self._exes:
                        continue
                t0 = time.perf_counter()
                A, B = _warm_inputs(key, batch)
                X, info = self.run(key, A, B)
                if self._origin.get((key, batch)) != "artifact":
                    compiled += 1  # an artifact hit compiled nothing
                if verbose:
                    print(
                        f"[serve.warmup] {key.label} b{batch}: "
                        f"{time.perf_counter() - t0:.2f}s"
                    )
        metrics.gauge("serve.warmup_s", ph.seconds)
        metrics.inc("serve.warmup_compiles", compiled)
        return compiled

    # -- restore (artifact-first cold start) -------------------------------

    def restore(
        self,
        batch_max: Optional[int] = None,
        verbose: bool = False,
        stop_check: Optional[Callable[[], bool]] = None,
    ) -> Dict[str, int]:
        """Bring every manifest entry live, artifact-first: load (or,
        where the store has nothing valid, compile) each executable and
        prime it with one dummy dispatch, so a subsequent steady-state
        stream never traces or compiles.  This is the cold-start path a
        fresh replica runs before reporting ``ready``.

        Per-entry failures (a fault-injected load, an execute fault on
        the priming dispatch, a poisoned artifact dir) are counted and
        skipped, never raised — a damaged store degrades the replica to
        recompiles-on-traffic, it does not keep it from coming up.

        Returns ``{"entries", "restored", "compiled", "failed",
        "skipped"}`` (restored = served from an export artifact;
        compiled = any other rung of the ladder, including cache_seed
        recompiles; skipped = already live when the pass reached it —
        e.g. traffic served while restoring built it first — so
        ``entries == restored + compiled + failed + skipped`` always
        holds).

        ``stop_check`` is polled between entries; True abandons the
        rest of the pass (the service passes its stopped flag so a
        replica torn down mid-restore does not keep compiling a large
        manifest for minutes on a daemon thread)."""
        with self._lock:
            todo = sorted(self._entries, key=lambda e: (e[0].label, e[1]))
        out = {
            "entries": 0, "restored": 0, "compiled": 0, "failed": 0,
            "skipped": 0,
        }
        with metrics.phase("serve.restore", always=True) as ph:
            for key, batch in todo:
                if stop_check is not None and stop_check():
                    metrics.inc("serve.restore_stopped")
                    break
                if batch_max is not None and batch > batch_max:
                    continue
                out["entries"] += 1
                with self._lock:
                    if (key, batch) in self._exes:
                        out["skipped"] += 1  # already live (a race won)
                        continue
                t0 = time.perf_counter()
                try:
                    A, B = _warm_inputs(key, batch)
                    self.run(key, A, B)  # loads-or-builds, then primes
                except Exception:  # noqa: BLE001 — degrade, never crash
                    out["failed"] += 1
                    metrics.inc("serve.restore_failed")
                    continue
                origin = self._origin.get((key, batch), "compile")
                out["restored" if origin == "artifact" else "compiled"] += 1
                if verbose:
                    print(
                        f"[serve.restore] {key.label} b{batch}: {origin} "
                        f"{time.perf_counter() - t0:.2f}s"
                    )
        metrics.gauge("serve.restore_s", ph.seconds)
        metrics.inc("serve.restore_restored", out["restored"])
        metrics.inc("serve.restore_compiled", out["compiled"])
        return out
