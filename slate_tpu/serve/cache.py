"""Executable cache + on-disk warmup manifest.

The cache maps ``(BucketKey, batch)`` to a compiled, metrics-
instrumented executable over padded global arrays.  Executables are
built lazily on first use; every build is appended to the warmup
manifest (``SLATE_TPU_WARMUP=/path.json`` or an explicit path), so a
deployment's steady-state bucket set accumulates across runs and
``warmup()`` can pre-compile the whole set at startup — after which a
stream of requests in warmed buckets is compile-free (the
``jit.compilations`` counter stays flat).

With ``SLATE_TPU_ARTIFACTS=/dir`` (or an explicit ``artifact_dir``)
the cache also consults a durable
:class:`~slate_tpu.serve.artifacts.ArtifactStore` before every cold
build and persists every build back to it, so a *fresh process*
pointed at the same directory restores the warmed executable set
(``restore()``) instead of recompiling it — the manifest stays the
recipe, the artifact store is the baked result.

With ``SLATE_TPU_DEVMON=1`` (aux/devmon) every cold build and artifact
restore also captures the executable's ``cost_analysis()`` (flops,
bytes accessed) and ``memory_analysis()`` (argument/output/temp/peak
bytes) into a per-``(BucketKey, batch)`` registry, persisted beside
each manifest entry (``"cost"`` field) and surfaced through
``SolverService.health()`` and the metrics JSONL —
``tools/roofline_report.py`` joins it with the execute timers into
compute- vs memory-bound verdicts per bucket.

Executable shape: ``fn(A_batch, B_batch) -> (X_batch, info_batch)``
with ``A: (batch, Mb, Nb)``, ``B: (batch, Mb, nrhs_b)`` — the drivers
vmapped over the leading axis (Matrix construction from the padded
globals happens inside the trace; tile layouts are static per bucket).
Only two batch points exist per key (1 and batch_max, see
``buckets.batch_bucket``), so the executable set stays bounded and
deterministic.  Solve-phase keys (the factor cache's trsm-only
family) take the FACTOR as their first operand, unbatched:
``fn(F: (Mb, Nb), B_batch) -> (X_batch, info_batch)`` via
``vmap(in_axes=(None, 0))`` — one factor serves the whole coalesced
batch without a batch-sized host copy or bb resident device copies.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from ..aux import devmon, faults, metrics, spans, sync
from ..exceptions import NumericalError
from .artifacts import ArtifactStore, store_from_env
from .buckets import (
    BucketKey,
    manifest_cost_loads,
    manifest_dumps,
    manifest_loads,
    mesh_fits,
    phase_flops,
    solve_factor_shape,
)

WARMUP_ENV = "SLATE_TPU_WARMUP"


def _device_id(device):
    """Stable priming identity of a dispatch placement (None = the
    default placement)."""
    return None if device is None else getattr(device, "id", device)

#: manifest paths already warned about this process (warn once, not per
#: ExecutableCache — a fleet of services sharing one bad path should
#: not spam)
_warned_manifests: Set[str] = set()


def _build_core(key: BucketKey) -> Callable:
    """The unbatched core over padded globals for one bucket.  Driver
    imports are local: serve must stay importable before drivers are
    (the lazy ``serve/__init__`` keeps ``drivers/eig -> serve.buckets``
    acyclic).  The key's factorization schedule is threaded into the
    drivers via Option.Schedule, so a manifest captured from a
    recursive-schedule deployment precompiles the recursion shapes."""
    from ..drivers import chol as _chol
    from ..drivers import lu as _lu
    from ..drivers import qr as _qr
    from ..enums import Option, Uplo
    from ..matrix.matrix import HermitianMatrix, Matrix

    nb = key.nb
    opts = {Option.Schedule: key.schedule}

    if key.mesh:
        # sharded bucket: the core is the explicit spmd program on the
        # key's submesh (parallel/spmd_core — distributed LU/Cholesky +
        # trsm pipelines under shard_map), wrapped to the cache's
        # batched calling convention by an unrolled trace-time loop —
        # never a vmap over shard_map (jax would replicate the mesh
        # axes).  Batch points beyond 1 exist so same-mesh-bucket
        # requests coalesce like the single-device lane; each item
        # still runs the full spmd pipeline, the loop just amortizes
        # the dispatch.
        import jax.numpy as jnp

        from ..parallel import spmd_core

        core1 = spmd_core.serve_core(key)

        def core(Ab, Bb):
            outs = [core1(Ab[i], Bb[i]) for i in range(Ab.shape[0])]
            X = jnp.stack([o[0] for o in outs])
            info = jnp.stack([jnp.reshape(o[1], ()) for o in outs])
            return X, info

        return core

    if key.phase == "solve":
        # trsm-only bucket (the factor cache's hit family): the first
        # operand is the bucket-padded FACTOR ([[LU,0],[0,I]] with the
        # rows of B pre-permuted on host for gesv, [[L,0],[0,I]] for
        # posv), not A — two triangular sweeps, O(n^2 nrhs) against the
        # full family's O(n^3).  Pure lax triangular algebra: no
        # Matrix/tile round trip, and the exported module is custom-
        # call-free on every backend where triangular_solve lowers
        # natively.
        import jax.numpy as jnp

        if key.routine == "gesv":

            def core(Fg, Bg):
                X = _lu.getrs_from_global(Fg, Bg, key.schedule)
                return X, jnp.zeros((), jnp.int32)

            return core

        if key.routine == "posv":

            def core(Fg, Bg):
                X = _chol.potrs_from_global(Fg, Bg, key.schedule)
                return X, jnp.zeros((), jnp.int32)

            return core

        if key.routine == "gels":
            # least squares from the packed QR factor (V/R + cached
            # compact-WY T panels, buckets.solve_factor_shape): blocked
            # Q^H apply + one trsm — O(m n nrhs) per solve against the
            # full family's O(m n^2) refactor
            def core(Fg, Bg):
                X = _qr.gels_solve_from_global(Fg, Bg, key.m, key.nb)
                return X, jnp.zeros((), jnp.int32)

            return core

        raise ValueError(
            f"solve-phase serving supports gesv/posv/gels, "
            f"not {key.routine!r}"
        )

    if key.tag == "abft" and key.routine in ("gesv", "posv"):
        # checksummed bucket (integrity/abft): the same driver pipeline
        # plus in-trace post-factor and post-trsm checksum checks whose
        # per-item verdict rides out as info = ABFT_BAD (< 0) — the
        # service's delivery certification reads it for free.  The
        # "abft" tag is a reserved options-fingerprint value: manifests
        # and artifact fingerprints key the checksummed executable
        # apart from its plain sibling without a BucketKey change.
        from ..integrity import abft as _abft

        return _abft.build_core(key.routine, nb, key.schedule)

    if key.precision == "mixed":
        # mixed-precision bucket: low-precision factor + device-resident
        # IR (drivers/mixed.serve_mixed_core — fully traceable, classical
        # IR only).  Non-converged solves come back NaN-poisoned; the
        # service's corrupt-result validation re-solves those items on
        # the full-precision direct path and the bucket breaker demotes
        # persistently non-converging buckets — the fallback policy
        # lives in the service, never in the executable.
        from ..drivers import mixed as _mixed

        if key.routine not in ("gesv", "posv"):
            raise ValueError(
                "mixed-precision serving supports gesv/posv, "
                f"not {key.routine!r}"
            )

        def core(Ag, Bg):
            return _mixed.serve_mixed_core(
                key.routine, Ag, Bg, nb, key.schedule
            )

        return core

    if key.routine == "gesv":

        def core(Ag, Bg):
            A = Matrix.from_global(Ag, nb)
            B = Matrix.from_global(Bg, nb)
            X, _LU, _piv, info = _lu.gesv(A, B, opts)
            return X.to_global(), info

        return core

    if key.routine == "posv":

        def core(Ag, Bg):
            A = HermitianMatrix.from_global(Ag, nb, uplo=Uplo.Lower)
            B = Matrix.from_global(Bg, nb)
            X, _L, info = _chol.posv(A, B, opts)
            return X.to_global(), info

        return core

    if key.routine == "gels":
        import jax.numpy as jnp

        def core(Ag, Bg):
            A = Matrix.from_global(Ag, nb)
            B = Matrix.from_global(Bg, nb)
            X = _qr.gels(A, B, opts)
            return X.to_global(), jnp.zeros((), jnp.int32)

        return core

    raise ValueError(f"unknown serving routine: {key.routine!r}")


def direct_call(routine: str, A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Unpadded, unbatched driver call — the reference result and the
    graceful-degradation fallback path.  Raises NumericalError on a
    nonzero info."""
    from ..drivers import chol as _chol
    from ..drivers import lu as _lu
    from ..drivers import qr as _qr
    from ..enums import Uplo
    from ..matrix.matrix import HermitianMatrix, Matrix

    faults.sleep("latency")
    faults.check("execute")
    nb = min(64, A.shape[1])
    if routine == "gesv":
        Bm = Matrix.from_global(B, nb)
        X, _LU, _piv, info = _lu.gesv(Matrix.from_global(A, nb), Bm)
        if int(info) != 0:
            raise NumericalError(
                f"gesv: singular U({int(info)})", int(info)
            ).with_context(routine=routine)
        # sdc_solve on the direct path too: the fallback/re-execution
        # lane is hardware like any other (finite wrong value on the
        # flat first element; certification must catch it)
        return faults.perturb("sdc_solve", np.asarray(X.to_global()))
    if routine == "posv":
        Bm = Matrix.from_global(B, nb)
        X, _L, info = _chol.posv(
            HermitianMatrix.from_global(A, nb, uplo=Uplo.Lower), Bm
        )
        if int(info) != 0:
            raise NumericalError(
                f"posv: not SPD at {int(info)}", int(info)
            ).with_context(routine=routine)
        return faults.perturb("sdc_solve", np.asarray(X.to_global()))
    if routine == "gels":
        nbm = min(64, max(A.shape))
        X = _qr.gels(Matrix.from_global(A, nbm), Matrix.from_global(B, nbm))
        return np.asarray(X.to_global())
    raise ValueError(f"unknown serving routine: {routine!r}")


def _warm_inputs(key: BucketKey, batch: int) -> Tuple[np.ndarray, np.ndarray]:
    """Well-conditioned dummy operands for a warmup compile: identity A
    (SPD, pivot-free, full rank — and a valid LU/Cholesky factor for
    the solve-phase family, whose first operand is the unbatched
    factor; for the gels pack the identity V/R with zero T panels is a
    valid QR of the identity — zero T makes every block reflector the
    identity apply) and zero B."""
    dt = np.dtype(key.dtype)
    d = min(key.m, key.n)
    if key.phase == "solve":
        A = np.zeros(solve_factor_shape(key), dtype=dt)
        A[np.arange(d), np.arange(d)] = 1
    else:
        A = np.zeros((batch, key.m, key.n), dtype=dt)
        A[:, np.arange(d), np.arange(d)] = 1
    B = np.zeros((batch, key.m, key.nrhs), dtype=dt)
    return A, B


class ExecutableCache:
    """(BucketKey, batch) -> compiled executable, with manifest
    persistence and (``artifact_dir`` / ``SLATE_TPU_ARTIFACTS``) an
    :class:`~slate_tpu.serve.artifacts.ArtifactStore` consulted
    *before* every cold build — restore beats recompile.  Thread-safe:
    the service worker, warmup() and restore() may race on first
    build."""

    def __init__(
        self,
        manifest_path: Optional[str] = None,
        artifact_dir: Optional[str] = None,
    ):
        # sync.RLock: plain threading.RLock unless SLATE_TPU_SYNC_CHECK
        # armed the race plane.  The worker pool, warmup() and
        # restore() all race on the tables below — the annotations are
        # ground truth for the lock-discipline / race-guarded-by rules
        self._lock = sync.RLock(name="cache.ExecutableCache._lock")
        self._exes: Dict[Tuple[BucketKey, int], Callable] = {}  # guarded by: _lock
        self._entries: Set[Tuple[BucketKey, int]] = set()  # guarded by: _lock
        # how each live executable came to be: "artifact" (export blob
        # deserialized) or "compile" (built here) — restore() reports it
        self._origin: Dict[Tuple[BucketKey, int], str] = {}  # guarded by: _lock
        # device ids each entry has dispatched on (None = default
        # placement): warmup/restore prime every replica device that is
        # not in here yet, so multi-replica steady state is compile-free
        # on EVERY device, not just the first one traffic happened to hit
        self._primed: Dict[Tuple[BucketKey, int], Set] = {}  # guarded by: _lock
        # single-flight cold builds: (key, batch) -> Event while one
        # thread builds.  The replica worker pool spreads a same-bucket
        # burst across lanes on purpose, so without this every lane
        # would pay the full trace+compile (~10-25 s per f64 shape) for
        # the SAME executable; the pre-placement single worker
        # serialized builds for free
        self._building: Dict[Tuple[BucketKey, int], threading.Event] = {}  # guarded by: _lock
        # per-executable cost/memory registry (aux/devmon build-time
        # capture): (key, batch) -> {"flops", "bytes_accessed",
        # "argument_bytes", "output_bytes", "temp_bytes", "peak_bytes",
        # ...}.  Persisted beside each manifest entry ("cost" field) so
        # a restored process has the evidence without recapturing
        self._costs: Dict[Tuple[BucketKey, int], dict] = {}  # guarded by: _lock
        self.artifacts: Optional[ArtifactStore] = store_from_env(artifact_dir)
        self.manifest_path = (
            manifest_path
            if manifest_path is not None
            else os.environ.get(WARMUP_ENV) or None
        )
        if self.manifest_path and os.path.exists(self.manifest_path):
            try:
                with open(self.manifest_path) as f:
                    doc = json.load(f)  # one parse feeds both loaders
                self._entries.update(manifest_loads(doc))
                self._costs.update(manifest_cost_loads(doc))
            except (OSError, ValueError, KeyError, TypeError) as e:
                # a corrupt manifest must never block serving — but a
                # silently ignored one hides that every bucket will pay
                # a cold compile: count it and warn once per path
                metrics.inc("serve.manifest_corrupt")
                if self.manifest_path not in _warned_manifests:
                    _warned_manifests.add(self.manifest_path)
                    warnings.warn(
                        f"corrupt warmup manifest at {self.manifest_path!r}"
                        f" ({type(e).__name__}: {e}); starting with an "
                        "empty bucket set — steady state will recompile",
                        RuntimeWarning,
                        stacklevel=2,
                    )

    # -- manifest ----------------------------------------------------------

    def entries(self) -> List[Tuple[BucketKey, int]]:
        with self._lock:
            return sorted(self._entries, key=lambda e: (e[0].label, e[1]))

    def _record(self, key: BucketKey, batch: int) -> None:
        with self._lock:
            if (key, batch) in self._entries:
                return
            self._entries.add((key, batch))
            self._flush_locked()

    def ensure_manifest(self, key: BucketKey, batches) -> None:
        """Record every batch point of a bucket's working set (the
        service registers both 1 and batch_max on first traffic, so a
        manifest captured after ANY dispatch warms both — lone and
        coalesced steady state alike)."""
        with self._lock:
            new = [b for b in batches if (key, int(b)) not in self._entries]
            if not new:
                return
            for b in new:
                self._entries.add((key, int(b)))
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self.manifest_path:
            return
        tmp = f"{self.manifest_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(manifest_dumps(self._entries, self._costs) + "\n")
            os.replace(tmp, self.manifest_path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def save_manifest(self, path: Optional[str] = None) -> Optional[str]:
        """Write the current bucket set to ``path`` (or the configured
        manifest path).  Returns the path written."""
        with self._lock:
            if path is not None:
                self.manifest_path = path
            self._flush_locked()
            return self.manifest_path

    # -- cost/memory registry (aux/devmon build-time capture) --------------

    def cost(self, key: BucketKey, batch: int) -> Optional[dict]:
        """The captured cost/memory record of one executable, or None
        when devmon never saw it build (devmon off, capture failure,
        or a pre-cost manifest)."""
        with self._lock:
            c = self._costs.get((key, int(batch)))
            return dict(c) if c else None

    def cost_registry(self) -> Dict[Tuple[BucketKey, int], dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._costs.items()}

    def costs_by_label(self) -> Dict[str, Dict[int, dict]]:
        """Registry re-keyed ``{bucket label: {batch: record}}`` — the
        join shape health() and the report tools consume."""
        out: Dict[str, Dict[int, dict]] = {}
        with self._lock:
            for (key, batch), c in self._costs.items():
                out.setdefault(key.label, {})[int(batch)] = dict(c)
        return out

    def _capture_cost(self, key: BucketKey, batch: int, jitted,
                      name: str) -> None:
        """Devmon build-time capture: AOT lower+compile ``jitted`` at
        this entry's arg specs, read ``cost_analysis`` +
        ``memory_analysis``, record under ``name`` (the metrics cost
        registry -> JSONL) and persist beside the manifest entry.
        One bool when devmon is off; an already-known entry (restored
        from a cost-bearing manifest) is never recaptured — the extra
        backend compile is paid at most once per (bucket, batch) per
        manifest lifetime.  Capture failure degrades to a counted
        miss, never a build error."""
        if not devmon.is_on():
            return
        with self._lock:
            known = self._costs.get((key, batch))
        if known is not None and known.get("device_kind") in (
            None, devmon.default_device_kind()
        ):
            # restored from a cost-bearing manifest on the same device
            # kind: the capture is skipped, but the evidence must
            # still reach THIS process's metrics registry — a
            # warm-restarted replica's JSONL otherwise carries run
            # timers with zero cost rows and roofline_report fails its
            # gate on a healthy stream
            metrics.record_cost(name, known)
            return
        if known is not None:
            # foreign evidence: the manifest was captured on another
            # backend (a CPU dev box feeding a TPU replica) — serving
            # its flops/bytes under this device's roofs would
            # mis-classify every bucket, so recapture and overwrite
            metrics.inc("serve.cost_foreign_recaptured")
        # NOTE: the capture executable cannot replace the dispatch jit
        # (AOT executables are committed to one device; run() needs
        # jit's per-device variants for replica pinning), so this IS a
        # second backend compile — cold-build-only, devmon-gated, and
        # timed below so warmup cost stays attributable.  record=False:
        # the record lands once below, after flops_model is attached
        t0 = time.perf_counter()
        _compiled, cost = devmon.capture_jitted(
            jitted, self._arg_specs(key, batch), name=name, record=False,
        )
        metrics.observe(f"{name}.cost_capture", time.perf_counter() - t0)
        if cost is None:
            metrics.inc("serve.cost_capture_failed")
            if known is not None:
                # a failed recapture must not leave the foreign record
                # live: no evidence beats wrong evidence
                with self._lock:
                    self._costs.pop((key, batch), None)
                    self._flush_locked()
            return
        # the hand-model FLOP count rides along as cross-check AND as
        # the rate fallback: vendor custom calls (CPU trsm/getrf)
        # report no XLA flops, and a warmed solve bucket must still be
        # roofline-classifiable (bench.py keeps the same gflops_model
        # convention)
        try:
            cost.setdefault("flops_model", phase_flops(key, batch))
        except Exception:  # noqa: BLE001 — attribution never breaks a build
            pass
        metrics.record_cost(name, cost)
        metrics.inc("serve.cost_captured")
        with self._lock:
            self._costs[(key, batch)] = cost
            self._flush_locked()

    # -- executables -------------------------------------------------------

    def _arg_specs(self, key: BucketKey, batch: int):
        """ShapeDtypeStructs of one executable's padded batch operands
        (the jax.export symbol table for save/load).  Solve-phase keys
        take the factor unbatched."""
        import jax

        dt = np.dtype(key.dtype)
        A_spec = (
            jax.ShapeDtypeStruct(solve_factor_shape(key), dt)
            if key.phase == "solve"
            else jax.ShapeDtypeStruct((batch, key.m, key.n), dt)
        )
        return (
            A_spec,
            jax.ShapeDtypeStruct((batch, key.m, key.nrhs), dt),
        )

    def is_live(self, key: BucketKey, batch: int) -> bool:
        """Whether the (key, batch) executable is already built —
        a cheap probe (never triggers a build) for callers that must
        stay compile-free, e.g. the sharded lane's coalescer, which
        batches only at batch points a warmup has already realized."""
        with self._lock:
            return (key, batch) in self._exes

    def executable(self, key: BucketKey, batch: int) -> Callable:
        """Get the compiled executable: memory cache, then the artifact
        store (a verified ``jax.export`` blob re-jits without retracing
        the drivers), then a cold build — which is persisted back to
        the store so the *next* replica restores instead.  Every
        artifact-verification failure (stale/corrupt/load_fail) has
        already been counted by the store and lands here on the build
        path: the degradation is a recompile, never an error."""
        while True:
            with self._lock:
                exe = self._exes.get((key, batch))
                if exe is not None:
                    return exe
                ev = self._building.get((key, batch))
                if ev is None:
                    ev = self._building[(key, batch)] = threading.Event()
                    break  # this thread owns the build
            # another thread is already building this executable: wait
            # it out, then re-check.  If that build FAILED the entry is
            # still absent and the loop takes over (a chaos compile
            # fault must not strand the waiters — each raises or builds
            # on its own terms).
            ev.wait()
        name = f"serve.{key.label}.b{batch}"
        try:
            return self._build_locked_out(key, batch, name)
        finally:
            with self._lock:
                self._building.pop((key, batch), None)
            ev.set()

    def _build_locked_out(self, key: BucketKey, batch: int, name: str):
        """The build half of :meth:`executable` — runs OUTSIDE the
        cache lock (compiles are seconds-to-minutes) under the
        single-flight guard the caller holds."""
        sp = spans.start("build", bucket=key.label, batch=batch) \
            if spans.is_on() else None
        try:
            exe, origin = self._build_inner(key, batch, name)
        except BaseException as e:
            spans.end(sp, outcome=type(e).__name__)
            raise
        # origin annotates whether a mid-traffic cold build actually
        # compiled or came from the artifact store
        spans.end(sp, outcome="ok", origin=origin)
        return exe

    def _build_inner(self, key: BucketKey, batch: int, name: str):
        import jax

        origin = "compile"
        jitted = None
        if self.artifacts is not None:
            call = self.artifacts.load(key, batch)
            if call is not None:
                # re-jit of deserialized StableHLO: no Python retrace,
                # no jax lowering; the backend compile is served by the
                # store-seeded persistent XLA cache.  (Donation is not
                # re-applied — exported modules own their buffers.)
                jitted = jax.jit(call)
                origin = "artifact"
        if jitted is None:
            faults.check("compile")  # cold builds only: loads never fire
            core = _build_core(key)
            if key.mesh:
                # sharded core: batching is the core's own unrolled
                # loop; no donation (the spmd program's operands are
                # resharded at the shard_map boundary) and no vmap
                jitted = jax.jit(core)
                jit_kw = {}
            else:
                # donate the padded batch operands on accelerators:
                # run() always builds them fresh from the request's
                # host arrays, so the factorizations work in place
                # instead of paying a batch-sized copy per dispatch
                # (XLA:CPU has no donation and would warn).  Solve-
                # phase cores map over B only: the factor is ONE
                # unbatched operand shared by the whole batch — and
                # possibly the fabric arena's device-resident copy, so
                # it is never donated (donation would invalidate the
                # arena's buffer after one dispatch).
                in_axes = (None, 0) if key.phase == "solve" else (0, 0)
                jit_kw = {}
                if jax.default_backend() != "cpu":
                    jit_kw["donate_argnums"] = (
                        (1,) if key.phase == "solve" else (0, 1)
                    )
                jitted = jax.jit(jax.vmap(core, in_axes=in_axes), **jit_kw)
            if self.artifacts is not None and not (
                self.artifacts.verified_cache_seed(key, batch)
            ):
                # persist for the next replica — exporting a NON-donated
                # jit of the same core (jax.export refuses donated
                # computations, which would demote every accelerator
                # bucket to the cache_seed rung; the loaded artifact
                # re-jits without donation anyway).  A load that just
                # verified a cache_seed entry for this fingerprint is
                # NOT re-saved: the rewrite would be byte-identical and
                # the export attempt is a full retrace on the worker
                # thread.
                export_target = (
                    jax.jit(jax.vmap(core, in_axes=in_axes))
                    if jit_kw else jitted
                )
                self.artifacts.save(
                    key, batch, export_target, self._arg_specs(key, batch)
                )
        # devmon build-time capture (cold build AND artifact restore):
        # flops/bytes + argument/output/temp/peak bytes per (bucket,
        # batch), recorded to metrics and persisted beside the manifest
        # entry.  Gated on devmon (one bool when off) because the AOT
        # lowering is a second backend compile of the program
        self._capture_cost(key, batch, jitted, name)
        # capture_cost=False: instrument_jit's own AOT capture would
        # double every warmup even with devmon off (metrics still
        # splits compile-vs-run wall per bucket; devmon owns cost)
        exe = metrics.instrument_jit(jitted, name, capture_cost=False)
        with self._lock:
            prev = self._exes.setdefault((key, batch), exe)
            if prev is exe:
                self._origin[(key, batch)] = origin
            exe = prev
        self._record(key, batch)
        return exe, origin

    def run(
        self,
        key: BucketKey,
        A_batch: np.ndarray,
        B_batch: np.ndarray,
        device=None,
    ):
        """Execute one padded batch; returns host (X_batch, info_batch).

        ``device`` pins the dispatch (and its per-device compiled
        variant) to one device — the replica-placement path; None runs
        on the default placement exactly as before.

        Fault sites (aux/faults; every check is one bool when off):
        ``latency`` sleeps before dispatch, ``execute`` raises in place
        of the dispatch, ``result_corrupt`` NaN-poisons item 0 of X,
        ``info_nonzero`` forces item 0's info nonzero."""
        import jax
        import jax.numpy as jnp

        faults.sleep("latency")
        faults.check("execute")
        # the batch point: the leading axis of A for the full family,
        # of B for the solve family (whose factor operand is unbatched)
        batch = (
            B_batch.shape[0] if key.phase == "solve" else A_batch.shape[0]
        )
        exe = self.executable(key, batch)
        if device is not None and not key.mesh:
            # straight host -> replica-device transfer: an asarray first
            # would commit the batch to the default device and pay a
            # second device-to-device hop, funneling the whole fleet's
            # traffic through device 0's memory
            A = jax.device_put(A_batch, device)
            B = jax.device_put(B_batch, device)
        else:
            A = jnp.asarray(A_batch)
            B = jnp.asarray(B_batch)
        X, info = exe(A, B)
        with self._lock:
            self._primed.setdefault((key, batch), set()).add(
                _device_id(None if key.mesh else device)
            )
        X = faults.corrupt("result_corrupt", np.asarray(X))
        if key.routine in ("gesv", "posv"):
            # sdc_solve: a device returning FINITE garbage (unlike
            # result_corrupt's NaN) — invisible to the finiteness
            # fence by construction; only delivery certification
            # (integrity/) can catch it.  Scoped to the routines the
            # certificate covers: injecting into gels (whose LS
            # residual admits no cheap fence) would be an escape no
            # configuration can defend, flagging chaos runs forever
            X = faults.perturb("sdc_solve", np.asarray(X))
        info = faults.poison_info(
            "info_nonzero", np.atleast_1d(np.asarray(info))
        )
        return np.asarray(X), info

    # -- warmup / restore (one loop, per-caller error policy) --------------

    def _live_todo(self, batch_max=None, extra_path=None):
        """The sorted (key, batch) work list both :meth:`warmup` and
        :meth:`restore` walk: manifest entries (plus an extra manifest
        file's), minus batch points past ``batch_max`` and minus
        mesh-keyed entries this process cannot realize (a 2x4 entry on
        a 1-device box — counted ``serve.mesh_unfit_skipped``, a
        replica warms only what its mesh can run).  Returns
        ``(todo, mesh_unfit_count)``."""
        with self._lock:  # the workers may add entries concurrently
            todo = list(self._entries)
        if extra_path is not None and os.path.exists(extra_path):
            with open(extra_path) as f:
                for e in manifest_loads(f.read()):
                    if e not in todo:
                        todo.append(e)
        todo.sort(key=lambda e: (e[0].label, e[1]))
        out = []
        unfit = 0
        ndev = None
        for key, batch in todo:
            if key.mesh:
                if batch < 1:
                    # malformed entry (hand-edited / foreign writer)
                    metrics.inc("serve.manifest_bad_batch")
                    continue
                if ndev is None:
                    import jax

                    ndev = len(jax.devices())
                if not mesh_fits(key.mesh, ndev):
                    unfit += 1
                    metrics.inc("serve.mesh_unfit_skipped")
                    continue
            if batch_max is not None and batch > batch_max:
                continue
            out.append((key, batch))
        return out, unfit

    def _bring_live(
        self,
        todo,
        devices=None,
        on_error: Optional[Callable] = None,
        stop_check: Optional[Callable[[], bool]] = None,
        verbose: bool = False,
        tag: str = "warmup",
    ):
        """The ONE loop behind :meth:`warmup` and :meth:`restore` —
        placement plumbing lands here exactly once.  Brings each entry
        live (artifact-first via :meth:`executable`) and primes it with
        one dummy dispatch on every device in ``devices`` it has not
        dispatched on yet (replica pinning: multi-replica steady state
        must be compile-free on EVERY replica device).  Mesh-keyed
        entries prime once — their placement is the mesh itself.

        Per-caller error policy: ``on_error=None`` propagates the
        first failure (warmup semantics — the caller wants to know its
        precompile failed); a callable receives ``(key, batch, exc)``
        and the entry is reported ``failed`` (restore semantics —
        degrade, never crash).  ``stop_check`` is polled between
        entries; True abandons the rest (``serve.restore_stopped``).

        Yields ``(key, batch, outcome, origin)`` rows with outcome in
        ``restored`` (came live from an export artifact) / ``compiled``
        (any other rung) / ``skipped`` (already live and primed
        everywhere requested) / ``failed``."""
        devs = [d for d in (devices if devices else [None])]
        # dedupe while preserving replica order (replicas may share a
        # device when the pool is smaller than the replica count)
        seen: Set = set()
        devs = [
            d for d in devs
            if _device_id(d) not in seen and not seen.add(_device_id(d))
        ]
        for key, batch in todo:
            if stop_check is not None and stop_check():
                metrics.inc("serve.restore_stopped")
                break
            want = [None] if key.mesh else devs
            with self._lock:
                live = (key, batch) in self._exes
                primed = set(self._primed.get((key, batch), ()))
            need = [d for d in want if _device_id(d) not in primed]
            if live and not need:
                yield key, batch, "skipped", None
                continue
            t0 = time.perf_counter()
            sp = spans.start(tag, lane=tag, bucket=key.label, batch=batch) \
                if spans.is_on() else None
            try:
                A, B = _warm_inputs(key, batch)
                for d in (need or want):
                    # loads-or-builds on the first device, then primes
                    # the per-device variants (subclassed caches keep
                    # the legacy 3-arg run() for the default placement)
                    if d is None:
                        self.run(key, A, B)
                    else:
                        self.run(key, A, B, device=d)
            except Exception as e:  # noqa: BLE001 — policy decides
                spans.end(sp, outcome="failed", error=type(e).__name__)
                if on_error is None:
                    raise
                on_error(key, batch, e)
                yield key, batch, "failed", None
                continue
            # under the lock: a worker-thread cold build may be
            # writing _origin concurrently with this pass (a true
            # positive the whole-program guarded-by run surfaced)
            with self._lock:
                origin = self._origin.get((key, batch), "compile")
            if live:
                # the executable predates this pass; only new devices
                # were primed — no fresh restore/compile to report, but
                # the per-device backend compiles are real cold-start
                # budget, so they are counted and printed, not hidden
                outcome = "skipped"
                primes = len(need)
            else:
                outcome = "restored" if origin == "artifact" else "compiled"
                primes = max(0, len(need or want) - 1)
            if primes:
                metrics.inc("serve.device_primes", primes)
            # the artifact-restore outcome rides on the entry's span:
            # restored-vs-compiled-vs-skipped is THE cold-start question
            spans.end(sp, outcome=outcome, origin=origin, primes=primes)
            if verbose:
                extra = f" +{primes} device prime(s)" if primes else ""
                print(
                    f"[serve.{tag}] {key.label} b{batch}: "
                    f"{'primed' if live else origin}"
                    f"{extra} {time.perf_counter() - t0:.2f}s"
                )
            yield key, batch, outcome, origin

    def warmup(
        self,
        path: Optional[str] = None,
        batch_max: Optional[int] = None,
        devices=None,
        verbose: bool = False,
    ) -> int:
        """Pre-compile every manifest entry (plus ``path``'s entries if
        given), priming each ``devices`` entry so a replica pool's
        steady state never compiles.  Returns the number of
        executables compiled — entries that ``executable()`` served
        from the artifact store instead are not counted (zero compiles
        happened; ``restore()`` is the pass that reports restores).
        Errors propagate (the caller asked for a precompile and should
        know it failed).  Per-bucket compile walls land in the
        ``serve.<bucket>.b<batch>.compile`` timers; the whole pass
        under the ``serve.warmup`` timer."""
        todo, _unfit = self._live_todo(batch_max=batch_max, extra_path=path)
        compiled = 0
        with metrics.phase("serve.warmup", always=True) as ph:
            for _k, _b, outcome, _origin in self._bring_live(
                todo, devices=devices, on_error=None, verbose=verbose,
                tag="warmup",
            ):
                if outcome == "compiled":
                    compiled += 1  # an artifact hit compiled nothing
        metrics.gauge("serve.warmup_s", ph.seconds)
        metrics.inc("serve.warmup_compiles", compiled)
        return compiled

    def restore(
        self,
        batch_max: Optional[int] = None,
        verbose: bool = False,
        stop_check: Optional[Callable[[], bool]] = None,
        devices=None,
    ) -> Dict[str, int]:
        """Bring every manifest entry live, artifact-first: load (or,
        where the store has nothing valid, compile) each executable and
        prime it with one dummy dispatch per ``devices`` entry, so a
        subsequent steady-state stream never traces or compiles on any
        replica.  This is the cold-start path a fresh replica runs
        before reporting ``ready``.

        Per-entry failures (a fault-injected load, an execute fault on
        the priming dispatch, a poisoned artifact dir) are counted and
        skipped, never raised — a damaged store degrades the replica to
        recompiles-on-traffic, it does not keep it from coming up.

        Returns ``{"entries", "restored", "compiled", "failed",
        "skipped"}`` (restored = served from an export artifact;
        compiled = any other rung of the ladder, including cache_seed
        recompiles; skipped = already live when the pass reached it —
        e.g. traffic served while restoring built it first — so
        ``entries == restored + compiled + failed + skipped`` always
        holds), plus ``mesh_unfit`` when manifest entries were skipped
        because their mesh shape does not fit this process's devices.

        ``stop_check`` is polled between entries; True abandons the
        rest of the pass (the service passes its stopped flag so a
        replica torn down mid-restore does not keep compiling a large
        manifest for minutes on a daemon thread)."""
        todo, unfit = self._live_todo(batch_max=batch_max)
        out = {
            "entries": 0, "restored": 0, "compiled": 0, "failed": 0,
            "skipped": 0,
        }
        if unfit:
            out["mesh_unfit"] = unfit

        def on_error(key, batch, exc):
            metrics.inc("serve.restore_failed")

        with metrics.phase("serve.restore", always=True) as ph:
            for _k, _b, outcome, _origin in self._bring_live(
                todo, devices=devices, on_error=on_error,
                stop_check=stop_check, verbose=verbose, tag="restore",
            ):
                out["entries"] += 1
                out[outcome] += 1
        metrics.gauge("serve.restore_s", ph.seconds)
        metrics.inc("serve.restore_restored", out["restored"])
        metrics.inc("serve.restore_compiled", out["compiled"])
        return out

    def prime(
        self,
        entries=None,
        devices=None,
        batch_max: Optional[int] = None,
        verbose: bool = False,
        stop_check: Optional[Callable[[], bool]] = None,
        tag: str = "prime",
    ) -> Dict[str, int]:
        """Partial :meth:`_bring_live` by plan: bring a CALLER-ORDERED
        ``(key, batch)`` subset live, artifact-first, priming each
        entry's per-``devices`` dispatch variants.  This is the
        scale-up lane's warm path (``SolverService.add_replica``) and
        the applicator of a predictive
        :class:`~slate_tpu.scale.warmup_plan.WarmupPlan` — the caller's
        order IS the ranking, so a priming deadline truncates from the
        plan's bottom, not alphabetically.

        ``entries=None`` walks the full live manifest (restore
        semantics over the whole working set).  Explicit entries are
        registered into the manifest first — a planned bucket this
        process has never dispatched still warms, and a later
        restart's restore pass inherits it.

        Failures are counted and skipped, never raised (a scale-up
        lane degrades to compile-on-traffic; it does not abort the
        scale-up).  Returns ``{"entries", "restored", "compiled",
        "failed", "skipped"}``."""
        if entries is None:
            todo, _unfit = self._live_todo(batch_max=batch_max)
        else:
            todo = []
            for key, batch in entries:
                batch = int(batch)
                if (batch_max is not None and not key.mesh
                        and batch > batch_max):
                    continue
                self.ensure_manifest(key, (batch,))
                todo.append((key, batch))
        out = {
            "entries": 0, "restored": 0, "compiled": 0, "failed": 0,
            "skipped": 0,
        }

        def on_error(key, batch, exc):
            metrics.inc("serve.prime_failed")

        with metrics.phase("serve.prime", always=True) as ph:
            for _k, _b, outcome, _origin in self._bring_live(
                todo, devices=devices, on_error=on_error,
                stop_check=stop_check, verbose=verbose, tag=tag,
            ):
                out["entries"] += 1
                out[outcome] += 1
        metrics.gauge("serve.prime_s", ph.seconds)
        return out
