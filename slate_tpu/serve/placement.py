"""Mesh-aware placement policy for the serving tier.

The policy answers two questions the SolverService asks on every
request, turning the device fleet into the serving domain (ROADMAP
item 1 — SLATE SC'19's 2D process grid as the placement domain,
Clipper NSDI'17's replica scale-out as the serving shape):

1. **Where does this request run?** (:meth:`PlacementPolicy.mesh_for`)
   Small buckets stay on the *replicated* tier — the executable is
   data-parallel-replicated across devices, one replica worker + queue
   per device (group), and throughput scales with chips.  Large-n
   requests (``n >= shard_threshold``) or explicitly-sharded submits
   route to the *sharded* tier — the existing ``parallel/`` spmd
   drivers under ``shard_map`` on a configured P x Q submesh
   (``parallel/grid.ProcessGrid``), so one request is no longer
   bounded by a single device's HBM and FLOPs.

2. **Which replica takes it?** (:meth:`PlacementPolicy.select_replica`)
   Least-loaded (queue depth + in-flight) with round-robin tie
   breaking, or plain round-robin; replicas whose circuit breaker for
   this bucket is OPEN are excluded while any healthy replica exists —
   a degraded replica sheds its batched traffic to its peers instead
   of forcing every request through the direct fallback.

The policy is pure decision logic (the mesh grammar and fit checks
live in serve/buckets so manifests can be filtered without jax);
devices resolve lazily so constructing a default single-replica
policy — the configuration every pre-placement deployment ran —
touches no jax state at all.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..enums import Option
from ..options import Options, get_option
from .buckets import (  # noqa: F401  (re-exports)
    DEFAULT_SHARD_THRESHOLD,
    check_mesh,
    mesh_fits,
    parse_mesh,
)

#: replica-selection strategies
LEAST_LOADED = "least_loaded"
ROUND_ROBIN = "round_robin"

#: routines the sharded tier can serve (the spmd drivers traced by
#: parallel/spmd_core; gels and mixed precision stay replicated)
SHARDABLE = ("gesv", "posv")


class PlacementPolicy:
    """Per-bucket placement: replica scale-out for small buckets,
    spmd submesh routing for large ones.

    Parameters
    ----------
    replicas: data-parallel replica worker count (default 1 — the
        single-worker service, behavior-identical to the pre-placement
        tier).  Each replica pins its dispatches to one device via
        :meth:`device_for`; with more replicas than devices the
        assignment wraps.
    mesh: ``"PxQ"`` submesh for sharded routing, ``""`` disables it.
        The sharded lane always binds the process's first P*Q global
        ``jax.devices()`` (parallel/spmd_core.grid_for) — the
        ``devices`` list below pins replicas only.
    shard_threshold: requests with ``n >= shard_threshold`` route to
        the mesh when one is configured (default 2048, matching
        Option.ServeShardThreshold; 0 disables size-based routing —
        explicit ``sharded=True`` submits still route).
    strategy: ``"least_loaded"`` (default) or ``"round_robin"``.
    devices: explicit device list for REPLICA pinning (tests);
        default = ``jax.devices()`` resolved lazily on first use.
    """

    def __init__(
        self,
        replicas: int = 1,
        mesh: str = "",
        shard_threshold: int = DEFAULT_SHARD_THRESHOLD,
        strategy: str = LEAST_LOADED,
        devices: Optional[Sequence] = None,
    ):
        self.replicas = max(int(replicas), 1)
        self.mesh = check_mesh(mesh)
        self.shard_threshold = max(int(shard_threshold), 0)
        if strategy not in (LEAST_LOADED, ROUND_ROBIN):
            raise ValueError(
                f"unknown placement strategy {strategy!r} "
                f"({LEAST_LOADED}|{ROUND_ROBIN})"
            )
        self.strategy = strategy
        self._devices = list(devices) if devices is not None else None
        self._rr = 0  # round-robin cursor (ties + pure round-robin)

    @staticmethod
    def from_options(opts: Optional[Options] = None, **kw) -> "PlacementPolicy":
        """Resolve the policy from the Serve* options (the service's
        default construction path); ``kw`` overrides fields."""
        cfg = dict(
            replicas=int(get_option(opts, Option.ServeReplicas)),
            mesh=str(get_option(opts, Option.ServeMesh) or ""),
            shard_threshold=int(get_option(opts, Option.ServeShardThreshold)),
        )
        cfg.update({k: v for k, v in kw.items() if v is not None})
        return PlacementPolicy(**cfg)

    # -- devices -------------------------------------------------------------

    def devices(self) -> List:
        """The device pool (lazy ``jax.devices()``)."""
        if self._devices is None:
            import jax

            self._devices = list(jax.devices())
        return self._devices

    def device_for(self, replica: int):
        """The device replica ``replica`` pins its dispatches to; None
        for the single-replica policy (default-device placement, the
        pre-placement behavior, no committed transfers).  When the pool
        is large enough to host the spmd submesh AND the replicas,
        replica pinning starts past the mesh slice (grid_for binds the
        first P*Q devices), so replicated batches and shard_map
        programs do not contend for the same chips while spares idle."""
        if self.replicas <= 1:
            return None
        devs = self.devices()
        p, q = parse_mesh(self.mesh)
        off = p * q if p and len(devs) >= p * q + self.replicas else 0
        return devs[(off + replica) % len(devs)]

    def replica_devices(self) -> List:
        """One entry per replica — what warmup/restore prime so steady
        state stays compile-free on EVERY replica, not just the first."""
        return [self.device_for(i) for i in range(self.replicas)]

    def set_replicas(self, n: int) -> int:
        """Resize the replica set (elastic capacity plane: the service
        calls this from ``add_replica``/``remove_replica`` so
        :meth:`device_for` / :meth:`replica_devices` track the LIVE
        lane count, not the construction-time one).  Clamped to >= 1;
        returns the applied count.

        Note the 1 -> 2 asymmetry: the original single replica was
        placed with ``device_for() -> None`` (default-device dispatch)
        and keeps that placement — re-pinning a live lane would
        invalidate its primed per-device executables mid-traffic.  New
        lanes get real pins from the grown pool."""
        self.replicas = max(int(n), 1)
        return self.replicas

    # -- routing -------------------------------------------------------------

    def mesh_for(
        self, routine: str, n: int, sharded: Optional[bool] = None
    ) -> str:
        """The mesh string this request's bucket should be keyed (and
        routed) by: ``""`` = replicated tier, ``"PxQ"`` = sharded tier.

        ``sharded`` is the per-submit override: True forces the mesh
        (the caller validates one is configured), False forces the
        replicated tier, None applies the size policy."""
        if routine not in SHARDABLE or not self.mesh:
            return ""
        if sharded is False:
            return ""
        if sharded:
            return self.mesh
        if self.shard_threshold and n >= self.shard_threshold:
            return self.mesh
        return ""

    # -- replica selection ---------------------------------------------------

    def select_replica(
        self,
        loads: Sequence[int],
        open_breaker: Optional[Sequence[bool]] = None,
    ) -> int:
        """Pick the replica index for one request.

        ``loads`` is per-replica pending work (queue depth + in-flight);
        ``open_breaker`` flags replicas whose breaker for this request's
        bucket is OPEN — they are excluded while any healthy replica
        exists (when ALL are open the least-loaded one takes it anyway
        and the per-replica breaker decides direct routing downstream).
        Ties break round-robin so equal-load replicas share traffic
        instead of replica 0 absorbing every lull."""
        n = len(loads)
        if n == 0:
            raise ValueError("no replicas to select from")
        cand = list(range(n))
        if open_breaker is not None:
            healthy = [i for i in cand if not open_breaker[i]]
            if healthy:
                cand = healthy
        if self.strategy == ROUND_ROBIN:
            pick = cand[self._rr % len(cand)]
            self._rr += 1
            return pick
        lo = min(loads[i] for i in cand)
        tied = [i for i in cand if loads[i] == lo]
        pick = tied[self._rr % len(tied)]
        self._rr += 1
        return pick
