"""Overload-resilient admission control: tenant fairness, priority
shedding, and a self-tuning batch window.

The serving tier was SLO-*measured* (per-bucket latency histograms,
``slo_burn`` tiers, ``oldest_queued_s``) but not SLO-*defended*: queue
limit and batch window were static configuration and every request was
anonymous — one bursty client could fill the bounded queue and starve
everyone else.  This module is the control plane that closes the loop
(ROADMAP item 3; Clipper NSDI'17 for the adaptive batching shape,
Dapper-style per-request context for the tenant/priority plumbing —
PAPERS.md):

* **Tenants** — ``submit(tenant=...)`` tags every request; a spec
  (:data:`TENANTS_ENV` / ``Option.ServeTenantQuota`` /
  ``SolverService(tenants=...)``) gives each tenant a weighted-fair
  share, a token-bucket quota, and a queue-share cap, so a hot tenant
  sheds ITS OWN load first (``Rejected`` becomes per-tenant) instead
  of filling the shared FIFO.
* **Weighted-fair queues** (:class:`FairQueue`) — each serving lane's
  FIFO becomes a per-tenant virtual-time scheduler: the next dispatch
  goes to the eligible tenant with the smallest virtual finish time,
  advanced by ``1/weight`` per pop, so an N-request backlog from one
  tenant no longer head-of-line-blocks everyone else.  FIFO order is
  preserved within a tenant, and with a single tenant the schedule
  degenerates to exactly the old FIFO.
* **Priority shedding** (:class:`OverloadController`) — three priority
  classes (``buckets.PRIORITIES``); when the EWMA of the delivered
  deadline-budget burn crosses a tier, admission sheds
  lowest-priority-first with a typed ``Shed`` error (distinct from
  ``Rejected``: the service is overloaded, not full — back off and
  retry later).  Escalation is immediate, de-escalation waits out a
  dwell (breaker-style hysteresis, so the controller never flaps), and
  while shedding the coalesce window is shrunk (batching latency is
  the one knob admission owns mid-flight).
* **Adaptive batch window** (:class:`AdaptiveWindow`) — per bucket, an
  AIMD controller picks the coalesce window from observed delivered
  latency vs. the p99 budget (Clipper's additive-increase /
  multiplicative-decrease shape): under budget the window widens
  additively toward ``Option.ServeBatchWindow`` (the ceiling — more
  coalescing, better throughput), over budget it halves (less waiting,
  lower tail), and in the hysteresis band between it holds.  Every
  decision is recorded (``serve.adaptive.<bucket>.window_s`` gauge,
  ``.widen``/``.shrink`` counters, an ``adaptive_window`` span
  instant) so ``tools/latency_report.py`` can show the trajectory.

**Zero overhead off**: with no tenant spec and adaptation off,
``AdmissionControl.from_options`` returns None and the service pays one
``is None`` branch per submit — queues stay plain deques, no metric is
emitted, behavior is byte-identical to the pre-admission tier.

Per-tenant metric families (``serve.tenant.<id>.*``,
``serve.latency.tenant.<id>.total``) are cardinality-capped at
:data:`TENANT_METRIC_CAP` distinct ids (``metrics.CappedKeys``, the
factor-cache fingerprint pattern), the control plane's own per-tenant
state at :data:`TENANT_STATE_CAP` (oldest unconfigured id evicted),
and FairQueue's virtual-time maps are pruned to the queue's current
tenant set — so a churning tenant-id stream cannot leak registry keys
OR process memory forever.

Spec grammar (:data:`TENANTS_ENV` / ``Option.ServeTenantQuota``)::

    spec        := tenant_spec (';' tenant_spec)*
    tenant_spec := name ':' item (',' item)*
    item        := 'weight=<float>'   # WFQ weight (default 1)
                 | 'rate=<float>'     # token-bucket refill, req/s
                                      # (default 0 = unlimited)
                 | 'burst=<int>'      # bucket capacity (default
                                      # max(1, ceil(rate)); requires
                                      # rate= — no refill, no quota)
                 | 'share=<float>'    # max fraction of the queue this
                                      # tenant may occupy (default 1.0)

The entry named ``default`` configures the anonymous pool AND is the
template for tenants the spec does not name.  Example::

    SLATE_TPU_TENANTS="gold:weight=4;free:weight=1,rate=20,share=0.25" \\
    SLATE_TPU_ADAPTIVE=0.25 python app.py   # adaptive on, p99 budget 250 ms
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..aux import metrics, spans, sync
from .buckets import (
    DEFAULT_TENANT,
    PRIORITIES,
    PRIO_NORMAL,
    check_priority,
)

TENANTS_ENV = "SLATE_TPU_TENANTS"
ADAPTIVE_ENV = "SLATE_TPU_ADAPTIVE"


def resolve_identity(tenant, priority) -> Tuple[str, int]:
    """Normalize a submit-time (tenant, priority) pair — the ONE
    normalizer, used by the plane-on path (AdmissionControl.resolve)
    AND the plane-off path in service.submit, so enabling tenancy
    never changes which tags a client may pass (a tenant id the plane
    would reject must fail identically with the plane off)."""
    t = DEFAULT_TENANT if tenant is None else str(tenant)
    if not t:
        raise ValueError("tenant id must be a non-empty string")
    p = PRIO_NORMAL if priority is None else check_priority(priority)
    return t, p

#: cardinality cap on the per-tenant metric families (counters AND the
#: per-tenant latency histograms): tenant ids are caller-controlled
#: strings, so without the cap a churning id stream leaks one registry
#: key per id forever.  Past the cap, events still count globally and
#: in the health snapshot; ``serve.tenant_overflow`` counts the spill.
TENANT_METRIC_CAP = 64

#: cap on the control plane's own per-tenant state (_TenantState:
#: counters + token bucket) — the in-memory twin of the metric cap.
#: Past it, the oldest UNCONFIGURED tenant's state is evicted (its
#: counters reset, its bucket refills on return); spec-named tenants
#: are never evicted, their count is operator-bounded.
TENANT_STATE_CAP = 256


# ---------------------------------------------------------------------------
# tenant configuration
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TenantConfig:
    """One tenant's admission contract (see the module grammar)."""

    name: str
    weight: float = 1.0
    rate: float = 0.0  # token-bucket refill, req/s; 0 = unlimited
    burst: int = 0  # bucket capacity; 0 = max(1, ceil(rate))
    share: float = 1.0  # max fraction of max_queue this tenant occupies

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name!r}: weight must be > 0")
        if self.rate < 0:
            raise ValueError(f"tenant {self.name!r}: rate must be >= 0")
        if self.burst < 0:
            raise ValueError(f"tenant {self.name!r}: burst must be >= 0")
        if self.burst > 0 and self.rate <= 0:
            # a bucket with capacity but no refill would either be
            # inert (what a silent pass produces) or a lifetime cap
            # (never what an operator means by "burst") — refuse to
            # start rather than ignore a quota the operator believes
            # is active
            raise ValueError(
                f"tenant {self.name!r}: burst= requires rate= "
                "(a token bucket with no refill is not a quota)"
            )
        if not 0 < self.share <= 1:
            raise ValueError(
                f"tenant {self.name!r}: share must be in (0, 1]"
            )

    @property
    def capacity(self) -> int:
        """Token-bucket capacity (0 when the quota is unlimited —
        rate == 0; validation refuses burst without rate)."""
        if self.rate <= 0:
            return 0
        return self.burst if self.burst > 0 else max(1, math.ceil(self.rate))


def parse_tenants(spec: str) -> Dict[str, TenantConfig]:
    """Parse the :data:`TENANTS_ENV` grammar into per-tenant configs."""
    out: Dict[str, TenantConfig] = {}
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        name, sep, items = part.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"tenant spec {part!r}: empty tenant name")
        kw: dict = {}
        if sep:
            for item in items.split(","):
                item = item.strip()
                if not item:
                    continue
                k, isep, v = item.partition("=")
                k, v = k.strip(), v.strip()
                if not isep:
                    raise ValueError(
                        f"tenant spec item {item!r} in {part!r}"
                    )
                if k in ("weight", "rate", "share"):
                    kw[k] = float(v)
                elif k == "burst":
                    kw[k] = int(v)
                else:
                    raise ValueError(
                        f"unknown tenant spec key {k!r} in {part!r}"
                    )
        out[name] = TenantConfig(name=name, **kw)
    return out


class TokenBucket:
    """Deterministic token bucket: ``capacity`` tokens, refilled at
    ``rate``/s from the timestamps the caller passes in (no internal
    clock — the quota-refill unit tests drive it with a fake one)."""

    __slots__ = ("rate", "capacity", "tokens", "t_last")

    def __init__(self, rate: float, capacity: int, now: float = 0.0):
        self.rate = float(rate)
        self.capacity = float(capacity)
        # refill state is mutated by take()/remaining(), always called
        # under the admission plane's lock
        self.tokens = float(capacity)  # guarded by: _lock (external)
        self.t_last = float(now)  # guarded by: _lock (external)

    def _refill(self, now: float) -> None:
        dt = now - self.t_last
        if dt <= 0:
            # never rewind the clock: a read with an older timestamp
            # (health() snapshots `now` before doing other work) must
            # not reset t_last backwards, or the next take() would
            # re-credit the already-consumed interval and admit a
            # rate-limited tenant above its configured rate
            return
        self.t_last = now
        self.tokens = min(self.capacity, self.tokens + dt * self.rate)

    def take(self, now: float) -> bool:
        """Consume one token (True) or report the bucket dry (False)."""
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def remaining(self, now: float) -> float:
        self._refill(now)
        return self.tokens


# ---------------------------------------------------------------------------
# weighted-fair lane queue
# ---------------------------------------------------------------------------


class FairQueue:
    """Per-tenant weighted-fair queue for one serving lane — the
    replacement for the lane's plain FIFO deque when tenancy is on.

    Virtual-time WFQ (stride-scheduling flavor): each tenant carries a
    virtual time advanced by ``1/weight`` per popped request;
    :meth:`pop_eligible` serves the eligible tenant with the smallest
    virtual time (ties broken oldest-first), so over any backlog window
    tenants drain in weight proportion and one tenant's burst cannot
    head-of-line-block the rest.  A tenant going idle and returning is
    clamped to the current virtual now (it gets its share, not a
    catch-up monopoly).  FIFO order within a tenant is preserved, and
    with a single tenant the schedule IS the old FIFO.

    Deque-compatible surface (``append``/``appendleft``/``remove``/
    ``clear``/``__len__``/``__iter__`` in arrival order) so the
    service's sweep/coalesce/drain code runs unchanged on either queue
    kind.  NOT internally locked: every access happens under the
    service's condition lock, like the deques it replaces.
    """

    __slots__ = ("_adm", "_items", "_vtime", "_vnow", "_depth")

    def __init__(self, adm: "AdmissionControl"):
        self._adm = adm
        # externally synchronized (see class docstring): every access
        # happens under the owning service's condition lock, like the
        # deque this queue replaces — the lint annotations document
        # that contract and police any access from OUTSIDE this class
        self._items: List = []  # guarded by: _cond (external) — arrival order
        self._vtime: Dict[str, float] = {}  # guarded by: _cond (external)
        self._vnow = 0.0  # guarded by: _cond (external)
        self._depth: Dict[str, int] = {}  # guarded by: _cond (external)

    # -- deque-compatible surface ------------------------------------------

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def _arrive(self, r) -> None:
        t = r.tenant
        if not self._depth.get(t):
            # idle tenant returning: clamp its virtual time forward so
            # a long-idle tenant cannot monopolize the lane to "catch
            # up" — it resumes at the current virtual now
            self._vtime[t] = max(self._vtime.get(t, 0.0), self._vnow)
        self._depth[t] = self._depth.get(t, 0) + 1

    def append(self, r) -> None:
        self._arrive(r)
        self._items.append(r)

    def appendleft(self, r) -> None:
        """Retry re-enqueue: the request goes back to its tenant's head
        (and, tenant-fairness aside, to the front of arrival order —
        the deque semantics the retry path was built on)."""
        self._arrive(r)
        self._items.insert(0, r)

    def remove(self, r) -> None:
        self._items.remove(r)
        t = r.tenant
        d = self._depth.get(t, 0) - 1
        if d > 0:
            self._depth[t] = d
        else:
            self._depth.pop(t, None)
            # bounded state: an idle tenant's virtual time is dropped —
            # the arrival clamp resumes it at the virtual now, so the
            # maps never outgrow the queue's CURRENT tenant set (a
            # churning caller-controlled id stream cannot leak one
            # float per id forever)
            self._vtime.pop(t, None)

    def clear(self) -> None:
        self._items.clear()
        self._depth.clear()
        self._vtime.clear()

    def depth(self, tenant: str) -> int:
        """Queued requests of one tenant in THIS lane."""
        return self._depth.get(tenant, 0)

    def depths(self) -> Dict[str, int]:
        """Per-tenant queued counts of THIS lane (a copy) — health()
        merges the lanes' maps instead of re-scanning every request."""
        return dict(self._depth)

    # -- the scheduler ------------------------------------------------------

    def pop_eligible(self, now: float):
        """The weighted-fair replacement for "oldest eligible request":
        among requests whose retry backoff has elapsed, serve the
        tenant with the smallest virtual time; None when nothing is
        eligible."""
        heads: Dict[str, object] = {}
        want = len(self._depth)  # tenants currently queued
        for r in self._items:
            if r.not_before <= now and r.tenant not in heads:
                heads[r.tenant] = r
                if len(heads) == want:
                    break  # every queued tenant has its head: the
                    # common single-tenant case stays near-O(1)
        if not heads:
            return None
        t = min(
            heads,
            key=lambda k: (self._vtime.get(k, 0.0), heads[k].t_submit),
        )
        r = heads[t]
        v = self._vtime.get(t, 0.0)  # before remove() may prune it
        self.remove(r)
        f = v + 1.0 / self._adm.config_for(t).weight  # finish tag
        # monotone virtual now, advanced to the served request's FINISH
        # tag: (a) a request popped late off a stale small vtime (retry
        # backoff) cannot drag vnow backwards and hand the next arrival
        # a catch-up monopoly; (b) the charge survives a pruned map
        # entry — a closed-loop tenant whose queue empties on every pop
        # re-enters AT its own finish tag via the arrival clamp, so it
        # drains in weight proportion instead of re-arriving in the
        # past and starving the backlogged tenants behind it
        self._vnow = max(self._vnow, f)
        if self._depth.get(t):
            self._vtime[t] = f
        return r


# ---------------------------------------------------------------------------
# adaptive batch window (AIMD, Clipper-shaped)
# ---------------------------------------------------------------------------


class AdaptiveWindow:
    """Per-bucket AIMD controller for the coalesce window.

    Decisions fire every ``decide_every`` finished observations over
    the worst (max) BURN RATIO — each request's total latency divided
    by ITS OWN budget — seen in that decision window (the small-sample
    p99 proxy).  Ratio, not raw latency: a bucket serving mixed
    deadlines (a 2 s solve inside a 5 s budget next to a 40 ms solve
    inside a 50 ms budget) must judge each against its own contract,
    or one tenant's generous deadline would misread as another's SLO
    melt.  Worst ratio > 1: multiplicative decrease (``window *=
    beta``, less lingering, lower tail).  Worst ratio <= 0.5: additive
    increase (``window += step`` up to the ceiling, more coalescing).
    Between the two — the hysteresis band — hold, so a latency sitting
    near budget never makes the window flap.  Budget-less observations
    ride the count but carry no ratio; a window with none is a no-op.
    Observation-count (not wall-clock) driven: a fake-clock-free pure
    function of the finished-latency sequence, which is what the
    convergence unit tests replay."""

    __slots__ = (
        "ceiling_s", "floor_s", "step_s", "beta", "decide_every",
        "window_s", "widens", "shrinks", "_worst", "_count", "_budgeted",
    )

    def __init__(
        self,
        ceiling_s: float,
        floor_s: float = 0.0,
        step_s: Optional[float] = None,
        beta: float = 0.5,
        decide_every: int = 8,
    ):
        self.ceiling_s = float(ceiling_s)
        self.floor_s = float(floor_s)
        self.step_s = (
            float(step_s) if step_s is not None
            else max(self.ceiling_s / 8.0, 1e-5)
        )
        self.beta = float(beta)
        self.decide_every = int(decide_every)
        # start at the ceiling: with no latency pressure the adaptive
        # service batches exactly like the static one
        self.window_s = self.ceiling_s
        self.widens = 0
        self.shrinks = 0
        self._worst = 0.0  # worst burn RATIO this decision window
        self._count = 0
        self._budgeted = 0

    def observe(self, total_s: float, budget_s: float) -> Optional[str]:
        """One finished total latency against ITS budget; returns
        ``"shrink"``/``"widen"`` when this observation completed a
        decision window that moved the window, else None."""
        if budget_s > 0:
            self._worst = max(self._worst, float(total_s) / budget_s)
            self._budgeted += 1
        self._count += 1
        if self._count < self.decide_every:
            return None
        worst, budgeted = self._worst, self._budgeted
        self._worst = 0.0
        self._count = 0
        self._budgeted = 0
        if budgeted == 0:
            return None  # nothing to judge against: hold
        if worst > 1.0 and self.window_s > self.floor_s:
            self.window_s = max(self.floor_s, self.window_s * self.beta)
            self.shrinks += 1
            return "shrink"
        if worst <= 0.5 and self.window_s < self.ceiling_s:
            self.window_s = min(
                self.ceiling_s, self.window_s + self.step_s
            )
            self.widens += 1
            return "widen"
        return None


# ---------------------------------------------------------------------------
# overload controller (priority shedding with hysteresis)
# ---------------------------------------------------------------------------


class OverloadController:
    """Sustained-burn shed controller.

    Tracks an EWMA of the deadline-budget burn ratio of every finished
    request (delivered total / budget; a queued-deadline cancel counts
    at its actual overrun — the SLO melted either way).  Levels:

    * 0 — healthy, nothing shed
    * 1 — ``low``-priority admissions shed (EWMA >= ``enter[0]``)
    * 2 — ``normal`` + ``low`` shed (EWMA >= ``enter[1]``); ``high``
      is never shed — only queue/quota ``Rejected`` can refuse it

    Breaker-style hysteresis: escalation is immediate (overload is an
    emergency), de-escalation requires the EWMA below the level's
    ``exit`` threshold AND ``dwell_s`` elapsed since the last change,
    so an oscillating burn near a threshold cannot flap the level.
    While shedding, :meth:`window_factor` shrinks the coalesce window
    (``shrink ** level``) — under overload the service stops lingering
    for company; on recovery the factor restores to 1.

    Recovery needs a signal even when shedding refuses ALL traffic:
    refused requests never execute, so nothing feeds the EWMA and a
    latched level would shed forever after the load vanished.
    :meth:`tick` (called at every admission) treats observation
    silence as evidence of no load: each idle ``dwell_s`` since the
    last burn sample halves the EWMA, and the normal dwelled
    de-escalation logic then runs — a flood that stops is forgiven in
    a few dwell windows, no probe traffic or restart required."""

    __slots__ = (
        "enter", "exit", "alpha", "dwell_s", "shrink",
        "level", "ewma", "observations", "_t_changed", "_t_observed",
    )

    def __init__(
        self,
        enter: Tuple[float, float] = (0.9, 1.5),
        exit: Tuple[float, float] = (0.5, 1.0),
        alpha: float = 0.25,
        dwell_s: float = 0.25,
        shrink: float = 0.25,
    ):
        if not (exit[0] < enter[0] and exit[1] < enter[1]):
            raise ValueError(
                "hysteresis requires exit thresholds below enter "
                f"thresholds (enter={enter}, exit={exit})"
            )
        self.enter = (float(enter[0]), float(enter[1]))
        self.exit = (float(exit[0]), float(exit[1]))
        self.alpha = float(alpha)
        self.dwell_s = float(dwell_s)
        self.shrink = float(shrink)
        # controller state advances under the admission plane's lock
        # (observe()/tick() callers hold it); `level` is additionally
        # READ lock-free on deliberately racy fast paths — those sites
        # carry their own justification + lint suppression
        self.level = 0  # guarded by: _lock (external)
        self.ewma = 0.0  # guarded by: _lock (external)
        self.observations = 0  # guarded by: _lock (external)
        self._t_changed = -math.inf  # guarded by: _lock (external)
        self._t_observed = -math.inf  # guarded by: _lock (external)

    def _retarget(self, now: float) -> Optional[Tuple[int, int]]:
        """Re-evaluate the level against the current EWMA (escalation
        immediate, de-escalation dwelled); returns the transition."""
        target = self.level
        while target < 2 and self.ewma >= self.enter[target]:
            target += 1
        while target > 0 and self.ewma < self.exit[target - 1]:
            target -= 1
        if target == self.level:
            return None
        if target < self.level and now - self._t_changed < self.dwell_s:
            return None  # recover slowly: dwell out the de-escalation
        old, self.level = self.level, target
        self._t_changed = now
        return (old, target)

    def observe(self, burn: float, now: float) -> Optional[Tuple[int, int]]:
        """Fold one burn ratio in; returns ``(old, new)`` when the shed
        level transitioned, else None."""
        self.ewma += self.alpha * (float(burn) - self.ewma)
        self.observations += 1
        self._t_observed = now
        return self._retarget(now)

    def tick(self, now: float) -> Optional[Tuple[int, int]]:
        """Idle decay: with the level raised and NO burn samples for a
        whole ``dwell_s``, halve the EWMA once per elapsed dwell window
        and re-evaluate — the anti-latch path (see class docstring).
        Escalation is impossible here (the EWMA only shrinks)."""
        if self.level == 0:
            return None
        idle = now - self._t_observed
        if idle < self.dwell_s:
            return None
        steps = int(idle / self.dwell_s)
        self.ewma *= 0.5 ** steps
        # consume the decayed idle time so a stream of ticks decays
        # once per dwell window, not once per admission attempt
        self._t_observed += steps * self.dwell_s
        return self._retarget(now)

    def sheds(self, priority: int) -> bool:
        """Whether an admission of this priority class is shed at the
        current level (lowest-priority-first; ``high`` never)."""
        return (
            self.level > 0 and priority >= len(PRIORITIES) - self.level
        )

    def window_factor(self) -> float:
        """Coalesce-window multiplier under overload (1.0 healthy)."""
        return self.shrink ** self.level if self.level else 1.0

    @staticmethod
    def shed_names(level: int) -> List[str]:
        """Priority-class names shed at ``level`` (lowest-first,
        ``high`` never) — the ONE spelling of the shed threshold, used
        by :meth:`sheds`' consumers that report class lists (health
        snapshot, overload span instants)."""
        if level <= 0:
            return []
        return [
            p for i, p in enumerate(PRIORITIES)
            if i >= len(PRIORITIES) - level
        ]


# ---------------------------------------------------------------------------
# the admission plane
# ---------------------------------------------------------------------------


#: per-tenant health/report counter keys (ints in the control plane so
#: health() works with metrics off; mirrored into serve.tenant.<id>.*)
_EVENTS = ("admitted", "shed", "rejected")


@dataclass
class _TenantState:
    cfg: TenantConfig
    bucket: Optional[TokenBucket] = None
    counts: Dict[str, int] = field(
        default_factory=lambda: {k: 0 for k in _EVENTS}
    )
    burn: Dict[str, int] = field(
        default_factory=lambda: {
            "requests": 0, "over_50": 0, "over_80": 0, "exhausted": 0,
        }
    )


class AdmissionControl:
    """The service's admission plane: tenant resolution + quotas +
    priority shedding + per-bucket adaptive windows.  One instance per
    :class:`~slate_tpu.serve.service.SolverService`; None (the
    ``from_options`` result with nothing configured) means the plane
    is OFF and the service behaves byte-identically to the
    pre-admission tier."""

    def __init__(
        self,
        tenants: Optional[Dict[str, TenantConfig]] = None,
        adaptive: bool = False,
        budget_s: float = 0.0,
        ceiling_s: float = 0.002,
        overload: Optional[OverloadController] = None,
        clock=time.monotonic,
    ):
        self.tenancy = bool(tenants)
        self.configs: Dict[str, TenantConfig] = dict(tenants or {})
        self.adaptive = bool(adaptive)
        self.budget_s = float(budget_s or 0.0)
        self.ceiling_s = float(ceiling_s)
        self.overload = overload or OverloadController()
        self.clock = clock
        # sync.Lock: plain threading.Lock unless SLATE_TPU_SYNC_CHECK
        # armed the race plane (zero overhead off)
        self._lock = sync.Lock(name="admission.AdmissionControl._lock")
        self._states: Dict[str, _TenantState] = {}  # guarded by: _lock
        self._windows: Dict[str, AdaptiveWindow] = {}  # guarded by: _lock
        self._capped = metrics.CappedKeys(TENANT_METRIC_CAP)
        # resolved-config memo for UNNAMED tenants: config_for sits in
        # the scheduler hot path (every FairQueue pop, under the
        # service lock) — rebuilding + revalidating a frozen dataclass
        # per dispatch is waste.  Bounded like _states (cleared, not
        # LRU'd: it only ever holds default-template clones)
        self._cfg_cache: Dict[str, TenantConfig] = {}

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_options(
        opts=None,
        tenants=None,
        adaptive: Optional[bool] = None,
        budget_s: Optional[float] = None,
        ceiling_s: float = 0.002,
        clock=time.monotonic,
    ) -> Optional["AdmissionControl"]:
        """Resolve the admission plane from explicit arguments, the
        Serve* options, and the env (:data:`TENANTS_ENV` /
        :data:`ADAPTIVE_ENV`); returns None when nothing is configured
        — the zero-overhead default."""
        from ..enums import Option
        from ..options import get_option

        if tenants is None:
            tenants = (
                get_option(opts, Option.ServeTenantQuota)
                or os.environ.get(TENANTS_ENV, "")
            )
        if isinstance(tenants, str):
            tenants = parse_tenants(tenants) if tenants.strip() else {}
        # SLATE_TPU_ADAPTIVE: "1"/"true" = on (budget from options);
        # a float = on with that p99 budget in seconds; "0"/"" = off.
        # Malformed values fail naming the knob (the faults-env rule:
        # silently ignoring a spec the operator believes active is
        # worse than refusing to start).
        env_adaptive = os.environ.get(ADAPTIVE_ENV, "").strip().lower()
        env_budget = 0.0
        env_on = False
        if env_adaptive and env_adaptive not in ("0", "false", "off"):
            env_on = True
            if env_adaptive not in ("1", "true", "on"):
                try:
                    env_budget = float(env_adaptive)
                except ValueError:
                    raise ValueError(
                        f"{ADAPTIVE_ENV}={env_adaptive!r}: expected 1 "
                        "or a p99 budget in seconds"
                    ) from None
                if env_budget <= 0:
                    # "0.0"/"0.00" mean off, same as "0" — arming the
                    # plane with a budget no controller can use would
                    # be pure overhead the operator asked to avoid
                    env_on = False
                    env_budget = 0.0
        if adaptive is None:
            adaptive = bool(
                get_option(opts, Option.ServeAdaptiveWindow) or env_on
            )
        if budget_s is None:
            budget_s = float(
                get_option(opts, Option.ServeLatencyBudget)
                or env_budget or 0.0
            )
        if not tenants and not adaptive:
            return None
        return AdmissionControl(
            tenants=tenants, adaptive=bool(adaptive),
            budget_s=float(budget_s), ceiling_s=float(ceiling_s),
            clock=clock,
        )

    def new_queue(self) -> FairQueue:
        """A weighted-fair lane queue bound to this plane's weights."""
        return FairQueue(self)

    # -- tenants ------------------------------------------------------------

    def config_for(self, tenant: str) -> TenantConfig:
        """The named tenant's config; unnamed tenants inherit the
        ``default`` entry (or the built-in defaults).  Memoized: this
        sits in the scheduler hot path."""
        cfg = self.configs.get(tenant)
        if cfg is not None:
            return cfg
        cfg = self._cfg_cache.get(tenant)
        if cfg is None:
            tmpl = self.configs.get(DEFAULT_TENANT)
            cfg = (
                TenantConfig(
                    name=tenant, weight=tmpl.weight, rate=tmpl.rate,
                    burst=tmpl.burst, share=tmpl.share,
                )
                if tmpl is not None else TenantConfig(name=tenant)
            )
            if len(self._cfg_cache) >= TENANT_STATE_CAP:
                self._cfg_cache.clear()  # churning ids: bounded, cheap
            self._cfg_cache[tenant] = cfg
        return cfg

    def _state_locked(self, tenant: str) -> _TenantState:
        # _locked suffix: the caller holds self._lock
        st = self._states.get(tenant)
        if st is None:
            cfg = self.config_for(tenant)
            st = _TenantState(cfg=cfg)
            if cfg.rate > 0:
                st.bucket = TokenBucket(
                    cfg.rate, cfg.capacity, now=self.clock()
                )
            if len(self._states) >= TENANT_STATE_CAP:
                # bounded control-plane memory (TENANT_STATE_CAP): a
                # churning caller-controlled id stream must not leak
                # one _TenantState per id forever.  Evict the oldest
                # unconfigured id (insertion order); an evicted tenant
                # that returns starts fresh — the same tradeoff the
                # metric cap makes, here trading its old counters and
                # a refilled bucket for boundedness
                for old in self._states:
                    if old not in self.configs:
                        del self._states[old]
                        break
            self._states[tenant] = st
        return st

    def tenant_event(self, tenant: str, event: str, n: int = 1) -> None:
        """Count one per-tenant admission event (health ints + the
        capped ``serve.tenant.<id>.<event>`` metric family)."""
        with self._lock:
            st = self._state_locked(tenant)
            st.counts[event] = st.counts.get(event, 0) + n
        if metrics.is_on():
            if self._capped.track(tenant):
                metrics.inc(f"serve.tenant.{tenant}.{event}", n)
            else:
                metrics.inc("serve.tenant_overflow", n)

    def quota_take(self, tenant: str, now: float) -> bool:
        """One admission against the tenant's token bucket (True =
        admitted; unlimited tenants always pass)."""
        with self._lock:
            st = self._state_locked(tenant)
            if st.bucket is None:
                return True
            return st.bucket.take(now)

    def quota_remaining(self, tenant: str, now: float) -> Optional[float]:
        with self._lock:
            st = self._states.get(tenant)
            if st is None or st.bucket is None:
                return None
            return st.bucket.remaining(now)

    def share_limit(self, tenant: str, max_queue: int) -> int:
        """This tenant's queue-occupancy cap in requests."""
        share = self.config_for(tenant).share
        if share >= 1.0:
            return int(max_queue)
        return max(1, int(share * max_queue))

    def sheds(self, priority: int) -> bool:
        return self.overload.sheds(priority)

    def tick(self, now: float) -> None:
        """Admission-time anti-latch hook: give the overload controller
        a chance to decay an idle EWMA and de-escalate even when
        shedding refuses every request that would otherwise feed it
        (``OverloadController.tick``)."""
        # lock-free steady state: tick only ever LOWERS the level, so a
        # racy read that misses a just-raised level merely defers the
        # (no-op-at-0 anyway) decay to the next submit
        if self.overload.level == 0:  # slate-lint: disable=lock-discipline
            return
        with self._lock:
            moved = self.overload.tick(now)
        self._emit_overload(moved)

    def _emit_overload(
        self, moved: Optional[Tuple[int, int]],
        trace: Optional[str] = None, lane: Optional[str] = None,
    ) -> None:
        """Metrics + span instant for one shed-level transition."""
        if moved is None:
            return
        old, new = moved
        metrics.gauge("serve.overload.level", new)
        metrics.inc(
            "serve.overload.enter" if new > old else "serve.overload.exit"
        )
        if spans.is_on():
            spans.event(
                "overload_enter" if new > old else "overload_exit",
                trace=trace, lane=lane, level=new,
                sheds=OverloadController.shed_names(new),
            )

    # -- the control loop ---------------------------------------------------

    def window_for(self, label: str) -> float:
        """The coalesce window one lane should linger for this bucket:
        the AIMD window (ceiling when adaptation is off) times the
        overload shrink factor."""
        if self.adaptive:
            with self._lock:
                w = self._windows.get(label)
                win = w.window_s if w is not None else self.ceiling_s
        else:
            win = self.ceiling_s
        return win * self.overload.window_factor()

    def _window_locked(self, label: str) -> AdaptiveWindow:
        w = self._windows.get(label)
        if w is None:
            w = self._windows[label] = AdaptiveWindow(self.ceiling_s)
            if metrics.is_on():
                metrics.gauge(f"serve.adaptive.{label}.window_s", w.window_s)
        return w

    def observe_finish(
        self,
        label: Optional[str],
        tenant: str,
        priority: int,
        total_s: float,
        budget_s: Optional[float],
        now: float,
        trace: Optional[str] = None,
        lane: Optional[str] = None,
        windowed: bool = True,
    ) -> None:
        """One finished request into the control loop: per-tenant burn
        accounting + latency histogram, the overload EWMA (shed-level
        transitions are metric'd + span-instant'd), and — with
        adaptation on — the bucket's AIMD window decision.
        ``windowed=False`` skips the window (direct-only and sharded
        requests never coalesce, so tuning a window nothing consults
        would be pure gauge noise)."""
        budget = (
            float(budget_s) if budget_s is not None and budget_s > 0
            else self.budget_s
        )
        burn = (total_s / budget) if budget > 0 else None
        tracked = metrics.is_on() and self._capped.track(tenant)
        if tracked:
            metrics.observe_hist(
                f"serve.latency.tenant.{tenant}.total", total_s
            )
        with self._lock:
            st = self._state_locked(tenant)
            if burn is not None:
                # the per-tenant twin of the service-wide slo_burn
                # tiers: each finished deadline request lands in one
                st.burn["requests"] += 1
                tier = (
                    "exhausted" if burn > 1.0
                    else "over_80" if burn > 0.8
                    else "over_50" if burn > 0.5
                    else None
                )
                if tier:
                    st.burn[tier] += 1
                if tracked:
                    metrics.inc(f"serve.tenant.{tenant}.slo_burn.requests")
                    if tier:
                        metrics.inc(
                            f"serve.tenant.{tenant}.slo_burn.{tier}"
                        )
            moved = (
                self.overload.observe(burn, now)
                if burn is not None else None
            )
            decision = None
            win = None
            if self.adaptive and windowed and label is not None \
                    and budget > 0:
                w = self._window_locked(label)
                decision = w.observe(total_s, budget)
                win = w.window_s
        self._emit_overload(moved, trace=trace, lane=lane)
        if decision is not None:
            if metrics.is_on():
                # adaptation runs with or without the registry; the
                # per-bucket f-string names are only built when it is on
                metrics.gauge(f"serve.adaptive.{label}.window_s", win)
                metrics.inc(f"serve.adaptive.{label}.{decision}")
            metrics.inc("serve.adaptive.changes")
            spans.event(
                "adaptive_window", trace=trace, lane=lane, bucket=label,
                window_s=round(win, 6), direction=decision,
            )

    def observe_burn(self, burn: float, now: float) -> None:
        """Fold one EXTERNALLY-measured burn ratio into the overload
        controller (the fleet router aggregating its hosts' reported
        burn EWMAs — ``fleet/router.py`` is the consumer).  Same lock,
        same transition emission as :meth:`observe_finish`, without the
        per-tenant accounting a remote sample has no identity for."""
        with self._lock:
            moved = self.overload.observe(float(burn), now)
        self._emit_overload(moved)

    # -- health -------------------------------------------------------------

    def tenants_health(
        self, depths: Dict[str, int], now: Optional[float] = None
    ) -> Dict[str, dict]:
        """The per-tenant ``health()`` section: queue depth, quota
        remaining, weight, admitted/shed/rejected counts, and the
        per-tenant burn tiers.  ``depths`` is the service's summed
        per-lane queue depth per tenant."""
        now = self.clock() if now is None else now
        with self._lock:
            names = set(self._states) | set(self.configs) | set(depths)
            out = {}
            for t in sorted(names):
                st = self._states.get(t)
                cfg = st.cfg if st is not None else self.config_for(t)
                out[t] = {
                    "depth": int(depths.get(t, 0)),
                    "weight": cfg.weight,
                    "share": cfg.share,
                    "quota_remaining": (
                        st.bucket.remaining(now)
                        if st is not None and st.bucket is not None
                        else None
                    ),
                    **{
                        k: (st.counts.get(k, 0) if st is not None else 0)
                        for k in _EVENTS
                    },
                    "burn": dict(st.burn) if st is not None else {
                        "requests": 0, "over_50": 0, "over_80": 0,
                        "exhausted": 0,
                    },
                }
            return out

    def snapshot(self) -> dict:
        """Controller state for ``health()["admission"]``."""
        with self._lock:
            # one consistent controller snapshot: level and EWMA read
            # under the same lock that advances them (a probe racing a
            # transition must not report level 2 beside a level-0 EWMA)
            windows = {
                lbl: round(w.window_s, 6)
                for lbl, w in self._windows.items()
            }
            lvl = self.overload.level
            ewma = self.overload.ewma
        return {
            "tenancy": self.tenancy,
            "adaptive": self.adaptive,
            "budget_s": self.budget_s,
            "overload_level": lvl,
            "shedding": OverloadController.shed_names(lvl),
            "burn_ewma": round(ewma, 4),
            "windows": windows,
        }
