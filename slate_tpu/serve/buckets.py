"""Canonical shape/dtype bucketing for the serving layer.

Unbounded user shapes must map onto a BOUNDED executable set or every
new (m, n, nrhs) pays a cold XLA trace+compile (minutes for the staged
paths, per BENCH_NOTES).  The scheme is the halving-bucket rule already
proven inside ``drivers/eig.py::_size_bucket_runs``: a size h is
assigned the smallest S = total / 2^m that still covers it, floored so
tiny sizes don't multiply compiled bodies.  For serving there is no
fixed ``total`` — buckets double up from ``floor`` instead, which is the
same lattice (``halving_bucket(h, total=2^k floor, floor)`` for k large
enough), so a dimension n lands on the unique power-of-two multiple of
``floor`` covering it.

Requests are padded up to their bucket and results cropped back:

* square systems (gesv/posv): A sits in the top-left corner and the
  trailing diagonal block is the identity, so the padded system is
  block-diagonal ``[[A, 0], [0, I]]`` — partial pivoting never selects a
  pad row for a real column (those entries are 0), Cholesky of the pad
  block is the identity, and the cropped solution equals the direct one.
* least squares (gels, m >= n): zero pad rows plus unit columns
  ``A_pad[m+i, n+i] = 1`` keep full column rank; the pad columns have
  support only in pad rows where B is zero, so the cropped X is the
  original LS solution.  ``bucket_mn`` bumps the row bucket when the
  column padding would not fit below the real rows.
* right-hand sides: zero columns, cropped back exactly.

This module is pure (stdlib + numpy only, no jax, no driver imports) so
``drivers/eig.py`` can share ``size_bucket_runs`` without an import
cycle through the lazy ``serve`` package.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Iterator, Sequence, Tuple

import numpy as np

DIM_FLOOR = 64
NRHS_FLOOR = 8

#: default size-routing threshold for the sharded serving tier: a
#: request with n >= this routes to the spmd submesh when one is
#: configured.  Defined HERE (the one import-pure serving module) so
#: Option.ServeShardThreshold (options.py) and a directly-constructed
#: PlacementPolicy share one value instead of two drifting literals.
DEFAULT_SHARD_THRESHOLD = 2048

#: accepted BucketKey.precision values (the single source of truth —
#: SolverService validates the service-wide setting and per-submit
#: overrides against this same check)
PRECISIONS = ("full", "mixed")

#: accepted BucketKey.phase values: "full" runs the whole factor+solve
#: pipeline; "solve" is the solve-only family the factor cache
#: dispatches on a hit (gesv: pre-permuted rows + two trsm sweeps,
#: posv: two trsm sweeps, gels: blocked Q^H apply from the packed
#: compact-WY factor + one trsm) — O(n^2 nrhs) / O(m n nrhs) against
#: the full phase's O(n^3) / O(m n^2)
PHASES = ("full", "solve")

#: request priority classes at admission (serve/admission.py), highest
#: first: under sustained SLO burn the overload controller sheds
#: lowest-priority-first — "low" is shed at level 1, "normal" joins it
#: at level 2, "high" is never shed (only bounded-queue / quota
#: Rejected can refuse it).  Defined HERE (the import-pure serving
#: module) so the admission plane, the service and the error context
#: share one ordering.
PRIORITIES = ("high", "normal", "low")
PRIO_HIGH, PRIO_NORMAL, PRIO_LOW = 0, 1, 2

#: tenant id of requests submitted without one — the anonymous pool
DEFAULT_TENANT = "default"


def check_priority(priority) -> int:
    """Normalize a priority ("high"|"normal"|"low", or its index) to
    the integer class; raises on anything else."""
    if isinstance(priority, str):
        if priority not in PRIORITIES:
            raise ValueError(
                f"unknown priority {priority!r} ({'|'.join(PRIORITIES)})"
            )
        return PRIORITIES.index(priority)
    p = int(priority)
    if not 0 <= p < len(PRIORITIES):
        raise ValueError(
            f"priority index out of range: {p} (0..{len(PRIORITIES) - 1})"
        )
    return p


def priority_name(level: int) -> str:
    """The class name of a priority index (error context / reports)."""
    return PRIORITIES[check_priority(level)]


def check_precision(precision: str) -> str:
    """Validate a serving-precision string; returns it unchanged."""
    if precision not in PRECISIONS:
        raise ValueError(
            f"unknown serving precision {precision!r} "
            f"({'|'.join(PRECISIONS)})"
        )
    return precision


def check_phase(phase: str) -> str:
    """Validate a serving-phase string; returns it unchanged."""
    if phase not in PHASES:
        raise ValueError(
            f"unknown serving phase {phase!r} ({'|'.join(PHASES)})"
        )
    return phase


def parse_mesh(mesh: str) -> Tuple[int, int]:
    """Parse a mesh-shape string ``"PxQ"`` into (p, q); ``""`` (the
    single-device placement) parses to (0, 0).  The grammar lives here
    (pure, no jax) so BucketKey validation, the placement policy, and
    the warmup/restore mesh filters all share one parser."""
    if not mesh:
        return (0, 0)
    parts = str(mesh).lower().split("x")
    if len(parts) != 2:
        raise ValueError(f"bad mesh shape {mesh!r} (want 'PxQ', e.g. '2x4')")
    try:
        p, q = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"bad mesh shape {mesh!r} (want 'PxQ', e.g. '2x4')"
        ) from None
    if p <= 0 or q <= 0:
        raise ValueError(f"mesh dims must be positive, got {mesh!r}")
    return p, q


def check_mesh(mesh: str) -> str:
    """Validate a BucketKey mesh string; returns it canonicalized
    (``""`` for single-device, ``"PxQ"`` otherwise)."""
    p, q = parse_mesh(mesh)
    return "" if p == 0 else f"{p}x{q}"


def mesh_fits(mesh: str, device_count: int) -> bool:
    """True when a mesh-shape string is realizable with ``device_count``
    devices — the warmup/restore filter: a replica warms only the
    manifest entries its own mesh can run (a 2x4 entry on a 1-device
    box is skipped, not crashed on)."""
    p, q = parse_mesh(mesh)
    return p * q <= max(int(device_count), 0)


def halving_bucket(h: int, total: int, floor: int = 1) -> int:
    """Smallest S = total / 2^m with S >= h, floored at min(floor, total)
    (the drivers' bucket rule: for total=6144, h=2500 buckets to 3072,
    not pow2ceil's 4096)."""
    S = total
    while S // 2 >= max(h, 1) and S // 2 >= min(floor, total):
        S //= 2
    return S


def size_bucket_runs(
    heights: Sequence[int], total: int, floor: int = 1024
) -> Iterator[Tuple[int, int, int]]:
    """Group consecutive indices into runs of equal ``halving_bucket``
    size: yields (i0, i1, S) with every height in [i0, i1) <= S.  The
    canonical implementation behind ``drivers/eig._size_bucket_runs``."""
    sizes = [halving_bucket(h, total, floor) for h in heights]
    i0 = 0
    while i0 < len(sizes):
        i1 = i0
        while i1 < len(sizes) and sizes[i1] == sizes[i0]:
            i1 += 1
        yield i0, i1, sizes[i0]
        i0 = i1


def bucket_dim(n: int, floor: int = DIM_FLOOR) -> int:
    """Bucket one dimension: the power-of-two multiple of ``floor``
    covering n (the doubling view of the halving lattice)."""
    if n <= 0:
        raise ValueError(f"dimension must be positive, got {n}")
    S = floor
    while S < n:
        S *= 2
    return S


def bucket_mn(m: int, n: int, floor: int = DIM_FLOOR) -> Tuple[int, int]:
    """Bucket a tall (m >= n) shape so the gels unit pad columns fit:
    needs Mb - m >= Nb - n (each pad column carries a 1 in its own pad
    row)."""
    Nb = bucket_dim(n, floor)
    Mb = bucket_dim(m, floor)
    if Mb - m < Nb - n:
        Mb = bucket_dim(m + (Nb - n), floor)
    return Mb, Nb


@dataclass(frozen=True)
class BucketKey:
    """Identity of one compiled executable: (routine, bucket shape,
    dtype, nb, options tag, schedule).  Hashable cache key, JSON
    round-trippable for the warmup manifest.

    ``schedule`` is the factorization schedule the executable's drivers
    were traced with (Option.Schedule: auto|flat|recursive) — a
    first-class key component so a warmup manifest captured from a
    recursive-schedule deployment precompiles the recursion shapes, not
    the flat ones.  The recursion's halving splits land exactly on this
    module's bucket lattice, so one warmed bucket covers every shape
    the recursive factor touches.

    ``precision`` selects the solve path the executable was traced
    with: ``"full"`` (the direct drivers — the legacy default, so old
    manifests round-trip unchanged) or ``"mixed"`` (low-precision
    factor + device-resident iterative refinement,
    ``drivers/mixed.serve_mixed_core``).  A warmed mixed bucket solves
    at MXU low-precision rates; non-converged items surface as
    non-finite X, which the service re-solves on the full-precision
    direct path while the bucket's circuit breaker demotes persistent
    offenders.

    ``mesh`` is the *placement* of the executable: ``""`` (the legacy
    default, so old manifests round-trip unchanged) means one device —
    the data-parallel replicated case — while ``"PxQ"`` means the
    executable was traced through the ``parallel/`` spmd drivers under
    ``shard_map`` on a P x Q submesh (serve/placement routes large-n
    or explicitly-sharded requests there).  A first-class key field:
    the same bucket shape traced for different mesh shapes is a
    different program, so manifests warm — and the artifact store
    fingerprints — per mesh shape (ROADMAP item 2's remnant: sharded
    executables no longer collide with the single-device key).

    ``phase`` selects how much of the pipeline the executable runs:
    ``"full"`` (factor + solve — the legacy default, so old manifests
    round-trip unchanged) or ``"solve"`` (trsm-only: the cheap family
    the factor cache dispatches on a hit, taking the *factor* as its
    first operand — gesv rides pre-permuted rows + two trsm sweeps,
    posv two trsm sweeps).  A first-class key field: the solve-phase
    executable is a different program over the same bucket shape, so
    manifests warm it separately and its artifact fingerprint never
    collides with the full-phase sibling's."""

    routine: str
    m: int  # row bucket
    n: int  # column bucket
    nrhs: int  # rhs bucket
    dtype: str  # canonical numpy name, e.g. "float64"
    nb: int  # tile size the executable was built with
    tag: str = ""  # options fingerprint (empty = defaults)
    schedule: str = "auto"  # factorization schedule (Option.Schedule)
    precision: str = "full"  # solve path: full | mixed
    mesh: str = ""  # placement: "" = single device | "PxQ" spmd submesh
    phase: str = "full"  # pipeline slice: full (factor+solve) | solve

    @property
    def label(self) -> str:
        """Metric-name fragment: serve.<routine>.<label>.b<batch>.run"""
        return (
            f"{self.routine}.{self.m}x{self.n}x{self.nrhs}.{self.dtype}"
            + (f".{self.tag}" if self.tag else "")
            + (f".{self.schedule}" if self.schedule != "auto" else "")
            + (f".{self.precision}" if self.precision != "full" else "")
            + (f".mesh{self.mesh}" if self.mesh else "")
            + (f".{self.phase}" if self.phase != "full" else "")
        )

    def to_json(self) -> dict:
        return {
            "routine": self.routine, "m": self.m, "n": self.n,
            "nrhs": self.nrhs, "dtype": self.dtype, "nb": self.nb,
            "tag": self.tag, "schedule": self.schedule,
            "precision": self.precision, "mesh": self.mesh,
            "phase": self.phase,
        }

    @staticmethod
    def from_json(d: dict) -> "BucketKey":
        return BucketKey(
            routine=str(d["routine"]), m=int(d["m"]), n=int(d["n"]),
            nrhs=int(d["nrhs"]), dtype=str(d["dtype"]), nb=int(d["nb"]),
            tag=str(d.get("tag", "")),
            schedule=str(d.get("schedule", "auto")),
            precision=str(d.get("precision", "full")),
            mesh=check_mesh(str(d.get("mesh", ""))),
            phase=check_phase(str(d.get("phase", "full"))),
        )

    def solve_sibling(self) -> "BucketKey":
        """The trsm-only (phase="solve") twin of a full-phase bucket —
        the executable the factor cache dispatches on a hit."""
        import dataclasses

        return dataclasses.replace(self, phase="solve")


# ---------------------------------------------------------------------------
# circuit breaker (per-BucketKey batched-path state; service.py drives it)
# ---------------------------------------------------------------------------

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


@dataclass
class Breaker:
    """Circuit-breaker state for one bucket's batched path.

    Lifecycle (SolverService drives the transitions, keyed by
    BucketKey):  ``closed`` --degrade_after consecutive failures-->
    ``open`` (requests route to the direct driver) --cooldown
    elapsed--> ``half_open`` (the next batch is a probe through the
    batched path) --probe success--> ``closed`` / --probe failure-->
    ``open`` with a fresh cooldown.  Unlike the permanent degradation
    it replaces, an open breaker is a *recoverable* state: one healthy
    probe restores batching.
    """

    state: str = BREAKER_CLOSED
    streak: int = 0  # consecutive batched-path failures
    opened_at: float = 0.0  # monotonic time of the last open transition
    opens: int = 0  # lifetime open transitions (health reporting)

    def record_failure(self, now: float, degrade_after: int) -> bool:
        """One batched-path failure; returns True when this failure
        opens the breaker (half-open probes reopen immediately)."""
        self.streak += 1
        if self.state == BREAKER_HALF_OPEN or (
            self.state == BREAKER_CLOSED and self.streak >= degrade_after
        ):
            self.state = BREAKER_OPEN
            self.opened_at = now
            self.opens += 1
            return True
        return False

    def record_success(self) -> bool:
        """One batched-path success; returns True when it closed a
        half-open breaker (the recovery transition)."""
        was_probe = self.state == BREAKER_HALF_OPEN
        self.state = BREAKER_CLOSED
        self.streak = 0
        return was_probe

    def cooling_down(self, now: float, cooldown_s: float) -> bool:
        """True while this breaker is OPEN and its cooldown has not yet
        elapsed — the ONE definition of the cooldown window, shared by
        :meth:`try_half_open` and the service's admission-side replica
        exclusion (an excluded lane must become selectable the moment
        a probe could fire, or it would stay open forever)."""
        return self.state == BREAKER_OPEN and now - self.opened_at < cooldown_s

    def try_half_open(self, now: float, cooldown_s: float) -> bool:
        """Move an open breaker whose cooldown has elapsed to
        half-open; returns True on that transition."""
        if self.state == BREAKER_OPEN and not self.cooling_down(
            now, cooldown_s
        ):
            self.state = BREAKER_HALF_OPEN
            return True
        return False


def _serve_nb(S: int) -> int:
    """Tile size for a serving executable: one MXU-friendly tile up to
    64, then the drivers' blocked paths take over."""
    return min(64, S)


def bucket_for(
    routine: str,
    m: int,
    n: int,
    nrhs: int,
    dtype,
    floor: int = DIM_FLOOR,
    nrhs_floor: int = NRHS_FLOOR,
    tag: str = "",
    schedule: str = "auto",
    precision: str = "full",
    mesh: str = "",
    phase: str = "full",
) -> BucketKey:
    """Map one request onto its BucketKey.  gesv/posv are square
    (m == n); gels buckets rows and columns independently (m >= n —
    underdetermined systems are served by the direct path, see api).
    ``schedule`` keys the executable by factorization schedule;
    ``precision`` by solve path (full | mixed — mixed is a square-solve
    feature: gels has no low-precision-factor refinement analogue
    here, so it stays on the full path).  ``mesh`` keys the executable
    by placement: ``"PxQ"`` routes it through the spmd drivers on that
    submesh (gesv/posv full-precision only — the sharded solvers have
    no mixed or least-squares trace; serve/placement enforces the
    routing policy, this validates the combination).  ``phase`` keys
    the pipeline slice: the ``"solve"`` (solve-only) family exists for
    gesv/posv/gels at full precision on a single device only — the
    factor cache owns the factor, the mesh and mixed tiers have no
    factor-reuse trace."""
    check_precision(precision)
    check_phase(phase)
    mesh = check_mesh(mesh)
    if phase != "full" and (
        routine not in ("gesv", "posv", "gels")
        or precision != "full" or mesh
    ):
        raise ValueError(
            "solve-phase buckets exist for single-device full-precision "
            f"gesv/posv/gels only (routine={routine!r}, "
            f"precision={precision!r}, mesh={mesh!r})"
        )
    dt = np.dtype(dtype).name
    rb = bucket_dim(nrhs, nrhs_floor)
    if routine in ("gesv", "posv"):
        if m != n:
            raise ValueError(f"{routine} requires square A, got {m}x{n}")
        if mesh and precision != "full":
            raise ValueError(
                "sharded serving is full-precision only "
                f"(mesh={mesh!r}, precision={precision!r})"
            )
        S = bucket_dim(n, floor)
        return BucketKey(
            routine, S, S, rb, dt, _serve_nb(S), tag, schedule, precision,
            mesh, phase,
        )
    if routine == "gels":
        if m < n:
            raise ValueError("gels serving path requires m >= n")
        if mesh:
            raise ValueError("gels has no sharded serving path")
        Mb, Nb = bucket_mn(m, n, floor)
        return BucketKey(
            routine, Mb, Nb, rb, dt, _serve_nb(Nb), tag, schedule, "full",
            "", phase,
        )
    raise ValueError(f"unknown serving routine: {routine!r}")


def gels_pack_kt(key: BucketKey) -> int:
    """Number of compact-WY T panels in a gels solve-phase factor pack
    (one per nb-wide column panel of the padded (Mb, Nb) global)."""
    return -(-key.n // key.nb)


def solve_factor_shape(key: BucketKey) -> Tuple[int, int]:
    """Shape of the solve-phase executable's (unbatched) factor
    operand.  gesv/posv: the (Mb, Nb) bucket-padded factor global.
    gels: the packed QR representation — V/R in rows [0, Mb), then the
    kt compact-WY T panels flattened below (panel k's (w, w) T lands
    in rows [Mb + k*nb, Mb + k*nb + w), cols [0, w)), so one array
    carries everything the Q^H apply + trsm needs and a hit dispatches
    with no host-side reassembly."""
    if key.routine == "gels":
        return (key.m + gels_pack_kt(key) * key.nb, key.n)
    return (key.m, key.n)


def batch_bucket(count: int, batch_max: int) -> int:
    """Two batch points per key — 1 (lone request) and batch_max
    (coalesced) — so steady state touches exactly the executables
    warmup compiled, regardless of arrival timing."""
    return 1 if count <= 1 else batch_max


# ---------------------------------------------------------------------------
# pad / crop
# ---------------------------------------------------------------------------


def pad_square(A: np.ndarray, S: int) -> np.ndarray:
    """Top-left embed with identity trailing block (gesv/posv)."""
    n = A.shape[0]
    out = np.zeros((S, S), dtype=A.dtype)
    out[:n, :n] = A
    if S > n:
        idx = np.arange(n, S)
        out[idx, idx] = 1
    return out


def pad_tall(A: np.ndarray, Mb: int, Nb: int) -> np.ndarray:
    """Zero row pad + unit pad columns in pad rows (gels, m >= n)."""
    m, n = A.shape
    out = np.zeros((Mb, Nb), dtype=A.dtype)
    out[:m, :n] = A
    for i in range(Nb - n):
        out[m + i, n + i] = 1
    return out


def pad_rhs(B: np.ndarray, rows: int, nrhs_b: int) -> np.ndarray:
    out = np.zeros((rows, nrhs_b), dtype=B.dtype)
    out[: B.shape[0], : B.shape[1]] = B
    return out


def pad_request(key: BucketKey, A: np.ndarray, B: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Pad one request's (A, B) to the key's bucket shapes."""
    if key.routine == "gels":
        return pad_tall(A, key.m, key.n), pad_rhs(B, key.m, key.nrhs)
    return pad_square(A, key.n), pad_rhs(B, key.n, key.nrhs)


def crop_result(key: BucketKey, X: np.ndarray, n: int, nrhs: int) -> np.ndarray:
    """Crop a padded solution back to the request's true (n, nrhs)."""
    return X[:n, :nrhs]


def pad_waste(key: BucketKey, m: int, n: int, nrhs: int) -> int:
    """Padded-minus-true element count of one request's operands (the
    ``serve.bucket_pad_waste`` counter unit)."""
    true = m * n + m * nrhs
    padded = key.m * key.n + key.m * key.nrhs
    return max(padded - true, 0)


def phase_flops(key: BucketKey, batch: int = 1) -> float:
    """Model FLOPs of one dispatch of this bucket's executable — the
    schedule-accounting mirror behind the factor cache's ≤ 10%
    acceptance criterion (the solve-only family must cost an order
    less than its full-phase sibling).  Full phase: the factorization
    (gesv 2/3 n^3, posv 1/3 n^3) plus the two trsm sweeps; solve
    phase: the trsm sweeps alone (2 n^2 nrhs — the row permute is a
    gather, FLOP-free), or for gels the blocked Q^H apply from the
    packed compact-WY factor (~4 m n nrhs) plus one trsm.  Per-item,
    times the batch point."""
    n, r = float(key.n), float(key.nrhs)
    solve = 2.0 * n * n * r
    if key.phase == "solve":
        if key.routine == "gels":
            return batch * (4.0 * float(key.m) * n * r + n * n * r)
        return batch * solve
    if key.routine == "gesv":
        return batch * (2.0 / 3.0 * n**3 + solve)
    if key.routine == "posv":
        return batch * (1.0 / 3.0 * n**3 + solve)
    # gels: QR factor + apply + triangular solve (m >= n)
    m = float(key.m)
    return batch * (2.0 * m * n * n - 2.0 / 3.0 * n**3 + 2.0 * m * n * r)


# ---------------------------------------------------------------------------
# fingerprinting (the durable-artifact identity, serve/artifacts.py)
# ---------------------------------------------------------------------------


def content_fields(key: BucketKey, batch: int) -> dict:
    """The *content* half of an executable artifact's identity: every
    BucketKey field (schedule, precision AND mesh included — two
    executables traced from different schedules, solve paths or mesh
    placements are different programs) plus the batch point.  Pure and canonical; the *runtime*
    half (jaxlib/backend version, device kind, x64 mode) is appended by
    ``serve/artifacts.py``, which may import jax."""
    return {**key.to_json(), "batch": int(batch)}


def fingerprint(fields: dict) -> str:
    """Stable hex digest of a fingerprint field dict: sha256 over the
    canonical (sorted-key, compact) JSON encoding, so any drift in any
    field — bucket shape, schedule, precision, jaxlib, device kind,
    x64 — produces a different artifact identity."""
    blob = json.dumps(fields, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def manifest_dumps(entries, costs=None) -> str:
    """Serialize [(BucketKey, batch), ...] as the warmup manifest JSON.
    ``costs`` is an optional ``{(key, batch): cost-record}`` mapping
    (the build-time ``cost_analysis``/``memory_analysis`` capture —
    serve/cache.py's registry): entries with a record get a ``"cost"``
    field, so the flops/bytes/peak evidence restores with the manifest
    instead of costing a recapture compile on the next cold start."""

    def entry(k, b):
        e = {**k.to_json(), "batch": int(b)}
        if costs:
            c = costs.get((k, int(b)))
            if c:
                e["cost"] = c
        return e

    return json.dumps(
        {
            "version": 1,
            "entries": sorted(
                (entry(k, b) for k, b in entries),
                key=lambda e: (e["routine"], e["m"], e["n"], e["nrhs"],
                               e["dtype"], e["tag"], e["schedule"],
                               e["precision"], e["mesh"], e["phase"],
                               e["batch"]),
            ),
        },
        indent=1,
    )


def _manifest_doc(text_or_doc):
    """One parse for both loaders: accepts the manifest JSON text or
    an already-parsed document dict (the cache reads the file once and
    feeds both loaders from the same doc)."""
    return (
        text_or_doc if isinstance(text_or_doc, dict)
        else json.loads(text_or_doc)
    )


def manifest_loads(text):
    """Parse a warmup manifest (JSON text or parsed doc) back into
    [(BucketKey, batch), ...]."""
    doc = _manifest_doc(text)
    out = []
    for e in doc.get("entries", []):
        out.append((BucketKey.from_json(e), int(e.get("batch", 1))))
    return out


def manifest_cost_loads(text):
    """Parse the per-entry ``"cost"`` records out of a warmup manifest
    (JSON text or parsed doc): ``{(BucketKey, batch): cost-record}``.
    Entries without the field (pre-PR11 manifests, or any devmon-off
    writer) simply yield nothing — the cache recaptures at the next
    devmon-on build; tools/warmup_report.py flags them ``no-cost``."""
    doc = _manifest_doc(text)
    out = {}
    for e in doc.get("entries", []):
        c = e.get("cost")
        if isinstance(c, dict) and c:
            out[(BucketKey.from_json(e), int(e.get("batch", 1)))] = dict(c)
    return out
