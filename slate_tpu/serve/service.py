"""SolverService: bounded request queue, worker thread, same-bucket
batch coalescing, deadlines, retries, and graceful degradation.

Execution model (one worker, deliberately simple — the architectural
seam later scaling PRs widen into multi-host dispatch / priority
tiers / admission control):

* ``submit()`` buckets the request (`buckets.bucket_for`), pads nothing
  yet, and enqueues.  A full queue rejects IMMEDIATELY with
  :class:`Rejected` — backpressure belongs at admission, not at a
  timeout deep in the pipeline.
* The worker pops the oldest request, waits up to ``batch_window_s``
  for company, then extracts every queued request with the SAME
  BucketKey (up to ``batch_max``) into one coalesced batch.  Batches
  are padded to the fixed ``batch_max`` point (`buckets.batch_bucket`)
  by repeating the first request, so only two executables exist per
  bucket and warmed steady state never compiles.
* Deadlines: a request whose deadline passes while still QUEUED is
  cancelled with :class:`DeadlineExceeded` (counted in
  ``serve.deadline_miss``) — it never starts.  A request that finishes
  past its deadline still delivers its result (XLA dispatches cannot be
  cancelled mid-flight) but also counts a miss.
* Failures: an executable exception re-enqueues the batch's requests
  while they have ``retries`` left; after that each request falls back
  to the direct driver (``serve.fallbacks``).  A bucket whose batched
  path fails ``degrade_after`` consecutive times is degraded — routed
  straight to the direct driver from then on (the api.py graceful-
  degradation contract).  A nonzero per-item ``info`` raises
  :class:`~slate_tpu.exceptions.NumericalError` on that item's future
  only (no retry: the failure is deterministic).

Metrics: ``serve.queue_depth`` gauge, ``serve.requests``,
``serve.batched`` (coalesced batches), ``serve.batched_requests``,
``serve.batch_pad`` (repeat-padding), ``serve.bucket_pad_waste``
(elements), ``serve.deadline_miss``, ``serve.rejected``,
``serve.fallbacks``, ``serve.degraded``; per-bucket compile/run split
via the cache's instrumented executables.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

import numpy as np

from ..aux import metrics
from ..exceptions import NumericalError, SlateError
from . import buckets as _bk
from .cache import ExecutableCache, direct_call


class Rejected(SlateError):
    """Queue-full backpressure: the request was never admitted."""


class DeadlineExceeded(SlateError):
    """The request's deadline passed before execution started."""


@dataclass
class _Request:
    routine: str
    key: Optional[_bk.BucketKey]  # None => direct-only (e.g. gels m < n)
    A: np.ndarray
    B: np.ndarray
    m: int
    n: int
    nrhs: int
    future: Future = field(default_factory=Future)
    deadline: Optional[float] = None  # absolute time.monotonic()
    retries: int = 0
    t_submit: float = field(default_factory=time.monotonic)

    def expired(self, now: Optional[float] = None) -> bool:
        return (
            self.deadline is not None
            and (now if now is not None else time.monotonic()) > self.deadline
        )


class SolverService:
    """Batching solver service over the driver stack.

    Parameters
    ----------
    cache: shared :class:`ExecutableCache` (one per process is the
        point — executables amortize across services); built from
        ``SLATE_TPU_WARMUP`` when omitted.
    max_queue: admission limit; ``submit`` past it raises Rejected.
    batch_max: coalesced batch point (and per-key executable batch).
    batch_window_s: how long the worker lingers for coalescable
        arrivals after popping a lone request.
    dim_floor / nrhs_floor: bucket lattice floors (buckets.py).
    degrade_after: consecutive batched-path failures of one bucket
        before it is permanently routed to the direct driver.
    schedule: factorization schedule the bucket executables trace their
        drivers with (Option.Schedule: "auto"|"flat"|"recursive") —
        part of the BucketKey, so manifests and warmup precompile the
        matching shapes; None reads the Option default.
    start: set False to build paused (tests; call :meth:`start`).
    """

    def __init__(
        self,
        cache: Optional[ExecutableCache] = None,
        max_queue: Optional[int] = None,
        batch_max: Optional[int] = None,
        batch_window_s: Optional[float] = None,
        dim_floor: int = _bk.DIM_FLOOR,
        nrhs_floor: int = _bk.NRHS_FLOOR,
        degrade_after: int = 2,
        schedule: Optional[str] = None,
        start: bool = True,
    ):
        # None -> the Serve* Option defaults (one source of truth with
        # options.py; api._make_service resolves per-call opts the same way)
        from ..enums import Option, Schedule
        from ..options import get_option

        self.cache = cache if cache is not None else ExecutableCache()
        self.max_queue = int(
            max_queue if max_queue is not None
            else get_option(None, Option.ServeQueueLimit)
        )
        self.batch_max = int(
            batch_max if batch_max is not None
            else get_option(None, Option.ServeBatchMax)
        )
        self.batch_window_s = float(
            batch_window_s if batch_window_s is not None
            else get_option(None, Option.ServeBatchWindow)
        )
        self.dim_floor = int(dim_floor)
        self.nrhs_floor = int(nrhs_floor)
        self.degrade_after = int(degrade_after)
        if schedule is None:
            schedule = get_option(None, Option.Schedule, Schedule.Auto)
        self.schedule = (
            schedule.value if isinstance(schedule, Schedule)
            else Schedule.from_string(str(schedule)).value
        )
        self._q: Deque[_Request] = deque()
        self._cond = threading.Condition()
        self._running = False
        self._stopped = False  # stop() called; submit() rejects until start()
        self._thread: Optional[threading.Thread] = None
        self._fail_streak: Dict[_bk.BucketKey, int] = {}
        self._degraded: set = set()
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SolverService":
        with self._cond:
            if self._running:
                return self
            self._running = True
            self._stopped = False
        self._thread = threading.Thread(
            target=self._loop, name="slate-serve-worker", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the worker; unstarted/leftover requests resolve with
        Rejected (futures never hang)."""
        with self._cond:
            self._running = False
            self._stopped = True
            leftovers = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        for r in leftovers:
            _resolve_exc(r.future, Rejected("service stopped"))
        metrics.gauge("serve.queue_depth", 0)

    def __enter__(self) -> "SolverService":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- admission ---------------------------------------------------------

    def submit(
        self,
        routine: str,
        A,
        B,
        deadline: Optional[float] = None,
        retries: int = 0,
    ) -> Future:
        """Enqueue one solve; returns a Future resolving to the cropped
        solution X (n x nrhs ndarray).

        ``deadline`` is seconds from now; ``retries`` re-runs the
        batched path on executable failure before falling back.
        Raises :class:`Rejected` when the queue is full."""
        A = np.asarray(A)
        B = np.asarray(B)
        if B.ndim == 1:
            B = B[:, None]
        if A.ndim != 2 or B.ndim != 2 or A.shape[0] != B.shape[0]:
            raise ValueError(
                f"{routine}: bad shapes A{A.shape} B{B.shape}"
            )
        m, n = A.shape
        nrhs = B.shape[1]
        key: Optional[_bk.BucketKey] = None
        if not (routine == "gels" and m < n):
            key = _bk.bucket_for(
                routine, m, n, nrhs, A.dtype,
                floor=self.dim_floor, nrhs_floor=self.nrhs_floor,
                schedule=self.schedule,
            )
        req = _Request(
            routine=routine, key=key, A=A, B=B, m=m, n=n, nrhs=nrhs,
            deadline=(
                time.monotonic() + deadline if deadline is not None else None
            ),
            retries=int(retries),
        )
        with self._cond:
            if self._stopped:
                # a stopped service has no worker to ever resolve the
                # future (a paused-but-never-started one does: start());
                # admitting here would hang the sync wrappers
                metrics.inc("serve.rejected")
                raise Rejected("service stopped; configure() a new one")
            if len(self._q) >= self.max_queue:
                metrics.inc("serve.rejected")
                raise Rejected(
                    f"queue full ({self.max_queue}); retry with backoff"
                )
            self._q.append(req)
            depth = len(self._q)
            self._cond.notify_all()
        metrics.inc("serve.requests")
        metrics.gauge("serve.queue_depth", depth)
        return req.future

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._q)

    # -- worker ------------------------------------------------------------

    def _loop(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            if batch:
                self._execute(batch)

    def _next_batch(self) -> Optional[List[_Request]]:
        """Pop the oldest live request plus every same-key request (up
        to batch_max).  None => stopped; [] => only expired requests
        were popped this round."""
        with self._cond:
            while self._running and not self._q:
                self._cond.wait(0.05)
            if not self._running:
                # resolve anything the failure path re-enqueued after
                # stop() drained the queue — futures must never strand
                leftovers = list(self._q)
                self._q.clear()
                for r in leftovers:
                    _resolve_exc(r.future, Rejected("service stopped"))
                return None
            first = self._q.popleft()
            metrics.gauge("serve.queue_depth", len(self._q))
        if first.expired():
            self._miss(first)
            return []
        if first.key is None:
            return [first]
        if self.batch_max > 1 and self.batch_window_s > 0:
            with self._cond:
                if not any(r.key == first.key for r in self._q):
                    self._cond.wait(self.batch_window_s)
        batch = [first]
        with self._cond:
            keep: Deque[_Request] = deque()
            while self._q and len(batch) < self.batch_max:
                r = self._q.popleft()
                if r.key == first.key:
                    batch.append(r)
                else:
                    keep.append(r)
            keep.extend(self._q)
            self._q = keep
            metrics.gauge("serve.queue_depth", len(self._q))
        live = []
        for r in batch:
            if r.expired():
                self._miss(r)
            else:
                live.append(r)
        return live

    def _miss(self, req: _Request) -> None:
        metrics.inc("serve.deadline_miss")
        _resolve_exc(
            req.future,
            DeadlineExceeded(
                f"{req.routine} {req.m}x{req.n}: deadline passed after "
                f"{time.monotonic() - req.t_submit:.3f}s in queue"
            ),
        )

    # -- execution ---------------------------------------------------------

    def _execute(self, batch: List[_Request]) -> None:
        key = batch[0].key
        if key is None or key in self._degraded:
            for r in batch:
                self._direct(r)
            return
        try:
            self._execute_batched(key, batch)
            self._fail_streak[key] = 0
        except Exception as e:  # noqa: BLE001 — futures carry the error
            retryable = [r for r in batch if r.retries > 0]
            rest = [r for r in batch if r.retries <= 0]
            streak = self._fail_streak.get(key, 0) + 1
            self._fail_streak[key] = streak
            if streak >= self.degrade_after:
                self._degraded.add(key)
                metrics.inc("serve.degraded")
            if retryable:
                with self._cond:
                    for r in reversed(retryable):
                        r.retries -= 1
                        self._q.appendleft(r)
                    self._cond.notify_all()
            for r in rest:
                self._direct(r, batched_error=e)

    def _execute_batched(self, key: _bk.BucketKey, batch: List[_Request]) -> None:
        self.cache.ensure_manifest(key, (1, self.batch_max))
        bb = _bk.batch_bucket(len(batch), self.batch_max)
        pads = [_bk.pad_request(key, r.A, r.B) for r in batch]
        while len(pads) < bb:  # repeat-pad to the fixed batch point
            pads.append(pads[0])
            metrics.inc("serve.batch_pad")
        A_b = np.stack([p[0] for p in pads])
        B_b = np.stack([p[1] for p in pads])
        X_b, info_b = self.cache.run(key, A_b, B_b)
        now = time.monotonic()
        for i, r in enumerate(batch):
            metrics.inc(
                "serve.bucket_pad_waste", _bk.pad_waste(key, r.m, r.n, r.nrhs)
            )
            if r.deadline is not None and now > r.deadline:
                metrics.inc("serve.deadline_miss")  # finished late; still delivered
            info = int(info_b[i]) if i < len(info_b) else 0
            if info != 0:
                _resolve_exc(
                    r.future,
                    NumericalError(f"{r.routine}: info={info}", info),
                )
            else:
                _resolve(r.future, _bk.crop_result(key, X_b[i], r.n, r.nrhs))
        if len(batch) > 1:
            metrics.inc("serve.batched")
            metrics.inc("serve.batched_requests", len(batch))

    def _direct(self, req: _Request, batched_error: Optional[Exception] = None) -> None:
        if req.key is not None:
            metrics.inc("serve.fallbacks")  # degradation, not routing
        else:
            metrics.inc("serve.direct_only")  # e.g. underdetermined gels
        try:
            with metrics.phase(f"serve.direct.{req.routine}"):
                X = direct_call(req.routine, req.A, req.B)
        except Exception as e:  # noqa: BLE001 — futures carry the error
            if batched_error is not None:
                e.__context__ = batched_error
            _resolve_exc(req.future, e)
            return
        if req.deadline is not None and time.monotonic() > req.deadline:
            metrics.inc("serve.deadline_miss")
        _resolve(req.future, X)


def _resolve(fut: Future, value) -> None:
    if not fut.cancelled():
        fut.set_result(value)


def _resolve_exc(fut: Future, exc: Exception) -> None:
    if not fut.cancelled():
        fut.set_exception(exc)
